#!/usr/bin/env python
"""Driver benchmark: TPC-H Q1/Q6-shaped aggregation on the coprocessor path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

value       = TPC-H Q1 rows/sec through the TPU(jax) engine end-to-end
              (SQL -> planner -> distsql fan-out -> device partial agg ->
              root final merge), the BASELINE.json headline metric.
vs_baseline = speedup of the TPU engine over the same framework's CPU
              (numpy oracle) engine — the stand-in for the reference's
              8-vCPU mocktikv path until a Go toolchain target exists.

Env knobs: BENCH_ROWS (default 4M), BENCH_ITERS (default 3),
BENCH_REGIONS (default 8).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if os.environ.get("BENCH_FORCE_CPU") == "1":
    # the image sitecustomize force-registers the TPU tunnel and overrides
    # JAX_PLATFORMS; config wins over both
    import jax

    jax.config.update("jax_platforms", "cpu")

from tidb_tpu.session import Domain  # noqa: E402

N_ROWS = int(os.environ.get("BENCH_ROWS", 4_000_000))
ITERS = int(os.environ.get("BENCH_ITERS", 3))
REGIONS = int(os.environ.get("BENCH_REGIONS", 8))

Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity), sum(l_extendedprice),
       sum(l_extendedprice * (1 - l_discount)),
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)),
       avg(l_quantity), avg(l_extendedprice), avg(l_discount),
       count(*)
from lineitem
where l_shipdate <= '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6 = """
select sum(l_extendedprice * l_discount)
from lineitem
where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""


def build_lineitem(domain: Domain, n: int):
    s = domain.new_session()
    s.execute(
        "create table lineitem ("
        " l_orderkey bigint, l_quantity decimal(15,2),"
        " l_extendedprice double, l_discount double, l_tax double,"
        " l_returnflag varchar(1), l_linestatus varchar(1),"
        " l_shipdate date)"
    )
    t = domain.catalog.info_schema().table("test", "lineitem")
    store = domain.storage.table(t.id)
    rng = np.random.default_rng(7)
    from tidb_tpu.types.values import parse_date

    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base
    flags = np.array(["A", "N", "R"], dtype=object)
    status = np.array(["F", "O"], dtype=object)
    CHUNK = 1 << 21
    for s0 in range(0, n, CHUNK):
        m = min(CHUNK, n - s0)
        arrays = [
            rng.integers(1, n // 4 + 2, m, dtype=np.int64),     # orderkey
            rng.integers(100, 5100, m, dtype=np.int64),          # qty (scaled .2)
            rng.uniform(900.0, 105000.0, m),                     # extendedprice
            np.round(rng.uniform(0.0, 0.1, m), 2),               # discount
            np.round(rng.uniform(0.0, 0.08, m), 2),              # tax
            flags[rng.integers(0, 3, m)],                        # returnflag
            status[rng.integers(0, 2, m)],                       # linestatus
            (base + rng.integers(0, span, m)).astype(np.int32),  # shipdate
        ]
        store.bulk_load_arrays(arrays, ts=domain.storage.current_ts())
    # split on device-tile boundaries so each region's scan maps 1:1 onto
    # cached device tiles (no tile shared between regions)
    from tidb_tpu.copr.jax_engine import TILE

    n_tiles = max((store.base_rows + TILE - 1) // TILE, 1)
    k = min(REGIONS, n_tiles)
    if k > 1:
        step_tiles = max(n_tiles // k, 1)
        splits = [i * step_tiles * TILE for i in range(1, k)]
        domain.storage.regions.split_at(t.id, splits)
    return s


def bench_query(sess, sql: str, engine: str) -> float:
    sess.execute(f"set tidb_use_tpu = {'1' if engine == 'tpu' else '0'}")
    sess.query(sql)  # warmup (device transfer + XLA compile)
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        sess.query(sql)
        best = min(best, time.perf_counter() - t0)
    return best


def _run(state: dict):
    domain = Domain()
    sess = build_lineitem(domain, N_ROWS)
    state["loaded"] = True

    state["q1_tpu"] = bench_query(sess, Q1, "tpu")
    state["q6_tpu"] = bench_query(sess, Q6, "tpu")
    # CPU-engine baseline on a subsample to bound wall time, scaled
    cpu_rows = min(N_ROWS, 1_000_000)
    if cpu_rows < N_ROWS:
        d2 = Domain()
        s2 = build_lineitem(d2, cpu_rows)
    else:
        s2 = sess
    state["q1_cpu"] = bench_query(s2, Q1, "cpu") * (N_ROWS / cpu_rows)
    state["q6_cpu"] = bench_query(s2, Q6, "cpu") * (N_ROWS / cpu_rows)
    state["done"] = True


def main():
    # The TPU arrives over a network tunnel in some environments; a hung
    # device must not leave the driver with NO output line, so the work
    # runs on a watchdog thread and partial results still print.
    import threading

    wall_limit = float(os.environ.get("BENCH_WALL_LIMIT", 1500))
    state: dict = {}
    t = threading.Thread(target=_run, args=(state,), daemon=True)
    t.start()
    t.join(wall_limit)

    q1_tpu = state.get("q1_tpu")
    if q1_tpu:
        value = N_ROWS / q1_tpu
        q1_cpu = state.get("q1_cpu")
        q6_tpu = state.get("q6_tpu")
        q6_cpu = state.get("q6_cpu")
        out = {
            "metric": "tpch_q1_rows_per_sec",
            "value": round(value, 1),
            "unit": "rows/s",
            "vs_baseline": round(q1_cpu / q1_tpu, 3) if q1_cpu else None,
            "detail": {
                "rows": N_ROWS,
                "q1_tpu_s": round(q1_tpu, 4),
                "q1_cpu_est_s": round(q1_cpu, 4) if q1_cpu else None,
                "q6_tpu_rows_per_sec":
                    round(N_ROWS / q6_tpu, 1) if q6_tpu else None,
                "q6_speedup":
                    round(q6_cpu / q6_tpu, 3) if q6_tpu and q6_cpu else None,
                "complete": bool(state.get("done")),
            },
        }
    else:
        out = {
            "metric": "tpch_q1_rows_per_sec", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "detail": {"error": "device unreachable or bench timed out",
                       "loaded": bool(state.get("loaded")),
                       "wall_limit_s": wall_limit},
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
