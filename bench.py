#!/usr/bin/env python
"""Driver benchmark: TPC-H Q1/Q6-shaped aggregation on the coprocessor path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

value       = TPC-H Q1 rows/sec through the TPU(jax) engine end-to-end
              (SQL -> planner -> distsql -> mesh-sharded device scan ->
              collective partial agg -> root final merge), steady-state
              (tile cache warm), at the largest row scale that fit the
              wall budget.
vs_baseline = speedup of the TPU engine over the same framework's CPU
              (numpy oracle) engine — the stand-in for the reference's
              8-vCPU mocktikv path.

Hostile-device resilience (the round-1 failure mode was a 25-minute hang
with zero output):
- phase 0 preflights jax.devices() on a watchdog thread and emits a
  distinct "tunnel unreachable" error line if it never returns;
- work runs on a daemon worker; the main thread enforces the global wall
  budget and ALWAYS prints the best state reached, phase by phase;
- row count starts at 256k and quadruples only while under budget, so a
  slow tunnel yields a small-scale number instead of nothing;
- warm-up (transfer+compile) is timed separately from steady state.

Env knobs: BENCH_ROWS (max scale, default 64M), BENCH_ITERS (default 3),
BENCH_REGIONS (default 8), BENCH_WALL_LIMIT (s, default 1500),
BENCH_FORCE_CPU=1 (pin jax to host cpu).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

MAX_ROWS = int(os.environ.get("BENCH_ROWS", 128_000_000))
ITERS = int(os.environ.get("BENCH_ITERS", 3))
REGIONS = int(os.environ.get("BENCH_REGIONS", 8))
WALL_LIMIT = float(os.environ.get("BENCH_WALL_LIMIT", 1500))
T0 = time.perf_counter()


def log(msg: str):
    print(f"[bench {time.perf_counter() - T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


def remaining() -> float:
    return WALL_LIMIT - (time.perf_counter() - T0)


Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity), sum(l_extendedprice),
       sum(l_extendedprice * (1 - l_discount)),
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)),
       avg(l_quantity), avg(l_extendedprice), avg(l_discount),
       count(*)
from lineitem
where l_shipdate <= '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6 = """
select sum(l_extendedprice * l_discount)
from lineitem
where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""

# canonical Q3 text lives beside its data builder (one plan shape across
# bench/dryruns/tests); imported lazily because jax must not load before
# preflight pins the platform
def _q3_sql():
    from tidb_tpu.tpch_data import Q3_SQL

    return Q3_SQL


def classify_probe_error(err: str) -> str:
    """Bucket a device-probe failure so receipts distinguish 'the tunnel
    is down' (deterministic — fail fast) from a slow or flaky link
    (transient — keep retrying) and from a broken environment."""
    e = (err or "").lower()
    if any(s in e for s in ("connection refused", "unreachable",
                            "failed to connect", "connection reset",
                            "no such host", "name or service not known")):
        return "tunnel-down"
    if any(s in e for s in ("timed out", "timeout", "deadline")):
        return "probe-timeout"
    if any(s in e for s in ("modulenotfound", "importerror",
                            "no module named")):
        return "environment"
    return "unknown"


def preflight(state: dict) -> bool:
    """Touch the device, retrying until half the wall budget is gone: a
    tunnel that comes up minutes into the run still yields a number
    (round-2 failure mode: one 300s try, then 0.0 forever).  A
    deterministic refusal (class 'tunnel-down') stops retrying after 3
    consecutive hits instead — burning half the budget on a dead tunnel
    starves the host-side fallback phases that keep the receipt useful."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # sitecustomize force-registers the TPU tunnel and overrides
        # JAX_PLATFORMS; config wins over both
        import jax

        jax.config.update("jax_platforms", "cpu")
    attempts: list = []
    deadline = min(0.5 * WALL_LIMIT, max(remaining() - 120, 30))
    last_err = "jax.devices() timed out"
    if os.environ.get("BENCH_FORCE_CPU") != "1":
        # probe in a SUBPROCESS until one succeeds: a fast in-process
        # failure (connection refused) poisons jax's cached backend init,
        # and a hung jax.devices() can't be cancelled — a child process
        # sidesteps both, so a tunnel that comes up minutes in still works.
        # The FIRST attempt uses a short timeout (a healthy tunnel answers
        # in ~5s) so the happy path never burns probe budget.
        import subprocess

        ok = False
        probe_timeout = 10
        hard_down = 0
        while time.perf_counter() - T0 < deadline:
            attempts.append(round(time.perf_counter() - T0, 1))
            try:
                p = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; print([str(d) for d in jax.devices()])"],
                    capture_output=True, text=True,
                    timeout=min(probe_timeout,
                                max(deadline - (time.perf_counter() - T0),
                                    10)),
                )
                if p.returncode == 0:
                    ok = True
                    break
                last_err = (p.stderr or p.stdout).strip()[-300:]
            except subprocess.TimeoutExpired:
                last_err = "probe subprocess timed out"
            klass = classify_probe_error(last_err)
            # tunnel-down AND environment failures are deterministic —
            # retrying either just burns the fallback phases' budget
            hard_down = (hard_down + 1
                         if klass in ("tunnel-down", "environment") else 0)
            if hard_down >= 3:
                log(f"device probe failed 3x in a row [{klass}]; "
                    "failing fast")
                break
            probe_timeout = min(probe_timeout * 2, 90)
            # transient flakes (probe-timeout / unknown) back off
            # exponentially with jitter instead of a fixed 10s hammer —
            # a recovering tunnel gets breathing room, a slow one still
            # gets retried well inside the probe deadline
            backoff = min(5.0 * (1.6 ** len(attempts)), 45.0)
            backoff *= 0.8 + 0.4 * ((hash((len(attempts), klass)) % 100)
                                    / 100.0)
            log(f"device probe failed [{klass}] "
                f"({time.perf_counter() - T0:.0f}s / {deadline:.0f}s); "
                f"retrying in {backoff:.0f}s")
            time.sleep(backoff)
        state["preflight_attempts"] = attempts
        if not ok:
            state["preflight_error"] = last_err
            state["preflight_error_class"] = classify_probe_error(last_err)
            log(f"device preflight FAILED "
                f"[{state['preflight_error_class']}]: {last_err}")
            return False

    # tunnel answers (or forced cpu): initialize jax in-process on a
    # watchdog thread.  The subprocess probe above can succeed while the
    # in-process init still hits a transient flake (round-3/5 failure
    # mode), so this stage RETRIES too instead of giving up on one shot.
    result: dict = {}

    def probe():
        try:
            import jax

            devs = jax.devices()
            import jax.numpy as jnp

            np.asarray(jnp.arange(8) * 2)  # round-trip one tiny program
            result["devices"] = [str(d) for d in devs]
        except BaseException as e:  # noqa: BLE001
            result["error"] = repr(e)

    for attempt in range(3):
        result.clear()
        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(min(180.0, max(remaining() - 60, 30)))
        if "devices" in result:
            state["devices"] = result["devices"]
            log(f"device preflight ok: {result['devices']}")
            return True
        err = result.get("error", "jax.devices() timed out")
        if attempt < 2 and remaining() > 240 \
                and classify_probe_error(err) in ("probe-timeout",
                                                  "unknown"):
            # a hung in-process init thread can't be cancelled, but a
            # fresh attempt can still win while the old one lingers
            log(f"in-process preflight attempt {attempt + 1} failed "
                f"({err[:120]}); retrying")
            time.sleep(5 * (attempt + 1))
            continue
        break
    state["preflight_error"] = result.get("error", "jax.devices() timed out")
    state["preflight_error_class"] = classify_probe_error(
        state["preflight_error"])
    log(f"device preflight FAILED [{state['preflight_error_class']}]: "
        f"{state['preflight_error']}")
    return False


def _host_fallback_worker():
    """The CPU phase of the fallback, run in a FRESH subprocess: when
    preflight failed at its in-process stage the parent's jax backend is
    already initialized (or init-locked) against the dead tunnel, and
    jax.config.update after backend init does not re-initialize — only a
    clean process reliably lands on CPU."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # config wins sitecustomize
    out: dict = {}
    n = 262_144
    t0 = time.perf_counter()
    sess = build_lineitem(n)
    out["load_s"] = round(time.perf_counter() - t0, 2)
    sess.execute("set tidb_use_tpu = 0")
    _, q1_cpu = time_query(sess, Q1, 1)
    _, q6_cpu = time_query(sess, Q6, 1)
    out["rows"] = n
    out["q1_cpu_s"] = round(q1_cpu, 4)
    out["q1_cpu_rows_per_sec"] = round(n / q1_cpu, 1)
    out["q6_cpu_s"] = round(q6_cpu, 4)
    out["q1_plan_ops"] = [r[0]
                          for r in sess.execute("explain " + Q1)[0].rows]
    # serving receipt survives tunnel outages: a small concurrent phase
    # on the CPU backend still exercises admission + micro-batching
    try:
        cstate: dict = {}
        concurrent_bench(cstate, n_rows=n, clients=8, dur_s=3.0)
        out["concurrent"] = cstate.get("concurrent")
    except BaseException as e:  # noqa: BLE001
        out["concurrent"] = {"error": repr(e)}
    # whole-fragment fusion receipt on the CPU harness: fused one-launch
    # mesh program vs the per-tile dispatch loop (TIDB_TPU_TILE is
    # shrunk by the parent so the table spans multiple tiles)
    try:
        sess.execute("set tidb_use_tpu = 1")
        out["fusion"] = fusion_bench(sess, n)
    except BaseException as e:  # noqa: BLE001
        out["fusion"] = {"error": repr(e)}
    # grouped-pushdown receipt on the CPU harness: the device-merged
    # GROUP BY below the exchange vs the host-merge rows path
    try:
        from tidb_tpu.tpch_data import build_q3_tables

        n3 = 131_072
        sess3 = build_q3_tables(n3, n3 // 8)
        sess3.execute("set tidb_enforce_mpp = 1")
        out["mpp_grouped_agg"] = mpp_grouped_bench(sess3, n3)
    except BaseException as e:  # noqa: BLE001
        out["mpp_grouped_agg"] = {"error": repr(e)}
    # adaptive-layout receipt on the CPU harness: cold-tier qps vs the
    # fixed-layout full-reload comparator under a squeezed byte cap
    try:
        out["layout"] = layout_bench(sess, n)
    except BaseException as e:  # noqa: BLE001
        out["layout"] = {"error": repr(e)}
    # zero-host-tail receipt on the CPU harness: computed-key and
    # compound-order shapes fused vs the ladder comparator (ISSUE 11)
    try:
        sess.execute("set tidb_use_tpu = 1")
        out["host_tail"] = host_tail_bench(sess, n)
    except BaseException as e:  # noqa: BLE001
        out["host_tail"] = {"error": repr(e)}
    # TPC-H residency matrix on the CPU harness (ISSUE 12): the fused
    # fraction over all 22 queries survives a dead tunnel
    try:
        out["tpch_matrix"] = tpch_matrix_bench(scale=1.0)
    except BaseException as e:  # noqa: BLE001
        out["tpch_matrix"] = {"error": repr(e)}
    # trace + profiler overhead on the CPU harness (ISSUE 13): the <2%
    # claim is a recorded receipt even when the tunnel is down
    try:
        out["trace_overhead"] = trace_overhead_bench(sess)
    except BaseException as e:  # noqa: BLE001
        out["trace_overhead"] = {"error": repr(e)}
    # lock-order witness receipt (ISSUE 16): the corpus replayed once
    # with TIDB_TPU_LOCKCHECK=1 in a fresh subprocess
    try:
        out["lockcheck"] = lockcheck_bench()
    except BaseException as e:  # noqa: BLE001
        out["lockcheck"] = {"error": repr(e)}
    # interruptible chunked dispatch receipt (ISSUE 17): KILL-to-return
    # latency chunked vs unchunked + 2-group RU fairness, on the CPU
    # harness
    try:
        sess.execute("set tidb_use_tpu = 1")
        out["kill_latency"] = kill_latency_bench(sess, n)
    except BaseException as e:  # noqa: BLE001
        out["kill_latency"] = {"error": repr(e)}
    # sharded data-plane receipt (ISSUE 18): 1-host vs 2-host scan
    # rows/s + exchange bytes, on the CPU harness
    try:
        out["dataplane_scan"] = dataplane_bench(n)
    except BaseException as e:  # noqa: BLE001
        out["dataplane_scan"] = {"error": repr(e)}
    print("FALLBACK_JSON " + json.dumps(out), flush=True)


def _fallback_cmd():
    return [sys.executable, os.path.abspath(__file__),
            "--host-fallback-worker"]


def _fallback_env():
    return dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FORCE_CPU="1",
                # multi-tile + multi-shard so the fusion receipt's
                # fused-vs-per-tile comparison is meaningful on CPU
                TIDB_TPU_TILE="65536",
                XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8"
                           ).strip())


def _fold_fallback_output(state: dict, stdout_text: str) -> bool:
    """Parse the worker's FALLBACK_JSON line into state; True on hit."""
    line = next((ln for ln in reversed((stdout_text or "").splitlines())
                 if ln.startswith("FALLBACK_JSON ")), None)
    if line is None:
        return False
    state.setdefault("host_fallback", {}).update(
        json.loads(line[len("FALLBACK_JSON "):]))
    return True


def start_parallel_fallback(state: dict):
    """Launch the host-side fallback worker IN PARALLEL with the device
    preflight (ISSUE 9 satellite, ROADMAP bench reliability): a
    tunnel-wedged driver run commits a nonzero CPU receipt as soon as
    the fallback phases finish — persisted incrementally — instead of
    only starting them after the preflight burns half the wall budget.
    Returns a handle for host_side_fallback / cancel, or None when the
    run is already forced to CPU (the main phases ARE the receipt)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1" \
            or os.environ.get("BENCH_PARALLEL_FALLBACK", "1") != "1":
        return None
    import subprocess
    import threading as _threading

    try:
        proc = subprocess.Popen(
            _fallback_cmd(), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=_fallback_env(),
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except BaseException as e:  # noqa: BLE001 — receipt path, never fatal
        state["parallel_fallback_error"] = repr(e)
        return None
    handle = {"proc": proc, "done": _threading.Event()}

    def collect():
        try:
            out, _err = proc.communicate(
                timeout=max(min(WALL_LIMIT - 60, 420), 60))
            if _fold_fallback_output(state, out):
                state.setdefault("phases", {})["fallback_cpu_done"] = \
                    round(time.perf_counter() - T0, 1)
                persist_partial(state)
                log("parallel host-fallback receipt committed")
        except subprocess.TimeoutExpired:
            proc.kill()
            state.setdefault("host_fallback", {}).setdefault(
                "error", "parallel fallback worker timed out")
        except BaseException as e:  # noqa: BLE001
            state.setdefault("host_fallback", {}).setdefault(
                "error", repr(e))
        finally:
            handle["done"].set()

    t = _threading.Thread(target=collect, daemon=True,
                          name="bench-parallel-fallback")
    t.start()
    handle["thread"] = t
    return handle


def cancel_parallel_fallback(handle, state: dict):
    """Device preflight succeeded: stop competing with the real run for
    host cores.  A receipt that already landed stays in the state as
    extra signal."""
    if handle is None:
        return
    proc = handle["proc"]
    if proc.poll() is None:
        proc.kill()
        state["parallel_fallback"] = "cancelled (device preflight ok)"


def host_side_fallback(state: dict, parallel=None):
    """Preflight failed: run the phases that need no device — plan build,
    the CPU oracle engine, the static-analysis gate — so the receipt
    carries real signal (error class, attempt timeline, host numbers)
    instead of a bare 0.0 rows/s.  With a `parallel` handle the CPU
    phase has been running since BEFORE the preflight and is merely
    harvested here; otherwise it spawns now.  Either way it is a
    timeout-bounded subprocess, so a poisoned in-process jax backend can
    neither skew the numbers nor hang the receipt past WALL_LIMIT."""
    if remaining() < 60:
        return
    import subprocess

    phases = state.setdefault("phases", {})
    if parallel is not None:
        parallel["done"].wait(timeout=max(min(remaining() - 60, 420), 30))
        fb = state.setdefault("host_fallback", {})
        if not fb:
            fb["error"] = "parallel fallback worker produced no receipt"
        elif "q1_cpu_rows_per_sec" in fb:
            log(f"host fallback (parallel): q1 cpu "
                f"{fb['q1_cpu_rows_per_sec']:,.0f} rows/s")
    else:
        fb = state["host_fallback"] = {}
        try:
            p = subprocess.run(
                _fallback_cmd(),
                capture_output=True, text=True, env=_fallback_env(),
                timeout=max(min(remaining() - 90, 420), 60),
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if _fold_fallback_output(state, p.stdout):
                phases["fallback_cpu_done"] = round(
                    time.perf_counter() - T0, 1)
                log(f"host fallback: q1 cpu "
                    f"{fb['q1_cpu_rows_per_sec']:,.0f} rows/s")
            else:
                fb["error"] = ((p.stderr or p.stdout).strip()[-300:]
                               or f"fallback worker exit {p.returncode}")
        except subprocess.TimeoutExpired:
            fb["error"] = "host fallback worker timed out"
        except BaseException as e:  # noqa: BLE001 — receipt must still emit
            fb["error"] = repr(e)
    if remaining() > 60:
        # the static gate is the signal that survives tunnel outages
        t0 = time.perf_counter()
        try:
            p = subprocess.run(
                [sys.executable, "-m", "tidb_tpu.lint"],
                capture_output=True, text=True,
                timeout=max(min(remaining() - 30, 600), 60),
                cwd=os.path.dirname(os.path.abspath(__file__)))
            fb["lint_exit"] = p.returncode
            fb["lint_tail"] = (p.stdout or p.stderr).strip()[-200:]
        except subprocess.TimeoutExpired:
            fb["lint_exit"] = None
            fb["lint_tail"] = "lint timed out"
        fb["lint_s"] = round(time.perf_counter() - t0, 1)
        phases["fallback_lint_done"] = round(time.perf_counter() - T0, 1)


def build_lineitem(n: int):
    from tidb_tpu.tpch_data import build_lineitem as build

    return build(n, regions=REGIONS)


# ---------------------------------------------------------------------------
# concurrent-client serving bench (shape buckets + micro-batching under
# contention, through the REAL wire server: admission -> session ->
# distsql -> serving/mesh)
# ---------------------------------------------------------------------------


class _WireClient:
    """Minimal blocking MySQL-wire client (protocol 4.1, text protocol):
    just enough to drive COM_QUERY load from N plain threads."""

    def __init__(self, host: str, port: int, db: str = "test"):
        import socket
        import struct

        self.sock = socket.create_connection((host, port), timeout=60)
        self.seq = 0
        self._recv()  # server greeting
        caps = 0x0200 | 0x8000 | 0x0008  # PROTO41|SECURE_CONN|WITH_DB
        resp = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        resp += bytes([33]) + b"\x00" * 23
        resp += b"root\x00" + b"\x00" + db.encode() + b"\x00"
        self._send(resp)
        ok = self._recv()
        if ok[0] != 0x00:
            raise ConnectionError(f"handshake refused: {ok!r}")

    def _read(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed")
            buf += chunk
        return buf

    def _recv(self) -> bytes:
        hdr = self._read(4)
        n = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
        self.seq = hdr[3] + 1
        return self._read(n)

    def _send(self, payload: bytes):
        self.sock.sendall(len(payload).to_bytes(3, "little")
                          + bytes([self.seq & 0xFF]) + payload)
        self.seq += 1

    def query(self, sql: str):
        """(result_rows, error_tuple_or_None)."""
        import struct

        self.seq = 0
        self._send(b"\x03" + sql.encode())
        first = self._recv()
        if first[0] == 0x00:
            return 0, None
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            return 0, (code, first[9:].decode("utf8", "replace"))
        ncols = first[0]  # lenenc; result sets here are narrow (<251)
        for _ in range(ncols):
            self._recv()
        self._recv()  # EOF after column defs
        rows = 0
        while True:
            pkt = self._recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            rows += 1
        return rows, None

    def close(self):
        try:
            self.seq = 0
            self._send(b"\x01")  # COM_QUIT
            self.sock.close()
        except Exception:
            pass


def _serve_domain(domain, workers: int = 16):
    """Start a MySQLServer for `domain` on an event loop in a daemon
    thread; returns (server, loop, thread)."""
    import asyncio

    from tidb_tpu.server import MySQLServer

    srv = MySQLServer(domain, port=0, workers=workers)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(srv.start())
        started.set()
        loop.run_forever()

    th = threading.Thread(target=run, daemon=True, name="bench-server")
    th.start()
    if not started.wait(30):
        raise RuntimeError("bench server failed to start")
    return srv, loop, th


def _stop_server(srv, loop, th):
    import asyncio

    try:
        fut = asyncio.run_coroutine_threadsafe(srv.shutdown(drain_s=2.0),
                                               loop)
        fut.result(20)
    except Exception:
        pass
    loop.call_soon_threadsafe(loop.stop)
    th.join(10)


def _client_loop(host, port, idx, dur_s, mode, n_rows, out, errs):
    rng = np.random.default_rng(1000 + idx)
    kmax = max(n_rows // 4, 2)
    lat = []
    n_err = 0
    try:
        cli = _WireClient(host, port)
    except Exception:
        errs[idx] = -1  # connection-level failure (admission cap etc.)
        out[idx] = lat
        return
    end = time.perf_counter() + dur_s
    try:
        while time.perf_counter() < end:
            r = rng.random() if mode == "mixed" else 0.0
            if r < 0.7:
                # identical-SHAPE point aggregate: parameter-different
                # keys share one hoisted program / one micro-batch class
                k = int(rng.integers(1, kmax))
                sql = ("select count(*), sum(l_quantity) from lineitem"
                       f" where l_orderkey = {k}")
            elif r < 0.9:
                lo = float(rng.uniform(0.02, 0.05))
                sql = ("select sum(l_extendedprice * l_discount) from"
                       f" lineitem where l_discount between {lo:.3f} and"
                       f" {lo + 0.02:.3f} and l_quantity < 24")
            else:
                sql = Q1
            t0 = time.perf_counter()
            rows, err = cli.query(sql)
            dt = time.perf_counter() - t0
            if err is not None:
                n_err += 1  # admission rejection under overload counts
            else:
                lat.append((dt, rows))
    except Exception:
        n_err += 1
    finally:
        cli.close()
    out[idx] = lat
    errs[idx] = n_err


def _pct(sorted_vals, p):
    if not sorted_vals:
        return None
    i = min(int(len(sorted_vals) * p / 100.0), len(sorted_vals) - 1)
    return sorted_vals[i]


def concurrent_bench(state: dict, n_rows: int = None, clients: int = None,
                     dur_s: float = None):
    """N client threads of mixed TPC-H + point lookups through the real
    server: p50/p99 latency, aggregate rows/s, and the micro-batched vs
    unbatched point-agg throughput on the same build."""
    n_rows = n_rows or min(state.get("loaded_rows", 1_048_576), 1_048_576)
    clients = clients or int(os.environ.get("BENCH_CLIENTS", "32"))
    dur_s = dur_s or float(os.environ.get("BENCH_CONC_S", "6"))
    window_ms = int(os.environ.get("BENCH_MB_WINDOW_MS", "5"))
    from tidb_tpu.metrics import REGISTRY

    log(f"concurrent bench: {clients} clients x {dur_s:.0f}s on "
        f"{n_rows} rows...")
    sess = build_lineitem(n_rows)
    # steady state: compile the point-agg/Q6/Q1 shapes once up front so
    # both modes measure dispatch amortization, not XLA compile time
    sess.query("select count(*), sum(l_quantity) from lineitem"
               " where l_orderkey = 1")
    sess.query(Q6)
    sess.query(Q1)
    srv, loop, th = _serve_domain(sess.domain)
    host, port = srv.host, srv.port
    ctrl = _WireClient(host, port)

    def phase(mode: str, window: int) -> dict:
        ctrl.query("set global tidb_tpu_microbatch_window_ms = "
                   f"{window}")
        m0 = REGISTRY.snapshot()
        out = [None] * clients
        errs = [0] * clients
        threads = [
            threading.Thread(target=_client_loop,
                             args=(host, port, i, dur_s, mode, n_rows,
                                   out, errs),
                             daemon=True, name=f"bench-client-{i}")
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(dur_s + 120)
        wall = time.perf_counter() - t0
        m1 = REGISTRY.snapshot()
        lats = sorted(d for per in out if per for d, _r in per)
        rows = sum(r for per in out if per for _d, r in per)
        nq = len(lats)
        return {
            "mode": mode, "window_ms": window, "queries": nq,
            "qps": round(nq / wall, 1) if wall else 0.0,
            "p50_ms": (round(_pct(lats, 50) * 1000, 3) if lats else None),
            "p99_ms": (round(_pct(lats, 99) * 1000, 3) if lats else None),
            "result_rows_per_sec": round(rows / wall, 1) if wall else 0.0,
            "errors": sum(e for e in errs if e > 0),
            "batches": round(m1.get("serving_batches_total", 0)
                             - m0.get("serving_batches_total", 0)),
            "batched_stmts": round(
                m1.get("serving_batched_stmts_total", 0)
                - m0.get("serving_batched_stmts_total", 0)),
        }

    try:
        unbatched = phase("point", 0)
        batched = phase("point", window_ms)
        mixed = phase("mixed", window_ms)
    finally:
        ctrl.query("set global tidb_tpu_microbatch_window_ms = 0")
        ctrl.close()
        _stop_server(srv, loop, th)
    speedup = (round(batched["qps"] / unbatched["qps"], 2)
               if unbatched["qps"] else None)
    snap = REGISTRY.snapshot()
    state["concurrent"] = {
        "clients": clients, "duration_s": dur_s, "rows": n_rows,
        "point_agg_unbatched": unbatched,
        "point_agg_batched": batched,
        "microbatch_speedup": speedup,
        "mixed": mixed,
        "admission_rejected": round(
            snap.get("admission_rejected_total", 0)),
        "batch_size_max": round(snap.get("serving_batch_size_max", 0)),
    }
    log(f"concurrent: point-agg {unbatched['qps']} -> {batched['qps']} "
        f"qps (x{speedup}) | mixed p50={mixed['p50_ms']}ms "
        f"p99={mixed['p99_ms']}ms qps={mixed['qps']}")


def time_query(sess, sql: str, iters: int):
    """(warmup_s, steady_best_s)"""
    t0 = time.perf_counter()
    sess.query(sql)
    warm = time.perf_counter() - t0
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        sess.query(sql)
        best = min(best, time.perf_counter() - t0)
    return warm, best


def _count_device_dispatches(sess, sql: str) -> int:
    """Run `sql` once under TRACE and count fused device launches —
    `copr.device.execute` spans (plus compile-labeled first dispatches)."""
    try:
        sess.execute("trace " + sql)
        tr = sess.last_trace
        if tr is None:
            return -1
        n = {"d": 0}

        def walk(s):
            if s.name in ("copr.device.execute", "mpp.rung",
                          "mpp.tree.final") or (
                    s.name == "copr.compile"
                    and (s.attrs or {}).get("cache") == "miss"):
                n["d"] += 1
            for c in s.children:
                walk(c)

        walk(tr.root)
        return n["d"]
    except BaseException:  # noqa: BLE001 — receipt survives trace issues
        return -1


def fusion_bench(sess, n: int) -> dict:
    """Whole-fragment fusion receipt: fused (ONE XLA launch per mesh
    dispatch) vs the per-tile dispatch loop (one launch + readback per
    tile with host glue between them — the unfused comparator,
    TIDB_TPU_FUSION=0), rows/s and dispatch counts for Q1/Q6."""
    out = {}
    prior = os.environ.get("TIDB_TPU_FUSION")
    for qname, sql in (("q1", Q1), ("q6", Q6)):
        try:
            os.environ["TIDB_TPU_FUSION"] = "1"
            _, fused_s = time_query(sess, sql, ITERS)
            fused_d = _count_device_dispatches(sess, sql)
            os.environ["TIDB_TPU_FUSION"] = "0"
            _, unf_s = time_query(sess, sql, ITERS)
            unf_d = _count_device_dispatches(sess, sql)
        finally:
            # restore the operator's setting, not a hardcoded default
            if prior is None:
                os.environ.pop("TIDB_TPU_FUSION", None)
            else:
                os.environ["TIDB_TPU_FUSION"] = prior
        out[qname] = {
            "fused_rows_per_sec": round(n / fused_s, 1),
            "per_phase_rows_per_sec": round(n / unf_s, 1),
            "fused_dispatches": fused_d,
            "per_phase_dispatches": unf_d,
            "speedup": round(unf_s / fused_s, 2),
        }
        log(f"fusion {qname}: fused={n / fused_s:,.0f} rows/s "
            f"({fused_d} dispatches) vs per-phase={n / unf_s:,.0f} rows/s "
            f"({unf_d} dispatches) -> {unf_s / fused_s:.2f}x")
    return out


_LOCKCHECK_WORKER_SRC = r"""
import json
import os
import sys
import threading

os.environ["TIDB_TPU_LOCKCHECK"] = "1"
os.environ.setdefault("TIDB_TPU_TILE", "1024")
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["LOCKCHECK_REPO"])
from bench import Q1, Q6, build_lineitem

from tidb_tpu.util_concurrency import witness_stats

n = int(os.environ.get("LOCKCHECK_ROWS", "65536"))
sess = build_lineitem(n)
sess.execute("set tidb_use_tpu = 1")
for q in (Q1, Q6):
    sess.query(q)
sess.execute("update lineitem set l_quantity = l_quantity + 1"
             " where l_orderkey = 1")


def client():
    s2 = sess.domain.new_session()
    for _ in range(3):
        s2.query(Q6)


threads = [threading.Thread(target=client) for _ in range(4)]
for t in threads:
    t.start()
for t in threads:
    t.join()
print("LOCKCHECK_JSON " + json.dumps(witness_stats()), flush=True)
"""


def lockcheck_bench(n: int = None) -> dict:
    """Lock-order witness receipt (ISSUE 16): replay the bench corpus
    (Q1/Q6 + DML + 4 concurrent client threads) once in a FRESH
    subprocess with TIDB_TPU_LOCKCHECK=1 — the witness wraps locks at
    construction time, so the parent process (whose locks are already
    plain) cannot flip it on after import — and report total guarded
    acquisitions, max held-lock depth and violations (must be zero)."""
    import subprocess

    n = int(n or 65_536)
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FORCE_CPU="1",
               LOCKCHECK_ROWS=str(n),
               LOCKCHECK_REPO=os.path.dirname(os.path.abspath(__file__)))
    t0 = time.perf_counter()
    proc = subprocess.run([sys.executable, "-c", _LOCKCHECK_WORKER_SRC],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    for ln in proc.stdout.splitlines():
        if ln.startswith("LOCKCHECK_JSON "):
            stats = json.loads(ln[len("LOCKCHECK_JSON "):])
            # per-lock contention (ISSUE 17): most-contended locks by
            # cumulative blocking wait, from the witness's log2
            # wait-histograms
            locks = stats.get("locks", {})
            hot = sorted(locks.items(),
                         key=lambda kv: -kv[1]["wait_ms"])[:5]
            return {
                "rows": n,
                "acquisitions": stats["acquisitions"],
                "max_held_depth": stats["max_depth"],
                "violations": stats["violations"],
                "wait_trips": stats.get("wait_trips", 0),
                "contended_locks": len(locks),
                "hot_locks": [
                    {"name": nm, "contended": rec["contended"],
                     "wait_ms": rec["wait_ms"]} for nm, rec in hot],
                "ok": (stats["violations"] == 0
                       and stats["acquisitions"] > 0),
                "wall_s": round(time.perf_counter() - t0, 2),
            }
    raise RuntimeError("lockcheck worker emitted no stats: "
                       + (proc.stderr or proc.stdout)[-400:])


def _measure_kill_latency(domain, sql: str):
    """Run `sql` in a victim session on its own thread, KILL it once the
    dispatch sequence is in flight, and return (kill-to-return seconds,
    outcome)."""
    import threading

    victim = domain.new_session()
    victim.execute("set tidb_use_tpu = 1")
    started = threading.Event()
    done = threading.Event()
    result = {}

    def run():
        started.set()
        try:
            victim.query(sql)
            result["outcome"] = "completed"
        except BaseException as e:  # noqa: BLE001
            result["outcome"] = type(e).__name__
        done.set()

    th = threading.Thread(target=run)
    th.start()
    started.wait()
    time.sleep(0.05)  # let the statement reach the device
    t0 = time.perf_counter()
    domain.kill(victim.conn_id, True)
    done.wait(timeout=120)
    lat = time.perf_counter() - t0
    th.join(timeout=10)
    return lat, result.get("outcome", "hung")


def kill_latency_bench(sess, n: int) -> dict:
    """Interruptible-dispatch receipt (ISSUE 17): KILL-to-return latency
    of an oversized scan with chunked dispatch vs the unchunked
    comparator (TIDB_TPU_DISPATCH_CHUNK=0 — the KILL waits out the whole
    fused dispatch), plus a 2-group 1:3 weighted-fairness run whose
    consumed-RU ratio must track the quota ratio."""
    import threading

    from tidb_tpu.metrics import REGISTRY

    d = sess.domain
    out: dict = {}
    prior_rows = os.environ.get("TIDB_TPU_DISPATCH_CHUNK_ROWS")
    prior_ms = os.environ.get("TIDB_TPU_DISPATCH_CHUNK")
    try:
        # chunked leg: force many chunks regardless of the latency
        # estimate so the between-chunk seam is exercised
        os.environ["TIDB_TPU_DISPATCH_CHUNK_ROWS"] = str(
            max(n // 64, 1024))
        os.environ.pop("TIDB_TPU_DISPATCH_CHUNK", None)
        lat_c, how_c = _measure_kill_latency(d, Q1)
        # unchunked comparator: one fused dispatch per fragment
        os.environ.pop("TIDB_TPU_DISPATCH_CHUNK_ROWS", None)
        os.environ["TIDB_TPU_DISPATCH_CHUNK"] = "0"
        lat_u, how_u = _measure_kill_latency(d, Q1)
    finally:
        for k, v in (("TIDB_TPU_DISPATCH_CHUNK_ROWS", prior_rows),
                     ("TIDB_TPU_DISPATCH_CHUNK", prior_ms)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out["chunked_kill_s"] = round(lat_c, 4)
    out["chunked_outcome"] = how_c
    out["unchunked_kill_s"] = round(lat_u, 4)
    out["unchunked_outcome"] = how_u
    out["speedup"] = round(lat_u / lat_c, 2) if lat_c > 0 else None
    log(f"kill latency: chunked={lat_c:.3f}s ({how_c}) "
        f"unchunked={lat_u:.3f}s ({how_u})")

    # ---- 2-group weighted fairness (1:3 RU quotas) ----------------------
    adm = d.new_session()
    adm.execute("create resource group if not exists bench_small"
                " ru_per_sec = 60")
    adm.execute("create resource group if not exists bench_big"
                " ru_per_sec = 180")
    base = REGISTRY.snapshot()
    stop = threading.Event()

    def worker(group):
        s2 = d.new_session()
        s2.execute(f"set tidb_tpu_resource_group = '{group}'")
        s2.execute("set tidb_use_tpu = 1")
        while not stop.is_set():
            try:
                s2.query(Q6)
            except BaseException:  # noqa: BLE001 — throttles expected
                pass

    threads = [threading.Thread(target=worker, args=(g,))
               for g in ("bench_small", "bench_big")]
    for t in threads:
        t.start()
    time.sleep(4.0)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    snap = REGISTRY.snapshot()

    def delta(name):
        return snap.get(name, 0.0) - base.get(name, 0.0)

    ru_small = delta("resgroup_bench_small_ru_consumed_total")
    ru_big = delta("resgroup_bench_big_ru_consumed_total")
    out["fairness"] = {
        "small_ru": round(ru_small, 1),
        "big_ru": round(ru_big, 1),
        "ratio": round(ru_big / ru_small, 2) if ru_small > 0 else None,
        "target_ratio": 3.0,
        "throttled": delta("resgroup_throttled_total"),
    }
    log(f"fairness 1:3 quotas -> consumed RU {ru_small:.0f}:{ru_big:.0f}"
        f" (ratio {out['fairness']['ratio']})")
    return out


def dataplane_bench(n: int) -> dict:
    """Sharded data-plane receipt (ISSUE 18): warm Q6 scan throughput
    with the whole table resident on ONE member (LocalPlane degenerate
    path) vs hash-sharded across TWO in-process members — coordinator
    + worker planes over real loopback RPC, fragments for remotely
    owned partitions fetched cross-host — plus the exchange bytes the
    2-host leg actually moved."""
    import tempfile

    from tidb_tpu.coord import get_plane
    from tidb_tpu.coord.plane import (Coordinator, CoordinatorPlane,
                                      WorkerPlane)
    from tidb_tpu.dataplane import activate_dataplane, deactivate_dataplane
    from tidb_tpu.metrics import REGISTRY

    n = min(n, 65_536)  # 3 extra table builds; keep the legs modest
    reps = max(ITERS, 3)
    out: dict = {"rows": n}

    def _tid(sess):
        return sess.domain.catalog.info_schema().table(
            "test", "lineitem").id

    def _leg(sess):
        sess.execute("set tidb_use_tpu = 1")
        sess.execute(Q6)  # warm: compile + partition materialization
        t0 = time.perf_counter()
        for _ in range(reps):
            sess.execute(Q6)
        return (time.perf_counter() - t0) / reps

    def _until(pred, timeout=20.0):
        t0 = time.time()
        while time.time() - t0 < timeout and not pred():
            time.sleep(0.05)

    with tempfile.TemporaryDirectory() as td:
        # ---- 1-host leg: degenerate LocalPlane ownership -----------------
        s1 = build_lineitem(n)
        dp1 = activate_dataplane(s1.domain.storage, plane=get_plane(),
                                 pid=0, data_dir=os.path.join(td, "one"),
                                 serve=False)
        dp1.shard_table(_tid(s1))
        q0 = REGISTRY.get("dataplane_queries_total") or 0.0
        try:
            one_s = _leg(s1)
            snap1 = dp1.snapshot()
        finally:
            deactivate_dataplane(s1.domain.storage)
        served = (REGISTRY.get("dataplane_queries_total") or 0.0) - q0
        out["one_host_s"] = round(one_s, 4)
        out["one_host_rows_per_sec"] = round(n / one_s, 1)
        out["n_parts"] = max((t["n_parts"]
                              for t in snap1["tables"].values()),
                             default=0)
        if served <= 0:
            out["error"] = "1-host leg bypassed the data plane"
            return out

        # ---- 2-host leg: coordinator + worker member over loopback ------
        sA = build_lineitem(n)
        sB = build_lineitem(n)
        coord = Coordinator(port=0, lease_s=4.0, expect=2, self_pid=0)
        host, port = coord.start()
        cp = CoordinatorPlane(coord, pid=0).start((0,))
        wp = WorkerPlane(f"{host}:{port}", 1, lease_s=4.0).start((1,))
        _until(lambda: cp.view().formed and len(cp.view().members) == 2)
        dpA = activate_dataplane(sA.domain.storage, plane=cp, pid=0,
                                 data_dir=os.path.join(td, "a"))
        dpB = activate_dataplane(sB.domain.storage, plane=wp, pid=1,
                                 data_dir=os.path.join(td, "b"))
        _until(lambda: len(cp.view().addrs) == 2
               and len(wp.view().addrs) == 2)
        dpA.shard_table(_tid(sA))
        dpB.shard_table(_tid(sB))
        b0 = REGISTRY.get("dataplane_exchange_bytes_total") or 0.0
        f0 = REGISTRY.get("dataplane_remote_fragments_total") or 0.0
        try:
            two_s = _leg(sA)
        finally:
            deactivate_dataplane(sA.domain.storage)
            deactivate_dataplane(sB.domain.storage)
            try:
                wp.stop(leave=True)
            except Exception:  # noqa: BLE001 — lease may already be gone
                pass
            cp.stop()
    out["two_host_s"] = round(two_s, 4)
    out["two_host_rows_per_sec"] = round(n / two_s, 1)
    out["exchange_bytes_per_query"] = round(
        ((REGISTRY.get("dataplane_exchange_bytes_total") or 0.0) - b0)
        / (reps + 1), 1)
    out["remote_fragments"] = int(
        (REGISTRY.get("dataplane_remote_fragments_total") or 0.0) - f0)
    out["two_host_overhead_x"] = round(two_s / one_s, 2) if one_s else None
    log(f"dataplane scan: 1-host {out['one_host_rows_per_sec']:.0f} "
        f"rows/s vs 2-host {out['two_host_rows_per_sec']:.0f} rows/s, "
        f"{out['exchange_bytes_per_query']:.0f} exchange B/query")

    # ---- kill-recovery leg (ISSUE 20): RF=1 cold replay vs RF=2 ---------
    # replica promotion.  One member leaves mid-steady-state; the
    # receipt is the survivor's first post-loss query (re-shard
    # included) — the time replication buys back on the critical path.
    n_k = min(n, 16_384)
    out["kill_recovery"] = {"rows": n_k}
    for rf in (1, 2):
        with tempfile.TemporaryDirectory() as td:
            sA = build_lineitem(n_k)
            sB = build_lineitem(n_k)
            coord = Coordinator(port=0, lease_s=4.0, expect=2, self_pid=0)
            host, port = coord.start()
            cp = CoordinatorPlane(coord, pid=0).start((0,))
            wp = WorkerPlane(f"{host}:{port}", 1, lease_s=4.0).start((1,))
            _until(lambda: cp.view().formed
                   and len(cp.view().members) == 2)
            dpA = activate_dataplane(sA.domain.storage, plane=cp, pid=0,
                                     data_dir=os.path.join(td, "k"),
                                     rf=rf)
            dpB = activate_dataplane(sB.domain.storage, plane=wp, pid=1,
                                     data_dir=os.path.join(td, "k"),
                                     rf=rf)
            _until(lambda: len(cp.view().addrs) == 2
                   and len(wp.view().addrs) == 2)
            dpA.shard_table(_tid(sA))
            dpB.shard_table(_tid(sB))
            try:
                sA.execute("set tidb_use_tpu = 1")
                sA.execute(Q6)  # warm steady state
                p0 = REGISTRY.get(
                    "dataplane_replica_promotions_total") or 0.0
                c0 = REGISTRY.get("dataplane_cold_reloads_total") or 0.0
                wp.stop(leave=True)
                deactivate_dataplane(sB.domain.storage)
                _until(lambda: 1 not in cp.view().members)
                t0 = time.perf_counter()
                sA.execute(Q6)  # triggers the survivor's re-shard
                rec_s = time.perf_counter() - t0
            finally:
                deactivate_dataplane(sA.domain.storage)
                try:
                    wp.stop(leave=True)
                except Exception:  # noqa: BLE001 — already left
                    pass
                cp.stop()
            out["kill_recovery"][f"rf{rf}"] = {
                "recovery_s": round(rec_s, 4),
                "promotions": int((REGISTRY.get(
                    "dataplane_replica_promotions_total") or 0.0) - p0),
                "cold_reloads": int((REGISTRY.get(
                    "dataplane_cold_reloads_total") or 0.0) - c0),
            }
    kr = out["kill_recovery"]
    if kr.get("rf1") and kr.get("rf2") and kr["rf2"]["recovery_s"]:
        kr["rf2_speedup_x"] = round(
            kr["rf1"]["recovery_s"] / kr["rf2"]["recovery_s"], 2)
    log(f"dataplane kill-recovery: rf1 {kr['rf1']['recovery_s']*1e3:.0f}ms"
        f" ({kr['rf1']['cold_reloads']} cold) vs rf2 "
        f"{kr['rf2']['recovery_s']*1e3:.0f}ms "
        f"({kr['rf2']['promotions']} promotions, "
        f"{kr['rf2']['cold_reloads']} cold)")
    return out


def trace_overhead_bench(sess, iters: int = None) -> dict:
    """Trace-overhead receipt (ISSUE 4, extended by ISSUE 13): steady-
    state Q1 untraced vs traced vs traced+profiled.  The continuous
    profiler folds every finished trace into the flame windows, so the
    profiled leg is the real production configuration — both deltas
    must stay under 2%."""
    from tidb_tpu.trace import PROFILER

    iters = ITERS if iters is None else iters
    prof_prev = PROFILER.enabled
    try:
        sess.execute("set tidb_enable_slow_log = 0")
        _, t_off = time_query(sess, Q1, iters)
        PROFILER.enabled = False
        sess.execute("set tidb_enable_slow_log = 1")
        _, t_on = time_query(sess, Q1, iters)
        PROFILER.enabled = True
        _, t_prof = time_query(sess, Q1, iters)
    finally:
        PROFILER.enabled = prof_prev
        sess.execute("set tidb_enable_slow_log = 1")
    delta_pct = (t_on - t_off) / t_off * 100.0
    prof_pct = (t_prof - t_off) / t_off * 100.0
    return {
        "untraced_s": round(t_off, 5),
        "traced_s": round(t_on, 5),
        "profiled_s": round(t_prof, 5),
        "delta_pct": round(delta_pct, 3),
        "profiled_delta_pct": round(prof_pct, 3),
        "ok": delta_pct < 2.0,
        "profiled_ok": prof_pct < 2.0,
        "flame_stacks": len(PROFILER.folded().splitlines()),
    }


def _trace_span_sum(sess, sql: str, span_name: str, attr: str) -> int:
    """Run `sql` once under TRACE and sum `attr` over `span_name` spans
    (e.g. host-readback bytes across copr.readback)."""
    try:
        sess.execute("trace " + sql)
        tr = sess.last_trace
        if tr is None:
            return -1
        total = {"n": 0}

        def walk(s):
            if s.name == span_name:
                total["n"] += int((s.attrs or {}).get(attr, 0) or 0)
            for c in s.children:
                walk(c)

        walk(tr.root)
        return total["n"]
    except BaseException:  # noqa: BLE001 — receipt survives trace issues
        return -1


def mpp_grouped_bench(sess_m, n_li: int) -> dict:
    """Grouped-pushdown receipt: GROUP BY over the MPP shuffle join with
    the grouped partial agg merged ON DEVICE (only O(G) rows read back)
    vs the host-merge comparator (TIDB_TPU_MPP_GROUPED=0: same device
    join, every joined row ships to the host and aggregates there)."""
    from tidb_tpu.metrics import REGISTRY

    GQ = ("select o_shippriority, count(*), sum(l_extendedprice),"
          " max(l_discount) from lineitem join orders"
          " on l_orderkey = o_orderkey where l_shipdate > '1995-03-15'"
          " group by o_shippriority")
    prior = os.environ.get("TIDB_TPU_MPP_GROUPED")
    try:
        os.environ["TIDB_TPU_MPP_GROUPED"] = "1"
        m0 = REGISTRY.snapshot()
        _, g_s = time_query(sess_m, GQ, ITERS)
        m1 = REGISTRY.snapshot()
        g_bytes = _trace_span_sum(sess_m, GQ, "copr.readback", "bytes")
        pushed = (m1.get("mpp_grouped_agg_pushed_total", 0)
                  - m0.get("mpp_grouped_agg_pushed_total", 0)) > 0
        os.environ["TIDB_TPU_MPP_GROUPED"] = "0"
        _, h_s = time_query(sess_m, GQ, ITERS)
        h_bytes = _trace_span_sum(sess_m, GQ, "copr.readback", "bytes")
    finally:
        if prior is None:
            os.environ.pop("TIDB_TPU_MPP_GROUPED", None)
        else:
            os.environ["TIDB_TPU_MPP_GROUPED"] = prior
    out = {
        "rows": n_li,
        "grouped_s": round(g_s, 5),
        "host_merge_s": round(h_s, 5),
        "grouped_rows_per_sec": round(n_li / g_s, 1),
        "host_merge_rows_per_sec": round(n_li / h_s, 1),
        "speedup": round(h_s / g_s, 2),
        "served_by_grouped_pushdown": pushed,
        "grouped_readback_bytes": g_bytes,
        "host_merge_readback_bytes": h_bytes,
    }
    log(f"MPP grouped agg: pushed={g_s:.4f}s host-merge={h_s:.4f}s "
        f"-> {h_s / g_s:.2f}x | readback {g_bytes} vs {h_bytes} bytes")
    return out


def host_tail_bench(sess, n: int) -> dict:
    """Zero-host-tail receipt (ISSUE 11): the shapes that used to split
    to a host tail — computed string group keys (device dict-code
    re-mapping) and multi-column TopN (packed compound ordering) — run
    fully fused vs the TIDB_TPU_FUSION=0 ladder comparator, with the
    fusion_splits_total delta across the corpus (must stay 0 fused)."""
    from tidb_tpu.metrics import REGISTRY

    shapes = (
        ("computed_key",
         "select concat(l_returnflag, '#'), count(*), sum(l_quantity)"
         " from lineitem group by concat(l_returnflag, '#')"),
        ("compound_order",
         "select l_orderkey from lineitem"
         " order by l_returnflag desc, l_shipdate, l_orderkey limit 10"),
    )
    from tidb_tpu.copr.fusion import SPLIT_REASONS

    def _reason_snap():
        snap = REGISTRY.snapshot()
        return {r: snap.get("fusion_splits_reason_"
                            + r.replace("-", "_") + "_total", 0)
                for r in SPLIT_REASONS}

    out = {}
    base_reasons = _reason_snap()  # deltas, like every other field
    prior = os.environ.get("TIDB_TPU_FUSION")
    for qname, sql in shapes:
        try:
            os.environ["TIDB_TPU_FUSION"] = "1"
            s0 = REGISTRY.get("fusion_splits_total")
            _, fused_s = time_query(sess, sql, ITERS)
            splits = REGISTRY.get("fusion_splits_total") - s0
            fused_d = _count_device_dispatches(sess, sql)
            os.environ["TIDB_TPU_FUSION"] = "0"
            _, unf_s = time_query(sess, sql, ITERS)
        finally:
            if prior is None:
                os.environ.pop("TIDB_TPU_FUSION", None)
            else:
                os.environ["TIDB_TPU_FUSION"] = prior
        out[qname] = {
            "fused_rows_per_sec": round(n / fused_s, 1),
            "unfused_rows_per_sec": round(n / unf_s, 1),
            "fused_dispatches": fused_d,
            "fusion_splits": int(splits),
            "speedup": round(unf_s / fused_s, 2),
        }
        log(f"host_tail {qname}: fused={n / fused_s:,.0f} rows/s "
            f"({fused_d} dispatches, {int(splits)} splits) vs "
            f"unfused={n / unf_s:,.0f} rows/s -> {unf_s / fused_s:.2f}x")
    end_reasons = _reason_snap()
    out["splits_by_reason"] = {
        r: int(end_reasons[r] - base_reasons[r]) for r in SPLIT_REASONS
    }
    return out


def layout_bench(sess, n: int) -> dict:
    """Adaptive-layout receipt (ISSUE 10) on a price-grid table (one
    group key + six low-NDV DOUBLE measure columns — the wide-wire
    shape the cold tier exists for), with the hot-tier byte cap set to
    ~a fifth of the working set:

    - ADAPTIVE (TIDB_TPU_LAYOUT on): the tuner keeps the highest-
      priority column hot within the budget and parks the measure
      columns on device as 2-4 bit packed blocks that decode
      in-register — steady state runs with ZERO host reloads (cold
      hits counted);
    - FIXED (TIDB_TPU_LAYOUT=0): the pre-layout hot-only byte-LRU —
      the working set over the cap re-transfers its f64 wire arrays
      every query (the full-reload comparator).

    Reports steady qps for both legs + the autotuned/fixed speedup;
    legs interleave and keep per-leg bests so host noise cancels."""
    import numpy as _np

    import tidb_tpu.layout.coldtier as coldtier
    from tidb_tpu.copr.parallel import MESH_CACHE
    from tidb_tpu.layout import LAYOUT, set_hot_cap_bytes
    from tidb_tpu.layout.autotuner import _table_wire_bytes
    from tidb_tpu.metrics import REGISTRY

    domain = sess.domain
    n_rows = min(max(n, 1 << 18), 1 << 20)
    s = domain.new_session()
    isc = domain.catalog.info_schema()
    if not isc.has_table("test", "layout_grid"):
        s.execute("create table layout_grid (g bigint, "
                  + ", ".join(f"v{i} double" for i in range(6)) + ")")
        rng = _np.random.default_rng(11)
        ladder = _np.round(_np.linspace(0.5, 3.5, 13), 2)
        tg = domain.catalog.info_schema().table("test", "layout_grid")
        domain.storage.table(tg.id).bulk_load_arrays(
            [rng.integers(0, 4, n_rows, dtype=_np.int64)]
            + [ladder[rng.integers(0, 13, n_rows)] for _ in range(6)],
            ts=domain.storage.current_ts())
    store = domain.storage.table(
        domain.catalog.info_schema().table("test", "layout_grid").id)
    wire = _table_wire_bytes(store)
    cap = max(int(wire * 0.2), 1 << 20)
    LQ = ("select g, count(*), " + ", ".join(
        f"sum(v{i})" for i in range(6)) + " from layout_grid group by g")
    out = {"rows": n_rows, "table_wire_bytes": wire,
           "hot_cap_bytes": cap}
    old_cap = MESH_CACHE._c.capacity
    saved = {k: os.environ.get(k) for k in
             ("TIDB_TPU_HBM_BYTES", "TIDB_TPU_LAYOUT",
              "TIDB_TPU_LAYOUT_RETUNE_S")}
    try:
        os.environ["TIDB_TPU_LAYOUT_RETUNE_S"] = "0"
        set_hot_cap_bytes(cap)

        def leg(adaptive: bool) -> float:
            if adaptive:
                os.environ.pop("TIDB_TPU_LAYOUT", None)
            else:
                os.environ["TIDB_TPU_LAYOUT"] = "0"
            MESH_CACHE.clear()
            coldtier.clear()
            LAYOUT.reset()
            _, best = time_query(s, LQ, ITERS + 5)
            return best

        # interleave the legs and keep each leg's best across rounds:
        # the structural cost (per-query reloads vs in-kernel decode)
        # survives a min; host noise does not
        m0 = REGISTRY.snapshot()
        ad_s = leg(True)
        m1 = REGISTRY.snapshot()
        fx_s = leg(False)
        ad_s = min(ad_s, leg(True))
        fx_s = min(fx_s, leg(False))
        out.update({
            "autotuned_s": round(ad_s, 5),
            "fixed_full_reload_s": round(fx_s, 5),
            "autotuned_rows_per_sec": round(n_rows / ad_s, 1),
            "fixed_rows_per_sec": round(n_rows / fx_s, 1),
            "speedup": round(fx_s / ad_s, 2),
            "cold_hits": round(
                m1.get("layout_cold_hits_total", 0)
                - m0.get("layout_cold_hits_total", 0)),
            "cold_demotions": round(
                m1.get("layout_cold_demotions_total", 0)
                - m0.get("layout_cold_demotions_total", 0)),
        })
        log(f"layout: autotuned={n_rows / ad_s:,.0f} rows/s vs "
            f"fixed/full-reload={n_rows / fx_s:,.0f} rows/s -> "
            f"{fx_s / ad_s:.2f}x (cap {cap} / wire {wire} bytes)")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        MESH_CACHE._c.capacity = old_cap
        MESH_CACHE.clear()
        coldtier.clear()
        LAYOUT.reset()
    return out


def tpch_matrix_bench(scale: float = 2.0) -> dict:
    """Full-suite residency matrix (ISSUE 12): all 22 TPC-H queries
    classified fused (every scan/join/agg engine-attributed to the
    device: mesh or mpp) / partial (mixed) / host, with steady-state
    rows/s and device-dispatch counts — the fused fraction is the
    PR-over-PR tracking number for the paper's all-22-on-device arc."""
    import re

    from tidb_tpu.tpch_data import (TPCH_N_TABLES, TPCH_QUERIES,
                                    build_tpch_domain)

    sess = build_tpch_domain(scale=scale)
    # per-table row counts measured off the built domain (not
    # re-derived formulas, which would silently drift from the recipe)
    sess.execute("set tidb_use_tpu = 0")
    counts = {t: sess.query(f"select count(*) from {t}")[0][0]
              for t in ("lineitem", "orders", "customer", "part",
                        "partsupp", "supplier", "nation", "region")}
    sess.execute("set tidb_use_tpu = 1")
    out: dict = {"scale": scale, "queries": {}}
    matrix = {"fused": [], "partial": [], "host": []}
    for name in sorted(TPCH_QUERIES,
                       key=lambda q: int(q.lstrip("q"))):
        sql = TPCH_QUERIES[name]
        entry: dict = {"n_tables": TPCH_N_TABLES[name]}
        try:
            rows_in = sum(c for t, c in counts.items()
                          if re.search(rf"\b{t}\b", sql))
            _, secs = time_query(sess, sql, 1)
            engines = set()
            for r in sess.execute("explain analyze " + sql)[0].rows:
                for m in re.finditer(r"engine:([^\s|]+)", str(r[4])):
                    engines.add(m.group(1).rstrip(","))
            device = {e for e in engines
                      if e.startswith(("mesh", "mpp-"))}
            if engines and device == engines:
                klass = "fused"
            elif device:
                klass = "partial"
            else:
                klass = "host"
            entry.update({
                "class": klass,
                "engines": sorted(engines),
                "s": round(secs, 4),
                "rows_per_sec": round(rows_in / secs, 1),
                "device_dispatches": _count_device_dispatches(sess, sql),
            })
        except BaseException as e:  # noqa: BLE001 — receipt survives
            klass = "host"
            entry.update({"class": "host", "error": repr(e)})
        matrix[klass].append(name)
        out["queries"][name] = entry
    out["matrix"] = matrix
    out["fused_count"] = len(matrix["fused"])
    out["fused_ge4_tables"] = [q for q in matrix["fused"]
                               if TPCH_N_TABLES[q] >= 4]
    log(f"tpch_matrix: fused={len(matrix['fused'])}/22 "
        f"(>=4-table fused: {out['fused_ge4_tables']}) "
        f"partial={len(matrix['partial'])} host={len(matrix['host'])}")
    return out


def _run(state: dict):
    try:
        _run_inner(state)
    except BaseException as e:  # surfaced in the output JSON
        state["worker_error"] = repr(e)
        import traceback

        traceback.print_exc(file=sys.stderr)


def _run_inner(state: dict):
    state.setdefault("phases", {})["worker_start"] = round(
        time.perf_counter() - T0, 1)
    scales = [s for s in (262_144, 1_048_576, 4_000_000, 64_000_000,
                          MAX_ROWS)
              if s <= MAX_ROWS]
    if not scales:
        scales = [MAX_ROWS]
    scales = sorted(set(scales))
    # chaos knob: simulate the round-1/3/5 failure mode (a wedge at a
    # LATE scale) — earlier scales' receipts must survive in the emitted
    # detail and in BENCH_PARTIAL.json (test-asserted)
    fail_at = int(os.environ.get("BENCH_FAIL_AT_SCALE", "0"))
    for n in scales:
        # only attempt the next (bigger) scale while at least 35% of the
        # wall budget remains — a completed smaller scale is always kept
        if state.get("q1") and remaining() < 0.35 * WALL_LIMIT:
            log(f"skipping scale {n}: {remaining():.0f}s left")
            break
        if fail_at and n >= fail_at:
            raise RuntimeError(f"injected late-scale failure at {n} rows")
        log(f"loading {n} rows...")
        t0 = time.perf_counter()
        sess = build_lineitem(n)
        load_s = time.perf_counter() - t0
        log(f"loaded {n} rows in {load_s:.1f}s")
        state["loaded_rows"] = n

        sess.execute("set tidb_use_tpu = 1")
        log("Q1 tpu warmup (transfer + compile)...")
        q1_warm, q1_best = time_query(sess, Q1, ITERS)
        log(f"Q1 tpu: warm={q1_warm:.3f}s steady={q1_best:.4f}s "
            f"({n / q1_best:,.0f} rows/s)")
        q6_warm, q6_best = time_query(sess, Q6, ITERS)
        log(f"Q6 tpu: warm={q6_warm:.3f}s steady={q6_best:.4f}s")
        state["q1"] = {
            "rows": n, "warm_s": round(q1_warm, 4),
            "steady_s": round(q1_best, 5),
            "rows_per_sec": round(n / q1_best, 1),
        }
        state["q6"] = {
            "rows": n, "warm_s": round(q6_warm, 4),
            "steady_s": round(q6_best, 5),
            "rows_per_sec": round(n / q6_best, 1),
        }
        state["load_s"] = round(load_s, 2)
        # whole-fragment fusion receipt: fused one-launch dispatch vs the
        # per-tile dispatch loop, with dispatch counts (ISSUE 7)
        fus = None
        if remaining() > 0.2 * WALL_LIMIT:
            try:
                fus = fusion_bench(sess, n)
                state["fusion"] = fus
            except BaseException as e:  # noqa: BLE001 — receipt survives
                fus = {"error": repr(e)}
        # per-scale receipt: a later-scale wedge (load hang, tunnel drop)
        # must never zero the measured trajectory — every completed scale
        # survives in the emitted detail
        state.setdefault("scales", []).append({
            "rows": n, "load_s": round(load_s, 2),
            "q1_rows_per_sec": round(n / q1_best, 1),
            "q6_rows_per_sec": round(n / q6_best, 1),
            "fusion": fus,
            "at_s": round(time.perf_counter() - T0, 1),
        })
        state["phases"][f"scale_{n}_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # trace-overhead receipt: the span recorder runs on every statement
    # when the slow log is enabled (the default) — steady-state Q1 with
    # tracing off vs on vs on+profiler must stay within 2% (ISSUE 4
    # acceptance, profiler leg added by ISSUE 13)
    if state.get("q1") and remaining() > 60:
        to = trace_overhead_bench(sess)
        state["trace_overhead"] = to
        log(f"trace overhead: off={to['untraced_s']}s "
            f"on={to['traced_s']}s (+{to['delta_pct']}%) "
            f"profiled={to['profiled_s']}s (+{to['profiled_delta_pct']}%)"
            f" ok={to['ok']} profiled_ok={to['profiled_ok']}")
        state["phases"]["trace_overhead_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # lock-order witness receipt (ISSUE 16): corpus replay with the
    # witness on (fresh CPU subprocess; the tunnel is irrelevant here)
    if remaining() > 90:
        try:
            lc = lockcheck_bench()
            state["lockcheck"] = lc
            log(f"lockcheck: acquisitions={lc['acquisitions']} "
                f"max_depth={lc['max_held_depth']} "
                f"violations={lc['violations']} ok={lc['ok']}")
        except BaseException as e:  # noqa: BLE001
            state["lockcheck"] = {"error": repr(e)}
        state["phases"]["lockcheck_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # interruptible chunked dispatch (ISSUE 17): KILL-to-return latency
    # chunked vs the unchunked comparator + 2-group RU fairness
    if state.get("q1") and remaining() > 90:
        try:
            state["kill_latency"] = kill_latency_bench(
                sess, state.get("loaded_rows", 262_144))
        except BaseException as e:  # noqa: BLE001
            state["kill_latency"] = {"error": repr(e)}
        state["phases"]["kill_latency_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # sharded data plane (ISSUE 18): 1-host vs 2-host scan throughput
    # plus the cross-host fragment bytes actually exchanged
    if state.get("q1") and remaining() > 120:
        try:
            state["dataplane_scan"] = dataplane_bench(
                state.get("loaded_rows", 65_536))
        except BaseException as e:  # noqa: BLE001
            state["dataplane_scan"] = {"error": repr(e)}
        state["phases"]["dataplane_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # Q3-shaped device join: scan+filter+JOIN+partial agg in ONE device
    # program (JoinLookupIR) vs the CPU oracle's root-side hash join
    if state.get("q1") and remaining() > 180:
        from tidb_tpu.tpch_data import build_q3_tables

        n_li = min(state.get("loaded_rows", 4_000_000), 16_000_000)
        n_ord = max(n_li // 8, 1000)
        log(f"Q3 join bench: {n_li} lineitem x {n_ord} orders...")
        sess3 = build_q3_tables(n_li, n_ord)
        Q3 = _q3_sql()
        plan = [r[0] for r in sess3.execute("explain " + Q3)[0].rows]
        in_cop = any("DeviceJoinReader" in op for op in plan)
        sess3.execute("set tidb_use_tpu = 1")
        q3_warm, q3_best = time_query(sess3, Q3, ITERS)
        sess3.execute("set tidb_use_tpu = 0")
        _, q3_cpu = time_query(sess3, Q3, 1)
        state["q3"] = {
            "rows": n_li, "warm_s": round(q3_warm, 4),
            "steady_s": round(q3_best, 5),
            "cpu_s": round(q3_cpu, 4),
            "speedup": round(q3_cpu / q3_best, 2),
            "join_in_cop_task": in_cop,
        }
        log(f"Q3 tpu: steady={q3_best:.4f}s cpu={q3_cpu:.3f}s "
            f"speedup={q3_cpu / q3_best:.1f}x cop-join={in_cop}")
        state["phases"]["q3_done"] = round(time.perf_counter() - T0, 1)
        persist_partial(state)

    # MPP shuffle join: both sides too big to broadcast — the exchange
    # engine (tidb_tpu/mpp) hash-partitions both scans across the mesh
    # with all_to_all and joins co-partitioned shards on device, vs the
    # same query on the root-side host hash join
    if state.get("q1") and remaining() > 150:
        from tidb_tpu.metrics import REGISTRY
        from tidb_tpu.tpch_data import build_q3_tables

        n_li = min(state.get("loaded_rows", 2_000_000), 8_000_000)
        n_ord = max(n_li // 4, 20_000)  # big build side: shuffle territory
        log(f"MPP join bench: {n_li} lineitem x {n_ord} orders...")
        sess_m = build_q3_tables(n_li, n_ord)
        MPPQ = ("select count(*), sum(l_extendedprice), max(o_shippriority)"
                " from lineitem join orders on l_orderkey = o_orderkey"
                " where l_shipdate > '1995-03-15'")
        sess_m.execute("set tidb_enforce_mpp = 1")
        plan = [r[0] for r in sess_m.execute("explain " + MPPQ)[0].rows]
        in_mpp = any("ExchangeSender" in op for op in plan)
        m0 = REGISTRY.snapshot()
        mpp_warm, mpp_best = time_query(sess_m, MPPQ, ITERS)
        m1 = REGISTRY.snapshot()
        served = (m1.get("mpp_joins_total", 0) - m0.get("mpp_joins_total", 0)
                  > 0)
        sess_m.execute("set tidb_allow_mpp = 0")
        sess_m.execute("set tidb_enforce_mpp = 0")
        _, mpp_host = time_query(sess_m, MPPQ, 1)
        state["mpp_join"] = {
            "rows": n_li, "build_rows": n_ord,
            "warm_s": round(mpp_warm, 4),
            "steady_s": round(mpp_best, 5),
            "host_join_s": round(mpp_host, 4),
            "speedup": round(mpp_host / mpp_best, 2),
            "plan_is_exchange": in_mpp,
            "served_by_mpp": served,
            "exchange_bytes": round(
                m1.get("mpp_exchange_bytes_total", 0)
                - m0.get("mpp_exchange_bytes_total", 0)),
        }
        log(f"MPP join: steady={mpp_best:.4f}s host={mpp_host:.3f}s "
            f"speedup={mpp_host / mpp_best:.1f}x exchange-plan={in_mpp}")
        state["phases"]["mpp_join_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

        # grouped partial aggregates below the exchange (ISSUE 8):
        # device-merged GROUP BY pushdown vs the host-merge rows path
        if remaining() > 90:
            try:
                sess_m.execute("set tidb_allow_mpp = 1")
                sess_m.execute("set tidb_enforce_mpp = 1")
                state["mpp_grouped_agg"] = mpp_grouped_bench(sess_m, n_li)
            except BaseException as e:  # noqa: BLE001 — receipt survives
                state["mpp_grouped_agg"] = {"error": repr(e)}
            state["phases"]["mpp_grouped_agg_done"] = round(
                time.perf_counter() - T0, 1)
            persist_partial(state)

    # adaptive-layout receipt (ISSUE 10): cold-tier qps vs the
    # fixed-layout full-reload comparator under a squeezed byte cap
    if state.get("q1") and remaining() > 90:
        try:
            state["layout"] = layout_bench(sess, state["loaded_rows"])
        except BaseException as e:  # noqa: BLE001 — receipt survives
            state["layout"] = {"error": repr(e)}
        state["phases"]["layout_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # zero-host-tail receipt (ISSUE 11): computed-key + compound-order
    # shapes fused vs the ladder comparator, splits-by-reason breakdown
    if state.get("q1") and remaining() > 60:
        try:
            state["host_tail"] = host_tail_bench(sess,
                                                 state["loaded_rows"])
        except BaseException as e:  # noqa: BLE001 — receipt survives
            state["host_tail"] = {"error": repr(e)}
        state["phases"]["host_tail_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # TPC-H residency matrix (ISSUE 12): per-query fused/partial/host
    # classification over all 22 queries — the join-tree compiler's
    # fused fraction, tracked PR over PR.  Gate above the stubbed-loop
    # wall budget (tests run _run_inner with WALL_LIMIT=140): the
    # matrix builds its own real domain, ~22 compiles
    if remaining() > 240:
        try:
            state["tpch_matrix"] = tpch_matrix_bench()
        except BaseException as e:  # noqa: BLE001 — receipt survives
            state["tpch_matrix"] = {"error": repr(e)}
        state["phases"]["tpch_matrix_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # concurrent-client serving bench: N wire clients of mixed TPC-H +
    # point lookups through the real server (admission, shape buckets,
    # micro-batcher under contention); reports p50/p99 + batched-vs-
    # unbatched point-agg throughput
    if state.get("q1") and remaining() > 150 \
            and os.environ.get("BENCH_CONCURRENT", "1") == "1":
        try:
            concurrent_bench(state)
        except BaseException as e:  # noqa: BLE001 — receipt must survive
            state["concurrent"] = {"error": repr(e)}
            log(f"concurrent bench failed: {e!r}")
        state["phases"]["concurrent_done"] = round(
            time.perf_counter() - T0, 1)
        persist_partial(state)

    # CPU oracle baseline on a bounded subsample, scaled linearly
    n = state.get("loaded_rows", 0)
    if n and remaining() > 60:
        cpu_rows = min(n, 1_000_000)
        log(f"cpu baseline on {cpu_rows} rows...")
        sess = build_lineitem(cpu_rows)
        sess.execute("set tidb_use_tpu = 0")
        _, q1_cpu = time_query(sess, Q1, 1)
        _, q6_cpu = time_query(sess, Q6, 1)
        scale = n / cpu_rows
        state["cpu"] = {
            "rows": cpu_rows,
            "q1_s_scaled": round(q1_cpu * scale, 4),
            "q6_s_scaled": round(q6_cpu * scale, 4),
        }
        log(f"cpu baseline: q1={q1_cpu:.3f}s q6={q6_cpu:.3f}s "
            f"(x{scale:.0f} scaled)")
    state["done"] = True
    persist_partial(state)


def persist_partial(state: dict):
    """Crash insurance: after every phase the full state lands in
    BENCH_PARTIAL.json (path overridable via BENCH_PARTIAL_PATH), so an
    externally killed run still leaves its best measured numbers on disk
    for the judge."""
    try:
        snap = dict(state)
        snap["phases"] = dict(snap.get("phases") or {})
        snap["scales"] = list(snap.get("scales") or [])
        path = os.environ.get("BENCH_PARTIAL_PATH") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_PARTIAL.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
    except Exception:
        pass  # insurance must never kill the bench


def emit(state: dict):
    # snapshot worker-shared mutables: the worker may still be appending
    # phase marks while we serialize (partial-emit path)
    state = dict(state)
    state["phases"] = dict(state.get("phases") or {})
    q1 = state.get("q1")
    if q1:
        cpu = state.get("cpu", {})
        q6 = state.get("q6", {})
        vs = None
        if cpu.get("q1_s_scaled"):
            vs = round(cpu["q1_s_scaled"] / q1["steady_s"], 3)
        out = {
            "metric": "tpch_q1_rows_per_sec",
            "value": q1["rows_per_sec"],
            "unit": "rows/s",
            "vs_baseline": vs,
            "detail": {
                "rows": q1["rows"],
                "q1_steady_s": q1["steady_s"],
                "q1_warm_s": q1["warm_s"],
                "q1_cpu_est_s": cpu.get("q1_s_scaled"),
                "q6_rows_per_sec": q6.get("rows_per_sec"),
                "q6_speedup": (
                    round(cpu["q6_s_scaled"] / q6["steady_s"], 3)
                    if cpu.get("q6_s_scaled") and q6.get("steady_s") else None
                ),
                "load_s": state.get("load_s"),
                "load_rows_per_sec": (
                    round(state["loaded_rows"] / state["load_s"], 1)
                    if state.get("load_s") and state.get("loaded_rows")
                    else None
                ),
                "q3": state.get("q3"),
                "mpp_join": state.get("mpp_join"),
                "mpp_grouped_agg": state.get("mpp_grouped_agg"),
                "concurrent": state.get("concurrent"),
                "fusion": state.get("fusion"),
                "layout": state.get("layout"),
                "scales": state.get("scales"),
                "trace_overhead": state.get("trace_overhead"),
                "lockcheck": state.get("lockcheck"),
                "devices": state.get("devices"),
                "complete": bool(state.get("done")),
                "worker_error": state.get("worker_error"),
                "phases": state.get("phases"),
                "preflight_attempts": state.get("preflight_attempts"),
            },
        }
    else:
        out = {
            "metric": "tpch_q1_rows_per_sec", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "detail": {
                "error": state.get(
                    "preflight_error",
                    state.get(
                        "worker_error",
                        "bench timed out before first Q1 completed",
                    ),
                ),
                "error_class": state.get("preflight_error_class"),
                "loaded_rows": state.get("loaded_rows", 0),
                "scales": state.get("scales"),
                "devices": state.get("devices"),
                "wall_limit_s": WALL_LIMIT,
                "phases": state.get("phases"),
                "preflight_attempts": state.get("preflight_attempts"),
                "host_fallback": state.get("host_fallback"),
            },
        }
    print(json.dumps(out), flush=True)


def main():
    state: dict = {}
    emitted = [False]
    emit_mu = threading.Lock()

    def emit_once():
        with emit_mu:
            if not emitted[0]:
                emit(state)
                emitted[0] = True

    def on_term(signum, frame):
        # the driver's timeout must still harvest our best numbers.
        # Signal handlers run ON the main thread: if the normal end-of-run
        # emit already holds the lock (we interrupted it mid-write), a
        # blocking acquire would self-deadlock and os._exit would truncate
        # the line — so try-acquire, and when busy just return and let the
        # interrupted emit finish on the resumed outer frame.
        log(f"signal {signum}: emitting best state before exit")
        persist_partial(state)
        if emit_mu.acquire(blocking=False):
            try:
                if not emitted[0]:
                    emit(state)
                    emitted[0] = True
            finally:
                emit_mu.release()
            os._exit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, on_term)
        except (ValueError, OSError):
            pass
    # the host-side fallback worker runs IN PARALLEL with the preflight:
    # a wedged tunnel still commits a nonzero CPU receipt (persisted the
    # moment the child finishes, even if the preflight is still spinning
    # when the driver's timeout harvests us)
    hf = start_parallel_fallback(state)
    if not preflight(state):
        host_side_fallback(state, parallel=hf)
        persist_partial(state)
        emit_once()
        return
    cancel_parallel_fallback(hf, state)
    worker = threading.Thread(target=_run, args=(state,), daemon=True)
    worker.start()
    # reserve time to print: join with a margin before the hard limit
    worker.join(max(remaining() - 10, 5))
    if worker.is_alive():
        log("wall budget reached with worker still running; emitting "
            "partial results")
    emit_once()


if __name__ == "__main__":
    if "--host-fallback-worker" in sys.argv:
        _host_fallback_worker()
    else:
        main()
