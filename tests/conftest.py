"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so every multi-chip sharding path
(mesh creation, shard_map scans, psum merges) executes without TPU hardware —
the moral equivalent of the reference testing the whole distributed stack
against in-process mocktikv (store/mockstore/tikv.go:100).
"""

import os

# Small device tiles so ordinary test tables (a few thousand rows) span
# multiple tiles AND multiple mesh shards — the cross-tile merge, deletion
# masks beyond tile 0, and shard_map collective paths all execute under test.
os.environ.setdefault("TIDB_TPU_TILE", "1024")

# Run the whole suite under the lock-order witness (ISSUE 16): every
# make_lock/make_rlock returns a RankedLock that raises on rank
# inversion.  Must be set before tidb_tpu is imported anywhere — the
# factories read it at lock construction time.
os.environ.setdefault("TIDB_TPU_LOCKCHECK", "1")

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon TPU-tunnel PJRT plugin and
# force-sets jax_platforms to "axon,cpu" in EVERY process; pin it back so
# unit tests never touch the tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _no_leaked_failpoints():
    """A failpoint left armed by one test silently injects faults into
    every later test — fail the LEAKING test, not its victims.  Use the
    scoped `with failpoint(name, action):` manager (store/fault.py) to
    make disarm structural."""
    from tidb_tpu.store.fault import FAILPOINTS

    yield
    leaked = FAILPOINTS.armed()
    if leaked:
        FAILPOINTS.clear()
        pytest.fail(f"test leaked armed failpoints: {leaked}")


@pytest.fixture(autouse=True)
def _no_lock_order_violations():
    """The witness raises LockOrderError at the acquire site, but a
    violation swallowed by a broad except (RPC boundaries, hook
    dispatch) still counts — fail the test that produced it."""
    from tidb_tpu.util_concurrency import witness_stats

    before = witness_stats()["violations"]
    yield
    after = witness_stats()["violations"]
    if after > before:
        pytest.fail(
            f"lock-order witness recorded {after - before} violation(s)"
            " during this test (TIDB_TPU_LOCKCHECK)")
