"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so every multi-chip sharding path
(mesh creation, shard_map scans, psum merges) executes without TPU hardware —
the moral equivalent of the reference testing the whole distributed stack
against in-process mocktikv (store/mockstore/tikv.go:100).
"""

import os

# Small device tiles so ordinary test tables (a few thousand rows) span
# multiple tiles AND multiple mesh shards — the cross-tile merge, deletion
# masks beyond tile 0, and shard_map collective paths all execute under test.
os.environ.setdefault("TIDB_TPU_TILE", "1024")

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon TPU-tunnel PJRT plugin and
# force-sets jax_platforms to "axon,cpu" in EVERY process; pin it back so
# unit tests never touch the tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _no_leaked_failpoints():
    """A failpoint left armed by one test silently injects faults into
    every later test — fail the LEAKING test, not its victims.  Use the
    scoped `with failpoint(name, action):` manager (store/fault.py) to
    make disarm structural."""
    from tidb_tpu.store.fault import FAILPOINTS

    yield
    leaked = FAILPOINTS.armed()
    if leaked:
        FAILPOINTS.clear()
        pytest.fail(f"test leaked armed failpoints: {leaked}")
