"""Coordination-plane chaos worker: ONE process of the 2-process
failover / rolling-restart acceptance test (tests/test_coord.py).

Unlike tests/multihost_worker.py this does NOT join jax.distributed —
each worker owns a private 4-virtual-device CPU mesh while the TEST
process runs the Coordinator, so the test exercises exactly what the
control plane owns across real OS processes: epoch-numbered membership
(lease expiry when a worker is SIGKILLed mid-query), cross-host span
forwarding, and session-state handoff across a restart.  The worker
checkpoints its prepared session EAGERLY (not only at drain), so even a
hard-killed incarnation's sessions replay when the pid rejoins.

argv: [process_id, coordinator_port].  Env knobs: COORD_LEASE_S,
COORD_WORKER_MAX_S (self-terminate budget).
"""

import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("TIDB_TPU_TILE", "1024")

    import jax

    jax.config.update("jax_platforms", "cpu")

    from tidb_tpu.coord import activate_worker
    from tidb_tpu.lifecycle import (
        collect_session_states,
        replay_session_states,
    )
    from tidb_tpu.metrics import REGISTRY
    from tidb_tpu.tpch_data import build_lineitem

    lease_s = float(os.environ.get("COORD_LEASE_S", "1.5"))
    max_s = float(os.environ.get("COORD_WORKER_MAX_S", "120"))
    t0 = time.monotonic()

    sess = build_lineitem(8192, regions=4)
    dom = sess.domain
    plane = activate_worker(("127.0.0.1", port), pid=pid,
                            devices=[d.id for d in jax.devices()],
                            lease_s=lease_s)

    # a previous incarnation of this pid parked sessions? replay them and
    # prove the prepared statement still executes (rolling restart)
    states = plane.take_handoff()
    n = replay_session_states(dom, states)
    if n:
        rsess = next(s for s in dom.sessions.values()
                     if getattr(s, "handoff_origin", None) is not None)
        rows = rsess.query("execute p_cnt")
        print(f"HANDOFF_REPLAYED pid={pid} n={n} rows={rows[0][0]} "
              f"sysvar={rsess.vars.get_int('tidb_slow_log_threshold')}",
              flush=True)

    # prepare a session and checkpoint it eagerly: SIGKILL must not lose it
    psess = dom.new_session()
    psess.execute("set tidb_slow_log_threshold = 4321")
    psess.execute("prepare p_cnt from 'select count(*) from lineitem'")
    plane.handoff_put(collect_session_states(dom))

    # one traced statement: its span tree rejoins the coordinator's ring
    sess.execute("trace format='row' select count(*) from lineitem")

    print(f"READY pid={pid}", flush=True)

    q6 = ("select sum(l_extendedprice * l_discount) from lineitem"
          " where l_discount between 0.05 and 0.07 and l_quantity < 24")
    sess.execute("set tidb_use_tpu = 0")
    want = sess.query(q6)[0][0]
    sess.execute("set tidb_use_tpu = 1")

    stop = [False]
    signal.signal(signal.SIGTERM, lambda *_a: stop.__setitem__(0, True))

    rounds = 0
    while not stop[0] and time.monotonic() - t0 < max_s:
        m0 = REGISTRY.get("mesh_scans_total")
        got = sess.query(q6)[0][0]
        ok = abs(got - want) <= 1e-9 * max(1.0, abs(want))
        mesh = int(REGISTRY.get("mesh_scans_total") > m0)
        print(f"ROUND pid={pid} n={rounds} epoch={plane.current_epoch()} "
              f"ok={int(ok)} mesh={mesh}", flush=True)
        rounds += 1
        time.sleep(0.05)

    # graceful drain: final handoff + immediate leave (epoch bumps NOW)
    plane.handoff_put(collect_session_states(dom))
    plane.leave()
    plane.stop()
    print(f"DRAINED pid={pid} rounds={rounds}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
