"""Sharded-data-plane chaos worker: ONE process of the 2-process
acceptance test (tests/test_dataplane_procs.py).

Each worker builds the SAME deterministic lineitem table, joins the
test-process Coordinator, activates the dataplane (fragment RPC server
advertised through the membership broadcast) and shards the table —
after which each process materializes ONLY its owned partitions and
every scan scatters across the fleet.  Rounds print parity vs the
CPU oracle AND a `dp=` marker proving the dataplane engine actually
served the round (parity alone cannot: the local fallback answers
identically from the full base table).  SIGKILL of the peer must show
up as a bumped epoch, a survivor-side re-shard, and ok=1 rounds that
keep carrying dp>=1.

argv: [process_id, coordinator_port].  Env knobs: COORD_LEASE_S,
COORD_WORKER_MAX_S, TIDB_TPU_DATAPLANE_DIR (shared replay directory).
"""

import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _approx(a, b):
    if isinstance(a, float) or isinstance(b, float):
        try:
            return abs(float(a) - float(b)) <= 1e-6 * max(
                1.0, abs(float(a)), abs(float(b)))
        except (TypeError, ValueError):
            return a == b
    return a == b


def _rows_match(got, want):
    if len(got) != len(want):
        return False
    return all(len(g) == len(w) and all(_approx(x, y)
               for x, y in zip(g, w))
               for g, w in zip(got, want))


def main() -> int:
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("TIDB_TPU_TILE", "1024")

    import jax

    jax.config.update("jax_platforms", "cpu")

    from tidb_tpu.coord import activate_worker
    from tidb_tpu.dataplane import activate_dataplane
    from tidb_tpu.metrics import REGISTRY

    lease_s = float(os.environ.get("COORD_LEASE_S", "1.5"))
    max_s = float(os.environ.get("COORD_WORKER_MAX_S", "120"))
    t0 = time.monotonic()

    from tidb_tpu.tpch_data import build_lineitem

    sess = build_lineitem(8192, regions=4)
    dom = sess.domain
    tid = dom.catalog.info_schema().table("test", "lineitem").id
    # small unsharded dimension side for the join acceptance query
    sess.execute("create table flags (f_flag varchar(1), f_ord bigint)")
    sess.execute("insert into flags values ('A', 0), ('N', 1), ('R', 2)")

    plane = activate_worker(("127.0.0.1", port), pid=pid,
                            devices=[d.id for d in jax.devices()],
                            lease_s=lease_s)
    dp = activate_dataplane(dom.storage, plane=plane, pid=pid)

    # shard once the fleet FORMED and every fragment endpoint is
    # advertised — ownership derived pre-formation would flap
    expect = int(os.environ.get("COORD_EXPECT", "2"))
    while time.monotonic() - t0 < 30:
        v = plane.view()
        if v.formed and len(v.members) >= expect and len(v.addrs) >= expect:
            break
        time.sleep(0.05)
    dp.shard_table(tid)
    st = dp.lookup(tid)
    print(f"SHARDED pid={pid} loaded={len(st.loaded)}/{st.n_parts}",
          flush=True)

    queries = [
        ("q6", "select sum(l_extendedprice * l_discount) from lineitem"
               " where l_discount between 0.05 and 0.07"
               " and l_quantity < 24"),
        ("q1", "select l_returnflag, l_linestatus, sum(l_quantity),"
               " sum(l_extendedprice), count(*) from lineitem"
               " where l_shipdate <= '1998-09-02'"
               " group by l_returnflag, l_linestatus"
               " order by l_returnflag, l_linestatus"),
        ("agg", "select l_returnflag, count(*), sum(l_quantity)"
                " from lineitem group by l_returnflag"
                " order by l_returnflag"),
        ("join", "select l_returnflag, count(*) from lineitem"
                 " join flags on l_returnflag = f_flag"
                 " where f_ord >= 0 group by l_returnflag"
                 " order by l_returnflag"),
    ]
    sess.execute("set tidb_use_tpu = 0")
    oracles = {name: sess.query(q) for name, q in queries}
    sess.execute("set tidb_use_tpu = 1")

    print(f"READY pid={pid}", flush=True)

    stop = [False]
    signal.signal(signal.SIGTERM, lambda *_a: stop.__setitem__(0, True))

    rounds = 0
    while not stop[0] and time.monotonic() - t0 < max_s:
        d0 = REGISTRY.get("dataplane_queries_total") or 0
        ok = 1
        for name, q in queries:
            if not _rows_match(sess.query(q), oracles[name]):
                ok = 0
                print(f"MISMATCH pid={pid} q={name}", flush=True)
        dp_used = int((REGISTRY.get("dataplane_queries_total") or 0) - d0)
        promote = int(REGISTRY.get("dataplane_replica_promotions_total")
                      or 0)
        cold = int(REGISTRY.get("dataplane_cold_reloads_total") or 0)
        print(f"ROUND pid={pid} n={rounds} epoch={plane.current_epoch()} "
              f"ok={ok} dp={dp_used} promote={promote} cold={cold}",
              flush=True)
        rounds += 1
        time.sleep(0.05)

    plane.leave()
    plane.stop()
    print(f"DRAINED pid={pid} rounds={rounds}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
