"""Multi-host worker: one process of a 2-process jax.distributed cluster.

Launched by tests/test_multihost.py (and __graft_entry__.dryrun_multihost)
with argv = [process_id, num_processes, coordinator_port].  Each process
contributes 4 virtual CPU devices; the mesh spans all 8 across both
processes, so the shard_map scan's psum merges ride the cross-process
collective fabric — the role of the reference's multi-node NCCL/MPI store
fabric (store/tikv/client_batch.go:38-387), carried by XLA collectives
over DCN in the real deployment.

Every process runs the SAME deterministic script: identical data build,
identical query sequence (multi-controller SPMD contract).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    os.environ["TIDB_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["TIDB_TPU_NUM_PROCESSES"] = str(nproc)
    os.environ["TIDB_TPU_PROCESS_ID"] = str(pid)
    os.environ["TIDB_TPU_TILE"] = "1024"
    os.environ["TIDB_TPU_COMPILE_CACHE"] = "0"  # per-process compiles

    import jax

    jax.config.update("jax_platforms", "cpu")

    # join the cluster on the MAIN thread before any worker thread races
    # into backend init (get_mesh -> _maybe_init_multihost)
    from tidb_tpu.copr.parallel import MESH_CACHE, get_mesh

    mesh = get_mesh()
    devs = mesh.devices.ravel()
    assert len(devs) == 4 * nproc, f"mesh spans {len(devs)} devices"
    assert len(jax.devices()) == 4 * nproc

    from tidb_tpu.tpch_data import build_lineitem

    sess = build_lineitem(16384, regions=4)  # deterministic in every proc

    q1 = ("select l_returnflag, l_linestatus, sum(l_quantity),"
          " sum(l_extendedprice), sum(l_extendedprice * (1 - l_discount)),"
          " avg(l_discount), count(*) from lineitem"
          " where l_shipdate <= '1998-09-02'"
          " group by l_returnflag, l_linestatus"
          " order by l_returnflag, l_linestatus")
    q6 = ("select sum(l_extendedprice * l_discount) from lineitem"
          " where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01'"
          " and l_discount between 0.05 and 0.07 and l_quantity < 24")

    # device broadcast join across BOTH processes' shards: the payload
    # broadcast and the joined partial-agg psum ride the same collective
    # fabric (deterministic per-process build order is the contract)
    from tidb_tpu.tpch_data import Q3_SQL as q3, build_q3_tables

    s3 = build_q3_tables(16384, 512, regions=4)
    # the broadcast join must actually BE in the cop task here
    plan_ops = [r[0] for r in s3.execute("explain " + q3)[0].rows]
    assert any("DeviceJoinReader" in op for op in plan_ops), plan_ops

    from tidb_tpu.metrics import REGISTRY

    before = REGISTRY.snapshot().get("mesh_scans_total", 0)
    results = {}
    for name, sess_q, q in (("q1", sess, q1), ("q6", sess, q6),
                            ("q3", s3, q3)):
        sess_q.execute("set tidb_use_tpu = 1")
        tpu = sess_q.query(q)
        sess_q.execute("set tidb_use_tpu = 0")
        cpu = sess_q.query(q)
        assert len(tpu) == len(cpu) and tpu, (name, tpu, cpu)
        for ra, rb in zip(tpu, cpu):
            for x, y in zip(ra, rb):
                if isinstance(x, float) or isinstance(y, float):
                    assert abs(x - y) <= 1e-9 * max(1.0, abs(y)), (name, ra, rb)
                else:
                    assert x == y, (name, ra, rb)
        results[name] = tpu
    assert REGISTRY.snapshot().get("mesh_scans_total", 0) > before, \
        "queries did not run on the distributed mesh"

    # the cached column arrays must span BOTH processes' devices: this
    # process only addresses its local shards, and the sharding's device
    # set covers every process index
    data, _ = next(iter(MESH_CACHE._cache.values()))
    all_procs = {d.process_index for d in data.sharding.device_set}
    local_procs = {s.device.process_index for s in data.addressable_shards}
    assert all_procs == set(range(nproc)), all_procs
    assert local_procs == {pid}, (local_procs, pid)

    # coordination plane (ISSUE 9): when the test wired a coord address,
    # the SAME two processes also form the control plane — assert the
    # formed membership broadcast spans both processes' device sets and
    # that a worker-side trace rejoined the coordinator's ring
    if os.environ.get("TIDB_TPU_COORD_ADDR"):
        import time as _time

        from tidb_tpu.coord import get_plane

        plane = get_plane()
        view = plane.view()
        assert set(view.members) == set(range(nproc)), view.members
        assert len(view.device_ids()) == 4 * nproc, view
        assert view.formed, view
        sess.execute("trace format='row' select count(*) from lineitem")
        if pid == 0:
            deadline = _time.time() + 20
            while (_time.time() < deadline
                   and REGISTRY.snapshot().get(
                       "coord_spans_ingested_total", 0) < 1):
                _time.sleep(0.2)
            assert REGISTRY.snapshot().get(
                "coord_spans_ingested_total", 0) >= 1
        else:
            assert REGISTRY.snapshot().get(
                "coord_spans_forwarded_total", 0) >= 1
        print(f"COORD_OK pid={pid} epoch={view.epoch}", flush=True)

    print(f"MULTIHOST_OK pid={pid} devices={len(devs)} "
          f"q1_rows={len(results['q1'])} q6={results['q6'][0][0]:.4f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
