"""ADMIN RECOVER/CLEANUP INDEX + RECOVER TABLE.

Reference: util/admin.go:281-312 (index repair from row data),
ddl/ddl_api.go:1457 (RecoverTable flashback before GC)."""

import numpy as np
import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()
    yield dom
    dom.maintenance.stop()


def _mk(d):
    s = d.new_session()
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values " + ", ".join(
        f"({i}, {i * 3})" for i in range(500)))
    t = d.catalog.info_schema().table("test", "t")
    d.storage.maybe_compact(t.id, threshold=0)  # rows -> base blocks
    s.execute("create index iv on t (v)")
    return s


def _corrupt_index(d, tname, cols):
    t = d.catalog.info_schema().table("test", tname)
    store = d.storage.table(t.id)
    offs = tuple(t.col_offsets(cols))
    idx = store.indexes.get(store, offs)
    # simulate a corrupted artifact: drop entries + scramble a key
    import dataclasses

    bad = dataclasses.replace(
        idx,
        handles=idx.handles[:-3],
        cols=[np.ascontiguousarray(c[:-3]) for c in idx.cols],
    )
    store.indexes.put(offs, bad)
    return offs


def test_check_detects_recover_fixes(d):
    s = _mk(d)
    s.execute("admin check table t")  # healthy
    _corrupt_index(d, "t", ["v"])
    with pytest.raises(TiDBTPUError, match="index 'iv'"):
        s.execute("admin check table t")
    rs = s.execute("admin recover index t iv")[0]
    assert rs.rows[0][1] == 500  # scanned every row
    s.execute("admin check table t")  # healthy again
    # index reads return correct rows after the repair
    assert s.query("select id from t where v = 99") == [(33,)]


def test_cleanup_index_reports_removed(d):
    s = _mk(d)
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    offs = tuple(t.col_offsets(["v"]))
    idx = store.indexes.get(store, offs)
    import dataclasses

    # bogus extra entries pointing past the table
    extra = dataclasses.replace(
        idx,
        handles=np.concatenate([idx.handles, [900, 901]]),
        cols=[np.concatenate([c, [10**6, 10**6 + 1]]) for c in idx.cols],
    )
    store.indexes.put(offs, extra)
    rs = s.execute("admin cleanup index t iv")[0]
    assert rs.headers == ["REMOVED_COUNT"] and rs.rows[0][0] == 2
    s.execute("admin check table t")


def test_recover_table_flashback(d):
    s = _mk(d)
    s.execute("drop table t")
    with pytest.raises(TiDBTPUError):
        s.query("select count(*) from t")
    s.execute("recover table t")
    assert s.query("select count(*) from t") == [(500,)]
    assert s.query("select v from t where id = 7") == [(21,)]
    # writes keep working after flashback
    s.execute("insert into t values (1000, 9)")
    assert s.query("select count(*) from t") == [(501,)]


def test_recover_table_gone_after_gc(d):
    s = _mk(d)
    s.execute("drop table t")
    d.global_vars["tidb_gc_life_time"] = "0"
    import time

    time.sleep(0.01)
    d.maintenance.tick()
    with pytest.raises(TiDBTPUError, match="recover"):
        s.execute("recover table t")


def test_recover_table_name_conflict(d):
    s = _mk(d)
    s.execute("drop table t")
    s.execute("create table t (x bigint)")
    with pytest.raises(TiDBTPUError):
        s.execute("recover table t")
    s.execute("drop table t")
    s.execute("recover table t")  # newest drop wins (the x-table)
    cols = [r[0] for r in s.query("show columns from t")]
    assert cols == ["x"]


def test_recover_partitioned_table(d):
    s = d.new_session()
    s.execute("create table pt (k bigint, v bigint)"
              " partition by hash(k) partitions 3")
    s.execute("insert into pt values (1, 10), (2, 20), (3, 30)")
    s.execute("drop table pt")
    s.execute("recover table pt")
    assert s.query("select sum(v) from pt") == [(60,)]
