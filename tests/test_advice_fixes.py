"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.store.fault import FAILPOINTS, once


@pytest.fixture()
def sess():
    return Domain().new_session()


def test_union_scan_sees_committed_base_update(sess):
    """ADVICE high #1: a committed UPDATE of a base row must stay visible
    through UnionScanExec when the session txn is dirty on the table."""
    sess.execute("create table t (a bigint, b bigint)")
    sess.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    # force rows into base blocks
    sess.domain.storage.maybe_compact(
        sess.domain.catalog.info_schema().table("test", "t").id, threshold=0
    )
    sess.execute("update t set b = 11 where a = 1")  # autocommit update
    sess.execute("begin")
    sess.execute("insert into t values (4, 40)")  # txn now dirty on t
    rows = sess.query("select a, b from t order by a")
    sess.execute("rollback")
    assert rows == [(1, 11), (2, 20), (3, 30), (4, 40)]


def test_union_scan_committed_update_not_compacted(sess):
    """Same scenario without compaction: update lands in the delta chain."""
    sess.execute("create table t (a bigint, b bigint)")
    sess.execute("insert into t values (1, 10), (2, 20)")
    sess.execute("update t set b = 99 where a = 2")
    sess.execute("begin")
    sess.execute("update t set b = 100 where a = 1")  # dirty
    rows = sess.query("select a, b from t order by a")
    sess.execute("commit")
    assert rows == [(1, 100), (2, 99)]


def test_correlated_count_returns_zero_not_null(sess):
    """ADVICE high #2: COUNT over an empty correlated group reads 0, so the
    unmatched outer row qualifies (classic COUNT decorrelation bug)."""
    sess.execute("create table t1 (a bigint)")
    sess.execute("create table t2 (b bigint)")
    sess.execute("insert into t1 values (5), (0)")
    sess.execute("insert into t2 values (5), (5)")
    # a=5: count=2, 5>2 yes.  a=0: count=0, 0>0 no.
    assert sess.query(
        "select a from t1 where a > (select count(*) from t2 where t2.b = t1.a)"
    ) == [(5,)]
    # and the zero must be observable as a value too
    sess.execute("create table t3 (c bigint)")
    sess.execute("insert into t3 values (7)")
    assert sess.query(
        "select c from t3 where (select count(*) from t2 where t2.b = t3.c) = 0"
    ) == [(7,)]


def test_join_null_keys_never_match_sentinel_value(sess):
    """ADVICE low #3: a probe value equal to the old NULL sentinel
    -(1<<62) must not match NULL build keys."""
    sentinel = -(1 << 62)
    sess.execute("create table b (k bigint, v bigint)")
    sess.execute("create table p (k bigint, w bigint)")
    sess.execute(f"insert into b values (null, 1), ({sentinel}, 2)")
    sess.execute(f"insert into p values ({sentinel}, 10), (null, 20)")
    rows = sess.query(
        "select p.w, b.v from p join b on p.k = b.k"
    )
    # only the real sentinel-valued pair matches; NULLs never join
    assert rows == [(10, 2)]


def test_keytable_sentinel_key():
    """ADVICE low #4: a real key equal to the C table's old EMPTY sentinel
    (INT64_MIN+7) must factorize correctly, not read uninitialized slots."""
    from tidb_tpu.native import KeyTable

    weird = np.int64(-(1 << 63) + 7)
    keys = np.array([weird, 5, weird, 7, weird], dtype=np.int64)
    t = KeyTable(4)
    codes = t.upsert(keys)
    assert codes[0] == codes[2] == codes[4]
    assert len({int(c) for c in codes}) == 3
    probe = t.lookup(np.array([weird, 6], dtype=np.int64))
    assert probe[0] == codes[0]
    assert probe[1] == -1


def test_select_result_close_cancels(sess):
    """ADVICE low #5: closing a SelectResult early (LIMIT satisfied) stops
    the producer instead of leaking a blocked thread."""
    import threading

    sess.execute("create table big (a bigint)")
    t = sess.domain.catalog.info_schema().table("test", "big")
    store = sess.domain.storage.table(t.id)
    store.bulk_load_arrays(
        [np.arange(200_000, dtype=np.int64)],
        ts=sess.domain.storage.current_ts(),
    )
    sess.domain.storage.regions.split_even(t.id, 16, store.base_rows)
    before = threading.active_count()
    for _ in range(5):
        rows = sess.query("select a from big limit 3")
        assert len(rows) == 3
    # producer threads must exit; allow scheduler slack
    import time

    deadline = time.time() + 5
    while threading.active_count() > before + 2 and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 2


def test_scan_fault_device_fallback(sess):
    """Runtime device error on one region task falls back to the CPU engine
    and the query still returns correct rows."""
    sess.execute("create table t (a bigint)")
    t = sess.domain.catalog.info_schema().table("test", "t")
    store = sess.domain.storage.table(t.id)
    store.bulk_load_arrays(
        [np.arange(1000, dtype=np.int64)],
        ts=sess.domain.storage.current_ts(),
    )
    sess.domain.storage.regions.split_even(t.id, 4, store.base_rows)
    FAILPOINTS.enable("distsql/task_error", once(RuntimeError("chip died")))
    try:
        rows = sess.query("select sum(a) from t")
        assert rows == [(sum(range(1000)),)]
    finally:
        FAILPOINTS.disable("distsql/task_error")


def test_scan_fault_transient_retry(sess):
    """A transient non-device task error retries with backoff and succeeds."""
    sess.execute("set tidb_use_tpu = 0")
    sess.execute("create table t (a bigint)")
    sess.execute("insert into t values (1), (2), (3)")
    FAILPOINTS.enable("distsql/task_error", once(OSError("net blip")))
    try:
        assert sess.query("select sum(a) from t") == [(6,)]
    finally:
        FAILPOINTS.disable("distsql/task_error")
