"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import threading

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.store.fault import failpoint, once


@pytest.fixture()
def sess():
    return Domain().new_session()


def test_union_scan_sees_committed_base_update(sess):
    """ADVICE high #1: a committed UPDATE of a base row must stay visible
    through UnionScanExec when the session txn is dirty on the table."""
    sess.execute("create table t (a bigint, b bigint)")
    sess.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    # force rows into base blocks
    sess.domain.storage.maybe_compact(
        sess.domain.catalog.info_schema().table("test", "t").id, threshold=0
    )
    sess.execute("update t set b = 11 where a = 1")  # autocommit update
    sess.execute("begin")
    sess.execute("insert into t values (4, 40)")  # txn now dirty on t
    rows = sess.query("select a, b from t order by a")
    sess.execute("rollback")
    assert rows == [(1, 11), (2, 20), (3, 30), (4, 40)]


def test_union_scan_committed_update_not_compacted(sess):
    """Same scenario without compaction: update lands in the delta chain."""
    sess.execute("create table t (a bigint, b bigint)")
    sess.execute("insert into t values (1, 10), (2, 20)")
    sess.execute("update t set b = 99 where a = 2")
    sess.execute("begin")
    sess.execute("update t set b = 100 where a = 1")  # dirty
    rows = sess.query("select a, b from t order by a")
    sess.execute("commit")
    assert rows == [(1, 100), (2, 99)]


def test_correlated_count_returns_zero_not_null(sess):
    """ADVICE high #2: COUNT over an empty correlated group reads 0, so the
    unmatched outer row qualifies (classic COUNT decorrelation bug)."""
    sess.execute("create table t1 (a bigint)")
    sess.execute("create table t2 (b bigint)")
    sess.execute("insert into t1 values (5), (0)")
    sess.execute("insert into t2 values (5), (5)")
    # a=5: count=2, 5>2 yes.  a=0: count=0, 0>0 no.
    assert sess.query(
        "select a from t1 where a > (select count(*) from t2 where t2.b = t1.a)"
    ) == [(5,)]
    # and the zero must be observable as a value too
    sess.execute("create table t3 (c bigint)")
    sess.execute("insert into t3 values (7)")
    assert sess.query(
        "select c from t3 where (select count(*) from t2 where t2.b = t3.c) = 0"
    ) == [(7,)]


def test_join_null_keys_never_match_sentinel_value(sess):
    """ADVICE low #3: a probe value equal to the old NULL sentinel
    -(1<<62) must not match NULL build keys."""
    sentinel = -(1 << 62)
    sess.execute("create table b (k bigint, v bigint)")
    sess.execute("create table p (k bigint, w bigint)")
    sess.execute(f"insert into b values (null, 1), ({sentinel}, 2)")
    sess.execute(f"insert into p values ({sentinel}, 10), (null, 20)")
    rows = sess.query(
        "select p.w, b.v from p join b on p.k = b.k"
    )
    # only the real sentinel-valued pair matches; NULLs never join
    assert rows == [(10, 2)]


def test_keytable_sentinel_key():
    """ADVICE low #4: a real key equal to the C table's old EMPTY sentinel
    (INT64_MIN+7) must factorize correctly, not read uninitialized slots."""
    from tidb_tpu.native import KeyTable

    weird = np.int64(-(1 << 63) + 7)
    keys = np.array([weird, 5, weird, 7, weird], dtype=np.int64)
    t = KeyTable(4)
    codes = t.upsert(keys)
    assert codes[0] == codes[2] == codes[4]
    assert len({int(c) for c in codes}) == 3
    probe = t.lookup(np.array([weird, 6], dtype=np.int64))
    assert probe[0] == codes[0]
    assert probe[1] == -1


def test_select_result_close_cancels(sess):
    """ADVICE low #5: closing a SelectResult early (LIMIT satisfied) stops
    the producer instead of leaking a blocked thread."""
    import threading

    sess.execute("create table big (a bigint)")
    t = sess.domain.catalog.info_schema().table("test", "big")
    store = sess.domain.storage.table(t.id)
    store.bulk_load_arrays(
        [np.arange(200_000, dtype=np.int64)],
        ts=sess.domain.storage.current_ts(),
    )
    sess.domain.storage.regions.split_even(t.id, 16, store.base_rows)
    before = threading.active_count()
    for _ in range(5):
        rows = sess.query("select a from big limit 3")
        assert len(rows) == 3
    # producer threads must exit; allow scheduler slack
    import time

    deadline = time.time() + 5
    while threading.active_count() > before + 2 and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 2


def test_scan_fault_device_fallback(sess):
    """Runtime device error on one region task falls back to the CPU engine
    and the query still returns correct rows."""
    sess.execute("create table t (a bigint)")
    t = sess.domain.catalog.info_schema().table("test", "t")
    store = sess.domain.storage.table(t.id)
    store.bulk_load_arrays(
        [np.arange(1000, dtype=np.int64)],
        ts=sess.domain.storage.current_ts(),
    )
    sess.domain.storage.regions.split_even(t.id, 4, store.base_rows)
    with failpoint("distsql/task_error", once(RuntimeError("chip died"))):
        rows = sess.query("select sum(a) from t")
        assert rows == [(sum(range(1000)),)]


def test_scan_fault_transient_retry(sess):
    """A transient non-device task error retries with backoff and succeeds."""
    sess.execute("set tidb_use_tpu = 0")
    sess.execute("create table t (a bigint)")
    sess.execute("insert into t values (1), (2), (3)")
    with failpoint("distsql/task_error", once(OSError("net blip"))):
        assert sess.query("select sum(a) from t") == [(6,)]


# ---------------------------------------------------------------------------
# round-4 advisor findings
# ---------------------------------------------------------------------------

def test_pinned_snapshot_survives_compaction(sess):
    """ADVICE r4 #1: SET tidb_snapshot pins the compaction/GC floor, so a
    historical read stays correct under write load + maintenance."""
    d = sess.domain
    sess.execute("create table hs (id bigint primary key, v bigint)")
    sess.execute("insert into hs values (1, 10), (2, 20)")
    ts0 = d.storage.current_ts()
    sess.execute(f"set tidb_snapshot = {ts0}")
    assert sess.query("select v from hs order by id") == [(10,), (20,)]
    # concurrent write load + aggressive maintenance must NOT fold the
    # base past the pinned TSO
    w = d.new_session()
    for i in range(20):
        w.execute(f"update hs set v = {100 + i} where id = 1")
    tid = d.catalog.info_schema().table("test", "hs").id
    d.storage.maybe_compact(tid, threshold=0)  # deferred: pin held
    d.maintenance.tick()
    assert sess.query("select v from hs order by id") == [(10,), (20,)]
    # releasing the pin lets compaction fold
    sess.execute("set tidb_snapshot = ''")
    d.storage.maybe_compact(tid, threshold=0)
    store = d.storage.table(tid)
    assert not store.delta  # folded now
    assert sess.query("select v from hs where id = 1") == [(119,)]


def test_read_below_compaction_horizon_errors(sess):
    """A read whose TSO predates the base rebuild fails loudly instead of
    returning an empty table."""
    from tidb_tpu.errors import TiDBTPUError

    d = sess.domain
    sess.execute("set tidb_use_tpu = 0")
    sess.execute("create table hz (id bigint primary key, v bigint)")
    sess.execute("insert into hz values (1, 1)")
    ts0 = d.storage.current_ts()
    tid = d.catalog.info_schema().table("test", "hz").id
    sess.execute("update hz set v = 2 where id = 1")
    d.storage.maybe_compact(tid, threshold=0)  # no pin: folds, base_ts > ts0
    assert d.storage.table(tid).base_ts > ts0
    sess.execute(f"set tidb_snapshot = {ts0}")
    with pytest.raises(TiDBTPUError, match="compaction horizon"):
        sess.query("select v from hz")
    sess.execute("set tidb_snapshot = ''")


def test_granter_must_hold_granted_privs(sess):
    """ADVICE r4 #2: CREATE USER or bare GRANT OPTION alone must not allow
    privilege escalation via GRANT ALL."""
    from tidb_tpu.errors import PrivilegeError

    d = sess.domain
    sess.execute("create user admin")
    sess.execute("grant create user on *.* to admin")
    sess.execute("create user mallory")
    adm = d.new_session()
    adm.user = "admin@%"
    # user management still works with CREATE USER
    adm.execute("create user bob")
    # ...but granting requires GRANT OPTION + the privileges themselves
    with pytest.raises(PrivilegeError):
        adm.execute("grant all on *.* to admin")
    sess.execute("grant grant option on *.* to mallory")
    mal = d.new_session()
    mal.user = "mallory@%"
    with pytest.raises(PrivilegeError):
        mal.execute("grant select on *.* to mallory")  # doesn't hold SELECT
    # a granter holding the priv + grant option succeeds
    sess.execute("grant select on *.* to mallory")
    mal.execute("grant select on *.* to bob")
    assert any("SELECT" in g for g in d.priv.show_grants("bob"))


def test_global_binding_requires_super(sess):
    """ADVICE r4 #3: GLOBAL bindings rewrite every session's plans —
    SUPER required; binding DDL is also a write under tidb_snapshot."""
    from tidb_tpu.errors import PrivilegeError, TiDBTPUError

    d = sess.domain
    sess.execute("create table bb (a bigint)")
    sess.execute("create user lowpriv")
    sess.execute("grant select on *.* to lowpriv")
    lp = d.new_session()
    lp.user = "lowpriv@%"
    with pytest.raises(PrivilegeError):
        lp.execute(
            "create global binding for select * from bb using "
            "select /*+ HASH_JOIN() */ * from bb")
    # session-scope binding is fine for a normal user
    lp.execute("create binding for select * from bb using "
               "select /*+ HASH_JOIN() */ * from bb")
    # writes under tidb_snapshot are rejected, including binding DDL
    ts0 = d.storage.current_ts()
    sess.execute(f"set tidb_snapshot = {ts0}")
    with pytest.raises(TiDBTPUError, match="tidb_snapshot"):
        sess.execute("create binding for select * from bb using "
                     "select /*+ HASH_JOIN() */ * from bb")
    sess.execute("set tidb_snapshot = ''")


def test_hash_partition_negative_keys_match_reference(sess):
    """ADVICE r4 #4: negative hash partition keys use abs(truncated rem),
    matching TiDB locateHashPartition (-5 % 3 -> bucket 2, not 1)."""
    sess.execute("create table hp (id bigint primary key, v bigint) "
                 "partition by hash(id) partitions 3")
    sess.execute("insert into hp values (-5, 1), (5, 2), (-3, 3), (4, 4)")
    isc = sess.domain.catalog.info_schema()
    t = isc.table("test", "hp")
    pi = t.partition_info
    assert pi.partition_for_value(-5) is pi.defs[2]
    assert pi.partition_for_value(5) is pi.defs[2]
    assert pi.partition_for_value(-3) is pi.defs[0]
    # the three routing paths agree and reads see every row
    assert sess.query("select v from hp where id = -5") == [(1,)]
    assert sess.query("select count(*) from hp") == [(4,)]


def test_point_get_below_horizon_errors_too(sess):
    """The horizon guard covers the index/point-get fast paths, not just
    the copr scan: stale snapshots must never see FUTURE data."""
    from tidb_tpu.errors import TiDBTPUError

    d = sess.domain
    sess.execute("create table pz (id bigint primary key, v bigint)")
    sess.execute("insert into pz values (1, 1), (2, 2)")
    ts0 = d.storage.current_ts()
    tid = d.catalog.info_schema().table("test", "pz").id
    sess.execute("update pz set v = 9 where id = 1")
    d.storage.maybe_compact(tid, threshold=0)
    sess.execute(f"set tidb_snapshot = {ts0}")
    for q in ("select v from pz where id = 1",          # PointGet
              "select v from pz where id in (1, 2)"):    # BatchPointGet
        with pytest.raises(TiDBTPUError, match="compaction horizon"):
            sess.query(q)
    sess.execute("set tidb_snapshot = ''")
    assert sess.query("select v from pz where id = 1") == [(9,)]


def test_emptied_table_below_horizon_errors(sess):
    """A fully-deleted-then-compacted table (base_rows == 0) still errors
    for a stale snapshot instead of silently returning []."""
    from tidb_tpu.errors import TiDBTPUError

    d = sess.domain
    sess.execute("set tidb_use_tpu = 0")
    sess.execute("create table ez (id bigint primary key, v bigint)")
    sess.execute("insert into ez values (1, 1)")
    ts0 = d.storage.current_ts()
    tid = d.catalog.info_schema().table("test", "ez").id
    sess.execute("delete from ez")
    d.storage.maybe_compact(tid, threshold=0)
    assert d.storage.table(tid).base_rows == 0
    sess.execute(f"set tidb_snapshot = {ts0}")
    with pytest.raises(TiDBTPUError, match="compaction horizon"):
        sess.query("select v from ez")
    sess.execute("set tidb_snapshot = ''")


def test_db_scope_grant_all_needs_only_db_privs(sess):
    """GRANT ALL at db scope expands to scope-applicable privileges only —
    a db admin without SUPER/CREATE USER can still GRANT ALL ON db.*."""
    d = sess.domain
    sess.execute("create user dbadmin")
    sess.execute("create user app")
    for p in ("select", "insert", "update", "delete", "create", "drop",
              "alter", "index", "create view", "grant option"):
        sess.execute(f"grant {p} on test.* to dbadmin")
    adm = d.new_session()
    adm.user = "dbadmin@%"
    adm.execute("grant all on test.* to app")
    assert d.priv.check("app", "select", "test", "t")


# ---------------------------------------------------------------------------
# round-5 advisor findings (shipped with the tidb_tpu.lint PR)
# ---------------------------------------------------------------------------


def test_rename_table_keeps_own_foreign_keys(sess):
    """ADVICE r5 medium: rename_table rebuilt TableInfo without
    foreign_keys, silently dropping the renamed table's OWN FK metadata
    (only OTHER tables' references were rewritten)."""
    sess.execute("create table parent (id bigint primary key)")
    sess.execute("create table child (id bigint primary key, pid bigint,"
                 " constraint fk_p foreign key (pid)"
                 " references parent (id))")
    sess.execute("rename table child to child2")
    t = sess.domain.catalog.info_schema().table("test", "child2")
    assert [fk["name"] for fk in t.foreign_keys] == ["fk_p"]
    sc = sess.query("show create table child2")[0][1]
    assert "FOREIGN KEY" in sc and "fk_p" in sc


def test_rehash_partitions_racing_commit_survives(sess, monkeypatch):
    """ADVICE r5 medium: _rehash_partitions took the fold TSO BEFORE
    detaching the old stores; a commit landing in that window got
    commit_ts > ts and compact(ts) silently discarded the row.  The TSO
    is now taken after all stores are detached, so a commit that beat
    the detach is folded in (and one that didn't aborts loudly)."""
    d = sess.domain
    sess.execute("create table hp (k bigint, v bigint)"
                 " partition by hash(k) partitions 4")
    sess.execute("insert into hp values "
                 + ", ".join(f"({i}, {i})" for i in range(40)))
    s2 = d.new_session()
    # a real racer resolved its schema BEFORE the DDL took Catalog._mu;
    # pin that pre-DDL snapshot so the in-window commit below doesn't
    # re-enter the catalog lock (which the DDL thread holds)
    isc = d.catalog.info_schema()
    monkeypatch.setattr(s2, "_infoschema", lambda: isc)
    # ... and post-commit auto-analyze re-reads the live schema too; it's
    # incidental bookkeeping, not the race under test
    monkeypatch.setattr(d, "maybe_auto_analyze", lambda table_ids: None)
    orig = d.storage.detach_table
    fired = []

    def detach_hook(pid):
        if not fired:
            fired.append(pid)
            # the racing commit: lands after any fold-TSO taken before
            # detach, but before any store is actually detached.  Run it
            # on its own thread (joined) the way a real racer would — the
            # DDL thread holds Catalog._mu here, and the lock-order
            # witness rightly rejects same-thread re-entry into the
            # session path from under it.
            t = threading.Thread(
                target=s2.execute, args=("insert into hp values (777, 777)",))
            t.start()
            t.join(timeout=30)
            assert not t.is_alive(), "racing commit wedged"
        return orig(pid)

    monkeypatch.setattr(d.storage, "detach_table", detach_hook)
    sess.execute("alter table hp coalesce partition 1")
    assert fired, "detach hook never fired — rehash path changed?"
    assert sess.query("select k, v from hp where k = 777") == [(777, 777)]
    assert sess.query("select count(*) from hp") == [(41,)]


def test_binding_recapture_after_drop(sess):
    """ADVICE r5 low: the domain-wide _capture_seen counter captured only
    on EXACTLY the second sighting, so a dropped captured binding could
    never be recaptured (the count kept growing past 2)."""
    s = sess
    s.execute("create table cb1 (id bigint)")
    s.execute("create table cb2 (id bigint)")
    s.execute("insert into cb1 values (1), (2), (3)")
    s.execute("insert into cb2 values (1), (2)")
    s.execute("set tidb_capture_plan_baselines = 1")
    q = "select count(*) from cb1 join cb2 on cb1.id = cb2.id"
    try:
        s.query(q)
        assert s.query("show global bindings") == []
        s.query(q)  # second sighting -> captured
        assert len(s.query("show global bindings")) == 1
        s.execute("drop global binding for " + q)
        assert s.query("show global bindings") == []
        s.query(q)
        s.query(q)  # two fresh sightings -> recaptured
        assert len(s.query("show global bindings")) == 1
    finally:
        s.execute("set tidb_capture_plan_baselines = 0")
        s.execute("drop global binding for " + q)


def test_checksum_delete_and_overlay_aware(sess):
    """The vectorized ADMIN CHECKSUM must still see the delta overlay:
    deletes shrink kvs, uncompacted inserts count, content changes the
    crc (the old per-row repr() loop is now tests/test_lint.py's
    canonical row-loop lint specimen)."""
    d = sess.domain
    sess.execute("create table ckv (a bigint, b varchar(8), c double)")
    sess.execute("insert into ckv values (1, 'x', 1.5), (2, 'y', 2.5),"
                 " (3, null, 3.5)")
    d.storage.maybe_compact(
        d.catalog.info_schema().table("test", "ckv").id, threshold=0)
    _, _, crc0, kvs0, _ = sess.execute("admin checksum table ckv")[0].rows[0]
    assert kvs0 == 3
    sess.execute("delete from ckv where a = 2")       # delta delete
    sess.execute("insert into ckv values (4, 'z', 4.5)")  # delta insert
    _, _, crc1, kvs1, nb1 = sess.execute("admin checksum table ckv")[0].rows[0]
    assert kvs1 == 3 and crc1 != crc0 and nb1 > 0
    # NULL flip changes the checksum even when the fill bytes match
    sess.execute("update ckv set b = '' where a = 3")
    crc2 = sess.execute("admin checksum table ckv")[0].rows[0][2]
    assert crc2 != crc1


def test_checksum_invariant_to_compaction_state(sess):
    """Identical VISIBLE content must checksum identically whether the
    deletes are a delta overlay over base rows or already physically
    compacted away — a replica mid-compaction must not report a false
    mismatch.  In particular an all-rows-deleted store contributes 0."""
    d = sess.domain
    sess.execute("create table ckc (a bigint, b varchar(8))")
    tid = d.catalog.info_schema().table("test", "ckc").id
    sess.execute("insert into ckc values (1, 'x'), (2, 'y')")
    d.storage.maybe_compact(tid, threshold=0)
    sess.execute("delete from ckc")
    deleted_overlay = sess.execute("admin checksum table ckc")[0].rows[0][2:]
    d.storage.maybe_compact(tid, threshold=0)   # deletes fold into base
    deleted_folded = sess.execute("admin checksum table ckc")[0].rows[0][2:]
    assert deleted_overlay == deleted_folded == (0, 0, 0)
    # same with surviving rows: overlay-deleted vs compacted must agree
    sess.execute("insert into ckc values (1, 'x'), (2, 'y'), (3, 'z')")
    d.storage.maybe_compact(tid, threshold=0)
    sess.execute("delete from ckc where a = 2")
    overlay = sess.execute("admin checksum table ckc")[0].rows[0][2:]
    d.storage.maybe_compact(tid, threshold=0)
    folded = sess.execute("admin checksum table ckc")[0].rows[0][2:]
    assert overlay == folded and overlay[1] == 2


def test_pushed_cond_uids_survive_projection_elimination(sess):
    """Planner bug found BY the new plan checker: eliminate_projections
    relabeled a datasource's schema uids but left pushed_conds pointing
    at the old ones, so the cop Selection read column #-1 (Python
    negative indexing -> the LAST scan column) — wrong rows on any
    multi-column scan under an eliminated identity projection."""
    sess.execute("create table pe (a bigint, b bigint)")
    sess.execute("insert into pe values (1, 5), (9, 1), (2, 5)")
    sess.domain.storage.maybe_compact(
        sess.domain.catalog.info_schema().table("test", "pe").id,
        threshold=0)
    assert sorted(sess.query(
        "select a from pe where a = 1"
        " union all select b from pe where b = 5")) == [(1,), (5,), (5,)]
    assert sess.query(
        "select a from (select a, b from pe) x where a = 1") == [(1,)]
    # and with the build-time checker off, results are still right
    sess.execute("set tidb_check_plan = 0")
    try:
        assert sess.query(
            "select a from (select a, b from pe) x where a = 1") == [(1,)]
    finally:
        sess.execute("set tidb_check_plan = 1")
