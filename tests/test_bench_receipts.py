"""bench.py reliability (ISSUE 8 satellite, ROADMAP carried item).

The driver's BENCH runs have repeatedly zeroed out on late-run wedges
(device preflight flakes, load hangs at a big scale) even though earlier
scales completed.  These tests drive bench's scale loop with stubbed
phases and assert the crash-insurance contract: every COMPLETED scale's
receipt survives an injected late-scale failure, both in the worker
state (what `emit` serializes) and in the BENCH_PARTIAL.json file."""

import json
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


class _StubSession:
    """Just enough session surface for bench's scale loop."""

    def execute(self, *a, **k):
        return [type("R", (), {"rows": []})()]

    def query(self, *a, **k):
        return []


@pytest.fixture
def stubbed(monkeypatch, tmp_path):
    monkeypatch.setenv("BENCH_PARTIAL_PATH", str(tmp_path / "partial.json"))
    monkeypatch.setattr(bench, "MAX_ROWS", 4_000_000)
    # small enough that the device-heavy phases (q3 join needs >180s
    # remaining, mpp join >150s) gate themselves off; big enough that
    # every scale in the stubbed loop still runs (gate: 35% remaining)
    monkeypatch.setattr(bench, "WALL_LIMIT", 140.0)
    monkeypatch.setattr(bench, "T0", time.perf_counter())
    monkeypatch.setattr(bench, "build_lineitem", lambda n: _StubSession())
    monkeypatch.setattr(bench, "time_query",
                        lambda s, q, iters: (0.1, 0.05))
    monkeypatch.setattr(bench, "fusion_bench",
                        lambda s, n: {"stub": True})
    return tmp_path


def test_partial_receipts_survive_injected_late_scale_failure(
        stubbed, monkeypatch):
    monkeypatch.setenv("BENCH_FAIL_AT_SCALE", "1048576")
    state: dict = {}
    bench._run(state)
    # the wedge surfaced, it did not zero the receipts
    assert "injected late-scale failure" in state.get("worker_error", "")
    done = [sc["rows"] for sc in state.get("scales", [])]
    assert done == [262_144], state
    # the per-scale receipt also landed on disk before the wedge
    data = json.loads((stubbed / "partial.json").read_text())
    assert [sc["rows"] for sc in data["scales"]] == [262_144]
    assert data["scales"][0]["q1_rows_per_sec"] > 0
    # and emit() keeps the completed scales in the detail payload
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.emit(state)
    out = json.loads(buf.getvalue())
    assert out["detail"]["scales"] and out["value"] > 0


def test_all_scales_complete_without_injection(stubbed):
    state: dict = {}
    bench._run(state)
    assert "worker_error" not in state
    assert [sc["rows"] for sc in state.get("scales", [])] == [
        262_144, 1_048_576, 4_000_000]
    data = json.loads((stubbed / "partial.json").read_text())
    assert len(data["scales"]) == 3


def test_probe_error_classes():
    assert bench.classify_probe_error("Connection refused") == "tunnel-down"
    assert bench.classify_probe_error("deadline exceeded") == "probe-timeout"
    assert bench.classify_probe_error("No module named jax") == "environment"
    assert bench.classify_probe_error("???") == "unknown"


def test_parallel_fallback_commits_receipt_before_preflight_ends(
        monkeypatch, tmp_path):
    """ISSUE 9 satellite: the host-side fallback runs IN PARALLEL with
    the device preflight — its receipt lands in state AND on disk as
    soon as the child finishes, so a tunnel-wedged run harvested by the
    driver's timeout still carries a nonzero receipt."""
    monkeypatch.setenv("BENCH_PARTIAL_PATH", str(tmp_path / "p.json"))
    monkeypatch.setattr(bench, "T0", time.perf_counter())
    monkeypatch.setattr(bench, "WALL_LIMIT", 120.0)
    monkeypatch.setattr(bench, "_fallback_cmd", lambda: [
        sys.executable, "-c",
        "print('FALLBACK_JSON {\"q1_cpu_rows_per_sec\": 123.0}')"])
    import os

    monkeypatch.setattr(bench, "_fallback_env", lambda: dict(os.environ))
    state: dict = {}
    h = bench.start_parallel_fallback(state)
    assert h is not None
    assert h["done"].wait(30)
    # committed to state + persisted WITHOUT host_side_fallback running
    assert state["host_fallback"]["q1_cpu_rows_per_sec"] == 123.0
    data = json.loads((tmp_path / "p.json").read_text())
    assert data["host_fallback"]["q1_cpu_rows_per_sec"] == 123.0
    # the failure path harvests the already-running worker (no respawn)
    bench.host_side_fallback(state, parallel=h)
    assert state["host_fallback"]["q1_cpu_rows_per_sec"] == 123.0


def test_parallel_fallback_cancelled_on_preflight_success(monkeypatch):
    monkeypatch.setattr(bench, "T0", time.perf_counter())
    monkeypatch.setattr(bench, "WALL_LIMIT", 120.0)
    monkeypatch.setattr(bench, "_fallback_cmd", lambda: [
        sys.executable, "-c", "import time; time.sleep(60)"])
    import os

    monkeypatch.setattr(bench, "_fallback_env", lambda: dict(os.environ))
    state: dict = {}
    h = bench.start_parallel_fallback(state)
    assert h is not None
    bench.cancel_parallel_fallback(h, state)
    assert state["parallel_fallback"].startswith("cancelled")
    assert h["done"].wait(30)  # the collector unwinds after the kill


def test_parallel_fallback_skipped_when_forced_cpu(monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    assert bench.start_parallel_fallback({}) is None
