"""Long-tail builtin surface vs MySQL reference semantics.

Reference: expression/builtin_string_vec.go, builtin_time_vec.go,
builtin_encryption_vec.go, builtin_json_vec.go."""

import pytest

from tidb_tpu.session import Domain


@pytest.fixture(scope="module")
def s():
    return Domain().new_session()


def q1(s, expr):
    return s.query(f"select {expr}")[0][0]


CASES = [
    # representation
    ("bin(12)", "1100"),
    ("oct(12)", "14"),
    ("conv('ff', 16, 10)", "255"),
    ("conv(255, 10, 16)", "FF"),
    ("conv('8', 10, 2)", "1000"),
    ("bit_length('abc')", 24),
    ("octet_length('abc')", 3),
    ("ord('a')", 97),
    ("char(77, 121, 83)", "MyS"),
    ("bit_count(29)", 4),
    # string pickers
    ("elt(2, 'a', 'b', 'c')", "b"),
    ("field('b', 'a', 'b', 'c')", 2),
    ("export_set(5, 'Y', 'N', ',', 4)", "Y,N,Y,N"),
    ("make_set(1 | 4, 'hello', 'nice', 'world')", "hello,world"),
    ("format(12332.1234, 2)", "12,332.12"),
    ("insert('Quadratic', 3, 4, 'What')", "QuWhattic"),
    ("position('bar', 'foobar')", 4),
    ("quote(concat('Do', 'n', char(39), 't'))", "'Don\\'t'"),
    ("substring_index('www.mysql.com', '.', 2)", "www.mysql"),
    ("substring_index('www.mysql.com', '.', -2)", "mysql.com"),
    ("soundex('Quadratically')", "Q36324"),
    # network / misc
    ("inet_aton('10.0.5.9')", 167773449),
    ("inet_ntoa(167773449)", "10.0.5.9"),
    ("any_value(42)", 42),
    # time
    ("dayname('2007-02-03')", "Saturday"),
    ("weekofyear('2008-02-20')", 8),
    ("yearweek('1987-01-01')", 198701),
    ("to_days('2007-10-07')", 733321),
    ("to_seconds('2009-11-29')", 63426672000),
    ("from_days(730669)", "2000-07-03"),
    ("makedate(2011, 31)", "2011-01-31"),
    ("period_add(200801, 2)", 200803),
    ("period_diff(200802, 200703)", 11),
    ("time('2003-12-31 01:02:03')", "01:02:03"),
    ("timediff('2000-01-01 00:00:00', '2000-01-01 00:00:30')",
     "-00:00:30"),
    ("addtime('01:00:00', '00:30:00')", "01:30:00"),
    ("subtime('01:00:00', '00:30:00')", "00:30:00"),
    ("time_format('19:30:10', '%H %i %s')", "19 30 10"),
    ("str_to_date('01,5,2013', '%d,%m,%Y')", "2013-05-01 00:00:00"),
    ("str_to_date('2013-05-01 12:30:45', '%Y-%m-%d %H:%i:%s')",
     "2013-05-01 12:30:45"),
    ("get_format(date, 'usa')", "%m.%d.%Y"),
    ("timestampadd(minute, 1, '2003-01-02')", "2003-01-02 00:01:00"),
    ("timestampadd(month, 1, '2003-01-31')", "2003-02-28 00:00:00"),
    # JSON breadth
    ("json_depth('[1, [2, 3]]')", 3),
    ("json_keys('{\"a\": 1, \"b\": {\"c\": 2}}')", '["a", "b"]'),
    ("json_quote('[1, 2]')", '"[1, 2]"'),
    ("json_contains('[1, 2, {\"x\": 3}]', '2')", 1),
    ("json_contains('[1, 2]', '4')", 0),
    ("json_contains_path('{\"a\": 1}', 'one', '$.a', '$.z')", 1),
    ("json_contains_path('{\"a\": 1}', 'all', '$.a', '$.z')", 0),
    ("json_set('{\"a\": 1}', '$.b', 2)", '{"a": 1, "b": 2}'),
    ("json_insert('{\"a\": 1}', '$.a', 9)", '{"a": 1}'),
    ("json_replace('{\"a\": 1}', '$.a', 9)", '{"a": 9}'),
    ("json_remove('{\"a\": 1, \"b\": 2}', '$.a')", '{"b": 2}'),
    ("json_merge_preserve('[1, 2]', '[3]')", "[1, 2, 3]"),
]


@pytest.mark.parametrize("expr,expected", CASES,
                         ids=[c[0][:40] for c in CASES])
def test_builtin_value(s, expr, expected):
    got = q1(s, expr)
    if isinstance(expected, float):
        assert abs(got - expected) < 1e-9, (expr, got)
    else:
        assert got == expected, (expr, got)


def test_aes_roundtrip(s):
    # nested round trip (the string carrier is byte-preserving latin-1)
    assert q1(s, "aes_decrypt(aes_encrypt('secret text', 'mykey'),"
              " 'mykey')") == "secret text"
    # wrong key: NULL on bad PKCS7 padding (overwhelmingly likely) or at
    # minimum NOT the plaintext
    got = q1(s, "aes_decrypt(aes_encrypt('secret text', 'mykey'),"
             " 'other')")
    assert got != "secret text"


def test_uuid_shape(s):
    u = q1(s, "uuid()")
    import re

    assert re.match(r"[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-"
                    r"[0-9a-f]{4}-[0-9a-f]{12}$", u)


def test_null_propagation(s):
    for e in ("bin(null)", "conv(null, 10, 2)", "elt(null, 'a')",
              "substring_index(null, '.', 1)", "str_to_date('x', '%Y')",
              "inet_aton('999.1.1.1')", "timediff(null, '00:00:01')"):
        assert q1(s, e) is None, e
    assert q1(s, "quote(null)") == "NULL"  # special: literal string


def test_vectorized_over_table(s):
    s.execute("create table bx (a bigint, t varchar(40))")
    s.execute("insert into bx values (5, 'www.a.b'), (12, 'x.y.z'),"
              " (null, null)")
    rows = s.query("select bin(a), substring_index(t, '.', 1),"
                   " field(t, 'x.y.z', 'www.a.b') from bx order by a")
    assert rows[0] == (None, None, 0)
    assert rows[1] == ("101", "www", 2)
    assert rows[2] == ("1100", "x", 1)
