"""Chaos-sweep harness + degraded-mesh device failover.

Tentpole coverage for the device-health subsystem (copr/device_health.py):

- a virtual device failpoint-killed MID-SCAN on the 8-device CPU mesh must
  not demote the query off the mesh path — the breaker trips, sharded
  arrays keyed to the dead device set evict, and the SAME shard_map
  program re-runs over the surviving 7 devices with identical results;
- information_schema.TIDB_TPU_DEVICE_HEALTH surfaces the tripped breaker
  and a later half-open probe restores the full mesh;
- the seeded chaos sweep arms every registered failpoint family across the
  query path (mesh, distsql fan-out, region routing, 2PC, DDL backfill)
  and asserts result parity vs the CPU engine, zero leaked locks and zero
  leaked producer threads.

Everything is deterministic: `once()` injections, seeded data, no sleeps
on the failure paths.
"""

import threading
import time

import numpy as np
import pytest

from tidb_tpu.copr.device_health import (
    DEVICE_HEALTH,
    DeviceFailure,
    HbmOomError,
)
from tidb_tpu.errors import TiDBTPUError, TxnConflictError
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import FAILPOINTS, failpoint, once

Q1 = ("select g, sum(x), count(*), min(x), max(x), avg(x) from t "
      "group by g order by g")
Q6 = "select sum(x) from t where k < 15000 and x < 50"
TOPN = "select k, x from t order by x desc limit 7"
FILTER = "select k from t where x < 2.5"

SWEEP_QUERIES = (Q1, Q6, TOPN, FILTER)


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table t (k bigint, g bigint, x double)")
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(7)
    n = 20_000
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 5, n, dtype=np.int64),
         rng.uniform(0, 100, n)],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, 4, store.base_rows)
    return s


@pytest.fixture(autouse=True)
def _healthy_devices():
    """Device health is process-global: every test starts AND ends with
    closed breakers so failures never bleed across tests/modules."""
    DEVICE_HEALTH.reset()
    yield
    DEVICE_HEALTH.reset()


def _approx_eq(a, b):
    if isinstance(a, float) or isinstance(b, float):
        return a == pytest.approx(b, rel=1e-9, abs=1e-9)
    return a == b


def _rows_eq(got, want, ctx=""):
    assert len(got) == len(want), (ctx, got, want)
    for ra, rb in zip(sorted(got), sorted(want)):
        assert all(_approx_eq(x, y) for x, y in zip(ra, rb)), (ctx, ra, rb)


def _cpu_rows(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _snap(*names):
    s = REGISTRY.snapshot()
    return tuple(s.get(n, 0) for n in names)


def _mesh_ids():
    from tidb_tpu.copr import parallel as pl

    mesh = pl._MESH
    return tuple(d.id for d in mesh.devices.ravel()) if mesh else ()


def _run_on_mesh(sess, sql):
    """Run `sql` on the tpu engine asserting it was SERVED BY THE MESH:
    mesh_scans_total grew and no per-region cop task ran (the whole-query
    fallback path increments cop_tasks_total)."""
    sess.execute("set tidb_use_tpu = 1")
    m0, c0, f0 = _snap("mesh_scans_total", "cop_tasks_total",
                       "mesh_scan_errors_total")
    rows = sess.query(sql)
    m1, c1, f1 = _snap("mesh_scans_total", "cop_tasks_total",
                       "mesh_scan_errors_total")
    assert m1 > m0, f"not on the mesh path: {sql}"
    assert c1 == c0, f"fell back to per-region fan-out: {sql}"
    assert f1 == f0, f"mesh scan errored into fallback: {sql}"
    return rows


# ---------------------------------------------------------------------------
# degraded-mesh failover (the tentpole acceptance path)
# ---------------------------------------------------------------------------


def test_device_kill_mid_scan_serves_from_rebuilt_mesh(sess):
    """Kill virtual device 3 mid-scan: Q1/Q6/TopN still complete with
    CPU-parity results, served by a REBUILT 7-device mesh (not the
    whole-query fallback); the health table shows the tripped breaker and
    a half-open probe later restores the full 8-device mesh."""
    from tidb_tpu.copr import parallel as pl

    # warm: full mesh in place
    _run_on_mesh(sess, Q6)
    assert len(_mesh_ids()) == 8

    # explicit EXPLAIN ANALYZE attribution: despite the mid-scan kill the
    # scan reports scan_engine == "mesh", served by the 7-device rebuild
    with failpoint("mesh/device_error",
                   once(DeviceFailure("device 3 halted mid-scan",
                                      device_ids=(3,)))):
        plan = "\n".join(str(r) for r in sess.execute(
            "explain analyze " + Q6)[0].rows)
    assert "engine:mesh" in plan, plan
    assert len(_mesh_ids()) == 7 and 3 not in _mesh_ids()

    for sql in (Q1, Q6, TOPN):
        want = _cpu_rows(sess, sql)
        r0 = _snap("mesh_failover_retries_total")[0]
        with failpoint("mesh/device_error",
                       once(DeviceFailure("device 3 halted mid-scan",
                                          device_ids=(3,)))):
            got = _run_on_mesh(sess, sql)
        _rows_eq(got, want, sql)
        assert _snap("mesh_failover_retries_total")[0] > r0
        ids = _mesh_ids()
        assert len(ids) == 7 and 3 not in ids, ids

    # the breaker is visible through information_schema
    h = {r[0]: r for r in sess.query(
        "select device_id, state, error_count, trip_count, in_current_mesh"
        " from information_schema.tidb_tpu_device_health")}
    assert h[3][1] == "tripped" and h[3][2] >= 1 and h[3][3] >= 1
    assert h[3][4] == 0  # quarantined out of the live mesh
    assert h[0][1] == "healthy" and h[0][4] == 1
    assert REGISTRY.snapshot().get("device_health_tripped_count") == 1

    # sharded arrays keyed to the dead device set were evicted: nothing in
    # the mesh cache may reference device 3
    for key in pl.MESH_CACHE._cache:
        assert 3 not in key[3], key

    # half-open probe: cooldown over -> device 3 rejoins for one trial,
    # the trial succeeds, the breaker closes, the FULL mesh is back
    DEVICE_HEALTH.expire_cooldowns()
    want = _cpu_rows(sess, Q1)
    got = _run_on_mesh(sess, Q1)
    _rows_eq(got, want, "post-probe Q1")
    assert len(_mesh_ids()) == 8
    h = {r[0]: r for r in sess.query(
        "select device_id, state, in_current_mesh"
        " from information_schema.tidb_tpu_device_health")}
    assert h[3][1] == "healthy" and h[3][2] == 1
    assert REGISTRY.snapshot().get("device_health_tripped_count") == 0


def test_failed_probe_retrips_breaker(sess):
    """A device that fails AGAIN during its half-open probe goes straight
    back to tripped (no flapping through healthy)."""
    _run_on_mesh(sess, Q6)
    DEVICE_HEALTH.record_error(2, RuntimeError("first failure"))
    assert DEVICE_HEALTH.state_of(2) == "tripped"
    DEVICE_HEALTH.expire_cooldowns()
    with failpoint("mesh/device_error",
                   once(DeviceFailure("still dead", device_ids=(2,)))):
        got = _run_on_mesh(sess, Q6)
    _rows_eq(got, _cpu_rows(sess, Q6), Q6)
    assert DEVICE_HEALTH.state_of(2) == "tripped"
    assert 2 not in _mesh_ids()


def test_hbm_oom_evicts_tile_caches_and_retries(sess):
    """HBM exhaustion is recoverable: evict the device tile caches (HBM is
    a cache over host blocks), re-run the same program, full parity — and
    no breaker trips for an unattributed OOM."""
    from tidb_tpu.copr import parallel as pl

    _run_on_mesh(sess, Q1)
    assert pl.MESH_CACHE._cache  # warm
    want = _cpu_rows(sess, Q1)
    o0 = _snap("mesh_hbm_oom_total")[0]
    with failpoint("mesh/hbm_oom",
                   once(HbmOomError("RESOURCE_EXHAUSTED: HBM space"))):
        got = _run_on_mesh(sess, Q1)
    _rows_eq(got, want, Q1)
    assert _snap("mesh_hbm_oom_total")[0] == o0 + 1
    assert len(_mesh_ids()) == 8  # nobody quarantined
    assert DEVICE_HEALTH.tripped_ids() == ()
    assert pl.MESH_CACHE._cache  # re-warmed by the retry


def test_all_breakers_open_steps_down_ladder(sess):
    """Every breaker open and no probe due: the mesh path steps aside and
    the per-region fan-out serves the query (next failover rung)."""
    import jax

    for d in jax.devices():
        DEVICE_HEALTH.record_error(d.id, RuntimeError(f"dead {d.id}"))
    want = _cpu_rows(sess, Q6)
    sess.execute("set tidb_use_tpu = 1")
    c0 = _snap("cop_tasks_total")[0]
    got = sess.query(Q6)
    _rows_eq(got, want, Q6)
    assert _snap("cop_tasks_total")[0] > c0  # per-region rung served it


# ---------------------------------------------------------------------------
# the seeded chaos sweep
# ---------------------------------------------------------------------------


def _wait_no_select_threads(timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "tidb-tpu-select" and t.is_alive()]
        if not alive:
            return []
        time.sleep(0.01)
    return alive


def _assert_no_leaks(domain):
    for tid in domain.storage.table_ids():
        assert domain.storage.table(tid).locks == {}, f"leaked locks t{tid}"
    assert _wait_no_select_threads() == [], "leaked producer threads"
    assert FAILPOINTS.armed() == [], "leaked armed failpoints"


def test_chaos_sweep_read_path(sess):
    """Arm each read-path failpoint site in turn (mesh device kill, HBM
    OOM, rebuild interruption, fan-out task error, region routing error)
    and assert every query shape keeps CPU parity with no leaks."""
    from tidb_tpu.errors import RegionError

    baselines = {sql: _cpu_rows(sess, sql) for sql in SWEEP_QUERIES}
    # (site, injected error, engine): mesh sites sit on the tpu mesh path;
    # the fan-out sites sit on the per-region path, exercised directly
    sites = [
        ("mesh/device_error",
         lambda: DeviceFailure("chip 5 died", device_ids=(5,)), "tpu"),
        ("mesh/hbm_oom",
         lambda: HbmOomError("hbm allocation failure"), "tpu"),
        ("mesh/rebuild", lambda: RuntimeError("rebuild interrupted"), "tpu"),
        ("distsql/task_error", lambda: RuntimeError("chip died"), "cpu"),
        ("copr/region_error", lambda: RegionError("injected"), "cpu"),
    ]
    for name, make_exc, engine in sites:
        sess.execute(f"set tidb_use_tpu = {1 if engine == 'tpu' else 0}")
        if name == "mesh/rebuild":
            # a rebuild only happens when the device set changes
            DEVICE_HEALTH.record_error(1, RuntimeError("pre-tripped"))
        for sql in SWEEP_QUERIES:
            fired = {"n": 0}

            def action(_exc=make_exc, _f=fired, **ctx):
                _f["n"] += 1
                if _f["n"] == 1:
                    raise _exc()

            with failpoint(name, action):
                got = sess.query(sql)
            _rows_eq(got, baselines[sql], f"{name}: {sql}")
            assert fired["n"] >= 1, f"failpoint {name} never fired ({sql})"
        DEVICE_HEALTH.reset()
        sess.execute("set tidb_use_tpu = 1")
    _assert_no_leaks(sess.domain)
    # and the full mesh serves cleanly after the whole sweep
    got = _run_on_mesh(sess, Q1)
    _rows_eq(got, baselines[Q1], "post-sweep Q1")
    assert len(_mesh_ids()) == 8


def test_chaos_sweep_write_and_ddl_path():
    """2PC prewrite conflicts and DDL backfill crashes: statements retry
    or roll back cleanly — committed state stays consistent, no lock or
    thread leaks."""
    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table w (a bigint primary key, b bigint)")
    s.execute("insert into w values (1, 10)")

    # 2PC: injected prewrite conflict -> the session's optimistic retry
    # re-runs the autocommit statement and commits
    with failpoint("2pc/prewrite", once(TxnConflictError((0, 0)))):
        s.execute("insert into w values (2, 20)")
    assert s.query("select a, b from w order by a") == [(1, 10), (2, 20)]

    # DDL: backfill (over a bulk-loaded base, so batches actually run)
    # dies -> job rolls back, the index name stays free, data unharmed;
    # a clean re-run succeeds
    s.execute("create table wd (a bigint, b bigint)")
    td = d.catalog.info_schema().table("test", "wd")
    sd = d.storage.table(td.id)
    sd.bulk_load_arrays(
        [np.arange(2000, dtype=np.int64),
         np.arange(2000, dtype=np.int64) % 10],
        ts=d.storage.current_ts())
    with failpoint("ddl/backfill_batch",
                   once(RuntimeError("backfill chip lost"))):
        with pytest.raises(RuntimeError):
            s.execute("create index ib on wd (b)")
    assert d.catalog.info_schema().table("test", "wd").find_index("ib") is None
    assert s.query("select count(*) from wd") == [(2000,)]
    s.execute("create index ib on wd (b)")
    assert s.query("select count(*) from wd where b = 3") == [(200,)]

    _assert_no_leaks(d)


def test_chaos_2pc_decision_point_runs_to_completion():
    """Past 2pc/before_commit_primary the transaction is DECIDED: a
    kill landing at that seam must not abort phase 2 — the primary and
    every secondary (2pc/commit_secondary) still commit, primary
    first."""
    from tidb_tpu.errors import QueryKilledError

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table p2 (a bigint primary key, b bigint)")
    order = []

    def at_decision(**ctx):
        order.append("decide")
        s.cancel_query("killed")  # lands AT the decision point: too late

    def at_secondary(**ctx):
        order.append("secondary")

    with failpoint("2pc/before_commit_primary", at_decision):
        with failpoint("2pc/commit_secondary", at_secondary):
            try:
                s.execute("insert into p2 values (1,10), (2,20), (3,30)")
            except QueryKilledError:
                pass  # the statement may unwind at a LATER seam...
    # ...but the commit itself ran to completion, in decision order
    assert order == ["decide", "secondary", "secondary"], order
    assert s.query("select a, b from p2 order by a") == \
        [(1, 10), (2, 20), (3, 30)]
    _assert_no_leaks(d)


# ---------------------------------------------------------------------------
# mpp/exchange: the eighth chaos site (device failure mid-shuffle)
# ---------------------------------------------------------------------------


def test_mpp_exchange_device_failure_degrades_down_ladder():
    """A device killed mid-shuffle must degrade, not fail: a transient
    kill retries on the REBUILT mesh (still MPP), a persistent one lands
    on the host hash join — CPU parity throughout, zero leaked threads,
    zero leaked failpoints."""
    from tidb_tpu.copr import parallel as pl

    d = Domain()
    s = d.new_session()
    s.execute("create table mo (k bigint primary key, f bigint)")
    s.execute("create table ml (k bigint, q bigint)")
    rng = np.random.default_rng(13)
    t_o = d.catalog.info_schema().table("test", "mo")
    t_l = d.catalog.info_schema().table("test", "ml")
    d.storage.table(t_o.id).bulk_load_arrays(
        [np.arange(4000, dtype=np.int64), rng.integers(0, 3, 4000)],
        ts=d.storage.current_ts())
    d.storage.table(t_l.id).bulk_load_arrays(
        [rng.integers(0, 12000, 16000), rng.integers(1, 9, 16000)],
        ts=d.storage.current_ts())
    s.execute("analyze table mo")
    s.execute("analyze table ml")
    s.execute("set tidb_enforce_mpp = 1")
    q = "select count(*), sum(q), max(f) from ml join mo on ml.k = mo.k"
    want = _cpu_rows(s, q)
    _rows_eq(s.query(q), want, "warm")

    # transient: one kill -> breaker trips, mesh rebuilds, SAME rung
    m0, f0 = _snap("mpp_joins_total", "mpp_fallback_total")
    with failpoint("mpp/exchange",
                   once(DeviceFailure("chip 3 died mid-shuffle",
                                      device_ids=(3,)))):
        got = s.query(q)
    _rows_eq(got, want, "transient mid-shuffle kill")
    m1, f1 = _snap("mpp_joins_total", "mpp_fallback_total")
    assert m1 > m0 and f1 == f0, "transient kill left the mpp rung"
    ids = tuple(dd.id for dd in pl._MESH.devices.ravel())
    assert 3 not in ids and len(ids) == 7, ids
    DEVICE_HEALTH.reset()

    # persistent: every retry dies -> host hash join serves with parity
    f0 = _snap("mpp_fallback_total")[0]
    from tidb_tpu.store.fault import always

    with failpoint("mpp/exchange",
                   always(DeviceFailure("chip 4 stays dead",
                                        device_ids=(4,)))):
        got = s.query(q)
    _rows_eq(got, want, "persistent mid-shuffle failure")
    assert _snap("mpp_fallback_total")[0] > f0, "host rung never served"
    DEVICE_HEALTH.reset()
    # drop this throwaway domain's sharded arrays: they were (re)loaded
    # on degraded meshes and must not linger for later modules
    uids = {d.storage.table(t_o.id).store_uid,
            d.storage.table(t_l.id).store_uid}
    pl.MESH_CACHE._c.evict_if(lambda k: k[0] in uids)
    _assert_no_leaks(d)


def test_tile_path_routes_around_tripped_default_device(sess):
    """ROADMAP PR-2 follow-up (a): the per-region tile path
    (jax_engine.run_base_jax) must not pin work to a tripped default
    device — tiles place on the surviving devices and a completed scan
    closes half-open breakers."""
    import jax

    from tidb_tpu.copr import jax_engine as je

    default_id = jax.devices()[0].id
    DEVICE_HEALTH.record_error(default_id, RuntimeError("chip 0 sick"))
    assert DEVICE_HEALTH.state_of(default_id) == "tripped"
    devs = je._tile_devices()
    assert default_id not in [d.id for d in devs]

    # drive a real per-region scan (mesh path disabled via many ranges is
    # intrusive; call the tile engine directly like distsql's fallback)
    d = sess.domain
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    from tidb_tpu.copr.ir import DAG, TableScanIR

    dag = DAG([TableScanIR(t.id, [0], [t.columns[0].ftype])])
    je.DEVICE_CACHE.clear()
    chunks = je.run_base_jax(store, dag, 0, store.base_rows, set())
    assert sum(c.num_rows for c in chunks) == store.base_rows
    placed = {k[4] for k in je.DEVICE_CACHE._c.items_view}
    assert default_id not in placed, placed
    # the completed scan recorded success for the devices it used; the
    # tripped default stays tripped until its half-open probe
    assert DEVICE_HEALTH.state_of(default_id) == "tripped"
    DEVICE_HEALTH.reset()
    je.DEVICE_CACHE.clear()


# ---------------------------------------------------------------------------
# fail-fast fan-out + configurable equal-jitter backoff (satellites)
# ---------------------------------------------------------------------------


def test_failfast_fanout_abandons_retrying_siblings():
    """First task error flags the stop event: a sibling stuck in its
    transient-retry loop abandons within one backoff step instead of
    burning the full 10s budget for a query that already failed."""
    from tidb_tpu.errors import ExecutorError

    d = Domain()
    s = d.new_session()
    s.execute("create table ff (a bigint)")
    t = d.catalog.info_schema().table("test", "ff")
    store = d.storage.table(t.id)
    store.bulk_load_arrays([np.arange(2000, dtype=np.int64)],
                           ts=d.storage.current_ts())
    d.storage.regions.split_even(t.id, 2, store.base_rows)
    s.execute("set tidb_use_tpu = 0")

    attempts = {"n": 0}

    def action(range=None, **ctx):
        if range.start == 0:
            time.sleep(0.05)
            raise ExecutorError("poison task")  # semantic: no retry
        attempts["n"] += 1
        raise OSError("flaky net")  # transient: retries with backoff

    f0 = _snap("cop_fanout_failfast_total")[0]
    with failpoint("distsql/task_error", action):
        with pytest.raises(ExecutorError):
            s.query("select sum(a) from ff")
        # the flaky sibling must stop retrying once the query failed
        time.sleep(0.7)
        settled = attempts["n"]
        time.sleep(0.7)
        assert attempts["n"] == settled, "sibling kept retrying after error"
    assert settled < 10  # nowhere near a full 10s budget worth of attempts
    assert _snap("cop_fanout_failfast_total")[0] == f0 + 1
    _assert_no_leaks(d)


def test_backoffer_equal_jitter_schedule():
    """Equal jitter (backoff.go NewBackoffFn): each sleep lands in
    [expo/2, expo] of the capped exponential schedule, and two backoffers
    de-synchronize."""
    import random

    from tidb_tpu.distsql.backoff import Backoffer

    sleeps = []
    bo = Backoffer(budget_ms=60_000, sleep=sleeps.append,
                   rng=random.Random(7))
    for _ in range(9):
        bo.backoff("task_error")
    assert bo.attempts("task_error") == 9
    for n, slept in enumerate(sleeps):
        expo_s = min(5 * (2 ** n), 1000) / 1000.0
        assert expo_s / 2 <= slept <= expo_s, (n, slept)
    other = []
    bo2 = Backoffer(budget_ms=60_000, sleep=other.append,
                    rng=random.Random(8))
    for _ in range(9):
        bo2.backoff("task_error")
    assert sleeps != other  # jitter de-synchronizes concurrent retries


def test_backoff_budget_exceeded_surfaces_last_error():
    import random

    from tidb_tpu.distsql.backoff import BackoffBudgetExceeded, Backoffer

    bo = Backoffer(budget_ms=5, sleep=lambda s: None, rng=random.Random(1))
    with pytest.raises(BackoffBudgetExceeded, match="flaky"):
        for _ in range(100):
            bo.backoff("task_error", OSError("flaky"))


def test_backoff_budget_session_var():
    """tidb_backoff_budget_ms replaces the hard-coded 10s: a tiny budget
    makes a permanently failing scan surface its error immediately."""
    from tidb_tpu.store.fault import always

    d = Domain()
    s = d.new_session()
    s.execute("create table bb (a bigint)")
    s.execute("insert into bb values (1), (2)")
    s.execute("set tidb_use_tpu = 0")
    s.execute("set tidb_backoff_budget_ms = 1")
    t0 = time.perf_counter()
    with failpoint("distsql/task_error", always(OSError("flaky net"))):
        with pytest.raises(TiDBTPUError, match="budget exhausted"):
            s.query("select sum(a) from bb")
    assert time.perf_counter() - t0 < 2.0  # not the default 10s budget
    _assert_no_leaks(d)


# ---------------------------------------------------------------------------
# exec/cancel: statement killed mid-distsql / mid-MPP / mid-backfill
# (ISSUE 5 chaos coverage)
# ---------------------------------------------------------------------------


def _cancel_at(site_wanted):
    """Failpoint action for exec/cancel: cancel the statement's scope
    (the way KILL QUERY does) the first time the named site is hit."""
    fired = {"n": 0}

    def action(site=None, scope=None, **ctx):
        if site == site_wanted and scope is not None:
            fired["n"] += 1
            if fired["n"] == 1:
                scope.cancel("killed")

    return action, fired


def test_exec_cancel_mid_distsql(sess):
    """Kill landing between distsql task dispatches: the statement errors
    with ER_QUERY_INTERRUPTED, leaks nothing, and an immediate re-run
    returns full parity."""
    from tidb_tpu.errors import QueryKilledError

    want = _cpu_rows(sess, Q1)
    sess.execute("set tidb_use_tpu = 0")
    action, fired = _cancel_at("distsql")
    with failpoint("exec/cancel", action):
        with pytest.raises(QueryKilledError):
            sess.query(Q1)
    assert fired["n"] >= 1, "exec/cancel never hit the distsql site"
    assert sess.last_termination == "killed"
    sess.execute("set tidb_use_tpu = 1")
    _assert_no_leaks(sess.domain)
    _rows_eq(sess.query(Q1), want, "post-cancel re-run parity")


def test_exec_cancel_mid_mpp():
    """Kill landing at an MPP rung transition: the exchange engine
    surfaces the termination error instead of stepping down the ladder,
    and the rebuilt state serves a clean re-run."""
    from tidb_tpu.errors import QueryKilledError

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table co (k bigint primary key, f bigint)")
    s.execute("create table cl (k bigint, q bigint)")
    rng = np.random.default_rng(23)
    t_o = d.catalog.info_schema().table("test", "co")
    t_l = d.catalog.info_schema().table("test", "cl")
    d.storage.table(t_o.id).bulk_load_arrays(
        [np.arange(3000, dtype=np.int64), rng.integers(0, 3, 3000)],
        ts=d.storage.current_ts())
    d.storage.table(t_l.id).bulk_load_arrays(
        [rng.integers(0, 9000, 12000), rng.integers(1, 9, 12000)],
        ts=d.storage.current_ts())
    s.execute("analyze table co")
    s.execute("analyze table cl")
    s.execute("set tidb_enforce_mpp = 1")
    q = "select count(*), sum(q) from cl join co on cl.k = co.k"
    want = _cpu_rows(s, q)

    action, fired = _cancel_at("mpp")
    with failpoint("exec/cancel", action):
        with pytest.raises(QueryKilledError):
            s.query(q)
    assert fired["n"] >= 1, "exec/cancel never hit the mpp site"
    assert s.last_termination == "killed"
    _assert_no_leaks(d)
    _rows_eq(s.query(q), want, "post-cancel mpp re-run parity")


def test_exec_cancel_mid_backfill():
    """Kill landing between DDL backfill batches: the online add-index
    job rolls back (name reusable, data unharmed), no reorg checkpoints
    leak, and a clean re-run builds the index."""
    from tidb_tpu.errors import QueryKilledError

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table cb (a bigint, b bigint)")
    t = d.catalog.info_schema().table("test", "cb")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(9000, dtype=np.int64),
         np.arange(9000, dtype=np.int64) % 10],
        ts=d.storage.current_ts())

    action, fired = _cancel_at("backfill")
    with failpoint("exec/cancel", action):
        with pytest.raises(QueryKilledError):
            s.execute("create index icb on cb (b)")
    assert fired["n"] >= 1, "exec/cancel never hit the backfill site"
    assert d.catalog.info_schema().table("test", "cb") \
        .find_index("icb") is None
    jobs = [j for j in d.catalog.jobs if j.table == "cb"]
    assert jobs and jobs[-1].state == "rollback"
    assert s.query("select count(*) from cb") == [(9000,)]
    _assert_no_leaks(d)
    s.execute("create index icb on cb (b)")
    assert s.query("select count(*) from cb where b = 3") == [(900,)]
