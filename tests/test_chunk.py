"""Columnar core tests (reference model: util/chunk/chunk_test.go)."""

import numpy as np

from tidb_tpu.chunk import (
    Chunk,
    Column,
    chunk_from_pylists,
    concat_chunks,
    decode_chunk,
    encode_chunk,
)
from tidb_tpu.types import (
    ty_date,
    ty_decimal,
    ty_float,
    ty_int,
    ty_string,
    parse_date,
)


def test_column_from_values_with_nulls():
    c = Column.from_values(ty_int(), [1, None, 3])
    assert len(c) == 3
    assert c.null_count() == 1
    assert c.get(0) == 1
    assert c.get(1) is None
    assert c.get(2) == 3
    assert c.to_pylist() == [1, None, 3]


def test_column_all_valid_normalizes():
    c = Column.from_values(ty_int(), [1, 2, 3])
    assert c.valid is None
    assert not c.has_nulls


def test_string_column():
    c = Column.from_values(ty_string(), ["a", None, "ccc"])
    assert c.to_pylist() == ["a", None, "ccc"]


def test_filter_take_slice():
    c = Column.from_values(ty_float(), [1.0, None, 3.0, 4.0])
    m = np.array([True, False, True, True])
    assert c.filter(m).to_pylist() == [1.0, 3.0, 4.0]
    assert c.take(np.array([3, 0])).to_pylist() == [4.0, 1.0]
    assert c.slice(1, 3).to_pylist() == [None, 3.0]


def test_chunk_basics():
    ch = chunk_from_pylists(
        [ty_int(), ty_string()], [[1, 2, 3], ["x", "y", None]]
    )
    assert ch.num_rows == 3
    assert ch.num_cols == 2
    assert ch.row(2) == (3, None)
    assert ch.to_pylist() == [(1, "x"), (2, "y"), (3, None)]


def test_chunk_split_and_concat():
    ch = chunk_from_pylists([ty_int()], [list(range(10))])
    parts = list(ch.split(4))
    assert [p.num_rows for p in parts] == [4, 4, 2]
    back = concat_chunks(parts)
    assert back.to_pylist() == ch.to_pylist()


def test_codec_roundtrip():
    ch = chunk_from_pylists(
        [ty_int(), ty_float(), ty_string(), ty_decimal(12, 2), ty_date()],
        [
            [1, None, 3],
            [1.5, 2.5, None],
            ["ab", "", None],
            [199, 250, -301],
            [parse_date("1998-09-02"), None, 0],
        ],
    )
    buf = encode_chunk(ch)
    back = decode_chunk(buf)
    # NULL string decodes as empty-with-null-flag; compare via to_pylist
    assert back.to_pylist() == ch.to_pylist()
    assert [c.ftype for c in back.columns] == [c.ftype for c in ch.columns]


def test_codec_empty_chunk():
    ch = chunk_from_pylists([ty_int(), ty_string()], [[], []])
    back = decode_chunk(encode_chunk(ch))
    assert back.num_rows == 0
    assert back.num_cols == 2
