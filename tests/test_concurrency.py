"""Concurrency stress + lock-order witness tests (ISSUE 16).

The suite-wide conftest sets TIDB_TPU_LOCKCHECK=1 before tidb_tpu is
imported, so every lock here is a RankedLock and the autouse
`_no_lock_order_violations` fixture fails any test whose threads invert
the declared rank order.  These tests hammer the three most contended
shared structures (ByteCapCache, metrics.Registry,
DeviceHealthRegistry) from 8 threads and assert the invariants the
locks exist to protect: no lost increments, consistent byte
accounting, no torn breaker state.
"""

import threading
import time

import numpy as np
import pytest

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.util_concurrency import (
    LockOrderError,
    held_depth,
    lockcheck_enabled,
    make_lock,
    make_rlock,
    reset_witness_stats,
    witness_stats,
)

N_THREADS = 8


def _run_threads(fn, n=N_THREADS):
    """Run fn(i) on n threads; re-raise the first worker exception."""
    errs = []

    def wrap(i):
        try:
            fn(i)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]


# ---------------------------------------------------------------------------
# witness semantics
# ---------------------------------------------------------------------------

def test_witness_enabled_in_suite():
    assert lockcheck_enabled()
    assert witness_stats()["enabled"]


def test_rank_inversion_raises(monkeypatch):
    from tidb_tpu.lint import concur

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:LO", 1)
    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:HI", 2)
    lo = make_lock("tests.concur:LO")
    hi = make_lock("tests.concur:HI")
    with lo:
        with hi:  # increasing rank: legal
            assert held_depth() == 2
    assert held_depth() == 0
    v0 = witness_stats()["violations"]
    with hi:
        with pytest.raises(LockOrderError):
            lo.acquire()
    assert held_depth() == 0
    assert witness_stats()["violations"] == v0 + 1
    # the violation above was deliberate — reset so the autouse
    # fixture does not fail this test for its own assertion
    reset_witness_stats()


def test_equal_rank_never_nests(monkeypatch):
    from tidb_tpu.lint import concur

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:A", 7)
    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:B", 7)
    a = make_rlock("tests.concur:A")
    b = make_rlock("tests.concur:B")
    with a:
        with a:  # same-OBJECT RLock re-entry is legal
            pass
        with pytest.raises(LockOrderError):
            b.acquire()  # same RANK, different lock: never legal
    reset_witness_stats()


def test_unregistered_lock_name_raises():
    with pytest.raises(LockOrderError):
        make_lock("tests.concur:not-in-the-registry")


def test_witness_trips_held_lock_wait(monkeypatch):
    """Concurrency (a): blocking on a condition/event WAIT while holding
    a ranked lock is banned outright — the notifier may need a lower-
    ranked lock to run, so the wait is a deadlock waiting for load.
    Counted under "wait_trips", NOT "violations" (the autouse fixture
    must not fail this test for its own assertion)."""
    from tidb_tpu.lint import concur
    from tidb_tpu.util_concurrency import witness_wait_check

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:W", 5)
    mu = make_lock("tests.concur:W")
    s0 = witness_stats()
    witness_wait_check("bare")  # no lock held: fine
    with mu:
        with pytest.raises(LockOrderError, match="held-lock wait"):
            witness_wait_check("Cond.wait")
    s1 = witness_stats()
    assert s1["wait_trips"] == s0["wait_trips"] + 1
    assert s1["violations"] == s0["violations"]
    reset_witness_stats()


def test_scope_wait_trips_under_held_lock(monkeypatch):
    """QueryScope.wait — the seam every backoff and throttle poll rides
    — calls the witness check, so a held-lock sleep anywhere in the
    stack surfaces immediately under test."""
    from tidb_tpu.lifecycle import QueryScope
    from tidb_tpu.lint import concur

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:SW", 5)
    mu = make_lock("tests.concur:SW")
    sc = QueryScope()
    assert sc.wait(0.001) is False  # unheld: a normal bounded sleep
    with mu:
        with pytest.raises(LockOrderError):
            sc.wait(0.001)
    reset_witness_stats()


def test_contention_counters_per_lock(monkeypatch):
    """Concurrency (c): contended acquisitions land in the per-lock
    log2 wait-ms histogram; uncontended ones stay off the books."""
    from tidb_tpu.lint import concur

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:CONT", 5)
    mu = make_lock("tests.concur:CONT")
    with mu:
        pass  # uncontended: no table entry for this lock
    assert "tests.concur:CONT" not in witness_stats()["locks"]

    gate = threading.Event()

    def holder():
        with mu:
            gate.set()
            time.sleep(0.02)

    t = threading.Thread(target=holder)
    t.start()
    gate.wait()
    with mu:  # blocks ~20ms behind the holder
        pass
    t.join()
    rec = witness_stats()["locks"]["tests.concur:CONT"]
    assert rec["contended"] >= 1
    assert rec["wait_ms"] > 0
    assert sum(rec["wait_ms_log2"]) == rec["contended"]
    reset_witness_stats()


# ---------------------------------------------------------------------------
# 8-thread stress
# ---------------------------------------------------------------------------

def test_registry_stress_no_lost_increments():
    per_thread = 2000
    c0 = REGISTRY.get("concurrency_stress_test_total")

    def work(_i):
        for _ in range(per_thread):
            REGISTRY.inc("concurrency_stress_test_total")

    _run_threads(work)
    got = REGISTRY.get("concurrency_stress_test_total") - c0
    assert got == N_THREADS * per_thread, f"lost {N_THREADS*per_thread-got}"


def test_bytecap_cache_stress_byte_accounting():
    from tidb_tpu.copr.cache import ByteCapCache

    cache = ByteCapCache(capacity_bytes=64 * 1024)
    # value-weighted eviction exercised concurrently too
    cache.set_policy(priority_fn=lambda k: k[1] % 3)
    n_keys = 23

    def _load(idx):
        # deterministic per-key size, 1..5 KiB of float32
        return (np.full(256 * (1 + idx % 5), float(idx), np.float32),)

    def work(i):
        for j in range(300):
            idx = (i * 7 + j) % n_keys
            v = cache.get_or_load(("stress", idx),
                                  lambda idx=idx: _load(idx))
            assert float(v[0][0]) == float(idx)  # never a torn value

    _run_threads(work)
    with cache._mu:
        resident = sum(sum(a.nbytes for a in v if a is not None)
                       for v in cache._cache.values())
        assert resident == cache._bytes  # accounting matches contents
        assert cache._bytes <= cache.capacity
        assert sorted(cache._order) == sorted(cache._cache)
        assert not cache._inflight  # every loader completed
        assert cache.hwm_bytes >= cache._bytes


def test_device_health_stress_consistent_states():
    from tidb_tpu.copr.device_health import (
        DeviceFailure,
        DeviceHealthRegistry,
    )

    class _Dev:
        __slots__ = ("id",)

        def __init__(self, i):
            self.id = i

    devs = [_Dev(i) for i in range(8)]
    reg = DeviceHealthRegistry(trip_threshold=3, probe_after_s=0.01)

    def work(i):
        for j in range(200):
            d = (i + j) % 8
            if (i + j) % 3 == 0:
                reg.record_error(d, DeviceFailure("stress", (d,)))
            else:
                reg.record_success([d])
            healthy = reg.select_devices(devs)
            assert len(healthy) <= 8
            reg.tripped_ids()
            if j % 50 == 0:
                reg.expire_cooldowns()

    _run_threads(work)
    snap = reg.snapshot()
    assert {s.device_id for s in snap} <= set(range(8))
    for s in snap:
        assert s.error_count >= 0 and s.trip_count >= 0
        # a consecutive-error run can never exceed the trip threshold:
        # hitting it trips the breaker (torn updates would overshoot)
        assert s.consecutive_errors <= 3


# ---------------------------------------------------------------------------
# regression: coordinator state replay vs concurrent registers
# ---------------------------------------------------------------------------

def test_coordinator_replay_races_register(tmp_path):
    """_load_state used to mutate _epoch/_members/_handoff OUTSIDE the
    membership mutex; a replay racing a register could clobber the
    concurrent join.  Replay now holds _mu (and flushes after releasing
    it — the witness enforces the _save_io_mu -> _mu order)."""
    from tidb_tpu.coord.plane import Coordinator

    state = tmp_path / "coord.json"
    c = Coordinator(lease_s=30.0, state_path=str(state))
    c.register(1, [0])
    c.register(2, [1])
    c._flush_state()

    stop = threading.Event()

    def replayer():
        while not stop.is_set():
            c._load_state()

    t = threading.Thread(target=replayer)
    t.start()
    try:
        for pid in range(10, 40):
            c.register(pid, [pid % 8])
    finally:
        stop.set()
        t.join()
    members = c.view().members
    for pid in [1, 2] + list(range(10, 40)):
        assert pid in members, f"replay clobbered concurrent join {pid}"


# ---------------------------------------------------------------------------
# ISSUE 18 concurrency (d): the wait-witness sweep — every remaining
# Event.wait/Condition.wait site rides witness_wait_check, and each gets
# a lint negative pinning the held-lock trip
# ---------------------------------------------------------------------------

def _assert_wait_trips(monkeypatch, fn):
    """`fn` must pass with no lock held and trip the witness (wait_trips,
    not violations) under a deliberately held ranked lock."""
    from tidb_tpu.lint import concur

    monkeypatch.setitem(concur.LOCK_RANKS, "tests.concur:WS", 5)
    mu = make_lock("tests.concur:WS")
    fn()  # unheld: a normal bounded wait
    s0 = witness_stats()
    with mu:
        with pytest.raises(LockOrderError, match="held-lock wait"):
            fn()
    s1 = witness_stats()
    assert s1["wait_trips"] == s0["wait_trips"] + 1
    assert s1["violations"] == s0["violations"]
    reset_witness_stats()


def test_worker_plane_heartbeat_wait_covered(monkeypatch):
    """WorkerPlane._heartbeat's lease park (coord/plane.py)."""
    from tidb_tpu.coord.plane import WorkerPlane

    wp = WorkerPlane("127.0.0.1:1", 99, heartbeat_s=0.001)
    _assert_wait_trips(monkeypatch, wp._hb_wait)


def test_worker_plane_span_flusher_wait_covered(monkeypatch):
    """WorkerPlane._span_flusher's age-flush park (coord/plane.py)."""
    from tidb_tpu.coord.plane import WorkerPlane

    wp = WorkerPlane("127.0.0.1:1", 99, heartbeat_s=0.001)
    wp._span_flush_s = 0.001
    _assert_wait_trips(monkeypatch, wp._flusher_wait)


def test_maintenance_idle_wait_covered(monkeypatch):
    """MaintenanceWorker._loop's interval park (session/maintenance.py).
    A GC/compaction daemon sleeping an INTERVAL with a ranked lock held
    would starve that lock for seconds, not milliseconds."""
    from tidb_tpu.session.maintenance import MaintenanceWorker

    mw = MaintenanceWorker(domain=None, interval_s=0.001)
    _assert_wait_trips(monkeypatch, mw._idle_wait)


def test_batcher_window_wait_covered(monkeypatch):
    """MicroBatcher's leader window park (serving/batcher.py)."""
    from types import SimpleNamespace

    from tidb_tpu.serving.batcher import MicroBatcher

    b = MicroBatcher()
    g = SimpleNamespace(full=threading.Event())
    _assert_wait_trips(monkeypatch, lambda: b._window_wait(g, 0.001))


def test_batcher_member_wait_covered(monkeypatch):
    """MicroBatcher's parked-member poll tick (serving/batcher.py)."""
    from types import SimpleNamespace

    from tidb_tpu.serving.batcher import MicroBatcher

    b = MicroBatcher()
    ev = threading.Event()
    ev.set()  # unheld path returns immediately
    m = SimpleNamespace(event=ev)
    _assert_wait_trips(monkeypatch, lambda: b._member_wait(m))
