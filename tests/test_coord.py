"""Multi-host coordination plane (ISSUE 9): epoch-numbered membership,
cross-host failover, span forwarding, and rolling-restart handoff.

Everything here runs WITHOUT jax.distributed — the control plane is
plain TCP plus the process-local loopback — so the tier-1 CPU suite
exercises the whole plane: protocol tests against a real Coordinator
socket, single-process degenerate loops (LocalPlane, satellite: the
plane works with one process), seeded chaos sweeps over the new
coord/member_lost and coord/handoff sites, server drain/restart handoff
over the wire, and a 2-OS-process failover + rolling-restart acceptance
test whose workers own private CPU meshes while sharing the
coordination plane (tests/coord_worker.py).
"""

import asyncio
import json
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tidb_tpu.coord import (
    CoordEpochMismatch,
    Coordinator,
    WorkerPlane,
    get_plane,
    reset_plane,
)
from tidb_tpu.copr.device_health import DEVICE_HEALTH, DeviceFailure
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import FAILPOINTS, always, failpoint
from tidb_tpu.trace import TRACE_RING, finish_trace, span, start_trace
from tidb_tpu.trace import recorder

Q1 = ("select g, sum(x), count(*), min(x), max(x), avg(x) from t "
      "group by g order by g")
Q6 = "select sum(x) from t where k < 15000 and x < 50"
TOPN = "select k, x from t order by x desc limit 7"
FILTER = "select k from t where x < 2.5"

SWEEP_QUERIES = (Q1, Q6, TOPN, FILTER)


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table t (k bigint, g bigint, x double)")
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(7)
    n = 20_000
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 5, n, dtype=np.int64),
         rng.uniform(0, 100, n)],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, 4, store.base_rows)
    return s


@pytest.fixture(autouse=True)
def _plane_isolation():
    """The plane and device health are process-global: every test starts
    AND ends on the lazy local default with closed breakers."""
    reset_plane()
    DEVICE_HEALTH.reset()
    yield
    reset_plane()
    DEVICE_HEALTH.reset()


def _approx_eq(a, b):
    if isinstance(a, float) or isinstance(b, float):
        return a == pytest.approx(b, rel=1e-9, abs=1e-9)
    return a == b


def _rows_eq(got, want, ctx=""):
    assert len(got) == len(want), (ctx, got, want)
    for ra, rb in zip(sorted(got), sorted(want)):
        assert all(_approx_eq(x, y) for x, y in zip(ra, rb)), (ctx, ra, rb)


def _cpu_rows(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _wait(pred, timeout_s=5.0, tick=0.05):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return pred()


# ---------------------------------------------------------------------------
# protocol: membership, broadcast, lease expiry
# ---------------------------------------------------------------------------

def test_membership_register_report_broadcast():
    """Two workers join over real sockets; an unhealthy-device report on
    one host bumps the epoch and every member converges on the same
    shrunken broadcast."""
    c = Coordinator(lease_s=30.0, expect=2)
    c.start()
    w1 = w2 = None
    try:
        w1 = WorkerPlane(("127.0.0.1", c.port), pid=1, lease_s=30.0,
                         heartbeat_s=0.2).start([0, 1, 2, 3])
        w2 = WorkerPlane(("127.0.0.1", c.port), pid=2, lease_s=30.0,
                         heartbeat_s=0.2).start([4, 5, 6, 7])
        v = c.view()
        assert set(v.members) == {1, 2} and v.formed
        assert v.members[1] == (0, 1, 2, 3)
        # breaker trip on host 2 (the DeviceHealthRegistry hook shape)
        w2.on_health_change((5,), "trip")
        v2 = c.view()
        assert v2.epoch == v.epoch + 1
        assert v2.members[2] == (4, 6, 7)
        assert v2.device_ids() == frozenset({0, 1, 2, 3, 4, 6, 7})
        # the OTHER worker's cached view converges via its heartbeat
        assert _wait(lambda: w1.current_epoch() == v2.epoch)
        assert w1.view().members[2] == (4, 6, 7)
        # recovery regrows the set and renumbers again
        w2.on_health_change((), "recover")
        v3 = c.view()
        assert v3.epoch == v2.epoch + 1
        assert v3.members[2] == (4, 5, 6, 7)
    finally:
        for w in (w1, w2):
            if w is not None:
                w.stop()
        c.stop()


def test_member_lease_expiry_bumps_epoch():
    """A worker that stops heartbeating (SIGKILL stand-in) is expired by
    the coordinator within ~one lease, the epoch bumps, and the
    survivor observes the new broadcast; formation stays latched so the
    survivor view remains authoritative."""
    c = Coordinator(lease_s=0.5, expect=2)
    c.start()
    w1 = w2 = None
    try:
        w1 = WorkerPlane(("127.0.0.1", c.port), pid=1,
                         lease_s=0.5).start([0])
        w2 = WorkerPlane(("127.0.0.1", c.port), pid=2,
                         lease_s=0.5).start([1])
        assert c.view().formed
        e0 = c.view().epoch
        x0 = REGISTRY.get("coord_members_expired_total")
        w2.stop()  # heartbeats cease
        assert _wait(lambda: 2 not in c.view().members, 5.0)
        v = c.view()
        assert v.epoch > e0 and v.formed
        assert REGISTRY.get("coord_members_expired_total") == x0 + 1
        assert _wait(lambda: 2 not in w1.view().members, 5.0)
    finally:
        for w in (w1, w2):
            if w is not None:
                w.stop()
        c.stop()


# ---------------------------------------------------------------------------
# single-process degenerate loops (satellite: the tier-1 suite exercises
# the plane with one process, no workers spawned)
# ---------------------------------------------------------------------------

def test_local_plane_epoch_bumps_on_breaker_transitions():
    plane = get_plane()
    assert plane.kind == "local"
    e0 = plane.current_epoch()
    DEVICE_HEALTH.record_error(3, DeviceFailure("chip 3 died",
                                                device_ids=(3,)))
    assert plane.current_epoch() == e0 + 1  # trip renumbers
    import jax

    DEVICE_HEALTH.expire_cooldowns()
    DEVICE_HEALTH.select_devices(list(jax.devices()))  # half-open probe
    assert plane.current_epoch() == e0 + 2
    DEVICE_HEALTH.record_success([3])  # probe closes: recovery
    assert plane.current_epoch() == e0 + 3


def test_local_membership_published_on_mesh_build(sess):
    """The mesh builder publishes its healthy device set to the plane,
    so the degenerate single-process membership broadcast is truthful."""
    sess.execute("set tidb_use_tpu = 1")
    sess.query(Q6)
    view = get_plane().view()
    assert set(view.members) == {0}
    assert len(view.device_ids()) == 8  # the 8-virtual-device harness
    assert view.formed


def test_local_handoff_replay_loop():
    """Single-process handoff loop: collect -> park -> take -> replay."""
    from tidb_tpu.lifecycle import (
        collect_session_states,
        replay_session_states,
    )

    d = Domain()
    try:
        s = d.new_session()
        s.execute("set tidb_slow_log_threshold = 777")
        s.execute("prepare px from 'select 6 * 7'")
        states = collect_session_states(d)
        assert len(states) == 1 and states[0]["prepared"]
        json.dumps(states)  # strictly JSON-portable
        plane = get_plane()
        plane.handoff_put(states)
        d2 = Domain()
        try:
            n = replay_session_states(d2, plane.take_handoff())
            assert n == 1
            sess2 = next(s2 for s2 in d2.sessions.values()
                         if getattr(s2, "handoff_origin", None) is not None)
            assert sess2.query("execute px") == [(42,)]
            assert sess2.vars.get_int("tidb_slow_log_threshold") == 777
            assert plane.take_handoff() == []  # consumed exactly once
        finally:
            d2.maintenance.stop()
    finally:
        d.maintenance.stop()


# ---------------------------------------------------------------------------
# dispatch-seam chaos: coord/member_lost
# ---------------------------------------------------------------------------

def test_chaos_member_lost_mid_query_rebuilds_with_parity(sess):
    """Seeded sweep over the new coord/member_lost site: a membership
    epoch bump lands exactly between mesh build and dispatch for every
    query shape — the typed CoordEpochMismatch retries on the rebuilt
    mesh with CPU parity, trips no breakers, leaks nothing."""
    plane = get_plane()
    for sql in SWEEP_QUERIES:
        want = _cpu_rows(sess, sql)
        fired = {"n": 0}

        def bump_once(**_ctx):
            if fired["n"] == 0:
                plane.bump("chaos: member lost")
            fired["n"] += 1

        m0 = REGISTRY.get("coord_epoch_mismatch_total")
        with failpoint("coord/member_lost", bump_once):
            sess.execute("set tidb_use_tpu = 1")
            got = sess.query(sql)
        _rows_eq(got, want, sql)
        assert fired["n"] >= 2, (sql, fired)  # the retry re-hit the seam
        assert REGISTRY.get("coord_epoch_mismatch_total") == m0 + 1, sql
        assert DEVICE_HEALTH.tripped_ids() == ()  # never a chip fault
    assert FAILPOINTS.armed() == []
    alive = [t.name for t in threading.enumerate()
             if t.name.startswith("tidb-tpu-select")]
    assert not alive, alive


def test_epoch_flapping_exhausts_retries_and_steps_down(sess):
    """A plane whose epoch moves on EVERY dispatch exhausts the mesh
    retry budget: the typed error surfaces to distsql, which steps down
    to the per-region rung — still correct, never a hang."""
    plane = get_plane()
    want = _cpu_rows(sess, Q6)
    c0 = REGISTRY.get("cop_tasks_total")
    e0 = REGISTRY.get("mesh_scan_errors_total")
    with failpoint("coord/member_lost",
                   lambda **_c: plane.bump("chaos: flapping")):
        sess.execute("set tidb_use_tpu = 1")
        got = sess.query(Q6)
    _rows_eq(got, want)
    assert REGISTRY.get("mesh_scan_errors_total") > e0
    assert REGISTRY.get("cop_tasks_total") > c0


def test_epoch_mismatch_error_is_typed_and_retriable(sess):
    """The raw dispatcher raises CoordEpochMismatch (not a hang, not a
    device fault) when the epoch moved under it."""
    from tidb_tpu.copr import parallel as pl
    from tidb_tpu.copr.device_health import classify_failure

    exc = CoordEpochMismatch(3, 4)
    assert classify_failure(exc) is None  # never trips breakers
    plane = get_plane()
    sess.execute("set tidb_use_tpu = 1")
    sess.query(Q6)  # mesh built + stamped
    stamped = pl.mesh_epoch()
    assert stamped == plane.current_epoch()
    plane.bump("out-of-band member change")
    with failpoint("coord/member_lost", lambda **_c: None):
        with pytest.raises(CoordEpochMismatch):
            pl._check_membership_epoch()


# ---------------------------------------------------------------------------
# span forwarding: one tree spanning hosts
# ---------------------------------------------------------------------------

def test_span_forwarding_grafts_one_tree():
    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        # the coordinator-side trace exists first (hook must not fire
        # for it: the worker plane installs the hook on start)
        tr_local, tok = start_trace("select 1", 1)
        finish_trace(tr_local, tok)
        w = WorkerPlane(("127.0.0.1", c.port), pid=7,
                        lease_s=30.0).start([0])
        assert recorder.TRACE_EXPORT_HOOK is not None
        tr_w, tok_w = start_trace("select 1", 9)
        with span("copr.device.execute"):
            pass
        tr_w.qid = tr_local.qid  # the SPMD statement-seq correlation
        f0 = REGISTRY.get("coord_spans_forwarded_total")
        g0 = REGISTRY.get("coord_spans_grafted_total")
        b0 = REGISTRY.get("coord_span_batches_total")
        finish_trace(tr_w, tok_w)
        # forwarding is batched + backgrounded (ISSUE 11): finish_trace
        # only enqueues; an explicit flush stands in for the age trigger
        w.flush_spans()
        assert REGISTRY.get("coord_spans_forwarded_total") == f0 + 1
        assert REGISTRY.get("coord_spans_grafted_total") == g0 + 1
        assert REGISTRY.get("coord_span_batches_total") == b0 + 1
        # ONE tree: the worker's root hangs under the coordinator's,
        # host-tagged, with its spans intact and renderable
        remote = [s for s in tr_local.root.children
                  if (s.attrs or {}).get("host") == 7]
        assert len(remote) == 1
        assert any(ch.name == "copr.device.execute"
                   for ch in remote[0].children)
        rendered = "\n".join(r[0] for r in tr_local.rows())
        assert "host: 7" in rendered and "copr.device.execute" in rendered
    finally:
        if w is not None:
            w.stop()
        c.stop()


def test_span_forwarding_respects_byte_cap(monkeypatch):
    monkeypatch.setenv("TIDB_TPU_COORD_SPAN_CAP", "64")
    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        w = WorkerPlane(("127.0.0.1", c.port), pid=3,
                        lease_s=30.0).start([0])
        d0 = REGISTRY.get("coord_spans_dropped_total")
        f0 = REGISTRY.get("coord_spans_forwarded_total")
        tr, tok = start_trace("select 'oversized payload'", 3)
        finish_trace(tr, tok)
        # the cap drop happens at ENQUEUE time (before any batching)
        w.flush_spans()
        assert REGISTRY.get("coord_spans_dropped_total") == d0 + 1
        assert REGISTRY.get("coord_spans_forwarded_total") == f0
    finally:
        if w is not None:
            w.stop()
        c.stop()


def test_span_forwarding_batches_and_drains(monkeypatch):
    """Coord follow-up (c): finish_trace enqueues; the bounded queue
    flushes by SIZE (batch threshold) or on drain — one RPC carries the
    whole batch, and a full queue drops with the counter instead of
    blocking the statement path."""
    monkeypatch.setenv("TIDB_TPU_COORD_SPAN_BATCH", "4")
    monkeypatch.setenv("TIDB_TPU_COORD_SPAN_QUEUE", "6")
    monkeypatch.setenv("TIDB_TPU_COORD_SPAN_FLUSH_S", "30")  # age off
    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        w = WorkerPlane(("127.0.0.1", c.port), pid=11,
                        lease_s=30.0).start([0])
        f0 = REGISTRY.get("coord_spans_forwarded_total")
        b0 = REGISTRY.get("coord_span_batches_total")
        i0 = REGISTRY.get("coord_spans_ingested_total")
        for _ in range(4):  # hits the size threshold -> one batch RPC
            tr, tok = start_trace("select 1", 11)
            finish_trace(tr, tok)
        deadline = time.monotonic() + 5.0
        while (REGISTRY.get("coord_spans_forwarded_total") < f0 + 4
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert REGISTRY.get("coord_spans_forwarded_total") == f0 + 4
        assert REGISTRY.get("coord_span_batches_total") == b0 + 1
        assert REGISTRY.get("coord_spans_ingested_total") == i0 + 4
        # below the threshold nothing flushes until drain
        tr, tok = start_trace("select 2", 11)
        finish_trace(tr, tok)
        assert REGISTRY.get("coord_spans_forwarded_total") == f0 + 4
        w.stop()  # drain flushes the remainder
        assert REGISTRY.get("coord_spans_forwarded_total") == f0 + 5
        w = None
        # queue bound: with no flusher (stopped), overflow drops
        w2 = WorkerPlane(("127.0.0.1", c.port), pid=12, lease_s=30.0)
        w2._span_queue_max = 2
        d0 = REGISTRY.get("coord_spans_dropped_total")
        for _ in range(4):
            tr, tok = start_trace("select 3", 12)
            finish_trace(tr, tok)  # hook is cleared: no forwarding
            w2.forward_trace(tr)
        assert REGISTRY.get("coord_spans_dropped_total") == d0 + 2
    finally:
        if w is not None:
            w.stop()
        c.stop()


def test_idle_worker_metrics_ride_heartbeat(monkeypatch):
    """ISSUE 16 satellite (d): a worker that finishes ZERO traces never
    sends a span batch, so its metric snapshot must piggyback on the
    heartbeat poll — an idle worker still appears in the coordinator's
    fleet view after one heartbeat interval."""
    monkeypatch.setenv("TIDB_TPU_COORD_METRICS_S", "0")  # every beat
    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        w = WorkerPlane(("127.0.0.1", c.port), pid=33, lease_s=30.0,
                        heartbeat_s=0.05).start([0])
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if 33 in c.fleet_snapshot(refresh=False):
                break
            time.sleep(0.02)
        snaps = c.fleet_snapshot(refresh=False)
        assert 33 in snaps, "idle worker missing from fleet view"
        assert "counters" in snaps[33]
    finally:
        if w is not None:
            w.stop(leave=True)
        c.stop()


def test_metrics_piggyback_on_span_batches(monkeypatch):
    """Fleet aggregation (ISSUE 13): workers piggyback registry
    snapshots on the span batches they already send; the coordinator
    stores the latest per pid and merges counters/histograms/gauges."""
    monkeypatch.setenv("TIDB_TPU_COORD_METRICS_S", "0")  # every batch
    from tidb_tpu.metrics import merge_fleet

    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        w = WorkerPlane(("127.0.0.1", c.port), pid=21,
                        lease_s=30.0).start([0])
        REGISTRY.inc("statements_total")
        REGISTRY.observe_hist("stmt_latency_point_ms", 3.0)
        m0 = REGISTRY.get("coord_metrics_snapshots_total")
        tr, tok = start_trace("select 1", 21)
        finish_trace(tr, tok)
        w.flush_spans()
        assert REGISTRY.get("coord_metrics_snapshots_total") == m0 + 1
        snaps = c.fleet_snapshot()
        assert 21 in snaps
        assert snaps[21]["counters"].get("statements_total", 0) >= 1
        merged = merge_fleet(snaps)
        assert merged["counters"]["statements_total"] >= 1
        assert merged["hists"]["stmt_latency_point_ms"]["count"] >= 1
        # gauges stay per-host, never summed
        assert "21" in merged["gauges"].get("coord_epoch", {})
        # a graceful leave prunes the snapshot — a departed host must
        # not inflate fleet totals forever (it has no lease to expire)
        w.stop(leave=True)
        w = None
        assert 21 not in c.fleet_snapshot()
    finally:
        if w is not None:
            w.stop(leave=True)
        c.stop()


def test_localplane_fleet_merge_degenerate_loop():
    """LocalPlane degenerates to a single-member fleet, so the whole
    merge path (counter sums, bucket-wise histogram merge, per-host
    gauges) runs in tier-1 without spawning workers."""
    from tidb_tpu.coord.plane import LocalPlane
    from tidb_tpu.metrics import merge_fleet

    REGISTRY.inc("statements_total")
    plane = LocalPlane()
    snaps = plane.fleet_metrics()
    assert list(snaps) == [0]
    payload = snaps[0]
    assert payload["counters"].get("statements_total", 0) >= 1
    merged = merge_fleet(snaps)
    assert merged["hosts"] == ["0"]
    # merging the same payload twice doubles every counter exactly
    doubled = merge_fleet({0: payload, 1: payload})
    assert doubled["hosts"] == ["0", "1"]
    for name, v in payload["counters"].items():
        if name.endswith("_total"):
            assert doubled["counters"][name] == pytest.approx(2 * v)
    for name, h in merged["hists"].items():
        assert doubled["hists"][name]["count"] == 2 * h["count"]


def test_import_does_not_consume_trace_seq():
    """Ingesting a forwarded trace must not advance the local statement
    sequence: SPMD qid correlation relies on every process assigning the
    same seq to the same statement, so a coordinator that consumed seqs
    on ingest would stop grafting after the first forwarded trace."""
    from tidb_tpu.trace import import_trace, trace_payload

    tr, tok = start_trace("select 1", 1)
    finish_trace(tr, tok)
    imported = import_trace(trace_payload(tr), host=5)
    assert imported.seq == -1 and imported.imported_from == 5
    tr2, tok2 = start_trace("select 1", 1)
    finish_trace(tr2, tok2)
    assert tr2.seq == tr.seq + 1  # the import consumed nothing


def test_coordinator_plane_take_handoff_reads_live_store():
    """A server drain ON the coordinator host parks straight into the
    live store; the restarted server's take_handoff must see it (not
    just the registration-time snapshot)."""
    from tidb_tpu.coord import activate_coordinator

    plane = activate_coordinator(port=0, pid=0, devices=[0])
    plane.handoff_put([{"conn_id": 1, "prepared": {"p": "select 1"}}])
    out = plane.take_handoff()
    assert out and out[0]["prepared"] == {"p": "select 1"}
    assert plane.take_handoff() == []  # consumed exactly once


def test_coordinator_kill_restart_replays_state(tmp_path):
    """Persist-backed coordinator (ISSUE 12 / ROADMAP coord (b)): a
    coordinator killed mid-epoch REPLAYS membership + parked handoff
    from the persist layer on restart — the epoch resumes strictly
    above anything ever broadcast, and a parked session survives the
    kill to ride back to its re-registering worker."""
    path = str(tmp_path / "coord_state.json")
    c = Coordinator(lease_s=30.0, expect=2, state_path=path)
    c.start()
    w1 = w2 = None
    try:
        w1 = WorkerPlane(("127.0.0.1", c.port), pid=1, lease_s=30.0,
                         heartbeat_s=5.0).start([0, 1])
        w2 = WorkerPlane(("127.0.0.1", c.port), pid=2, lease_s=30.0,
                         heartbeat_s=5.0).start([2, 3])
        e0 = c.view().epoch
        c.put_handoff(1, [{"conn_id": 7, "prepared": {"p": "select 1"}}])
    finally:
        c.stop()  # SIGKILL stand-in: no leave protocol ever runs
        for w in (w1, w2):
            if w is not None:
                w.stop()  # leave=False: the state file keeps both pids

    r0 = REGISTRY.snapshot().get("coord_state_replayed_total", 0)
    c2 = Coordinator(lease_s=30.0, expect=2, state_path=path)
    c2.start()
    try:
        assert REGISTRY.snapshot().get(
            "coord_state_replayed_total", 0) > r0
        v = c2.view()
        # the restart renumbers ONCE above the replayed epoch: surviving
        # workers' stamped meshes are strictly behind, never ambiguous
        assert v.epoch > e0
        assert set(v.members) == {1, 2} and v.formed
        assert v.members[1] == (0, 1) and v.members[2] == (2, 3)
        # the parked session rides back on re-registration, exactly once
        out = c2.register(1, [0, 1])
        assert out["handoff"] and out["handoff"][0]["conn_id"] == 7
        assert c2.register(1, [0, 1])["handoff"] == []
    finally:
        c2.stop()

    # a third restart still replays (the handoff pop persisted durably)
    c3 = Coordinator(lease_s=30.0, expect=2, state_path=path)
    try:
        assert c3.pop_handoff(1) == []
        assert c3.view().epoch > v.epoch
    finally:
        c3.stop()


def test_coordinator_state_survives_torn_write(tmp_path):
    """A torn/corrupt state file loads as a fresh start, never a crash
    (the table persister's crash contract)."""
    path = str(tmp_path / "coord_state.json")
    with open(path, "w") as f:
        f.write('{"epoch": 5, "members": {')  # torn mid-document
    c = Coordinator(lease_s=30.0, state_path=path)
    try:
        assert c.view().epoch == 0  # fresh start, no replay
    finally:
        c.stop()


def test_forwarding_survives_dead_coordinator():
    """A dead coordinator costs a counted RPC error, never a query
    failure."""
    c = Coordinator(lease_s=30.0)
    c.start()
    w = None
    try:
        w = WorkerPlane(("127.0.0.1", c.port), pid=4, lease_s=30.0,
                        rpc_timeout_s=0.5).start([0])
        c.stop()
        r0 = REGISTRY.get("coord_rpc_errors_total")
        tr, tok = start_trace("select 1", 4)
        finish_trace(tr, tok)  # must not raise (enqueue only)
        w.flush_spans()        # the flusher's RPC hits the dead socket
        assert REGISTRY.get("coord_rpc_errors_total") > r0
    finally:
        if w is not None:
            w.stop()
        c.stop()


# ---------------------------------------------------------------------------
# server drain handoff (rolling restart in one process) + chaos
# ---------------------------------------------------------------------------

def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_server_drain_hands_off_and_restart_replays():
    """Rolling restart over the wire: server A drains with a prepared
    session; server B (fresh domain, same process — the LocalPlane
    loop) starts and replays it, losing no prepared sessions."""
    from tidb_tpu.server.server import MySQLServer
    from test_lifecycle import WireClient

    async def body():
        dom_a = Domain()
        srv_a = MySQLServer(dom_a, port=0)
        await srv_a.start()
        cl = WireClient(srv_a.host, srv_a.port)
        await cl.connect()
        await cl.query("prepare ps1 from 'select 21 * 2'")
        await cl.query("set tidb_slow_log_threshold = 4321")
        p0 = REGISTRY.get("coord_handoff_put_total")
        r0 = REGISTRY.get("coord_handoff_replayed_total")
        await srv_a.shutdown(drain_s=2.0)
        dom_a.maintenance.stop()
        assert REGISTRY.get("coord_handoff_put_total") == p0 + 1
        dom_b = Domain()
        srv_b = MySQLServer(dom_b, port=0)
        await srv_b.start()
        try:
            assert REGISTRY.get("coord_handoff_replayed_total") == r0 + 1
            replayed = [s for s in dom_b.sessions.values()
                        if getattr(s, "handoff_origin", None) is not None]
            assert len(replayed) == 1
            assert replayed[0].query("execute ps1") == [(42,)]
            assert replayed[0].vars.get_int(
                "tidb_slow_log_threshold") == 4321
        finally:
            await srv_b.stop()
            dom_b.maintenance.stop()

    _run(body())


def test_chaos_handoff_site_fails_safe():
    """The coord/handoff chaos site: a handoff lost mid-drain (raised
    action) is counted and the drain still completes; the replacement
    starts empty instead of crashing."""
    from tidb_tpu.server.server import MySQLServer
    from test_lifecycle import WireClient

    async def body():
        dom_a = Domain()
        srv_a = MySQLServer(dom_a, port=0)
        await srv_a.start()
        cl = WireClient(srv_a.host, srv_a.port)
        await cl.connect()
        await cl.query("prepare ps1 from 'select 1'")
        f0 = REGISTRY.get("coord_handoff_failed_total")
        with failpoint("coord/handoff",
                       always(RuntimeError("injected: handoff lost"))):
            await srv_a.shutdown(drain_s=1.0)
        dom_a.maintenance.stop()
        assert REGISTRY.get("coord_handoff_failed_total") == f0 + 1
        assert get_plane().take_handoff() == []
        dom_b = Domain()
        srv_b = MySQLServer(dom_b, port=0)
        await srv_b.start()
        try:
            assert not any(
                getattr(s, "handoff_origin", None) is not None
                for s in dom_b.sessions.values())
        finally:
            await srv_b.stop()
            dom_b.maintenance.stop()

    _run(body())


def test_status_endpoint_reports_coord_section():
    from tidb_tpu.server.http_status import StatusServer

    d = Domain()
    ss = StatusServer(d, port=0)
    host, port = ss.start()
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/status", timeout=5).read())
        coord = body["coord"]
        assert coord["kind"] == "local"
        assert coord["epoch"] >= 1
        assert "coord_epoch_bumps_total" in coord["metrics"]
        assert "coord_handoff_replayed_total" in coord["metrics"]
    finally:
        ss.stop()
        d.maintenance.stop()


# ---------------------------------------------------------------------------
# acceptance: 2 worker processes, kill mid-query, rolling restart
# ---------------------------------------------------------------------------

def _spawn_worker(pid, port):
    import os

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["COORD_LEASE_S"] = "1.5"
    env["COORD_WORKER_MAX_S"] = "150"
    worker = os.path.join(os.path.dirname(__file__), "coord_worker.py")
    p = subprocess.Popen(
        [sys.executable, worker, str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, bufsize=1)
    lines = []

    def pump():
        for line in p.stdout:
            lines.append(line.strip())

    threading.Thread(target=pump, daemon=True).start()
    return p, lines


def _wait_line(lines, pred, timeout_s, procs=()):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if any(pred(ln) for ln in list(lines)):
            return True
        if procs and all(p.poll() is not None for p in procs):
            break
        time.sleep(0.1)
    return any(pred(ln) for ln in list(lines))


def test_two_process_failover_and_rolling_restart():
    """Acceptance (ISSUE 9): 2 worker processes under load sharing the
    coordination plane.  SIGKILL one mid-query -> lease expiry bumps the
    epoch and the survivor keeps answering with parity on its rebuilt
    mesh at the new epoch (no hang, every round ok=1 mesh=1).  Restart
    the victim -> it rejoins at a newer epoch with its prepared session
    replayed from the eager handoff checkpoint.  Span trees from both
    hosts landed in the coordinator-side ring."""
    threads_before = {t.name for t in threading.enumerate()}
    c = Coordinator(lease_s=1.5, expect=2)
    c.start()
    procs = []
    try:
        w0, l0 = _spawn_worker(0, c.port)
        procs.append(w0)
        w1, l1 = _spawn_worker(1, c.port)
        procs.append(w1)
        assert _wait_line(l0, lambda s: s.startswith("READY"), 90,
                          (w0,)), (l0[-10:], l1[-10:])
        assert _wait_line(l1, lambda s: s.startswith("READY"), 90,
                          (w1,)), (l0[-10:], l1[-10:])
        v = c.view()
        assert set(v.members) == {0, 1} and v.formed
        # both under load on their meshes
        ok_round = lambda s: (s.startswith("ROUND") and "ok=1" in s
                              and "mesh=1" in s)  # noqa: E731
        assert _wait_line(l0, ok_round, 30, (w0,)), l0[-5:]
        assert _wait_line(l1, ok_round, 30, (w1,)), l1[-5:]

        # ---- hard kill mid-query ------------------------------------
        e_before = c.view().epoch
        w1.kill()
        assert _wait(lambda: 1 not in c.view().members, 15.0), \
            "lease expiry did not evict the killed worker"
        v_after = c.view()
        assert v_after.epoch > e_before and v_after.formed
        # the survivor observes the bumped epoch and keeps serving with
        # parity — a completed query at the new epoch, not a hang
        assert _wait_line(
            l0, lambda s: ok_round(s) and f"epoch={v_after.epoch}" in s,
            30, (w0,)), l0[-5:]
        assert not any("ok=0" in s for s in list(l0)), \
            [s for s in l0 if "ok=0" in s]

        # ---- rolling restart of the victim --------------------------
        w1b, l1b = _spawn_worker(1, c.port)
        procs.append(w1b)
        assert _wait_line(l1b, lambda s: s.startswith("HANDOFF_REPLAYED"),
                          90, (w1b,)), l1b[-10:]
        line = next(s for s in list(l1b)
                    if s.startswith("HANDOFF_REPLAYED"))
        assert "n=1" in line and "rows=8192" in line \
            and "sysvar=4321" in line, line
        assert _wait(lambda: 1 in c.view().members, 10.0)
        assert c.view().epoch > v_after.epoch  # rejoined at a NEW epoch

        # ---- cross-host spans rejoined the coordinator's ring -------
        assert any(getattr(tr, "imported_from", None) in (0, 1)
                   for tr in list(TRACE_RING))

        # ---- fleet metric snapshots piggybacked on span batches -----
        # (ISSUE 13): both live workers' registries reach the
        # coordinator and merge — counters summed, histograms
        # bucket-merged across REAL OS processes
        assert _wait(lambda: {0, 1} <= set(c.fleet_snapshot()), 15.0), \
            c.fleet_snapshot().keys()
        from tidb_tpu.metrics import merge_fleet

        fleet = c.fleet_snapshot()
        for pid in (0, 1):
            assert fleet[pid]["counters"].get(
                "statements_total", 0) > 0, pid
        merged = merge_fleet(fleet)
        assert merged["counters"]["statements_total"] >= sum(
            fleet[p]["counters"]["statements_total"] for p in (0, 1))
        assert any(n.startswith("stmt_latency_")
                   for n in merged["hists"]), merged["hists"].keys()

        # ---- graceful drains ----------------------------------------
        w0.send_signal(signal.SIGTERM)
        assert _wait_line(l0, lambda s: s.startswith("DRAINED"), 30, (w0,))
        w1b.send_signal(signal.SIGTERM)
        assert _wait_line(l1b, lambda s: s.startswith("DRAINED"), 30,
                          (w1b,))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        c.stop()
    # no leaked coordinator threads or armed failpoints in this process
    time.sleep(0.3)
    leaked = {t.name for t in threading.enumerate()} - threads_before
    leaked = {n for n in leaked if n.startswith("tidb-tpu-coord")}
    assert not leaked, leaked
    assert FAILPOINTS.armed() == []
