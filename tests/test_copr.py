"""Coprocessor engine tests: CPU oracle vs JAX device engine result parity.

This is the framework's north-star test pattern (SURVEY.md §4 carry-over):
the same DAG runs on both engines and must produce identical result sets.
"""

import numpy as np
import pytest

from tidb_tpu.chunk import concat_chunks
from tidb_tpu.copr.aggstate import merge_partials_to_final
from tidb_tpu.copr.ir import (
    DAG,
    AggregationIR,
    LimitIR,
    ProjectionIR,
    SelectionIR,
    TableScanIR,
    TopNIR,
)
from tidb_tpu.expr import ColumnExpr, Constant, ScalarFunc
from tidb_tpu.expr.aggregation import AggDesc
from tidb_tpu.expr.builtins import infer_ftype
from tidb_tpu.store import BlockStorage, CopRequest, KeyRange
from tidb_tpu.types import (
    parse_date,
    ty_date,
    ty_decimal,
    ty_float,
    ty_int,
    ty_string,
)

N = 5000


@pytest.fixture(scope="module")
def storage():
    st = BlockStorage()
    t = st.create_table(
        1,
        [
            ("k", ty_int(False)),
            ("qty", ty_decimal(15, 2)),
            ("price", ty_decimal(15, 2)),
            ("disc", ty_float()),
            ("ship", ty_date()),
            ("flag", ty_string()),
        ],
    )
    rng = np.random.default_rng(7)
    k = np.arange(N, dtype=np.int64)
    qty = rng.integers(100, 5000, N)  # 1.00 .. 50.00
    price = rng.integers(10000, 100000, N)
    disc = np.round(rng.random(N) * 0.1, 2)
    ship = parse_date("1994-01-01") + rng.integers(0, 2000, N).astype(np.int32)
    flag = np.array([["A", "N", "R"][i] for i in rng.integers(0, 3, N)], dtype=object)
    # sprinkle NULLs in disc
    disc_valid = rng.random(N) > 0.05
    t.bulk_load_arrays([k, qty, price, disc, ship, flag],
                       [None, None, None, disc_valid, None, None], ts=0)
    st.regions.split_even(1, 3, N)
    return st


def scan_ir():
    return TableScanIR(
        1, [0, 1, 2, 3, 4, 5],
        [ty_int(False), ty_decimal(15, 2), ty_decimal(15, 2), ty_float(),
         ty_date(), ty_string()],
    )


def col(i, ft):
    return ColumnExpr(i, ft)


def fn(name, *args, meta=None):
    meta = meta or {}
    ft = infer_ftype(name, [a.ftype for a in args], meta)
    return ScalarFunc(name, list(args), ft, meta)


def run_both(storage, dag: DAG, n_keys=None, aggs=None):
    """Run via the pushdown boundary on both engines; return row sets."""
    results = {}
    for engine in ("cpu", "tpu"):
        req = CopRequest(
            dag=dag.to_dict(), ranges=[KeyRange(1, 0, 1 << 62)],
            ts=storage.current_ts(), engine=engine,
        )
        chunks = []
        for resp in storage.get_client().send(req):
            chunks.extend(resp.chunks)
        if aggs is not None:
            final = merge_partials_to_final(n_keys, aggs, chunks)
            rows = final.to_pylist() if final is not None else []
        else:
            whole = concat_chunks(chunks)
            # root-side merge of per-region partial TopN/Limit results
            tail = dag.executors[-1]
            if whole is not None and isinstance(tail, TopNIR):
                from tidb_tpu.copr.cpu_engine import run_topn

                whole = run_topn(tail.order_by, tail.limit, whole)
            elif whole is not None and isinstance(tail, LimitIR):
                whole = whole.slice(0, min(tail.limit, whole.num_rows))
            rows = whole.to_pylist() if whole else []
        results[engine] = rows
    return results["cpu"], results["tpu"]


def test_filter_parity(storage):
    # WHERE qty < 24.00 AND disc BETWEEN 0.05 AND 0.07  (Q6 shape)
    conds = [
        fn("<", col(1, ty_decimal(15, 2)), Constant(2400, ty_decimal(15, 2))),
        fn(">=", col(3, ty_float()), Constant(0.05, ty_float())),
        fn("<=", col(3, ty_float()), Constant(0.07, ty_float())),
    ]
    dag = DAG([scan_ir(), SelectionIR(conds)])
    cpu, tpu = run_both(storage, dag)
    assert len(cpu) > 0
    assert sorted(cpu) == sorted(tpu)


def test_filter_on_dict_string(storage):
    conds = [fn("=", col(5, ty_string()), Constant("R", ty_string()))]
    dag = DAG([scan_ir(), SelectionIR(conds)])
    cpu, tpu = run_both(storage, dag)
    assert len(cpu) > 0 and sorted(cpu) == sorted(tpu)
    # range predicate over sorted dictionary
    conds2 = [fn(">=", col(5, ty_string()), Constant("N", ty_string()))]
    dag2 = DAG([scan_ir(), SelectionIR(conds2)])
    cpu2, tpu2 = run_both(storage, dag2)
    assert sorted(cpu2) == sorted(tpu2)
    assert all(r[5] in ("N", "R") for r in cpu2)


def test_projection_parity(storage):
    # SELECT price * (1 - disc) ... the Q1 revenue expression
    one = Constant(1.0, ty_float())
    rev = fn("*", col(2, ty_decimal(15, 2)), fn("-", one, col(3, ty_float())))
    dag = DAG([scan_ir(),
               SelectionIR([fn("<", col(0, ty_int(False)), Constant(1000, ty_int()))]),
               ProjectionIR([col(0, ty_int(False)), rev])])
    cpu, tpu = run_both(storage, dag)
    assert len(cpu) == 1000
    for (ka, va), (kb, vb) in zip(sorted(cpu), sorted(tpu)):
        assert ka == kb
        if va is None:
            assert vb is None
        else:
            assert va == pytest.approx(vb, rel=1e-12)


def test_scalar_agg_parity(storage):
    aggs = [
        AggDesc("count", []),
        AggDesc("sum", [col(2, ty_decimal(15, 2))]),
        AggDesc("avg", [col(1, ty_decimal(15, 2))]),
        AggDesc("min", [col(4, ty_date())]),
        AggDesc("max", [col(4, ty_date())]),
        AggDesc("sum", [col(3, ty_float())]),
    ]
    dag = DAG([scan_ir(), AggregationIR([], aggs, mode="partial")])
    cpu, tpu = run_both(storage, dag, n_keys=0, aggs=aggs)
    assert len(cpu) == 1 and len(tpu) == 1
    for a, b in zip(cpu[0], tpu[0]):
        if isinstance(a, float):
            assert a == pytest.approx(b, rel=1e-9)
        else:
            assert a == b


def test_group_agg_parity(storage):
    # GROUP BY flag (dict string) — Q1 shape
    aggs = [
        AggDesc("count", []),
        AggDesc("sum", [col(1, ty_decimal(15, 2))]),
        AggDesc("avg", [col(3, ty_float())]),
        AggDesc("min", [col(2, ty_decimal(15, 2))]),
        AggDesc("max", [col(5, ty_string())]),
        AggDesc("first_row", [col(5, ty_string())]),
    ]
    gb = [col(5, ty_string())]
    dag = DAG([scan_ir(), AggregationIR(gb, aggs, mode="partial")])
    cpu, tpu = run_both(storage, dag, n_keys=1, aggs=aggs)
    assert len(cpu) == 3
    key = lambda r: r[0]
    for a, b in zip(sorted(cpu, key=key), sorted(tpu, key=key)):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert x == pytest.approx(y, rel=1e-9)
            else:
                assert x == y


def test_group_by_int_key_with_filter(storage):
    # GROUP BY year(ship)? — not a bare column; use int key k % small via
    # group on date column year range instead: group by ship (int32 date,
    # card ~2000) with a filter
    aggs = [AggDesc("count", []), AggDesc("sum", [col(2, ty_decimal(15, 2))])]
    gb = [col(4, ty_date())]
    conds = [fn("<", col(0, ty_int(False)), Constant(500, ty_int()))]
    dag = DAG([scan_ir(), SelectionIR(conds),
               AggregationIR(gb, aggs, mode="partial")])
    cpu, tpu = run_both(storage, dag, n_keys=1, aggs=aggs)
    assert sorted(cpu) == sorted(tpu)
    assert sum(r[1] for r in cpu) == 500


def test_topn_parity(storage):
    dag = DAG([
        scan_ir(),
        SelectionIR([fn("=", col(5, ty_string()), Constant("A", ty_string()))]),
        TopNIR([(col(2, ty_decimal(15, 2)), True)], 7),
    ])
    cpu, tpu = run_both(storage, dag)
    assert len(cpu) == 7 and len(tpu) == 7
    # same price ordering (ties may reorder other cols; compare sort keys)
    assert [r[2] for r in cpu] == [r[2] for r in tpu]


def test_limit(storage):
    dag = DAG([scan_ir(), LimitIR(13)])
    cpu, tpu = run_both(storage, dag)
    assert len(cpu) == 13 and len(tpu) == 13


def test_region_error_retry(storage):
    from tidb_tpu.errors import RegionError
    from tidb_tpu.store.fault import failpoint, once

    with failpoint("copr/region_error", once(RegionError("injected"))):
        dag = DAG([scan_ir(), LimitIR(5)])
        req = CopRequest(dag=dag.to_dict(), ranges=[KeyRange(1, 0, 100)],
                         ts=storage.current_ts(), engine="cpu")
        chunks = []
        for resp in storage.get_client().send(req):
            chunks.extend(resp.chunks)
        assert concat_chunks(chunks).num_rows == 5


def test_delta_overlay_included(storage):
    # runs last: mutates the module-scoped fixture's data
    txn = storage.begin()
    t = storage.table(1)
    h = t.alloc_handle()
    txn.put(1, h, (999999, 100, 100, 0.5, parse_date("2001-01-01"), "Z"))
    txn.delete(1, 0)
    txn.commit()
    conds = [fn(">=", col(0, ty_int(False)), Constant(0, ty_int()))]
    dag = DAG([scan_ir(), SelectionIR(conds)])
    cpu, tpu = run_both(storage, dag)
    assert sorted(cpu) == sorted(tpu)
    keys = {r[0] for r in cpu}
    assert 999999 in keys  # delta insert visible
    assert len([r for r in cpu if r[0] == 0]) == 0  # base row 0 deleted
