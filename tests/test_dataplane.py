"""Sharded data plane (ISSUE 18): partition-map determinism, the
degenerate LocalPlane path, cross-"host" exchange between two in-process
members, survivor re-sharding, and the dataplane/reshard chaos site.

The 2-OS-process acceptance (SIGKILL survival) lives in
test_dataplane_procs.py on the coord_worker.py pattern; these tests
exercise the SAME map/ownership/re-shard/exchange code in one process,
where failure injection and counter assertions are cheap.
"""

import threading
import time

import pytest

from tidb_tpu.coord import get_plane
from tidb_tpu.coord.plane import Coordinator, CoordinatorPlane, WorkerPlane
from tidb_tpu.dataplane import (PartitionMapMismatch, activate_dataplane,
                                build_partition_map, deactivate_dataplane,
                                get_dataplane)
from tidb_tpu.dataplane.shard import _pack_column, _unpack_column
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.store.fault import FAILPOINTS, failpoint, once
from tidb_tpu.tpch_data import build_lineitem

Q6 = ("select sum(l_extendedprice * l_discount) from lineitem "
      "where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01' "
      "and l_discount between 0.05 and 0.07 and l_quantity < 24")
Q1 = ("select l_returnflag, l_linestatus, sum(l_quantity), "
      "sum(l_extendedprice), avg(l_discount), count(*) from lineitem "
      "where l_shipdate <= '1998-09-02' group by l_returnflag, "
      "l_linestatus order by l_returnflag, l_linestatus")
GROUPED = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag order by l_returnflag")


def _cnt(name):
    return REGISTRY.get(name) or 0.0


def _oracle(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.execute(sql)[0].rows
    finally:
        sess.execute("set tidb_use_tpu = 1")


class _View:
    def __init__(self, epoch, members):
        self.epoch = epoch
        self.members = {p: () for p in members}
        self.addrs = {}
        self.formed = True


# ---------------------------------------------------------------------------
# partition map (pure)
# ---------------------------------------------------------------------------

def test_partition_map_deterministic_and_epoch_numbered():
    v = _View(7, [0, 1, 2])
    a = build_partition_map(v, 16)
    b = build_partition_map(_View(7, [2, 1, 0]), 16)
    # pure function of the broadcast: member enumeration order is noise
    assert a == b
    assert a.epoch == 7 and a.n_parts == 16
    assert set(a.owners) <= {0, 1, 2}
    # every member owns something at 16 partitions / 3 members (HRW
    # balance is statistical, but 16 draws over 3 buckets never leaves
    # one empty for this fixed hash)
    assert set(a.owners) == {0, 1, 2}


def test_partition_map_minimal_motion_on_member_loss():
    before = build_partition_map(_View(1, [0, 1, 2]), 32)
    after = build_partition_map(_View(2, [0, 2]), 32)
    # rendezvous hashing: ONLY the dead member's partitions move
    for p in range(32):
        if before.owners[p] != 1:
            assert after.owners[p] == before.owners[p]
        else:
            assert after.owners[p] in (0, 2)


def test_partition_map_mismatch_typed_like_coord_epoch_mismatch():
    pmap = build_partition_map(_View(3, [0]), 4)
    pmap.check(3)  # same epoch: fine
    with pytest.raises(PartitionMapMismatch) as ei:
        pmap.check(5)
    assert ei.value.built_at == 3 and ei.value.current == 5
    # retriable-classification hygiene: no device-failure vocabulary
    msg = str(ei.value).lower()
    for word in ("device", "xla", "tpu", "chip"):
        assert word not in msg


def test_pack_roundtrip_all_widths():
    import numpy as np

    for card in (2, 3, 11, 200, 4000):
        rng = np.random.default_rng(card)
        codes = rng.integers(0, card, size=777).astype(np.int32)
        payload, bits = _pack_column(codes, card)
        out = _unpack_column(payload, bits, len(codes))
        assert (out == codes).all()
        if card <= 256:
            assert bits in (1, 2, 4, 8)
            # the point of preferring packed codes for re-replication
            assert payload.nbytes <= codes.nbytes // (8 // bits) + 8
        else:
            assert bits == 0


# ---------------------------------------------------------------------------
# degenerate LocalPlane path (single host owns every partition)
# ---------------------------------------------------------------------------

def test_localplane_dataplane_parity_and_introspection(tmp_path):
    sess = build_lineitem(4096, regions=4)
    storage = sess.domain.storage
    tid = sess.domain.catalog.info_schema().table("test", "lineitem").id
    oracles = {q: _oracle(sess, q) for q in (Q1, Q6, GROUPED)}
    dp = activate_dataplane(storage, plane=get_plane(), pid=0,
                            data_dir=str(tmp_path), serve=False)
    try:
        st = dp.shard_table(tid)
        assert sorted(st.loaded) == list(range(st.n_parts))
        for q in (Q1, Q6, GROUPED):
            before = _cnt("dataplane_queries_total")
            assert sess.execute(q)[0].rows == oracles[q]
            # parity must come FROM the data plane, not a silent bypass
            assert _cnt("dataplane_queries_total") == before + 1
        rows = sess.execute(
            "select table_id, partition_id, row_start, row_end, "
            "owner_pid, local from information_schema."
            "tidb_tpu_partition_map order by partition_id")[0].rows
        assert len(rows) == st.n_parts
        assert all(r[0] == tid and r[4] == 0 and r[5] == 1 for r in rows)
        # contiguous cover of the table
        assert rows[0][2] == 0 and rows[-1][3] == 4096
        for a, b in zip(rows, rows[1:]):
            assert a[3] == b[2]
        snap = dp.snapshot()
        assert snap["tables"][tid]["n_rows"] == 4096
    finally:
        deactivate_dataplane(storage)
    # partitions detach with the plane: no synthetic tables leak
    assert all(t < (1 << 28) for t in storage.table_ids())


def test_dataplane_bypasses_on_dml_delta():
    sess = build_lineitem(2048, regions=4)
    storage = sess.domain.storage
    tid = sess.domain.catalog.info_schema().table("test", "lineitem").id
    dp = activate_dataplane(storage, plane=get_plane(), pid=0, serve=False)
    try:
        dp.shard_table(tid)
        before_q = _cnt("dataplane_queries_total")
        sess.execute(Q6)
        assert _cnt("dataplane_queries_total") == before_q + 1
        # committed DML invalidates the shard snapshot: the plane must
        # step aside (partitions miss the new row) until re-sharded
        sess.execute(
            "insert into lineitem values "
            "(999999, 1.0, 10.0, 0.06, 0.02, 'N', 'O', '1994-06-01')")
        before_b = _cnt("dataplane_bypass_total")
        got = sess.execute(
            "select count(*) from lineitem where l_orderkey = 999999"
        )[0].rows
        assert got == [(1,)]
        assert _cnt("dataplane_bypass_total") > before_b
        assert _cnt("dataplane_queries_total") == before_q + 1
    finally:
        deactivate_dataplane(storage)


# ---------------------------------------------------------------------------
# two in-process members: real exchange, survivor re-shard, chaos site
# ---------------------------------------------------------------------------

def _fleet(tmp_path, rf=None):
    """Coordinator member (pid 0) + worker member (pid 1), each with its
    own Domain holding the SAME deterministic lineitem build — the
    in-process model of two hosts that loaded the same base table."""
    sA = build_lineitem(4096, regions=4)
    sB = build_lineitem(4096, regions=4)
    coord = Coordinator(port=0, lease_s=4.0, expect=2, self_pid=0)
    host, port = coord.start()
    cp = CoordinatorPlane(coord, pid=0).start((0,))
    wp = WorkerPlane(f"{host}:{port}", 1, lease_s=4.0).start((1,))
    _wait(lambda: cp.view().formed and len(cp.view().members) == 2)
    dpA = activate_dataplane(sA.domain.storage, plane=cp, pid=0,
                             data_dir=str(tmp_path), rf=rf)
    dpB = activate_dataplane(sB.domain.storage, plane=wp, pid=1,
                             data_dir=str(tmp_path), rf=rf)
    _wait(lambda: len(cp.view().addrs) == 2 and len(wp.view().addrs) == 2)
    try:
        yield sA, sB, cp, wp, dpA, dpB
    finally:
        deactivate_dataplane(sA.domain.storage)
        deactivate_dataplane(sB.domain.storage)
        try:
            wp.stop(leave=True)
        except Exception:
            pass
        cp.stop()


@pytest.fixture
def two_member_fleet(tmp_path):
    yield from _fleet(tmp_path)


@pytest.fixture
def two_member_fleet_rf1(tmp_path):
    """RF=1 fleet: the PR-18 behavior — no warm replicas, so a member
    loss MUST replay orphaned partitions from the cold tier."""
    yield from _fleet(tmp_path, rf=1)


def _wait(pred, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError("condition not reached in %.1fs" % timeout)


def test_two_member_exchange_parity_and_survivor_reshard(two_member_fleet):
    sA, sB, cp, wp, dpA, dpB = two_member_fleet
    tid = sA.domain.catalog.info_schema().table("test", "lineitem").id
    oracle6 = _oracle(sA, Q6)
    oracle1 = _oracle(sA, Q1)
    stA = dpA.shard_table(tid)
    stB = dpB.shard_table(tid)
    # PRIMARY ownership is a partition (disjoint cover) across the two
    # members; each member materializes every chain slot it holds (at
    # RF=2 over 2 hosts, that is everything — warm replicas, not owners)
    pmap = dpA.sync()
    primA, primB = set(pmap.owned_by(0)), set(pmap.owned_by(1))
    assert primA.isdisjoint(primB)
    assert sorted(primA | primB) == list(range(stA.n_parts))
    assert sorted(stA.loaded) == sorted(pmap.replica_of(0))
    assert sorted(stB.loaded) == sorted(pmap.replica_of(1))

    before_remote = _cnt("dataplane_remote_fragments_total")
    before_bytes = _cnt("dataplane_exchange_bytes_total")
    assert sA.execute(Q6)[0].rows == oracle6
    assert sA.execute(Q1)[0].rows == oracle1
    # cross-host execution actually happened (parity alone can't prove
    # it — the local fallback answers identically)
    assert _cnt("dataplane_remote_fragments_total") > before_remote
    assert _cnt("dataplane_exchange_bytes_total") > before_bytes
    # and the other direction: the worker member scatters to pid 0
    sB.execute("set tidb_use_tpu = 1")
    assert sB.execute(Q6)[0].rows == oracle6

    # ---- survivor re-shard: member 1 leaves, epoch bumps ----
    epoch_before = cp.view().epoch
    wp.stop(leave=True)
    deactivate_dataplane(sB.domain.storage)
    _wait(lambda: 1 not in cp.view().members)
    assert cp.view().epoch > epoch_before
    before_reshard = _cnt("dataplane_reshards_total")
    before_q = _cnt("dataplane_queries_total")
    before_promote = _cnt("dataplane_replica_promotions_total")
    before_cold = _cnt("dataplane_cold_reloads_total")
    assert sA.execute(Q6)[0].rows == oracle6
    assert _cnt("dataplane_reshards_total") == before_reshard + 1
    assert _cnt("dataplane_queries_total") == before_q + 1
    # the survivor now owns (and materialized) every partition — and at
    # RF=2 it already HELD the dead member's partitions as warm
    # replicas, so the takeover is pure promotion: zero cold reloads
    assert sorted(stA.loaded) == list(range(stA.n_parts))
    assert _cnt("dataplane_replica_promotions_total") > before_promote
    assert _cnt("dataplane_cold_reloads_total") == before_cold
    assert sA.execute(Q1)[0].rows == oracle1


def test_reshard_chaos_site_falls_back_then_converges(two_member_fleet):
    sA, sB, cp, wp, dpA, dpB = two_member_fleet
    tid = sA.domain.catalog.info_schema().table("test", "lineitem").id
    oracle6 = _oracle(sA, Q6)
    dpA.shard_table(tid)
    dpB.shard_table(tid)
    assert sA.execute(Q6)[0].rows == oracle6

    wp.stop(leave=True)
    deactivate_dataplane(sB.domain.storage)
    _wait(lambda: 1 not in cp.view().members)
    # the chaos site: the FIRST replay of an orphaned partition dies
    # mid-re-shard.  The dispatch must fall back (parity preserved) and
    # the NEXT dispatch must replay the whole transition successfully.
    with failpoint("dataplane/reshard", once(RuntimeError("injected"))):
        before_err = _cnt("dataplane_errors_total")
        assert sA.execute(Q6)[0].rows == oracle6
        assert _cnt("dataplane_errors_total") > before_err
    before_q = _cnt("dataplane_queries_total")
    assert sA.execute(Q6)[0].rows == oracle6
    assert _cnt("dataplane_queries_total") == before_q + 1
    assert sorted(dpA.lookup(tid).loaded) == \
        list(range(dpA.lookup(tid).n_parts))


def test_survivor_reshard_replays_persisted_packed_blocks(
        two_member_fleet_rf1):
    sA, sB, cp, wp, dpA, dpB = two_member_fleet_rf1
    tid = sA.domain.catalog.info_schema().table("test", "lineitem").id
    oracle = _oracle(sA, GROUPED)
    dpA.shard_table(tid)
    dpB.shard_table(tid)
    wp.stop(leave=True)
    deactivate_dataplane(sB.domain.storage)
    _wait(lambda: 1 not in cp.view().members)
    before_packed = _cnt("dataplane_replay_packed_total")
    assert sA.execute(GROUPED)[0].rows == oracle
    # orphaned partitions replayed from the persisted bit-packed form,
    # not re-sliced from the live source table
    assert _cnt("dataplane_replay_packed_total") > before_packed


def test_dataplane_threads_reclaimed(two_member_fleet):
    sA, sB, cp, wp, dpA, dpB = two_member_fleet
    tid = sA.domain.catalog.info_schema().table("test", "lineitem").id
    dpA.shard_table(tid)
    dpB.shard_table(tid)
    sA.execute(Q6)
    deactivate_dataplane(sA.domain.storage)
    deactivate_dataplane(sB.domain.storage)
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("dataplane-rpc")]
        if not leaked:
            break
        time.sleep(0.1)
    assert not leaked, leaked
