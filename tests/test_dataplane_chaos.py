"""Chaos-hardened replicated data plane (ISSUE 20): the seeded sweep
over the three new dataplane failpoints (`dataplane/peer_error`,
`dataplane/peer_stall`, `dataplane/replica_load`), the failover ladder
(primary -> replica chain -> local bypass), hedged reads with
winner-only byte metering, the pooled `PeerClient`, owner-side fragment
dedup, and the bounded-wait KILL contract during a stalled peer RPC.

Everything is deterministic: event-gated stalls, `once()`/`always()`
injections, the same seeded lineitem build in every member — no sleeps
decide correctness, only bounds.
"""

import threading
import time

import pytest

from tidb_tpu.coord.plane import Coordinator, CoordinatorPlane, WorkerPlane
from tidb_tpu.dataplane import (POOL, activate_dataplane,
                                deactivate_dataplane)
from tidb_tpu.errors import QueryKilledError
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.store.fault import FAILPOINTS, always, failpoint
from tidb_tpu.tpch_data import build_lineitem

Q6 = ("select sum(l_extendedprice * l_discount) from lineitem "
      "where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01' "
      "and l_discount between 0.05 and 0.07 and l_quantity < 24")
Q1 = ("select l_returnflag, l_linestatus, sum(l_quantity), "
      "sum(l_extendedprice), count(*) from lineitem "
      "where l_shipdate <= '1998-09-02' group by l_returnflag, "
      "l_linestatus order by l_returnflag, l_linestatus")


def _cnt(name):
    return REGISTRY.get(name) or 0.0


def _oracle(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.execute(sql)[0].rows
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _wait(pred, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError("condition not reached in %.1fs" % timeout)


@pytest.fixture(scope="module")
def fleet3(tmp_path_factory):
    """Three in-process members at RF=2: every partition has a warm
    replica on a second member, and from any member's view some chains
    are fully remote (the hedge pair) while others include the member
    itself (the local-replica failover rung)."""
    tmp = tmp_path_factory.mktemp("dp3")
    sessions = [build_lineitem(2048, regions=4) for _ in range(3)]
    coord = Coordinator(port=0, lease_s=6.0, expect=3, self_pid=0)
    host, port = coord.start()
    cp = CoordinatorPlane(coord, pid=0).start((0,))
    wps = [WorkerPlane(f"{host}:{port}", pid, lease_s=6.0).start((pid,))
           for pid in (1, 2)]
    _wait(lambda: cp.view().formed and len(cp.view().members) == 3)
    planes = [cp] + wps
    dps = [activate_dataplane(s.domain.storage, plane=pl, pid=i,
                              data_dir=str(tmp), rf=2)
           for i, (s, pl) in enumerate(zip(sessions, planes))]
    _wait(lambda: all(len(pl.view().addrs) == 3 for pl in planes))
    tid = sessions[0].domain.catalog.info_schema().table(
        "test", "lineitem").id
    for dp in dps:
        dp.shard_table(tid)
    try:
        yield sessions, planes, dps, tid
    finally:
        for s in sessions:
            deactivate_dataplane(s.domain.storage)
        for wp in wps:
            try:
                wp.stop(leave=True)
            except Exception:
                pass
        cp.stop()


def test_rf2_replica_placement(fleet3):
    sessions, planes, dps, tid = fleet3
    pmap = dps[0].sync()
    assert pmap.rf() == 2
    for p in range(pmap.n_parts):
        ch = pmap.chain(p)
        assert len(ch) == 2 and len(set(ch)) == 2
        assert ch[0] == pmap.owner(p)
    # every member materialized exactly its chain slots — more than its
    # primaries (warm replicas), and 2x coverage overall
    for i, dp in enumerate(dps):
        st = dp.lookup(tid)
        assert sorted(st.loaded) == sorted(pmap.replica_of(i))
    total_loaded = sum(len(dp.lookup(tid).loaded) for dp in dps)
    assert total_loaded == 2 * pmap.n_parts


def test_peer_error_fails_over_down_the_chain(fleet3):
    """`dataplane/peer_error` armed ALWAYS: every remote rung answers a
    transient exec error, so each fragment walks the ladder — local
    replica where this member is in the chain, local bypass where it is
    not — and the query still answers with parity THROUGH the
    dataplane (never the outer fallback)."""
    sessions, planes, dps, tid = fleet3
    sA = sessions[0]
    want6, want1 = _oracle(sA, Q6), _oracle(sA, Q1)
    before = {n: _cnt(n) for n in (
        "dataplane_queries_total", "dataplane_failovers_total",
        "dataplane_replica_reads_total", "dataplane_failover_bypass_total",
        "dataplane_errors_total")}
    with failpoint("dataplane/peer_error", always(RuntimeError("chaos"))):
        assert sA.execute(Q6)[0].rows == want6
        assert sA.execute(Q1)[0].rows == want1
    assert _cnt("dataplane_queries_total") == \
        before["dataplane_queries_total"] + 2
    assert _cnt("dataplane_failovers_total") > \
        before["dataplane_failovers_total"]
    # some chains include pid 0 (warm local replica rung), some do not
    # (chain exhausted -> pre-shard base bypass); both rungs must fire
    assert _cnt("dataplane_replica_reads_total") > \
        before["dataplane_replica_reads_total"]
    assert _cnt("dataplane_failover_bypass_total") > \
        before["dataplane_failover_bypass_total"]
    assert _cnt("dataplane_errors_total") == \
        before["dataplane_errors_total"]
    # disarmed: the next dispatch exchanges remotely again
    r0 = _cnt("dataplane_remote_fragments_total")
    assert sA.execute(Q6)[0].rows == want6
    assert _cnt("dataplane_remote_fragments_total") > r0


def test_peer_stall_fails_over_within_deadline(fleet3, monkeypatch):
    """`dataplane/peer_stall` wedges every remote owner: the
    per-fragment deadline (not a 30 s socket timeout) bounds each rung,
    the ladder walks to a rung that can answer, and parity holds."""
    sessions, planes, dps, tid = fleet3
    sA = sessions[0]
    want = _oracle(sA, Q6)
    monkeypatch.setenv("TIDB_TPU_DATAPLANE_FRAG_TIMEOUT_S", "0.3")
    release = threading.Event()

    def stall(**ctx):
        release.wait(5.0)

    f0 = _cnt("dataplane_failovers_total")
    q0 = _cnt("dataplane_queries_total")
    t0 = time.monotonic()
    try:
        with failpoint("dataplane/peer_stall", stall):
            assert sA.execute(Q6)[0].rows == want
    finally:
        release.set()
    elapsed = time.monotonic() - t0
    # every stalled rung cost at most its 0.3s deadline (+ ladder walk),
    # nowhere near the 5s stall or a socket-timeout tail
    assert elapsed < 4.5, elapsed
    assert _cnt("dataplane_failovers_total") > f0
    assert _cnt("dataplane_queries_total") == q0 + 1
    time.sleep(0.1)  # stalled server threads observe the release


def test_replica_load_chaos_is_nonfatal(tmp_path):
    """`dataplane/replica_load` killing a secondary fill must not fail
    the shard — the slot is skipped (counted), the primary still
    serves, and parity holds; the replica fills on first failover
    touch."""
    sA = build_lineitem(1024, regions=2)
    sB = build_lineitem(1024, regions=2)
    coord = Coordinator(port=0, lease_s=6.0, expect=2, self_pid=0)
    host, port = coord.start()
    cp = CoordinatorPlane(coord, pid=0).start((0,))
    wp = WorkerPlane(f"{host}:{port}", 1, lease_s=6.0).start((1,))
    _wait(lambda: cp.view().formed and len(cp.view().members) == 2)
    dpA = activate_dataplane(sA.domain.storage, plane=cp, pid=0,
                             data_dir=str(tmp_path), rf=2)
    dpB = activate_dataplane(sB.domain.storage, plane=wp, pid=1,
                             data_dir=str(tmp_path), rf=2)
    _wait(lambda: len(cp.view().addrs) == 2)
    tid = sA.domain.catalog.info_schema().table("test", "lineitem").id
    try:
        want = _oracle(sA, Q6)
        e0 = _cnt("dataplane_replica_fill_errors_total")
        with failpoint("dataplane/replica_load",
                       always(RuntimeError("fill chaos"))):
            stA = dpA.shard_table(tid)
            dpB.shard_table(tid)
        assert _cnt("dataplane_replica_fill_errors_total") > e0
        pmap = dpA.sync()
        # primaries materialized; the chaos-killed replica slots did not
        assert set(stA.loaded) == set(pmap.owned_by(0))
        assert sA.execute(Q6)[0].rows == want
        # disarmed: ensure_replica heals the missing slot on demand
        missing = sorted(set(pmap.replica_of(0)) - set(stA.loaded))
        assert missing
        assert dpA.ensure_replica(tid, missing[0]) is not None
        assert missing[0] in stA.loaded
    finally:
        deactivate_dataplane(sA.domain.storage)
        deactivate_dataplane(sB.domain.storage)
        try:
            wp.stop(leave=True)
        except Exception:
            pass
        cp.stop()


def test_kill_during_stalled_peer_rpc_is_bounded(fleet3):
    """ISSUE 20 acceptance: KILL QUERY while a fragment waits on a
    stalled peer returns within the scope's bounded wait — the sliced
    recv observes the cancel within one poll, not after a 30 s socket
    timeout (or the 5 s stall)."""
    sessions, planes, dps, tid = fleet3
    sA = sessions[0]
    killer = sA.domain.new_session()
    release = threading.Event()
    stalled = threading.Event()

    def stall(**ctx):
        stalled.set()
        release.wait(6.0)

    result = {}

    def run():
        try:
            sA.execute(Q6)
        except Exception as e:  # noqa: BLE001 - recorded for assertion
            result["err"] = e
        result["t"] = time.monotonic()

    try:
        with failpoint("dataplane/peer_stall", stall):
            th = threading.Thread(target=run)
            th.start()
            assert stalled.wait(10.0), "no fragment reached the stall"
            t_kill = time.monotonic()
            killer.execute(f"kill query {sA.conn_id}")
            th.join(timeout=3.0)
        assert not th.is_alive(), "statement survived KILL"
        assert isinstance(result.get("err"), QueryKilledError), result
        assert result["t"] - t_kill < 1.5, "KILL latency exceeded bound"
    finally:
        release.set()
    time.sleep(0.1)
    # the session is healthy afterwards and the plane still serves
    q0 = _cnt("dataplane_queries_total")
    want = _oracle(sA, Q6)
    assert sA.execute(Q6)[0].rows == want
    assert _cnt("dataplane_queries_total") == q0 + 1


def test_hedged_read_wins_without_double_counting_exchange(fleet3,
                                                           monkeypatch):
    """Slow every owner and hedge after 1ms: the pair races, the first
    answer wins, and `dataplane_exchange_bytes_total` grows by exactly
    the unhedged amount — the loser's bytes land in the wasted counter
    or nowhere, never in the query's exchange."""
    sessions, planes, dps, tid = fleet3
    sA = sessions[0]
    want = _oracle(sA, Q6)
    x0 = _cnt("dataplane_exchange_bytes_total")
    assert sA.execute(Q6)[0].rows == want
    unhedged_delta = _cnt("dataplane_exchange_bytes_total") - x0
    assert unhedged_delta > 0

    monkeypatch.setenv("TIDB_TPU_DATAPLANE_HEDGE_MS", "1")
    h0 = _cnt("dataplane_hedged_fragments_total")
    x1 = _cnt("dataplane_exchange_bytes_total")

    def slow(**ctx):
        time.sleep(0.15)

    with failpoint("dataplane/peer_stall", slow):
        assert sA.execute(Q6)[0].rows == want
    hedged_delta = _cnt("dataplane_exchange_bytes_total") - x1
    assert _cnt("dataplane_hedged_fragments_total") > h0
    # winner-only metering: the hedged run moved the same exchange
    # volume as the unhedged run (a double count would be ~2x)
    assert hedged_delta == unhedged_delta, (hedged_delta, unhedged_delta)
    time.sleep(0.3)  # losers drain before the leak check below


def test_peer_pool_reuses_connections(fleet3):
    sessions, planes, dps, tid = fleet3
    sA = sessions[0]
    sA.execute(Q6)  # warm the pool
    d0, r0 = _cnt("dataplane_conn_dials_total"), \
        _cnt("dataplane_conn_reuse_total")
    sA.execute(Q6)
    sA.execute(Q1)
    assert _cnt("dataplane_conn_dials_total") == d0, "dialed per fragment"
    assert _cnt("dataplane_conn_reuse_total") > r0


def test_server_dedup_never_double_executes(fleet3):
    """Two calls carrying the SAME dedup key execute once: the twin is
    answered from the owner's result cache (hedge-pair idempotence on a
    single server, and retry idempotence after an abandoned response)."""
    from tidb_tpu.dataplane.rpc import PeerClient

    sessions, planes, dps, tid = fleet3
    addr = planes[0].view().addrs[1]
    c = PeerClient(addr)
    try:
        epoch = planes[0].view().epoch
        # an empty-range fragment executes trivially; what matters is
        # that the SECOND call replays the cached result instead of
        # re-entering the executor
        e0 = _cnt("dataplane_remote_fragments_total")
        d0 = _cnt("dataplane_dedup_hits_total")
        r1, _ = c.exec_fragment({"bogus": 1}, [], 0, epoch, "tpu",
                                frag="test-dedup-key-1")
        r2, _ = c.exec_fragment({"bogus": 1}, [], 0, epoch, "tpu",
                                frag="test-dedup-key-1")
        assert r2 == r1
        assert _cnt("dataplane_remote_fragments_total") == e0 + 1
        assert _cnt("dataplane_dedup_hits_total") == d0 + 1
    finally:
        c.close()


def test_chaos_sweep_leaves_no_threads_or_sockets(fleet3):
    """After the whole module's chaos ran: no fragment/hedge worker
    threads linger, no failpoints stay armed, and the pool holds only
    healthy idle sockets to LIVE peers."""
    time.sleep(0.2)
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("dataplane-frag")]
    assert not leaked, leaked
    assert FAILPOINTS.armed() == []
    sessions, planes, dps, tid = fleet3
    live = set(planes[0].view().addrs.values())
    with POOL._mu:
        pooled = set(POOL._idle)
    assert pooled <= live, (pooled, live)
