"""2-OS-process dataplane acceptance (ISSUE 18): a table sharded across
two real processes answers Q1/Q6/grouped-agg/join with parity vs the
CPU oracle THROUGH the dataplane (dp>=N markers — parity alone cannot
distinguish cross-host execution from the always-correct local
fallback); SIGKILL of one process bumps the epoch via lease expiry and
the survivor re-shards the orphaned partitions and keeps answering with
parity at the new epoch, still through the dataplane."""

import signal
import subprocess
import sys
import threading
import time

from tidb_tpu.coord.plane import Coordinator
from tidb_tpu.store.fault import FAILPOINTS


def _spawn_worker(pid, port, dp_dir, rf=1, expect=2):
    import os

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["COORD_LEASE_S"] = "1.5"
    env["COORD_WORKER_MAX_S"] = "150"
    env["TIDB_TPU_DATAPLANE_DIR"] = dp_dir
    env["TIDB_TPU_DATAPLANE_RF"] = str(rf)
    env["COORD_EXPECT"] = str(expect)
    worker = os.path.join(os.path.dirname(__file__), "dataplane_worker.py")
    p = subprocess.Popen(
        [sys.executable, worker, str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, bufsize=1)
    lines = []

    def pump():
        for line in p.stdout:
            lines.append(line.strip())

    threading.Thread(target=pump, daemon=True).start()
    return p, lines


def _wait_line(lines, pred, timeout_s, procs=()):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if any(pred(ln) for ln in list(lines)):
            return True
        if procs and all(p.poll() is not None for p in procs):
            break
        time.sleep(0.1)
    return any(pred(ln) for ln in list(lines))


def _wait(pred, timeout_s):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return pred()


def _dp_round(s):
    """A parity round that the dataplane actually served (every query
    in the round went through the sharded path)."""
    if not (s.startswith("ROUND") and "ok=1" in s):
        return False
    try:
        return int(s.split("dp=")[1].split()[0]) >= 4
    except (IndexError, ValueError):
        return False


def test_two_process_dataplane_shard_and_sigkill_reshard(tmp_path):
    threads_before = {t.name for t in threading.enumerate()}
    c = Coordinator(lease_s=1.5, expect=2)
    c.start()
    procs = []
    dp_dir = str(tmp_path)
    try:
        w0, l0 = _spawn_worker(0, c.port, dp_dir)
        procs.append(w0)
        w1, l1 = _spawn_worker(1, c.port, dp_dir)
        procs.append(w1)
        assert _wait_line(l0, lambda s: s.startswith("READY"), 90,
                          (w0,)), (l0[-10:], l1[-10:])
        assert _wait_line(l1, lambda s: s.startswith("READY"), 90,
                          (w1,)), (l0[-10:], l1[-10:])
        v = c.view()
        assert set(v.members) == {0, 1} and v.formed
        # both advertised fragment endpoints through the broadcast
        assert set(v.addrs) == {0, 1}, v.addrs
        # each worker materialized a strict subset of the partitions —
        # the table is actually SPLIT across the two processes
        sh0 = next(s for s in list(l0) if s.startswith("SHARDED"))
        sh1 = next(s for s in list(l1) if s.startswith("SHARDED"))
        n0 = int(sh0.split("loaded=")[1].split("/")[0])
        n1 = int(sh1.split("loaded=")[1].split("/")[0])
        total = int(sh0.split("/")[1])
        assert 0 < n0 < total and 0 < n1 < total and n0 + n1 == total, \
            (sh0, sh1)

        # parity rounds served by the dataplane, on BOTH members
        assert _wait_line(l0, _dp_round, 60, (w0,)), l0[-5:]
        assert _wait_line(l1, _dp_round, 60, (w1,)), l1[-5:]

        # ---- SIGKILL one member mid-load -----------------------------
        e_before = c.view().epoch
        w1.kill()
        assert _wait(lambda: 1 not in c.view().members, 15.0), \
            "lease expiry did not evict the killed worker"
        v_after = c.view()
        assert v_after.epoch > e_before
        # the survivor re-shards the orphaned partitions and keeps
        # serving THROUGH the dataplane at the bumped epoch
        assert _wait_line(
            l0,
            lambda s: _dp_round(s) and f"epoch={v_after.epoch}" in s,
            45, (w0,)), l0[-5:]
        assert not any("ok=0" in s for s in list(l0)), \
            [s for s in l0 if "ok=0" in s]
        assert not any(s.startswith("MISMATCH") for s in list(l0))

        # ---- graceful drain ------------------------------------------
        w0.send_signal(signal.SIGTERM)
        assert _wait_line(l0, lambda s: s.startswith("DRAINED"), 30, (w0,))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        c.stop()
    time.sleep(0.3)
    leaked = {t.name for t in threading.enumerate()} - threads_before
    leaked = {n for n in leaked
              if n.startswith(("tidb-tpu-coord", "dataplane-rpc"))}
    assert not leaked, leaked
    assert FAILPOINTS.armed() == []


def _round_counter(s, key):
    try:
        return int(s.split(f"{key}=")[1].split()[0])
    except (IndexError, ValueError):
        return -1


def test_three_process_sigkill_promotes_replica_no_cold_reload(tmp_path):
    """ISSUE 20 acceptance: RF=2 over 3 processes.  SIGKILL one member
    mid-query; lease expiry bumps the epoch and the survivors take over
    its partitions by PROMOTING their warm replicas — promotions > 0,
    cold reloads == 0 on every survivor — while rounds keep answering
    with parity THROUGH the dataplane at the bumped epoch."""
    threads_before = {t.name for t in threading.enumerate()}
    c = Coordinator(lease_s=1.5, expect=3)
    c.start()
    procs = []
    dp_dir = str(tmp_path)
    try:
        workers = []
        for pid in range(3):
            w, lines = _spawn_worker(pid, c.port, dp_dir, rf=2, expect=3)
            procs.append(w)
            workers.append((w, lines))
        for w, lines in workers:
            assert _wait_line(lines, lambda s: s.startswith("READY"), 120,
                              (w,)), lines[-10:]
        v = c.view()
        assert set(v.members) == {0, 1, 2} and v.formed
        assert set(v.addrs) == {0, 1, 2}, v.addrs
        # every member materialized MORE than its primaries (replica
        # slots) but the union still covers the table
        loads = {}
        for _w, lines in workers:
            sh = next(s for s in list(lines) if s.startswith("SHARDED"))
            loads[int(sh.split("pid=")[1].split()[0])] = (
                int(sh.split("loaded=")[1].split("/")[0]))
        total = 8
        assert all(0 < n <= total for n in loads.values()), loads
        assert sum(loads.values()) >= total + 1, loads  # replication > 1x

        # dataplane-served parity rounds on every member
        for w, lines in workers:
            assert _wait_line(lines, _dp_round, 60, (w,)), lines[-5:]

        # ---- SIGKILL one member mid-query ----------------------------
        e_before = c.view().epoch
        procs[2].kill()
        assert _wait(lambda: 2 not in c.view().members, 15.0), \
            "lease expiry did not evict the killed worker"
        v_after = c.view()
        assert v_after.epoch > e_before

        survivors = workers[:2]
        for w, lines in survivors:
            assert _wait_line(
                lines,
                lambda s: _dp_round(s) and f"epoch={v_after.epoch}" in s,
                60, (w,)), lines[-5:]
            assert not any("ok=0" in s for s in list(lines)), \
                [s for s in lines if "ok=0" in s]
            assert not any(s.startswith("MISMATCH") for s in list(lines))
        # the takeover was replica PROMOTION, not a cold-tier reload:
        # at least one survivor promoted, and NOBODY reloaded cold
        post = []
        for _w, lines in survivors:
            rounds = [s for s in list(lines)
                      if _dp_round(s) and f"epoch={v_after.epoch}" in s]
            post.append(rounds[-1])
        assert sum(_round_counter(s, "promote") for s in post) > 0, post
        assert all(_round_counter(s, "cold") == 0 for s in post), post

        # ---- graceful drain ------------------------------------------
        for w, lines in survivors:
            w.send_signal(signal.SIGTERM)
        for w, lines in survivors:
            assert _wait_line(lines, lambda s: s.startswith("DRAINED"),
                              30, (w,))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        c.stop()
    time.sleep(0.3)
    leaked = {t.name for t in threading.enumerate()} - threads_before
    leaked = {n for n in leaked
              if n.startswith(("tidb-tpu-coord", "dataplane-rpc"))}
    assert not leaked, leaked
    assert FAILPOINTS.armed() == []
