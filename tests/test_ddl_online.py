"""Online DDL: F1 state ladder + resumable add-index backfill.

Reference: ddl/ddl_worker.go:466-469 (none -> delete-only -> write-only ->
write-reorg -> public, one schema-version bump per step), ddl/reorg.go
(range-batched backfill with job-checkpointed progress, resumed by the
re-elected owner after a crash)."""

import numpy as np
import pytest

from tidb_tpu.catalog.schema import STATE_PUBLIC
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import failpoint


@pytest.fixture()
def data_dir(tmp_path):
    return str(tmp_path / "data")


def _load(d, n=20_000):
    s = d.new_session()
    s.execute("create table t (a bigint, b bigint)")
    t = d.catalog.info_schema().table("test", "t")
    rng = np.random.default_rng(9)
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 1000, n, dtype=np.int64)],
        ts=d.storage.current_ts())
    return s


def test_ladder_walks_all_states(data_dir):
    d = Domain(data_dir=data_dir)
    s = _load(d)
    ver0 = d.catalog.schema_version
    s.execute("create index ib on t (b)")
    job = [j for j in d.catalog.jobs if j.typ == "add_index"][-1]
    assert job.state == "done"
    assert job.states_walked == [
        "none", "delete-only", "write-only", "write-reorg", "public"]
    # one version bump per transition
    assert d.catalog.schema_version - ver0 >= 4
    ix = d.catalog.info_schema().table("test", "t").find_index("ib")
    assert ix.state == STATE_PUBLIC
    s.execute("analyze table t")
    plan = s.execute("explain select a from t where b = 7")[0].rows
    assert any("IndexLookUp" in r[0] for r in plan), plan


class Die(BaseException):
    """kill -9 stand-in: a real crash never runs except-Exception handlers,
    so the rollback path must NOT fire for BaseException."""


def test_nonpublic_index_not_planned(data_dir):
    """A mid-ladder index (simulated crash) must not serve reads."""
    d = Domain(data_dir=data_dir)
    s = _load(d)

    def crash(job, upto):
        raise Die()

    with failpoint("ddl/backfill_batch", crash):
        with pytest.raises(Die):
            s.execute("create index ib on t (b)")
    ix = d.catalog.info_schema().table("test", "t").find_index("ib")
    assert ix is not None and ix.state != STATE_PUBLIC
    plan = s.execute("explain select a from t where b = 7")[0].rows
    assert not any("IndexLookUp" in r[0] for r in plan), plan


def test_error_mid_ladder_rolls_back(data_dir):
    """A plain ERROR (not a crash) rolls the job back: the index name is
    free again and the job records the failure."""
    d = Domain(data_dir=data_dir)
    s = _load(d)

    def boom(job, upto):
        raise RuntimeError("disk full")

    with failpoint("ddl/backfill_batch", boom):
        with pytest.raises(RuntimeError):
            s.execute("create index ib on t (b)")
    assert d.catalog.info_schema().table("test", "t").find_index("ib") is None
    job = [j for j in d.catalog.jobs if j.typ == "add_index"][-1]
    assert job.state == "rollback" and "disk full" in job.error
    # the name is reusable
    s.execute("create index ib on t (b)")
    assert d.catalog.info_schema().table(
        "test", "t").find_index("ib").state == STATE_PUBLIC


def test_unique_violation_fails_and_backfill_rechecks(data_dir):
    d = Domain(data_dir=data_dir)
    s = _load(d, n=100)  # b = arange % 500: a-col unique, b-col has dups
    with pytest.raises(Exception, match="duplicate"):
        s.execute("create unique index ub on t (b)")
    assert d.catalog.info_schema().table("test", "t").find_index("ub") is None
    # the backfill-time recheck also fires when only base rows collide and
    # the upfront gate is bypassed (delete-only-window writes analog)
    orig = d.catalog._check_unique
    d.catalog._check_unique = lambda *a, **k: None
    try:
        with pytest.raises(Exception, match="duplicate"):
            s.execute("create unique index ub2 on t (b)")
    finally:
        d.catalog._check_unique = orig
    assert d.catalog.info_schema().table("test", "t").find_index("ub2") is None
    job = [j for j in d.catalog.jobs if j.typ == "add_index"][-1]
    assert job.state == "rollback"
    # non-dup unique succeeds
    s.execute("create unique index ua on t (a)")
    assert d.catalog.info_schema().table(
        "test", "t").find_index("ua").state == STATE_PUBLIC


def test_delete_only_window_insert_dup_fails_ddl(data_dir):
    """A duplicate committed while the index is delete-only lives in the
    delta overlay (dml.py skips unique maintenance for delete-only
    indexes); the backfill recheck must still see it and roll back."""
    d = Domain(data_dir=data_dir)
    s = _load(d, n=100)
    s2 = d.new_session()

    def sneak(job, state):
        if state == "delete-only":
            # a=5 already exists in base (a = arange over 100 rows)
            s2.execute("insert into t values (5, 999999)")

    with failpoint("ddl/set_state", sneak):
        with pytest.raises(Exception, match="duplicate"):
            s.execute("create unique index ua on t (a)")
    assert d.catalog.info_schema().table("test", "t").find_index("ua") is None
    job = [j for j in d.catalog.jobs if j.typ == "add_index"][-1]
    assert job.state == "rollback"


def test_open_txn_straddling_ddl_conflicts_at_commit(data_dir):
    """A txn whose buffered write executed while an index was delete-only
    (no unique enforcement) must NOT commit blind after the index goes
    public: the commit-time schema check forces a retry (session.go
    checkSchemaValidity / domain/schema_validator.go analog)."""
    from tidb_tpu.errors import SchemaChangedError

    d = Domain(data_dir=data_dir)
    s = _load(d, n=100)
    s2 = d.new_session()
    s2.execute("begin")
    s2.execute("insert into t values (5, 999999)")  # dup of base a=5
    # DDL runs while s2's write sits in its txn buffer (invisible to the
    # backfill recheck — not yet prewritten)
    s.execute("create unique index ua on t (a)")
    with pytest.raises(SchemaChangedError):
        s2.execute("commit")
    # retry under the new schema: now the public unique index enforces
    s2.execute("begin")
    with pytest.raises(Exception, match="[Dd]uplicate"):
        s2.execute("insert into t values (5, 999999)")
    s2.execute("rollback")
    # and a non-conflicting retry commits fine
    s2.execute("begin")
    s2.execute("insert into t values (100001, 999999)")
    s2.execute("commit")


def test_crash_mid_backfill_resumes_on_reopen(data_dir):
    d = Domain(data_dir=data_dir)
    s = _load(d)
    want = sorted(s.query("select a from t where b = 7"))

    # die after the second backfill batch is checkpointed
    def crash(job, upto):
        if upto >= 2 * d.catalog.BACKFILL_BATCH:
            raise Die()

    with failpoint("ddl/backfill_batch", crash):
        with pytest.raises(Die):
            s.execute("create index ib on t (b)")
    job = [j for j in d.catalog.jobs if j.typ == "add_index"][-1]
    assert job.state == "running"
    assert job.reorg_progress >= 2 * d.catalog.BACKFILL_BATCH
    checkpoint = job.reorg_progress

    # the process "dies"; a fresh domain reopens the same data_dir
    d2 = Domain(data_dir=data_dir)
    job2 = [j for j in d2.catalog.jobs if j.typ == "add_index"][-1]
    assert job2.state == "done", (job2.state, job2.states_walked)
    # resume continued from the checkpoint, not from zero
    assert job2.reorg_progress >= checkpoint
    ix = d2.catalog.info_schema().table("test", "t").find_index("ib")
    assert ix is not None and ix.state == STATE_PUBLIC
    s2 = d2.new_session()
    s2.execute("analyze table t")
    plan = s2.execute("explain select a from t where b = 7")[0].rows
    assert any("IndexLookUp" in r[0] for r in plan), plan
    assert sorted(s2.query("select a from t where b = 7")) == want
