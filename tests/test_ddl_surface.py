"""DDL/admin surface breadth: CHANGE COLUMN, RENAME INDEX,
AUTO_INCREMENT rebase, table COMMENT, FOREIGN KEY metadata, DROP STATS,
REPAIR TABLE, ADMIN CHECKSUM TABLE, ADMIN SHOW ... NEXT_ROW_ID.

Reference: ddl/ddl_api.go (:1999 rebase, :2785 change, :2902 comment,
:3105 rename index, :3509/:3541 FK, :3936 repair), kv checksum request
(kv/kv.go:206-211), executor ShowNextRowID."""

import numpy as np
import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    return Domain()


def test_change_column_rename_and_retype(d):
    s = d.new_session()
    s.execute("create table c (a bigint, b varchar(10))")
    s.execute("insert into c values (1, 'x'), (2, 'y')")
    s.execute("alter table c change a a2 double")
    cols = [r[0] for r in s.query("show columns from c")]
    assert cols == ["a2", "b"]
    assert s.query("select a2 from c order by a2") == [(1.0,), (2.0,)]
    with pytest.raises(TiDBTPUError):
        s.execute("alter table c change a2 b bigint")  # name collision
    # plain rename (same type)
    s.execute("alter table c change column b tag varchar(10)")
    assert s.query("select tag from c where a2 = 1") == [("x",)]


def test_rename_index_and_auto_increment_and_comment(d):
    s = d.new_session()
    s.execute("create table r (id bigint primary key, v bigint)")
    s.execute("create index iv on r (v)")
    s.execute("alter table r rename index iv to v_idx")
    t = d.catalog.info_schema().table("test", "r")
    assert [ix.name for ix in t.indexes if not ix.primary] == ["v_idx"]
    with pytest.raises(TiDBTPUError):
        s.execute("alter table r rename index nope to x")
    s.execute("alter table r auto_increment = 1000")
    assert d.catalog.info_schema().table("test", "r").auto_inc_id == 1000
    s.execute("alter table r auto_increment = 5")  # never goes backwards
    assert d.catalog.info_schema().table("test", "r").auto_inc_id == 1000
    s.execute("alter table r comment = 'facts'")
    assert d.catalog.info_schema().table("test", "r").comment == "facts"


def test_foreign_key_metadata(d):
    s = d.new_session()
    s.execute("create table parent (id bigint primary key, v bigint)")
    s.execute("create table child (id bigint, pid bigint,"
              " constraint fk_p foreign key (pid) references parent (id)"
              " on delete cascade)")
    t = d.catalog.info_schema().table("test", "child")
    assert t.foreign_keys == [{
        "name": "fk_p", "columns": ["pid"], "ref_db": "test",
        "ref_table": "parent", "ref_columns": ["id"]}]
    sc = s.query("show create table child")[0][1]
    assert "CONSTRAINT `fk_p` FOREIGN KEY (`pid`) REFERENCES `parent`" in sc
    # ALTER add/drop
    s.execute("alter table child add constraint fk2 foreign key (id)"
              " references parent (id)")
    assert len(d.catalog.info_schema().table("test", "child")
               .foreign_keys) == 2
    s.execute("alter table child drop foreign key fk_p")
    fks = d.catalog.info_schema().table("test", "child").foreign_keys
    assert [fk["name"] for fk in fks] == ["fk2"]
    with pytest.raises(TiDBTPUError):
        s.execute("alter table child drop foreign key nope")
    # FKs survive a catalog persist round trip
    blob = d.catalog.to_json()
    from tidb_tpu.catalog.catalog import Catalog

    c2 = Catalog(d.storage)
    c2.load_json(blob)
    assert c2.info_schema().table("test", "child").foreign_keys == fks
    # unenforced: orphan rows insert fine (the reference's support level)
    s.execute("insert into child values (1, 999)")


def test_drop_stats(d):
    s = d.new_session()
    s.execute("create table ds (a bigint)")
    s.execute("insert into ds values (1), (2)")
    s.execute("analyze table ds")
    t = d.catalog.info_schema().table("test", "ds")
    assert d.stats.get(t.id) is not None
    s.execute("drop stats ds")
    assert d.stats.get(t.id) is None


def test_repair_table(d):
    s = d.new_session()
    s.execute("create table rp (id bigint primary key, v bigint)")
    s.execute("insert into rp values " + ", ".join(
        f"({i}, {i})" for i in range(300)))
    t = d.catalog.info_schema().table("test", "rp")
    d.storage.maybe_compact(t.id, threshold=0)
    s.execute("create index iv on rp (v)")
    store = d.storage.table(t.id)
    offs = tuple(t.col_offsets(["v"]))
    import dataclasses

    idx = store.indexes.get(store, offs)
    store.indexes.put(offs, dataclasses.replace(
        idx, handles=idx.handles[:-1], cols=[c[:-1] for c in idx.cols]))
    with pytest.raises(TiDBTPUError):
        s.execute("admin check table rp")
    s.execute("repair table rp")
    s.execute("admin check table rp")


def test_checksum_table(d):
    s = d.new_session()
    s.execute("create table ck (a bigint, b varchar(8))")
    s.execute("insert into ck values (1, 'x'), (2, 'y')")
    rs = s.execute("admin checksum table ck")[0]
    assert rs.headers[0] == "Db_name"
    db, name, crc, kvs, nbytes = rs.rows[0]
    assert (db, name, kvs) == ("test", "ck", 2) and nbytes > 0
    # checksum is content-sensitive and delta-aware
    s.execute("insert into ck values (3, 'z')")
    crc2 = s.execute("admin checksum table ck")[0].rows[0][2]
    assert crc2 != crc
    assert s.execute("admin checksum table ck")[0].rows[0][3] == 3


def test_show_next_row_id(d):
    s = d.new_session()
    s.execute("create table nr (id bigint primary key, v bigint)")
    s.execute("insert into nr values (1, 1), (2, 2)")
    rs = s.execute("admin show nr next_row_id")[0]
    assert rs.rows[0][0] == "test" and rs.rows[0][1] == "nr"
    assert rs.rows[0][3] >= 2


def test_change_column_fixes_indexes_and_fks(d):
    s = d.new_session()
    s.execute("create table p2 (id bigint primary key)")
    s.execute("create table t2 (b bigint, pid bigint,"
              " foreign key fkx (pid) references p2 (id))")
    s.execute("create index ib on t2 (b)")
    s.execute("alter table t2 change b b2 bigint")
    t = d.catalog.info_schema().table("test", "t2")
    assert any(ix.columns == ["b2"] for ix in t.indexes)
    s.execute("insert into t2 values (5, 1)")  # unique-check path works
    s.execute("analyze table t2")              # stats path works
    s.execute("admin check table t2")
    # FK column rename on the child side
    s.execute("alter table t2 change pid parent_id bigint")
    t = d.catalog.info_schema().table("test", "t2")
    assert t.foreign_keys[0]["columns"] == ["parent_id"]
    # renaming the PARENT's key column updates referencing metadata
    s.execute("alter table p2 change id id2 bigint")
    t = d.catalog.info_schema().table("test", "t2")
    assert t.foreign_keys[0]["ref_columns"] == ["id2"]
    # renaming the parent table updates ref_table
    s.execute("alter table p2 rename to p3")
    t = d.catalog.info_schema().table("test", "t2")
    assert t.foreign_keys[0]["ref_table"] == "p3"


def test_comment_survives_restart(tmp_path):
    dd = str(tmp_path / "data")
    d1 = Domain(data_dir=dd)
    s1 = d1.new_session()
    s1.execute("create table cm (a bigint)")
    s1.execute("alter table cm comment = 'kept'")
    d1.maintenance.stop()
    d2 = Domain(data_dir=dd)
    assert d2.catalog.info_schema().table("test", "cm").comment == "kept"
    d2.maintenance.stop()


def test_create_table_fk_validation(d):
    s = d.new_session()
    with pytest.raises(TiDBTPUError):
        s.execute("create table bad (pid bigint,"
                  " foreign key (pid) references nope (id))")
    s.execute("create table par (id bigint primary key)")
    with pytest.raises(TiDBTPUError):
        s.execute("create table bad (pid bigint,"
                  " foreign key (pid) references par (missing))")
