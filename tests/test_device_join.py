"""Device broadcast lookup join (JoinLookupIR): the inner join + partial
aggregation complete inside the cop task.

Reference role: executor/join.go HashJoinExec (build :232, probe workers
:307-414) — relocated into the coprocessor so join-heavy aggregates return
partials, not probe streams."""

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.types.values import parse_date


@pytest.fixture()
def d():
    return Domain()


def _load(d, n_o=500, n_l=8000, null_probe_keys=False):
    s = d.new_session()
    s.execute("create table orders (o_orderkey bigint primary key,"
              " o_orderdate date, o_shippriority bigint)")
    s.execute("create table li (l_orderkey bigint,"
              " l_extendedprice decimal(15,2), l_discount decimal(15,2),"
              " l_shipdate date)")
    rng = np.random.default_rng(3)
    base = parse_date("1995-01-01")
    t_o = d.catalog.info_schema().table("test", "orders")
    t_l = d.catalog.info_schema().table("test", "li")
    d.storage.table(t_o.id).bulk_load_arrays([
        np.arange(n_o, dtype=np.int64),
        (base + rng.integers(-200, 200, n_o)).astype(np.int64),
        rng.integers(0, 5, n_o),
    ], ts=d.storage.current_ts())
    lk = rng.integers(0, n_o * 2, n_l)  # half the keys have no match
    lv = None
    if null_probe_keys:
        lv = [np.ones(n_l, np.bool_), None, None, None]
        lv[0][:100] = False
    d.storage.table(t_l.id).bulk_load_arrays([
        lk,
        rng.integers(90_000, 10_500_001, n_l),
        rng.integers(0, 11, n_l),
        (base + rng.integers(-300, 300, n_l)).astype(np.int64),
    ], [lv[i] if lv else None for i in range(4)] if lv else None,
        ts=d.storage.current_ts())
    d.storage.regions.split_even(t_l.id, 8, n_l)
    s.execute("analyze table orders")
    s.execute("analyze table li")
    return s


Q3 = ("select l_orderkey, o_orderdate, o_shippriority,"
      " sum(l_extendedprice * (1 - l_discount)) as rev"
      " from li, orders where l_orderkey = o_orderkey"
      " and o_orderdate < '1995-03-15' and l_shipdate > '1995-03-15'"
      " group by l_orderkey, o_orderdate, o_shippriority"
      " order by rev desc, l_orderkey limit 10")


def _parity(s, q):
    s.execute("set tidb_use_tpu = 1")
    tpu = s.query(q)
    s.execute("set tidb_use_tpu = 0")
    cpu = s.query(q)
    s.execute("set tidb_use_tpu = 1")
    assert tpu == cpu, (tpu[:3], cpu[:3])
    return tpu


def _plan_ops(s, q):
    return [r[0] for r in s.execute("explain " + q)[0].rows]


def test_q3_shape_joins_in_cop_task(d):
    s = _load(d)
    ops = _plan_ops(s, Q3)
    assert any("DeviceJoinReader" in op for op in ops), ops
    assert any("JoinLookup" in op for op in ops), ops
    rows = _parity(s, Q3)
    assert len(rows) == 10


def test_scalar_agg_over_join(d):
    s = _load(d)
    q = ("select count(*), sum(l_extendedprice) from li, orders"
         " where l_orderkey = o_orderkey and o_shippriority < 3")
    assert any("DeviceJoinReader" in op for op in _plan_ops(s, q))
    _parity(s, q)


def test_group_by_payload_column(d):
    s = _load(d)
    q = ("select o_shippriority, count(*), min(l_extendedprice)"
         " from li, orders where l_orderkey = o_orderkey"
         " group by o_shippriority order by o_shippriority")
    assert any("DeviceJoinReader" in op for op in _plan_ops(s, q))
    _parity(s, q)


def test_empty_build_side(d):
    s = _load(d)
    q = ("select count(*) from li, orders where l_orderkey = o_orderkey"
         " and o_orderdate < '1200-01-01'")
    assert _parity(s, q) == [(0,)]


def test_delta_rows_join_through_cpu_engine(d):
    """Committed delta inserts on the probe table flow through the CPU
    engine's JoinLookupIR path and merge with device partials."""
    s = _load(d)
    s.execute("insert into li values (1, 1000.00, 0.00, '1995-06-01'),"
              " (1, 2000.00, 0.00, '1995-06-01')")
    q = ("select count(*), sum(l_extendedprice) from li, orders"
         " where l_orderkey = o_orderkey")
    _parity(s, q)


def test_null_probe_keys_never_match(d):
    s = _load(d, null_probe_keys=True)
    q = ("select count(*) from li, orders where l_orderkey = o_orderkey")
    _parity(s, q)


def test_non_unique_build_key_not_planned_as_device_join(d):
    """No PK/unique index on the build key -> planner keeps the root hash
    join (uniqueness is a hard requirement for the lookup join)."""
    s = _load(d)
    s.execute("create table dup_dim (k bigint, v bigint)")
    s.execute("insert into dup_dim values (1, 10), (1, 20), (2, 30)")
    s.execute("insert into li values (1, 5000.00, 0.00, '1995-06-01')")
    q = ("select count(*), sum(v) from li, dup_dim where l_orderkey = k")
    ops = _plan_ops(s, q)
    assert not any("DeviceJoinReader" in op for op in ops), ops
    _parity(s, q)


def test_merge_join_preference_overrides_device_join(d):
    s = _load(d)
    q = ("select count(*) from li, orders where l_orderkey = o_orderkey")
    s.execute("set tidb_opt_prefer_merge_join = 1")
    try:
        ops = _plan_ops(s, q)
        assert not any("DeviceJoinReader" in op for op in ops), ops
        assert any("MergeJoin" in op for op in ops), ops
        _parity(s, q)
    finally:
        s.execute("set tidb_opt_prefer_merge_join = 0")


def test_uniqueness_through_filtered_build(d):
    """Build side with its own filter keeps key uniqueness (Selection
    preserves it) and still device-joins."""
    s = _load(d)
    q = ("select count(*) from li, orders where l_orderkey = o_orderkey"
         " and o_orderdate >= '1994-06-01' and o_shippriority = 1")
    assert any("DeviceJoinReader" in op for op in _plan_ops(s, q))
    _parity(s, q)


def test_explain_analyze_runs(d):
    s = _load(d)
    rows = s.execute("explain analyze " + Q3)[0].rows
    assert any("DeviceJoinReader" in r[0] for r in rows)
