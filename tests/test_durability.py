"""Durability + recovery: load, kill, reopen, query — identical results.

Reference model (SURVEY.md §3.4): durable state lives in the store; a
restarting node reloads and serves.  Prewrite locks are volatile by design —
a crash aborts in-flight transactions via lock absence.
"""

import numpy as np
import pytest

from tidb_tpu.session import Domain


@pytest.fixture()
def data_dir(tmp_path):
    return str(tmp_path / "data")


def _fresh(data_dir):
    return Domain(data_dir=data_dir).new_session()


def test_restart_preserves_rows(data_dir):
    s = _fresh(data_dir)
    s.execute("create table t (a bigint, b double, s varchar(10), d date)")
    s.execute("insert into t values (1, 1.5, 'x', '2020-01-01'), "
              "(2, null, null, null), (3, 3.5, 'héllo', '1999-12-31')")
    before = s.query("select * from t order by a")
    del s  # no clean shutdown: durability must not rely on one

    s2 = _fresh(data_dir)
    assert s2.query("select * from t order by a") == before
    # and the reloaded store keeps working: DML + read-your-writes
    s2.execute("insert into t values (4, 4.5, 'y', '2021-06-01')")
    assert s2.query("select count(*) from t") == [(4,)]

    s3 = _fresh(data_dir)
    assert s3.query("select count(*) from t") == [(4,)]


def test_restart_preserves_bulk_base_and_delta(data_dir):
    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table big (k bigint, v double)")
    t = d.catalog.info_schema().table("test", "big")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(0)
    store.bulk_load_arrays(
        [np.arange(5000, dtype=np.int64), rng.uniform(0, 1, 5000)],
        ts=d.storage.current_ts(),
    )
    s.execute("update big set v = 99.0 where k = 17")   # delta put
    s.execute("delete from big where k >= 4990")        # delta deletes
    expect_cnt = s.query("select count(*), sum(k) from big")
    expect_17 = s.query("select v from big where k = 17")

    s2 = _fresh(data_dir)
    assert s2.query("select count(*), sum(k) from big") == expect_cnt
    assert s2.query("select v from big where k = 17") == expect_17


def test_restart_after_compact(data_dir):
    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint, s varchar(8))")
    s.execute("insert into t values (1, 'aa'), (2, 'bb'), (3, 'cc')")
    s.execute("update t set s = 'zz' where a = 2")
    t = d.catalog.info_schema().table("test", "t")
    d.storage.maybe_compact(t.id, threshold=0)  # folds delta, rewrites base
    before = s.query("select * from t order by a")

    s2 = _fresh(data_dir)
    assert s2.query("select * from t order by a") == before


def test_uncommitted_txn_lost_on_restart(data_dir):
    """Percolator semantics: prewrite locks are volatile; a crash mid-txn
    aborts it."""
    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1)")
    s.execute("begin")
    s.execute("insert into t values (2)")
    # no commit: process "dies"
    s2 = _fresh(data_dir)
    assert s2.query("select a from t") == [(1,)]


def test_dml_then_bulk_load_keeps_both(data_dir):
    """A bulk load after committed DML must not drop the DML rows: the
    base snapshot rewrite re-emits the in-memory delta log."""
    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1), (2)")
    t = d.catalog.info_schema().table("test", "t")
    d.storage.table(t.id).bulk_load_arrays(
        [np.array([10, 11], dtype=np.int64)], ts=d.storage.current_ts())
    before = sorted(s.query("select a from t"))
    s2 = _fresh(data_dir)
    assert sorted(s2.query("select a from t")) == before == \
        [(1,), (2,), (10,), (11,)]


def test_alter_table_survives_restart(data_dir):
    s = _fresh(data_dir)
    s.execute("create table t (a bigint, b bigint)")
    s.execute("insert into t values (1, 10), (2, 20)")
    s.execute("alter table t add column c varchar(4) default 'x'")
    s.execute("alter table t drop column b")
    before = s.query("select * from t order by a")
    s2 = _fresh(data_dir)
    assert s2.query("select * from t order by a") == before


def test_injected_storage_with_data_dir_rejected(tmp_path):
    from tidb_tpu.store.storage import BlockStorage

    with pytest.raises(ValueError):
        Domain(storage=BlockStorage(), data_dir=str(tmp_path))


def test_drop_table_keeps_files_until_gc(data_dir):
    """DROP TABLE detaches into the recycle bin (RECOVER TABLE flashback
    source); the GC worker destroys the files after gc_life — the
    reference's delete-range task timing."""
    import os
    import time

    s = _fresh(data_dir)
    d = s.domain
    d.maintenance.stop()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1)")
    tdir = os.path.join(data_dir, "tables")
    assert os.listdir(tdir)
    s.execute("drop table t")
    # data survives the drop (flashback window)...
    s.execute("recover table t")
    assert s.query("select * from t") == [(1,)]
    s.execute("drop table t")
    # ...until GC passes the retention window
    d.global_vars["tidb_gc_life_time"] = "0"
    time.sleep(0.01)
    d.maintenance.tick()
    assert not any(f.endswith((".npz", ".log")) for f in os.listdir(tdir))

    s2 = _fresh(data_dir)
    import tidb_tpu.errors as errs

    with pytest.raises(errs.TiDBTPUError):
        s2.query("select * from t")


def test_committed_txn_survives_hard_kill(data_dir):
    """kill -9 analog: a txn whose COMMIT returned must be on disk at that
    instant — no later flush, close, or GC hook may be required.  We freeze
    the table files right after commit and restore them over whatever the
    dying process left behind."""
    import os
    import shutil

    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("begin")
    s.execute("insert into t values (7)")
    s.execute("commit")
    # snapshot the on-disk state as of commit-return
    frozen = str(data_dir) + ".frozen"
    shutil.copytree(data_dir, frozen)
    # the process "dies" here; reopen from the frozen-at-commit state
    shutil.rmtree(data_dir)
    shutil.copytree(frozen, data_dir)
    s2 = _fresh(data_dir)
    assert s2.query("select a from t") == [(7,)]


def _delta_path(data_dir, d, name="t"):
    import os

    tid = d.catalog.info_schema().table("test", name).id
    return os.path.join(data_dir, "tables", f"t{tid}.delta.log")


def test_torn_delta_tail_recovers_at_random_kill_offsets(data_dir, tmp_path):
    """Crash-hardened recovery: the writer dies mid-append at an arbitrary
    byte offset — recovery drops the torn final record with a warning +
    metric instead of crashing in json.loads, and keeps every fully
    synced record (leveldb WAL torn-tail semantics)."""
    import os
    import shutil

    from tidb_tpu.metrics import REGISTRY

    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint, s varchar(8))")
    for i in range(6):
        s.execute(f"insert into t values ({i}, 'r{i}')")
    path = _delta_path(data_dir, d)
    raw = open(path, "rb").read()
    line_ends = [i + 1 for i, b in enumerate(raw) if b == 0x0A]
    assert len(line_ends) == 6
    del d, s  # no clean shutdown

    rng = np.random.default_rng(11)
    offsets = sorted({int(o) for o in rng.integers(line_ends[0], len(raw), 8)})
    for cut in offsets:
        work = str(tmp_path / f"cut{cut}")
        shutil.copytree(data_dir, work)
        wpath = os.path.join(work, "tables", os.path.basename(path))
        with open(wpath, "r+b") as f:
            f.truncate(cut)
        # oracle: a record survives iff its JSON line is complete in the
        # truncated file (a cut that only eats the trailing newline keeps
        # the record — the payload itself is intact)
        import json

        complete, torn = 0, 0
        for ln in raw[:cut].decode().splitlines():
            if not ln.strip():
                continue
            try:
                json.loads(ln)
                complete += 1
            except ValueError:
                torn = 1
                break
        before = REGISTRY.snapshot().get("delta_log_torn_tail_total", 0)
        s2 = Domain(data_dir=work).new_session()
        assert s2.query("select count(*) from t") == [(complete,)], cut
        after = REGISTRY.snapshot().get("delta_log_torn_tail_total", 0)
        assert after - before == torn, cut
        # recovered store keeps accepting writes, and recovery REPAIRED
        # the log (truncated the torn bytes): a post-recovery commit must
        # not concatenate onto the torn fragment and vanish (or corrupt
        # the log) on the NEXT reopen
        s2.execute("insert into t values (99, 'post')")
        assert s2.query("select count(*) from t") == [(complete + 1,)]
        del s2
        s3 = Domain(data_dir=work).new_session()
        assert s3.query("select count(*) from t") == [(complete + 1,)], cut
        assert s3.query("select s from t where a = 99") == [("post",)]


def test_corrupt_delta_mid_file_is_not_silently_dropped(data_dir):
    """Only the FINAL record may be torn (crash truncation clips the end);
    garbage in the middle is real corruption and must surface loudly
    instead of silently losing committed rows."""
    from tidb_tpu.store.persist import CorruptDeltaLogError

    d = Domain(data_dir=data_dir)
    s = d.new_session()
    s.execute("create table t (a bigint)")
    for i in range(3):
        s.execute(f"insert into t values ({i})")
    path = _delta_path(data_dir, d)
    lines = open(path, "rb").read().splitlines(keepends=True)
    lines[1] = b"{garbage!!\n"
    with open(path, "wb") as f:
        f.writelines(lines)
    del d, s
    with pytest.raises(CorruptDeltaLogError):
        Domain(data_dir=data_dir)
