"""Expression engine tests — vectorized eval + NULL semantics.

Reference model: expression/builtin_*_test.go and evaluator_test.go.
"""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk, Column, chunk_from_pylists
from tidb_tpu.expr import ColumnExpr, Constant, ScalarFunc, eval_expr, eval_bool_mask
from tidb_tpu.expr.builtins import infer_ftype
from tidb_tpu.types import (
    TypeKind,
    parse_date,
    ty_date,
    ty_decimal,
    ty_float,
    ty_int,
    ty_string,
)


def col(i, ft, name="c"):
    return ColumnExpr(i, ft, name)


def lit(v, ft):
    return Constant(v, ft)


def fn(name, *args, meta=None, ftype=None):
    meta = meta or {}
    if ftype is None:
        ftype = infer_ftype(name, [a.ftype for a in args], meta)
    return ScalarFunc(name, list(args), ftype, meta)


@pytest.fixture
def chk():
    return chunk_from_pylists(
        [ty_int(), ty_float(), ty_int(), ty_string()],
        [
            [1, 2, None, 4],
            [1.5, None, 3.5, -2.0],
            [10, 20, 30, 40],
            ["apple", "Banana", None, "cherry"],
        ],
    )


def test_add_int(chk):
    e = fn("+", col(0, ty_int()), col(2, ty_int()))
    out = eval_expr(e, chk)
    assert out.to_pylist() == [11, 22, None, 44]


def test_mixed_float(chk):
    e = fn("*", col(0, ty_int()), col(1, ty_float()))
    assert eval_expr(e, chk).to_pylist() == [1.5, None, None, -8.0]


def test_division_by_zero_yields_null(chk):
    e = fn("/", col(0, ty_int()), lit(0, ty_int()))
    out = eval_expr(e, chk)
    assert out.to_pylist() == [None, None, None, None]
    e2 = fn("div", lit(7, ty_int()), lit(2, ty_int()))
    assert eval_expr(e2, chk).to_pylist() == [3, 3, 3, 3]
    e3 = fn("div", lit(-7, ty_int()), lit(2, ty_int()))
    assert eval_expr(e3, chk).to_pylist() == [-3] * 4  # truncates toward zero


def test_int_div_decimal_result(chk):
    e = fn("/", lit(7, ty_int()), lit(2, ty_int()))
    out = eval_expr(e, chk)
    assert out.ftype.kind == TypeKind.DECIMAL and out.ftype.scale == 4
    assert out.to_pylist()[0] == 35000  # 3.5000 scaled


def test_decimal_arith():
    chk = chunk_from_pylists(
        [ty_decimal(10, 2), ty_decimal(10, 2)], [[150, 299], [100, -50]]
    )  # 1.50, 2.99 ; 1.00, -0.50
    add = fn("+", col(0, ty_decimal(10, 2)), col(1, ty_decimal(10, 2)))
    assert eval_expr(add, chk).to_pylist() == [250, 249]
    mul = fn("*", col(0, ty_decimal(10, 2)), col(1, ty_decimal(10, 2)))
    out = eval_expr(mul, chk)
    assert out.ftype.scale == 4
    assert out.to_pylist() == [15000, -14950]  # 1.5000, -1.4950


def test_comparisons_and_mask(chk):
    pred = fn(">", col(0, ty_int()), lit(1, ty_int()))
    mask = eval_bool_mask([pred], chk)
    assert mask.tolist() == [False, True, False, True]  # NULL -> False


def test_three_valued_logic():
    chk = chunk_from_pylists([ty_int(), ty_int()], [[1, 0, None], [None, 0, None]])
    a, b = col(0, ty_int()), col(1, ty_int())
    res_and = eval_expr(fn("and", a, b), chk)
    assert res_and.to_pylist() == [None, 0, None]
    res_or = eval_expr(fn("or", a, b), chk)
    assert res_or.to_pylist() == [1, 0, None]
    # false AND null = false; true OR null = true
    chk2 = chunk_from_pylists([ty_int(), ty_int()], [[0, 1], [None, None]])
    assert eval_expr(fn("and", col(0, ty_int()), col(1, ty_int())), chk2).to_pylist() == [0, None]
    assert eval_expr(fn("or", col(0, ty_int()), col(1, ty_int())), chk2).to_pylist() == [None, 1]


def test_is_null(chk):
    e = fn("isnull", col(0, ty_int()))
    assert eval_expr(e, chk).to_pylist() == [0, 0, 1, 0]


def test_in_with_nulls():
    chk = chunk_from_pylists([ty_int()], [[1, 5, None]])
    e = fn("in", col(0, ty_int()), lit(1, ty_int()), lit(2, ty_int()))
    assert eval_expr(e, chk).to_pylist() == [1, 0, None]
    # no match + null item -> NULL
    e2 = fn("in", col(0, ty_int()), lit(2, ty_int()), lit(None, ty_int()))
    assert eval_expr(e2, chk).to_pylist() == [None, None, None]


def test_like(chk):
    e = fn("like", col(3, ty_string()), lit("%an%", ty_string()))
    assert eval_expr(e, chk).to_pylist() == [0, 1, None, 0]
    e2 = fn("like", col(3, ty_string()), lit("_pple", ty_string()))
    assert eval_expr(e2, chk).to_pylist() == [1, 0, None, 0]


def test_case_when(chk):
    e = fn(
        "case",
        fn(">", col(0, ty_int()), lit(1, ty_int())), lit("big", ty_string()),
        lit("small", ty_string()),
    )
    assert eval_expr(e, chk).to_pylist() == ["small", "big", "small", "big"]


def test_if_ifnull_coalesce(chk):
    e = fn("ifnull", col(0, ty_int()), lit(-1, ty_int()))
    assert eval_expr(e, chk).to_pylist() == [1, 2, -1, 4]
    e2 = fn("coalesce", col(0, ty_int()), col(2, ty_int()))
    assert eval_expr(e2, chk).to_pylist() == [1, 2, 30, 4]
    e3 = fn("if", fn("isnull", col(0, ty_int())), lit(0, ty_int()), col(0, ty_int()))
    assert eval_expr(e3, chk).to_pylist() == [1, 2, 0, 4]


def test_string_funcs(chk):
    e = fn("upper", col(3, ty_string()))
    assert eval_expr(e, chk).to_pylist() == ["APPLE", "BANANA", None, "CHERRY"]
    e2 = fn("substring", col(3, ty_string()), lit(2, ty_int()), lit(3, ty_int()))
    assert eval_expr(e2, chk).to_pylist() == ["ppl", "ana", None, "her"]
    e3 = fn("concat", col(3, ty_string()), lit("!", ty_string()))
    assert eval_expr(e3, chk).to_pylist() == ["apple!", "Banana!", None, "cherry!"]
    e4 = fn("length", col(3, ty_string()))
    assert eval_expr(e4, chk).to_pylist() == [5, 6, None, 6]


def test_cast(chk):
    e = fn("cast", col(1, ty_float()), meta={"target": ty_int()})
    assert eval_expr(e, chk).to_pylist() == [2, None, 4, -2]
    e2 = fn("cast", col(0, ty_int()), meta={"target": ty_string()})
    assert eval_expr(e2, chk).to_pylist() == ["1", "2", None, "4"]
    e3 = fn("cast", lit("12.7", ty_string()), meta={"target": ty_decimal(10, 1)})
    assert eval_expr(e3, chk).to_pylist() == [127] * 4


def test_temporal():
    d0 = parse_date("1998-09-02")
    chk = chunk_from_pylists([ty_date()], [[d0, d0 + 120, None]])
    assert eval_expr(fn("year", col(0, ty_date())), chk).to_pylist() == [1998, 1998, None]
    assert eval_expr(fn("month", col(0, ty_date())), chk).to_pylist() == [9, 12, None]
    assert eval_expr(fn("dayofmonth", col(0, ty_date())), chk).to_pylist() == [2, 31, None]
    e = fn("date_add", col(0, ty_date()), lit(1, ty_int()), meta={"unit": "year"})
    out = eval_expr(e, chk)
    assert out.to_pylist()[0] == parse_date("1999-09-02")
    e2 = fn("date_sub", col(0, ty_date()), lit(108, ty_int()), meta={"unit": "day"})
    assert eval_expr(e2, chk).to_pylist()[0] == parse_date("1998-05-17")
    e3 = fn("datediff", col(0, ty_date()), col(0, ty_date()))
    assert eval_expr(e3, chk).to_pylist() == [0, 0, None]


def test_math():
    chk = chunk_from_pylists([ty_float()], [[4.0, 2.25, -1.0]])
    assert eval_expr(fn("sqrt", col(0, ty_float())), chk).to_pylist() == [2.0, 1.5, None]
    assert eval_expr(fn("abs", col(0, ty_float())), chk).to_pylist() == [4.0, 2.25, 1.0]
    assert eval_expr(fn("floor", col(0, ty_float())), chk).to_pylist() == [4, 2, -1]
    assert eval_expr(fn("ceil", col(0, ty_float())), chk).to_pylist() == [4, 3, -1]
    r = eval_expr(fn("round", lit(2.675, ty_float()), lit(2, ty_int()), meta={"digits": 2}), chk)
    assert r.to_pylist()[0] == pytest.approx(2.68)


def test_pushdown_registry():
    from tidb_tpu.expr.pushdown import can_push_expr

    e = fn("+", col(0, ty_int()), lit(1, ty_int()))
    assert can_push_expr(e)
    s = fn("upper", col(0, ty_string(), "s"))
    assert not can_push_expr(s)
    # string equality pushable only when dict-encoded
    eq = fn("=", col(0, ty_string(), "s"), lit("x", ty_string()))
    assert not can_push_expr(eq)
    assert can_push_expr(eq, dict_cols={0})
    assert not can_push_expr(e, blacklist={"+"})
