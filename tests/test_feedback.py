"""Statistics query feedback + NDV join cardinality.

Reference: statistics/feedback.go:51 (collect), handle/update.go:411-489
(apply); join output estimation from key NDVs (System-R containment)."""

import numpy as np
import pytest

from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    return Domain()


def _est_of(s, q, op_prefix):
    rows = s.execute("explain " + q)[0].rows
    for r in rows:
        if r[0].lstrip(" └─").startswith(op_prefix):
            return float(r[1])
    raise AssertionError(f"no {op_prefix} in plan: {rows}")


def test_feedback_learns_true_selectivity(d):
    s = d.new_session()
    s.execute("create table f (a bigint, b bigint)")
    t = d.catalog.info_schema().table("test", "f")
    n = 20000
    # b correlates perfectly with a: independence assumption is ~100x off
    a = np.repeat(np.arange(100), n // 100)
    d.storage.table(t.id).bulk_load_arrays([a, a.copy()],
                                           ts=d.storage.current_ts())
    s.execute("analyze table f")
    q = "select * from f where a = 7 and b = 7"
    est0 = _est_of(s, q, "TableReader")
    actual = n // 100  # 200 rows (perfect correlation)
    # independence says ~1% of 1% = 2 rows: badly off
    assert est0 < actual / 10
    rows = s.query(q)
    assert len(rows) == actual
    est1 = _est_of(s, q, "TableReader")
    assert abs(est1 - actual) / actual < 0.35  # converged after one run
    s.query(q)
    est2 = _est_of(s, q, "TableReader")
    assert abs(est2 - actual) / actual < 0.15
    # ANALYZE resets learned corrections (fresh stats supersede)
    s.execute("analyze table f")
    assert not d.stats.feedback.snapshot()


def test_feedback_ignores_partial_drains(d):
    """LIMIT stops the scan early; the truncated count must NOT poison
    the learned selectivity."""
    s = d.new_session()
    s.execute("create table g (a bigint)")
    t = d.catalog.info_schema().table("test", "g")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(10000, dtype=np.int64)], ts=d.storage.current_ts())
    s.execute("analyze table g")
    s.query("select * from g where a >= 0 limit 5")
    fb = d.stats.feedback.snapshot()
    assert not fb, fb  # nothing learned from the truncated scan


def test_join_cardinality_uses_key_ndv(d):
    """FK join: |L ⋈ R| ≈ |L| when the build key is near-unique; a
    low-NDV key multiplies out instead of max(l, r)."""
    s = d.new_session()
    s.execute("create table fact (k bigint, v bigint)")
    s.execute("create table dim (k bigint, w bigint)")
    tf = d.catalog.info_schema().table("test", "fact")
    td = d.catalog.info_schema().table("test", "dim")
    rng = np.random.default_rng(9)
    n_f, n_d = 20000, 50
    d.storage.table(tf.id).bulk_load_arrays(
        [rng.integers(0, n_d, n_f), rng.integers(0, 10, n_f)],
        ts=d.storage.current_ts())
    d.storage.table(td.id).bulk_load_arrays(
        [np.arange(n_d, dtype=np.int64), np.arange(n_d, dtype=np.int64)],
        ts=d.storage.current_ts())
    s.execute("analyze table fact")
    s.execute("analyze table dim")
    # dim.k has 50 distinct, fact.k has 50 distinct -> est = f*d/50 = f
    q = "select fact.v, dim.w from fact join dim on fact.k = dim.k"
    est = _est_of(s, q, "HashJoin")
    assert 0.5 * n_f <= est <= 2 * n_f, est
    actual = len(s.query(q))
    assert actual == n_f


def test_learned_selectivity_flips_join_build_side(d):
    """The hash join builds from the smaller side; a correlated predicate
    the histogram overestimates keeps the wrong side until feedback
    teaches the planner the true row count — then the build side flips."""
    s = d.new_session()
    s.execute("create table l (k bigint, a bigint, b bigint)")
    s.execute("create table r (k bigint, w bigint)")
    tl = d.catalog.info_schema().table("test", "l")
    tr = d.catalog.info_schema().table("test", "r")
    rng = np.random.default_rng(4)
    n_l, n_r = 30000, 3000
    av = np.repeat(np.arange(5), n_l // 5)  # a=3&b=3 truly keeps 6000 rows
    d.storage.table(tl.id).bulk_load_arrays(
        [rng.integers(0, 1000, n_l), av, av.copy()],
        ts=d.storage.current_ts())
    d.storage.table(tr.id).bulk_load_arrays(
        [rng.integers(0, 1000, n_r), rng.integers(0, 5, n_r)],
        ts=d.storage.current_ts())
    s.execute("analyze table l")
    s.execute("analyze table r")
    # independence says a=3 AND b=3 keeps ~1200 of 30000 rows -> l looks
    # smaller than r (3000) and becomes the build side.  Truth: 6000.
    q = ("select l.k, r.w from l join r on l.k = r.k"
         " where l.a = 3 and l.b = 3")

    def build_side():
        for row in s.execute("explain " + q)[0].rows:
            if "HashJoin" in row[0]:
                return "build:right" if "build:right" in row[3] else \
                    "build:left"
        raise AssertionError("no hash join in plan")

    first = build_side()
    # teach the planner: run the filter part so the scan records feedback
    s.query("select * from l where a = 3 and b = 3")
    second = build_side()
    assert first != second, (first, second)
    # and the joined result is still correct through both plans
    assert len(s.query(q)) == len(s.query(
        "select /*+ anything */ l.k, r.w from l join r on l.k = r.k"
        " where l.a = 3 and l.b = 3"))
