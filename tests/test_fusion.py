"""Whole-fragment kernel fusion (copr/fusion.py): parity + span counts.

The fusion contract (ISSUE 7 acceptance):

- every fragment shape — filter-only, filter+project, dense agg, scalar
  agg, sort agg, topN, IN-lists, delta-overlay fallback, MPP-fused —
  returns results identical to the CPU oracle;
- steady-state fragments execute as exactly ONE XLA launch per mesh
  dispatch: one `copr.device.execute` span, one packed `copr.readback`,
  zero intermediate host readbacks;
- multi-range fragments run in the same single dispatch (range bounds
  are runtime slots, not program shape) and share one compiled program
  with single-range fragments;
- the chaos site `copr/fusion_split` forces the region splitter to cut
  at every executor boundary in turn and parity still holds (the host
  tail interprets the peeled suffix — never fail the query).
"""

import numpy as np
import pytest

from tidb_tpu.copr.jax_eval import JaxUnsupported
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import failpoint

N = 20_000


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table ft (k bigint primary key, g bigint, x double,"
              " c varchar(8), j bigint)")
    rng = np.random.default_rng(23)
    t = d.catalog.info_schema().table("test", "ft")
    tags = np.array([f"t{i:02d}" for i in range(12)], dtype=object)
    d.storage.table(t.id).bulk_load_arrays([
        np.arange(N, dtype=np.int64),
        rng.integers(0, 5, N, dtype=np.int64),
        rng.uniform(0, 100, N),
        tags[rng.integers(0, 12, N)],
        rng.integers(0, 9000, N, dtype=np.int64),  # join key (see MPP test)
    ], ts=d.storage.current_ts())
    s.execute("analyze table ft")
    return s


CORPUS = (
    # filter-only
    "select k from ft where x < 20",
    # filter + device projection
    "select k, x * 2 + 1 from ft where x < 20",
    # dense agg (group keys with known small cardinality)
    "select g, sum(x), count(*), min(x), max(x), avg(x) from ft group by g",
    # scalar agg
    "select sum(x), count(*) from ft where k < 15000",
    # sort-mode agg (float group key: dense codes would truncate)
    "select g, min(k) from ft where x < 60 group by g, c",
    # topn
    "select k, x from ft order by x desc limit 7",
    # IN-list (pow2-bucketed hoisted slots)
    "select count(*) from ft where g in (1, 2, 3)",
    # string dict predicate + agg
    "select count(*), sum(x) from ft where c = 't03'",
)


def _cpu(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _approx_rows(got, want, ctx=""):
    assert len(got) == len(want), (ctx, len(got), len(want))
    for ra, rb in zip(sorted(got, key=str), sorted(want, key=str)):
        for a, b in zip(ra, rb):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (ctx, ra, rb)
            else:
                assert a == b, (ctx, ra, rb)


def _spans(tr, name):
    out = []

    def walk(s):
        if s.name == name:
            out.append(s)
        for c in s.children:
            walk(c)

    walk(tr.root)
    return out


# ---------------------------------------------------------------------------
# fused-vs-oracle parity across the corpus
# ---------------------------------------------------------------------------


def test_fused_corpus_parity(sess):
    sess.execute("set tidb_use_tpu = 1")
    for sql in CORPUS:
        _approx_rows(sess.query(sql), _cpu(sess, sql), sql)


def test_fused_parity_with_delta_overlay(sess):
    """Committed delta rows ride the CPU interpreter and merge with the
    fused base scan — parity must hold across the overlay."""
    sess.execute("insert into ft values (20001, 1, 50.5, 't01', 11),"
                 " (20002, 4, 3.25, 't07', 222)")
    sess.execute("delete from ft where k = 7")
    try:
        for sql in CORPUS:
            _approx_rows(sess.query(sql), _cpu(sess, sql), f"delta: {sql}")
    finally:
        sess.execute("delete from ft where k > 20000")
        sess.execute("insert into ft values (7, 2, 41.5, 't05', 7)")


# ---------------------------------------------------------------------------
# span-count invariants: one XLA launch per mesh dispatch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sql", [
    "select g, sum(x), count(*), avg(x) from ft group by g",   # Q1 shape
    "select sum(x) from ft where x < 50 and k < 18000",        # Q6 shape
])
def test_steady_state_is_one_device_execute_span(sess, sql):
    sess.execute("set tidb_use_tpu = 1")
    sess.query(sql)            # warm: compile + transfer
    sess.query(sql)            # steady state
    tr = sess.last_trace
    exe = _spans(tr, "copr.device.execute")
    assert len(exe) == 1, [s.name for s in exe]
    # zero intermediate host readbacks: ONE packed readback carries the
    # whole result, nothing crosses the link between fused phases
    rb = _spans(tr, "copr.readback")
    assert len(rb) == 1, len(rb)
    # steady state hits the program cache (no recompiles)
    hits = [s for s in _spans(tr, "copr.compile")
            if (s.attrs or {}).get("cache") == "hit"]
    assert hits
    # ... and no transfers: scan data is device-resident
    assert not _spans(tr, "copr.transfer")


def test_multirange_single_dispatch_shares_program(sess):
    """A 3-range request runs in the SAME single fused dispatch and the
    SAME compiled program as a 1-range one (range bounds are runtime
    parameter slots, never program shape)."""
    from tidb_tpu.copr import parallel as pl
    from tidb_tpu.copr.ir import DAG
    from tidb_tpu.parser import parse_one
    from tidb_tpu.store.kv import CopRequest, KeyRange

    d = sess.domain
    t = d.catalog.info_schema().table("test", "ft")
    store = d.storage.table(t.id)
    phys = sess._plan(parse_one("select sum(x), count(*) from ft"))

    def find_dag(p):
        if getattr(p, "dag", None) is not None:
            return p.dag
        for c in getattr(p, "children", ()) or ():
            r = find_dag(c)
            if r is not None:
                return r
        return None

    dag = find_dag(phys).to_dict()
    ts = d.storage.current_ts()
    spans3 = [(0, 3000), (7000, 7500), (12000, N)]

    def run(ranges):
        req = CopRequest(
            dag=dag, ranges=[KeyRange(t.id, a, b) for a, b in ranges],
            ts=ts, concurrency=1, keep_order=False, streaming=False,
            engine="tpu")
        out = pl.try_run_mesh(d.storage, req)
        assert out is not None, getattr(req, "mesh_reject_reason", None)
        chunks = list(out)
        assert len(chunks) == 1
        c = chunks[0]
        # partial-agg layout: [sum state, count state]
        return float(c.col(0).data[0]), int(c.col(1).data[0])

    x = np.asarray(store.base_chunk([2], 0, store.base_rows).col(0).data)
    deleted, inserted = store.delta_overlay(ts, 0, 1 << 62)

    def expected(ranges):
        tot, cnt = 0.0, 0
        for a, b in ranges:
            bb = min(b, store.base_rows)
            if a < bb:
                idx = np.arange(a, bb)
                keep = ~np.isin(idx, sorted(deleted))
                tot += float(x[a:bb][keep].sum())
                cnt += int(keep.sum())
            for h, row in inserted.items():
                if a <= h < b:
                    tot += float(row[2])
                    cnt += 1
        return tot, cnt

    s1, c1 = run([(0, N)])
    n0 = len(pl._COMPILED)
    s3, c3 = run(spans3)
    assert len(pl._COMPILED) == n0, \
        "range-count change recompiled the fused program"
    w3, n3 = expected(spans3)
    assert s3 == pytest.approx(w3) and c3 == n3
    w1, n1 = expected([(0, N)])
    assert s1 == pytest.approx(w1) and c1 == n1


# ---------------------------------------------------------------------------
# the fallback ladder: chaos-split at every region boundary
# ---------------------------------------------------------------------------


def test_chaos_split_at_every_boundary_keeps_parity(sess):
    """Force the splitter to cut the fused region at each executor
    boundary in turn: the host tail serves the peeled suffix with
    identical results, and the query NEVER fails."""
    sess.execute("set tidb_use_tpu = 1")
    want = {sql: _cpu(sess, sql) for sql in CORPUS}
    for cut_at in (2, 3, 4):
        def force_split(cut=None, boundary=None, _at=cut_at, **ctx):
            if cut is not None and cut >= _at:
                raise JaxUnsupported(f"chaos split at cut {cut}")

        with failpoint("copr/fusion_split", force_split):
            for sql in CORPUS:
                _approx_rows(sess.query(sql), want[sql],
                             f"split@{cut_at}: {sql}")


def test_split_region_runs_device_head_plus_host_tail(sess):
    """A forced split below the aggregation leaves scan+selection fused
    on device and interprets the agg host-side: fusion_splits_total
    grows and results match."""
    sql = "select g, sum(x), count(*) from ft where x < 30 group by g"
    want = _cpu(sess, sql)

    def split_below_agg(cut=None, boundary=None, **ctx):
        if boundary == "AggregationIR":
            raise JaxUnsupported("chaos: agg unfusable")

    s0 = REGISTRY.get("fusion_splits_total")
    with failpoint("copr/fusion_split", split_below_agg):
        got = sess.query(sql)
    _approx_rows(got, want, sql)
    assert REGISTRY.get("fusion_splits_total") > s0


def test_plan_regions_ladder_unit(sess):
    """plan_regions peels an unfusable suffix and keeps scan-layout
    heads only; an all-unfusable fragment raises with the reason."""
    from tidb_tpu.copr.fusion import plan_regions
    from tidb_tpu.copr.ir import DAG
    from tidb_tpu.planner import build  # noqa: F401  (plan machinery)

    d = sess.domain
    t = d.catalog.info_schema().table("test", "ft")
    table = d.storage.table(t.id)
    phys = sess._plan(__import__("tidb_tpu.parser", fromlist=["parse_one"])
                      .parse_one(
        "select g, sum(x) from ft where x < 30 group by g"))

    def dags(p, acc):
        if getattr(p, "dag", None) is not None:
            acc.append(p.dag)
        for c in getattr(p, "children", ()) or ():
            dags(c, acc)
        return acc

    dag = DAG.from_dict(dags(phys, [])[0].to_dict())
    plan = plan_regions(dag, table)
    assert not plan.tail  # fully fused
    # force a split below the agg: head must be scan+selection shaped
    def split(cut=None, boundary=None, **ctx):
        if boundary == "AggregationIR":
            raise JaxUnsupported("forced")

    with failpoint("copr/fusion_split", split):
        plan = plan_regions(dag, table)
    assert plan.tail and plan.an.agg is None
    assert plan.split_reason


# ---------------------------------------------------------------------------
# MPP-fused fragments
# ---------------------------------------------------------------------------


def test_mpp_fused_join_parity_and_span(sess):
    """An MPP shuffle join (scan+filter+exchange+join+partial agg) is
    ONE fused program: parity vs the host hash join and a single
    copr.device.execute inside the mpp.exchange span."""
    d = sess.domain
    sess.execute("create table fo (o_key bigint primary key, o_w double)")
    t = d.catalog.info_schema().table("test", "fo")
    rng = np.random.default_rng(5)
    n_o = 3000
    d.storage.table(t.id).bulk_load_arrays([
        np.arange(n_o, dtype=np.int64),
        rng.uniform(0, 10, n_o),
    ], ts=d.storage.current_ts())
    sess.execute("analyze table fo")
    sql = ("select count(*), sum(x) from ft join fo on j = o_key"
           " where x < 80")
    # (j in [0, 9000), o_key in [0, 3000): ~1/3 of probe rows match;
    # host oracle = allow_mpp off)
    sess.execute("set tidb_use_tpu = 1")
    sess.execute("set tidb_enforce_mpp = 1")
    try:
        m0 = REGISTRY.get("mpp_joins_total")
        got = sess.query(sql)
        served_mpp = REGISTRY.get("mpp_joins_total") > m0
        sess.execute("set tidb_allow_mpp = 0")
        sess.execute("set tidb_enforce_mpp = 0")
        want = sess.query(sql)
        _approx_rows(got, want, sql)
        if served_mpp:
            sess.execute("set tidb_allow_mpp = 1")
            sess.execute("set tidb_enforce_mpp = 1")
            sess.query(sql)
            sess.query(sql)  # steady state
            tr = sess.last_trace
            ex = _spans(tr, "mpp.exchange")
            assert ex, "no exchange span on the MPP rung"
            assert len(_spans(tr, "copr.device.execute")) == 1
    finally:
        sess.execute("set tidb_allow_mpp = 1")
        sess.execute("set tidb_enforce_mpp = 0")


# ---------------------------------------------------------------------------
# serving-layer composition (satellite: LIMIT / IN-list hoisting)
# ---------------------------------------------------------------------------


def test_in_list_lengths_share_program(sess):
    from tidb_tpu.copr import parallel as pl

    sess.execute("set tidb_use_tpu = 1")
    base = "select count(*) from ft where g in ({})"
    sess.query(base.format("0, 1, 2"))   # warm: 3 pads to 4 slots
    n0 = len(pl._COMPILED)
    r4 = sess.query(base.format("1, 2, 3, 4"))
    assert len(pl._COMPILED) == n0, \
        "IN-list length 3 vs 4 compiled two programs"
    _approx_rows(r4, _cpu(sess, base.format("1, 2, 3, 4")), "in4")


def test_microbatch_limits_share_batch_class(sess):
    """`LIMIT 5` and `LIMIT 7` filter statements land in one batch key
    class and return their own exact row counts."""
    from tidb_tpu import serving

    serving.configure(microbatch_window_ms=40.0)
    try:
        import threading

        results = {}

        def run(lim):
            s2 = sess.domain.new_session()
            s2.execute("set tidb_use_tpu = 1")
            results[lim] = s2.query(
                f"select k from ft where x < 90 limit {lim}")

        ts = [threading.Thread(target=run, args=(lim,)) for lim in (5, 7)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(results[5]) == 5 and len(results[7]) == 7
    finally:
        serving.configure(microbatch_window_ms=0.0)


def test_adaptive_window_widens_and_shrinks():
    from tidb_tpu import serving

    serving.configure(microbatch_window_ms=10.0)
    try:
        REGISTRY.set("admission_queue_depth", 0.0)
        idle = serving.effective_window_s()
        assert idle == pytest.approx(0.005)  # shrinks when idle
        REGISTRY.set("admission_queue_depth", 6.0)
        busy = serving.effective_window_s()
        assert busy == pytest.approx(0.040)  # widens under pressure
        REGISTRY.set("admission_queue_depth", 1000.0)
        capped = serving.effective_window_s()
        assert capped == pytest.approx(0.080)  # bounded
        # effective window is exported on /metrics
        assert REGISTRY.get("serving_effective_window_ms") \
            == pytest.approx(80.0)
    finally:
        REGISTRY.set("admission_queue_depth", 0.0)
        serving.configure(microbatch_window_ms=0.0)


# ---------------------------------------------------------------------------
# ISSUE 11 zero-host-tail corpus: computed keys, compound ordering,
# hybrid regions, split-reason labels, the Pallas comparator
# ---------------------------------------------------------------------------

#: shapes that split to a host tail before ISSUE 11 and now fully fuse
HOST_TAIL_CORPUS = (
    # computed string group keys -> device dict-code re-mapping
    "select substr(c, 2, 2), count(*), sum(x) from ft"
    " group by substr(c, 2, 2)",
    "select concat(c, '#'), min(x), max(k) from ft where x < 70"
    " group by concat(c, '#')",
    "select upper(c), count(*) from ft group by upper(c)",
    # multi-column TopN -> packed lexicographic compound key
    "select k, g, x from ft order by g desc, c, k limit 7",
    "select k from ft where x < 50 order by c, k limit 9",
)


def test_host_tail_corpus_fuses_with_parity(sess):
    """The newly-lowered shapes return CPU-oracle results, leave
    fusion_splits_total untouched (zero host tails), and execute as
    exactly ONE copr.device.execute in steady state."""
    sess.execute("set tidb_use_tpu = 1")
    s0 = REGISTRY.get("fusion_splits_total")
    for sql in HOST_TAIL_CORPUS:
        _approx_rows(sess.query(sql), _cpu(sess, sql), sql)
    assert REGISTRY.get("fusion_splits_total") == s0, \
        "a newly-lowered shape still split to a host tail"
    for sql in HOST_TAIL_CORPUS:
        sess.query(sql)
        sess.query(sql)  # steady state
        exe = _spans(sess.last_trace, "copr.device.execute")
        assert len(exe) == 1, (sql, [s.name for s in exe])


def test_host_tail_corpus_vs_unfused_and_pallas_comparators(sess):
    """Parity through BOTH comparators: TIDB_TPU_FUSION=0 (per-tile
    dispatch ladder) and TIDB_TPU_PALLAS=0 (plain-XLA compositions in
    place of the Pallas kernel tier)."""
    import os

    sess.execute("set tidb_use_tpu = 1")
    want = {sql: _cpu(sess, sql) for sql in HOST_TAIL_CORPUS}
    for var in ("TIDB_TPU_FUSION", "TIDB_TPU_PALLAS"):
        prior = os.environ.get(var)
        os.environ[var] = "0"
        try:
            for sql in HOST_TAIL_CORPUS:
                _approx_rows(sess.query(sql), want[sql],
                             f"{var}=0: {sql}")
        finally:
            if prior is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prior


def test_compound_order_split_reason_labelled(sess):
    """An order-by list the packer cannot lower (unbounded float second
    key) still runs — split to a labelled host tail — and the reason
    shows up on the metric, /status-shaped section and
    INFORMATION_SCHEMA.TIDB_TPU_FUSION_SPLITS."""
    sql = "select k from ft where x < 40 order by g, x limit 6"
    want = _cpu(sess, sql)
    r0 = REGISTRY.get("fusion_splits_reason_compound_order_total")
    s0 = REGISTRY.get("fusion_splits_total")
    _approx_rows(sess.query(sql), want, sql)
    assert REGISTRY.get("fusion_splits_total") > s0
    assert REGISTRY.get("fusion_splits_reason_compound_order_total") > r0
    rows = sess.query(
        "select reason, splits from information_schema"
        ".tidb_tpu_fusion_splits")
    by_reason = {r[0]: r[1] for r in rows}
    assert by_reason["compound-order"] >= 1
    assert by_reason["total"] >= sum(
        v for k, v in by_reason.items() if k != "total")


def test_hybrid_projection_head_keeps_device_projection(sess):
    """Hybrid device-partial/host-final regions: a tail AFTER a device
    projection keeps the projection fused (the tail reads the projected
    layout across the boundary) instead of peeling back to scan+sel."""
    import numpy as np

    from tidb_tpu.copr import parallel as pl
    from tidb_tpu.copr.cpu_engine import run_dag_on_chunk
    from tidb_tpu.copr.fusion import plan_regions
    from tidb_tpu.copr.ir import (DAG, ProjectionIR, SelectionIR,
                                  TableScanIR)
    from tidb_tpu.expr.expression import ColumnExpr, Constant, ScalarFunc
    from tidb_tpu.store.kv import CopRequest, KeyRange
    from tidb_tpu.types import FieldType, TypeKind, ty_int

    d = sess.domain
    t = d.catalog.info_schema().table("test", "ft")
    store = d.storage.table(t.id)
    f64 = FieldType(TypeKind.FLOAT)
    i64 = ty_int()
    scan = TableScanIR(t.id, [0, 2], [i64, f64])
    sel = SelectionIR([ScalarFunc(
        "<", [ColumnExpr(1, f64), Constant(30.0, f64)], i64)])
    proj = ProjectionIR([
        ColumnExpr(0, i64),
        ScalarFunc("*", [ColumnExpr(1, f64), Constant(2.0, f64)], f64),
    ])
    # the tail: a selection over the PROJECTED layout (x*2 > 20) — a
    # selection after a projection has no device form, so the splitter
    # must cut here and the head must keep the projection
    tail_sel = SelectionIR([ScalarFunc(
        ">", [ColumnExpr(1, f64), Constant(20.0, f64)], i64)])
    dag = DAG([scan, sel, proj, tail_sel])
    plan = plan_regions(DAG.from_dict(dag.to_dict()), store)
    assert plan.tail and plan.an.projection is not None, \
        "projection peeled out of the hybrid head"
    ts = d.storage.current_ts()
    req = CopRequest(dag=dag.to_dict(),
                     ranges=[KeyRange(t.id, 0, store.base_rows)],
                     ts=ts, concurrency=1, keep_order=False,
                     streaming=False, engine="tpu")
    s0 = REGISTRY.get("fusion_splits_total")
    out = pl.try_run_mesh(d.storage, req)
    assert out is not None, getattr(req, "mesh_reject_reason", None)
    got = [tuple(float(c.col(j).data[i]) for j in range(2))
           for c in out for i in range(c.num_rows)]
    assert REGISTRY.get("fusion_splits_total") > s0
    # oracle: the whole DAG through the CPU interpreter
    base = store.base_chunk([0, 2], 0, store.base_rows)
    ref = run_dag_on_chunk(DAG.from_dict(dag.to_dict()), base)
    want = [tuple(float(ref.col(j).data[i]) for j in range(2))
            for i in range(ref.num_rows)]
    assert sorted(got) == sorted(want)


def test_mesh_agg_overflow_peels_agg_to_host_tail():
    """ROADMAP fusion follow-up (c): a blown sort-agg budget re-enters
    the fused mesh with the AGG peeled to the host tail (scan+selection
    stays device-resident and streamed) instead of dropping the whole
    fragment to the per-tile fan-out rung — parity + mesh_agg_peel
    metric."""
    import os

    from tidb_tpu.session import Domain

    prior = os.environ.get("TIDB_TPU_AGG_OUT")
    os.environ["TIDB_TPU_AGG_OUT"] = "64"
    try:
        d = Domain()
        s = d.new_session()
        s.execute("create table peelt (k bigint, v double, w bigint)")
        t = d.catalog.info_schema().table("test", "peelt")
        rng = np.random.default_rng(5)
        n = 40000
        kvalid = [np.ones(n, np.bool_), None, None]
        kvalid[0][rng.integers(0, n, 500)] = False  # NULLable -> sort agg
        d.storage.table(t.id).bulk_load_arrays(
            [rng.integers(0, 20000, n), rng.uniform(0, 10, n),
             rng.integers(0, 100, n)], kvalid, ts=d.storage.current_ts())
        s.execute("analyze table peelt")
        q = "select k, count(*), sum(v) from peelt where w < 80 group by k"
        m0 = REGISTRY.snapshot().get("mesh_agg_peel_total", 0)
        got = s.query(q)
        assert REGISTRY.snapshot().get("mesh_agg_peel_total", 0) > m0, \
            "sort-agg overflow did not take the agg-peel rung"
        s.execute("set tidb_use_tpu = 0")
        want = s.query(q)
        s.execute("set tidb_use_tpu = 1")

        def key(r):
            return tuple((0, "") if x is None else (1, float(x))
                         for x in r)

        assert sorted(got, key=key) == sorted(want, key=key)
    finally:
        if prior is None:
            os.environ.pop("TIDB_TPU_AGG_OUT", None)
        else:
            os.environ["TIDB_TPU_AGG_OUT"] = prior
