"""Index path tests: point get, index lookup, ranger, delta-merge policy."""

import pytest

from tidb_tpu.session import Domain


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table u (id bigint primary key, name varchar(16), "
              "score double)")
    rows = ",".join(f"({i}, 'n{i % 100}', {i * 1.5})" for i in range(6000))
    s.execute(f"insert into u values {rows}")
    return s


def plan_names(sess, sql):
    return [r[0].strip("└─ ") for r in sess.execute("explain " + sql)[0].rows]


class TestPointGet:
    def test_unique_eq_is_point_get(self, sess):
        names = plan_names(sess, "select name from u where id = 1234")
        assert any("PointGet" in n for n in names)
        assert sess.query("select name from u where id = 1234") == [("n34",)]

    def test_point_get_miss(self, sess):
        assert sess.query("select name from u where id = 99999") == []

    def test_point_get_sees_updates(self, sess):
        sess.execute("update u set score = -1 where id = 10")
        assert sess.query("select score from u where id = 10") == [(-1.0,)]

    def test_point_get_sees_txn_buffer(self, sess):
        sess.execute("begin")
        sess.execute("update u set score = -2 where id = 10")
        assert sess.query("select score from u where id = 10") == [(-2.0,)]
        sess.execute("rollback")
        assert sess.query("select score from u where id = 10") == [(15.0,)]

    def test_point_get_deleted_row(self, sess):
        sess.execute("delete from u where id = 7")
        assert sess.query("select name from u where id = 7") == []


class TestIndexLookUp:
    def test_secondary_index_chosen_with_stats(self, sess):
        sess.execute("create index iname on u (name)")
        sess.execute("analyze table u")
        names = plan_names(sess, "select id from u where name = 'n5'")
        assert any("IndexLookUp" in n for n in names)
        got = sorted(sess.query("select id from u where name = 'n5'"))
        assert got == [(i,) for i in range(5, 6000, 100)]

    def test_pk_range(self, sess):
        sess.execute("analyze table u")
        assert sess.query(
            "select count(*) from u where id >= 100 and id < 130"
        ) == [(30,)]

    def test_fractional_float_bounds(self, sess):
        sess.execute("analyze table u")
        # int_col > 10.5 must include 11; int_col < 13 excludes 13
        rows = sess.query("select id from u where id > 10.5 and id < 13")
        assert sorted(rows) == [(11,), (12,)]
        rows = sess.query("select id from u where id < 2.5 and id >= 0")
        assert sorted(rows) == [(0,), (1,), (2,)]

    def test_explicit_txn_compacts_on_commit(self):
        s = Domain().new_session()
        s.execute("create table big (a bigint, b varchar(8))")
        s.execute("begin")
        rows = ",".join(f"({i}, 's{i % 7}')" for i in range(5000))
        s.execute(f"insert into big values {rows}")
        s.execute("commit")
        t = s.domain.catalog.info_schema().table("test", "big")
        store = s.domain.storage.table(t.id)
        assert store.base_rows == 5000 and len(store.delta) == 0
        assert s.domain.stats.get(t.id) is not None  # auto-analyzed

    def test_residual_condition(self, sess):
        sess.execute("analyze table u")
        rows = sess.query(
            "select id from u where id >= 10 and id < 20 and score > 20"
        )
        assert sorted(rows) == [(i,) for i in range(14, 20)]

    def test_no_stats_no_secondary_index(self, sess):
        sess.execute("create index iname on u (name)")
        # with stats dropped, a non-unique index is not chosen (device scan
        # brute-force wins by default)
        t = sess.domain.catalog.info_schema().table("test", "u")
        sess.domain.stats.drop(t.id)
        names = plan_names(sess, "select id from u where name = 'n5'")
        assert any("TableReader" in n for n in names)


class TestDeltaMerge:
    def test_dml_compacts_into_base(self, sess):
        t = sess.domain.catalog.info_schema().table("test", "u")
        store = sess.domain.storage.table(t.id)
        assert store.base_rows == 6000  # bulk insert auto-compacted
        assert len(store.delta) == 0
        assert store.cols[1].dictionary is not None  # strings dict-encoded

    def test_small_dml_stays_in_delta(self, sess):
        sess.execute("insert into u values (9999, 'zz', 0.0)")
        t = sess.domain.catalog.info_schema().table("test", "u")
        store = sess.domain.storage.table(t.id)
        assert len(store.delta) == 1
        assert sess.query("select name from u where id = 9999") == [("zz",)]


class TestRangerConstantBounds:
    """Decimal/float literal bounds against int/decimal/double index columns
    (exact Fraction math — IEEE noise like 0.07*100 != 7.0 must not shift
    index range boundaries)."""

    @pytest.fixture(scope="class")
    def bsess(self):
        s = Domain().new_session()
        s.execute("create table fb (id bigint, v double, key (v))")
        for i in range(10):
            s.execute(f"insert into fb values ({i}, {i + 0.5})")
        s.execute("create table db (id bigint, w decimal(12,2), key (w))")
        for i in range(12):
            s.execute(f"insert into db values ({i}, {i/100.0})")
        return s

    def test_decimal_literal_on_double_index(self, bsess):
        assert bsess.query("select id from fb where v = 1.5") == [(1,)]
        assert sorted(bsess.query("select id from fb where v < 2.5")) == \
            [(0,), (1,)]

    def test_float_exponent_literal_on_decimal_index(self, bsess):
        assert sorted(bsess.query(
            "select id from db where w >= 7e-2 and w < 9e-2")) == [(7,), (8,)]
        assert sorted(bsess.query(
            "select id from db where w < 7e-2 and w > 5e-2")) == [(6,)]

    def test_decimal_literal_fractional_on_int_index(self, bsess):
        bsess.execute("create table ib (id bigint, key (id))")
        for i in range(5):
            bsess.execute(f"insert into ib values ({i})")
        assert sorted(bsess.query("select id from ib where id > 1.5")) == \
            [(2,), (3,), (4,)]
        assert bsess.query("select id from ib where id = 1.5") == []
