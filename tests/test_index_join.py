"""Index-join family, covering IndexReader and BatchPointGet.

Reference behaviors: executor/index_lookup_join.go:1-687 (+ hash/merge
variants), executor/distsql.go:317 (IndexReader), and
executor/batch_point_get.go:1-176.
"""

import pytest

from tidb_tpu.session import Domain


def plan_names(sess, sql):
    return [r[0].strip("└─ ") for r in sess.execute("explain " + sql)[0].rows]


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table item (id bigint primary key, cat varchar(8), "
              "price double)")
    rows = ",".join(f"({i}, 'c{i % 40}', {i * 0.25})" for i in range(6000))
    s.execute(f"insert into item values {rows}")
    s.execute("create index icat on item (cat)")
    s.execute("create table ord (oid bigint, item_id bigint, qty bigint)")
    rows = ",".join(f"({i}, {(i * 37) % 6000}, {i % 5})" for i in range(40))
    s.execute(f"insert into ord values {rows}")
    s.execute("analyze table item")
    s.execute("analyze table ord")
    return s


class TestBatchPointGet:
    def test_in_on_pk_is_batch_point_get(self, sess):
        sql = "select cat from item where id in (3, 1, 4, 1, 5)"
        assert any("Batch_Point_Get" in n for n in plan_names(sess, sql))
        assert sorted(sess.query(sql)) == sorted(
            [("c3",), ("c1",), ("c4",), ("c5",)])

    def test_misses_and_unrepresentable(self, sess):
        # 2.5 can't be an int key (matches nothing); 99999 misses
        rows = sess.query(
            "select id from item where id in (7, 2.5, 99999)")
        assert rows == [(7,)]

    def test_residual_condition(self, sess):
        rows = sess.query(
            "select id from item where id in (8, 9, 10) and price > 2.2")
        assert sorted(rows) == [(9,), (10,)]

    def test_sees_txn_buffer_and_deletes(self, sess):
        sess.execute("delete from item where id = 11")
        sess.execute("begin")
        sess.execute("update item set cat = 'zz' where id = 12")
        rows = sess.query("select id, cat from item where id in (11, 12)")
        assert rows == [(12, "zz")]
        sess.execute("rollback")
        rows = sess.query("select id, cat from item where id in (11, 12)")
        assert rows == [(12, "c12")]


class TestIndexReader:
    def test_covering_scan_skips_table(self, sess):
        sql = "select cat from item where cat = 'c7'"
        names = plan_names(sess, sql)
        assert any("IndexReader" in n for n in names)
        assert not any("IndexLookUp" in n for n in names)
        assert sess.query(sql) == [("c7",)] * 150

    def test_non_covering_falls_back(self, sess):
        # price is not in the index -> IndexLookUp, same rows
        sql = "select cat, price from item where cat = 'c7'"
        names = plan_names(sess, sql)
        assert any("IndexLookUp" in n for n in names)
        got = sorted(sess.query(sql))
        assert len(got) == 150 and got[0] == ("c7", 1.75)

    def test_pk_range_covering(self, sess):
        sql = "select id from item where id >= 100 and id < 110"
        assert any("IndexReader" in n for n in plan_names(sess, sql))
        assert sorted(sess.query(sql)) == [(i,) for i in range(100, 110)]

    def test_overlay_rows_visible(self, sess):
        sess.execute("insert into item values (90001, 'c7', 1.0)")
        sess.execute("delete from item where id = 7")
        sess.execute("update item set cat = 'c7' where id = 8")
        rows = sess.query("select cat from item where cat = 'c7'")
        # 150 base matches - deleted(7) - but +insert(90001) +update(8)
        assert rows == [("c7",)] * 151

    def test_nullable_unconstrained_column_not_covering(self, sess):
        # n is nullable and the index drops NULL rows: a bare scan of the
        # index would lose rows, so the planner must not pick IndexReader
        # unless every nullable key column is pinned by an access cond
        sess.execute("create table nt (a bigint, n bigint, key kan (a, n))")
        rows = ",".join(f"({i % 50}, {i})" if i % 3 else f"({i % 50}, null)"
                        for i in range(5000))
        sess.execute(f"insert into nt values {rows}")
        sess.execute("analyze table nt")
        sql = "select a, n from nt where a = 5"
        assert not any("IndexReader" in n for n in plan_names(sess, sql))
        rows = sess.query(sql)
        assert len(rows) == 100 and sum(1 for r in rows if r[1] is None) > 0
        # pinning n with a range makes it null-rejecting -> covering is safe
        sql2 = "select a, n from nt where a = 5 and n >= 0"
        assert any("IndexReader" in n for n in plan_names(sess, sql2))
        assert len(sess.query(sql2)) == 100 - sum(
            1 for r in rows if r[1] is None)


class TestIndexLookUpJoin:
    JOIN = ("select o.oid, i.cat from ord o join item i "
            "on o.item_id = i.id where o.qty > 0")

    def expected(self, sess):
        sess.execute("set tidb_opt_enable_index_join = 0")
        rows = sorted(sess.query(self.JOIN))
        sess.execute("set tidb_opt_enable_index_join = 1")
        return rows

    def test_planner_picks_index_join(self, sess):
        names = plan_names(sess, self.JOIN)
        assert any("IndexLookUpJoin" in n for n in names)
        assert not any("HashJoin" in n for n in names)

    @pytest.mark.parametrize("variant", ["lookup", "hash", "merge"])
    def test_variants_match_hash_join(self, sess, variant):
        sess.execute(f"set tidb_index_join_variant = '{variant}'")
        want = self.expected(sess)
        assert sorted(sess.query(self.JOIN)) == want
        assert len(want) == 32  # qty>0 drops i%5==0

    def test_left_outer(self, sess):
        sess.execute("insert into ord values (100, -5, 1)")  # no match
        sql = ("select o.oid, i.price from ord o left join item i "
               "on o.item_id = i.id")
        assert any("IndexLookUpJoin" in n for n in plan_names(sess, sql))
        rows = dict(sess.query(sql))
        assert rows[100] is None and len(rows) == 41
        assert rows[1] == 37 * 0.25

    def test_semi_and_anti(self, sess):
        sess.execute("insert into ord values (100, -5, 1)")
        semi = ("select oid from ord o where exists "
                "(select 1 from item i where i.id = o.item_id)")
        anti = ("select oid from ord o where not exists "
                "(select 1 from item i where i.id = o.item_id)")
        assert any("IndexLookUpJoin" in n for n in plan_names(sess, semi))
        assert len(sess.query(semi)) == 40
        assert sess.query(anti) == [(100,)]

    def test_string_key_join(self, sess):
        sess.execute("create table want (c varchar(8))")
        sess.execute("insert into want values ('c3'), ('c9'), ('zz')")
        sql = ("select w.c, count(*) from want w join item i on i.cat = w.c "
               "group by w.c")
        assert sorted(sess.query(sql)) == [("c3", 150), ("c9", 150)]

    def test_inner_conds_apply(self, sess):
        sql = ("select o.oid from ord o join item i on o.item_id = i.id "
               "and i.price > 100")
        want = self_join_fallback(sess, sql)
        assert sorted(sess.query(sql)) == want

    def test_txn_overlay_on_inner(self, sess):
        sess.execute("begin")
        sess.execute("update item set cat = 'xx' where id = 37")
        sess.execute("delete from item where id = 74")
        rows = dict(sess.query(
            "select o.oid, i.cat from ord o join item i on o.item_id = i.id"))
        assert rows[1] == "xx"       # ord 1 -> item 37, buffered update
        assert 2 not in rows          # ord 2 -> item 74, buffered delete
        sess.execute("rollback")

    def test_composite_key_probe(self, sess):
        # two-column index: the probe narrows the run per trailing column
        # (no full expansion of the low-cardinality leading run)
        sess.execute("create table ev (kind bigint, seq bigint, "
                     "v double, key kks (kind, seq))")
        rows = ",".join(f"({i % 3}, {i}, {i * 1.0})" for i in range(4500))
        sess.execute(f"insert into ev values {rows}")
        sess.execute("create table probe (kind bigint, seq bigint)")
        sess.execute("insert into probe values (0, 9), (1, 10), (2, 2), "
                     "(1, 1), (2, 99999)")
        sess.execute("analyze table ev")
        sess.execute("analyze table probe")
        sql = ("select p.seq, e.v from probe p join ev e "
               "on e.kind = p.kind and e.seq = p.seq")
        assert any("IndexLookUpJoin" in n for n in plan_names(sess, sql))
        assert sorted(sess.query(sql)) == [
            (1, 1.0), (2, 2.0), (9, 9.0), (10, 10.0)]

    def test_outer_est_gate(self, sess):
        # joining two big tables must NOT take the lookup path
        sql = "select count(*) from item a join item b on a.id = b.id"
        names = plan_names(sess, sql)
        assert not any("IndexLookUpJoin" in n for n in names)
        assert sess.query(sql) == [(6000,)]


def self_join_fallback(sess, sql):
    sess.execute("set tidb_opt_enable_index_join = 0")
    rows = sorted(sess.query(sql))
    sess.execute("set tidb_opt_enable_index_join = 1")
    return rows
