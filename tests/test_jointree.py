"""Join-tree compiler: multi-way device-resident rung ladders (ISSUE 12).

Acceptance coverage:

- a >=3-table equi-join tree lowers to ONE MPPJoinTree ladder whose
  intermediate results stay device-resident between rungs — trace-
  asserted: zero `copr.transfer` spans inside the warm `mpp.tree` span;
- EXPLAIN shows the chosen join order with est_rows per rung;
- EXISTS / NOT EXISTS / IN / NOT IN subqueries (Q4-shaped) decorrelate
  into semi / anti-semi RUNGS of the same ladder, with parity vs the
  CPU oracle;
- per-rung overflow steps down the ladder (emission-buffer boost,
  partition overflow -> broadcast) without wrong results, and the chaos
  site `mpp/tree_rung` drives the host-chain fallback with parity.
"""

import numpy as np
import pytest

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain

N_CUST = 300
N_ORD = 2000
N_ITEM = 9000
N_PART = 150


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    rng = np.random.default_rng(23)
    s.execute("create table cust (c_id bigint primary key,"
              " c_nation bigint, c_seg varchar(10))")
    s.execute("create table ord (o_id bigint primary key,"
              " o_cust bigint, o_flag bigint, o_total double)")
    s.execute("create table item (i_ord bigint, i_part bigint,"
              " i_qty bigint, i_price decimal(12,2))")
    s.execute("create table part (p_id bigint primary key,"
              " p_cat varchar(12))")
    ts = d.storage.current_ts()

    def table(name):
        return d.storage.table(d.catalog.info_schema().table(
            "test", name).id)

    segs = np.array(["BUILDING", "MACHINERY", "AUTO", "HOUSE"],
                    dtype=object)
    table("cust").bulk_load_arrays([
        np.arange(N_CUST, dtype=np.int64),
        rng.integers(0, 12, N_CUST),
        segs[rng.integers(0, 4, N_CUST)],
    ], ts=ts)
    # 60 trailing custkeys get no orders (NOT IN / anti-semi fodder)
    table("ord").bulk_load_arrays([
        np.arange(N_ORD, dtype=np.int64),
        rng.integers(0, N_CUST - 60, N_ORD),
        rng.integers(0, 5, N_ORD),
        rng.uniform(10, 9999, N_ORD),
    ], ts=ts)
    ik = rng.integers(0, N_ORD * 2, N_ITEM)  # >50% dangling keys
    ivalid = [np.ones(N_ITEM, np.bool_), None, None, None]
    ivalid[0][rng.integers(0, N_ITEM, 300)] = False
    table("item").bulk_load_arrays([
        ik,
        rng.integers(0, N_PART, N_ITEM),
        rng.integers(1, 51, N_ITEM),
        rng.integers(100, 1_000_000, N_ITEM),
    ], ivalid, ts=ts)
    cats = np.array([f"CAT{i:02d}" for i in range(9)], dtype=object)
    table("part").bulk_load_arrays([
        np.arange(N_PART, dtype=np.int64),
        cats[rng.integers(0, 9, N_PART)],
    ], ts=ts)
    for t in ("cust", "ord", "item", "part"):
        s.execute(f"analyze table {t}")
    s.execute("set tidb_enforce_mpp = 1")
    return s


def _cpu(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _nullsafe(r):
    return tuple((None is x and (0, "") or (1, x)) for x in r)


def _rows_eq(got, want, ctx=""):
    assert len(got) == len(want), (ctx, len(got), len(want))
    for ra, rb in zip(sorted(got, key=_nullsafe),
                      sorted(want, key=_nullsafe)):
        for a, b in zip(ra, rb):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9, abs=1e-9), \
                    (ctx, ra, rb)
            else:
                assert a == b, (ctx, ra, rb)


def _snap(*names):
    s = REGISTRY.snapshot()
    return tuple(s.get(n, 0) for n in names)


def _run_tree(sess, sql):
    t0, f0 = _snap("mpp_tree_joins_total", "mpp_tree_fallback_total")
    rows = sess.query(sql)
    t1, f1 = _snap("mpp_tree_joins_total", "mpp_tree_fallback_total")
    assert t1 > t0, f"not served by the device rung ladder: {sql}"
    assert f1 == f0, f"fell back to the host join chain: {sql}"
    return rows


def _spans(sess, name):
    out = []

    def walk(s):
        if s.name == name:
            out.append(s)
        for c in s.children:
            walk(c)

    walk(sess.last_trace.root)
    return out


THREE_WAY = ("select i_qty, i_price, o_flag, o_total, c_nation"
             " from item join ord on i_ord = o_id"
             " join cust on o_cust = c_id where i_qty < 40")
FOUR_WAY_AGG = ("select c_nation, count(*), sum(i_price)"
                " from item join ord on i_ord = o_id"
                " join cust on o_cust = c_id"
                " join part on i_part = p_id"
                " where o_flag < 4 group by c_nation")
EXISTS_Q4 = ("select o_flag, count(*) from ord"
             " where exists (select 1 from item"
             "               where i_ord = o_id and i_qty > 30)"
             " group by o_flag")
NOT_EXISTS = ("select count(*), sum(o_total) from ord"
              " where not exists (select 1 from item"
              "                   where i_ord = o_id and i_qty > 45)")
IN_SUB = ("select o_flag, count(*) from ord"
          " where o_cust in (select c_id from cust"
          "                  where c_seg = 'BUILDING')"
          " group by o_flag")
NOT_IN = ("select count(*) from cust"
          " where c_id not in (select o_cust from ord)")


def test_explain_shows_join_order_and_est_rows(sess):
    rows = sess.execute("explain " + THREE_WAY)[0].rows
    plan = "\n".join(" | ".join(str(x) for x in r) for r in rows)
    assert "MPPJoinTree" in plan and "mpp[tpu]" in plan, plan
    assert "order: " in plan, plan
    rungs = [r for r in rows if r[0].strip().startswith("└─Rung_")]
    assert len(rungs) == 2, plan
    for r in rungs:
        assert float(r[1]) >= 1.0, r  # est_rows annotated per rung
        assert "build:" in r[3], r


def test_rung_est_rows_single_sourced_from_dp(sess, monkeypatch):
    """Jointree follow-up (f): the containment cardinality estimate
    lives ONCE — the DP's per-step numbers ARE the EXPLAIN est_rows
    (and thereby the grouped-agg budgets), never a second copy of the
    formula in rung assembly."""
    from tidb_tpu.planner import jointree as jt

    captured = []
    orig = jt._order_members

    def spy(sides, edges, pctx):
        out = orig(sides, edges, pctx)
        if out is not None:
            captured.append(list(out[1]))
        return out

    monkeypatch.setattr(jt, "_order_members", spy)
    sess._plan_cache.clear()  # a cached plan would skip assembly
    rows = sess.execute("explain " + THREE_WAY)[0].rows
    rungs = [r for r in rows if r[0].strip().startswith("└─Rung_")]
    assert captured and len(rungs) == 2, (captured, rows)
    dp_ests = captured[-1]
    assert len(dp_ests) == len(rungs)
    assert [r[1] for r in rungs] == [f"{e:.2f}" for e in dp_ests], \
        (rungs, dp_ests)


def test_three_way_rows_parity(sess):
    got = _run_tree(sess, THREE_WAY)
    assert len(got) > 0
    _rows_eq(got, _cpu(sess, THREE_WAY), "3way-rows")


def test_four_way_grouped_agg_parity(sess):
    got = _run_tree(sess, FOUR_WAY_AGG)
    assert len(got) > 0
    _rows_eq(got, _cpu(sess, FOUR_WAY_AGG), "4way-agg")


def test_no_transfers_between_rungs_when_warm(sess):
    """Device residency: on a warm column cache the whole ladder runs
    with ZERO copr.transfer spans — intermediate results never leave
    HBM between rungs (ISSUE 12 acceptance)."""
    sess.query(FOUR_WAY_AGG)  # warm the compiled programs + cache
    sess.query(FOUR_WAY_AGG)
    sess.execute("trace " + FOUR_WAY_AGG)
    trees = _spans(sess, "mpp.tree")
    assert trees, "query no longer served by the rung ladder"
    rungs = _spans(sess, "mpp.rung")
    assert len(rungs) == 3, [s.attrs for s in rungs]
    transfers = _spans(sess, "copr.transfer")
    assert not transfers, (
        f"{len(transfers)} host transfers inside the warm ladder")
    finals = _spans(sess, "mpp.tree.final")
    assert len(finals) == 1  # on-device partial agg, O(G) readback


def test_exists_decorrelates_to_semi_rung(sess):
    rows = sess.execute("explain " + EXISTS_Q4)[0].rows
    plan = "\n".join(" | ".join(str(x) for x in r) for r in rows)
    assert "MPPJoinTree" in plan, plan
    assert "semi build:item" in plan, plan
    got = _run_tree(sess, EXISTS_Q4)
    assert len(got) > 0
    _rows_eq(got, _cpu(sess, EXISTS_Q4), "exists-q4")


def test_not_exists_decorrelates_to_anti_rung(sess):
    rows = sess.execute("explain " + NOT_EXISTS)[0].rows
    plan = "\n".join(" | ".join(str(x) for x in r) for r in rows)
    assert "anti_semi build:item" in plan, plan
    got = _run_tree(sess, NOT_EXISTS)
    _rows_eq(got, _cpu(sess, NOT_EXISTS), "not-exists")


def test_in_and_not_in_subqueries_parity(sess):
    got = _run_tree(sess, IN_SUB)
    assert len(got) > 0
    _rows_eq(got, _cpu(sess, IN_SUB), "in-sub")
    got = _run_tree(sess, NOT_IN)
    assert got[0][0] > 0  # the 60 order-less custkeys
    _rows_eq(got, _cpu(sess, NOT_IN), "not-in")


def test_correlated_exists_with_noneq_conjunct(sess):
    """A correlated non-equality conjunct rides as a rung other-cond,
    evaluated per candidate pair on device."""
    q = ("select count(*) from ord"
         " where exists (select 1 from item"
         "               where i_ord = o_id and i_price > o_total)")
    got = _run_tree(sess, q)
    _rows_eq(got, _cpu(sess, q), "corr-noneq")


def test_emission_overflow_boosts_rung_buffer(sess):
    """An emission-buffer overflow grows THAT rung's cap_out and
    retries on device (duplicate keys expand past the estimate)."""
    from tidb_tpu.mpp.jointree import MPPTreeOverflow
    from tidb_tpu.store.fault import failpoint, once

    with failpoint("mpp/tree_rung",
                   once(MPPTreeOverflow(0, "emit", "chaos emit"))):
        got = _run_tree(sess, THREE_WAY)
    _rows_eq(got, _cpu(sess, THREE_WAY), "emit-boost")


def test_partition_overflow_demotes_rung_to_broadcast(sess):
    """Partition-bucket overflow steps ONE rung down to the broadcast
    strategy; the rest of the ladder stays on shuffle."""
    from tidb_tpu.mpp.jointree import MPPTreeOverflow
    from tidb_tpu.store.fault import failpoint, once

    with failpoint("mpp/tree_rung",
                   once(MPPTreeOverflow(1, "partition", "chaos part"))):
        got = _run_tree(sess, THREE_WAY)
    _rows_eq(got, _cpu(sess, THREE_WAY), "bcast-demote")
    sess.execute("trace " + THREE_WAY)  # disarmed: all-shuffle again
    assert _spans(sess, "mpp.tree"), "ladder did not recover"


def test_chaos_ineligible_falls_back_to_host_chain(sess):
    """A structural decline mid-ladder serves the SAME join order as
    chained host hash joins — correctness never depends on the mesh."""
    from tidb_tpu.mpp.engine import MPPIneligible
    from tidb_tpu.store.fault import failpoint, once

    f0 = _snap("mpp_tree_fallback_total")[0]
    with failpoint("mpp/tree_rung", once(MPPIneligible("chaos"))):
        got = sess.query(THREE_WAY)
    assert _snap("mpp_tree_fallback_total")[0] > f0
    _rows_eq(got, _cpu(sess, THREE_WAY), "host-chain")
    _run_tree(sess, THREE_WAY)  # disarmed: back on the device ladder


def test_explain_analyze_attributes_tree_engine(sess):
    rows = sess.execute("explain analyze " + THREE_WAY)[0].rows
    trees = [r for r in rows if "MPPJoinTree" in r[0]]
    assert trees, rows
    assert any("engine:mpp-tree" in str(r[4]) for r in trees), trees


def test_kill_mid_rung_is_scope_bounded(sess):
    """ISSUE 17: the rung ladder IS the chunk sequence on the MPP path.
    A KILL landing inside rung 1's seam must stop the ladder there —
    no later rung dispatches — and surface the typed scope error; a
    re-run over the same ladder has full parity."""
    from tidb_tpu.errors import QueryKilledError
    from tidb_tpu.store.fault import failpoint

    d = sess.domain
    for q in (THREE_WAY, FOUR_WAY_AGG):
        victim = d.new_session()
        victim.execute("set tidb_enforce_mpp = 1")
        hits = []

        def action(**ctx):
            if ctx.get("kind") != "mpp":
                return
            hits.append(ctx["chunk"])
            if ctx["chunk"] == 1:
                d.kill(victim.conn_id, True)

        with failpoint("copr/chunk_dispatch", action):
            with pytest.raises(QueryKilledError):
                victim.query(q)
        assert hits, f"mpp chunk failpoint never fired: {q}"
        assert max(hits) <= 1, \
            f"rungs kept dispatching after the kill: {hits}"
        _rows_eq(_run_tree(victim, q), _cpu(sess, q), "post-kill rerun")


def test_same_key_ladder_elides_reshuffle(sess):
    """Jointree (e): a shuffle rung whose key slots match the
    partitioning the previous shuffle rung left behind skips the
    probe-side exchange — equal keys already co-reside.  A fact joined
    to three dims all on f_k shuffles ONCE (rung 0); rungs 1 and 2 run
    with elided=1 and bump mpp_tree_reshuffle_elided_total, with full
    parity vs the CPU oracle."""
    d = sess.domain
    rng = np.random.default_rng(31)
    sess.execute("create table fxf (f_k bigint, f_v bigint)")
    for t in ("dza", "dzb", "dzc"):
        sess.execute(f"create table {t} ({t}_k bigint primary key,"
                     f" {t}_v bigint)")
    ts = d.storage.current_ts()

    def table(name):
        return d.storage.table(d.catalog.info_schema().table(
            "test", name).id)

    n_dim, n_fact = 400, 3000
    table("fxf").bulk_load_arrays([
        rng.integers(0, n_dim, n_fact),
        rng.integers(0, 1000, n_fact),
    ], ts=ts)
    for t in ("dza", "dzb", "dzc"):
        table(t).bulk_load_arrays([
            np.arange(n_dim, dtype=np.int64),
            rng.integers(0, 100, n_dim),
        ], ts=ts)
    for t in ("fxf", "dza", "dzb", "dzc"):
        sess.execute(f"analyze table {t}")

    sql = ("select f_v, dza_v, dzb_v, dzc_v from fxf"
           " join dza on f_k = dza_k"
           " join dzb on f_k = dzb_k"
           " join dzc on f_k = dzc_k")
    e0 = _snap("mpp_tree_reshuffle_elided_total")[0]
    got = _run_tree(sess, sql)
    assert _snap("mpp_tree_reshuffle_elided_total")[0] == e0 + 2, \
        "rungs 1 and 2 should both skip the probe re-shuffle"
    _rows_eq(got, _cpu(sess, sql), "same-key-ladder")
    sess.execute("trace " + sql)
    rungs = _spans(sess, "mpp.rung")
    assert [s.attrs.get("elided") for s in rungs] == [0, 1, 1], \
        [s.attrs for s in rungs]
    assert _snap("mpp_tree_reshuffle_elided_total")[0] == e0 + 4
