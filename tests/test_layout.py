"""Workload-adaptive layout engine + compressed cold tier (ISSUE 10).

Acceptance contract:

- the autotuner CHOOSES {dictionary vs direct encoding, residency
  priority/tier, tile-size bucket} per column from observed stats, and
  the decisions are visible on /status and in
  INFORMATION_SCHEMA.TIDB_TPU_COLUMN_LAYOUT;
- a table whose columns exceed the hot-tier byte cap answers
  Q1/Q6-shaped aggregations, TopN and joins correctly with ZERO
  full-table host reloads after warmup: cold columns are device-resident
  compressed blocks decoded in-register (one `copr.device.execute`, no
  `copr.transfer` span on the steady state) — metric-asserted via
  layout_cold_{hits,loads,promotions,demotions}_total;
- ByteCapCache eviction is value-weighted: lowest-priority victims
  demote to the cold tier before being dropped;
- the chaos site `layout/decompress` fails cold access over to the hot
  tier with identical results;
- layout-class re-tunes are rate-limited (no recompile storms).
"""

import os

import numpy as np
import pytest

from tidb_tpu.chunk import Column
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import always, failpoint
from tidb_tpu.types import ty_int, ty_string

N = 20_000


def _mk_domain(n=N, seed=7):
    d = Domain()
    s = d.new_session()
    s.execute("create table li (a bigint, b bigint, f double,"
              " c varchar(8))")
    s.execute("create table dim (id bigint, nm varchar(8))")
    rng = np.random.default_rng(seed)
    t = d.catalog.info_schema().table("test", "li")
    tags = np.array([f"t{i}" for i in range(6)], dtype=object)
    d.storage.table(t.id).bulk_load_arrays([
        rng.integers(0, 40, n, dtype=np.int64),        # low range: packable
        rng.integers(0, 10**12, n, dtype=np.int64),    # high NDV: direct/hot
        rng.choice([0.01, 0.02, 0.05, 0.07], n),       # low-NDV float
        tags[rng.integers(0, 6, n)],                   # dict string
    ], ts=d.storage.current_ts())
    td = d.catalog.info_schema().table("test", "dim")
    d.storage.table(td.id).bulk_load_arrays([
        np.arange(40, dtype=np.int64),
        np.array([f"n{i % 4}" for i in range(40)], dtype=object),
    ], ts=d.storage.current_ts())
    s.execute("analyze table li")
    return d, s


@pytest.fixture
def layout_env(monkeypatch):
    """Fast re-tunes + guaranteed restoration of the hot cap, tiers and
    tuner state (the LAYOUT engine and caches are process-global).

    EVERY env knob the layout engine reads (`TIDB_TPU_HBM_BYTES`,
    `TIDB_TPU_LAYOUT`, the cache capacities) is snapshotted here and
    restored on teardown — tests mutating layout state outside this
    fixture were a known cross-test flake source (ISSUE 12 hygiene)."""
    from tidb_tpu.copr.parallel import MESH_CACHE
    from tidb_tpu.layout import LAYOUT, coldtier

    monkeypatch.setenv("TIDB_TPU_LAYOUT_RETUNE_S", "0")
    old_cap = MESH_CACHE._c.capacity
    saved = {k: os.environ.get(k)
             for k in ("TIDB_TPU_HBM_BYTES", "TIDB_TPU_LAYOUT")}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    MESH_CACHE._c.capacity = old_cap
    MESH_CACHE.clear()
    coldtier.clear()
    LAYOUT.reset()


def _cpu(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _approx_rows(got, want, ctx=""):
    assert len(got) == len(want), (ctx, len(got), len(want))
    for ra, rb in zip(sorted(got, key=str), sorted(want, key=str)):
        for a, b in zip(ra, rb):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (ctx, ra, rb)
            else:
                assert a == b, (ctx, ra, rb)


def _spans(tr, name):
    out = []

    def walk(sp):
        if sp.name == name:
            out.append(sp)
        for ch in sp.children:
            walk(ch)

    walk(tr.root)
    return out


# ---------------------------------------------------------------------------
# autotuner decisions (unit)
# ---------------------------------------------------------------------------


def test_autotuner_decisions(layout_env):
    from tidb_tpu.layout import LAYOUT, set_hot_cap_bytes

    d, s = _mk_domain()
    store = d.storage.table(
        d.catalog.info_schema().table("test", "li").id)
    # no pressure: everything hot, pow2 tiling
    set_hot_cap_bytes(8 << 30)
    for ci in range(store.n_cols):
        p = LAYOUT.plan_for(store, ci)
        assert p.tier == "hot" and p.tile_bucket == "pow2", (ci, p)
    # squeeze below the table's wire bytes: packable columns flip cold,
    # the un-packable high-NDV column stays hot, tiling goes exact
    set_hot_cap_bytes(100_000)
    pa = LAYOUT.plan_for(store, 0)
    pb = LAYOUT.plan_for(store, 1)
    pf = LAYOUT.plan_for(store, 2)
    pc = LAYOUT.plan_for(store, 3)
    assert pa.tier == "cold" and pa.encoding == "dict" and pa.bits == 8
    assert pb.tier == "hot" and pb.encoding == "direct" and pb.bits == 0
    assert pf.tier == "cold" and 0 < pf.bits <= 4
    assert pc.tier == "cold" and pc.encoding == "dict"
    assert pa.tile_bucket == "exact"
    # residency priority follows observed usage (keys weigh double)
    before = LAYOUT.priority(store.store_uid, 0)
    LAYOUT.observe(store, 0, "agg_key")
    LAYOUT.observe(store, 0, "scan")
    assert LAYOUT.priority(store.store_uid, 0) >= before + 3.0


def test_pack_roundtrip(layout_env):
    import jax

    from tidb_tpu.copr.fusion import decode_packed
    from tidb_tpu.layout.coldtier import pack_codes

    rng = np.random.default_rng(3)
    for bits in (1, 2, 4, 8):
        n = 4096
        codes = rng.integers(0, 1 << bits, n).astype(np.uint8)
        packed = pack_codes(codes, bits)
        dict_vals = (np.arange(1 << bits, dtype=np.int64) * 3 + 5)
        got = jax.jit(
            lambda p, dv: decode_packed(p, dv, bits, n))(packed, dict_vals)
        np.testing.assert_array_equal(
            np.asarray(got), dict_vals[codes.astype(np.int64)])


def test_bytecap_value_weighted_eviction():
    from tidb_tpu.copr.cache import ByteCapCache

    class A:
        def __init__(self, nb):
            self.nbytes = nb

    prio = {"a": 5.0, "b": 1.0, "c": 3.0}
    demoted = []
    c = ByteCapCache(250)
    c.set_policy(priority_fn=lambda k: prio[k[0]],
                 demote_fn=lambda k, v: demoted.append(k[0]))
    c.get_or_load(("a",), lambda: (A(100),))
    c.get_or_load(("b",), lambda: (A(100),))
    # inserting c (100b) overflows: the LOWEST-priority resident ("b")
    # is the victim and flows through the demote hook, not plain drop
    c.get_or_load(("c",), lambda: (A(100),))
    assert demoted == ["b"]
    assert c.peek(("a",)) is not None and c.peek(("b",)) is None


# ---------------------------------------------------------------------------
# cold-tier parity corpus (table > byte cap; dict + direct + delta)
# ---------------------------------------------------------------------------

CORPUS = (
    # Q1 shape: dense agg over packed int key with packed-float filter
    "select a, count(*), sum(b) from li where f < 0.04 group by a",
    # Q6 shape: scalar agg over two cold columns
    "select sum(f) from li where a < 10",
    # sort-mode grouped agg over the dict string column
    "select c, count(*), min(f) from li group by c",
    # topn keyed on a cold column
    "select b from li order by f desc, b desc limit 7",
    # filter stream (cold predicate, hot output column)
    "select b from li where a = 3 and f < 0.02",
)


def test_cold_tier_parity_and_single_dispatch(layout_env):
    from tidb_tpu.layout import set_hot_cap_bytes

    d, s = _mk_domain()
    # delta overlay rides along: committed DML over the cold-pressured
    # base must still merge through the host delta path
    s.execute("insert into li values (3, 77, 0.01, 't1'),"
              " (999, 88, 0.07, 't2')")
    s.execute("delete from li where b = 77 and a = 3 and f = 0.01")
    want = [_cpu(s, q) for q in CORPUS]
    m0 = REGISTRY.snapshot()
    set_hot_cap_bytes(170_000)  # < table wire bytes: b stays hot, rest cold
    for q, w in zip(CORPUS, want):
        _approx_rows(s.query(q), w, q)
        _approx_rows(s.query(q), w, q + " (steady)")  # cold HITS
    m1 = REGISTRY.snapshot()
    assert m1.get("layout_cold_loads_total", 0) > m0.get(
        "layout_cold_loads_total", 0)
    assert m1.get("layout_cold_hits_total", 0) > m0.get(
        "layout_cold_hits_total", 0)
    # steady state: ONE fused dispatch, ZERO host->device transfers —
    # the cold columns are served from device-resident compressed blocks
    s.execute("trace " + CORPUS[0])
    tr = s.last_trace
    assert len(_spans(tr, "copr.device.execute")) == 1
    assert len(_spans(tr, "copr.transfer")) == 0
    # decisions surface in INFORMATION_SCHEMA
    rows = s.query(
        "select column_name, tier, encoding from"
        " information_schema.tidb_tpu_column_layout where tier = 'cold'")
    assert {r[0] for r in rows} >= {"a", "f", "c"}


def test_cold_join_parity(layout_env):
    from tidb_tpu.layout import set_hot_cap_bytes

    d, s = _mk_domain()
    q = ("select nm, count(*), sum(f) from li join dim on a = id"
         " where f < 0.06 group by nm")
    want = _cpu(s, q)
    set_hot_cap_bytes(170_000)
    _approx_rows(s.query(q), want, q)
    _approx_rows(s.query(q), want, q + " (steady)")


def test_fixed_layout_comparator(layout_env, monkeypatch):
    # TIDB_TPU_LAYOUT=0: the pre-layout behavior — everything hot, no
    # cold traffic, results identical (the bench's comparator leg)
    from tidb_tpu.layout import set_hot_cap_bytes

    d, s = _mk_domain()
    q = CORPUS[0]
    want = _cpu(s, q)
    set_hot_cap_bytes(170_000)
    monkeypatch.setenv("TIDB_TPU_LAYOUT", "0")
    m0 = REGISTRY.get("layout_cold_loads_total")
    _approx_rows(s.query(q), want, q)
    assert REGISTRY.get("layout_cold_loads_total") == m0


# ---------------------------------------------------------------------------
# demotion / promotion
# ---------------------------------------------------------------------------


def test_eviction_demotes_then_promotes(layout_env):
    from tidb_tpu.copr.parallel import MESH_CACHE
    from tidb_tpu.layout import COLD_CACHE, set_hot_cap_bytes

    d, s = _mk_domain(n=8192)
    s2 = d.new_session()
    s2.execute("create table other (x bigint, y bigint)")
    rng = np.random.default_rng(5)
    to = d.catalog.info_schema().table("test", "other")
    d.storage.table(to.id).bulk_load_arrays([
        rng.integers(0, 30, 65536, dtype=np.int64),
        rng.integers(0, 10**12, 65536, dtype=np.int64),
    ], ts=d.storage.current_ts())
    q_li = "select a, count(*), min(f) from li group by a"
    want_li = _cpu(s, q_li)
    # cap fits ONE working set: li's columns load hot, then `other`'s
    # big direct column squeezes the hot tier — the packable li column
    # must DEMOTE to cold, not drop
    set_hot_cap_bytes(560_000)
    _approx_rows(s.query(q_li), want_li, "warm")
    m0 = REGISTRY.snapshot()
    s.query("select x, count(*), sum(y) from other group by x")
    m1 = REGISTRY.snapshot()
    assert m1.get("layout_cold_demotions_total", 0) > m0.get(
        "layout_cold_demotions_total", 0)
    # layout follow-up (e): demotion re-encodes ON DEVICE and reads
    # back only the packed codes (8-64x smaller than raw values)
    assert m1.get("layout_demote_code_readback_bytes", 0) > m0.get(
        "layout_demote_code_readback_bytes", 0)
    assert len(COLD_CACHE) > 0
    # the demoted column now serves COLD (hit, no reload), still correct
    _approx_rows(s.query(q_li), want_li, "cold after demote")
    m2 = REGISTRY.snapshot()
    assert m2.get("layout_cold_hits_total", 0) > m1.get(
        "layout_cold_hits_total", 0)
    # capacity returns: the tuner promotes the column back to hot
    set_hot_cap_bytes(8 << 30)
    MESH_CACHE.clear()
    _approx_rows(s.query(q_li), want_li, "promoted")
    m3 = REGISTRY.snapshot()
    assert m3.get("layout_cold_promotions_total", 0) > m2.get(
        "layout_cold_promotions_total", 0)


def test_retune_rate_limit(layout_env, monkeypatch):
    from tidb_tpu.layout import LAYOUT, set_hot_cap_bytes

    # layout_env snapshots/restores the env knobs and caches; this test
    # only needs a SLOW retune window on top of it
    monkeypatch.setenv("TIDB_TPU_LAYOUT_RETUNE_S", "3600")
    d, s = _mk_domain(n=4096)
    store = d.storage.table(
        d.catalog.info_schema().table("test", "li").id)
    set_hot_cap_bytes(10_000)
    p0 = LAYOUT.plan_for(store, 0)
    assert p0.tier == "cold"
    # pressure vanishes immediately: the class flip is SUPPRESSED
    # (rate limit) — no refingerprint storm from a flapping signal
    m0 = REGISTRY.get("layout_retunes_suppressed_total")
    set_hot_cap_bytes(8 << 30)
    p1 = LAYOUT.plan_for(store, 0)
    assert p1.tier == "cold"  # kept the old class
    assert REGISTRY.get("layout_retunes_suppressed_total") > m0


# ---------------------------------------------------------------------------
# chaos: layout/decompress fails over to the hot tier
# ---------------------------------------------------------------------------


def test_chaos_decompress_parity(layout_env):
    from tidb_tpu.layout import set_hot_cap_bytes

    d, s = _mk_domain()
    q = CORPUS[1]
    want = _cpu(s, q)
    set_hot_cap_bytes(170_000)
    m0 = REGISTRY.get("layout_cold_fallbacks_total")
    with failpoint("layout/decompress", always(RuntimeError("chaos"))):
        _approx_rows(s.query(q), want, "decompress chaos")
    assert REGISTRY.get("layout_cold_fallbacks_total") > m0
    # disarmed: the same query comes back on the cold tier
    h0 = REGISTRY.get("layout_cold_hits_total") + REGISTRY.get(
        "layout_cold_loads_total")
    _approx_rows(s.query(q), want, "recovered")
    assert REGISTRY.get("layout_cold_hits_total") + REGISTRY.get(
        "layout_cold_loads_total") > h0


# ---------------------------------------------------------------------------
# /status section
# ---------------------------------------------------------------------------


def test_status_section(layout_env):
    from tidb_tpu.layout import set_hot_cap_bytes, status_section

    d, s = _mk_domain(n=4096)
    set_hot_cap_bytes(10_000)
    s.query("select count(*) from li where a < 5")
    sec = status_section()
    assert sec["enabled"] and sec["hot_cap_bytes"] == 10_000
    assert any(c["tier"] == "cold" for c in sec["columns"])
    assert "layout_cold_loads_total" in sec["metrics"]


# ---------------------------------------------------------------------------
# vectorized row-loop replacements (lint allowlist 9 -> 7)
# ---------------------------------------------------------------------------


def test_group_indices_multicol_vectorized():
    from tidb_tpu.copr.aggstate import group_indices

    ga = Column(ty_int(), np.array([3, 1, 3, 2, 1, 3]),
                np.array([True, True, False, True, True, True]))
    gb = Column(ty_string(),
                np.array(["x", "y", "x", "x", "y", "x"], dtype=object))
    gidx, keys, G = group_indices([ga, gb])
    # first-appearance group ids, NULL is its own group, keys are python
    # tuples with None for NULL — the old row-at-a-time dict contract
    assert G == 4
    assert gidx.tolist() == [0, 1, 2, 3, 1, 0]
    assert keys == [(3, "x"), (1, "y"), (None, "x"), (2, "x")]


def test_unique_key_sets_vectorized():
    d = Domain()
    s = d.new_session()
    s.execute("create table u (a bigint, b varchar(8), c bigint,"
              " unique key uk (a, b))")
    s.execute("insert into u values (1, 'x', 10), (2, 'y', 20),"
              " (3, null, 30)")
    # NULL key parts never collide (MySQL unique semantics)
    s.execute("insert into u values (3, null, 31)")
    with pytest.raises(Exception, match="[Dd]uplicate"):
        s.execute("insert into u values (1, 'x', 99)")
    # update onto an existing key also trips the columnar key set
    with pytest.raises(Exception, match="[Dd]uplicate"):
        s.execute("update u set a = 2, b = 'y' where c = 10")
    assert s.query("select count(*) from u")[0][0] == 4
