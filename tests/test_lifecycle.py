"""Query lifecycle & admission control (ISSUE 5).

- QueryScope: deadline/cancel propagation to every blocking host seam —
  backoff sleeps wake on KILL with bounded latency, max_execution_time
  terminates long scans between device dispatches, the termination
  reason (killed/timeout/mem_quota/overload/shutdown) flows into the
  slow log, the statement summary, /metrics and the trace;
- the server front door: connection cap, bounded admission queue with a
  queue deadline (fast MySQL-level rejection past the bound), and
  graceful drain that finishes in-flight statements before the listener
  closes.  None of it may leak producer threads.
"""

import asyncio
import random
import struct
import threading
import time

import pytest

from tidb_tpu.distsql.backoff import Backoffer
from tidb_tpu.errors import (
    MaxExecutionTimeExceeded,
    QueryKilledError,
    TiDBTPUError,
)
from tidb_tpu.lifecycle import (
    NULL_SCOPE,
    QueryScope,
    activate_scope,
    classify_termination,
    current_scope,
    deactivate_scope,
)
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import failpoint


def _wait_no_select_threads(timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "tidb-tpu-select" and t.is_alive()]
        if not alive:
            return []
        time.sleep(0.01)
    return alive


def _metric(name):
    return REGISTRY.snapshot().get(name, 0)


@pytest.fixture()
def domain():
    d = Domain()
    yield d
    d.maintenance.stop()


@pytest.fixture()
def sess(domain):
    import numpy as np

    s = domain.new_session()
    s.execute("create table t (k bigint, g bigint, x double)")
    t = domain.catalog.info_schema().table("test", "t")
    store = domain.storage.table(t.id)
    n = 2000
    # bulk-load into BASE blocks so the mesh/tile device paths engage
    # (INSERTed rows live in the delta and run on the CPU engine)
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         np.arange(n, dtype=np.int64) % 5,
         np.arange(n, dtype=np.float64) + 0.5],
        ts=domain.storage.current_ts(),
    )
    domain.storage.regions.split_even(t.id, 4, store.base_rows)
    return s


# ---------------------------------------------------------------------------
# QueryScope unit behavior
# ---------------------------------------------------------------------------


class TestScope:
    def test_first_cancel_wins(self):
        sc = QueryScope()
        sc.cancel("timeout")
        sc.cancel("killed")
        assert sc.reason == "timeout"
        with pytest.raises(MaxExecutionTimeExceeded):
            sc.check()

    def test_deadline_fires_as_timeout(self):
        sc = QueryScope(timeout_s=0.01)
        sc.check()  # not yet
        time.sleep(0.02)
        assert sc.cancelled()
        assert sc.reason == "timeout"
        with pytest.raises(MaxExecutionTimeExceeded):
            sc.check()

    def test_wait_wakes_on_cancel(self):
        sc = QueryScope()
        threading.Timer(0.03, lambda: sc.cancel("killed")).start()
        t0 = time.monotonic()
        assert sc.wait(5.0) is True
        assert time.monotonic() - t0 < 0.5

    def test_null_scope_is_inert(self):
        NULL_SCOPE.cancel("killed")
        NULL_SCOPE.check()  # never raises
        assert not NULL_SCOPE.cancelled()
        assert current_scope() is NULL_SCOPE  # no scope active here

    def test_classification_precedence(self):
        sc = QueryScope()
        sc.cancel("shutdown")
        # the scope's recorded reason wins over exception-type inference
        assert classify_termination(QueryKilledError(), sc) == "shutdown"
        assert classify_termination(None, QueryScope()) == "ok"
        assert classify_termination(RuntimeError("x"), QueryScope()) \
            == "error"


# ---------------------------------------------------------------------------
# Backoffer: KILL mid-backoff with bounded latency (satellite 1)
# ---------------------------------------------------------------------------


def test_backoffer_kill_mid_backoff_bounded_latency():
    """A Backoffer sleeping a multi-second expo wait must wake within the
    <500ms acceptance bound when the scope is cancelled."""
    sc = QueryScope()
    bo = Backoffer(budget_ms=60_000, rng=random.Random(7), scope=sc)
    # grow the device_error schedule to its 2s cap so the next sleep is
    # long enough that an uninterruptible sleep would blow the bound
    result = {}

    def run():
        try:
            for _ in range(12):
                bo.backoff("device_error", RuntimeError("sick device"))
        except TiDBTPUError as e:
            result["err"] = e
            result["t"] = time.monotonic()

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.15)  # deep inside a backoff sleep by now
    t_kill = time.monotonic()
    sc.cancel("killed")
    th.join(timeout=2.0)
    assert not th.is_alive(), "backoff sleep ignored the kill"
    assert isinstance(result["err"], QueryKilledError)
    assert result["t"] - t_kill < 0.5, "kill latency exceeded bound"


def test_backoffer_deadline_is_honored():
    sc = QueryScope(timeout_s=0.05)
    bo = Backoffer(budget_ms=60_000, rng=random.Random(3), scope=sc)
    t0 = time.monotonic()
    with pytest.raises(MaxExecutionTimeExceeded):
        for _ in range(12):
            bo.backoff("device_error")
    assert time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# KILL QUERY while a statement sits in a distsql backoff sleep
# ---------------------------------------------------------------------------


def test_kill_query_mid_distsql_backoff(domain, sess):
    """ISSUE 5 acceptance: KILL QUERY issued while a statement sits in a
    distsql backoff sleep returns the connection an error within 500ms,
    with termination reason 'killed' everywhere."""
    sess.execute("set tidb_use_tpu = 0")  # per-region fan-out path
    killer = domain.new_session()
    k0 = _metric("stmt_terminated_killed_total")
    result = {}

    def run():
        try:
            sess.query("select sum(x) from t where x < 1e9")
        except TiDBTPUError as e:
            result["err"] = e
        result["t"] = time.monotonic()

    # every cop task fails -> tasks retry inside equal-jitter backoff
    # sleeps against a 10s budget; only the kill can end this early
    def sick_store(**ctx):
        raise RuntimeError("store unreachable")

    with failpoint("distsql/task_error", sick_store):
        th = threading.Thread(target=run)
        th.start()
        time.sleep(0.2)  # statements are now inside backoff sleeps
        t_kill = time.monotonic()
        killer.execute(f"kill query {sess.conn_id}")
        th.join(timeout=2.0)
    assert not th.is_alive(), "statement survived KILL QUERY"
    assert isinstance(result.get("err"), QueryKilledError), result
    assert result["t"] - t_kill < 0.5, "KILL latency exceeded bound"
    assert sess.last_termination == "killed"
    assert _metric("stmt_terminated_killed_total") == k0 + 1
    assert _wait_no_select_threads() == [], "leaked producer threads"
    sess.execute("set tidb_use_tpu = 1")
    # the session is healthy afterwards (KILL QUERY, not CONNECTION)
    assert sess.query("select count(*) from t") == [(2000,)]


# ---------------------------------------------------------------------------
# max_execution_time: deadline between device dispatches
# ---------------------------------------------------------------------------


def test_max_execution_time_terminates_scan(domain, sess):
    """A long scan is terminated between host-side dispatch units with
    termination reason 'timeout' visible in SLOW_QUERY, /metrics and the
    trace (ISSUE 5 acceptance)."""
    sess.execute("set tidb_slow_log_threshold = 0")
    sess.execute("set max_execution_time = 50")
    t0 = _metric("stmt_terminated_timeout_total")
    sql = "select sum(x), count(*) from t"

    # each mesh range dispatch is preceded by an 80ms stall (an injected
    # slow device), so the 50ms deadline passes before the next host seam
    def slow_device(**ctx):
        time.sleep(0.08)

    with failpoint("mesh/device_error", slow_device):
        with pytest.raises(MaxExecutionTimeExceeded) as ei:
            sess.query(sql)
    assert ei.value.code == 3024
    assert sess.last_termination == "timeout"
    # the trace tags the failing statement
    assert (sess.last_trace.root.attrs or {}).get("termination") == "timeout"
    sess.execute("set max_execution_time = 0")
    assert _metric("stmt_terminated_timeout_total") == t0 + 1
    # ... and SLOW_QUERY exposes the TERMINATION column
    rows = sess.query(
        "select termination, query from information_schema.slow_query")
    assert ("timeout", sql) in [(r[0], r[1]) for r in rows]
    # ... and the statement summary counts it per digest
    srows = sess.query(
        "select terminations from information_schema.statements_summary"
        " where sample_text = '%s'" % sql)
    assert srows and "timeout:1" in srows[0][0]
    assert _wait_no_select_threads() == []


def test_timeout_interrupts_sleep(sess):
    sess.execute("set max_execution_time = 60")
    t0 = time.monotonic()
    with pytest.raises(MaxExecutionTimeExceeded):
        sess.query("select sleep(5)")
    assert time.monotonic() - t0 < 1.0
    assert sess.last_termination == "timeout"
    sess.execute("set max_execution_time = 0")


def test_kill_interrupts_sleep(domain, sess):
    killer = domain.new_session()

    def kill_soon():
        time.sleep(0.1)
        killer.execute(f"kill query {sess.conn_id}")

    th = threading.Thread(target=kill_soon)
    th.start()
    t0 = time.monotonic()
    with pytest.raises(QueryKilledError):
        sess.query("select sleep(5)")
    th.join()
    assert time.monotonic() - t0 < 1.0
    assert sess.last_termination == "killed"


def test_mem_quota_termination_reason(domain):
    s = domain.new_session()
    s.execute("create table big (a bigint)")
    rows = ", ".join(f"({i})" for i in range(5000))
    s.execute("insert into big values " + rows)
    s.execute("set tidb_mem_quota_query = 1000")
    s.execute("set tidb_oom_action = 'cancel'")
    from tidb_tpu.errors import MemoryQuotaExceededError

    with pytest.raises(MemoryQuotaExceededError):
        # cross join blows the tiny quota before spilling can save it
        s.query("select count(*) from big b1, big b2 where b1.a > b2.a")
    assert s.last_termination == "mem_quota"


# ---------------------------------------------------------------------------
# 2PC + row-lock waits honor the scope
# ---------------------------------------------------------------------------


def test_prewrite_cancellation_rolls_back_locks(domain, sess):
    """A kill mid-prewrite aborts the txn and leaks no locks."""
    sess.execute("begin")
    sess.execute("insert into t values (9001, 0, 1.0), (9002, 0, 2.0),"
                 " (9003, 0, 3.0)")

    fired = {"n": 0}

    def cancel_on_second(**ctx):
        fired["n"] += 1
        if fired["n"] == 2:
            sess.cancel_query("killed")

    with failpoint("2pc/prewrite", cancel_on_second):
        with pytest.raises(QueryKilledError):
            sess.execute("commit")
    for tid in domain.storage.table_ids():
        assert domain.storage.table(tid).locks == {}, "leaked locks"
    assert sess.query("select count(*) from t where k >= 9001") == [(0,)]


def test_lock_wait_interruptible(domain, sess):
    """KILL wakes a session parked in a pessimistic row-lock wait
    instead of letting it poll out innodb_lock_wait_timeout (50s)."""
    holder = domain.new_session()
    holder.execute("begin")
    holder.execute("select x from t where k = 1 for update")

    waiter = domain.new_session()
    waiter.execute("begin")
    result = {}

    def run():
        try:
            # blocks in the pessimistic row-lock wait on holder's lock
            waiter.execute("select x from t where k = 1 for update")
        except TiDBTPUError as e:
            result["err"] = e
        result["t"] = time.monotonic()

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.15)  # waiter is inside the lock-wait loop now
    t_kill = time.monotonic()
    waiter.kill()
    th.join(timeout=2.0)
    assert not th.is_alive(), "lock wait ignored the kill"
    assert isinstance(result.get("err"), QueryKilledError)
    assert result["t"] - t_kill < 0.5
    waiter.rollback()
    holder.execute("rollback")


# ---------------------------------------------------------------------------
# contextvar hygiene
# ---------------------------------------------------------------------------


def test_scope_deactivates_after_statement(sess):
    sess.query("select count(*) from t")
    assert current_scope() is NULL_SCOPE


def test_nested_execute_shares_outer_scope(sess):
    """EXECUTE of a prepared statement runs under the OUTER statement's
    scope: one deadline governs the whole top-level statement."""
    seen = {}
    sc = QueryScope(timeout_s=30.0)
    token = activate_scope(sc)
    try:
        sess.execute("prepare p1 from 'select count(*) from t'")
        sess.execute("execute p1")
        seen["scope"] = current_scope()
    finally:
        deactivate_scope(token)
    assert seen["scope"] is sc
    # the nested statements did not clobber the session's view
    assert sess.last_termination == "ok"


# ---------------------------------------------------------------------------
# the server front door: admission + drain (async, over the real wire)
# ---------------------------------------------------------------------------

from tidb_tpu.server import MySQLServer  # noqa: E402
from tidb_tpu.server import protocol as P  # noqa: E402
from tidb_tpu.server.packet import (  # noqa: E402
    PacketReader,
    PacketWriter,
    read_lenenc_int,
    read_lenenc_str,
)


class WireClient:
    """Just enough protocol 4.1 for lifecycle tests (handshake +
    COM_QUERY text results/errors)."""

    def __init__(self, host, port):
        self.host, self.port = host, port

    async def connect(self, db="test"):
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port)
        self.pr = PacketReader(self.reader)
        self.pw = PacketWriter(self.writer)
        greeting = await self.pr.recv()
        if greeting and greeting[0] == 0xFF:  # rejected pre-handshake
            code = struct.unpack_from("<H", greeting, 1)[0]
            raise ConnectionRefusedError(f"server rejected: {code}")
        assert greeting[0] == 10
        caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
        if db:
            caps |= P.CLIENT_CONNECT_WITH_DB
        resp = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        resp += bytes([33]) + b"\x00" * 23
        resp += b"root\x00" + b"\x00"
        if db:
            resp += db.encode() + b"\x00"
        self.pw.seq = self.pr.seq
        await self.pw.send(resp)
        ok = await self.pr.recv()
        assert ok[0] == 0x00, ok

    async def send_query(self, sql: str):
        self.pw.reset_seq()
        await self.pw.send(b"\x03" + sql.encode())

    async def read_result(self):
        first = await self.pr.recv()
        if first[0] == 0x00:
            return {"ok": True}
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            return {"error": code, "message": first[9:].decode()}
        ncols, _ = read_lenenc_int(first, 0)
        for _ in range(ncols):
            await self.pr.recv()
        await self.pr.recv()  # eof
        rows = []
        while True:
            pkt = await self.pr.recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            pos, row = 0, []
            for _ in range(ncols):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    v, pos = read_lenenc_str(pkt, pos)
                    row.append(v.decode())
            rows.append(tuple(row))
        return {"rows": rows}

    async def query(self, sql: str):
        await self.send_query(sql)
        return await self.read_result()

    def close(self):
        self.writer.close()


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


def test_connection_cap_fast_rejects():
    """Connections past max_connections get ERR 1040 instead of a
    handshake (no unbounded accept queue)."""
    async def body():
        srv = MySQLServer(port=0, max_connections=2)
        await srv.start()
        try:
            c1, c2 = WireClient(srv.host, srv.port), \
                WireClient(srv.host, srv.port)
            await c1.connect()
            await c2.connect()
            r0 = REGISTRY.snapshot().get(
                "server_connections_rejected_total", 0)
            c3 = WireClient(srv.host, srv.port)
            with pytest.raises(ConnectionRefusedError, match="1040"):
                await c3.connect()
            assert REGISTRY.snapshot().get(
                "server_connections_rejected_total", 0) == r0 + 1
            # a freed slot admits the next client
            c1.close()
            await asyncio.sleep(0.05)
            c4 = WireClient(srv.host, srv.port)
            await c4.connect()
            r = await c4.query("select 1")
            assert r["rows"] == [("1",)]
            c2.close()
            c4.close()
        finally:
            await srv.stop()
            srv.domain.maintenance.stop()

    run(body())


def test_admission_queue_full_fast_rejects():
    """With one worker busy and a zero-length queue, a concurrent
    statement is rejected immediately with a MySQL-level error — no
    unbounded queue growth (ISSUE 5 acceptance)."""
    async def body():
        srv = MySQLServer(port=0, workers=1, max_queued=0)
        await srv.start()
        try:
            busy, probe = WireClient(srv.host, srv.port), \
                WireClient(srv.host, srv.port)
            await busy.connect()
            await probe.connect()
            await busy.send_query("select sleep(0.6)")
            await asyncio.sleep(0.1)  # the worker slot is now held
            a0 = REGISTRY.snapshot().get("admission_rejected_total", 0)
            t0 = time.monotonic()
            r = await probe.query("select 1")
            assert r.get("error") == 1040, r
            assert "overloaded" in r["message"]
            assert time.monotonic() - t0 < 0.4, "rejection was not fast"
            assert REGISTRY.snapshot().get(
                "admission_rejected_total", 0) == a0 + 1
            assert REGISTRY.snapshot().get(
                "stmt_terminated_overload_total", 0) >= 1
            # the running statement is unaffected
            r = await busy.read_result()
            assert r["rows"] == [("0",)]
            # with the slot free, the same client is admitted again
            r = await probe.query("select 1")
            assert r["rows"] == [("1",)]
            busy.close()
            probe.close()
        finally:
            await srv.stop()
            srv.domain.maintenance.stop()

    run(body())


def test_admission_queue_deadline():
    """A statement allowed to queue but not served within the queue
    deadline is rejected (bounded wait, not unbounded)."""
    async def body():
        srv = MySQLServer(port=0, workers=1, max_queued=4,
                          queue_deadline_s=0.15)
        await srv.start()
        try:
            busy, waiter = WireClient(srv.host, srv.port), \
                WireClient(srv.host, srv.port)
            await busy.connect()
            await waiter.connect()
            await busy.send_query("select sleep(0.8)")
            await asyncio.sleep(0.1)
            t0 = time.monotonic()
            r = await waiter.query("select 1")
            dt = time.monotonic() - t0
            assert r.get("error") == 1040 and "deadline" in r["message"]
            assert 0.1 < dt < 0.6, f"queue deadline not honored ({dt:.2f}s)"
            r = await busy.read_result()
            assert r["rows"] == [("0",)]
            busy.close()
            waiter.close()
        finally:
            await srv.stop()
            srv.domain.maintenance.stop()

    run(body())


def test_graceful_drain_finishes_inflight_then_closes():
    """shutdown(): the in-flight statement completes and its rows reach
    the client, new connections are refused, and the listener closes —
    leaking no producer threads (ISSUE 5 acceptance)."""
    async def body():
        srv = MySQLServer(port=0)
        await srv.start()
        cli = WireClient(srv.host, srv.port)
        await cli.connect()
        await cli.query("create table d (a bigint)")
        await cli.query("insert into d values (42)")
        await cli.send_query("select a, sleep(1.0) from d")
        # the statement must be EXECUTING (not just parked in the
        # admission queue) before the drain starts, or a loaded box
        # races the drain's in-flight census — 0.05 s flaked under
        # full-suite load on the 1-vCPU harness
        await asyncio.sleep(0.3)
        drain = asyncio.ensure_future(srv.shutdown(drain_s=5.0))
        await asyncio.sleep(0.05)
        # mid-drain: the listener is closed to NEW work
        with pytest.raises((ConnectionRefusedError, OSError)):
            c2 = WireClient(srv.host, srv.port)
            await c2.connect()
        # ... but the in-flight statement runs to completion
        r = await cli.read_result()
        assert r["rows"] == [("42", "0")]
        await drain
        srv.domain.maintenance.stop()

    run(body())
    assert _wait_no_select_threads() == []


def test_drain_cancels_survivors_with_shutdown_reason():
    """A statement still running past the drain budget is cancelled
    through its scope: the client gets ERR 1053 (shutdown in progress)
    rather than a hang or a bare connection reset."""
    async def body():
        srv = MySQLServer(port=0)
        await srv.start()
        cli = WireClient(srv.host, srv.port)
        await cli.connect()
        await cli.send_query("select sleep(30)")
        await asyncio.sleep(0.1)
        t0 = time.monotonic()
        await srv.shutdown(drain_s=0.1)
        r = await cli.read_result()
        assert r.get("error") == 1053, r
        assert time.monotonic() - t0 < 5.0
        s0 = REGISTRY.snapshot()
        assert s0.get("server_drain_cancelled_total", 0) >= 1
        assert s0.get("stmt_terminated_shutdown_total", 0) >= 1
        srv.domain.maintenance.stop()

    run(body())
    assert _wait_no_select_threads() == []


def test_wire_read_span_records_socket_wait(domain):
    """ROADMAP PR-4 (c): the statement's trace carries an asyncio-level
    wire.read span with the measured socket wait, distinct from the
    admission.wait span."""
    async def body():
        srv = MySQLServer(domain, port=0)
        await srv.start()
        try:
            cli = WireClient(srv.host, srv.port)
            await cli.connect()
            await cli.query("create table w (a bigint)")
            await asyncio.sleep(0.12)  # client think time = socket wait
            await cli.query("select a from w")
            sess = next(iter(srv.domain.sessions.values()))
            tr = sess.last_trace
            spans = {sp.name: sp for sp in tr.root.children}
            assert "wire.read" in spans
            # the span carries the payload size AND the measured wait
            assert spans["wire.read"].attrs["bytes"] > 0
            assert spans["wire.read"].dur_ns >= int(0.1 * 1e9)
            cli.close()
        finally:
            await srv.stop()

    run(body())


def test_periodic_handoff_checkpoint():
    """Lifecycle follow-up (d): with tidb_tpu_handoff_checkpoint_s set,
    the server eagerly parks prepared-session state on the coordination
    plane on a timer — a SIGKILLed process (no drain) loses at most one
    interval, because the replacement replays the latest checkpoint."""
    from tidb_tpu.coord import get_plane
    from tidb_tpu.metrics import REGISTRY
    from tidb_tpu.session import Domain

    async def body():
        dom = Domain()
        dom.global_vars["tidb_tpu_handoff_checkpoint_s"] = "1"
        srv = MySQLServer(dom, port=0)
        await srv.start()
        try:
            sess = dom.new_session()
            sess.execute("set tidb_slow_log_threshold = 777")
            sess.execute("create table ck (a bigint)")
            sess.execute("prepare pck from 'select count(*) from ck'")
            m0 = REGISTRY.get("coord_handoff_checkpoint_total")
            for _ in range(40):  # first tick lands within ~1s
                await asyncio.sleep(0.05)
                if REGISTRY.get("coord_handoff_checkpoint_total") > m0:
                    break
            assert REGISTRY.get("coord_handoff_checkpoint_total") > m0
            # the plane now holds the checkpoint WITHOUT any drain having
            # run — the hard-kill survivability this policy buys
            states = get_plane().take_handoff()
            assert any("pck" in (st.get("prepared") or {})
                       and st.get("sysvars", {}).get(
                           "tidb_slow_log_threshold") == "777"
                       for st in states)
        finally:
            await srv.shutdown(drain_s=0.0)
            dom.maintenance.stop()
            # the drain itself re-parks the prepared session on the
            # process-global plane; drain it so later tests' servers
            # don't adopt this test's session
            get_plane().take_handoff()

    run(body())
