"""Tier-1 gate for tidb_tpu.lint — the project-native static-analysis
suite (hot-path purity lint, plan/schema typechecker, kernel-contract
checker).

Two halves:

1. the GATE: the full suite over today's tree must produce zero findings
   outside the checked-in, justified baseline allowlist (the same check
   `python -m tidb_tpu.lint` runs in CI);
2. NEGATIVE tests: each pass family must catch a seeded violation —
   host-sync in copr code, a schema-mismatched plan node, a shape-broken
   kernel — otherwise the gate is a rubber stamp.

Everything runs host-side (conftest pins JAX_PLATFORMS=cpu), so this
signal survives TPU-tunnel outages.
"""

import textwrap

import pytest

from tidb_tpu.lint import assign_ordinals, run_all
from tidb_tpu.lint.baseline import apply, load_baseline
from tidb_tpu.lint.purity import lint_source


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def test_tree_clean_vs_baseline():
    """`python -m tidb_tpu.lint` semantics: no finding outside the
    baseline allowlist.  Stale entries are reported but non-fatal (a fix
    must never be punished) — they surface in the assertion message only
    when something else fails."""
    findings = run_all()
    new, stale = apply(findings, load_baseline())
    assert not new, (
        "new static-analysis findings (fix them or baseline with a "
        "justification):\n" + "\n".join(f.render() for f in new)
        + ("\nstale baseline entries: " + ", ".join(stale) if stale else "")
    )


def test_finding_keys_stable_under_line_drift():
    """Baseline keys must not contain line numbers: the same violation on
    a different line keeps its identity; a second identical one gets the
    next ordinal."""
    src = "import jax\nimport numpy as np\n\ndef f(x):\n    a = np.asarray(x)\n    b = np.asarray(x)\n    return a, b\n"
    shifted = "import jax\nimport numpy as np\n\n# pushed down two lines\n\ndef f(x):\n    a = np.asarray(x)\n    b = np.asarray(x)\n    return a, b\n"
    k1 = [f.key for f in assign_ordinals(lint_source(src, "tidb_tpu/copr/x.py"))]
    k2 = [f.key for f in assign_ordinals(lint_source(shifted, "tidb_tpu/copr/x.py"))]
    assert k1 == k2 and len(set(k1)) == 2


# ---------------------------------------------------------------------------
# purity: device-array provenance (lint follow-up (a))
# ---------------------------------------------------------------------------


def test_purity_no_jax_import_means_no_host_sync():
    """A module that never imports jax cannot hold a device array, so
    np.asarray there is a host normalization, not a sync — the rule that
    retired 11 baseline allowlist entries."""
    src = textwrap.dedent("""
        import numpy as np

        def route(vals):
            return np.asarray(sorted(vals), dtype=np.int64)
    """)
    assert lint_source(src, "tidb_tpu/executor/seeded.py") == []


def test_purity_jit_result_readback_is_boundary():
    """np.asarray on the direct result of a jit-bound callable is the
    designed readback boundary (program finished, single transfer) —
    not a hazard; any OTHER np.asarray in the same module still is."""
    src = textwrap.dedent("""
        import jax
        import numpy as np

        def make(fn):
            jitted = jax.jit(fn)

            def call(*args):
                out = jitted(*args)
                buf = np.asarray(out)            # designed readback
                also = np.asarray(jitted(args))  # direct-call form
                return buf, also

            return call

        def leak(x):
            return np.asarray(x)  # unknown provenance: still flagged
    """)
    fs = lint_source(src, "tidb_tpu/copr/seeded.py")
    assert [(f.rule, f.scope) for f in fs] == [("host-sync", "leak")]


def test_purity_boundary_names_are_function_scoped():
    """A boundary name in one function must not whitelist the SAME bare
    name holding a device array in a sibling function."""
    src = textwrap.dedent("""
        import jax
        import numpy as np

        def reader(fn):
            jitted = jax.jit(fn)
            out = jitted(1)
            return np.asarray(out)      # boundary: fine

        def other(device_array):
            out = device_array + 1
            return np.asarray(out)      # same name, NOT a boundary
    """)
    fs = lint_source(src, "tidb_tpu/copr/seeded.py")
    assert [(f.rule, f.scope) for f in fs] == [("host-sync", "other")]


# ---------------------------------------------------------------------------
# purity: seeded violations per rule
# ---------------------------------------------------------------------------


def _rules(findings):
    return {f.rule for f in findings}


def test_purity_catches_host_sync_in_copr():
    src = textwrap.dedent("""
        import jax
        import numpy as np

        def fetch_tile(buf):
            arr = jax.device_get(buf)
            arr.block_until_ready()
            return np.asarray(arr)
    """)
    fs = lint_source(src, "tidb_tpu/copr/seeded.py")
    assert _rules(fs) == {"host-sync"}
    assert {f.token for f in fs} == {"jax.device_get", ".block_until_ready",
                                     "np.asarray"}


def test_purity_catches_row_loops():
    """Python row loops over chunk data — the seeded specimen is the OLD
    ADMIN CHECKSUM implementation (per-row repr()/crc32 walk), replaced
    by the columnar digest in this PR: proof the rule catches exactly
    the hazard class the advisor flagged."""
    old_checksum = textwrap.dedent("""
        import zlib

        def _checksum_table(store, dele):
            crc = kvs = nbytes = 0
            n = store.base_rows
            step = 1 << 16
            for lo in range(0, n, step):
                chunk = store.base_chunk(range(store.n_cols), lo,
                                         min(lo + step, n))
                for off, row in enumerate(chunk.to_pylist()):
                    if lo + off in dele:
                        continue
                    raw = repr(row).encode()
                    crc ^= zlib.crc32(raw)
                    kvs += 1
                    nbytes += len(raw)
            return crc, kvs, nbytes
    """)
    fs = lint_source(old_checksum, "tidb_tpu/executor/seeded.py")
    assert any(f.rule == "row-loop" and f.token == ".to_pylist" for f in fs)
    # and the range(.num_rows) loop form
    loop = textwrap.dedent("""
        def agg(chunk):
            total = 0
            for i in range(chunk.num_rows):
                total += chunk.col(0).get(i)
            return total
    """)
    fs2 = lint_source(loop, "tidb_tpu/executor/seeded2.py")
    assert any(f.rule == "row-loop" and f.token == "range(num_rows)"
               for f in fs2)


def test_purity_catches_jit_hazards():
    src = textwrap.dedent("""
        import time
        import jax
        import numpy as np

        @jax.jit
        def kern(x):
            t = time.time()
            r = np.random.uniform()
            v = float(x)
            return x * t * r * v

        def host(x):
            return float(x) + time.time()  # NOT jitted: no finding
    """)
    fs = lint_source(src, "tidb_tpu/ops/seeded.py")
    assert _rules(fs) == {"time-in-jit", "rng-in-jit", "tracer-coercion"}
    assert all(f.scope == "kern" for f in fs)


def test_purity_catches_unhashable_static_args():
    """The spec binds to the JITTED name (build_j), not the wrapped
    original: build(x, dims=[...]) is a legal plain-Python call and must
    not be flagged; build_j(x, dims=[...]) raises at call time and must."""
    src = textwrap.dedent("""
        import jax

        def build(x, dims):
            return x

        build_j = jax.jit(build, static_argnames=("dims",))

        def run(x):
            return build_j(x, dims=[1, 2])

        def host(x):
            return build(x, dims=[1, 2])  # unjitted original: legal
    """)
    fs = lint_source(src, "tidb_tpu/copr/seeded.py")
    assert _rules(fs) == {"static-unhashable"}
    assert [f.token for f in fs] == ["build_j"]
    # decorator form with positional static args
    dec = textwrap.dedent("""
        from functools import partial

        import jax

        @partial(jax.jit, static_argnums=(1,))
        def kern(x, dims):
            return x

        def run(x):
            return kern(x, [1, 2])
    """)
    fs2 = lint_source(dec, "tidb_tpu/copr/seeded2.py")
    assert any(f.rule == "static-unhashable" and f.token == "kern"
               for f in fs2)


# ---------------------------------------------------------------------------
# plancheck: seeded schema-mismatched plan nodes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def corpus_session():
    from tidb_tpu.lint.plancheck import _canonical_session

    return _canonical_session()


def _planned(s, sql):
    from tidb_tpu.parser import parse_one

    return s._plan(parse_one(sql))


def _first_reader(phys):
    from tidb_tpu.lint.kernelcheck import _reader_dags

    readers = _reader_dags(phys)
    assert readers, "expected a cop reader in the plan"
    return readers[0]


def test_plancheck_clean_plan_passes(corpus_session):
    from tidb_tpu.lint.plancheck import check_plan

    phys = _planned(corpus_session,
                    "select l_orderkey, l_quantity from lineitem"
                    " where l_quantity < 5")
    assert check_plan(phys) == []


def test_plancheck_catches_out_of_range_scan_offset(corpus_session):
    from tidb_tpu.lint.plancheck import check_plan

    phys = _planned(corpus_session,
                    "select l_orderkey, l_quantity from lineitem"
                    " where l_quantity < 5")
    _node, dag = _first_reader(phys)
    dag.executors[0].columns[0] = 999  # seed: scan points past storage
    problems = check_plan(phys)
    assert any("store offset 999 out of range" in p for p in problems)


def test_plancheck_catches_reader_schema_mismatch(corpus_session):
    from tidb_tpu.lint.plancheck import (PlanCheckError, assert_plan,
                                         check_plan)

    phys = _planned(corpus_session,
                    "select l_orderkey, l_quantity from lineitem"
                    " where l_quantity < 5")
    node, _dag = _first_reader(phys)
    node.schema.cols.pop()  # seed: reader schema narrower than its DAG
    problems = check_plan(phys)
    assert any("reader schema width" in p for p in problems)
    with pytest.raises(PlanCheckError):
        assert_plan(phys)


def test_plancheck_catches_unregistered_pushed_function(corpus_session):
    from tidb_tpu.lint.plancheck import check_plan

    phys = _planned(corpus_session,
                    "select l_orderkey from lineitem where l_quantity < 5")
    _node, dag = _first_reader(phys)
    from tidb_tpu.copr.ir import SelectionIR

    sel = next(ex for ex in dag.executors if isinstance(ex, SelectionIR))
    for e in sel.conditions:
        if getattr(e, "name", None):
            e.name = "totally_not_pushable"  # seed: rewrite broke registry
    problems = check_plan(phys)
    assert any("not in the TPU-executable registry" in p for p in problems)


def test_check_plan_session_var_wired(corpus_session):
    """tidb_check_plan (default on) feeds PhysicalContext.check_plan, the
    finish_plan hook that vets every planner rewrite's OUTPUT."""
    s = corpus_session
    assert s._pctx().check_plan is True
    s.execute("set tidb_check_plan = 0")
    try:
        assert s._pctx().check_plan is False
    finally:
        s.execute("set tidb_check_plan = 1")


def test_lint_canonical_plan_corpus_clean():
    from tidb_tpu.lint.plancheck import lint_canonical_plans

    assert lint_canonical_plans() == []


# ---------------------------------------------------------------------------
# kernelcheck: shape-broken kernels and regression guards
# ---------------------------------------------------------------------------


def _lineitem_table(s):
    dom = s.domain
    return dom.storage.table(
        dom.catalog.info_schema().table("test", "lineitem").id)


def test_kernelcheck_traces_clean_kernel(corpus_session):
    from tidb_tpu.lint.kernelcheck import trace_kernel

    phys = _planned(corpus_session,
                    "select sum(l_quantity) from lineitem"
                    " where l_discount < 0.05")
    _node, dag = _first_reader(phys)
    stats = trace_kernel(_lineitem_table(corpus_session), dag)
    assert stats["eqns"] > 0 and stats["i64_eqns"] >= 0


def test_kernelcheck_catches_shape_broken_kernel(corpus_session):
    from tidb_tpu.copr.ir import SelectionIR
    from tidb_tpu.expr.expression import ColumnExpr
    from tidb_tpu.lint.kernelcheck import trace_kernel

    phys = _planned(corpus_session,
                    "select sum(l_quantity) from lineitem"
                    " where l_discount < 0.05")
    _node, dag = _first_reader(phys)
    sel = next(ex for ex in dag.executors if isinstance(ex, SelectionIR))

    def break_refs(e):
        if isinstance(e, ColumnExpr):
            e.index = 99  # seed: ref past every scanned column
        for a in getattr(e, "args", ()):
            break_refs(a)

    for c in sel.conditions:
        break_refs(c)
    with pytest.raises(Exception):
        trace_kernel(_lineitem_table(corpus_session), dag)


def test_metric_name_pass_catches_violations():
    """ISSUE 13: every literal metric name must match [a-z0-9_]+ and
    carry a conventional suffix — the fleet merge keys sum-vs-gauge
    semantics off `_total`, so a misnamed counter silently becomes a
    per-host gauge."""
    from tidb_tpu.lint.metricnames import lint_source as lint_metrics

    src = textwrap.dedent("""
        from tidb_tpu.metrics import REGISTRY

        def f(cls):
            REGISTRY.inc("Bad-Name")
            REGISTRY.inc("queries_served")
            REGISTRY.inc("queries_served_total")
            REGISTRY.observe_hist("lat_ms", 1.0)
            REGISTRY.observe_hist("lat", 1.0)
            REGISTRY.set("queue_depth", 3)
            REGISTRY.inc(f"slo_{cls}_breach_total")
            REGISTRY.inc(f"trace_phase_{cls}")
    """)
    fs = lint_metrics(src, "tidb_tpu/x.py")
    tokens = {f.token for f in fs}
    assert "Bad-Name" in tokens                # charset violation
    assert "queries_served" in tokens          # counter missing _total
    assert "lat" in tokens                     # histogram missing unit
    assert "queries_served_total" not in tokens
    assert "lat_ms" not in tokens
    assert "queue_depth" not in tokens
    # f-strings: literal tail is checked, dynamic tail is skipped
    assert "slox_breach_total" not in tokens
    assert "trace_phase_x" not in tokens


def test_metric_name_pass_runs_in_cli_families():
    from tidb_tpu.lint import PASS_RULES

    assert PASS_RULES["metric"] == ("metric-name",)


def test_kernelcheck_detects_int64_chain_growth():
    """A tightened baseline must flip the suite red: this is the guard
    against reintroducing the int64-emulation chains VERDICT.md names as
    the Q1 VPU bottleneck (and a live negative test of the whole
    lint_kernels loop, recompile-bomb census included)."""
    from tidb_tpu.lint.kernelcheck import lint_kernels

    base = {name: {"i64_eqns": 0}
            for name in ("q1-dense-agg", "q6-scalar-agg", "filter-project",
                         "topn", "minmax-agg")}
    base["__signatures__"] = {"max": 10_000}
    findings = lint_kernels(baseline_kernels=base)
    growth = [f for f in findings if "int64 equation count grew" in f.message]
    assert growth, "expected int64-growth findings against a zeroed baseline"
    # and no OTHER finding kinds fired (kernels themselves are healthy)
    assert {f.rule for f in findings} == {"kernel-contract"}
    assert not [f for f in findings if "trace failed" in f.message]


def test_concur_catches_unregistered_lock():
    """ISSUE 16: every lock construction goes through util_concurrency
    with a declared rank — a raw threading.Lock is invisible to both
    the static order graph and the runtime witness."""
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py")
    assert [(f.rule, f.path, f.line) for f in fs] == \
        [("lock-rank", "tidb_tpu/mymod.py", 6)]


def test_concur_catches_rank_inverting_nested_with():
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        from tidb_tpu.util_concurrency import make_lock

        class C:
            def __init__(self):
                self._a = make_lock("mymod:C._a")
                self._b = make_lock("mymod:C._b")

            def f(self):
                with self._a:
                    with self._b:
                        pass
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py",
                     ranks={"mymod:C._a": 2, "mymod:C._b": 1})
    assert [(f.rule, f.path, f.line) for f in fs] == \
        [("lock-order", "tidb_tpu/mymod.py", 11)]
    assert "rank" in fs[0].message
    # same code under the consistent rank order is clean
    assert lint_concur(src, "tidb_tpu/mymod.py",
                       ranks={"mymod:C._a": 1, "mymod:C._b": 2}) == []


def test_concur_catches_sleep_under_lock():
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        import time

        from tidb_tpu.util_concurrency import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("mymod:C._mu")

            def f(self):
                with self._mu:
                    time.sleep(0.1)
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py", ranks={"mymod:C._mu": 1})
    assert [(f.rule, f.path, f.line, f.token) for f in fs] == \
        [("lock-blocking", "tidb_tpu/mymod.py", 12, "time.sleep")]


def test_concur_catches_guarded_attr_read_bare():
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        from tidb_tpu.util_concurrency import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("mymod:C._mu")
                self.x = 0

            def bump(self):
                with self._mu:
                    self.x += 1

            def peek(self):
                return self.x
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py", ranks={"mymod:C._mu": 1})
    assert [(f.rule, f.path, f.line, f.token) for f in fs] == \
        [("lock-guard", "tidb_tpu/mymod.py", 14, "x")]


def test_concur_cross_object_guard_catches_unheld_store():
    """ISSUE 20 satellite: a class declaring `_guarded_by_` puts its
    instance state under ANOTHER object's lock — plain stores and
    container-mutator calls through a ctor-typed local must hold it."""
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        from tidb_tpu.util_concurrency import make_lock

        class _Job:
            _guarded_by_ = "mymod:Plane._mu"

            def __init__(self):
                self.items = []
                self.closed = False

        class Plane:
            def __init__(self):
                self._mu = make_lock("mymod:Plane._mu")
                self._jobs = {}

            def good(self, key):
                with self._mu:
                    j = _Job()
                    j.items.append(key)
                    self._jobs[key] = j

            def bad(self, key):
                j = _Job()
                j.closed = True
                j.items.append(key)
                with self._mu:
                    self._jobs[key] = j
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py",
                     ranks={"mymod:Plane._mu": 1})
    hits = sorted((f.rule, f.line, f.token) for f in fs)
    assert hits == [("lock-guard", 24, "_Job.closed"),
                    ("lock-guard", 25, "_Job.items")], fs


def test_concur_cross_object_guard_allows_lockfree_loads():
    """Loads through a guarded-typed local (the batcher's lock-free
    Event handshake) never flag; annotated helper args are typed too,
    and *_locked helpers of the lock's owner count as held."""
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        import threading

        from tidb_tpu.util_concurrency import make_lock

        class _Job:
            _guarded_by_ = "mymod:Plane._mu"

            def __init__(self):
                self.items = []
                self.done = threading.Event()

        class Plane:
            def __init__(self):
                self._mu = make_lock("mymod:Plane._mu")

            def peek(self, j: "_Job"):
                return len(j.items), j.done.is_set()

            def _push_locked(self, j: "_Job", key):
                j.items.append(key)
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py",
                     ranks={"mymod:Plane._mu": 1})
    assert [f for f in fs if f.rule == "lock-guard"] == [], fs


def test_concur_catches_wait_whose_notifier_needs_held_lock():
    """ISSUE 17 concurrency (a): a `.wait()` under a held ranked lock
    whose notifier acquires a lock ranked at or below the waiter's is
    the classic condition-under-lock deadlock — the notifier blocks
    behind the very lock the waiter holds, so the wait never wakes."""
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        import threading

        from tidb_tpu.util_concurrency import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("mymod:C._mu")
                self._cv = threading.Condition()

            def consume(self):
                with self._mu:
                    with self._cv:
                        self._cv.wait()

            def produce(self):
                with self._mu:
                    with self._cv:
                        self._cv.notify()
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py", ranks={"mymod:C._mu": 1})
    waits = [(f.rule, f.line, f.token) for f in fs if f.rule == "lock-wait"]
    assert waits == [("lock-wait", 14, "self._cv")]


def test_concur_wait_clean_when_lock_released_first():
    from tidb_tpu.lint.concur import lint_source as lint_concur

    src = textwrap.dedent("""
        import threading

        from tidb_tpu.util_concurrency import make_lock

        class C:
            def __init__(self):
                self._mu = make_lock("mymod:C._mu")
                self._cv = threading.Condition()

            def consume(self):
                with self._cv:
                    self._cv.wait()

            def produce(self):
                with self._mu:
                    pass
                with self._cv:
                    self._cv.notify()
    """)
    fs = lint_concur(src, "tidb_tpu/mymod.py", ranks={"mymod:C._mu": 1})
    assert [f for f in fs if f.rule == "lock-wait"] == []


def test_concur_pass_runs_in_cli_families():
    from tidb_tpu.lint import PASS_RULES

    assert PASS_RULES["concur"] == (
        "lock-rank", "lock-order", "lock-blocking", "lock-guard",
        "lock-wait")


def test_chaoscover_flags_untested_failpoints(tmp_path):
    """ISSUE 20 satellite: every FAILPOINTS.hit site name must appear
    in at least one test — literal names, module-level constants and
    cross-module *_FAILPOINT imports all resolve; computed names are
    themselves findings."""
    from tidb_tpu.lint.chaoscover import lint_tree as lint_chaos

    pkg = tmp_path / "tidb_tpu"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "names.py").write_text(
        'SHARED_FAILPOINT = "store/shared_site"\n')
    (pkg / "sub" / "mod.py").write_text(textwrap.dedent("""
        from ..names import SHARED_FAILPOINT

        LOCAL_FP = "store/local_site"

        def f(x):
            FAILPOINTS.hit("store/covered_site", a=1)
            FAILPOINTS.hit("store/orphan_site")
            FAILPOINTS.hit(LOCAL_FP)
            FAILPOINTS.hit(SHARED_FAILPOINT)
            FAILPOINTS.hit("x/" + x)
    """))
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_mod.py").write_text(
        '# arms store/covered_site and store/shared_site\n')
    fs = lint_chaos(str(tmp_path))
    by_token = {f.token: f for f in fs}
    assert "store/orphan_site" in by_token
    assert "store/local_site" in by_token  # constant resolved, untested
    assert "store/covered_site" not in by_token
    assert "store/shared_site" not in by_token  # cross-module resolved
    # the computed name is flagged as unresolvable
    unresolved = [f for f in fs if "not statically" in f.message]
    assert len(unresolved) == 1
    # rule family is registered for CLI/baseline staleness
    from tidb_tpu.lint import PASS_RULES

    assert PASS_RULES["chaos"] == ("chaos-cover",)


def test_chaoscover_clean_on_real_tree():
    """Every failpoint in the shipped tree is swept by some test — the
    acceptance the chaos archetype rides on (no baseline debt)."""
    from tidb_tpu.lint.chaoscover import lint_tree as lint_chaos

    assert lint_chaos() == []
