"""Background maintenance: GC worker, compaction scheduling, expensive-query
watchdog.

Reference: store/tikv/gcworker/gc_worker.go:213-289 (safepoint = now -
gc_life_time, bounded by live txn min start_ts), TiFlash delta-merge
scheduling, util/expensivequery/expensivequery.go:50-154 (threshold logs +
max_execution_time kill)."""

import threading
import time

import pytest

from tidb_tpu.errors import QueryKilledError, TiDBTPUError
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()  # tests drive tick() deterministically
    yield dom
    dom.maintenance.stop()


def _chain_len(d, name="t"):
    t = d.catalog.info_schema().table("test", name)
    store = d.storage.table(t.id)
    return sum(len(c) for c in store.delta.values())


def test_gc_prunes_version_chains_under_sustained_dml(d):
    s = d.new_session()
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values (1, 0)")
    for i in range(12):
        s.execute(f"update t set v = {i} where id = 1")
    assert _chain_len(d) >= 12  # one version per update
    d.global_vars["tidb_gc_life_time"] = "0"
    time.sleep(0.01)  # let the safepoint's physical ms pass the commits
    d.maintenance.tick()
    assert _chain_len(d) <= 1  # only the newest survives
    # the row itself is intact
    assert s.query("select v from t") == [(11,)]


def test_gc_respects_live_transaction_snapshot(d):
    s = d.new_session()
    s.execute("create table t (id bigint primary key, v bigint)")
    s.execute("insert into t values (1, 0)")
    reader = d.new_session()
    reader.execute("begin")
    assert reader.query("select v from t") == [(0,)]  # pins start_ts
    for i in range(5):
        s.execute(f"update t set v = {i + 1} where id = 1")
    d.global_vars["tidb_gc_life_time"] = "0"
    d.maintenance.tick()
    # versions the live reader can see survived
    assert reader.query("select v from t") == [(0,)]
    reader.execute("commit")
    time.sleep(0.01)
    d.maintenance.tick()
    assert _chain_len(d) <= 1


def test_compaction_scheduled_by_worker(d):
    """Delta written through the raw txn API (no session commit hooks)
    is folded by the background worker."""
    s = d.new_session()
    s.execute("create table t (id bigint, v bigint)")
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    txn = d.storage.begin()
    for i in range(5000):
        txn.put(t.id, store.alloc_handle(), (i, i))
    txn.commit()
    assert len(store.delta) > 4096  # over the compaction threshold
    d.maintenance.tick()
    assert len(store.delta) == 0  # folded into base blocks
    assert store.base_rows == 5000


def test_expensive_query_flagged(d):
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1), (2), (3)")
    d.global_vars["tidb_expensive_query_time_threshold"] = "0.05"
    before = REGISTRY.snapshot().get("expensive_queries_total", 0)
    done = []

    def slow():
        s.execute("select sleep(0.15) from t")  # ~0.45s across chunks
        done.append(1)

    th = threading.Thread(target=slow)
    th.start()
    time.sleep(0.1)
    d.maintenance.tick()  # statement still running and past threshold
    th.join(10)
    after = REGISTRY.snapshot().get("expensive_queries_total", 0)
    assert after > before


def test_max_execution_time_kills_runaway(d):
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values " +
              ", ".join(f"({i})" for i in range(20)))
    s.execute("set max_execution_time = 100")
    errs = []

    def runaway():
        try:
            # kill flag is checked between executor next() calls: the
            # query surfaces QueryKilled right after the sleep returns
            s.execute("select sleep(1.5) from t limit 1")
            errs.append("completed")
        except QueryKilledError:
            errs.append("killed")
        except TiDBTPUError as e:
            errs.append(type(e).__name__)

    th = threading.Thread(target=runaway)
    th.start()
    for _ in range(60):
        time.sleep(0.05)
        d.maintenance.tick()
        if errs:
            break
    th.join(10)
    assert errs and errs[0] == "killed", errs
    # the session survives (KILL QUERY, not KILL CONNECTION)
    assert s.query("select count(*) from t") == [(20,)]


def test_worker_thread_runs(d):
    before = REGISTRY.snapshot().get("maintenance_ticks_total", 0)
    w = d.maintenance
    w.stop()
    w.interval_s = 0.05
    w.start()
    time.sleep(0.3)
    w.stop()
    assert REGISTRY.snapshot().get("maintenance_ticks_total", 0) > before


def test_conflict_aborted_txn_does_not_pin_safepoint(d):
    """A commit that aborts on write-write conflict must leave the live-txn
    registry (else the GC safepoint is pinned forever)."""
    s = d.new_session()
    s.execute("create table cc (id bigint primary key, v bigint)")
    s.execute("insert into cc values (1, 0)")
    a, b = d.new_session(), d.new_session()
    a.execute("begin")
    a.execute("update cc set v = 1 where id = 1")
    b.execute("begin")
    b.execute("update cc set v = 2 where id = 1")
    a.execute("commit")
    with pytest.raises(TiDBTPUError):
        b.execute("commit")
    assert not d.storage._live_txns


def test_orphan_lock_sweep_resolves_dead_sessions_locks(d):
    """Proactive orphan-lock resolution (PR: degraded-mesh failover):
    TTL-expired locks from txns this process no longer tracks are rolled
    back on the maintenance tick instead of blocking the next writer to
    touch the row (gc_worker.go resolveLocks analog)."""
    s = d.new_session()
    s.execute("create table ol (a bigint primary key, b bigint)")
    s.execute("insert into ol values (1, 10)")
    tid = d.catalog.info_schema().table("test", "ol").id
    store = d.storage.table(tid)

    # a live txn's lock is NEVER swept, even with an expired TTL
    live = d.storage.begin()
    live.lock_keys((tid, 1), ttl_ms=1)
    time.sleep(0.005)
    assert d.maintenance.sweep_orphan_locks() == 0
    assert 1 in store.locks
    live.rollback()

    # crash analog: the lock's owner vanished from the live-txn registry
    dead = d.storage.begin()
    dead.lock_keys((tid, 1), ttl_ms=1)
    d.storage.txn_finished(dead.start_ts)  # process forgot the txn
    time.sleep(0.005)
    before = REGISTRY.snapshot().get("orphan_locks_resolved_total", 0)
    assert d.maintenance.sweep_orphan_locks() == 1
    assert store.locks == {}
    assert REGISTRY.snapshot()["orphan_locks_resolved_total"] == before + 1
    # the row is immediately writable again, no lock-wait needed
    s.execute("update ol set b = 11 where a = 1")
    assert s.query("select b from ol") == [(11,)]
