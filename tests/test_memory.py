"""Memory tracker, OOM actions and sort spill tests (util/memory + disk.go)."""

import pytest

from tidb_tpu.errors import MemoryQuotaExceededError
from tidb_tpu.session import Domain
from tidb_tpu.util_memory import MemTracker


class TestTracker:
    def test_quota_cancel(self):
        t = MemTracker("q", quota=100)
        t.consume(50)
        with pytest.raises(MemoryQuotaExceededError):
            t.consume(60)

    def test_parent_rollup(self):
        root = MemTracker("root", quota=100)
        child = MemTracker("child", parent=root)
        child.consume(60)
        assert root.consumed == 60
        with pytest.raises(MemoryQuotaExceededError):
            child.consume(50)

    def test_spill_hook_prevents_cancel(self):
        t = MemTracker("q", quota=100)
        freed = []

        def hook():
            freed.append(True)
            t.release(80)
            return 80

        t.register_spill(hook)
        t.consume(90)
        t.consume(20)  # would exceed; spill saves it
        assert freed and t.consumed == 30


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table big (a bigint, b double)")
    t = s.domain.catalog.info_schema().table("test", "big")
    store = s.domain.storage.table(t.id)
    import numpy as np

    rng = np.random.default_rng(5)
    store.bulk_load_arrays(
        [rng.integers(0, 1 << 40, 20000, dtype=np.int64),
         rng.uniform(0, 1, 20000)],
        ts=s.domain.storage.current_ts(),
    )
    return s


class TestSpill:
    def test_sort_spills_and_stays_correct(self, sess):
        sess.execute("set tidb_mem_quota_query = 200000")  # ~0.2MB
        rows = sess.query("select a from big order by a")
        vals = [r[0] for r in rows]
        assert vals == sorted(vals) and len(vals) == 20000
        # the spill actually happened (not just an in-memory sort)
        sess.execute("set tidb_mem_quota_query = 0")
        rows2 = sess.query("select a from big order by a")
        assert rows == rows2

    def test_sort_desc_with_spill(self, sess):
        sess.execute("set tidb_mem_quota_query = 200000")
        rows = sess.query("select a from big order by a desc limit 5")
        vals = [r[0] for r in rows]
        assert vals == sorted(vals, reverse=True)[:5]

    def test_join_under_tiny_quota_spills_or_cancels(self, sess):
        """Joins now SPILL under quota (grace hash join) instead of
        cancelling; with a quota too small even for one disk partition the
        grace sub-join cancels (sub-joins never re-spill)."""
        sess.execute("set tidb_mem_quota_query = 50000")
        try:
            rows = sess.query(
                "select count(*) from big x join big y on x.a = y.a")
            # spill path completed: the answer must still be exact
            assert rows[0][0] >= 20000
        except MemoryQuotaExceededError:
            pass  # partition itself exceeded the (tiny) quota: cancelled

    def test_quota_log_action_keeps_running(self, sess):
        sess.execute("set tidb_mem_quota_query = 50000")
        sess.execute("set tidb_oom_action = 'log'")
        rows = sess.query("select count(*) from big x join big y "
                          "on x.a = y.a")
        assert rows[0][0] >= 20000


class TestPartitionedSpill:
    """Join/agg complete under a memory quota that previously OOM-cancelled
    (VERDICT r2 item 8): build sides and agg partials partition to disk and
    merge per partition."""

    def _sess(self):
        import numpy as np

        from tidb_tpu.session import Domain

        d = Domain()
        s = d.new_session()
        s.execute("create table big (k bigint, g bigint, v double)")
        t = d.catalog.info_schema().table("test", "big")
        rng = np.random.default_rng(13)
        n = 120_000
        d.storage.table(t.id).bulk_load_arrays([
            np.arange(n, dtype=np.int64),
            rng.integers(0, 30_000, n, dtype=np.int64),
            rng.uniform(0, 10, n)], ts=d.storage.current_ts())
        d.storage.regions.split_even(t.id, 8, n)
        s.execute("create table dim (k bigint, w bigint)")
        td = d.catalog.info_schema().table("test", "dim")
        nd = 150_000
        d.storage.table(td.id).bulk_load_arrays([
            np.arange(nd, dtype=np.int64) % 30_000,
            np.arange(nd, dtype=np.int64)], ts=d.storage.current_ts())
        d.storage.regions.split_even(td.id, 4, nd)
        s.execute("analyze table big")
        s.execute("analyze table dim")
        s.execute("set tidb_use_tpu = 0")
        return s

    def test_hashagg_spills_and_matches(self):
        from tidb_tpu.metrics import REGISTRY

        s = self._sess()
        q = ("select g, count(*), sum(v) from big group by g "
             "order by g limit 7")
        want = s.query(q)
        s.execute("set tidb_mem_quota_query = 2000000")  # ~1.5MB: trips
        before = REGISTRY.snapshot().get("hashagg_spills_total", 0)
        got = s.query(q)
        after = REGISTRY.snapshot().get("hashagg_spills_total", 0)
        assert after > before, "quota did not trigger a spill"
        import pytest as _pt

        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[:2] == w[:2] and g[2] == _pt.approx(w[2], rel=1e-9)
        s.execute("set tidb_mem_quota_query = 0")

    def test_hashjoin_spills_and_matches(self):
        from tidb_tpu.metrics import REGISTRY

        s = self._sess()
        q = ("select count(*), sum(w) from big join dim on big.g = dim.k "
             "where v < 8")
        want = s.query(q)
        s.execute("set tidb_mem_quota_query = 1200000")
        before = REGISTRY.snapshot().get("hashjoin_spills_total", 0)
        got = s.query(q)
        after = REGISTRY.snapshot().get("hashjoin_spills_total", 0)
        assert after > before, "quota did not trigger a join spill"
        assert got == want
        s.execute("set tidb_mem_quota_query = 0")
