"""Memory tracker, OOM actions and sort spill tests (util/memory + disk.go)."""

import pytest

from tidb_tpu.errors import MemoryQuotaExceededError
from tidb_tpu.session import Domain
from tidb_tpu.util_memory import MemTracker


class TestTracker:
    def test_quota_cancel(self):
        t = MemTracker("q", quota=100)
        t.consume(50)
        with pytest.raises(MemoryQuotaExceededError):
            t.consume(60)

    def test_parent_rollup(self):
        root = MemTracker("root", quota=100)
        child = MemTracker("child", parent=root)
        child.consume(60)
        assert root.consumed == 60
        with pytest.raises(MemoryQuotaExceededError):
            child.consume(50)

    def test_spill_hook_prevents_cancel(self):
        t = MemTracker("q", quota=100)
        freed = []

        def hook():
            freed.append(True)
            t.release(80)
            return 80

        t.register_spill(hook)
        t.consume(90)
        t.consume(20)  # would exceed; spill saves it
        assert freed and t.consumed == 30


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table big (a bigint, b double)")
    t = s.domain.catalog.info_schema().table("test", "big")
    store = s.domain.storage.table(t.id)
    import numpy as np

    rng = np.random.default_rng(5)
    store.bulk_load_arrays(
        [rng.integers(0, 1 << 40, 20000, dtype=np.int64),
         rng.uniform(0, 1, 20000)],
        ts=s.domain.storage.current_ts(),
    )
    return s


class TestSpill:
    def test_sort_spills_and_stays_correct(self, sess):
        sess.execute("set tidb_mem_quota_query = 200000")  # ~0.2MB
        rows = sess.query("select a from big order by a")
        vals = [r[0] for r in rows]
        assert vals == sorted(vals) and len(vals) == 20000
        # the spill actually happened (not just an in-memory sort)
        sess.execute("set tidb_mem_quota_query = 0")
        rows2 = sess.query("select a from big order by a")
        assert rows == rows2

    def test_sort_desc_with_spill(self, sess):
        sess.execute("set tidb_mem_quota_query = 200000")
        rows = sess.query("select a from big order by a desc limit 5")
        vals = [r[0] for r in rows]
        assert vals == sorted(vals, reverse=True)[:5]

    def test_join_quota_cancel(self, sess):
        sess.execute("set tidb_mem_quota_query = 50000")
        with pytest.raises(MemoryQuotaExceededError):
            sess.query("select count(*) from big x join big y on x.a = y.a")

    def test_quota_log_action_keeps_running(self, sess):
        sess.execute("set tidb_mem_quota_query = 50000")
        sess.execute("set tidb_oom_action = 'log'")
        rows = sess.query("select count(*) from big x join big y "
                          "on x.a = y.a")
        assert rows[0][0] >= 20000
