"""Real sort-merge join (executor/merge_join.go analog): vectorized range
merge over key-sorted inputs, verified against HashJoinExec on identical
data for every join kind."""

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.executor.join import HashJoinExec, MergeJoinExec
from tidb_tpu.expr.expression import ColumnExpr, ScalarFunc
from tidb_tpu.session import Domain
from tidb_tpu.types import ty_int, ty_string


class ListExec(Executor):
    def __init__(self, ctx, chunks, ftypes):
        super().__init__(ctx, ftypes, [])
        self.chunks = chunks
        self._i = 0

    def _open(self):
        self._i = 0

    def _next(self):
        if self._i >= len(self.chunks):
            return None
        c = self.chunks[self._i]
        self._i += 1
        return c


@pytest.fixture()
def ctx():
    d = Domain()
    s = d.new_session()
    return ExecContext(d.storage, None, read_ts=d.storage.current_ts(),
                       sess_vars=s.vars)


def _mk(ctx, rows, ftypes, sort_by=0):
    rows = sorted(rows, key=lambda r: (r[sort_by] is None, r[sort_by]))
    cols = [Column.from_values(ft, [r[i] for r in rows])
            for i, ft in enumerate(ftypes)]
    return ListExec(ctx, [Chunk(cols)], ftypes)


def _drain(e):
    e.open()
    out = []
    while True:
        c = e.next()
        if c is None:
            break
        for i in range(c.num_rows):
            out.append(c.row(i))
    e.close()
    return out


LEFT = [(1, "a"), (2, "b"), (2, "bb"), (4, "d"), (None, "n"), (7, "x")]
RIGHT = [(2, 20), (2, 21), (3, 30), (4, 40), (None, -1), (8, 80)]
LT = [ty_int(True), ty_string(True)]
RT = [ty_int(True), ty_int(True)]


@pytest.mark.parametrize("kind", ["inner", "left_outer", "semi", "anti_semi"])
def test_merge_matches_hash(ctx, kind):
    def build(cls, lexec, rexec):
        lk = [ColumnExpr(0, LT[0], "k", -1)]
        rk = [ColumnExpr(0, RT[0], "k", -1)]
        if cls is MergeJoinExec:
            return MergeJoinExec(ctx, lexec, rexec, kind, lk, rk, [])
        return HashJoinExec(ctx, rexec, lexec, kind, rk, lk, [],
                            probe_is_left=True)

    got = _drain(build(MergeJoinExec, _mk(ctx, LEFT, LT), _mk(ctx, RIGHT, RT)))
    want = _drain(build(HashJoinExec, _mk(ctx, LEFT, LT), _mk(ctx, RIGHT, RT)))
    assert sorted(got, key=repr) == sorted(want, key=repr), kind


def test_merge_preserves_left_order(ctx):
    lk = [ColumnExpr(0, LT[0], "k", -1)]
    rk = [ColumnExpr(0, RT[0], "k", -1)]
    e = MergeJoinExec(ctx, _mk(ctx, LEFT, LT), _mk(ctx, RIGHT, RT),
                      "inner", lk, rk, [])
    rows = _drain(e)
    keys = [r[0] for r in rows]
    assert keys == sorted(keys)  # left-order preserved


def test_merge_other_conds(ctx):
    lk = [ColumnExpr(0, LT[0], "k", -1)]
    rk = [ColumnExpr(0, RT[0], "k", -1)]
    cond = ScalarFunc(">", [ColumnExpr(3, RT[1], "v", -1),
                            ColumnExpr(0, LT[0], "k", -1)],
                      ty_int(False), {})
    e = MergeJoinExec(ctx, _mk(ctx, LEFT, LT), _mk(ctx, RIGHT, RT),
                      "inner", lk, rk, [cond])
    rows = _drain(e)
    assert all(r[3] > r[0] for r in rows) and rows


FLOATL = [(-2.0, 1), (-1.0, 2), (0.5, 3), (2.0, 4)]
FLOATR = [(-2.0, 10), (-1.0, 11), (0.5, 12), (3.0, 13)]


def test_merge_float_keys_negative(ctx):
    from tidb_tpu.types import ty_float

    ft = [ty_float(True), ty_int(True)]
    lk = [ColumnExpr(0, ft[0], "k", -1)]
    rk = [ColumnExpr(0, ft[0], "k", -1)]
    got = _drain(MergeJoinExec(ctx, _mk(ctx, FLOATL, ft), _mk(ctx, FLOATR, ft),
                               "inner", lk, rk, []))
    want = _drain(HashJoinExec(ctx, _mk(ctx, FLOATR, ft), _mk(ctx, FLOATL, ft),
                               "inner", rk, lk, [], probe_is_left=True))
    assert sorted(got, key=repr) == sorted(want, key=repr)
    assert len(got) == 3  # -2, -1, 0.5 match


def test_merge_left_outer_preserves_order(ctx):
    lk = [ColumnExpr(0, LT[0], "k", -1)]
    rk = [ColumnExpr(0, RT[0], "k", -1)]
    rows = _drain(MergeJoinExec(ctx, _mk(ctx, LEFT, LT), _mk(ctx, RIGHT, RT),
                                "left_outer", lk, rk, []))
    keys = [(r[0] is None, r[0]) for r in rows]
    assert keys == sorted(keys)  # NULLs-first sorted order preserved


def test_planner_emits_merge_join():
    d = Domain()
    s = d.new_session()
    s.execute("create table a (x bigint, y bigint)")
    s.execute("create table b (x bigint, z bigint)")
    s.execute("insert into a values (1,10),(2,20),(3,30)")
    s.execute("insert into b values (2,200),(3,300),(4,400)")
    want = s.query("select a.x, y, z from a join b on a.x = b.x order by a.x")
    s.execute("set tidb_opt_prefer_merge_join = 1")
    plan = s.execute("explain select a.x, y, z from a join b on a.x = b.x")[0]
    assert any("MergeJoin" in r[0] for r in plan.rows), plan.rows
    got = s.query("select a.x, y, z from a join b on a.x = b.x order by a.x")
    assert got == want
