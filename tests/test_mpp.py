"""MPP exchange engine: device-resident partitioned shuffle joins.

Tentpole coverage (ISSUE 3 acceptance):

- shuffle join parity vs the host HashJoinExec on seeded TPC-H-shaped
  data: inner + left outer, NULL keys, >50% non-matching keys;
- EXPLAIN shows ExchangeSender/ExchangeReceiver (mpp[tpu]) with
  est_rows, and EXPLAIN ANALYZE attributes the serving rung;
- partition overflow (skewed keys) demotes shuffle -> broadcast without
  wrong results; delta rows and disabled engines demote to the host
  hash join;
- scalar partial aggregation runs inside the exchange program (psum'd
  sums/counts, host-merged min/max) and only G=1 partials leave.
"""

import numpy as np
import pytest

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain

N_ORDERS = 4000
N_LINES = 24000


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table orders (o_orderkey bigint primary key,"
              " o_flag bigint, o_total double, o_clerk varchar(8))")
    s.execute("create table li (l_orderkey bigint, l_qty bigint,"
              " l_price decimal(12,2), l_comment varchar(8))")
    rng = np.random.default_rng(11)
    t_o = d.catalog.info_schema().table("test", "orders")
    t_l = d.catalog.info_schema().table("test", "li")
    clerks = np.array([f"c{i:03d}" for i in range(40)], dtype=object)
    d.storage.table(t_o.id).bulk_load_arrays([
        np.arange(N_ORDERS, dtype=np.int64),
        rng.integers(0, 5, N_ORDERS),
        rng.uniform(1, 9999, N_ORDERS),
        clerks[rng.integers(0, 40, N_ORDERS)],
    ], ts=d.storage.current_ts())
    # >50% of probe keys have no match; some keys are NULL
    lk = rng.integers(0, N_ORDERS * 3, N_LINES)
    lvalid = [np.ones(N_LINES, np.bool_), None, None, None]
    lvalid[0][rng.integers(0, N_LINES, 500)] = False
    comments = np.array([f"m{i:02d}" for i in range(20)], dtype=object)
    d.storage.table(t_l.id).bulk_load_arrays([
        lk,
        rng.integers(1, 51, N_LINES),
        rng.integers(100, 1_000_000, N_LINES),
        comments[rng.integers(0, 20, N_LINES)],
    ], lvalid, ts=d.storage.current_ts())
    s.execute("analyze table orders")
    s.execute("analyze table li")
    s.execute("set tidb_enforce_mpp = 1")
    return s


def _cpu(sess, sql):
    sess.execute("set tidb_use_tpu = 0")
    try:
        return sess.query(sql)
    finally:
        sess.execute("set tidb_use_tpu = 1")


def _nullsafe(r):
    return tuple((None is x and (0, "") or (1, x)) for x in r)


def _rows_eq(got, want, ctx=""):
    assert len(got) == len(want), (ctx, len(got), len(want))
    for ra, rb in zip(sorted(got, key=_nullsafe), sorted(want, key=_nullsafe)):
        for a, b in zip(ra, rb):
            if isinstance(a, float) or isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-9, abs=1e-9), (ctx, ra, rb)
            else:
                assert a == b, (ctx, ra, rb)


def _snap(*names):
    s = REGISTRY.snapshot()
    return tuple(s.get(n, 0) for n in names)


def _run_mpp(sess, sql, want_mode="shuffle"):
    # rung names sanitize into the Prometheus grammar for metric names
    metric = ("mpp_joins_"
              + want_mode.replace("+", "_").replace("-", "_") + "_total")
    m0, f0 = _snap(metric, "mpp_fallback_total")
    rows = sess.query(sql)
    m1, f1 = _snap(metric, "mpp_fallback_total")
    assert m1 > m0, f"not served by the mpp {want_mode} rung: {sql}"
    assert f1 == f0, f"fell back to the host join: {sql}"
    return rows


INNER = ("select l_orderkey, l_qty, l_price, o_flag, o_total from li"
         " join orders on l_orderkey = o_orderkey where l_qty < 40")
LOUTER = ("select l_orderkey, l_qty, o_flag, o_total from li"
          " left join orders on l_orderkey = o_orderkey")
STRINGS = ("select l_comment, o_clerk from li"
           " join orders on l_orderkey = o_orderkey where o_flag = 2")
AGG = ("select count(*), count(o_flag), sum(l_price), avg(o_total),"
       " min(l_qty), max(o_total) from li"
       " join orders on l_orderkey = o_orderkey where l_qty < 30")


def test_explain_shows_exchange_operators(sess):
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in sess.execute("explain " + INNER)[0].rows)
    assert "ExchangeSender" in plan and "ExchangeReceiver" in plan, plan
    assert "MPPJoin" in plan and "mpp[tpu]" in plan, plan
    assert "ExchangeType: HashPartition" in plan, plan
    # est_rows annotated on the exchange operators
    for r in sess.execute("explain " + INNER)[0].rows:
        if "ExchangeSender" in r[0] or "ExchangeReceiver" in r[0]:
            assert float(r[1]) > 0, r


def test_inner_join_parity_null_and_nonmatching_keys(sess):
    got = _run_mpp(sess, INNER)
    _rows_eq(got, _cpu(sess, INNER), "inner")


def test_left_outer_join_parity(sess):
    got = _run_mpp(sess, LOUTER)
    want = _cpu(sess, LOUTER)
    _rows_eq(got, want, "left outer")
    # NULL-key and non-matching probe rows survive with NULL build cols
    assert any(r[2] is None for r in got)


def test_string_columns_cross_the_exchange(sess):
    got = _run_mpp(sess, STRINGS)
    _rows_eq(got, _cpu(sess, STRINGS), "strings")


def test_scalar_partial_agg_inside_exchange_program(sess):
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in sess.execute("explain " + AGG)[0].rows)
    assert "partial aggs" in plan and "mode:final" in plan, plan
    got = _run_mpp(sess, AGG)
    want = _cpu(sess, AGG)
    assert len(got) == 1
    for a, b in zip(got[0], want[0]):
        assert float(a) == pytest.approx(float(b), rel=1e-9), (got, want)


def test_explain_analyze_attributes_rung(sess):
    plan = "\n".join(str(r) for r in sess.execute(
        "explain analyze " + INNER)[0].rows)
    assert "engine:mpp-shuffle" in plan, plan


def test_partition_overflow_demotes_to_broadcast(sess):
    d = sess.domain
    s = sess
    s.execute("create table skew (k bigint, v bigint)")
    t = d.catalog.info_schema().table("test", "skew")
    n = 16000
    d.storage.table(t.id).bulk_load_arrays(
        [np.full(n, 7, np.int64), np.arange(n, dtype=np.int64)],
        ts=d.storage.current_ts())
    s.execute("analyze table skew")
    q = "select v, o_flag from skew join orders on k = o_orderkey"
    o0 = _snap("mpp_partition_overflow_total")[0]
    got = _run_mpp(sess, q, want_mode="broadcast")
    assert _snap("mpp_partition_overflow_total")[0] > o0
    _rows_eq(got, _cpu(sess, q), "skew")


def test_delta_rows_fall_back_to_host_join(sess):
    d = sess.domain
    s = d.new_session()
    s.execute("create table dlt (k bigint primary key, v bigint)")
    t = d.catalog.info_schema().table("test", "dlt")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(3000, dtype=np.int64),
         np.arange(3000, dtype=np.int64) % 9],
        ts=d.storage.current_ts())
    s.execute("analyze table dlt")
    s.execute("set tidb_enforce_mpp = 1")
    s.execute("insert into dlt values (90001, 4)")  # committed delta row
    q = ("select l_orderkey, v from li join dlt on l_orderkey = k"
         " where l_qty < 10")
    f0 = _snap("mpp_fallback_total")[0]
    got = s.query(q)
    assert _snap("mpp_fallback_total")[0] > f0
    s.execute("set tidb_use_tpu = 0")
    want = s.query(q)
    s.execute("set tidb_use_tpu = 1")
    _rows_eq(got, want, "delta fallback")


def test_cost_gate_small_build_stays_off_mpp(sess):
    d = sess.domain
    s = d.new_session()  # fresh session: default cost-based routing
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in s.execute("explain " + INNER)[0].rows)
    # build side (orders, 4000 rows) is under the 10240-row broadcast
    # threshold: the host hash join serves it, no exchange operators
    assert "ExchangeSender" not in plan, plan
    # a lower threshold flips the choice IN THE SAME SESSION: the mpp
    # routing vars are part of the plan-cache key, so the cached host
    # plan must not serve the re-tuned statement
    s.execute("set tidb_broadcast_join_threshold_count = 1000")
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in s.execute("explain " + INNER)[0].rows)
    assert "ExchangeSender" in plan, plan
    s.execute("set tidb_broadcast_join_threshold_count = 10240")


def test_exchange_bytes_metric_accounts_traffic(sess):
    b0 = _snap("mpp_exchange_bytes_total")[0]
    _run_mpp(sess, INNER)
    assert _snap("mpp_exchange_bytes_total")[0] > b0


# ---------------------------------------------------------------------------
# co-partitioned join elision (ROADMAP PR-3 follow-up (d))
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def copart_sess():
    """Both sides HASH-partitioned ON the join key with equal partition
    counts: partition i can only match partition i, so the exchange pair
    is provably unnecessary."""
    d = Domain()
    s = d.new_session()
    s.execute("create table cli (l_orderkey bigint, l_qty double)"
              " partition by hash(l_orderkey) partitions 4")
    s.execute("create table cord (o_orderkey bigint primary key,"
              " o_price double) partition by hash(o_orderkey) partitions 4")
    s.execute("insert into cli values "
              + ", ".join(f"({k % 160}, {k}.5)" for k in range(2400)))
    s.execute("insert into cord values "
              + ", ".join(f"({k}, {k * 10}.0)" for k in range(160)))
    isc = d.catalog.info_schema()
    for name in ("cli", "cord"):
        for pid in isc.table("test", name).physical_ids():
            d.storage.maybe_compact(pid, threshold=0)
    s.execute("analyze table cli")
    s.execute("analyze table cord")
    s.execute("set tidb_enforce_mpp = 1")
    return s


COPQ = ("select count(*), sum(l_qty) from cli join cord"
        " on l_orderkey = o_orderkey")


def test_copartitioned_explain_elides_exchange(copart_sess):
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in copart_sess.execute("explain " + COPQ)[0].rows)
    assert "exchange elided (co-partitioned)" in plan, plan
    assert "MPPScan" in plan, plan
    assert "ExchangeReceiver" not in plan, plan
    assert "ExchangeType" not in plan, plan


def test_copartitioned_join_parity_and_metric(copart_sess):
    s = copart_sess
    e0 = REGISTRY.snapshot().get("mpp_exchange_elided_total", 0)
    got = s.query(COPQ)
    assert REGISTRY.snapshot().get("mpp_exchange_elided_total", 0) > e0
    _rows_eq(got, _cpu(s, COPQ), "copart")
    # row-output (non-agg) shape over the same pairs
    q = ("select l_orderkey, o_price from cli join cord"
         " on l_orderkey = o_orderkey where l_qty < 500")
    got2 = s.query(q)
    _rows_eq(got2, _cpu(s, q), "copart-rows")


def test_copartitioned_grouped_agg_parity(copart_sess):
    """Grouped agg over the elided co-partitioned join: served via the
    per-pair rung (grouped pushdown declines copart plans — each pair
    would budget G independently), parity against the host."""
    s = copart_sess
    q = ("select l_orderkey, count(*), sum(l_qty), max(o_price) from cli"
         " join cord on l_orderkey = o_orderkey group by l_orderkey")
    got = s.query(q)
    _rows_eq(got, _cpu(s, q), "copart-grouped")


def test_copartitioned_unequal_counts_not_elided(copart_sess):
    s = copart_sess
    s.execute("create table cord8 (o_orderkey bigint primary key,"
              " o_price double) partition by hash(o_orderkey) partitions 8")
    s.execute("insert into cord8 values (1, 1.0)")
    plan = "\n".join(
        r[0] for r in s.execute(
            "explain select count(*) from cli join cord8"
            " on l_orderkey = o_orderkey")[0].rows)
    assert "MPPScan" not in plan  # 4 vs 8 partitions: no elision


# ---------------------------------------------------------------------------
# grouped partial aggregates below the exchange (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------


GROUPED_CORPUS = [
    # probe-side int key
    ("select l_qty, count(*), sum(l_price) from li join orders"
     " on l_orderkey = o_orderkey group by l_qty"),
    # build-side key + every pushable agg incl. avg/min/max
    ("select o_flag, count(*), count(o_total), sum(l_price),"
     " avg(o_total), min(l_qty), max(o_total) from li join orders"
     " on l_orderkey = o_orderkey group by o_flag"),
    # dict-string group keys from BOTH sides
    ("select o_clerk, count(*), sum(l_qty) from li join orders"
     " on l_orderkey = o_orderkey group by o_clerk"),
    ("select l_comment, count(*), max(o_total) from li join orders"
     " on l_orderkey = o_orderkey where o_flag < 4 group by l_comment"),
    # multi-column group key spanning both sides
    ("select o_flag, l_comment, count(*), sum(l_price) from li"
     " join orders on l_orderkey = o_orderkey"
     " group by o_flag, l_comment"),
    # COMPUTED string group keys (ISSUE 11 / MPP follow-up (d)): a
    # post-join dict-code re-map through a runtime mapping operand,
    # probe-side and build-side, incl. mixed with a plain key
    ("select substr(o_clerk, 2, 2), count(*), sum(l_qty) from li"
     " join orders on l_orderkey = o_orderkey"
     " group by substr(o_clerk, 2, 2)"),
    ("select concat(l_comment, '!'), o_flag, count(*), max(o_total)"
     " from li join orders on l_orderkey = o_orderkey"
     " where o_flag < 4 group by concat(l_comment, '!'), o_flag"),
]


def test_grouped_agg_pushdown_parity_corpus(sess):
    for q in GROUPED_CORPUS:
        got = _run_mpp(sess, q, want_mode="shuffle+grouped")
        _rows_eq(got, _cpu(sess, q), q)


def test_grouped_agg_pushdown_metric_and_explain(sess):
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in sess.execute("explain " + GROUPED_CORPUS[1])[0].rows)
    assert "group by:[o_flag]" in plan and "budget:" in plan, plan
    assert "mode:final" in plan, plan
    p0 = _snap("mpp_grouped_agg_pushed_total")[0]
    sess.query(GROUPED_CORPUS[1])
    assert _snap("mpp_grouped_agg_pushed_total")[0] > p0


def test_grouped_pushdown_single_dispatch_and_readback_o_of_g(sess):
    """Steady-state grouped pushdown: ONE fused device dispatch, and the
    host readback is O(G) — orders of magnitude below the joined-row
    readback the forced host-merge comparator pays on the same plan."""
    import os

    q = GROUPED_CORPUS[1]
    sess.query(q)  # warm the compiled program
    sess.query(q)

    def spans(name):
        out = []

        def walk(s):
            if s.name == name:
                out.append(s)
            for c in s.children:
                walk(c)

        walk(sess.last_trace.root)
        return out

    sess.execute("trace " + q)
    execs = spans("copr.device.execute")
    grouped_bytes = sum(
        int((s.attrs or {}).get("bytes", 0)) for s in spans("copr.readback"))
    assert len(execs) == 1, f"{len(execs)} device dispatches (want 1)"
    # host-merge comparator: same compiled join, rows ship to the host
    os.environ["TIDB_TPU_MPP_GROUPED"] = "0"
    try:
        sess.execute("trace " + q)
    finally:
        os.environ.pop("TIDB_TPU_MPP_GROUPED", None)
    host_bytes = sum(
        int((s.attrs or {}).get("bytes", 0)) for s in spans("copr.readback"))
    assert grouped_bytes * 5 < host_bytes, (grouped_bytes, host_bytes)


def test_grouped_overflow_falls_back_to_agg_peel(sess, monkeypatch):
    """A genuine on-device group-budget overflow (budget pinned tiny,
    high-NDV key): the join stays device-resident and the agg peels to
    the host tail, with parity and the overflow/fallback metrics."""
    monkeypatch.setenv("TIDB_TPU_MPP_GROUP_BUDGET", "8")
    q = ("select l_orderkey, count(*), sum(o_total) from li join orders"
         " on l_orderkey = o_orderkey group by l_orderkey")
    o0, f0 = _snap("mpp_grouped_agg_overflow_total",
                   "mpp_grouped_agg_fallback_total")
    got = _run_mpp(sess, q, want_mode="shuffle+agg-peel")
    o1, f1 = _snap("mpp_grouped_agg_overflow_total",
                   "mpp_grouped_agg_fallback_total")
    assert o1 > o0 and f1 > f0
    _rows_eq(got, _cpu(sess, q), "grouped-overflow-peel")
    plan = "\n".join(str(r) for r in sess.execute(
        "explain analyze " + q)[0].rows)
    assert "engine:mpp-shuffle+agg-peel" in plan, plan


def test_grouped_overflow_chaos_failpoint(sess):
    """The mpp/grouped_agg_overflow chaos site drives the same agg-peel
    rung a real overflow takes: parity, metrics, no leaked failpoints
    (autouse conftest fixture)."""
    from tidb_tpu.mpp.engine import MPPGroupedAggOverflow
    from tidb_tpu.store.fault import failpoint, once

    q = GROUPED_CORPUS[0]
    f0 = _snap("mpp_grouped_agg_fallback_total")[0]
    with failpoint("mpp/grouped_agg_overflow",
                   once(MPPGroupedAggOverflow("chaos injected"))):
        got = _run_mpp(sess, q, want_mode="shuffle+agg-peel")
    assert _snap("mpp_grouped_agg_fallback_total")[0] > f0
    _rows_eq(got, _cpu(sess, q), "grouped-chaos")
    # the next run (failpoint disarmed) pushes down again
    got2 = _run_mpp(sess, q, want_mode="shuffle+grouped")
    _rows_eq(got2, _cpu(sess, q), "grouped-chaos-recovered")


def test_grouped_skewed_keys_stay_grouped(sess):
    """Skewed group-key distribution (one dominant group) must not blow
    the budget: G is what matters, not per-group row counts."""
    d = sess.domain
    s = d.new_session()
    s.execute("create table skg (k bigint, grp bigint, v double)")
    t = d.catalog.info_schema().table("test", "skg")
    n = 20000
    rng = np.random.default_rng(23)
    grp = np.where(rng.random(n) < 0.9, 3, rng.integers(0, 40, n))
    d.storage.table(t.id).bulk_load_arrays(
        [rng.integers(0, N_ORDERS, n), grp, rng.uniform(0, 10, n)],
        ts=d.storage.current_ts())
    s.execute("analyze table skg")
    s.execute("set tidb_enforce_mpp = 1")
    q = ("select grp, count(*), sum(v) from skg join orders"
         " on k = o_orderkey group by grp")
    p0 = _snap("mpp_grouped_agg_pushed_total")[0]
    got = s.query(q)
    assert _snap("mpp_grouped_agg_pushed_total")[0] > p0
    s.execute("set tidb_use_tpu = 0")
    want = s.query(q)
    s.execute("set tidb_use_tpu = 1")
    _rows_eq(got, want, "skewed-grouped")


def test_grouped_delta_rows_fall_back_to_host_with_parity(sess):
    """Committed delta rows keep the grouped plan OFF the device; the
    host rung emits the same grouped-partial layout the final HashAgg
    merges."""
    d = sess.domain
    s = d.new_session()
    s.execute("create table gdlt (k bigint primary key, g bigint,"
              " v double)")
    t = d.catalog.info_schema().table("test", "gdlt")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(3000, dtype=np.int64),
         np.arange(3000, dtype=np.int64) % 7,
         np.arange(3000, dtype=np.float64)],
        ts=d.storage.current_ts())
    s.execute("analyze table gdlt")
    s.execute("set tidb_enforce_mpp = 1")
    s.execute("insert into gdlt values (90001, 3, 1.5)")
    q = ("select g, count(*), sum(l_qty), min(v) from li join gdlt"
         " on l_orderkey = k group by g")
    f0 = _snap("mpp_fallback_total")[0]
    got = s.query(q)
    assert _snap("mpp_fallback_total")[0] > f0
    s.execute("set tidb_use_tpu = 0")
    want = s.query(q)
    s.execute("set tidb_use_tpu = 1")
    _rows_eq(got, want, "grouped-delta-fallback")


# ---------------------------------------------------------------------------
# multi-column and non-unique build join keys (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dup_sess():
    """Build side with DUPLICATE join keys (and NULLs) plus a
    two-column-key pair — the two-pass count+emit shapes."""
    d = Domain()
    s = d.new_session()
    s.execute("create table dup (k bigint, g bigint, v double)")
    s.execute("create table probe (pk bigint, q bigint)")
    s.execute("create table a2 (k1 bigint, k2 bigint, x bigint)")
    s.execute("create table b2 (m1 bigint, m2 bigint, y double)")
    rng = np.random.default_rng(7)
    t = d.catalog.info_schema()
    n_d, n_p = 12000, 20000
    dvalid = [np.ones(n_d, np.bool_), None, None]
    dvalid[0][rng.integers(0, n_d, 300)] = False
    d.storage.table(t.table("test", "dup").id).bulk_load_arrays(
        [rng.integers(0, 4000, n_d), rng.integers(0, 7, n_d),
         rng.uniform(0, 100, n_d)], dvalid, ts=d.storage.current_ts())
    d.storage.table(t.table("test", "probe").id).bulk_load_arrays(
        [rng.integers(0, 12000, n_p), rng.integers(0, 50, n_p)],
        ts=d.storage.current_ts())
    n_a, n_b = 16000, 6000
    d.storage.table(t.table("test", "a2").id).bulk_load_arrays(
        [rng.integers(0, 50, n_a), rng.integers(0, 40, n_a),
         rng.integers(0, 9, n_a)], ts=d.storage.current_ts())
    d.storage.table(t.table("test", "b2").id).bulk_load_arrays(
        [rng.integers(0, 50, n_b), rng.integers(0, 40, n_b),
         rng.uniform(0, 10, n_b)], ts=d.storage.current_ts())
    for name in ("dup", "probe", "a2", "b2"):
        s.execute(f"analyze table {name}")
    s.execute("set tidb_enforce_mpp = 1")
    return s


def _dup_par(s, q, label, want_mode=None):
    if want_mode is not None:
        got = _run_mpp(s, q, want_mode=want_mode)
    else:
        got = s.query(q)
    _rows_eq(got, _cpu(s, q), label)
    return got


def test_nonunique_build_keys_inner_expansion(dup_sess):
    """Duplicate build keys expand via the two-pass count+emit: every
    (probe, match) pair emits — no more dup demotion to the host
    (_run_mpp already asserts the MPP run itself took no fallback)."""
    _dup_par(dup_sess,
             "select pk, q, g, v from probe join dup on pk = k"
             " where q < 25", "nonunique-inner", want_mode="shuffle")


def test_nonunique_build_keys_left_outer(dup_sess):
    got = _dup_par(dup_sess,
                   "select pk, q, v from probe left join dup on pk = k",
                   "nonunique-louter", want_mode="shuffle")
    assert any(r[2] is None for r in got)  # unmatched rows NULL-extend


def test_nonunique_build_grouped_agg(dup_sess):
    _dup_par(dup_sess,
             "select g, count(*), sum(v), avg(q) from probe join dup"
             " on pk = k group by g", "nonunique-grouped",
             want_mode="shuffle+grouped")


def test_multicolumn_join_keys_rows_and_grouped(dup_sess):
    """Two-column equi-join exchanges a mix-hash and re-verifies true
    per-column equality on device."""
    _dup_par(dup_sess,
             "select x, y from a2 join b2 on k1 = m1 and k2 = m2"
             " where x < 5", "multicol-rows", want_mode="shuffle")
    _dup_par(dup_sess,
             "select x, count(*), sum(y) from a2 join b2"
             " on k1 = m1 and k2 = m2 group by x", "multicol-grouped",
             want_mode="shuffle+grouped")


def test_multicolumn_left_outer_runs_on_device(dup_sess):
    """ISSUE 11 (MPP follow-up (c)): multi-key LEFT-OUTER joins compose
    their keys EXACTLY (stride packing over both sides' column stats —
    pack_keys_exact), so no probe row can lose its NULL-extension slot
    to a hash collision and the join plans + runs as MPP."""
    plan = "\n".join(
        " | ".join(str(x) for x in r)
        for r in dup_sess.execute(
            "explain select x, y from a2 left join b2"
            " on k1 = m1 and k2 = m2")[0].rows)
    assert "ExchangeSender" in plan, plan
    q = ("select k1, k2, x, y from a2 left join b2"
         " on k1 = m1 and k2 = m2 where x < 3")
    got = _dup_par(dup_sess, q, "multicol-louter", want_mode="shuffle")
    # ~5% of the 2000 (k1, k2) combos have no build match (6000 build
    # rows over 2000 combos): unmatched rows NULL-extend the build side
    assert any(r[3] is None for r in got), "no NULL-extended rows"
