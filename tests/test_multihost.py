"""Multi-host distributed execution proof: TWO OS processes join a
jax.distributed cluster over localhost, build one 8-device mesh (4 virtual
CPU devices per process), and run Q1/Q6 through the full SQL stack with
the scan sharded across BOTH processes' devices.

This is the working proof of SURVEY §5's "distributed communication
backend" row: the reference scales with a NCCL/MPI + gRPC batch fabric
(store/tikv/client_batch.go:38-387); here the same role is XLA's
collective runtime reached through jax.distributed — identical code path
on real multi-host TPU pods (ICI in-host, DCN across hosts)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_query_parity():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=560)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers timed out; partial: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK pid={pid} devices=8" in out, out[-2000:]
    # both processes computed the same answers (SPMD determinism)
    tail0 = outs[0].splitlines()[-1].split("q1_rows=")[1]
    tail1 = outs[1].splitlines()[-1].split("q1_rows=")[1]
    assert tail0 == tail1
