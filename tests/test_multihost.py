"""Multi-host distributed execution proof: TWO OS processes join a
jax.distributed cluster over localhost, build one 8-device mesh (4 virtual
CPU devices per process), and run Q1/Q6 through the full SQL stack with
the scan sharded across BOTH processes' devices.

This is the working proof of SURVEY §5's "distributed communication
backend" row: the reference scales with a NCCL/MPI + gRPC batch fabric
(store/tikv/client_batch.go:38-387); here the same role is XLA's
collective runtime reached through jax.distributed — identical code path
on real multi-host TPU pods (ICI in-host, DCN across hosts).  The same
two processes also form the coordination plane (tidb_tpu/coord) when
TIDB_TPU_COORD_ADDR is set: membership broadcast + span forwarding ride
the control plane while the scan rides the collectives.

Environment preflight (ISSUE 9 satellite): sandboxed environments that
black-hole jax.distributed's gRPC coordination service used to burn the
full 560 s worker timeout and then FAIL; a cheap bind+join+barrier probe
now detects that up front and SKIPS with an actionable reason, while
fully-supported environments still run the real test."""

import functools
import os
import socket
import subprocess
import sys
from typing import Optional

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


#: bind+join+barrier budget: a healthy localhost cluster forms in a few
#: seconds; a sandbox that silently drops the gRPC traffic never will
PREFLIGHT_TIMEOUT_S = 75

_PREFLIGHT_SRC = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
assert jax.process_count() == 2
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("preflight")
print("PREFLIGHT_OK", flush=True)
'''


def _clean_env() -> dict:
    return {k: v for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}


@functools.lru_cache(maxsize=1)
def _cluster_preflight() -> Optional[str]:
    """None when this environment can form a localhost jax.distributed
    cluster (coordinator bind + join + one barrier across two tiny
    subprocesses, short timeout); else the actionable skip reason."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PREFLIGHT_SRC,
             f"127.0.0.1:{port}", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_clean_env(),
        )
        for pid in (0, 1)
    ]
    outs = ["", ""]
    try:
        for i, p in enumerate(procs):
            outs[i], _ = p.communicate(timeout=PREFLIGHT_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return (f"coordinator bind/join + barrier did not complete within "
                f"{PREFLIGHT_TIMEOUT_S}s — jax.distributed's gRPC "
                "coordination service appears blocked in this sandbox")
    for i, p in enumerate(procs):
        if p.returncode != 0 or "PREFLIGHT_OK" not in outs[i]:
            tail = (outs[i].strip().splitlines() or [f"exit {p.returncode}"]
                    )[-1][:200]
            return f"preflight worker {i} failed: {tail}"
    return None


def test_two_process_distributed_query_parity():
    reason = _cluster_preflight()
    if reason:
        pytest.skip("multihost cluster unsupported in this environment: "
                    + reason)
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = _clean_env()
    # the coordination plane rides along: process 0 binds this port and
    # both processes assert membership + span forwarding (COORD_OK)
    env["TIDB_TPU_COORD_ADDR"] = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=560)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers timed out; partial: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_OK pid={pid} devices=8" in out, out[-2000:]
        assert f"COORD_OK pid={pid}" in out, out[-2000:]
    # both processes computed the same answers (SPMD determinism)
    tail0 = outs[0].splitlines()[-1].split("q1_rows=")[1]
    tail1 = outs[1].splitlines()[-1].split("q1_rows=")[1]
    assert tail0 == tail1
