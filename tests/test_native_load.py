"""Native C++ CSV -> columnar bulk loader (native/csvkit.cpp).

Reference role: executor/load_data.go's hot loop, rebuilt as one native
pass emitting columnar arrays for bulk_load_arrays.  The Python csv-module
path stays as the semantically identical fallback (quoted fields, exotic
types, missing toolchain) — these tests pin the two paths together."""

import os
import tempfile

import numpy as np
import pytest

from tidb_tpu.native import csv_parse_columns
from tidb_tpu.session import Domain
from tidb_tpu.types import (
    ty_date,
    ty_datetime,
    ty_decimal,
    ty_float,
    ty_int,
    ty_string,
)
from tidb_tpu.types.values import parse_date, parse_datetime


def test_parser_unit():
    buf = (b"1|2.5|hello|1998-09-02|12.345|2020-01-02 03:04:05.5\n"
           b"-7|\\N||2000-01-01|0.01|2000-01-01\n"
           b"\\N|1e3|x\xc3\xa9|\\N|-3.999|\\N\n")
    fts = [ty_int(), ty_float(), ty_string(), ty_date(),
           ty_decimal(10, 2), ty_datetime()]
    arrays, valids = csv_parse_columns(buf, fts, "|")
    assert list(arrays[0]) == [1, -7, 0] and not valids[0][2]
    assert arrays[1][2] == 1000.0 and not valids[1][1]
    # empty string field is '' (valid), \N is NULL
    assert arrays[2][1] == "" and valids[2][1]
    assert arrays[2][2] == "x\u00e9"
    assert arrays[3][0] == parse_date("1998-09-02")
    assert list(arrays[4]) == [1235, 1, -400]  # half-away-from-zero
    assert arrays[5][0] == parse_datetime("2020-01-02 03:04:05.5")


def test_parser_rejects_quotes():
    assert csv_parse_columns(b'1|"q"\n', [ty_int(), ty_string()], "|") \
        is None


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()
    return dom


def _write_tbl(n):
    rng = np.random.default_rng(3)
    path = tempfile.mktemp(suffix=".csv")
    with open(path, "w") as f:
        for i in range(n):
            if i % 100 == 99:
                f.write(f"{i}|\\N|\\N|\\N\n")
            else:
                f.write(f"{i}|{rng.integers(1, 10**6) / 100:.2f}"
                        f"|name{i % 97}|19{94 + i % 5}-0{1 + i % 9}-1{i % 9}\n")
    return path


def test_native_python_load_parity(d):
    s = d.new_session()
    ddl = ("(k bigint, price decimal(12,2), name varchar(16), dt date)"
           " partition by hash (k) partitions 4")
    s.execute(f"create table ln {ddl}")
    s.execute(f"create table lp {ddl}")
    path = _write_tbl(20_000)
    try:
        s.execute(f"load data infile '{path}' into table ln"
                  f" fields terminated by '|'")
        import tidb_tpu.native as nat

        orig = nat.csv_parse_columns
        nat.csv_parse_columns = lambda *a, **k: None  # force Python path
        try:
            s.execute(f"load data infile '{path}' into table lp"
                      f" fields terminated by '|'")
        finally:
            nat.csv_parse_columns = orig
        assert s.query("select count(*), count(price), sum(price)"
                       " from ln") == \
            s.query("select count(*), count(price), sum(price) from lp")
        assert sorted(s.query("select * from ln where k < 200")) == \
            sorted(s.query("select * from lp where k < 200"))
    finally:
        os.unlink(path)


def test_native_load_range_partition_routing(d):
    s = d.new_session()
    s.execute("create table lr (k bigint, v bigint)"
              " partition by range (k) ("
              " partition p0 values less than (100),"
              " partition p1 values less than maxvalue)")
    path = tempfile.mktemp()
    with open(path, "w") as f:
        f.write("5|50\n500|5000\n99|1\n100|2\n")
    try:
        s.execute(f"load data infile '{path}' into table lr"
                  f" fields terminated by '|'")
        t = d.catalog.info_schema().table("test", "lr")
        p0, p1 = t.partition_info.defs
        assert d.storage.table(p0.id).base_rows == 2  # 5, 99
        assert d.storage.table(p1.id).base_rows == 2  # 500, 100
        assert sorted(s.query("select k from lr where k < 100")) == [
            (5,), (99,)]
    finally:
        os.unlink(path)


def test_native_load_out_of_range_errors(d):
    from tidb_tpu.errors import KVError

    s = d.new_session()
    s.execute("create table nr (k bigint) partition by range (k) ("
              " partition p0 values less than (10))")
    path = tempfile.mktemp()
    with open(path, "w") as f:
        f.write("5\n50\n")
    try:
        with pytest.raises(KVError):
            s.execute(f"load data infile '{path}' into table nr")
    finally:
        os.unlink(path)


def test_crlf_and_overflow_edges():
    from tidb_tpu.types import ty_int, ty_string

    arrays, valids = csv_parse_columns(
        b"1|ab\r\n2|cd\r\n", [ty_int(), ty_string()], "|")
    assert list(arrays[0]) == [1, 2]
    assert list(arrays[1]) == ["ab", "cd"]  # \r belongs to the terminator
    # out-of-int64 values are NULL on both the native and Python paths
    arrays, valids = csv_parse_columns(
        b"9223372036854775808\n5\n", [ty_int()], "|")
    assert not valids[0][0] and arrays[0][1] == 5
    from tidb_tpu.executor.dml import _parse_field

    assert _parse_field("9223372036854775808", ty_int()) is None
