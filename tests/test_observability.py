"""Ops/observability shell: HTTP /metrics + /status + /schema, per-digest
statement summary, SHOW STATS_* / PROCESSLIST.

Reference: server/http_status.go:74-115 (status port),
util/stmtsummary/statement_summary.go:59,213 (digest aggregation),
executor/show_stats.go (SHOW STATS_META/_HISTOGRAMS/_BUCKETS)."""

import json
import urllib.request

import pytest

from tidb_tpu.session import Domain
from tidb_tpu.session.domain import sql_digest


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()
    yield dom


def test_sql_digest_normalizes_literals():
    a = sql_digest("SELECT * FROM t WHERE a = 5 AND b = 'x' AND c IN (1,2)")
    b = sql_digest("select *  from t where a=9 and b='zz' and c in (3,4,5)")
    assert a == b == "select * from t where a = ? and b = ? and c in (...)"


def test_statement_summary_aggregates_by_digest(d):
    s = d.new_session()
    s.execute("create table t (a bigint)")
    for i in range(5):
        s.execute(f"insert into t values ({i})")
    for i in range(3):
        s.execute(f"select * from t where a = {i}")
    rows = s.query("select digest_text, exec_count, sum_rows from"
                   " information_schema.statements_summary"
                   " where digest_text like '%where a =%'")
    assert rows == [("select * from t where a = ?", 3, 3)]
    ins = s.query("select exec_count from"
                  " information_schema.statements_summary"
                  " where digest_text like 'insert%'")
    assert ins == [(5,)]


def test_show_stats_surface(d):
    s = d.new_session()
    s.execute("create table st (a bigint, b varchar(4))")
    s.execute("insert into st values (1,'x'), (2,'y'), (3,'x')")
    s.execute("analyze table st")
    meta = s.query("show stats_meta")
    assert any(r[1] == "st" and r[5] == 3 for r in meta)
    hist = s.query("show stats_histograms")
    assert {r[3] for r in hist if r[1] == "st"} == {"a", "b"}
    buckets = s.query("show stats_buckets")
    assert any(r[1] == "st" and r[3] == "a" for r in buckets)


def test_show_stats_covers_partitions(d):
    s = d.new_session()
    s.execute("create table pt (k bigint) partition by hash (k) partitions 2")
    s.execute("insert into pt values (1), (2), (3)")
    s.execute("analyze table pt")
    meta = s.query("show stats_meta")
    parts = {r[2] for r in meta if r[1] == "pt"}
    assert parts == {"", "p0", "p1"}  # logical + both partitions


def test_processlist_shows_running_statement(d):
    import threading
    import time

    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1)")
    watcher = d.new_session()

    got = {}

    def slow():
        s.execute("select sleep(0.4) from t")

    th = threading.Thread(target=slow)
    th.start()
    time.sleep(0.15)
    rows = watcher.query("show processlist")
    th.join(5)
    running = [r for r in rows if r[4] == "Query" and "sleep" in r[6]]
    assert running, rows
    assert running[0][5] > 0  # elapsed time


def test_http_endpoints(d):
    from tidb_tpu.server import StatusServer

    s = d.new_session()
    s.execute("create table ht (a bigint)"
              " partition by hash (a) partitions 2")
    s.execute("insert into ht values (1)")
    srv = StatusServer(d, port=0)
    host, port = srv.start()
    try:
        base = f"http://{host}:{port}"
        metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "tidb_tpu_statements_total" in metrics
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["version"].endswith("tidb-tpu-0.1.0")
        assert status["connections"] >= 1
        schema = json.loads(urllib.request.urlopen(base + "/schema").read())
        t = [x for x in schema["test"] if x["name"] == "ht"][0]
        assert t["partitions"] == ["p0", "p1"]
        # 404 for unknown paths
        try:
            urllib.request.urlopen(base + "/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_http_device_health_surfaces_breaker_trips(d):
    """PR-2 follow-up (d): circuit-breaker trips are visible on the
    status port, not just information_schema — /status carries the
    tripped-device summary and /device-health the full breaker state."""
    from tidb_tpu.copr.device_health import DEVICE_HEALTH
    from tidb_tpu.server import StatusServer

    DEVICE_HEALTH.reset()
    srv = StatusServer(d, port=0)
    host, port = srv.start()
    try:
        base = f"http://{host}:{port}"
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["tripped_devices"] == []
        DEVICE_HEALTH.record_error(3, RuntimeError("chip 3 halted"))
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["tripped_devices"] == [3]
        health = json.loads(
            urllib.request.urlopen(base + "/device-health").read())
        assert health["tripped"] == [3]
        st = {h["device_id"]: h for h in health["devices"]}
        assert st[3]["state"] == "tripped" and st[3]["trip_count"] >= 1
        assert "chip 3 halted" in st[3]["last_error"]
    finally:
        srv.stop()
        DEVICE_HEALTH.reset()


def test_infoschema_breadth(d):
    s = d.new_session()
    s.execute("create table ib (k bigint primary key, v varchar(4))"
              " partition by range (k) ("
              " partition p0 values less than (10),"
              " partition p1 values less than maxvalue)")
    s.execute("insert into ib values (1, 'a'), (50, 'b')")
    s.execute("create view vv as select k from ib")
    parts = s.query("select partition_name, partition_method,"
                    " partition_description, table_rows from"
                    " information_schema.partitions"
                    " where table_name = 'ib' order by partition_name")
    assert parts == [("p0", "RANGE", "10", 1), ("p1", "RANGE", "MAXVALUE", 1)]
    assert s.query("select table_name from information_schema.views") == [
        ("vv",)]
    idx = s.query("select key_name, column_name from"
                  " information_schema.tidb_indexes"
                  " where table_name = 'ib'")
    assert ("PRIMARY", "k") in idx
    assert s.query("select constraint_name from"
                   " information_schema.key_column_usage"
                   " where table_name = 'ib'") == [("PRIMARY",)]
    assert s.query("select engine from information_schema.engines") == [
        ("tidb-tpu",)]


def test_hash_and_encoding_functions(d):
    import hashlib
    import zlib

    s = d.new_session()
    (md5, sha, sha2, crc, hx, unhx, b64, unb64), = s.query(
        "select md5('abc'), sha1('abc'), sha2('abc', 512), crc32('abc'),"
        " hex(255), unhex('4869'), to_base64('hi'), from_base64('aGk=')")
    assert md5 == hashlib.md5(b"abc").hexdigest()
    assert sha == hashlib.sha1(b"abc").hexdigest()
    assert sha2 == hashlib.sha512(b"abc").hexdigest()
    assert crc == zlib.crc32(b"abc")
    assert (hx, unhx, b64, unb64) == ("FF", "Hi", "aGk=", "hi")
    assert s.query("select sha2('x', 3)") == [(None,)]  # bad bits -> NULL
    assert s.query("select uncompress(compress('roundtrip'))") == [
        ("roundtrip",)]


# ---------------------------------------------------------------------------
# histogram SLO metrics, continuous profiling, fleet /status (ISSUE 13)
# ---------------------------------------------------------------------------


def test_histogram_quantiles_within_one_log2_bucket():
    """p50/p95/p99 from the bounded log2 buckets are exact to one
    bucket: true_q <= estimate <= 2 * true_q (the estimator returns the
    bucket's upper edge)."""
    import numpy as np

    from tidb_tpu.metrics import Registry

    r = Registry()
    vals = np.random.default_rng(7).lognormal(2.0, 1.5, 4000)
    for v in vals:
        r.observe_hist("unit_lat_ms", float(v))
    for q in (0.50, 0.95, 0.99):
        true = float(np.quantile(vals, q))
        est = r.quantile("unit_lat_ms", q)
        assert true <= est <= 2.0 * true + 1e-9, (q, true, est)
    st = r.hist_stats("unit_lat_ms")
    assert st["count"] == 4000
    assert abs(st["sum"] - float(vals.sum())) < 1e-6 * float(vals.sum())
    # merge parity: two copies bucket-merge to doubled counts, same edges
    from tidb_tpu.metrics import merge_fleet

    payload = r.export_fleet_payload()
    merged = merge_fleet({0: payload, 1: payload})
    h = merged["hists"]["unit_lat_ms"]
    assert h["count"] == 8000
    assert h["p99"] == r.quantile("unit_lat_ms", 0.99)


def test_prometheus_histogram_exposition():
    from tidb_tpu.metrics import Registry

    r = Registry()
    r.inc("x_total", 2)
    for v in (0.5, 3.0, 100.0):
        r.observe_hist("y_ms", v)
    lines = r.prometheus_lines()
    assert "tidb_tpu_x_total 2.0" in lines
    buckets = [ln for ln in lines
               if ln.startswith("tidb_tpu_y_ms_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts) and counts[-1] == 3  # cumulative
    assert 'le="+Inf"' in buckets[-1]
    assert "tidb_tpu_y_ms_count 3" in lines
    assert any(ln.startswith("tidb_tpu_y_ms_sum") for ln in lines)


def test_status_profile_slo_memory_fleet_sections_and_flame(d):
    """The ISSUE 13 /status sections + /flame over the wire: profile
    has stacks after traced statements, slo carries thresholds + burn,
    memory reports every named device cache with watermarks, fleet
    degenerates to the single LocalPlane host — and /flame emits
    parseable folded-stacks text."""
    from tidb_tpu.server import StatusServer

    s = d.new_session()
    s.execute("create table ob13 (a bigint, b bigint)")
    s.execute("insert into ob13 values (1,2),(3,4),(5,6),(7,8)")
    s.execute("analyze table ob13")
    s.query("select sum(a) from ob13 where b > 1")
    s.query("select a from ob13 where a = 3")
    srv = StatusServer(d, port=0)
    host, port = srv.start()
    try:
        base = f"http://{host}:{port}"
        st = json.loads(urllib.request.urlopen(base + "/status").read())
        for key in ("profile", "slo", "memory", "fleet"):
            assert key in st, st.keys()
            assert "error" not in st[key], (key, st[key])
        assert st["profile"]["top"], st["profile"]
        assert st["profile"]["top"][0]["stack"].startswith(
            "session.execute")
        slo = st["slo"]
        assert set(slo) == {"point", "agg", "join", "dml", "other"}
        assert slo["agg"]["threshold_ms"] > 0
        assert slo["agg"].get("count", 0) >= 1  # the sum() above
        caches = st["memory"]["caches"]
        assert "mesh" in caches and "tile" in caches
        for cs in caches.values():
            assert cs["watermark_bytes"] >= cs["bytes"] >= 0
        fleet = st["fleet"]
        assert fleet["hosts"] == ["0"] and fleet["kind"] == "local"
        assert fleet["counters"].get("statements_total", 0) > 0
        # lock-order witness counters (ISSUE 16): the suite runs with
        # TIDB_TPU_LOCKCHECK=1, so acquisitions accumulate and depth>0
        lc = st["lockcheck"]
        assert lc["enabled"] and lc["violations"] == 0
        assert lc["acquisitions"] > 0 and lc["max_depth"] >= 1
        assert any(n.startswith("stmt_latency_") for n in fleet["hists"])
        flame = urllib.request.urlopen(base + "/flame").read().decode()
        assert flame.strip(), "/flame must be non-empty after queries"
        for ln in flame.strip().splitlines():
            stack, weight = ln.rsplit(" ", 1)
            assert stack and int(weight) >= 0
        assert any(ln.startswith("session.execute")
                   for ln in flame.splitlines())
        metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "_bucket{le=" in metrics
        assert "tidb_tpu_stmt_latency_agg_ms_count" in metrics
        assert "tidb_tpu_cache_mesh_watermark_bytes" in metrics
    finally:
        srv.stop()
    # the same data through INFORMATION_SCHEMA
    rows = s.query("select stack, count, self_ms from"
                   " information_schema.tidb_tpu_profile")
    assert rows and any(r[0].startswith("session.execute") for r in rows)
    fm = s.query("select host, kind, value from"
                 " information_schema.tidb_tpu_fleet_metrics"
                 " where name = 'statements_total'")
    assert ("fleet", "counter") in {(r[0], r[1]) for r in fm}
    assert all(r[2] > 0 for r in fm)


def test_slo_burn_counters_ride_sysvars(d):
    from tidb_tpu.metrics import REGISTRY

    s = d.new_session()
    s.execute("set global tidb_tpu_slo_point_ms = 1")
    b0 = REGISTRY.get("slo_point_breach_total")
    ok0 = REGISTRY.get("slo_point_ok_total")
    try:
        s.query("select sleep(0.05)")  # point-class, forced breach
    finally:
        s.execute("set global tidb_tpu_slo_point_ms = 100000")
    s.query("select 1")  # point-class, comfortably inside
    assert REGISTRY.get("slo_point_breach_total") == b0 + 1
    assert REGISTRY.get("slo_point_ok_total") >= ok0 + 1
    # 0 disables burn accounting (histogram still records)
    s.execute("set global tidb_tpu_slo_point_ms = 0")
    b1 = REGISTRY.get("slo_point_breach_total")
    ok1 = REGISTRY.get("slo_point_ok_total")
    h0 = REGISTRY.hist_stats("stmt_latency_point_ms")["count"]
    try:
        s.query("select 1")
    finally:
        s.execute("set global tidb_tpu_slo_point_ms = 100")
    assert REGISTRY.get("slo_point_breach_total") == b1
    assert REGISTRY.get("slo_point_ok_total") == ok1
    assert REGISTRY.hist_stats("stmt_latency_point_ms")["count"] == h0 + 1
    # a SESSION-scope override never drives the fleet-wide burn
    # counters (they must agree with the global threshold /status
    # reports); the global threshold (100ms) still counts it ok
    s.execute("set session tidb_tpu_slo_point_ms = 1")
    b2 = REGISTRY.get("slo_point_breach_total")
    try:
        s.query("select sleep(0.05)")
    finally:
        s.execute("set session tidb_tpu_slo_point_ms = 100")
    assert REGISTRY.get("slo_point_breach_total") == b2


def test_slo_auto_windows_unit(monkeypatch):
    """The rolling tracker (ISSUE 20 satellite): min-sample gate,
    headroom x merged-window p99, window rotation ages samples out."""
    monkeypatch.setenv("TIDB_TPU_SLO_AUTO_WINDOW_S", "0.1")
    monkeypatch.setenv("TIDB_TPU_SLO_AUTO_MIN_SAMPLES", "10")
    monkeypatch.setenv("TIDB_TPU_SLO_AUTO_HEADROOM", "2.0")
    from tidb_tpu.trace.slo import (
        SloAutoWindows, is_auto, resolve_threshold_ms)

    w = SloAutoWindows()
    for _ in range(9):
        w.observe("point", 4.0)
    assert w.threshold_ms("point") == 0.0  # under the sample floor
    w.observe("point", 4.0)
    # p99 bucket upper edge of 4.0 is 4.0; headroom doubles it
    assert w.threshold_ms("point") == pytest.approx(8.0)
    snap = w.snapshot("point")
    assert snap["samples"] == 10 and snap["p99_ms"] == pytest.approx(4.0)
    # two rotations (cur -> prev -> gone) age the baseline out
    import time as _time

    _time.sleep(0.12)
    w.observe("point", 4.0)  # rotation 1: the 10 samples move to prev
    assert w.threshold_ms("point") == pytest.approx(8.0)  # still merged
    _time.sleep(0.12)
    w.observe("point", 4.0)  # rotation 2: they are gone
    assert w.threshold_ms("point") == 0.0  # 2 samples < floor
    # the sysvar-value helpers
    assert is_auto(" AUTO ") and not is_auto("100")
    assert resolve_threshold_ms("250", "point") == 250.0
    assert resolve_threshold_ms("garbage", "point") == 0.0


def test_slo_auto_mode_end_to_end(d, monkeypatch):
    """`set global tidb_tpu_slo_point_ms = 'auto'`: burn accounting
    stays off during warmup, then breaches against the derived
    rolling-p99 threshold; /status reports the auto baseline."""
    monkeypatch.setenv("TIDB_TPU_SLO_AUTO_MIN_SAMPLES", "5")
    from tidb_tpu.metrics import REGISTRY
    from tidb_tpu.server.http_status import _slo_section
    from tidb_tpu.trace.slo import SLO_AUTO

    SLO_AUTO.reset()
    s = d.new_session()
    s.execute("set global tidb_tpu_slo_point_ms = 'auto'")
    b0 = REGISTRY.get("slo_point_breach_total")
    ok0 = REGISTRY.get("slo_point_ok_total")
    try:
        s.query("select 1")  # warmup: under the sample floor
        assert REGISTRY.get("slo_point_breach_total") == b0
        assert REGISTRY.get("slo_point_ok_total") == ok0
        for _ in range(6):  # build the fast baseline past the floor
            s.query("select 1")
        ok1 = REGISTRY.get("slo_point_ok_total")
        assert ok1 > ok0, "warm auto baseline stopped counting ok"
        sec = _slo_section(d)
        assert sec["point"]["mode"] == "auto"
        assert sec["point"]["auto"]["samples"] >= 5
        assert sec["point"]["threshold_ms"] > 0
        # a statement far beyond 2x the rolling p99 burns budget
        s.query("select sleep(0.3)")
        assert REGISTRY.get("slo_point_breach_total") == b0 + 1
    finally:
        s.execute("set global tidb_tpu_slo_point_ms = 100")
        SLO_AUTO.reset()


def test_show_stats_healthy_and_analyze_status(d):
    import time as _time

    s = d.new_session()
    s.execute("create table sh (a bigint)")
    s.execute("insert into sh values (1), (2), (3), (4)")
    s.execute("analyze table sh")
    healthy = s.query("show stats_healthy")
    assert ("test", "sh", "", 100) in healthy
    status = s.query("show analyze status")
    row = [r for r in status if r[1] == "sh"][0]
    assert row[0] == "test" and row[4] == 4 and row[6] == "finished"
    # deletes mutate delta chains in place: health must still degrade
    # (modifications = versions newer than the stats build)
    _time.sleep(0.01)
    s.execute("delete from sh where a < 4")
    h = [r for r in s.query("show stats_healthy") if r[1] == "sh"][0][3]
    assert h <= 50, h


# ---------------------------------------------------------------------------
# operator sampling into the profiler (ISSUE 18 trace (a))
# ---------------------------------------------------------------------------

def test_profiler_fold_explain_op_stacks():
    """fold_explain turns a pre-order (depth, op_id, inclusive_ns) list
    into op-id stacks weighted by SELF time (inclusive minus direct
    children), matching the span-walk's attribution rules."""
    from tidb_tpu.trace.profiler import Profiler

    p = Profiler(enabled=True, window_s=3600, n_windows=2,
                 max_paths=64, persist_dir="")
    p.fold_explain([
        (0, "Projection_7", 10_000_000),
        (1, "HashAgg_3", 8_000_000),
        (2, "TableReader_5", 5_000_000),
        (1, "Limit_9", 1_000_000),
    ])
    got = dict(ln.rsplit(" ", 1) for ln in
               p.folded().strip().splitlines())
    assert got == {
        # 10ms - (8ms + 1ms) children = 1ms self
        "op:Projection_7": "1000",
        "op:Projection_7;op:HashAgg_3": "3000",
        "op:Projection_7;op:HashAgg_3;op:TableReader_5": "5000",
        "op:Projection_7;op:Limit_9": "1000",
    }


def test_explain_analyze_samples_ops_into_profiler(d):
    """EXPLAIN ANALYZE feeds its per-operator stats into the continuous
    profiler: /flame stacks carry the plan's operator ids."""
    import re

    from tidb_tpu.metrics import REGISTRY
    from tidb_tpu.trace.profiler import PROFILER

    s = d.new_session()
    s.execute("create table opprof (a bigint, g bigint)")
    s.execute("insert into opprof values (1,1),(2,1),(3,2),(4,2)")
    before = REGISTRY.snapshot().get("profile_op_samples_total", 0)
    rows = s.query("explain analyze select g, sum(a) from opprof"
                   " group by g")
    assert rows  # the statement itself still explains
    after = REGISTRY.snapshot().get("profile_op_samples_total", 0)
    assert after == before + 1
    op_lines = [ln for ln in PROFILER.folded().splitlines()
                if ln.startswith("op:")]
    assert op_lines, "no operator stacks reached the profiler"
    # frames are operator IDS (name_id), root-to-leaf chains
    assert any(re.search(r"op:\w+_\d+;op:\w+_\d+", ln)
               for ln in op_lines), op_lines
