"""Mesh-parallel scan path (copr/parallel.py): shard_map + collectives.

These tests run on the 8-virtual-CPU-device mesh (conftest) with TILE=1024,
so a 20k-row table spans ~20 tiles across all 8 shards — the cross-tile
merge, cross-shard psum/pmin/pmax, deletion masks beyond tile 0, and the
device cache all execute.  Parity is asserted against the CPU oracle engine.
"""

import numpy as np
import pytest

import jax

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain


def _approx_eq(a, b):
    if isinstance(a, float) or isinstance(b, float):
        return a == pytest.approx(b, rel=1e-9, abs=1e-9)
    return a == b


def _parity(sess, sql):
    sess.execute("set tidb_use_tpu = 1")
    tpu = sess.query(sql)
    sess.execute("set tidb_use_tpu = 0")
    cpu = sess.query(sql)
    sess.execute("set tidb_use_tpu = 1")
    assert len(tpu) == len(cpu), (sql, tpu, cpu)
    for ra, rb in zip(tpu, cpu):
        assert all(_approx_eq(x, y) for x, y in zip(ra, rb)), (sql, ra, rb)
    return tpu


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute(
        "create table t (k bigint, g bigint, x double, s varchar(10), "
        "d decimal(10,2))"
    )
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(3)
    n = 20_000
    names = np.array(["aa", "bb", "cc"], dtype=object)
    store.bulk_load_arrays(
        [
            np.arange(n, dtype=np.int64),
            rng.integers(0, 7, n, dtype=np.int64),
            rng.uniform(0, 100, n),
            names[rng.integers(0, 3, n)],
            rng.integers(0, 10_000, n, dtype=np.int64),  # scaled .2
        ],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, 4, store.base_rows)
    return s


def _mesh_count():
    return REGISTRY.snapshot().get("mesh_scans_total", 0)


def test_mesh_used_and_sharded(sess):
    """The query must go through the mesh program, and the cached tile
    arrays must actually be laid out across every device (not replicated,
    not single-device)."""
    before = _mesh_count()
    sess.execute("set tidb_use_tpu = 1")
    sess.query("select g, count(*) from t group by g")
    assert _mesh_count() > before, "query did not take the mesh path"

    from tidb_tpu.copr.parallel import MESH_CACHE

    assert MESH_CACHE._cache, "mesh cache empty"
    data, _valid = next(iter(MESH_CACHE._cache.values()))
    used = {s.device for s in data.addressable_shards}
    assert len(used) == len(jax.devices()), (
        f"tiles on {len(used)} devices, expected {len(jax.devices())}"
    )


def test_mesh_agg_parity(sess):
    _parity(
        sess,
        "select g, sum(x), count(*), min(x), max(x), avg(x), sum(d) from t "
        "where k < 15000 and s != 'bb' group by g order by g",
    )


def test_mesh_agg_no_groupby(sess):
    _parity(sess, "select sum(x), count(*), min(k), max(k) from t "
                  "where x between 10 and 60")


def test_mesh_string_group_key(sess):
    _parity(sess, "select s, count(*), avg(x) from t group by s order by s")


def test_mesh_topn_parity(sess):
    _parity(sess, "select k, x from t where s = 'aa' order by x desc limit 9")
    _parity(sess, "select k, x from t order by x limit 5")


def test_mesh_filter_parity(sess):
    r = _parity(sess, "select k from t where x < 0.5 and s != 'cc' order by k")
    assert len(r) > 0


def test_mesh_limit(sess):
    sess.execute("set tidb_use_tpu = 1")
    rows = sess.query("select k from t where x < 50 limit 13")
    assert len(rows) == 13


def test_mesh_with_deletes_and_updates(sess):
    """MVCC delta overlay on the mesh path: deletes mask rows in high tiles,
    updates surface through the CPU delta merge."""
    sess.execute("set tidb_use_tpu = 1")
    sess.execute("delete from t where k >= 18000 and k < 18500")
    sess.execute("update t set x = 1000000.0 where k = 19000")
    _parity(sess, "select g, count(*), sum(x) from t group by g order by g")
    _parity(sess, "select k, x from t order by x desc limit 3")
    rows = sess.query("select max(x) from t")
    assert rows[0][0] == pytest.approx(1000000.0)
    cnt = sess.query("select count(*) from t where k >= 18000 and k < 18500")
    assert cnt == [(0,)]


def test_mesh_first_row_groupkey(sess):
    """first_row partials (SELECT of a group key col) resolve globally."""
    _parity(sess, "select s, min(k) from t group by s order by s")


@pytest.fixture(scope="module")
def ndv_sess():
    """High-NDV / float / NULLable group keys -> the sort-based device agg."""
    d = Domain()
    s = d.new_session()
    s.execute("create table h (k bigint, f double, g bigint, x double)")
    t = d.catalog.info_schema().table("test", "h")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(5)
    n = 30_000
    gv = rng.integers(0, 200_000, n)       # NDV far beyond the 64k dense cap
    gvalid = rng.random(n) > 0.02          # ~2% NULL keys
    fv = np.round(rng.uniform(0, 3, n), 1)
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64), fv, gv.astype(np.int64),
         rng.uniform(0, 10, n)],
        valids=[None, None, gvalid, None],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, 5, store.base_rows)
    return s


def _sort_parity(sess, sql):
    e0 = REGISTRY.snapshot().get("mesh_scan_errors_total", 0)
    m0 = _mesh_count()
    rows = _parity(sess, sql)
    assert _mesh_count() > m0, f"not on the mesh path: {sql}"
    assert REGISTRY.snapshot().get("mesh_scan_errors_total", 0) == e0
    return rows


def test_sort_agg_high_ndv(ndv_sess):
    rows = _sort_parity(
        ndv_sess,
        "select g, count(*), sum(x), min(x), max(x), avg(x) from h "
        "group by g order by g limit 50",
    )
    assert len(rows) == 50


def test_sort_agg_null_key_group(ndv_sess):
    """NULL is its own group and must survive the device path."""
    rows = _sort_parity(
        ndv_sess, "select count(*) from h where g is null")
    assert rows[0][0] > 0


def test_sort_agg_float_key(ndv_sess):
    rows = _sort_parity(
        ndv_sess, "select f, count(*), sum(x) from h group by f order by f")
    assert len(rows) == 31


def test_sort_agg_multi_key(ndv_sess):
    _sort_parity(
        ndv_sess,
        "select f, g, count(*) from h where g < 1000 "
        "group by f, g order by f, g",
    )


def test_sort_agg_first_row_key(ndv_sess):
    """Selecting a group key column uses first_row partials."""
    _sort_parity(
        ndv_sess,
        "select g, min(k) from h where g < 5000 group by g order by g",
    )


@pytest.fixture(scope="module")
def q3_sess():
    """customer ⋈ orders ⋈ lineitem with the fact scan on the mesh."""
    from tidb_tpu.types.values import parse_date

    d = Domain()
    s = d.new_session()
    rng = np.random.default_rng(2)
    s.execute("create table customer (c_custkey bigint, c_mktsegment varchar(10))")
    s.execute("create table orders (o_orderkey bigint, o_custkey bigint, "
              "o_orderdate date, o_shippriority bigint)")
    s.execute("create table lineitem (l_orderkey bigint, l_extendedprice double, "
              "l_discount double, l_shipdate date)")
    nc, no, nl = 1000, 4000, 20000
    segs = np.array(["BUILDING", "AUTOMOBILE", "MACHINERY"], dtype=object)
    base = parse_date("1995-01-01")
    for name, arrays in (
        ("customer", [np.arange(1, nc + 1, dtype=np.int64),
                      segs[rng.integers(0, 3, nc)]]),
        ("orders", [np.arange(1, no + 1, dtype=np.int64),
                    rng.integers(1, nc + 1, no).astype(np.int64),
                    (base + rng.integers(-200, 200, no)).astype(np.int32),
                    rng.integers(0, 3, no).astype(np.int64)]),
        ("lineitem", [rng.integers(1, no + 1, nl).astype(np.int64),
                      rng.uniform(900, 100000, nl),
                      np.round(rng.uniform(0, 0.1, nl), 2),
                      (base + rng.integers(-200, 200, nl)).astype(np.int32)]),
    ):
        t = d.catalog.info_schema().table("test", name)
        d.storage.table(t.id).bulk_load_arrays(
            arrays, ts=d.storage.current_ts())
    lt = d.catalog.info_schema().table("test", "lineitem")
    d.storage.regions.split_even(lt.id, 6, d.storage.table(lt.id).base_rows)
    return s


Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)), o_orderdate,
       o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < '1995-03-15' and l_shipdate > '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by 2 desc, o_orderdate limit 10
"""


def test_q3_plans_runtime_filter(q3_sess):
    """With MPP lanes off, Q3 keeps the host hash-join plan whose build
    side pushes a runtime filter into the probe scan.  (With MPP on the
    join-tree compiler now owns this shape end-to-end — ISSUE 12 — so
    the runtime-filter lane is the fallback under test here.)"""
    q3_sess.execute("set tidb_allow_mpp = 0")
    try:
        rs = q3_sess.execute("explain " + Q3)[0]
    finally:
        q3_sess.execute("set tidb_allow_mpp = 1")
    plan = "\n".join(str(r) for r in rs.rows)
    assert "JoinProbe" in plan, plan
    assert "runtime-filter" in plan, plan


def test_q3_plans_device_join_tree(q3_sess):
    """The default plan for the Q3 shape is now the device rung ladder
    with the chosen join order and per-rung estimates."""
    rs = q3_sess.execute("explain " + Q3)[0]
    plan = "\n".join(str(r) for r in rs.rows)
    assert "MPPJoinTree" in plan, plan
    assert "order: " in plan, plan


def test_q3_parity_with_device_probe(q3_sess):
    e0 = REGISTRY.snapshot().get("mesh_scan_errors_total", 0)
    _parity(q3_sess, Q3)
    assert REGISTRY.snapshot().get("mesh_scan_errors_total", 0) == e0


def test_runtime_filter_semi_join(q3_sess):
    _parity(
        q3_sess,
        "select count(*) from lineitem where l_orderkey in "
        "(select o_orderkey from orders where o_orderdate < '1994-09-01')",
    )


def test_runtime_filter_null_probe_keys():
    """Probe rows with NULL keys never pass the device filter."""
    d = Domain()
    s = d.new_session()
    s.execute("create table bb (k bigint, v bigint)")
    s.execute("create table pp (k bigint, w bigint)")
    s.execute("insert into bb values (1, 1), (2, 2)")
    s.execute("insert into pp values (1, 10), (null, 99), (2, 20)")
    rows = sorted(s.query(
        "select pp.w, bb.v from pp join bb on pp.k = bb.k"))
    assert rows == [(10, 1), (20, 2)]


def test_mesh_multi_range_not_used():
    """>4 disjoint ranges falls back to the per-region path but stays
    correct."""
    d = Domain()
    s = d.new_session()
    s.execute("create table m (a bigint, b bigint)")
    s.execute("insert into m values " + ", ".join(
        f"({i}, {i * 2})" for i in range(100)
    ))
    assert s.query("select sum(b) from m") == [(sum(i * 2 for i in range(100)),)]


def test_dense_first_row_bare_column(sess):
    """A bare non-grouped column becomes a first_row agg: exercises the
    dense-mode per-shard argfirst partial + host min-merge (the axon TPU
    backend only lowers Sum all-reduces, so first_row cannot pmin)."""
    before = REGISTRY.snapshot()
    _parity(sess, "select g, s, min(k) from t group by g order by g")
    after = REGISTRY.snapshot()
    assert after.get("mesh_scans_total", 0) > before.get("mesh_scans_total", 0)
    assert after.get("mesh_scan_errors_total", 0) == \
        before.get("mesh_scan_errors_total", 0)


def test_dense_minmax_partial_merge(sess):
    """min/max partials are per-shard (host-merged): cover groups that are
    empty on some shards via a selective filter."""
    _parity(sess, "select g, min(d), max(d), min(x), max(x) from t "
                  "where k < 1500 group by g order by g")


def test_filter_results_stream_in_bounded_chunks():
    """Low-selectivity mesh filters gather selected rows in STREAM_ROWS
    slices (distsql/stream.go analog): peak host materialization per step
    is bounded, and LIMIT stops the gather early (VERDICT r2 item 9)."""
    from tidb_tpu.copr import parallel as pp

    d = Domain()
    s = d.new_session()
    s.execute("create table st (a bigint, b bigint)")
    t = d.catalog.info_schema().table("test", "st")
    n = 60_000
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         np.arange(n, dtype=np.int64) % 7],
        ts=d.storage.current_ts())
    s.execute("set tidb_use_tpu = 1")
    orig = pp.STREAM_ROWS
    pp.STREAM_ROWS = 4096
    try:
        before = REGISTRY.snapshot().get("mesh_stream_chunks_total", 0)
        rows = s.query("select a from st where b < 6")  # ~86% selectivity
        after = REGISTRY.snapshot().get("mesh_stream_chunks_total", 0)
        assert len(rows) == sum(1 for i in range(n) if i % 7 < 6)
        assert after - before >= len(rows) / 4096  # many bounded chunks
        # LIMIT early-stop: only ~1 slice gathered despite ~51k matches
        before = REGISTRY.snapshot().get("mesh_stream_chunks_total", 0)
        rows = s.query("select a from st where b < 6 limit 10")
        after = REGISTRY.snapshot().get("mesh_stream_chunks_total", 0)
        assert len(rows) == 10
        assert after - before <= 2
    finally:
        pp.STREAM_ROWS = orig
