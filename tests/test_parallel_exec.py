"""Parallel root executors consume their concurrency sysvars.

Reference: executor/aggregate.go:101-169 (partial/final worker graph),
executor/join.go:307-414 (probe workers), executor/projection.go:185-217
(parallel projection).  These tests assert (a) the knobs are actually read
— the worker metric moves with the setting — and (b) results are identical
to the serial path (order-preserving pipelines).
"""

import numpy as np
import pytest

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table p (a bigint, b bigint, g bigint)")
    t = d.catalog.info_schema().table("test", "p")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(7)
    n = 40_000
    store.bulk_load_arrays([
        np.arange(n, dtype=np.int64),
        rng.integers(0, 1000, n, dtype=np.int64),
        rng.integers(0, 12_000, n, dtype=np.int64),  # high NDV for final
    ], ts=d.storage.current_ts())
    d.storage.regions.split_even(t.id, 8, store.base_rows)
    s.execute("create table q (k bigint, v bigint)")
    s.execute("insert into q values " + ",".join(
        f"({i},{i * 10})" for i in range(500)))
    return s


def _workers_used(sess, sql):
    before = REGISTRY.snapshot().get("executor_parallel_workers_total", 0)
    rows = sess.query(sql)
    after = REGISTRY.snapshot().get("executor_parallel_workers_total", 0)
    return rows, after - before


def test_projection_workers_follow_sysvar(sess):
    sess.execute("set tidb_use_tpu = 0")  # fan-out: multi-chunk stream
    sql = "select a + b * 2, b - a from p"
    sess.execute("set tidb_projection_concurrency = 1")
    serial, w1 = _workers_used(sess, sql)
    sess.execute("set tidb_projection_concurrency = 3")
    par, w3 = _workers_used(sess, sql)
    sess.execute("set tidb_use_tpu = 1")
    # scan fan-out arrival order is nondeterministic (as_completed), so
    # compare as multisets; the pipeline itself preserves its input order
    assert sorted(serial) == sorted(par)
    assert w3 > w1  # the knob reached the pool


def test_hash_join_probe_workers(sess):
    # cpu engine: per-region fan-out yields a multi-chunk probe stream
    # (the lazy pipeline stays inline for single-chunk streams by design)
    sess.execute("set tidb_use_tpu = 0")
    sql = ("select count(*), sum(v) from p join q on p.b = q.k")
    sess.execute("set tidb_hash_join_concurrency = 1")
    serial, _ = _workers_used(sess, sql)
    sess.execute("set tidb_hash_join_concurrency = 4")
    par, w = _workers_used(sess, sql)
    sess.execute("set tidb_use_tpu = 1")
    assert serial == par
    assert w >= 4


def test_hashagg_final_workers_partition_merge(sess):
    # 12k distinct groups -> partial rows >> 8192 threshold: the final
    # merge partitions across tidb_hashagg_final_concurrency workers
    sql = "select g, count(*), sum(a) from p group by g order by g limit 5"
    sess.execute("set tidb_use_tpu = 0")  # host HashAgg path
    sess.execute("set tidb_hashagg_final_concurrency = 1")
    serial, _ = _workers_used(sess, sql)
    sess.execute("set tidb_hashagg_final_concurrency = 4")
    par, w = _workers_used(sess, sql)
    sess.execute("set tidb_use_tpu = 1")
    assert serial == par
    assert w >= 4


def test_umbrella_executor_concurrency(sess):
    # per-operator knob unset (-1, the registered default) falls back to
    # tidb_executor_concurrency
    sess.execute("set tidb_use_tpu = 0")
    sess.execute("set tidb_projection_concurrency = -1")
    sess.execute("set tidb_executor_concurrency = 6")
    _, w = _workers_used(sess, "select a * 3 from p")
    sess.execute("set tidb_projection_concurrency = 4")
    sess.execute("set tidb_executor_concurrency = 5")
    sess.execute("set tidb_use_tpu = 1")
    assert w >= 6
