"""Parser tests — statement surface + TPC-H query shapes.

Reference model: pingcap/parser test suites; TPC-H text from the reference's
cmd/explaintest/t/tpch.test (shapes re-typed, not copied).
"""

import pytest

from tidb_tpu.errors import ParseError
from tidb_tpu.parser import ast, parse, parse_one


def test_simple_select():
    s = parse_one("SELECT a, b+1 AS c FROM t WHERE a > 3 ORDER BY b DESC LIMIT 10")
    assert isinstance(s, ast.SelectStmt)
    assert len(s.fields) == 2
    assert s.fields[1].alias == "c"
    assert isinstance(s.where, ast.BinaryOp) and s.where.op == ">"
    assert s.order_by[0].desc
    assert s.limit == 10


def test_operator_precedence():
    s = parse_one("SELECT 1+2*3")
    e = s.fields[0].expr
    assert e.op == "+" and e.right.op == "*"
    s = parse_one("SELECT a OR b AND NOT c = 1")
    e = s.fields[0].expr
    assert e.op == "or"
    assert e.right.op == "and"


def test_string_escapes():
    s = parse_one("SELECT 'it''s', 'a\\nb', \"dq\"")
    vals = [f.expr.value for f in s.fields]
    assert vals == ["it's", "a\nb", "dq"]


def test_in_between_like_null():
    s = parse_one(
        "SELECT * FROM t WHERE a IN (1,2,3) AND b NOT IN (4) AND c BETWEEN 1 AND 9 "
        "AND d LIKE 'x%' AND e IS NOT NULL"
    )
    assert s.where is not None


def test_join_tree():
    s = parse_one(
        "SELECT * FROM a JOIN b ON a.x=b.x LEFT JOIN c ON b.y=c.y, d"
    )
    j = s.from_clause
    assert isinstance(j, ast.Join) and j.kind == "cross"
    assert j.left.kind == "left"
    assert j.left.left.kind == "inner"


def test_subqueries():
    s = parse_one(
        "SELECT (SELECT MAX(x) FROM t2), a FROM (SELECT * FROM t3) sub "
        "WHERE EXISTS (SELECT 1 FROM t4) AND a IN (SELECT b FROM t5)"
    )
    assert isinstance(s.fields[0].expr, ast.ScalarSubquery)
    assert isinstance(s.from_clause, ast.SubqueryRef)
    assert isinstance(s.where.left, ast.Exists)
    assert isinstance(s.where.right, ast.InSubquery)


def test_case_cast_interval():
    s = parse_one(
        "SELECT CASE WHEN a>0 THEN 'p' ELSE 'n' END, CAST(a AS DECIMAL(10,2)), "
        "d + INTERVAL 3 MONTH, DATE '1995-01-01' FROM t"
    )
    assert isinstance(s.fields[0].expr, ast.CaseWhen)
    c = s.fields[1].expr
    assert isinstance(c, ast.Cast) and c.precision == 10 and c.scale == 2
    iv = s.fields[2].expr.right
    assert isinstance(iv, ast.Interval) and iv.unit == "month"
    assert s.fields[3].expr.type_hint == "date"


def test_aggregates():
    s = parse_one("SELECT COUNT(*), COUNT(DISTINCT a), SUM(b), AVG(c) FROM t GROUP BY d HAVING SUM(b)>0")
    assert s.fields[0].expr.name == "count"
    assert isinstance(s.fields[0].expr.args[0], ast.Star)
    assert s.fields[1].expr.distinct
    assert len(s.group_by) == 1 and s.having is not None


def test_create_table():
    s = parse_one(
        """CREATE TABLE IF NOT EXISTS lineitem (
            l_orderkey BIGINT NOT NULL,
            l_quantity DECIMAL(15,2),
            l_comment VARCHAR(44),
            l_shipdate DATE,
            PRIMARY KEY (l_orderkey),
            KEY idx_ship (l_shipdate)
        )"""
    )
    assert isinstance(s, ast.CreateTableStmt)
    assert s.if_not_exists
    assert [c.name for c in s.columns] == [
        "l_orderkey", "l_quantity", "l_comment", "l_shipdate"
    ]
    assert s.columns[0].not_null
    assert s.columns[1].type_name == "decimal" and s.columns[1].scale == 2
    assert len(s.indexes) == 2 and s.indexes[0].primary


def test_insert_update_delete():
    i = parse_one("INSERT INTO t (a,b) VALUES (1,'x'), (2,NULL)")
    assert len(i.values) == 2
    u = parse_one("UPDATE t SET a = a + 1 WHERE b < 3")
    assert u.assignments[0][0] == "a"
    d = parse_one("DELETE FROM t WHERE a = 5 LIMIT 2")
    assert d.limit == 2


def test_utility_statements():
    assert isinstance(parse_one("BEGIN"), ast.BeginStmt)
    assert isinstance(parse_one("START TRANSACTION"), ast.BeginStmt)
    assert isinstance(parse_one("COMMIT"), ast.CommitStmt)
    assert isinstance(parse_one("ROLLBACK"), ast.RollbackStmt)
    assert isinstance(parse_one("USE test"), ast.UseStmt)
    e = parse_one("EXPLAIN ANALYZE SELECT 1")
    assert e.analyze and isinstance(e.target, ast.SelectStmt)
    sh = parse_one("SHOW TABLES")
    assert sh.kind == "tables"
    st = parse_one("SET @@session.tidb_executor_concurrency = 8, GLOBAL x = 1")
    assert st.assignments[0][0] == "tidb_executor_concurrency"
    assert st.assignments[1][1] is True
    an = parse_one("ANALYZE TABLE t1, t2")
    assert len(an.tables) == 2


def test_multi_statement():
    stmts = parse("SELECT 1; SELECT 2;")
    assert len(stmts) == 2


def test_union():
    u = parse_one("SELECT a FROM t1 UNION ALL SELECT b FROM t2 ORDER BY 1 LIMIT 5")
    assert isinstance(u, ast.UnionStmt) and u.all and u.limit == 5


def test_parse_error_location():
    with pytest.raises(ParseError):
        parse_one("SELECT FROM WHERE")
    with pytest.raises(ParseError):
        parse_one("SELEC 1")


def test_tpch_q1_shape():
    # TPC-H Q1 (re-typed shape; reference runs it in cmd/explaintest/t/tpch.test)
    q = """
    select l_returnflag, l_linestatus,
        sum(l_quantity) as sum_qty,
        sum(l_extendedprice) as sum_base_price,
        sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
        sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
        avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
        avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem
    where l_shipdate <= date '1998-12-01' - interval 108 day
    group by l_returnflag, l_linestatus
    order by l_returnflag, l_linestatus
    """
    s = parse_one(q)
    assert len(s.fields) == 10
    assert len(s.group_by) == 2
    assert isinstance(s.where.right, ast.BinaryOp)


def test_tpch_q3_shape():
    q = """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'AUTOMOBILE' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-13'
      and l_shipdate > date '1995-03-13'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10
    """
    s = parse_one(q)
    assert s.limit == 10 and s.order_by[0].desc
    j = s.from_clause
    assert isinstance(j, ast.Join) and j.kind == "cross"


def test_tpch_q6_shape():
    q = """
    select sum(l_extendedprice * l_discount) as revenue
    from lineitem
    where l_shipdate >= '1994-01-01'
      and l_shipdate < date '1994-01-01' + interval '1' year
      and l_discount between 0.06 - 0.01 and 0.06 + 0.01
      and l_quantity < 24
    """
    s = parse_one(q)
    assert s.fields[0].alias == "revenue"


def test_prepared_params():
    p = parse_one("SELECT * FROM t WHERE a = ? AND b > ?")
    refs = []

    def walk(e):
        if isinstance(e, ast.Param):
            refs.append(e.index)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, ast.Node):
                walk(v)
    walk(p.where)
    assert refs == [0, 1]
