"""Partitioned tables: RANGE/HASH DDL, write routing, plan-time pruning,
per-partition mesh scans with dual-engine parity, row movement, DDL on
partitioned tables, persistence.

Reference: planner/core/rule_partition_processor.go:1-249 (pruning),
table/tables/partition.go (locatePartition / cross-partition row movement),
ddl/ddl_api.go checkPartitionKeysConstraint (unique keys must embed the
partition column, MySQL error 1503)."""

import numpy as np
import pytest

from tidb_tpu.errors import KVError, PlanError, TiDBTPUError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    return Domain()


@pytest.fixture()
def s(d):
    sess = d.new_session()
    sess.execute(
        "create table r (id bigint primary key, v bigint, name varchar(16))"
        " partition by range (id) ("
        "  partition p0 values less than (100),"
        "  partition p1 values less than (1000),"
        "  partition pmax values less than maxvalue)")
    return sess


def _rows(sess, sql):
    return sess.execute(sql)[-1].rows


def _parity(sess, sql):
    sess.execute("set tidb_use_tpu = 1")
    dev = _rows(sess, sql)
    sess.execute("set tidb_use_tpu = 0")
    cpu = _rows(sess, sql)
    sess.execute("set tidb_use_tpu = 1")
    assert sorted(map(repr, dev)) == sorted(map(repr, cpu)), sql
    return dev


def _plan(sess, sql):
    return "\n".join(r[0] + " " + r[3] for r in _rows(sess, "explain " + sql))


# ---------------------------------------------------------------------------
# DDL + metadata
# ---------------------------------------------------------------------------


def test_create_and_show_create(s, d):
    t = d.catalog.info_schema().table("test", "r")
    assert t.partition_info is not None
    assert [p.name for p in t.partition_info.defs] == ["p0", "p1", "pmax"]
    # each partition owns a real store
    for pd in t.partition_info.defs:
        assert d.storage.has_table(pd.id)
    out = _rows(s, "show create table r")[0][1]
    assert "PARTITION BY RANGE" in out and "MAXVALUE" in out


def test_hash_partitions(d):
    s = d.new_session()
    s.execute("create table h (k bigint, x bigint)"
              " partition by hash (k) partitions 4")
    t = d.catalog.info_schema().table("test", "h")
    assert len(t.partition_info.defs) == 4
    s.execute("insert into h values (0,0),(1,1),(2,2),(3,3),(4,4),(7,7)")
    # rows routed by k % 4
    counts = {}
    for i, pd in enumerate(t.partition_info.defs):
        st = d.storage.table(pd.id)
        _, inserted = st.delta_overlay(d.storage.current_ts(), 0, 1 << 62)
        counts[i] = len(inserted) + st.base_rows
    assert counts == {0: 2, 1: 1, 2: 1, 3: 2}  # 0,4 | 1 | 2 | 3,7


def test_range_bounds_must_increase(d):
    s = d.new_session()
    with pytest.raises(TiDBTPUError):
        s.execute("create table bad (a bigint) partition by range (a) ("
                  " partition p0 values less than (10),"
                  " partition p1 values less than (5))")


def test_maxvalue_only_last(d):
    s = d.new_session()
    with pytest.raises(TiDBTPUError):
        s.execute("create table bad (a bigint) partition by range (a) ("
                  " partition p0 values less than maxvalue,"
                  " partition p1 values less than (5))")


def test_unique_must_include_partition_col(d):
    s = d.new_session()
    with pytest.raises(TiDBTPUError):
        s.execute("create table bad (a bigint, b bigint unique)"
                  " partition by hash (a) partitions 2")
    # ALTER path enforces it too
    s.execute("create table ok (a bigint, b bigint)"
              " partition by hash (a) partitions 2")
    with pytest.raises(TiDBTPUError):
        s.execute("create unique index ub on ok (b)")
    s.execute("create unique index uab on ok (a, b)")  # embeds a: fine
    t = d.catalog.info_schema().table("test", "ok")
    assert t.find_index("uab") is not None


def test_column_ddl_on_partitioned(s, d):
    s.execute("insert into r values (1, 10, 'a'), (200, 20, 'b')")
    s.execute("commit")
    s.execute("alter table r add column extra bigint default 7")
    assert sorted(_rows(s, "select id, extra from r")) == [(1, 7), (200, 7)]
    s.execute("alter table r drop column extra")
    assert len(_rows(s, "select * from r")[0]) == 3


def test_truncate_and_drop(s, d):
    s.execute("insert into r values (1, 10, 'a'), (200, 20, 'b')")
    old = d.catalog.info_schema().table("test", "r")
    old_pids = [p.id for p in old.partition_info.defs]
    s.execute("truncate table r")
    assert _rows(s, "select count(*) from r") == [(0,)]
    new = d.catalog.info_schema().table("test", "r")
    assert [p.id for p in new.partition_info.defs] != old_pids
    for pid in old_pids:
        assert not d.storage.has_table(pid)
    s.execute("drop table r")
    for pd in new.partition_info.defs:
        assert not d.storage.has_table(pd.id)


def test_catalog_persistence_roundtrip(tmp_path):
    dd = str(tmp_path / "data")
    d1 = Domain(data_dir=dd)
    s1 = d1.new_session()
    s1.execute("create table pr (id bigint primary key, v bigint)"
               " partition by range (id) ("
               " partition a values less than (10),"
               " partition b values less than maxvalue)")
    s1.execute("insert into pr values (5, 50), (15, 150)")
    s1.execute("commit")
    d2 = Domain(data_dir=dd)
    s2 = d2.new_session()
    t = d2.catalog.info_schema().table("test", "pr")
    assert t.partition_info is not None
    assert [p.name for p in t.partition_info.defs] == ["a", "b"]
    assert sorted(_rows(s2, "select * from pr")) == [(5, 50), (15, 150)]


# ---------------------------------------------------------------------------
# routing + pruning
# ---------------------------------------------------------------------------


def test_insert_routes_to_partition(s, d):
    s.execute("insert into r values (5,1,'x'), (150,2,'y'), (5000,3,'z')")
    t = d.catalog.info_schema().table("test", "r")
    ts = d.storage.current_ts()
    per = []
    for pd in t.partition_info.defs:
        _, ins = d.storage.table(pd.id).delta_overlay(ts, 0, 1 << 62)
        per.append(sorted(row[0] for row in ins.values()))
    assert per == [[5], [150], [5000]]


def test_out_of_range_value_rejected(s):
    s2_sql = ("create table nr (a bigint) partition by range (a) ("
              " partition p0 values less than (10))")
    s.execute(s2_sql)
    with pytest.raises(TiDBTPUError):
        s.execute("insert into nr values (11)")


def test_pruning_in_explain(s):
    s.execute("insert into r values (5,1,'x'), (150,2,'y'), (5000,3,'z')")
    s.execute("commit")
    assert "partition:p0" in _plan(s, "select * from r where id < 50")
    assert "partition:p1 " in _plan(s, "select * from r where id = 500") or \
        "partition:p1" in _plan(s, "select * from r where id = 500")
    p = _plan(s, "select * from r where id >= 100 and id < 900")
    assert "partition:p1" in p and "p0" not in p and "pmax" not in p
    p = _plan(s, "select * from r where id in (5, 7)")
    assert "partition:p0" in p and "p1" not in p
    # no predicate on the partition column: all partitions
    p = _plan(s, "select * from r where v = 1")
    assert "partition:p0,p1,pmax" in p


def test_impossible_range_prunes_everything(s):
    s.execute("insert into r values (5,1,'x')")
    s.execute("commit")
    assert _rows(s, "select * from r where id < 5 and id > 50") == []
    p = _plan(s, "select * from r where id < 5 and id > 50")
    assert "Dual" in p


def test_pruning_correctness_vs_full_scan(d):
    s = d.new_session()
    s.execute("create table big (id bigint, v bigint)"
              " partition by range (id) ("
              " partition p0 values less than (1000),"
              " partition p1 values less than (2000),"
              " partition p2 values less than maxvalue)")
    t = d.catalog.info_schema().table("test", "big")
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 3000, 6000, dtype=np.int64)
    vs = rng.integers(0, 100, 6000, dtype=np.int64)
    ts = d.storage.current_ts()
    for pd, lo, hi in zip(t.partition_info.defs,
                          [0, 1000, 2000], [1000, 2000, 3001]):
        m = (ids >= lo) & (ids < hi)
        d.storage.table(pd.id).bulk_load_arrays(
            [ids[m], vs[m]], ts=ts)
    for q in [
        "select count(*), sum(v) from big",
        "select count(*) from big where id < 1500",
        "select sum(v) from big where id >= 1000 and id < 2000",
        "select v, count(*) from big where id < 2200 group by v",
        "select * from big where id = 1234",
        "select v from big order by v desc limit 5",
    ]:
        _parity(s, q)


# ---------------------------------------------------------------------------
# DML semantics
# ---------------------------------------------------------------------------


def test_update_moves_row_across_partitions(s, d):
    s.execute("insert into r values (5, 1, 'x')")
    s.execute("update r set id = 2500 where id = 5")
    assert _rows(s, "select id from r") == [(2500,)]
    t = d.catalog.info_schema().table("test", "r")
    # the txn buffer put must target pmax's store
    pmax = t.partition_info.defs[-1]
    assert sorted(_rows(s, "select id from r where id > 1000")) == [(2500,)]
    s.execute("commit")
    ts = d.storage.current_ts()
    _, ins = d.storage.table(pmax.id).delta_overlay(ts, 0, 1 << 62)
    assert [row[0] for row in ins.values()] == [2500]


def test_unique_enforced_within_partition(s):
    s.execute("insert into r values (5, 1, 'x')")
    s.execute("commit")
    with pytest.raises(KVError):
        s.execute("insert into r values (5, 2, 'y')")
    # replace overwrites
    s.execute("replace into r values (5, 9, 'z')")
    assert _rows(s, "select v from r where id = 5") == [(9,)]
    # on duplicate key update
    s.execute("insert into r values (5, 1, 'w')"
              " on duplicate key update v = v + 100")
    assert _rows(s, "select v from r where id = 5") == [(109,)]


def test_delete_with_pruning(s):
    s.execute("insert into r values (5,1,'a'), (150,2,'b'), (5000,3,'c')")
    s.execute("delete from r where id < 100")
    assert sorted(r[0] for r in _rows(s, "select id from r")) == [150, 5000]


def test_autocommit_txn_crosses_partitions_atomically(s, d):
    s.execute("begin")
    s.execute("insert into r values (5,1,'a'), (150,2,'b')")
    s.execute("rollback")
    assert _rows(s, "select count(*) from r") == [(0,)]
    s.execute("begin")
    s.execute("insert into r values (5,1,'a'), (150,2,'b')")
    s.execute("commit")
    assert _rows(s, "select count(*) from r") == [(2,)]


def test_update_no_halloween(d):
    """A row moved into a later partition must not be updated again by that
    partition's reader (update.go reads at start_ts; here: materialize all
    reads before the first write)."""
    s = d.new_session()
    s.execute("create table hw (id bigint primary key, v bigint)"
              " partition by range (id) ("
              " partition p0 values less than (100),"
              " partition p1 values less than (200),"
              " partition p2 values less than maxvalue)")
    s.execute("insert into hw values (50, 1), (1000, 2)")
    s.execute("update hw set id = id + 100")
    assert sorted(r[0] for r in _rows(s, "select id from hw")) == [150, 1100]


def test_commit_schema_check_covers_partitions(d):
    """DDL on a partitioned table must fail a concurrent txn's commit, same
    as non-partitioned (2pc.go:1151-1155 schema check on physical ids)."""
    a, b = d.new_session(), d.new_session()
    a.execute("create table sc (x bigint, y bigint)"
              " partition by hash (x) partitions 2")
    a.execute("begin")
    a.execute("insert into sc values (1, 1)")
    b.execute("create unique index ux on sc (x)")
    with pytest.raises(TiDBTPUError):
        a.execute("commit")


def test_on_dup_update_moves_then_reinserts(d):
    """ON DUPLICATE KEY UPDATE that moves the row frees its old key: a later
    duplicate in the same statement inserts fresh (MySQL semantics)."""
    s = d.new_session()
    s.execute("create table od (id bigint primary key, v bigint)"
              " partition by hash (id) partitions 4")
    s.execute("insert into od values (1, 10)")
    s.execute("insert into od values (1, 0), (1, 99)"
              " on duplicate key update id = id + 1, v = values(v)")
    assert sorted(_rows(s, "select * from od")) == [(1, 99), (2, 0)]


def test_rename_preserves_views_and_partitions(d):
    s = d.new_session()
    s.execute("create table b (x bigint)")
    s.execute("insert into b values (1)")
    s.execute("create view v as select x from b")
    s.execute("rename table v to w")
    assert _rows(s, "select * from w") == [(1,)]  # still a view
    s.execute("create table pr (k bigint) partition by hash (k) partitions 2")
    a = d.new_session()
    a.execute("begin")
    a.execute("insert into pr values (1)")
    s.execute("rename table pr to pr2")
    with pytest.raises(TiDBTPUError):
        a.execute("commit")  # schema check sees the rename via partition ids


def test_insert_ignore_skips_out_of_range(d):
    s = d.new_session()
    s.execute("create table nr2 (a bigint) partition by range (a) ("
              " partition p0 values less than (10))")
    s.execute("insert ignore into nr2 values (5), (99)")
    assert _rows(s, "select * from nr2") == [(5,)]


def test_auto_analyze_refreshes_merged_stats(d):
    s = d.new_session()
    s.execute("create table aa (k bigint, v bigint)"
              " partition by hash (k) partitions 2")
    s.execute("analyze table aa")
    t = d.catalog.info_schema().table("test", "aa")
    rows = ", ".join(f"({i}, {i})" for i in range(2000))
    s.execute(f"insert into aa values {rows}")
    st = d.stats.get(t.id)
    assert st is not None and st.row_count == 2000


def test_analyze_partitioned(s, d):
    s.execute("insert into r values (5,1,'a'), (150,2,'b'), (5000,3,'c')")
    s.execute("commit")
    s.execute("analyze table r")
    t = d.catalog.info_schema().table("test", "r")
    st = d.stats.get(t.id)
    assert st is not None and st.row_count == 3
    for pd in t.partition_info.defs:
        assert d.stats.get(pd.id) is not None


# ---------------------------------------------------------------------------
# partition management DDL (ddl_api.go:2187-2316 analog)
# ---------------------------------------------------------------------------

def _month_table(d):
    s = d.new_session()
    s.execute("create table ev (ts bigint, v bigint) partition by range (ts) ("
              " partition p2023 values less than (202400),"
              " partition p2024 values less than (202500))")
    s.execute("insert into ev values (202301, 1), (202401, 2), (202402, 3)")
    return s


def test_add_partition_range(d):
    s = _month_table(d)
    s.execute("alter table ev add partition ("
              "partition p2025 values less than (202600))")
    s.execute("insert into ev values (202501, 9)")
    assert s.query("select sum(v) from ev") == [(15,)]
    rows = s.execute("explain select * from ev where ts >= 202500")[0].rows
    assert any("p2025" in r[3] for r in rows)  # pruned to the new partition
    # bound validation
    import pytest as _pytest
    from tidb_tpu.errors import TiDBTPUError

    with _pytest.raises(TiDBTPUError):
        s.execute("alter table ev add partition ("
                  "partition bad values less than (100))")
    with _pytest.raises(TiDBTPUError):
        s.execute("alter table ev add partition ("
                  "partition p2025 values less than (202700))")


def test_drop_partition_removes_rows_and_stats(d):
    s = _month_table(d)
    s.execute("analyze table ev")
    old = d.catalog.info_schema().table("test", "ev")
    old_pid = old.partition_info.defs[0].id
    s.execute("alter table ev drop partition p2023")
    assert s.query("select sum(v) from ev") == [(5,)]
    assert d.stats.get(old_pid) is None  # per-partition stats invalidated
    t = d.catalog.info_schema().table("test", "ev")
    assert [p.name for p in t.partition_info.defs] == ["p2024"]
    import pytest as _pytest
    from tidb_tpu.errors import TiDBTPUError

    with _pytest.raises(TiDBTPUError):
        s.execute("alter table ev drop partition p2024")  # last one


def test_truncate_partition(d):
    s = _month_table(d)
    s.execute("alter table ev truncate partition p2024")
    assert s.query("select sum(v) from ev") == [(1,)]
    s.execute("insert into ev values (202403, 7)")
    assert s.query("select sum(v) from ev") == [(8,)]


def test_hash_add_and_coalesce_rebucket(d):
    s = d.new_session()
    s.execute("create table h (k bigint primary key, v bigint)"
              " partition by hash(k) partitions 3")
    s.execute("insert into h values " + ", ".join(
        f"({i}, {i * 10})" for i in range(50)))
    s.execute("alter table h add partition partitions 2")  # 3 -> 5 buckets
    t = d.catalog.info_schema().table("test", "h")
    assert len(t.partition_info.defs) == 5
    assert s.query("select count(*), sum(v) from h") == [(50, 12250)]
    # point reads re-route to the new buckets
    assert s.query("select v from h where k = 17") == [(170,)]
    s.execute("alter table h coalesce partition 3")  # 5 -> 2 buckets
    t = d.catalog.info_schema().table("test", "h")
    assert len(t.partition_info.defs) == 2
    assert s.query("select count(*), sum(v) from h") == [(50, 12250)]
    assert s.query("select v from h where k = 17") == [(170,)]
    s.execute("insert into h values (100, 1000)")
    assert s.query("select v from h where k = 100") == [(1000,)]


def test_rolling_month_partition_under_concurrent_reads(d):
    """The #1 real-world RANGE partition use: add the new month, drop the
    old month, while readers keep querying — every read sees a consistent
    schema snapshot and correct rows."""
    import threading

    s = _month_table(d)
    stop = threading.Event()
    errors = []
    ok_reads = [0]

    def reader():
        r = d.new_session()
        while not stop.is_set():
            try:
                rows = r.query("select count(*) from ev")
                assert rows[0][0] >= 1
                ok_reads[0] += 1
            except Exception as e:  # noqa: BLE001
                if "no storage for table" in str(e):
                    continue  # read raced the drop mid-statement: retried
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for th in threads:
        th.start()
    try:
        for month in range(5):
            bound = 202600 + month * 100
            s.execute(f"alter table ev add partition ("
                      f"partition pm{month} values less than ({bound}))")
            s.execute(f"insert into ev values ({bound - 50}, {month})")
            oldest = d.catalog.info_schema().table(
                "test", "ev").partition_info.defs[0].name
            s.execute(f"alter table ev drop partition {oldest}")
    finally:
        stop.set()
        for th in threads:
            th.join(10)
    assert not errors, errors
    assert ok_reads[0] > 0
    # final state: parity between engines
    s.execute("set tidb_use_tpu = 0")
    cpu = s.query("select sum(v) from ev")
    s.execute("set tidb_use_tpu = 1")
    assert s.query("select sum(v) from ev") == cpu
