"""Pessimistic SQL surface: SELECT ... FOR UPDATE row locks, blocking lock
waits, wait-for-graph deadlock detection with requester-as-victim, REPLACE.

Reference: executor/adapter.go:338-372 (SelectLockExec wiring),
store/tikv/2pc.go:668 (pessimistic lock_keys), util/deadlock/deadlock.go:
22-130 (Detect: the requesting txn whose edge closes a cycle aborts)."""

import threading
import time

import pytest

from tidb_tpu.errors import DeadlockError, LockWaitTimeoutError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    dom = Domain()
    s = dom.new_session()
    s.execute("create table acc (id bigint primary key, bal bigint)")
    s.execute("insert into acc values (1, 100), (2, 200), (3, 300)")
    return dom


def test_for_update_takes_row_locks(d):
    a = d.new_session()
    a.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    t = d.catalog.info_schema().table("test", "acc")
    store = d.storage.table(t.id)
    assert len(store.locks) == 1  # exactly the matched row
    a.execute("rollback")
    assert len(store.locks) == 0


def test_for_update_outside_txn_is_snapshot_read(d):
    a = d.new_session()  # autocommit: locks would release immediately
    assert a.query("select bal from acc where id = 1 for update") == [(100,)]
    t = d.catalog.info_schema().table("test", "acc")
    assert len(d.storage.table(t.id).locks) == 0


def test_lock_wait_blocks_until_release(d):
    a, b = d.new_session(), d.new_session()
    a.execute("begin")
    b.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    acquired = []

    def b_wait():
        b.execute("select * from acc where id = 1 for update")
        acquired.append(time.monotonic())

    th = threading.Thread(target=b_wait)
    th.start()
    time.sleep(0.25)
    assert not acquired  # still blocked
    release_at = time.monotonic()
    a.execute("commit")
    th.join(5)
    assert acquired and acquired[0] >= release_at
    b.execute("rollback")


def test_deadlock_aborts_requester_deterministically(d):
    """A holds r1 + wants r2; B holds r2 + wants r1 -> B (whose request
    closes the cycle) gets ER_LOCK_DEADLOCK; A then proceeds."""
    a, b = d.new_session(), d.new_session()
    a.execute("begin")
    b.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    b.execute("select * from acc where id = 2 for update")
    results = {}

    def a_then():
        try:
            a.execute("select * from acc where id = 2 for update")
            results["a"] = "ok"
        except Exception as e:
            results["a"] = type(e).__name__

    def b_then():
        time.sleep(0.2)  # ensure A is already waiting
        try:
            b.execute("select * from acc where id = 1 for update")
            results["b"] = "ok"
        except Exception as e:
            results["b"] = type(e).__name__

    ta = threading.Thread(target=a_then)
    tb = threading.Thread(target=b_then)
    ta.start()
    tb.start()
    tb.join(10)
    assert results.get("b") == "DeadlockError", results
    b.execute("rollback")  # victim restarts; A's wait resolves
    ta.join(10)
    assert results.get("a") == "ok", results
    a.execute("update acc set bal = bal - 10 where id = 2")
    a.execute("commit")
    chk = d.new_session()
    assert chk.query("select bal from acc where id = 2") == [(190,)]


def test_write_waits_for_for_update_lock(d):
    """An autocommit UPDATE's 2PC prewrite waits out a FOR UPDATE lock
    rather than erroring (prewrite backoff)."""
    a = d.new_session()
    a.execute("begin")
    a.execute("select * from acc where id = 3 for update")
    w = d.new_session()
    done = []

    def upd():
        w.execute("update acc set bal = 0 where id = 3")
        done.append(time.monotonic())

    th = threading.Thread(target=upd)
    th.start()
    time.sleep(0.25)
    assert not done
    rel = time.monotonic()
    a.execute("commit")
    th.join(5)
    assert done and done[0] >= rel
    assert w.query("select bal from acc where id = 3") == [(0,)]


def test_lock_wait_timeout(d):
    """The per-session innodb_lock_wait_timeout bounds the row-lock wait
    (plumbed into the transaction at _begin_txn; ADVICE r4 #5)."""
    a, b = d.new_session(), d.new_session()
    a.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    b.execute("set innodb_lock_wait_timeout = 1")  # MySQL minimum
    b.execute("begin")
    try:
        t0 = time.monotonic()
        with pytest.raises(LockWaitTimeoutError):
            b.execute("select * from acc where id = 1 for update")
        elapsed = time.monotonic() - t0
        assert 0.9 <= elapsed < 5  # honored 1s, not the 50s default
    finally:
        a.execute("rollback")
        b.execute("rollback")


def test_live_holder_keeps_lock_past_ttl(d):
    """A LIVE txn never loses its locks to a waiter — TTL resolution only
    covers txns this process no longer tracks (crash recovery)."""
    a = d.new_session()
    a.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    time.sleep(3.2)  # beyond the 3s lock TTL
    b = d.new_session()
    done = []

    def upd():
        b.execute("update acc set bal = 777 where id = 1")
        done.append(1)

    th = threading.Thread(target=upd)
    th.start()
    time.sleep(0.3)
    assert not done  # still excluded despite TTL expiry
    a.execute("update acc set bal = 111 where id = 1")
    a.execute("commit")
    th.join(10)
    chk = d.new_session()
    assert chk.query("select bal from acc where id = 1") == [(777,)]


def test_for_update_is_current_read_no_lost_update(d):
    """FOR UPDATE locks and reads the LATEST committed version
    (for_update_ts), so increments never overwrite concurrent commits."""
    p = d.new_session()
    p.execute("begin")
    p.execute("select 1")  # pin start_ts
    q = d.new_session()
    q.execute("update acc set bal = 555 where id = 2")
    assert p.query("select bal from acc where id = 2 for update") == [(555,)]
    p.execute("update acc set bal = bal + 1 where id = 2")
    p.execute("commit")
    chk = d.new_session()
    assert chk.query("select bal from acc where id = 2") == [(556,)]
    # plain SELECT in a txn still reads its snapshot
    r = d.new_session()
    r.execute("begin")
    r.execute("select 1")
    q.execute("update acc set bal = 999 where id = 1")
    assert r.query("select bal from acc where id = 1") == [(100,)]
    r.execute("rollback")


def test_for_update_locks_buffered_rows(d):
    """Rows the txn itself modified still take the KV lock so a second
    session's FOR UPDATE blocks instead of double-granting."""
    m = d.new_session()
    m.execute("begin")
    m.execute("update acc set bal = 1 where id = 1")
    m.execute("select * from acc where id = 1 for update")
    n = d.new_session()
    n.execute("begin")
    got = []

    def n_lock():
        n.execute("select * from acc where id = 1 for update")
        got.append(time.monotonic())

    th = threading.Thread(target=n_lock)
    th.start()
    time.sleep(0.3)
    assert not got  # blocked on m's lock
    rel = time.monotonic()
    m.execute("rollback")
    th.join(10)
    assert got and got[0] >= rel
    n.execute("rollback")


def test_for_update_alias_and_subquery_fallback(d):
    a = d.new_session()
    a.execute("begin")
    assert a.query("select * from acc x where x.id = 1 for update") == [
        (1, 100)]
    t = d.catalog.info_schema().table("test", "acc")
    assert len(d.storage.table(t.id).locks) == 1
    rs = a.execute("select * from acc where id in (select id from acc)"
                   " for update")[-1]
    assert any("snapshot" in w for w in rs.warnings)
    a.execute("rollback")


def test_replace_and_multi_table_warning(d):
    s = d.new_session()
    s.execute("replace into acc values (1, 999)")
    assert s.query("select bal from acc where id = 1") == [(999,)]
    s.execute("create table other (x bigint)")
    s.execute("begin")
    rs = s.execute("select * from acc, other for update")[-1]
    assert any("snapshot" in w for w in rs.warnings)
    s.execute("rollback")


def test_deadlock_victim_rolls_back_so_survivor_proceeds(d):
    """The deadlock victim's transaction rolls back automatically: the
    surviving waiter acquires the lock immediately, not after a lock-wait
    timeout (MySQL victim semantics)."""
    a, b = d.new_session(), d.new_session()
    a.execute("begin")
    b.execute("begin")
    a.execute("select * from acc where id = 1 for update")
    b.execute("select * from acc where id = 2 for update")
    res = {}
    t0 = time.monotonic()

    def a_then():
        a.execute("select * from acc where id = 2 for update")
        res["a_time"] = time.monotonic() - t0

    def b_then():
        time.sleep(0.2)
        try:
            b.execute("select * from acc where id = 1 for update")
        except DeadlockError:
            res["b"] = "victim"

    ta = threading.Thread(target=a_then)
    tb = threading.Thread(target=b_then)
    ta.start()
    tb.start()
    tb.join(10)
    ta.join(10)
    assert res.get("b") == "victim"
    assert res["a_time"] < 2.0  # did not ride out the 5s timeout
    a.execute("rollback")
    b.execute("rollback")


def test_atomic_lock_upgrade_under_contention(d):
    """Commit upgrades a pessimistic lock in place: a polling waiter can
    never steal the row between lock release and prewrite."""
    s0 = d.new_session()
    for _ in range(15):
        x, y = d.new_session(), d.new_session()
        x.execute("begin")
        x.execute("select * from acc where id = 3 for update")
        x.execute("update acc set bal = bal + 1 where id = 3")
        done = []

        def contend():
            y.execute("update acc set bal = bal + 1 where id = 3")
            done.append(1)

        th = threading.Thread(target=contend)
        th.start()
        x.execute("commit")
        th.join(5)
        assert done
    assert s0.query("select bal from acc where id = 3") == [(330,)]
