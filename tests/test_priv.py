"""Privilege enforcement: grant tables, plan-time checks, wire auth.

Reference: privilege/privileges/cache.go:1037 (RequestVerification over
user/db/table priv rows), planner/optimize.go:128-131 (CheckPrivilege
before planning), server/conn.go (mysql_native_password handshake)."""

import asyncio
import hashlib
import struct

import pytest

from tidb_tpu.errors import KVError, PrivilegeError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    return Domain()


@pytest.fixture()
def root(d):
    s = d.new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (1)")
    return s


def _as(d, user):
    s = d.new_session()
    s.user = user if "@" in user else f"{user}@%"
    return s


def test_unprivileged_user_denied_everything(d, root):
    root.execute("create user alice")
    alice = _as(d, "alice")
    for q in ("select * from t", "insert into t values (2)",
              "update t set a = 2", "delete from t",
              "create table x (a bigint)", "drop table t",
              "alter table t add column b bigint",
              "create index i on t (a)",
              "grant select on *.* to bob", "create user bob",
              "kill 1"):
        with pytest.raises(PrivilegeError):
            alice.execute(q)


def test_grant_revoke_roundtrip(d, root):
    root.execute("create user alice")
    alice = _as(d, "alice")
    # table-level SELECT
    root.execute("grant select on test.t to alice")
    assert alice.query("select * from t") == [(1,)]
    with pytest.raises(PrivilegeError):
        alice.execute("update t set a = 9")
    # db-level UPDATE
    root.execute("grant update on test.* to alice")
    alice.execute("update t set a = 9")
    assert root.query("select * from t") == [(9,)]
    # revoke closes the door again
    root.execute("revoke select on test.t from alice")
    with pytest.raises(PrivilegeError):
        alice.execute("select * from t")
    # global grant covers everything
    root.execute("grant all on *.* to alice")
    alice.execute("select * from t")
    alice.execute("create table fresh (x bigint)")


def test_subquery_tables_checked(d, root):
    root.execute("create table t2 (b bigint)")
    root.execute("create user carol")
    root.execute("grant select on test.t to carol")
    carol = _as(d, "carol")
    with pytest.raises(PrivilegeError):
        carol.execute("select * from t where a in (select b from t2)")
    root.execute("grant select on test.t2 to carol")
    carol.execute("select * from t where a in (select b from t2)")


def test_insert_select_needs_both(d, root):
    root.execute("create table src (a bigint)")
    root.execute("create user dave")
    root.execute("grant insert on test.t to dave")
    dave = _as(d, "dave")
    with pytest.raises(PrivilegeError):
        dave.execute("insert into t select a from src")
    root.execute("grant select on test.src to dave")
    dave.execute("insert into t select a from src")


def test_show_grants(d, root):
    root.execute("create user eve identified by 'pw'")
    root.execute("grant select, insert on test.t to eve")
    root.execute("grant create on db2.* to eve")
    grants = [r[0] for r in root.query("show grants for eve")]
    assert any("USAGE ON *.*" in g for g in grants)
    assert any("`test`.`t`" in g and "SELECT" in g and "INSERT" in g
               for g in grants)
    assert any("`db2`.*" in g and "CREATE" in g for g in grants)
    # a user's own grants
    eve = _as(d, "eve")
    assert [r[0] for r in eve.query("show grants")] == grants


def test_native_password_auth(d, root):
    root.execute("create user frank identified by 's3cret'")
    pm = d.priv
    salt = bytes(range(20))

    def token(pw):
        s1 = hashlib.sha1(pw.encode()).digest()
        s2 = hashlib.sha1(s1).digest()
        mix = hashlib.sha1(salt + s2).digest()
        return bytes(a ^ b for a, b in zip(s1, mix))

    assert pm.auth("frank", token("s3cret"), salt)
    assert not pm.auth("frank", token("nope"), salt)
    assert not pm.auth("frank", b"", salt)
    assert not pm.auth("ghost", token("s3cret"), salt)
    root.execute("set password for frank = 'other'")
    assert pm.auth("frank", token("other"), salt)


def test_drop_user_and_persistence(tmp_path, ):
    dd = str(tmp_path / "data")
    d1 = Domain(data_dir=dd)
    r1 = d1.new_session()
    r1.execute("create user gary identified by 'x'")
    r1.execute("grant select on test.* to gary")
    d2 = Domain(data_dir=dd)
    assert d2.priv.check("gary", "select", "test")
    r2 = d2.new_session()
    r2.execute("drop user gary")
    with pytest.raises(KVError):
        r2.execute("drop user gary")
    d3 = Domain(data_dir=dd)
    assert not d3.priv.check("gary", "select", "test")


def test_grant_requires_existing_user(d, root):
    with pytest.raises(KVError):
        root.execute("grant select on *.* to typo_user")


def test_revoke_semantics(d, root):
    root.execute("create user rv")
    root.execute("grant all on *.* to rv")
    root.execute("revoke select on *.* from rv")
    assert not d.priv.check("rv", "select")
    assert d.priv.check("rv", "insert")  # ALL expanded, not dropped
    root.execute("revoke all on *.* from rv")
    assert not d.priv.check("rv", "insert")


def test_create_view_priv_and_grant_option(d, root):
    root.execute("create user vu")
    root.execute("grant select on test.* to vu")
    vu = _as(d, "vu")
    with pytest.raises(PrivilegeError):
        vu.execute("create view v1 as select a from t")
    root.execute("grant create view on test.* to vu")
    vu.execute("create view v1 as select a from t")
    # GRANT OPTION lets a non-admin grant — but only privileges they
    # themselves hold at that scope (MySQL executor/grant.go semantics)
    root.execute("create user go_user")
    root.execute("create user target_user")
    root.execute("grant grant option on *.* to go_user")
    gs = _as(d, "go_user")
    with pytest.raises(PrivilegeError):
        gs.execute("grant select on test.t to target_user")  # lacks SELECT
    root.execute("grant select on *.* to go_user")
    gs.execute("grant select on test.t to target_user")
    assert d.priv.check("target_user", "select", "test", "t")


def test_show_grants_for_other_user_admin_only(d, root):
    root.execute("create user peek")
    peek = _as(d, "peek")
    with pytest.raises(PrivilegeError):
        peek.execute("show grants for root")
    peek.execute("show grants")  # own grants always visible


# ---------------------------------------------------------------------------
# wire-level: handshake auth + denied SELECT over the wire
# ---------------------------------------------------------------------------


def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


async def _wire_connect(host, port, user, password):
    """Minimal 4.1 client returning (reader-pkt, writer) after auth; the
    auth result packet is returned raw."""
    from tidb_tpu.server import protocol as P
    from tidb_tpu.server.packet import PacketReader, PacketWriter

    reader, writer = await asyncio.open_connection(host, port)
    pr, pw = PacketReader(reader), PacketWriter(writer)
    greeting = await pr.recv()
    # salt: 8 bytes after conn_id, 12 more before the plugin name
    p = greeting.index(b"\x00", 1) + 1  # skip version string
    p += 4  # conn id
    salt = greeting[p:p + 8]
    rest = greeting[p + 9 + 2 + 1 + 2 + 2 + 1 + 10:]
    salt += rest[:12]
    caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
    if password:
        s1 = hashlib.sha1(password.encode()).digest()
        s2 = hashlib.sha1(s1).digest()
        mix = hashlib.sha1(salt + s2).digest()
        auth = bytes(a ^ b for a, b in zip(s1, mix))
    else:
        auth = b""
    resp = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
    resp += bytes([33]) + b"\x00" * 23
    resp += user.encode() + b"\x00" + bytes([len(auth)]) + auth
    pw.seq = pr.seq
    await pw.send(resp)
    result = await pr.recv()
    return pr, pw, result, writer


def test_wire_auth_and_denied_select():
    from tidb_tpu.server import MySQLServer

    async def body():
        srv = MySQLServer(port=0)
        await srv.start()
        root = srv.domain.new_session()
        root.execute("create table wt (a bigint)")
        root.execute("insert into wt values (7)")
        root.execute("create user hank identified by 'pw'")
        root.execute("grant select on test.wt to hank")
        host, port = srv.host, srv.port

        # wrong password -> error packet 1045
        _, _, res, w = await _wire_connect(host, port, "hank", "bad")
        assert res[0] == 0xFF
        assert struct.unpack_from("<H", res, 1)[0] == 1045
        w.close()

        # right password -> OK; SELECT allowed on wt, denied elsewhere
        pr, pw, res, w = await _wire_connect(host, port, "hank", "pw")
        assert res[0] == 0x00, res

        async def q(sql):
            pw.reset_seq()
            await pw.send(bytes([0x03]) + sql.encode())
            return await pr.recv()

        first = await q("select a from test.wt")
        assert first[0] not in (0x00, 0xFF)  # column-count: result set
        # drain both EOFs (column phase, then row phase)
        eofs = 0
        while eofs < 2:
            pkt = await pr.recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                eofs += 1
        root.execute("create table secret (x bigint)")
        err = await q("select * from test.secret")
        assert err[0] == 0xFF
        assert struct.unpack_from("<H", err, 1)[0] == 1142
        w.close()
        await srv.stop()

    _run(body())


def test_user_host_pattern_matching(d, root):
    """user@host accounts resolve by MySQL specificity: exact host beats
    pattern beats % (privilege/privileges/cache.go role)."""
    pm = d.priv
    root.execute("create user 'app'@'10.0.0.5' identified by 'exact'")
    root.execute("create user 'app'@'10.0.%' identified by 'subnet'")
    root.execute("create user 'app'@'%' identified by 'anywhere'")
    assert pm.match_account("app", "10.0.0.5") == "app@10.0.0.5"
    assert pm.match_account("app", "10.0.3.7") == "app@10.0.%"
    assert pm.match_account("app", "192.168.1.1") == "app@%"
    assert pm.match_account("app", "127.0.0.1") == "app@%"
    assert pm.match_account("nobody", "10.0.0.5") is None
    # localhost account matches loopback clients
    root.execute("create user 'op'@'localhost'")
    assert pm.match_account("op", "127.0.0.1") == "op@localhost"
    # per-host grants are distinct identities
    root.execute("grant select on test.* to 'app'@'10.0.%'")
    assert pm.check("app@10.0.%", "select", "test", "t")
    assert not pm.check("app@%", "select", "test", "t")


def test_auth_resolves_most_specific_account(d, root):
    import hashlib

    pm = d.priv
    root.execute("create user 'svc'@'10.1.%' identified by 'subnetpw'")
    root.execute("create user 'svc'@'%' identified by 'globalpw'")
    salt = b"12345678901234567890"

    def token(pw):
        stage1 = hashlib.sha1(pw.encode()).digest()
        stage2 = hashlib.sha1(stage1).digest()
        mix = hashlib.sha1(salt + stage2).digest()
        return bytes(a ^ b for a, b in zip(stage1, mix))

    # the subnet client must authenticate with the SUBNET account's pw
    assert pm.auth("svc", token("subnetpw"), salt, host="10.1.2.3") == \
        "svc@10.1.%"
    assert pm.auth("svc", token("globalpw"), salt, host="10.1.2.3") is None
    assert pm.auth("svc", token("globalpw"), salt, host="8.8.8.8") == "svc@%"


# ---------------------------------------------------------------------------
# MySQL roles (executor/simple.go SET ROLE family, privilege merge with
# activeRoles in privileges/cache.go)
# ---------------------------------------------------------------------------


def test_roles_grant_activate_and_merge(d, root):
    root.execute("create role 'r_read', 'r_write'")
    root.execute("grant select on test.* to r_read")
    root.execute("grant insert, update on test.* to r_write")
    root.execute("create user rolf identified by 'x'")
    root.execute("grant r_read, r_write to rolf")
    rolf = _as(d, "rolf")
    # granted but NOT active: no access yet
    with pytest.raises(PrivilegeError):
        rolf.query("select * from t")
    rolf.execute("set role 'r_read'")
    assert rolf.query("select * from t") == [(1,)]
    with pytest.raises(PrivilegeError):
        rolf.execute("insert into t values (5)")
    rolf.execute("set role all")
    rolf.execute("insert into t values (5)")
    rolf.execute("set role none")
    with pytest.raises(PrivilegeError):
        rolf.query("select * from t")
    # activating a role you don't have fails
    with pytest.raises(KVError):
        rolf.execute("set role 'r_admin'")


def test_default_roles_and_drop_role(d, root):
    root.execute("create role r1")
    root.execute("grant select on test.* to r1")
    root.execute("create user du")
    root.execute("grant r1 to du")
    root.execute("set default role all to du")
    assert d.priv.default_roles("du") == {"r1@%"}
    du = _as(d, "du")
    du.execute("set role default")
    assert du.query("select * from t")
    # dropping the role revokes it everywhere
    root.execute("drop role r1")
    assert d.priv.granted_roles("du") == set()
    du2 = _as(d, "du")
    du2.active_roles = ["r1@%"]  # stale activation no longer grants
    with pytest.raises(PrivilegeError):
        du2.query("select * from t")


def test_role_management_requires_admin(d, root):
    root.execute("create user pleb")
    pleb = _as(d, "pleb")
    for q in ("create role nope", "drop role nope",
              "grant nope to pleb", "set default role none to root"):
        with pytest.raises(PrivilegeError):
            pleb.execute(q)
    # SET DEFAULT ROLE for yourself is allowed (with granted roles)
    root.execute("create role rx")
    root.execute("grant rx to pleb")
    pleb.execute("set default role all to pleb")
    assert d.priv.default_roles("pleb") == {"rx@%"}


def test_roles_cannot_login_and_mixed_case(d, root):
    pm = d.priv
    root.execute("create role 'Admin'")
    root.execute("grant super on *.* to 'Admin'")
    # a role never authenticates, even with an empty token
    assert pm.auth("Admin", b"", bytes(20)) is None
    assert pm.match_account("Admin", "127.0.0.1") is None
    # case-preserving grant of a quoted/mixed-case role
    root.execute("create user mc")
    root.execute("grant 'Admin' to mc")
    assert pm.granted_roles("mc") == {"Admin@%"}
    mc = _as(d, "mc")
    mc.execute("set role 'Admin'")
    mc.execute("kill 99")  # SUPER via the active role
    root.execute("revoke 'Admin' from mc")
    assert pm.granted_roles("mc") == set()


def test_drop_user_cleans_role_references(d, root):
    root.execute("create role rr")
    root.execute("create user uu")
    root.execute("grant rr to uu")
    root.execute("drop user rr")  # dropped via DROP USER, not DROP ROLE
    assert d.priv.granted_roles("uu") == set()
