"""Interruptible chunked dispatch + per-statement resource groups
(ISSUE 17).

Tentpole coverage:

- chunked-vs-unchunked parity across the fusion corpus (rows, agg,
  TopN) — chunking changes only range-slot operand VALUES on the same
  compiled program, never results;
- the chunk count must NOT enter any program fingerprint: no new
  compiled entries appear when the chunk budget changes;
- KILL of an in-flight oversized scan lands at the between-chunk seam:
  the statement returns within two chunk dispatches of the kill instead
  of running the remaining sequence;
- resource groups: token-bucket quotas charge per chunk, depleted
  non-burstable groups raise the typed retriable ResourceGroupThrottled,
  two groups with 1:3 quotas observe device-time share near the ratio,
  and QUERY_LIMIT cancels the runaway statement through its scope with
  reason ``resource_group``;
- the DDL surface (CREATE/ALTER/DROP RESOURCE GROUP, ALTER USER ...
  RESOURCE GROUP, the tidb_tpu_resource_group sysvar) and the
  INFORMATION_SCHEMA.TIDB_TPU_RESOURCE_GROUPS memtable.
"""

import os
import threading
import time

import numpy as np
import pytest

from tidb_tpu.errors import (
    QueryKilledError,
    ResourceGroupThrottled,
    TiDBTPUError,
)
from tidb_tpu.lifecycle import QueryScope, classify_termination
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import FAILPOINTS, failpoint

Q_AGG = ("select g, sum(x), count(*), min(x), max(x) from t "
         "group by g order by g")
Q_SUM = "select sum(x) from t where k < 15000 and x < 50"
Q_TOPN = "select k, x from t order by x desc limit 7"
Q_FILTER = "select k from t where x < 2.5"

CORPUS = (Q_AGG, Q_SUM, Q_TOPN, Q_FILTER)


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    s.execute("create table t (k bigint, g bigint, x double)")
    t = d.catalog.info_schema().table("test", "t")
    store = d.storage.table(t.id)
    rng = np.random.default_rng(17)
    n = 20_000
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 5, n, dtype=np.int64),
         rng.uniform(0, 100, n)],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, 4, store.base_rows)
    s.execute("set tidb_use_tpu = 1")
    return s


@pytest.fixture()
def chunked():
    """Force multi-chunk dispatch regardless of the latency estimate."""
    os.environ["TIDB_TPU_DISPATCH_CHUNK_ROWS"] = "2048"
    yield
    os.environ.pop("TIDB_TPU_DISPATCH_CHUNK_ROWS", None)


def _approx_eq(a, b):
    if isinstance(a, float) or isinstance(b, float):
        return a == pytest.approx(b, rel=1e-9, abs=1e-9)
    return a == b


def _rows_eq(got, want, ctx=""):
    assert len(got) == len(want), (ctx, got, want)
    for ra, rb in zip(sorted(got), sorted(want)):
        assert all(_approx_eq(x, y) for x, y in zip(ra, rb)), (ctx, ra, rb)


# ---------------------------------------------------------------------------
# chunk_bounds unit behavior
# ---------------------------------------------------------------------------

def test_chunk_bounds_split_and_disabled():
    from tidb_tpu.copr.chunking import chunk_bounds

    # budget 0 => ONE chunk, bounds verbatim (the disabled path)
    assert chunk_bounds([(0, 10), (20, 25)], 0) == [[(0, 10), (20, 25)]]
    assert chunk_bounds([], 100) == []
    # rows split across chunks, ranges stay disjoint + ascending
    assert chunk_bounds([(0, 10)], 4) == [[(0, 4)], [(4, 8)], [(8, 10)]]
    # max_slots caps ranges per chunk even under budget
    out = chunk_bounds([(0, 1), (2, 3), (4, 5), (6, 7), (8, 9)], 100,
                       max_slots=2)
    assert all(len(c) <= 2 for c in out)
    flat = [r for c in out for r in c]
    assert flat == [(0, 1), (2, 3), (4, 5), (6, 7), (8, 9)]
    # coverage is exact: no row lost or duplicated, order preserved
    out = chunk_bounds([(3, 1000), (1500, 1501), (2000, 2500)], 137)
    flat = [r for c in out for r in c]
    assert sum(hi - lo for lo, hi in flat) == (1000 - 3) + 1 + 500
    for (_, a1), (b0, _) in zip(flat, flat[1:]):
        assert a1 <= b0


# ---------------------------------------------------------------------------
# parity: chunked == unchunked across the corpus
# ---------------------------------------------------------------------------

def test_chunked_parity_corpus(sess, chunked):
    before = REGISTRY.snapshot().get("dispatch_chunks_total", 0)
    got = {q: sess.query(q) for q in CORPUS}
    after = REGISTRY.snapshot().get("dispatch_chunks_total", 0)
    assert after > before + len(CORPUS), \
        "queries did not take the chunked path"
    os.environ.pop("TIDB_TPU_DISPATCH_CHUNK_ROWS", None)
    os.environ["TIDB_TPU_DISPATCH_CHUNK"] = "0"
    try:
        for q, rows in got.items():
            _rows_eq(rows, sess.query(q), ctx=q)
    finally:
        os.environ.pop("TIDB_TPU_DISPATCH_CHUNK", None)


def test_chunked_filter_limit_parity(sess, chunked):
    # LIMIT decrements across chunks: first-N selection must match the
    # single-dispatch selection (ranges ascend, so order is global)
    q = "select k from t where x < 50 limit 100"
    got = sess.query(q)
    os.environ["TIDB_TPU_DISPATCH_CHUNK_ROWS"] = "0"
    assert got == sess.query(q)


# ---------------------------------------------------------------------------
# fingerprint invariance: chunking must never recompile
# ---------------------------------------------------------------------------

def test_chunk_budget_not_in_fingerprint(sess):
    from tidb_tpu.copr import parallel as pl

    for q in CORPUS:
        keys = []
        try:
            for budget in ("2048", "4096", "0"):
                os.environ["TIDB_TPU_DISPATCH_CHUNK_ROWS"] = budget
                sess.query(q)
                keys.append(set(pl._COMPILED._d.keys()))
        finally:
            os.environ.pop("TIDB_TPU_DISPATCH_CHUNK_ROWS", None)
        assert keys[0] == keys[1] == keys[2], \
            f"chunk budget leaked into a program fingerprint: {q}"


# ---------------------------------------------------------------------------
# KILL lands at the between-chunk seam
# ---------------------------------------------------------------------------

def test_kill_bounded_by_chunk_seam(sess, chunked):
    """Kill fired from inside chunk 1's failpoint: the statement must
    unwind at the NEXT seam — at most one more chunk dispatches after
    the kill (the acceptance bound: within 2 chunk budgets)."""
    d = sess.domain
    victim = d.new_session()
    victim.execute("set tidb_use_tpu = 1")
    hits = []

    def action(**ctx):
        if ctx.get("kind") != "agg":
            return
        hits.append(ctx["chunk"])
        if ctx["chunk"] == 1:
            d.kill(victim.conn_id, True)

    with failpoint("copr/chunk_dispatch", action):
        with pytest.raises(QueryKilledError):
            victim.query(Q_AGG)
    assert hits, "chunk failpoint never fired"
    total_chunks = 20_000 // 2048 + 1
    assert max(hits) <= 2, \
        f"kill latency exceeded the chunk bound: chunks ran {hits}"
    assert max(hits) < total_chunks - 1, "kill did not interrupt the scan"
    # the session is healthy afterwards and re-running has full parity
    _rows_eq(victim.query(Q_AGG), sess.query(Q_AGG))


def test_kill_mid_chunk_streaming_filter(sess, chunked):
    """Same bound on the rows-streaming filter path: kill mid-sequence
    produces the scope-bounded typed error, and a re-run full parity."""
    d = sess.domain
    victim = d.new_session()
    victim.execute("set tidb_use_tpu = 1")
    hits = []

    def action(**ctx):
        if ctx.get("kind") != "filter":
            return
        hits.append(ctx["chunk"])
        if ctx["chunk"] == 1:
            d.kill(victim.conn_id, True)

    with failpoint("copr/chunk_dispatch", action):
        with pytest.raises(QueryKilledError):
            victim.query(Q_FILTER)
    assert hits and max(hits) <= 2, hits
    _rows_eq(victim.query(Q_FILTER), sess.query(Q_FILTER), ctx=Q_FILTER)


def test_no_failpoint_leaks_after_kills(sess):
    # the conftest autouse fixtures assert no armed failpoints and no
    # witness violations leak; this is the explicit no-leak checkpoint
    assert not FAILPOINTS._points


# ---------------------------------------------------------------------------
# resource groups: bucket mechanics
# ---------------------------------------------------------------------------

def test_resgroup_registry_basics():
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    g = reg.create("gold", ru_per_sec=100, burstable=True,
                   query_limit_ms=500)
    assert reg.get("gold") is g
    with pytest.raises(ValueError):
        reg.create("gold")
    assert reg.create("gold", if_not_exists=True) is g
    reg.alter("gold", ru_per_sec=200)
    assert g.ru_per_sec == 200
    with pytest.raises(KeyError):
        reg.alter("nope")
    reg.bind_user("alice", "gold")
    assert reg.resolve("alice@%").name == "gold"
    # sysvar wins over binding; unknown names fall back to default
    assert reg.resolve("alice", "default").name == "default"
    assert reg.resolve("bob", "ghost").name == "default"
    with pytest.raises(ValueError):
        reg.drop("default")
    reg.drop("gold")
    assert reg.resolve("alice").name == "default"
    reg.drop("gold", if_exists=True)
    with pytest.raises(KeyError):
        reg.drop("gold")


def test_resgroup_charge_and_refill():
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    g = reg.create("bronze", ru_per_sec=1000)
    sc = QueryScope()
    sc.resgroup = g
    g.charge(400.0, sc)
    assert sc.device_ms == pytest.approx(400.0)
    snap = g.snapshot()
    assert snap["consumed_ru"] == pytest.approx(400.0)
    assert snap["tokens"] < 1000.0
    assert REGISTRY.snapshot().get(
        "resgroup_bronze_ru_consumed_total", 0) >= 400.0


def test_dispatch_admission_bills_device_time_not_lock_wait():
    """RU accounting (ISSUE 20 satellite): the charge clock starts
    INSIDE the DISPATCH_LOCK — a tenant stuck behind another tenant's
    chunk in the lock queue is not billed for the queue time."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry
    from tidb_tpu.lifecycle.resgroup import dispatch_admission
    from tidb_tpu.lifecycle.scope import attach_scope

    reg = ResourceGroupRegistry()
    g = reg.create("metered", ru_per_sec=0)  # unlimited: admit is free
    sc = QueryScope()
    sc.resgroup = g
    lock = threading.Lock()
    entered = threading.Event()
    release = threading.Event()

    def hog():
        with lock:
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=hog)
    t.start()
    assert entered.wait(5.0)
    timer = threading.Timer(0.25, release.set)
    timer.start()
    try:
        with attach_scope(sc):
            with dispatch_admission(lock):
                time.sleep(0.02)  # the "device" body
    finally:
        release.set()
        t.join()
        timer.cancel()
    consumed = g.snapshot()["consumed_ru"]
    # billed the ~20ms body, never the ~250ms queue wait
    assert 5.0 <= consumed < 150.0, consumed
    assert sc.device_ms == pytest.approx(consumed, abs=0.01)


def test_dispatch_admission_charges_on_exception_without_lock_wait():
    """An exception inside the locked body still charges only the time
    spent holding the lock — never a bogus absolute timestamp."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry
    from tidb_tpu.lifecycle.resgroup import dispatch_admission
    from tidb_tpu.lifecycle.scope import attach_scope

    reg = ResourceGroupRegistry()
    g = reg.create("metered_exc", ru_per_sec=0)
    sc = QueryScope()
    sc.resgroup = g
    lock = threading.Lock()
    with pytest.raises(RuntimeError):
        with attach_scope(sc):
            with dispatch_admission(lock):
                time.sleep(0.01)
                raise RuntimeError("device fault")
    consumed = g.snapshot()["consumed_ru"]
    assert 1.0 <= consumed < 150.0, consumed


def test_resgroup_throttled_typed_error(monkeypatch):
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    monkeypatch.setenv("TIDB_TPU_RESGROUP_MAX_WAIT_MS", "40")
    reg = ResourceGroupRegistry()
    g = reg.create("tiny", ru_per_sec=1)
    sc = QueryScope()
    sc.resgroup = g
    g.charge(50.0, sc)  # drive the bucket deep into debt
    t0 = time.monotonic()
    with pytest.raises(ResourceGroupThrottled) as ei:
        g.admit(sc)
    assert ei.value.group == "tiny"
    assert ei.value.wait_ms >= 40.0
    assert time.monotonic() - t0 < 5.0
    assert REGISTRY.snapshot().get("resgroup_tiny_throttled_total", 0) >= 1


def test_resgroup_admit_interrupted_by_kill(monkeypatch):
    """A statement parked at admission still honors KILL: the poll loop
    checks the scope, so cancellation preempts the throttle wait."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    monkeypatch.setenv("TIDB_TPU_RESGROUP_MAX_WAIT_MS", "60000")
    reg = ResourceGroupRegistry()
    g = reg.create("parked", ru_per_sec=1)
    sc = QueryScope()
    sc.resgroup = g
    g.charge(10_000.0, sc)
    t = threading.Timer(0.05, sc.cancel, args=("killed",))
    t.start()
    t0 = time.monotonic()
    with pytest.raises(QueryKilledError):
        g.admit(sc)
    assert time.monotonic() - t0 < 5.0
    t.join()


def test_burstable_runs_on_debt():
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    g = reg.create("bursty", ru_per_sec=1, burstable=True)
    sc = QueryScope()
    sc.resgroup = g
    g.charge(500.0, sc)
    # depleted but burstable with nobody else waiting: admits on debt
    assert g.admit(sc) == 0.0


def test_query_limit_cancels_via_scope():
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    g = reg.create("capped", ru_per_sec=0, query_limit_ms=100)
    sc = QueryScope()
    sc.resgroup = g
    g.charge(60.0, sc)
    assert not sc.cancelled()
    g.charge(60.0, sc)  # total 120ms > QUERY_LIMIT 100ms
    assert sc.cancelled()
    assert sc.reason == "resource_group"
    with pytest.raises(QueryKilledError):
        sc.check()
    assert classify_termination(QueryKilledError(), sc) == "resource_group"


# ---------------------------------------------------------------------------
# weighted fairness: 1:3 quotas -> ~1:3 device share
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_group_fairness_ratio(sess, chunked):
    d = sess.domain
    adm = d.new_session()
    adm.execute("create resource group fair_a ru_per_sec = 40")
    adm.execute("create resource group fair_b ru_per_sec = 120")
    base = REGISTRY.snapshot()
    stop = threading.Event()
    errs = []

    def worker(group):
        s2 = d.new_session()
        s2.execute(f"set tidb_tpu_resource_group = '{group}'")
        s2.execute("set tidb_use_tpu = 1")
        while not stop.is_set():
            try:
                s2.query(Q_AGG)
            except ResourceGroupThrottled:
                pass
            except BaseException as e:  # noqa: BLE001
                errs.append(e)
                return

    threads = [threading.Thread(target=worker, args=(g,))
               for g in ("fair_a", "fair_b")]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    adm.execute("drop resource group fair_a")
    adm.execute("drop resource group fair_b")
    assert not errs, errs
    snap = REGISTRY.snapshot()
    ru_a = (snap.get("resgroup_fair_a_ru_consumed_total", 0)
            - base.get("resgroup_fair_a_ru_consumed_total", 0))
    ru_b = (snap.get("resgroup_fair_b_ru_consumed_total", 0)
            - base.get("resgroup_fair_b_ru_consumed_total", 0))
    assert ru_a > 0 and ru_b > 0, (ru_a, ru_b)
    ratio = ru_b / ru_a
    # acceptance: device-time share within 25% of the 3.0 quota ratio
    assert 3.0 * 0.75 <= ratio <= 3.0 * 1.25, \
        f"consumed RU ratio {ratio:.2f} strays from the 1:3 quotas"


def test_depleted_group_throttles_while_other_proceeds(sess, chunked,
                                                       monkeypatch):
    monkeypatch.setenv("TIDB_TPU_RESGROUP_MAX_WAIT_MS", "30")
    d = sess.domain
    adm = d.new_session()
    adm.execute("create resource group starved ru_per_sec = 1")
    try:
        s_starved = d.new_session()
        s_starved.execute("set tidb_tpu_resource_group = 'starved'")
        s_starved.execute("set tidb_use_tpu = 1")
        # burn the 1-RU budget, then a later chunk must throttle
        with pytest.raises(ResourceGroupThrottled):
            for _ in range(50):
                s_starved.query(Q_AGG)
        # an unbound session (default group, unlimited) is unaffected
        t0 = time.perf_counter()
        sess.query(Q_AGG)
        assert time.perf_counter() - t0 < 30.0
    finally:
        adm.execute("drop resource group starved")


# ---------------------------------------------------------------------------
# SQL surface + observability
# ---------------------------------------------------------------------------

def test_resource_group_ddl_surface(sess):
    d = sess.domain
    s = d.new_session()
    s.execute("create resource group rg_ddl ru_per_sec = 500 burstable")
    s.execute("alter resource group rg_ddl ru_per_sec = 700, "
              "query_limit = (exec_elapsed = 9000)")
    s.execute("create user 'carol' identified by 'pw'")
    s.execute("alter user 'carol' resource group rg_ddl")
    rows = s.query("select name, ru_per_sec, burstable, query_limit_ms, "
                   "users from information_schema."
                   "tidb_tpu_resource_groups where name = 'rg_ddl'")
    assert rows == [("rg_ddl", 700, 1, 9000, "carol")]
    # duplicate create is a typed error; IF NOT EXISTS is not
    with pytest.raises(TiDBTPUError):
        s.execute("create resource group rg_ddl")
    s.execute("create resource group if not exists rg_ddl")
    s.execute("drop resource group rg_ddl")
    with pytest.raises(TiDBTPUError):
        s.execute("drop resource group rg_ddl")
    s.execute("drop resource group if exists rg_ddl")
    assert s.query("select name from information_schema."
                   "tidb_tpu_resource_groups") == [("default",)]


def test_scope_carries_group_and_charges(sess, chunked):
    d = sess.domain
    s = d.new_session()
    s.execute("create resource group rg_scope ru_per_sec = 100000")
    try:
        s.execute("set tidb_tpu_resource_group = 'rg_scope'")
        s.execute("set tidb_use_tpu = 1")
        base = REGISTRY.snapshot().get(
            "resgroup_rg_scope_ru_consumed_total", 0)
        s.query(Q_AGG)
        after = REGISTRY.snapshot().get(
            "resgroup_rg_scope_ru_consumed_total", 0)
        assert after > base, "chunk charges did not land on the group"
    finally:
        s.execute("set tidb_tpu_resource_group = ''")
        s.execute("drop resource group rg_scope")


def test_explain_analyze_reports_chunks(sess, chunked):
    sess.execute("set tidb_enable_slow_log = 1")
    try:
        rows = sess.query("explain analyze " + Q_AGG)
    finally:
        sess.execute("set tidb_enable_slow_log = 0")
    root_extra = rows[0][-1]
    assert "chunks:" in root_extra, root_extra


def test_status_and_snapshot_sections(sess):
    snap = sess.domain.resgroups.snapshot()
    assert any(g["name"] == "default" for g in snap)
    from tidb_tpu.server.http_status import _resgroups_section

    sec = _resgroups_section(sess.domain)
    assert "groups" in sec and "error" not in sec


# ---------------------------------------------------------------------------
# PRIORITY: weighted-fair admission order (ISSUE 18 lifecycle (c))
# ---------------------------------------------------------------------------

def test_priority_ddl_and_infoschema():
    d = Domain()
    s = d.new_session()
    s.execute("create resource group rg_prio ru_per_sec = 500 "
              "priority = 4")
    g = d.resgroups.get("rg_prio")
    assert g.priority == 4
    s.execute("alter resource group rg_prio priority = 2")
    assert g.priority == 2
    rows = s.query("select name, priority from information_schema."
                   "tidb_tpu_resource_groups where name = 'rg_prio'")
    assert rows == [("rg_prio", 2)]
    # default group keeps weight 1; priority floor clamps to 1
    assert d.resgroups.get("default").priority == 1
    s.execute("alter resource group rg_prio priority = 0")
    assert g.priority == 1
    s.execute("drop resource group rg_prio")


def test_priority_gate_inert_without_differing_contention():
    """A group running alone — or against equal-priority peers — pays
    nothing for the gate: admission stays the original token behavior."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    hi = reg.create("solo_hi", priority=8)
    sc = QueryScope()
    sc.resgroup = hi
    for _ in range(50):
        assert hi.admit(sc) == 0.0  # no contender: instant every time
    reg = ResourceGroupRegistry()  # fresh: solo_hi is still "recent"
    eq_a = reg.create("eq_a", priority=3)
    eq_b = reg.create("eq_b", priority=3)
    sa, sb = QueryScope(), QueryScope()
    sa.resgroup, sb.resgroup = eq_a, eq_b
    for _ in range(50):
        assert eq_a.admit(sa) == 0.0
        assert eq_b.admit(sb) == 0.0  # same weight: gate never engages


def test_priority_two_to_one_admission_under_contention():
    """Sustained contention between a PRIORITY=2 and a PRIORITY=1 group
    admits chunks ~2:1 — the weighted-fair finish tags advance at
    1/priority per admitted chunk, so the device boundary crossings
    track the weights."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    reg = ResourceGroupRegistry()
    hi = reg.create("wfq_hi", priority=2)
    lo = reg.create("wfq_lo", priority=1)
    counts = {"wfq_hi": 0, "wfq_lo": 0}
    stop = threading.Event()

    def pump(g):
        sc = QueryScope()
        sc.resgroup = g
        while not stop.is_set():
            g.admit(sc)
            counts[g.name] += 1

    threads = [threading.Thread(target=pump, args=(g,))
               for g in (hi, lo)]
    for t in threads:
        t.start()
    # measure AFTER both groups are engaged: until the second thread's
    # first arrival the gate is rightly inert (no contention) and the
    # first group tight-loops ungated — that ramp is not contention
    time.sleep(0.15)
    base = dict(counts)
    time.sleep(0.7)
    delta = {k: counts[k] - base[k] for k in counts}
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert delta["wfq_lo"] >= 30, delta  # no starvation, real contention
    ratio = delta["wfq_hi"] / delta["wfq_lo"]
    assert 1.4 <= ratio <= 2.8, delta


def test_priority_never_throttles_on_priority_alone(monkeypatch):
    """A low-priority group held back ONLY by the weighted-fair gate
    passes through at the bounded wait instead of raising
    ResourceGroupThrottled — priority shapes order, not quota."""
    from tidb_tpu.lifecycle import ResourceGroupRegistry

    monkeypatch.setenv("TIDB_TPU_RESGROUP_MAX_WAIT_MS", "50")
    reg = ResourceGroupRegistry()
    hi = reg.create("rush_hi", priority=64)
    lo = reg.create("rush_lo", priority=1)
    stop = threading.Event()

    def flood():
        sc = QueryScope()
        sc.resgroup = hi
        while not stop.is_set():
            hi.admit(sc)

    t = threading.Thread(target=flood)
    t.start()
    try:
        sc = QueryScope()
        sc.resgroup = lo
        for _ in range(5):
            lo.admit(sc)  # must NEVER raise: tokens are unlimited
    finally:
        stop.set()
        t.join(timeout=10)
    assert lo.snapshot()["throttled"] == 0


# ---------------------------------------------------------------------------
# definition replication through the coord plane (ISSUE 18 lifecycle (e))
# ---------------------------------------------------------------------------

def test_resgroup_defs_replicate_over_local_plane():
    """Two domains attached to one plane converge on the same
    definitions: CREATE/ALTER/bind/DROP on one side shows up on the
    other at its next resolve(), preserving live token balances."""
    from tidb_tpu.coord.plane import LocalPlane

    plane = LocalPlane()
    dA, dB = Domain(), Domain()
    dA.resgroups.attach_plane(plane)
    dB.resgroups.attach_plane(plane)
    sA = dA.new_session()
    sA.execute("create resource group silver ru_per_sec = 800 "
               "burstable priority = 3, query_limit = 1200")
    sA.execute("create user 'dave' identified by 'pw'")
    sA.execute("alter user 'dave' resource group silver")
    # the replica adopts the definitions at resolve time
    g = dB.resgroups.resolve("dave@%")
    assert (g.name, g.ru_per_sec, g.burstable, g.priority,
            g.query_limit_ms) == ("silver", 800, True, 3, 1200)
    # ALTER replicates without resetting the replica's live balance
    sc = QueryScope()
    sc.resgroup = g
    g.charge(300.0, sc)
    tokens_before = g.snapshot()["tokens"]
    sA.execute("alter resource group silver priority = 5, "
               "query_limit = 900")
    g2 = dB.resgroups.resolve("dave@%")
    assert g2 is g  # updated in place, not replaced
    assert g.priority == 5 and g.query_limit_ms == 900
    assert g.snapshot()["tokens"] == pytest.approx(
        tokens_before, abs=50.0)  # balance survived (modulo refill)
    # DROP replicates; the binding falls back to default
    sA.execute("drop resource group silver")
    assert dB.resgroups.resolve("dave@%").name == "default"
    # a DETACHED domain never syncs from the plane
    dC = Domain()
    sA.execute("create resource group silver ru_per_sec = 1")
    assert dC.resgroups.get("silver") is None


def test_resgroup_defs_replicate_over_rpc_plane():
    """The worker-plane path: definitions published on the coordinator
    member ride the membership broadcast (shared store piggyback) and a
    worker-side domain adopts them without any direct RPC of its own."""
    from tidb_tpu.coord.plane import (
        Coordinator, CoordinatorPlane, WorkerPlane)

    coord = Coordinator(port=0, lease_s=4.0, expect=2, self_pid=0)
    host, port = coord.start()
    cp = CoordinatorPlane(coord, pid=0).start((0,))
    wp = WorkerPlane(f"{host}:{port}", 1, lease_s=4.0,
                     heartbeat_s=0.05).start((1,))
    try:
        _wait_for(lambda: cp.view().formed and wp.view().formed)
        dA, dB = Domain(), Domain()
        dA.resgroups.attach_plane(cp)
        dB.resgroups.attach_plane(wp)
        sA = dA.new_session()
        sA.execute("create resource group fleetwide ru_per_sec = 250 "
                   "priority = 7")
        # the worker's local shared cache fills from the heartbeat
        _wait_for(lambda: wp.shared_version("resgroups") >= 1)
        g = dB.resgroups.resolve("", "fleetwide")
        assert (g.name, g.ru_per_sec, g.priority) == \
            ("fleetwide", 250, 7)
    finally:
        try:
            wp.stop(leave=True)
        except Exception:
            pass
        cp.stop()


def _wait_for(pred, timeout=15.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError("condition not reached")
