"""MySQL wire protocol tests with a minimal in-repo client.

Reference test model: server/conn_test.go + packetio tests — the client here
speaks just enough protocol 4.1 (handshake response, COM_QUERY, COM_PING,
COM_STMT_PREPARE/EXECUTE) to verify framing, result sets and errors.
"""

import asyncio
import struct

import pytest

from tidb_tpu.server import MySQLServer
from tidb_tpu.server.packet import (
    PacketReader,
    PacketWriter,
    read_lenenc_int,
    read_lenenc_str,
)
from tidb_tpu.server import protocol as P


class MiniClient:
    def __init__(self, host, port):
        self.host, self.port = host, port

    async def connect(self, db=""):
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )
        self.pr = PacketReader(self.reader)
        self.pw = PacketWriter(self.writer)
        greeting = await self.pr.recv()
        assert greeting[0] == 10  # protocol version
        caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
        if db:
            caps |= P.CLIENT_CONNECT_WITH_DB
        resp = struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
        resp += bytes([33]) + b"\x00" * 23
        resp += b"root\x00" + b"\x00"  # user, empty auth
        if db:
            resp += db.encode() + b"\x00"
        self.pw.seq = self.pr.seq
        await self.pw.send(resp)
        ok = await self.pr.recv()
        assert ok[0] == 0x00, ok

    async def command(self, cmd: int, payload: bytes = b""):
        self.pw.reset_seq()
        await self.pw.send(bytes([cmd]) + payload)

    async def query(self, sql: str):
        await self.command(0x03, sql.encode())
        first = await self.pr.recv()
        if first[0] == 0x00:  # OK
            affected, pos = read_lenenc_int(first, 1)
            return {"ok": True, "affected": affected}
        if first[0] == 0xFF:
            code = struct.unpack_from("<H", first, 1)[0]
            return {"error": code, "message": first[9:].decode()}
        ncols, _ = read_lenenc_int(first, 0)
        cols = []
        for _ in range(ncols):
            cdef = await self.pr.recv()
            pos = 0
            vals = []
            for _ in range(6):
                v, pos = read_lenenc_str(cdef, pos)
                vals.append(v)
            cols.append(vals[4].decode())
        eof = await self.pr.recv()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = await self.pr.recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            pos = 0
            row = []
            for _ in range(ncols):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    v, pos = read_lenenc_str(pkt, pos)
                    row.append(v.decode())
            rows.append(tuple(row))
        return {"cols": cols, "rows": rows}

    async def close(self):
        await self.command(0x01)
        self.writer.close()


@pytest.fixture()
def server_client():
    async def setup():
        srv = MySQLServer(port=0)
        await srv.start()
        cli = MiniClient(srv.host, srv.port)
        await cli.connect(db="test")
        return srv, cli

    return setup


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def test_handshake_query_roundtrip(server_client):
    async def body():
        srv, cli = await server_client()
        r = await cli.query("create table t (a bigint, b varchar(10))")
        assert r.get("ok")
        r = await cli.query("insert into t values (1, 'x'), (2, null)")
        assert r["affected"] == 2
        r = await cli.query("select a, b from t order by a")
        assert r["cols"] == ["a", "b"]
        assert r["rows"] == [("1", "x"), ("2", None)]
        r = await cli.query("select count(*), sum(a) from t")
        assert r["rows"] == [("2", "3")]
        await cli.close()
        await srv.stop()

    run(body())


def test_error_packet(server_client):
    async def body():
        srv, cli = await server_client()
        r = await cli.query("select * from nosuchtable")
        assert "error" in r
        await cli.close()
        await srv.stop()

    run(body())


def test_ping_and_init_db(server_client):
    async def body():
        srv, cli = await server_client()
        await cli.command(0x0E)  # ping
        ok = await cli.pr.recv()
        assert ok[0] == 0x00
        await cli.command(0x02, b"mysql")  # init_db
        ok = await cli.pr.recv()
        assert ok[0] == 0x00
        await cli.close()
        await srv.stop()

    run(body())


def test_prepared_statement_binary(server_client):
    async def body():
        srv, cli = await server_client()
        await cli.query("create table p (a bigint, b varchar(10))")
        await cli.query("insert into p values (1,'x'),(2,'y'),(3,'z')")
        await cli.command(0x16, b"select b from p where a = ?")
        resp = await cli.pr.recv()
        assert resp[0] == 0x00
        stmt_id = struct.unpack_from("<I", resp, 1)[0]
        n_params = struct.unpack_from("<H", resp, 7)[0]
        assert n_params == 1
        for _ in range(n_params):
            await cli.pr.recv()  # param defs
        await cli.pr.recv()  # eof
        # execute with long param = 2
        payload = struct.pack("<I", stmt_id) + b"\x00" + struct.pack("<I", 1)
        payload += b"\x00"  # null bitmap
        payload += b"\x01"  # new params bound
        payload += bytes([0x08, 0x00])  # longlong
        payload += struct.pack("<q", 2)
        await cli.command(0x17, payload)
        first = await cli.pr.recv()
        ncols, _ = read_lenenc_int(first, 0)
        for _ in range(ncols):
            await cli.pr.recv()
        await cli.pr.recv()  # eof
        rows = []
        while True:
            pkt = await cli.pr.recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            # binary-protocol row: 0x00 header, null bitmap, lenenc string
            assert pkt[0] == 0x00
            nb = (ncols + 9) // 8
            v, _ = read_lenenc_str(pkt, 1 + nb)
            rows.append(v.decode())
        assert rows == ["y"]
        # re-execute WITHOUT re-sending types (new_params_bound_flag = 0)
        payload2 = struct.pack("<I", stmt_id) + b"\x00" + struct.pack("<I", 1)
        payload2 += b"\x00"  # null bitmap
        payload2 += b"\x00"  # new params bound = 0 -> reuse cached types
        payload2 += struct.pack("<q", 3)
        await cli.command(0x17, payload2)
        first = await cli.pr.recv()
        ncols, _ = read_lenenc_int(first, 0)
        for _ in range(ncols):
            await cli.pr.recv()
        await cli.pr.recv()
        rows2 = []
        while True:
            pkt = await cli.pr.recv()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            nb = (ncols + 9) // 8
            v, _ = read_lenenc_str(pkt, 1 + nb)
            rows2.append(v.decode())
        assert rows2 == ["z"]
        await cli.close()
        await srv.stop()

    run(body())


def test_param_count_ignores_literal_question_marks(server_client):
    async def body():
        srv, cli = await server_client()
        await cli.query("create table q (a bigint, s varchar(10))")
        await cli.query("insert into q values (1, 'who?')")
        await cli.command(
            0x16, b"select a from q where s = 'who?' and a = ?"
        )
        resp = await cli.pr.recv()
        assert resp[0] == 0x00
        n_params = struct.unpack_from("<H", resp, 7)[0]
        assert n_params == 1  # the '?' inside the literal doesn't count
        await cli.close()
        await srv.stop()

    run(body())


def test_concurrent_connections(server_client):
    async def body():
        srv, cli = await server_client()
        await cli.query("create table c (a bigint)")
        await cli.query("insert into c values (1)")
        cli2 = MiniClient(srv.host, srv.port)
        await cli2.connect(db="test")
        r = await cli2.query("select a from c")
        assert r["rows"] == [("1",)]
        await cli2.close()
        await cli.close()
        await srv.stop()

    run(body())


def test_trace_statement_over_the_wire(server_client):
    """ISSUE 4 acceptance: TRACE on a TPC-H-shaped query returns the
    span tree over the MySQL protocol, and the wire layer appends its
    write span to the finished trace."""
    async def body():
        srv, cli = await server_client()
        await cli.query("create table li (k bigint, qty bigint,"
                        " price double, flag varchar(1))")
        rows = ", ".join(f"({i % 9}, {i % 50}, {i}.5, 'A')"
                         for i in range(512))
        await cli.query("insert into li values " + rows)
        r = await cli.query(
            "trace select flag, sum(qty), avg(price), count(*) from li"
            " where qty < 40 group by flag")
        assert r["cols"] == ["operation", "startTS", "duration"]
        ops = [row[0].strip() for row in r["rows"]]
        assert ops[0].startswith("session.execute")
        assert "wire_read_bytes" in ops[0]  # COM_QUERY payload recorded
        assert any(o.startswith("plan") for o in ops)
        assert any(o.startswith("executor.next") for o in ops)
        # json format crosses the wire too
        r = await cli.query("trace format='json' select count(*) from li")
        import json as _json

        doc = _json.loads(r["rows"][0][0])
        assert doc["root"]["name"] == "session.execute"
        # the finished trace gained a wire.write span from the server
        sess = next(iter(srv.domain.sessions.values()))
        tr = sess.last_trace
        names = [sp.name for sp in tr.root.children]
        assert "wire.write" in names
        wired = [sp for sp in tr.root.children if sp.name == "wire.write"]
        assert wired[0].attrs["bytes"] > 0
        await cli.close()
        await srv.stop()

    run(body())
