"""Shape-bucketed plan serving & query micro-batching (tidb_tpu/serving).

Parity is the contract: bucketed/padded layouts, hoisted-parameter
programs and micro-batched dispatches must return results identical to
solo execution — including when a batch member is KILLed mid-window,
hits its deadline mid-window, or the batch dispatch itself dies on the
seeded chaos site `serving/batch_dispatch`.
"""

import threading

import numpy as np
import pytest

from tidb_tpu import serving
from tidb_tpu.errors import MaxExecutionTimeExceeded, QueryKilledError
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain
from tidb_tpu.store.fault import failpoint, once


@pytest.fixture(autouse=True)
def _serving_defaults():
    """Serving config is process-global; every test starts and ends at
    the defaults so a SET in one test never bleeds into the next."""
    serving.configure(shape_buckets=True, microbatch_window_ms=0.0,
                      microbatch_max=32)
    yield
    serving.configure(shape_buckets=True, microbatch_window_ms=0.0,
                      microbatch_max=32)


def _load(sess, name: str, n: int = 20_000, regions: int = 4):
    d = sess.domain
    sess.execute(f"create table {name} (k bigint, g bigint, x double)")
    t = d.catalog.info_schema().table("test", name)
    store = d.storage.table(t.id)
    rng = np.random.default_rng(11)
    store.bulk_load_arrays(
        [np.arange(n, dtype=np.int64),
         rng.integers(0, 5, n, dtype=np.int64),
         rng.uniform(0, 100, n)],
        ts=d.storage.current_ts(),
    )
    d.storage.regions.split_even(t.id, regions, store.base_rows)
    return store


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    _load(s, "t")
    return s


def _snap(*names):
    s = REGISTRY.snapshot()
    return tuple(s.get(n, 0) for n in names)


def _approx_rows(got, want, ctx=""):
    assert len(got) == len(want), (ctx, got, want)
    for ra, rb in zip(sorted(got), sorted(want)):
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                assert x == pytest.approx(y, rel=1e-9, abs=1e-9), (ctx, ra, rb)
            else:
                assert x == y, (ctx, ra, rb)


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------


def test_shape_bucket_units():
    from tidb_tpu.serving import shape_bucket, topn_budget

    assert shape_bucket(1) == 1
    assert shape_bucket(3) == 4
    assert shape_bucket(4) == 4
    assert shape_bucket(33) == 64
    assert shape_bucket(0, floor=16) == 16
    assert topn_budget(5) == 16  # floor
    assert topn_budget(100) == 128
    serving.configure(shape_buckets=False)
    assert topn_budget(5) == 5  # disabled: exact


def test_param_hoist_shares_one_mesh_program(sess):
    from tidb_tpu.copr import parallel as pl

    sess.query("select k from t where x < 11.5")  # warm the shape class
    n0 = len(pl._COMPILED)
    r1 = sess.query("select k from t where x < 23.5")
    n1 = len(pl._COMPILED)
    r2 = sess.query("select k from t where x < 42.0")
    n2 = len(pl._COMPILED)
    assert n1 == n0 and n2 == n0, "parameter-different filters recompiled"
    assert len(r2) > len(r1) > 0
    # parity against the CPU oracle
    sess.execute("set tidb_use_tpu = 0")
    cpu = sess.query("select k from t where x < 42.0")
    sess.execute("set tidb_use_tpu = 1")
    _approx_rows(r2, cpu, "hoisted filter")


def test_point_agg_hoist_shares_program(sess):
    from tidb_tpu.copr import parallel as pl

    sess.query("select count(*), sum(x) from t where k = 5")
    n0 = len(pl._COMPILED)
    for k in (9, 123, 19_999):
        rows = sess.query(f"select count(*), sum(x) from t where k = {k}")
        assert rows[0][0] == 1
    assert len(pl._COMPILED) == n0, "point lookups recompiled per literal"


def test_shape_bucket_parity_toggle(sess):
    queries = (
        "select g, sum(x), count(*), min(x), max(x) from t group by g"
        " order by g",
        "select sum(x) from t where k < 15000 and x < 50",
        "select k, x from t order by x desc limit 7",
        "select k from t where x < 2.5",
    )
    serving.configure(shape_buckets=False)
    plain = [sess.query(q) for q in queries]
    serving.configure(shape_buckets=True)
    bucketed = [sess.query(q) for q in queries]
    for q, a, b in zip(queries, plain, bucketed):
        _approx_rows(b, a, q)


def test_topn_budget_shares_program(sess):
    from tidb_tpu.copr import parallel as pl

    r5 = sess.query("select k, x from t order by x desc limit 5")
    n0 = len(pl._COMPILED)
    r7 = sess.query("select k, x from t order by x desc limit 7")
    assert len(pl._COMPILED) == n0, "LIMIT 5 vs 7 compiled two programs"
    assert len(r5) == 5 and len(r7) == 7
    assert [r[0] for r in r7[:5]] == [r[0] for r in r5]


# ---------------------------------------------------------------------------
# plan cache satellites
# ---------------------------------------------------------------------------


def test_plan_cache_size_sysvar(sess):
    sess.execute("set tidb_plan_cache_size = 2")
    try:
        for i in range(4):
            sess.query(f"select k from t where x < {10 + i}.5")
        assert len(sess._plan_cache) <= 2
    finally:
        sess.execute("set tidb_plan_cache_size = 128")


def test_plan_cache_survives_small_dml():
    d = Domain()
    s = d.new_session()
    _load(s, "t_pc", n=4000, regions=2)
    # pin stats first: the stats build-epoch is (deliberately) part of
    # the key, so the test isolates the table-version component
    s.execute("analyze table t_pc")
    q = "select g, count(*) from t_pc group by g order by g"
    s.query(q)
    h0, = _snap("plan_cache_hits_total")
    s.query(q)
    h1, = _snap("plan_cache_hits_total")
    assert h1 == h0 + 1
    # small DML stays inside the table's pow2 row bucket: the cached
    # plan remains valid (results re-read data at execution time)
    s.execute("insert into t_pc values (4000, 1, 2.5)")
    before = s.query(q)
    h2, = _snap("plan_cache_hits_total")
    assert h2 == h1 + 1, "an in-bucket insert invalidated the cached plan"
    s.execute("set tidb_use_tpu = 0")
    cpu = s.query(q)
    s.execute("set tidb_use_tpu = 1")
    _approx_rows(before, cpu, "post-DML cached plan")


def test_program_cache_lru_and_metrics():
    from tidb_tpu.copr.cache import ProgramCache

    h0, m0, e0 = _snap("compiled_programs_hits_total",
                       "compiled_programs_misses_total",
                       "compiled_programs_evictions_total")
    c = ProgramCache("unit-test", capacity=2)
    assert c.get("a") is None
    c.put("a", 1)
    c.put("b", 2)
    assert c.get("a") == 1  # refreshes LRU position
    c.put("c", 3)  # evicts b (a was refreshed)
    assert c.get("b") is None
    assert c.get("a") == 1 and c.get("c") == 3
    st = c.stats()
    assert st["size"] == 2 and st["evictions"] == 1
    h1, m1, e1 = _snap("compiled_programs_hits_total",
                       "compiled_programs_misses_total",
                       "compiled_programs_evictions_total")
    assert h1 - h0 == 3 and m1 - m0 == 2 and e1 - e0 == 1


def test_status_reports_compiled_caches(sess):
    import json
    import urllib.request

    import tidb_tpu.serving.batcher  # noqa: F401 — registers its cache
    from tidb_tpu.server.http_status import StatusServer

    srv = StatusServer(sess.domain, port=0)
    host, port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/status", timeout=5) as r:
            body = json.loads(r.read())
    finally:
        srv.stop()
    caches = body["compiled_programs"]
    assert "tile" in caches and "mesh" in caches and "microbatch" in caches
    assert caches["mesh"]["size"] >= 1  # the module's queries compiled


# ---------------------------------------------------------------------------
# micro-batching
# ---------------------------------------------------------------------------


def _concurrent(d, sqls, window_ms=250):
    """Run sqls on fresh sessions, one thread each, batching window on;
    returns (results, errors) in input order."""
    serving.configure(microbatch_window_ms=float(window_ms))
    results = [None] * len(sqls)
    errors = [None] * len(sqls)
    sessions = [d.new_session() for _ in sqls]
    barrier = threading.Barrier(len(sqls))

    def run(i):
        barrier.wait()
        try:
            results[i] = sessions[i].query(sqls[i])
        except BaseException as e:  # noqa: BLE001 — asserted by tests
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i,), daemon=True,
                                name=f"serving-test-{i}")
               for i in range(len(sqls))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    serving.configure(microbatch_window_ms=0.0)
    return results, errors, sessions


def test_microbatch_agg_parity(sess):
    d = sess.domain
    sqls = [f"select count(*), sum(x), min(x) from t where k = {k}"
            for k in (3, 7, 4242, 19_998)]
    solo = [sess.query(q) for q in sqls]
    b0, s0 = _snap("serving_batches_total", "serving_batched_stmts_total")
    results, errors, _ = _concurrent(d, sqls)
    assert errors == [None] * 4, errors
    for q, got, want in zip(sqls, results, solo):
        _approx_rows(got, want, q)
    b1, s1 = _snap("serving_batches_total", "serving_batched_stmts_total")
    assert b1 > b0, "no batch formed"
    assert s1 - s0 >= 2, "fewer than 2 statements batched"
    assert (s1 - s0) > (b1 - b0), "batches never held >1 statement"


def test_microbatch_filter_parity(sess):
    d = sess.domain
    sqls = [f"select k, g, x from t where k = {k}" for k in (5, 42, 777)]
    solo = [sess.query(q) for q in sqls]
    results, errors, _ = _concurrent(d, sqls)
    assert errors == [None] * 3, errors
    for q, got, want in zip(sqls, results, solo):
        _approx_rows(got, want, q)


def test_microbatch_distinct_columns_never_merge(sess):
    """Regression: the DAG fingerprint keys columns by scan-output index,
    so `where k = ?` and `where g = ?` serialize identically — the batch
    key must pin the resolved STORE columns or the two queries would
    batch together and return each other's results."""
    d = sess.domain
    sqls = ["select count(*), sum(x) from t where k = 3",
            "select count(*), sum(x) from t where g = 3"]
    solo = [sess.query(q) for q in sqls]
    assert solo[0] != solo[1]  # the shapes must be distinguishable
    results, errors, _ = _concurrent(d, sqls, window_ms=250)
    assert errors == [None, None], errors
    for q, got, want in zip(sqls, results, solo):
        _approx_rows(got, want, q)


def test_microbatch_leader_kill_unblocks_window():
    """A KILLed leader must not sit out the batching window: the window
    wait wakes on its cancel event and the batch closes early."""
    import time

    from tidb_tpu.lifecycle import QueryScope
    from tidb_tpu.serving.batcher import MicroBatcher, _Member

    b = MicroBatcher()
    sc = QueryScope()
    m = _Member(np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float64),
                sc)
    out = {}

    def run():
        t0 = time.monotonic()
        try:
            b.submit(("unit-key",), m, 5.0, 8, lambda live: None)
        except BaseException as e:  # noqa: BLE001
            out["err"] = e
        out["dt"] = time.monotonic() - t0

    th = threading.Thread(target=run, daemon=True)
    th.start()
    time.sleep(0.1)
    sc.cancel("killed")
    th.join(5)
    assert out.get("dt") is not None, "leader never returned"
    assert out["dt"] < 1.0, f"KILL blocked on the window: {out['dt']:.2f}s"
    assert isinstance(out.get("err"), QueryKilledError)


def test_microbatch_member_killed_mid_window(sess):
    d = sess.domain
    sqls = ["select count(*), sum(x) from t where k = 1",
            "select count(*), sum(x) from t where k = 2"]
    solo = sess.query(sqls[1])
    serving.configure(microbatch_window_ms=500.0)
    results = [None, None]
    errors = [None, None]
    sessions = [d.new_session(), d.new_session()]
    started = threading.Barrier(3)

    def run(i):
        started.wait()
        try:
            results[i] = sessions[i].query(sqls[i])
        except BaseException as e:  # noqa: BLE001
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    started.wait()
    # kill member 0 while the window is still open: it must raise
    # promptly and be masked out; member 1's batch completes normally
    import time

    time.sleep(0.15)
    sessions[0].cancel_query("killed")
    for t in threads:
        t.join(30)
    serving.configure(microbatch_window_ms=0.0)
    assert isinstance(errors[0], QueryKilledError), errors
    assert errors[1] is None, errors
    _approx_rows(results[1], solo, "survivor of killed batch member")
    assert sessions[0].last_termination == "killed"


def test_microbatch_member_deadline_mid_window(sess):
    d = sess.domain
    sqls = ["select count(*), sum(x) from t where k = 8",
            "select count(*), sum(x) from t where k = 9"]
    solo = sess.query(sqls[1])
    serving.configure(microbatch_window_ms=600.0)
    sessions = [d.new_session(), d.new_session()]
    sessions[0].execute("set max_execution_time = 120")  # expires in-window
    results = [None, None]
    errors = [None, None]
    barrier = threading.Barrier(2)

    def run(i):
        barrier.wait()
        try:
            results[i] = sessions[i].query(sqls[i])
        except BaseException as e:  # noqa: BLE001
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    serving.configure(microbatch_window_ms=0.0)
    assert isinstance(errors[0], MaxExecutionTimeExceeded), errors
    assert errors[1] is None, errors
    _approx_rows(results[1], solo, "survivor of deadline batch member")
    assert sessions[0].last_termination == "timeout"


def test_microbatch_chaos_batch_dispatch(sess):
    """Seeded chaos: the batch dispatch dies once — every member falls
    back to solo execution with identical results, nothing leaks."""
    d = sess.domain
    sqls = [f"select count(*), sum(x) from t where k = {k}"
            for k in (100, 200)]
    solo = [sess.query(q) for q in sqls]
    e0, = _snap("serving_batch_errors_total")
    with failpoint("serving/batch_dispatch", once(RuntimeError("chaos"))):
        results, errors, _ = _concurrent(d, sqls, window_ms=300)
    assert errors == [None, None], errors
    for q, got, want in zip(sqls, results, solo):
        _approx_rows(got, want, q)
    e1, = _snap("serving_batch_errors_total")
    assert e1 == e0 + 1, "chaos site never fired on the batch path"


def test_microbatch_respects_max_batch(sess):
    d = sess.domain
    serving.configure(microbatch_max=2)
    sqls = [f"select count(*) from t where k = {k}" for k in range(4)]
    solo = [sess.query(q) for q in sqls]
    b0, = _snap("serving_batches_total")
    results, errors, _ = _concurrent(d, sqls, window_ms=250)
    serving.configure(microbatch_max=32)
    assert errors == [None] * 4
    for got, want in zip(results, solo):
        _approx_rows(got, want)
    b1, = _snap("serving_batches_total")
    assert b1 - b0 >= 2, "max=2 should split 4 members into >=2 batches"


def test_microbatch_skips_tables_with_delta():
    """MVCC delta makes the base scan ts-dependent: such tables must
    run solo (parity over throughput)."""
    d = Domain()
    s = d.new_session()
    _load(s, "t_delta", n=4000, regions=2)
    s.execute("insert into t_delta values (4000, 2, 7.5)")
    q = "select count(*), sum(x) from t_delta where k >= 3999"
    solo = s.query(q)
    b0, = _snap("serving_batches_total")
    results, errors, _ = _concurrent(d, [q, q], window_ms=200)
    assert errors == [None, None]
    _approx_rows(results[0], solo)
    _approx_rows(results[1], solo)
    b1, = _snap("serving_batches_total")
    assert b1 == b0, "a delta'd table entered the micro-batch path"
