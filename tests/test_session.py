"""End-to-end SQL tests through the session layer.

Mirrors the reference's dominant test tier: full stack in-process against
the embedded store (testkit.MustQuery().Check() style, SURVEY.md §4).
"""

import pytest

from tidb_tpu.errors import KVError, TiDBTPUError
from tidb_tpu.session import Domain


@pytest.fixture()
def sess():
    d = Domain()
    return d.new_session()


@pytest.fixture()
def tsess(sess):
    sess.execute("create table t (a bigint, b double, c varchar(20))")
    sess.execute(
        "insert into t values (1, 1.5, 'x'), (2, 2.5, 'y'), "
        "(3, 3.5, 'x'), (null, 9.0, 'z')"
    )
    return sess


def q(sess, sql):
    return sess.query(sql)


class TestBasic:
    def test_select_all(self, tsess):
        assert q(tsess, "select * from t") == [
            (1, 1.5, "x"), (2, 2.5, "y"), (3, 3.5, "x"), (None, 9.0, "z")
        ]

    def test_where_arith(self, tsess):
        assert q(tsess, "select a+1, b*2 from t where a >= 2") == [
            (3, 5.0), (4, 7.0)
        ]

    def test_group_agg(self, tsess):
        assert q(tsess, "select c, count(*), sum(b) from t "
                        "where a is not null group by c order by c") == [
            ("x", 2, 5.0), ("y", 1, 2.5)
        ]

    def test_scalar_agg(self, tsess):
        assert q(tsess, "select count(*), count(a), avg(b) from t") == [
            (4, 3, 4.125)
        ]

    def test_scalar_agg_empty(self, tsess):
        assert q(tsess, "select count(*), sum(a), min(b) from t "
                        "where a > 100") == [(0, None, None)]

    def test_order_limit(self, tsess):
        assert q(tsess, "select a from t where a is not null "
                        "order by a desc limit 2") == [(3,), (2,)]

    def test_distinct(self, tsess):
        assert q(tsess, "select distinct c from t order by c") == [
            ("x",), ("y",), ("z",)
        ]

    def test_select_no_table(self, sess):
        assert q(sess, "select 1+1") == [(2,)]

    def test_case_when(self, tsess):
        rows = q(tsess, "select a, case when a >= 2 then 'big' else 'small' "
                        "end from t where a is not null order by a")
        assert rows == [(1, "small"), (2, "big"), (3, "big")]

    def test_having(self, tsess):
        assert q(tsess, "select c, count(*) as n from t group by c "
                        "having n > 1") == [("x", 2)]

    def test_alias_order(self, tsess):
        assert q(tsess, "select a*10 as x from t where a is not null "
                        "order by x desc") == [(30,), (20,), (10,)]


class TestJoins:
    @pytest.fixture()
    def jsess(self, sess):
        sess.execute("create table t1 (a bigint, b varchar(10))")
        sess.execute("create table t2 (a bigint, v double)")
        sess.execute("insert into t1 values (1,'p'),(2,'q'),(3,'r')")
        sess.execute(
            "insert into t2 values (1,10.0),(1,11.0),(3,30.0),(4,40.0)"
        )
        return sess

    def test_inner(self, jsess):
        assert q(jsess, "select t1.a, t2.v from t1 join t2 on t1.a = t2.a "
                        "order by t1.a, t2.v") == [
            (1, 10.0), (1, 11.0), (3, 30.0)
        ]

    def test_left(self, jsess):
        assert q(jsess, "select t1.a, t2.v from t1 left join t2 "
                        "on t1.a = t2.a order by t1.a, t2.v") == [
            (1, 10.0), (1, 11.0), (2, None), (3, 30.0)
        ]

    def test_right(self, jsess):
        rows = q(jsess, "select t1.a, t2.a from t1 right join t2 "
                        "on t1.a = t2.a order by t2.a, t1.a")
        assert rows == [(1, 1), (1, 1), (3, 3), (None, 4)]

    def test_semi_in(self, jsess):
        assert q(jsess, "select a from t1 where a in (select a from t2) "
                        "order by a") == [(1,), (3,)]

    def test_anti_in(self, jsess):
        assert q(jsess, "select a from t1 where a not in "
                        "(select a from t2) order by a") == [(2,)]

    def test_join_where(self, jsess):
        assert q(jsess, "select t1.a, t2.v from t1, t2 "
                        "where t1.a = t2.a and t2.v > 10 "
                        "order by t2.v") == [(1, 11.0), (3, 30.0)]

    def test_self_join_alias(self, jsess):
        rows = q(jsess, "select x.a, y.v from t2 x join t2 y "
                        "on x.a = y.a where x.v = 10 order by y.v")
        assert rows == [(1, 10.0), (1, 11.0)]

    def test_scalar_subquery(self, jsess):
        assert q(jsess, "select a from t1 where a > "
                        "(select min(a) from t2) order by a") == [(2,), (3,)]

    def test_cross_join(self, jsess):
        assert q(jsess, "select count(*) from t1, t2") == [(12,)]


class TestDML:
    def test_update_delete(self, tsess):
        tsess.execute("update t set b = b + 1 where a = 1")
        assert q(tsess, "select b from t where a = 1") == [(2.5,)]
        rs = tsess.execute("delete from t where a is null")[0]
        assert rs.affected_rows == 1
        assert q(tsess, "select count(*) from t") == [(3,)]

    def test_insert_select(self, tsess):
        tsess.execute("create table t2 (a bigint, b double, c varchar(20))")
        tsess.execute("insert into t2 select * from t where a is not null")
        assert q(tsess, "select count(*) from t2") == [(3,)]

    def test_txn_commit_rollback(self, tsess):
        tsess.execute("begin")
        tsess.execute("insert into t values (10, 0.0, 'tx')")
        assert q(tsess, "select count(*) from t") == [(5,)]
        tsess.execute("rollback")
        assert q(tsess, "select count(*) from t") == [(4,)]
        tsess.execute("begin")
        tsess.execute("insert into t values (11, 0.0, 'tx2')")
        tsess.execute("commit")
        assert q(tsess, "select count(*) from t") == [(5,)]

    def test_txn_isolation(self, tsess):
        s2 = tsess.domain.new_session()
        tsess.execute("begin")
        tsess.execute("insert into t values (42, 0.0, 'mine')")
        # other session must not see uncommitted rows
        assert q(s2, "select count(*) from t") == [(4,)]
        tsess.execute("commit")
        assert q(s2, "select count(*) from t") == [(5,)]

    def test_write_conflict_autocommit_retries(self, tsess):
        s2 = tsess.domain.new_session()
        tsess.execute("update t set b = 100 where a = 1")
        s2.execute("update t set b = 200 where a = 1")
        assert q(tsess, "select b from t where a = 1") == [(200.0,)]

    def test_replace_unique(self, sess):
        sess.execute("create table u (id bigint primary key, v double)")
        sess.execute("insert into u values (1, 1.0), (2, 2.0)")
        with pytest.raises(KVError):
            sess.execute("insert into u values (1, 99.0)")
        sess.execute("replace into u values (1, 99.0)")
        assert q(sess, "select v from u where id = 1") == [(99.0,)]

    def test_insert_on_dup(self, sess):
        sess.execute("create table u (id bigint primary key, v bigint)")
        sess.execute("insert into u values (1, 1)")
        sess.execute("insert into u values (1, 5) on duplicate key update "
                     "v = v + 10")
        assert q(sess, "select v from u") == [(11,)]

    def test_auto_increment(self, sess):
        sess.execute(
            "create table ai (id bigint primary key auto_increment, "
            "v varchar(5))"
        )
        sess.execute("insert into ai (v) values ('a'), ('b')")
        assert q(sess, "select id, v from ai order by id") == [
            (1, "a"), (2, "b")
        ]


class TestDDL:
    def test_create_drop(self, sess):
        sess.execute("create table d1 (a bigint)")
        sess.execute("insert into d1 values (1)")
        sess.execute("drop table d1")
        with pytest.raises(TiDBTPUError):
            q(sess, "select * from d1")

    def test_truncate(self, tsess):
        tsess.execute("truncate table t")
        assert q(tsess, "select count(*) from t") == [(0,)]

    def test_add_drop_column(self, tsess):
        tsess.execute("alter table t add column d bigint default 7")
        assert q(tsess, "select d from t where a = 1") == [(7,)]
        tsess.execute("alter table t drop column b")
        assert q(tsess, "select * from t where a = 1") == [(1, "x", 7)]

    def test_rename(self, tsess):
        tsess.execute("rename table t to t9")
        assert q(tsess, "select count(*) from t9") == [(4,)]

    def test_view(self, tsess):
        tsess.execute("create view v1 as select c, sum(b) as s from t "
                      "group by c")
        assert q(tsess, "select * from v1 order by c") == [
            ("x", 5.0), ("y", 2.5), ("z", 9.0)
        ]

    def test_create_index_unique_violation(self, tsess):
        with pytest.raises(KVError):
            tsess.execute("create unique index ux on t (c)")
        tsess.execute("create index ix on t (c)")
        assert any(r[2] == "ix" for r in q(tsess, "show index from t"))

    def test_ddl_jobs_history(self, tsess):
        rows = q(tsess, "admin show ddl jobs")
        assert any(r[1] == "create_table" for r in rows)

    def test_show_create_table(self, tsess):
        rows = q(tsess, "show create table t")
        assert "CREATE TABLE `t`" in rows[0][1]


class TestShow:
    def test_show_tables_databases(self, tsess):
        assert ("t",) in q(tsess, "show tables")
        assert ("test",) in q(tsess, "show databases")

    def test_desc(self, tsess):
        rows = q(tsess, "desc t")
        assert rows[0][0] == "a"

    def test_set_show_variables(self, sess):
        sess.execute("set tidb_distsql_scan_concurrency = 4")
        allv = dict(q(sess, "show variables like 'tidb_distsql%'"))
        assert allv["tidb_distsql_scan_concurrency"] == "4"

    def test_use_unknown_db(self, sess):
        with pytest.raises(TiDBTPUError):
            sess.execute("use nosuchdb")

    def test_show_regions_and_split(self, tsess):
        rs = tsess.execute("split table t regions 4")[0]
        assert rs.rows[0][0] >= 2
        rows = q(tsess, "show table regions t")
        assert len(rows) == rs.rows[0][0]
        # a multi-region scan still returns every row exactly once
        assert q(tsess, "select count(*) from t") == [(4,)]


class TestExplain:
    def test_pushdown_plan_shape(self, tsess):
        rows = q(tsess, "explain select c, sum(b) from t group by c")
        tasks = [r[2] for r in rows]
        assert "cop[tpu]" in tasks  # partial agg pushed to device
        names = "".join(r[0] for r in rows)
        assert "HashAgg" in names and "TableReader" in names

    def test_selection_pushdown(self, tsess):
        rows = q(tsess, "explain select a from t where b > 2.0")
        cop = [r for r in rows if r[2] == "cop[tpu]"]
        assert any("Selection" in r[0] for r in cop)

    def test_explain_analyze(self, tsess):
        rows = q(tsess, "explain analyze select count(*) from t")
        assert rows and len(rows[0]) == 5

    def test_est_rows_after_analyze(self, tsess):
        tsess.execute("analyze table t")
        rows = q(tsess, "explain select a from t where a > 2")
        reader = [r for r in rows if "TableReader" in r[0]][0]
        assert reader[1] != ""  # estRows populated from histogram


class TestUnionAndSubquery:
    def test_union_all(self, tsess):
        rows = q(tsess, "select a from t where a = 1 union all "
                        "select a from t where a = 1")
        assert rows == [(1,), (1,)]

    def test_union_distinct(self, tsess):
        rows = q(tsess, "select a from t where a = 1 union "
                        "select a from t where a = 1")
        assert rows == [(1,)]

    def test_from_subquery(self, tsess):
        rows = q(tsess, "select s.c, s.n from (select c, count(*) as n "
                        "from t group by c) s order by s.c")
        assert rows == [("x", 2), ("y", 1), ("z", 1)]

    def test_exists(self, tsess):
        assert q(tsess, "select count(*) from t where exists "
                        "(select 1 from t)") == [(4,)]


class TestPrepared:
    def test_prepare_execute(self, tsess):
        tsess.execute("prepare s1 from 'select a from t where a = 2'")
        assert tsess.execute("execute s1")[-1].rows == [(2,)]
        tsess.execute("deallocate prepare s1")


class TestEngineParity:
    """cpu oracle vs tpu(jax) engine must agree (SURVEY.md north star)."""

    QUERIES = [
        "select count(*), sum(a), min(b), max(b) from t",
        "select c, count(*), avg(b) from t group by c order by c",
        "select a, b from t where b > 2 and a is not null order by a",
        "select a from t order by b desc limit 2",
    ]

    def test_parity(self, tsess):
        for sql in self.QUERIES:
            tsess.execute("set tidb_use_tpu = 1")
            tpu_rows = q(tsess, sql)
            tsess.execute("set tidb_use_tpu = 0")
            cpu_rows = q(tsess, sql)
            assert tpu_rows == cpu_rows, sql


class TestPlanCache:
    """Repeated identical statements reuse their physical plan
    (planner/core/cache.go analog); DML/DDL invalidate."""

    def test_repeat_hits_and_invalidation(self):
        from tidb_tpu.metrics import REGISTRY
        from tidb_tpu.session import Domain

        s = Domain().new_session()
        s.execute("create table pc (a bigint, b bigint)")
        s.execute("insert into pc values (1, 2), (3, 4)")
        q = "select a, b from pc where a > 0 order by a"

        def delta(fn):
            b = REGISTRY.snapshot()
            fn()
            a = REGISTRY.snapshot()
            return (a.get("plan_cache_hits_total", 0)
                    - b.get("plan_cache_hits_total", 0))

        first = s.query(q)
        assert delta(lambda: s.query(q)) == 1  # second run hits
        assert s.query(q) == first
        # DML bumps data_version -> miss, then hits again
        s.execute("insert into pc values (5, 6)")
        assert delta(lambda: s.query(q)) == 0
        assert delta(lambda: s.query(q)) == 1
        # DDL bumps schema_version -> miss
        s.execute("alter table pc add column c bigint")
        assert delta(lambda: s.query(q)) == 0
        # ANALYZE bumps the stats epoch -> miss (join orders may change)
        s.execute("analyze table pc")
        assert delta(lambda: s.query(q)) == 0
        assert delta(lambda: s.query(q)) == 1
        # explicit txns never use the cache (dirty reads change pushdown)
        s.execute("begin")
        assert delta(lambda: s.query(q)) == 0
        s.execute("rollback")


def test_cost_routing_small_scan_to_host():
    """With the dispatch-cost knob set, a small scan routes to the host
    engine and EXPLAIN ANALYZE says so; a huge threshold never flips the
    flagship path when dispatch cost is zero."""
    import numpy as np

    from tidb_tpu.session import Domain

    d = Domain()
    s = d.new_session()
    s.execute("create table cr (a bigint)")
    t = d.catalog.info_schema().table("test", "cr")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(5000, dtype=np.int64)], ts=d.storage.current_ts())
    s.execute("set tidb_use_tpu = 1")
    s.execute("set tidb_opt_device_dispatch_us = 70000")
    rows = s.execute("explain analyze select count(*) from cr")[0].rows
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("cost-routed" in r[4] and "engine:cpu" in r[4]
               for r in readers), readers
    s.execute("set tidb_opt_device_dispatch_us = 0")
    rows = s.execute("explain analyze select count(*) from cr")[0].rows
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("engine:mesh" in r[4] for r in readers), readers


def test_admin_check_table_verifies_indexes():
    """ADMIN CHECK TABLE verifies existing index artifacts against current
    data and unique constraints over the full base+delta overlay
    (executor/admin.go CheckTable role)."""
    import pytest as _pytest

    from tidb_tpu.errors import ExecutorError
    from tidb_tpu.session import Domain

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table ac (a bigint primary key, b bigint)")
    s.execute("insert into ac values (1, 10), (2, 20), (3, 30)")
    t = d.catalog.info_schema().table("test", "ac")
    store = d.storage.table(t.id)
    store.compact(d.storage.current_ts())
    s.execute("create index ib on ac (b)")
    s.execute("admin check table ac")  # clean
    offs = tuple(t.col_offsets(["b"]))
    idx = store.indexes.get(store, offs)  # materialize the artifact
    idx.cols[0][0] = 999  # poison one key
    with _pytest.raises(ExecutorError):
        s.execute("admin check table ac")
    # unique violations hiding in the DELTA are caught too: sneak a
    # duplicate past the executor via the raw txn API
    s2 = d.new_session()
    s2.execute("create table uq (a bigint primary key)")
    s2.execute("insert into uq values (1)")
    t2 = d.catalog.info_schema().table("test", "uq")
    st2 = d.storage.table(t2.id)
    txn = d.storage.begin()
    txn.put(t2.id, st2.alloc_handle(), (1,))  # duplicate PK, no checks
    txn.commit()
    with _pytest.raises(ExecutorError):
        s2.execute("admin check table uq")
    # partitioned: per-store artifacts verified after compaction
    s.execute("create table pc (k bigint primary key)"
              " partition by hash (k) partitions 2")
    s.execute("insert into pc values (1), (2), (3)")
    tp = d.catalog.info_schema().table("test", "pc")
    for pd in tp.partition_info.defs:
        d.storage.table(pd.id).compact(d.storage.current_ts())
    s.execute("admin check table pc")


def test_tidb_snapshot_historical_read():
    """SET tidb_snapshot pins autocommit reads at a historical TSO
    (session.go setSnapshotTS): reads see the old state, writes refuse,
    clearing restores current reads."""
    import time as _time

    import pytest as _pytest

    from tidb_tpu.errors import TiDBTPUError
    from tidb_tpu.session import Domain

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table h (v bigint)")
    s.execute("insert into h values (1)")
    ts0 = d.storage.current_ts()
    _time.sleep(0.005)
    s.execute("insert into h values (2)")
    s.execute("update h set v = 99 where v = 1")
    s.execute(f"set tidb_snapshot = {ts0}")
    assert s.query("select v from h order by v") == [(1,)]
    with _pytest.raises(TiDBTPUError):
        s.execute("insert into h values (3)")
    s.execute("set tidb_snapshot = ''")
    assert sorted(s.query("select v from h")) == [(2,), (99,)]


def test_tidb_snapshot_schema_and_write_guards():
    """Historical reads use the schema of that time; every write statement
    (incl. EXPLAIN ANALYZE DML and DDL) refuses while pinned; bad values
    and in-transaction SETs are typed errors."""
    import time as _time

    import pytest as _pytest

    from tidb_tpu.errors import TiDBTPUError
    from tidb_tpu.session import Domain

    d = Domain()
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table h (v bigint)")
    s.execute("insert into h values (1)")
    _time.sleep(0.005)
    ts0 = d.storage.current_ts()
    _time.sleep(0.005)
    s.execute("create table later_t (x bigint)")
    s.execute(f"set tidb_snapshot = {ts0}")
    with _pytest.raises(TiDBTPUError):
        s.query("select * from later_t")  # didn't exist yet
    assert s.query("show tables") == [("h",)]
    for q in ("explain analyze insert into h values (9)",
              "drop table h", "analyze table h",
              "create table zzz (a bigint)"):
        with _pytest.raises(TiDBTPUError):
            s.execute(q)
    s.execute("explain select * from h")  # plain EXPLAIN is read-only
    with _pytest.raises(TiDBTPUError):
        s.execute("set tidb_snapshot = 'bogus'")
    s.execute("set tidb_snapshot = ''")
    s.execute("begin")
    with _pytest.raises(TiDBTPUError):
        s.execute(f"set tidb_snapshot = {ts0}")
    s.execute("rollback")
