"""Statistics subsystem tests (histograms, CM sketch, selectivity, ANALYZE)."""

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.statistics import CMSketch, FMSketch, Histogram


class TestHistogram:
    def test_build_and_bounds(self):
        v = np.arange(1000, dtype=np.float64)
        h = Histogram.build(v, null_count=10, n_buckets=16)
        assert h.total == 1000 and h.null_count == 10
        assert h.ndv == 1000
        assert abs(h.less_row_count(500) - 500) < 80
        assert h.between_row_count(100, 200) == pytest.approx(100, abs=80)

    def test_equal_row_count_skew(self):
        v = np.concatenate([np.zeros(900), np.arange(1, 101)]).astype(float)
        h = Histogram.build(v, n_buckets=8)
        assert h.equal_row_count(0.0) > 100  # repeat captures heavy hitter

    def test_empty(self):
        h = Histogram.build(np.zeros(0))
        assert h.row_count() == 0
        assert h.between_row_count(None, None) == 0.0


class TestSketches:
    def test_cmsketch(self):
        rng = np.random.default_rng(3)
        vals = rng.integers(0, 100, 10000, dtype=np.int64)
        cms = CMSketch()
        cms.insert_batch(vals)
        true = int((vals == 42).sum())
        assert abs(cms.query(42) - true) <= max(30, true * 0.3)

    def test_fmsketch(self):
        rng = np.random.default_rng(4)
        vals = rng.integers(0, 5000, 20000, dtype=np.int64)
        fm = FMSketch(max_size=1000)
        fm.insert_batch(vals)
        true_ndv = len(np.unique(vals))
        assert 0.4 * true_ndv < fm.ndv() < 2.5 * true_ndv


class TestAnalyze:
    @pytest.fixture()
    def sess(self):
        s = Domain().new_session()
        s.execute("create table t (a bigint, b double, c varchar(8))")
        rows = ",".join(
            f"({i % 50}, {i * 0.5}, 'k{i % 10}')" for i in range(500)
        )
        s.execute(f"insert into t values {rows}")
        return s

    def test_analyze_builds_stats(self, sess):
        sess.execute("analyze table t")
        t = sess.domain.catalog.info_schema().table("test", "t")
        st = sess.domain.stats.get(t.id)
        assert st is not None and st.row_count == 500
        assert st.columns[0].ndv == 50
        assert st.columns[2].ndv == 10  # dict codes

    def test_selectivity_drives_estimates(self, sess):
        sess.execute("analyze table t")
        rows = sess.query("explain select a from t where a < 10")
        reader = [r for r in rows if "TableReader" in r[0]][0]
        est = float(reader[1])
        assert 50 < est < 200  # true rows = 100

    def test_auto_analyze_after_churn(self, sess):
        sess.execute("analyze table t")
        t = sess.domain.catalog.info_schema().table("test", "t")
        v0 = sess.domain.stats.get(t.id).version
        big = ",".join(f"({i}, 1.0, 'z')" for i in range(400))
        sess.execute(f"insert into t values {big}")
        st = sess.domain.stats.get(t.id)
        assert st.version != v0  # auto-analyze refreshed after heavy churn

    def test_need_auto_analyze_flag(self, sess):
        t = sess.domain.catalog.info_schema().table("test", "t")
        # the insert in the fixture already triggered first-touch auto-analyze
        assert sess.domain.stats.get(t.id) is not None
        sess.domain.stats.drop(t.id)
        assert sess.domain.stats.need_auto_analyze(t.id)  # no stats, rows > 0
        sess.execute("analyze table t")
        assert not sess.domain.stats.need_auto_analyze(t.id)
