"""Stats sharpening (range intersection, index NDV, per-table plan cache)
and SQL plan management (bindinfo-lite).

Reference: statistics/selectivity.go (conjunct estimation),
statistics/index.go (index NDV), planner/core/cache.go (plan cache key),
bindinfo/handle.go:122,545 (bind-record match before planning)."""

import numpy as np
import pytest

from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()
    return dom


def _est(s, q):
    for r in s.execute("explain " + q)[0].rows:
        if "TableReader" in r[0] or "IndexLookUp" in r[0]:
            return float(r[1])
    return None


@pytest.fixture()
def loaded(d):
    s = d.new_session()
    s.execute("create table f (a bigint, b bigint, c bigint)")
    t = d.catalog.info_schema().table("test", "f")
    rng = np.random.default_rng(1)
    n = 50_000
    combo = rng.integers(0, 100, n)
    d.storage.table(t.id).bulk_load_arrays(
        [rng.integers(0, 1000, n), combo, combo * 7],
        ts=d.storage.current_ts())
    s.execute("create index iab on f (b, c)")
    s.execute("analyze table f")
    return s


def test_range_conjunction_intersects(loaded):
    """a > 100 AND a < 200 estimates as ONE interval (~5k of 50k), not as
    two independent quarter-selective conds."""
    e = _est(loaded, "select * from f where a > 100 and a < 200")
    assert 3000 < e < 8000, e


def test_correlated_eq_uses_index_ndv(loaded):
    """b and c are perfectly correlated (c = 7b, 100 combos); the (b,c)
    index NDV estimates ~500 rows where independence would say ~5."""
    e = _est(loaded, "select * from f where b = 5 and c = 35")
    assert 200 < e < 1500, e


def test_estimates_move_with_analyze(d):
    s = d.new_session()
    s.execute("create table g (a bigint)")
    t = d.catalog.info_schema().table("test", "g")
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(1000, dtype=np.int64)], ts=d.storage.current_ts())
    s.execute("analyze table g")
    e1 = _est(s, "select * from g where a < 100")
    d.storage.table(t.id).bulk_load_arrays(
        [np.zeros(9000, dtype=np.int64)], ts=d.storage.current_ts())
    s.execute("analyze table g")
    e2 = _est(s, "select * from g where a < 100")
    assert e2 > e1 * 5  # the new skew shows up in the estimate


def test_plan_cache_per_table_versions(d):
    s = d.new_session()
    s.execute("create table pa (x bigint)")
    s.execute("create table pb (y bigint)")
    s.execute("insert into pa values (1)")
    s.execute("insert into pb values (1)")
    s.query("select * from pa")

    def hits():
        return REGISTRY.snapshot().get("plan_cache_hits_total", 0)

    base = hits()
    s.query("select * from pa")
    assert hits() == base + 1  # repeat hits
    s.execute("insert into pb values (2)")  # unrelated DML
    s.query("select * from pa")
    assert hits() == base + 2  # survives
    s.execute("analyze table pb")  # unrelated ANALYZE
    s.query("select * from pa")
    assert hits() == base + 3  # survives
    s.execute("insert into pa values (2)")  # related DML
    s.query("select * from pa")
    assert hits() == base + 3  # invalidated (miss)
    assert s.query("select count(*) from pa") == [(2,)]


# ---------------------------------------------------------------------------
# bindinfo
# ---------------------------------------------------------------------------


@pytest.fixture()
def joined(d):
    s = d.new_session()
    s.execute("create table big (id bigint, v bigint)")
    s.execute("create table small (id bigint primary key, x bigint)")
    t = d.catalog.info_schema().table("test", "big")
    rng = np.random.default_rng(0)
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(20_000) % 500, rng.integers(0, 9, 20_000)],
        ts=d.storage.current_ts())
    s.execute("insert into small values " +
              ", ".join(f"({i},{i})" for i in range(500)))
    s.execute("analyze table big")
    s.execute("analyze table small")
    return s


_Q = ("select count(*) from big join small on big.id = small.id"
      " where small.x < 10")


def _ops(s, q):
    return [r[0] for r in s.execute("explain " + q)[0].rows]


def _default_join_op(ops):
    # agg-over-join now plans as the device broadcast join when eligible
    return any("HashJoin" in op or "DeviceJoinReader" in op for op in ops)


def test_binding_flips_join_algorithm(joined):
    s = joined
    assert _default_join_op(_ops(s, _Q))
    s.execute(f"create session binding for {_Q} using "
              f"select /*+ MERGE_JOIN */ count(*) from big join small"
              f" on big.id = small.id where small.x < 10")
    assert any("MergeJoin" in op for op in _ops(s, _Q))
    # literals normalize away: a different constant still matches
    q2 = _Q.replace("< 10", "< 7")
    assert any("MergeJoin" in op for op in _ops(s, q2))
    # execution uses the bound plan and stays correct
    assert s.query(_Q) == [(400,)]
    s.execute(f"drop session binding for {_Q}")
    assert _default_join_op(_ops(s, _Q))


def test_global_binding_and_show(joined, d):
    s = joined
    s.execute(f"create global binding for {_Q} using "
              f"select /*+ MERGE_JOIN */ count(*) from big join small"
              f" on big.id = small.id where small.x < 10")
    # a different session sees the global binding
    s2 = d.new_session()
    assert any("MergeJoin" in op for op in _ops(s2, _Q))
    rows = s.query("show bindings")
    assert rows and rows[0][2] == "global"
    s.execute(f"drop global binding for {_Q}")
    assert s.query("show bindings") == []


def test_binding_applies_to_for_join_using_clause(d):
    """JOIN ... USING (col) in the original must not confuse the USING
    splitter."""
    s = d.new_session()
    s.execute("create table u1 (k bigint)")
    s.execute("create table u2 (k bigint)")
    q = "select count(*) from u1 join u2 using (k)"
    s.execute(f"create session binding for {q} using "
              f"select /*+ MERGE_JOIN */ count(*) from u1 join u2 using (k)")
    assert any("MergeJoin" in op for op in _ops(s, q))


def test_compaction_deferred_under_open_snapshot(d):
    """Background compaction must not fold the delta while a transaction
    holds an older snapshot (it would see an empty table mid-txn)."""
    s = d.new_session()
    s.execute("create table sn (id bigint, v bigint)")
    t = d.catalog.info_schema().table("test", "sn")
    store = d.storage.table(t.id)
    txn = d.storage.begin()
    for i in range(5000):
        txn.put(t.id, store.alloc_handle(), (i, i))
    txn.commit()
    reader = d.new_session()
    reader.execute("begin")
    assert reader.query("select count(*) from sn") == [(5000,)]
    d.maintenance.tick()
    assert reader.query("select count(*) from sn") == [(5000,)]
    reader.execute("commit")
    d.maintenance.tick()
    assert len(store.delta) == 0  # folded once the snapshot closed


def test_index_join_toggle_invalidates_cache(d):
    s = d.new_session()
    s.execute("create table jb (id bigint, v bigint)")
    s.execute("create table js (id bigint primary key, x bigint)")
    s.execute("insert into js values (1,1)")
    s.execute("insert into jb values (1,1)")
    q = "select count(*) from js join jb on js.id = jb.id"
    s.query(q)
    s.query(q)  # cached
    s.execute("set tidb_opt_enable_index_join = 0")
    plan = [r[0] for r in s.execute("explain " + q)[0].rows]
    assert not any("IndexJoin" in x for x in plan), plan


def test_index_ndv_survives_auto_analyze_and_string_deltas(d):
    s = d.new_session()
    s.execute("create table ixs (a varchar(4), b varchar(4))")
    s.execute("insert into ixs values ('x','y'), ('x','y'), ('p','q')")
    s.execute("create index iab on ixs (a, b)")
    s.execute("analyze table ixs")
    tid = d.catalog.info_schema().table("test", "ixs").id
    assert list(d.stats.get(tid).index_ndv.values()) == [2]
    # heavy churn triggers auto-analyze; delta strings must encode into
    # the same dictionary domain as base codes (no double counting)
    s.execute("insert into ixs values " +
              ", ".join("('x','y')" for _ in range(10)))
    st = d.stats.get(tid)
    assert st.index_ndv and list(st.index_ndv.values()) == [2]


def test_baseline_capture_on_second_execution(joined, d):
    """tidb_capture_plan_baselines: the second sighting of a digest
    captures a GLOBAL binding pinning the current join plan
    (bindinfo/handle.go:545) — and a LITERAL VARIANT of the statement
    still executes ITS OWN literals (bindings carry hints, not text)."""
    s = joined
    s.execute("set tidb_capture_plan_baselines = 1")
    q = ("select count(*) from big join small on big.id = small.id"
         " where small.x < 10")
    try:
        s.query(q)
        assert s.query("show global bindings") == []
        assert s.query(q) == [(400,)]  # second sighting -> captured
        rows = s.query("show global bindings")
        assert rows and rows[0][2] == "global"
        assert "/*+" in rows[0][1]
        # literal variants share the digest; each returns its OWN answer
        truth3 = s.query("select count(*) from big join small"
                         " on big.id = small.id where small.x < 3"
                         " and 1 = 1")  # different digest: no binding
        got3 = s.query(q.replace("< 10", "< 3"))
        assert got3 == truth3 and got3 != [(400,)], (got3, truth3)
        # capture requires SUPER: a plain user's repeats don't publish
        s.execute("drop global binding for " + q)
        d.priv.create_user("lowpriv", "", False)
        lp = d.new_session()
        lp.user = "lowpriv@%"
        lp.execute("set tidb_capture_plan_baselines = 1")
        d.priv.grant("lowpriv", ["select"], "*.*")
        lp.query(q)
        lp.query(q)
        assert s.query("show global bindings") == []
    finally:
        s.execute("set tidb_capture_plan_baselines = 0")


def test_explicit_binding_rejects_mismatched_statement(joined):
    """CREATE BINDING validates the hinted text normalizes to the same
    digest as the original (handle.go CreateBindRecord)."""
    import pytest as _pytest

    from tidb_tpu.errors import TiDBTPUError

    s = joined
    with _pytest.raises(TiDBTPUError):
        s.execute(
            "create session binding for select count(*) from big using "
            "select /*+ MERGE_JOIN */ count(*) from small")


def test_json_conjunct_split_keeps_device_scan(d):
    """A JSON conjunct stays root-side while the numeric conjuncts of the
    same WHERE still run on the device mesh (round-4 weak #7 pinned)."""
    import numpy as np

    s = d.new_session()
    s.execute("create table js (a bigint, doc json)")
    t = d.catalog.info_schema().table("test", "js")
    docs = np.array(['{"k": %d}' % (i % 5) for i in range(5000)],
                    dtype=object)
    d.storage.table(t.id).bulk_load_arrays(
        [np.arange(5000), docs], ts=d.storage.current_ts())
    q = ("select count(*) from js"
         " where a < 2500 and json_extract(doc, '$.k') = 2")
    rows = s.execute("explain analyze " + q)[0].rows
    reader = next(r for r in rows if "TableReader" in r[0])
    assert "engine:mesh" in reader[-1], reader
    assert s.query(q) == [(500,)]
