"""Storage layer tests: blocks, dictionaries, MVCC/2PC, regions, faults.

Reference model: store/mockstore tests + store/tikv 2pc/lock-resolver tests.
"""

import numpy as np
import pytest

from tidb_tpu.errors import LockedError, RegionError, TxnConflictError
from tidb_tpu.store import BlockStorage, KeyRange
from tidb_tpu.store.fault import FAILPOINTS, failpoint, once
from tidb_tpu.store.txn import resolve_lock
from tidb_tpu.types import ty_float, ty_int, ty_string


@pytest.fixture
def storage():
    FAILPOINTS.clear()
    return BlockStorage(n_stores=4)


def make_table(storage, tid=1, n=100):
    ts = storage.create_table(tid, [("a", ty_int()), ("b", ty_float()), ("s", ty_string())])
    a = np.arange(n, dtype=np.int64)
    b = np.arange(n, dtype=np.float64) * 0.5
    s = np.array([f"v{i % 10}" for i in range(n)], dtype=object)
    ts.bulk_load_arrays([a, b, s], ts=0)
    return ts


def test_bulk_load_and_read(storage):
    t = make_table(storage)
    chunk = t.base_chunk([0, 1, 2], 0, 5)
    assert chunk.to_pylist()[0] == (0, 0.0, "v0")
    assert chunk.to_pylist()[4] == (4, 2.0, "v4")
    assert t.base_rows == 100


def test_dictionary_sorted_and_merge(storage):
    t = storage.create_table(9, [("s", ty_string())])
    t.bulk_load_arrays([np.array(["b", "a", "c"], dtype=object)])
    assert t.cols[0].dictionary == ["a", "b", "c"]
    # codes in block must be sorted-dictionary codes
    blk = t._blocks[0][0]
    assert blk.tolist() == [1, 0, 2]
    # second load with new values triggers remap
    t.bulk_load_arrays([np.array(["aa", "z"], dtype=object)])
    assert t.cols[0].dictionary == ["a", "aa", "b", "c", "z"]
    chunk = t.base_chunk([0], 0, 5)
    assert [r[0] for r in chunk.to_pylist()] == ["b", "a", "c", "aa", "z"]


def test_column_stats(storage):
    t = make_table(storage)
    lo, hi, has_null = t.column_stats(0)
    assert (lo, hi, has_null) == (0, 99, False)
    lo, hi, _ = t.column_stats(2)  # dict column: code range
    assert (lo, hi) == (0, 9)


def test_txn_commit_visibility(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.put(1, t.alloc_handle(), (100, 50.0, "new"))
    ts_before = storage.current_ts()
    commit_ts = txn.commit()
    assert commit_ts > txn.start_ts
    # invisible before commit_ts, visible after
    _, ins_before = t.delta_overlay(ts_before, 0, 1 << 62)
    assert ins_before == {}
    _, ins_after = t.delta_overlay(storage.current_ts(), 0, 1 << 62)
    assert list(ins_after.values()) == [(100, 50.0, "new")]


def test_txn_update_delete_overlay(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.put(1, 5, (5, 99.0, "upd"))  # update base row 5
    txn.delete(1, 7)
    txn.commit()
    ts = storage.current_ts()
    deleted, inserted = t.delta_overlay(ts, 0, 1 << 62)
    assert sorted(deleted) == [5, 7]
    assert inserted[5] == (5, 99.0, "upd")
    assert t.read_row(7, ts) is None
    assert t.read_row(5, ts) == (5, 99.0, "upd")
    assert t.read_row(3, ts) == (3, 1.5, "v3")


def test_write_conflict(storage):
    t = make_table(storage)
    t1 = storage.begin()
    t2 = storage.begin()
    t1.put(1, 3, (3, 0.0, "t1"))
    t2.put(1, 3, (3, 0.0, "t2"))
    t1.commit()
    with pytest.raises((TxnConflictError, LockedError)):
        t2.commit()


def test_lock_blocks_reader_until_resolved(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.put(1, 3, (3, 0.0, "locked"))
    # simulate prewrite done but the OWNER PROCESS dead: drop it from the
    # live-txn registry (a real crash restarts with an empty registry)
    storage.txn_finished(txn.start_ts)
    keys = sorted(txn.buffer.keys())
    primary = keys[0]
    for tid, h in keys:
        storage.table(tid).prewrite(h, "put", txn.buffer[(tid, h)].values,
                                    primary, txn.start_ts, ttl_ms=0)
    read_ts = storage.current_ts()
    with pytest.raises(LockedError):
        t.read_row(3, read_ts)
    # resolver rolls the orphan txn back (primary lock still present, expired)
    resolve_lock(storage, 1, 3)
    assert t.read_row(3, read_ts) == (3, 1.5, "v3")


def test_resolve_lock_rolls_forward_after_primary_commit(storage):
    t = make_table(storage)
    txn = storage.begin()
    h_new = t.alloc_handle()
    txn.put(1, 3, (3, 0.0, "A"))
    txn.put(1, h_new, (200, 1.0, "B"))
    storage.txn_finished(txn.start_ts)  # owner process died mid-commit
    keys = sorted(txn.buffer.keys())
    primary = keys[0]
    for tid, h in keys:
        storage.table(tid).prewrite(h, "put", txn.buffer[(tid, h)].values,
                                    primary, txn.start_ts, ttl_ms=0)
    commit_ts = storage.oracle.get_timestamp()
    t.commit(primary[1], txn.start_ts, commit_ts)  # primary committed only
    # secondary has an orphan lock; resolver must roll it FORWARD
    resolve_lock(storage, 1, keys[1][1])
    ts = storage.current_ts()
    assert t.read_row(keys[1][1], ts) is not None


def test_rollback(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.put(1, 3, (3, 0.0, "x"))
    txn.rollback()
    assert t.read_row(3, storage.current_ts()) == (3, 1.5, "v3")


def test_compact_folds_delta(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.delete(1, 0)
    txn.put(1, 50, (50, -1.0, "upd"))
    txn.put(1, t.alloc_handle(), (500, 5.0, "ins"))
    txn.commit()
    ts = storage.current_ts()
    t.compact(ts)
    assert t.delta == {}
    assert t.base_rows == 100  # 100 - 1 deleted + 1 inserted
    rows = t.base_chunk([0, 1, 2], 0, t.base_rows).to_pylist()
    assert (500, 5.0, "ins") in rows
    assert (0, 0.0, "v0") not in rows
    assert (50, -1.0, "upd") in rows


def test_regions_split_locate(storage):
    make_table(storage)
    storage.regions.split_even(1, 4, 100)
    regions = storage.regions.regions_of(1)
    assert len(regions) == 4
    assert [r.start for r in regions] == [0, 25, 50, 75]
    located = storage.regions.locate(KeyRange(1, 30, 80))
    assert [(r.start, c.start, c.end) for r, c in located] == [
        (25, 30, 50), (50, 50, 75), (75, 75, 80),
    ]


def test_region_epoch_error(storage):
    make_table(storage)
    r0 = storage.regions.regions_of(1)[0]
    storage.regions.split_at(1, [50])
    with pytest.raises(RegionError):
        storage.regions.check_epoch(r0.region_id, r0.epoch, 1)


def test_gc_drops_old_versions(storage):
    t = make_table(storage)
    for i in range(3):
        txn = storage.begin()
        txn.put(1, 5, (5, float(i), f"g{i}"))
        txn.commit()
    assert len(t.delta[5]) == 3
    safepoint = storage.current_ts()
    t.gc(safepoint)
    assert len(t.delta[5]) == 1
    assert t.read_row(5, storage.current_ts())[2] == "g2"


def test_2pc_failpoint_prewrite_conflict(storage):
    t = make_table(storage)
    txn = storage.begin()
    txn.put(1, 3, (3, 0.0, "x"))
    with failpoint("2pc/prewrite", once(TxnConflictError((1, 3)))):
        with pytest.raises(TxnConflictError):
            txn.commit()
        # locks must have been cleaned up
        assert t.locks == {}


def test_dict_encode_fast_path_type_safety():
    """Cross-type-equal objects (5 vs 5.0) must encode via str() like the
    slow path — never collapse into one dictionary entry."""
    import numpy as np

    from tidb_tpu.store.blockstore import TableStore
    from tidb_tpu.types import ty_string

    st = TableStore(1, [("s", ty_string())])
    arr = np.empty(4, dtype=object)
    arr[:] = [5, 5.0, "5", "5.0"]
    st.bulk_load_arrays([arr], ts=1)
    chunk = st.base_chunk([0], 0, 4)
    assert list(chunk.col(0).data) == ["5", "5.0", "5", "5.0"]
    assert st.cols[0].dictionary == ["5", "5.0"]


def test_dict_encode_high_cardinality_falls_back():
    import numpy as np

    from tidb_tpu.store.blockstore import TableStore
    from tidb_tpu.types import ty_string

    st = TableStore(1, [("s", ty_string())])
    arr = np.array([f"v{i:05d}" for i in range(5000)], dtype=object)
    st.bulk_load_arrays([arr], ts=1)
    assert len(st.cols[0].dictionary) == 5000
    assert list(st.base_chunk([0], 0, 3).col(0).data) == \
        ["v00000", "v00001", "v00002"]


def test_coded_ingest_validates_before_append():
    """A bad dictionary for a LATER column must not leave earlier columns
    with phantom blocks (torn store)."""
    import numpy as np
    import pytest as _pytest

    from tidb_tpu.errors import KVError
    from tidb_tpu.store.blockstore import TableStore
    from tidb_tpu.types import ty_int, ty_string

    st = TableStore(1, [("a", ty_int()), ("s", ty_string())])
    with _pytest.raises(KVError):
        st.bulk_load_arrays(
            [np.arange(4), np.array([0, 1, 2, 3], dtype=np.int32)],
            ts=1, dictionaries={1: ["b", "a"]})  # unsorted dict
    assert st.base_rows == 0
    assert all(not blocks for blocks in st._blocks)
    # valid coded ingest round-trips, merging with a later object load
    st.bulk_load_arrays(
        [np.arange(3), np.array([2, 0, 1], dtype=np.int32)],
        ts=1, dictionaries={1: ["a", "b", "c"]})
    arr = np.empty(2, dtype=object)
    arr[:] = ["b", "z"]
    st.bulk_load_arrays([np.arange(2), arr], ts=2)
    assert list(st.base_chunk([1], 0, 5).col(0).data) == \
        ["c", "a", "b", "b", "z"]
    assert st.cols[1].dictionary == ["a", "b", "c", "z"]
