"""Correlated subquery decorrelation tests (rule_decorrelate.go analog)."""

import pytest

from tidb_tpu.errors import PlanError
from tidb_tpu.session import Domain


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table orders (o_orderkey bigint, o_custkey bigint, "
              "o_total double)")
    s.execute("create table lineitem (l_orderkey bigint, l_qty bigint, "
              "l_price double)")
    s.execute("insert into orders values (1, 10, 100.0), (2, 20, 200.0), "
              "(3, 30, 300.0)")
    s.execute("insert into lineitem values (1, 5, 9.0), (1, 7, 8.0), "
              "(2, 40, 7.0)")
    return s


def test_correlated_exists(sess):
    assert sess.query(
        "select o_orderkey from orders where exists (select 1 from lineitem "
        "where l_orderkey = o_orderkey and l_qty > 6) order by o_orderkey"
    ) == [(1,), (2,)]


def test_correlated_not_exists(sess):
    assert sess.query(
        "select o_orderkey from orders where not exists (select 1 from "
        "lineitem where l_orderkey = o_orderkey) order by o_orderkey"
    ) == [(3,)]


def test_correlated_scalar_agg(sess):
    # o1: 100 > 10*(9+8)=170 no; o2: 200 > 70 yes; o3: no lineitems -> NULL
    assert sess.query(
        "select o_orderkey from orders where o_total > (select sum(l_price) "
        "* 10 from lineitem where l_orderkey = o_orderkey) "
        "order by o_orderkey"
    ) == [(2,)]


def test_correlated_scalar_in_derived_expr(sess):
    # o1: 100 > 15*avg(5,7)=90 yes; o2: 200 > 15*40=600 no; o3: NULL
    assert sess.query(
        "select o_orderkey from orders where o_total > (select 15 * "
        "avg(l_qty) from lineitem where l_orderkey = o_orderkey) "
        "order by o_orderkey"
    ) == [(1,)]


def test_correlated_in_equality(sess):
    assert sess.query(
        "select o_orderkey from orders where o_orderkey in (select "
        "l_orderkey from lineitem where l_orderkey = o_orderkey and "
        "l_qty > 6) order by o_orderkey"
    ) == [(1,), (2,)]


def test_non_equality_correlation_as_join_cond(sess):
    # qtys are 5,7,40: custkey 10 -> 5,7 qualify; 20 -> all; 30 -> all
    assert sess.query(
        "select o_orderkey from orders where exists (select 1 from "
        "lineitem where l_qty < o_custkey) order by o_orderkey"
    ) == [(1,), (2,), (3,)]
    assert sess.query(
        "select o_orderkey from orders where exists (select 1 from "
        "lineitem where l_qty > 3 * o_custkey) order by o_orderkey"
    ) == [(1,)]  # 40 > 30 only for custkey 10

    # correlated scalar aggs still demand equality correlation
    with pytest.raises(PlanError):
        sess.query(
            "select o_orderkey from orders where o_total > (select "
            "avg(l_price) from lineitem where l_qty < o_custkey)"
        )


def test_uncorrelated_paths_still_work(sess):
    assert sess.query(
        "select o_orderkey from orders where o_orderkey in "
        "(select l_orderkey from lineitem) order by o_orderkey"
    ) == [(1,), (2,)]
    assert sess.query(
        "select count(*) from orders where o_total > "
        "(select avg(o_total) from orders)"
    ) == [(1,)]


def test_tpch_q17_shape(sess):
    # 0.2 * avg quantity threshold against per-order lineitems
    rows = sess.query(
        "select sum(l_price) from lineitem, orders "
        "where l_orderkey = o_orderkey and l_qty < (select 10 + avg(l_qty) "
        "from lineitem where l_orderkey = o_orderkey)"
    )
    # o1 threshold 16: qty 5,7 pass (9+8); o2 threshold 50: qty 40 passes (7)
    assert rows[0][0] == pytest.approx(24.0)


def test_tpch_q21_shape(sess):
    rows = sess.query(
        "select o_orderkey from orders where exists (select 1 from lineitem "
        "where l_orderkey = o_orderkey and l_qty > 5) and not exists "
        "(select 1 from lineitem where l_orderkey = o_orderkey and "
        "l_qty > 30) order by o_orderkey"
    )
    assert rows == [(1,)]
