"""mysql.* system tables (internal-SQL surface) and LOCK/UNLOCK TABLES.

Reference: session/bootstrap.go (mysql.user/db/tables_priv/bind_info/
stats_meta bootstrap tables), ddl/table_lock.go + MySQL LOCK TABLES
semantics."""

import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.session import Domain


@pytest.fixture()
def d():
    dom = Domain()
    dom.maintenance.stop()
    return dom


def test_mysql_grant_tables_reflect_priv_state(d):
    s = d.new_session()
    s.execute("create user app identified by 'pw'")
    s.execute("grant select on test.t1 to app")
    s.execute("grant insert, delete on appdb.* to app")
    users = dict((u, p) for _h, u, _a, p in
                 s.query("select * from mysql.user"))
    assert users["root"] == "ALL"
    assert users["app"] == "USAGE"
    assert s.query("select db, priv from mysql.db where user = 'app'") == [
        ("appdb", "DELETE,INSERT")]
    assert s.query("select table_name, table_priv from mysql.tables_priv"
                   " where user = 'app'") == [("t1", "SELECT")]
    # passwords stored as stage2 hashes, never plaintext
    (auth,), = s.query("select authentication_string from mysql.user"
                       " where user = 'app'")
    assert auth and "pw" not in auth


def test_mysql_bind_info_and_stats_meta(d):
    s = d.new_session()
    s.execute("create table bt (a bigint)")
    s.execute("insert into bt values (1), (2)")
    s.execute("create global binding for select * from bt using"
              " select /*+ HASH_JOIN */ * from bt")
    assert s.query("select status from mysql.bind_info") == [("using",)]
    s.execute("analyze table bt")
    rows = s.query("select count from mysql.stats_meta")
    assert (2,) in rows


def test_mysql_tables_priv_protected(d):
    from tidb_tpu.errors import PrivilegeError

    s = d.new_session()
    s.execute("create user peek")
    peek = d.new_session()
    peek.user = "peek@%"
    with pytest.raises(PrivilegeError):
        peek.execute("select * from mysql.user")


def test_lock_tables_semantics(d):
    a, b = d.new_session(), d.new_session()
    a.execute("create table lt (x bigint)")
    a.execute("insert into lt values (1)")
    a.execute("create table other (y bigint)")
    a.execute("lock tables lt read")
    assert a.query("select * from lt") == [(1,)]
    with pytest.raises(TiDBTPUError):  # READ lock: owner can't write
        a.execute("insert into lt values (2)")
    with pytest.raises(TiDBTPUError):  # unlocked table inaccessible
        a.query("select * from other")
    assert b.query("select * from lt") == [(1,)]  # READ is shared
    with pytest.raises(TiDBTPUError):  # ...but blocks foreign writes
        b.execute("insert into lt values (3)")
    a.execute("unlock tables")
    a.execute("lock tables lt write")
    with pytest.raises(TiDBTPUError):  # WRITE excludes foreign reads
        b.query("select * from lt")
    a.execute("insert into lt values (9)")  # owner writes fine
    a.execute("unlock tables")
    assert sorted(b.query("select * from lt")) == [(1,), (9,)]


def test_lock_tables_released_by_relock(d):
    a = d.new_session()
    a.execute("create table r1 (x bigint)")
    a.execute("create table r2 (x bigint)")
    a.execute("lock tables r1 write")
    a.execute("lock tables r2 write")  # implicitly releases r1
    b = d.new_session()
    assert b.query("select * from r1") == []  # r1 free again
    with pytest.raises(TiDBTPUError):
        b.query("select * from r2")
    a.execute("unlock tables")


def test_shared_read_locks_track_owners(d):
    a, b, c = d.new_session(), d.new_session(), d.new_session()
    a.execute("create table sr (x bigint)")
    a.execute("insert into sr values (1)")
    a.execute("lock tables sr read")
    b.execute("lock tables sr read")  # shared
    b.execute("unlock tables")  # must not drop A's hold
    assert a.query("select * from sr") == [(1,)]
    with pytest.raises(TiDBTPUError):
        c.execute("insert into sr values (2)")
    a.execute("unlock tables")
    c.execute("insert into sr values (2)")  # free now


def test_foreign_lock_blocks_ddl(d):
    a, b = d.new_session(), d.new_session()
    a.execute("create table dl (x bigint)")
    a.execute("lock tables dl read")
    for q in ("drop table dl", "truncate table dl",
              "alter table dl add column y bigint",
              "create index i on dl (x)"):
        with pytest.raises(TiDBTPUError):
            b.execute(q)
    a.execute("unlock tables")
    b.execute("drop table dl")


def test_system_schemas_exempt_from_lock_tables(d):
    a = d.new_session()
    a.execute("create table ex (x bigint)")
    a.execute("lock tables ex read")
    assert a.query("select * from information_schema.tables")  # exempt
    assert a.query("select user from mysql.user where user = 'root'")
    a.execute("unlock tables")


# ---------------------------------------------------------------------------
# cluster/ops deep introspection + profiling (cluster_reader.go:42,
# util/profile roles)
# ---------------------------------------------------------------------------

def test_cluster_introspection_tables(d):
    s = d.new_session()
    cfg = s.query("select name, value from information_schema.cluster_config"
                  " where type = 'tidb-tpu'")
    assert any(n == "tidb_gc_life_time" for n, _ in cfg)
    hw = s.query("select * from information_schema.cluster_hardware")
    assert any(r[2] == "cpu" for r in hw)
    si = s.query("select name, value from"
                 " information_schema.cluster_systeminfo")
    names = {n for n, _ in si}
    assert "os" in names and "pid" in names


def test_engine_state_table_shows_cache(d):
    s = d.new_session()
    s.execute("create table eng (a bigint)")
    s.execute("insert into eng values " + ", ".join(
        f"({i})" for i in range(3000)))
    t = d.catalog.info_schema().table("test", "eng")
    d.storage.maybe_compact(t.id, threshold=0)  # rows -> base blocks
    s.query("select sum(a) from eng")  # warms the mesh column cache
    rows = s.query("select component, name, value from"
                   " information_schema.tidb_tpu_engine")
    comp = {r[0] for r in rows}
    assert "mesh" in comp and "column_cache" in comp and "programs" in comp
    entries = [r for r in rows
               if r[0] == "column_cache" and r[1] == "entries"]
    assert entries and int(entries[0][2]) >= 1
    # the per-entry rows expose the narrow wire dtype used for HBM/scan
    detail = [r for r in rows if r[0] == "column_cache"
              and r[1].startswith("store=")]
    assert detail and "dtype=" in detail[0][2]


def test_profiling_table(d):
    s = d.new_session()
    s.execute("create table pr (a bigint)")
    s.execute("insert into pr values (1), (2), (3)")
    assert s.query("select * from information_schema.tidb_profile") == []
    s.execute("set tidb_profiling = 1")
    for _ in range(3):
        s.query("select sum(a) from pr")
    prof = s.query("select function, calls, cum_time_ms from"
                   " information_schema.tidb_profile")
    assert prof, "profiler collected nothing"
    assert any("session.py" in r[0] or "execute" in r[0] for r in prof)
    assert all(r[1] >= 1 for r in prof)
    s.execute("set tidb_profiling = 0")
    assert s.query("select * from information_schema.tidb_profile") == []


def test_cluster_log_ring(d):
    import logging

    s = d.new_session()
    logging.getLogger("tidb_tpu.test").warning("hello ring %d", 42)
    rows = s.query("select level, message from"
                   " information_schema.cluster_log")
    assert any("hello ring 42" in m and lvl == "WARNING"
               for lvl, m in rows)
