"""TPC-H golden suite: the explaintest analog (SURVEY.md §4 carry-over).

Every query runs on BOTH engines — device (jax) and host oracle (numpy) —
and the result sets must be identical (the north-star's result-identity
requirement).  The schema/data recipe AND the 22-query corpus live in
tidb_tpu/tpch_data.py (shared with bench.py's `tpch_matrix` receipt so
the parity suite and the fused-fraction receipt can never drift apart).

Reference: cmd/explaintest/t/tpch.test (golden TPC-H plans).
"""

import pytest

from tidb_tpu.tpch_data import TPCH_QUERIES, build_tpch_domain


@pytest.fixture(scope="module")
def sess():
    return build_tpch_domain()


QUERIES = TPCH_QUERIES


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(v, 6) if isinstance(v, float) else v for v in r
        ))
    return out


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_parity(sess, name):
    sql = QUERIES[name]
    sess.execute("set tidb_use_tpu = 1")
    tpu = _norm(sess.query(sql))
    sess.execute("set tidb_use_tpu = 0")
    cpu = _norm(sess.query(sql))
    assert tpu == cpu, f"{name}: engine mismatch"
    if name not in ("q18", "q20", "q21"):
        # q18/q20/q21 can legitimately be empty at this scale factor
        assert len(tpu) > 0, f"{name}: empty result"


def test_tpch_covers_all_22(sess):
    """Every TPC-H query shape q1-q22 is present (VERDICT r2 item 6)."""
    have = {n.split("_")[0] for n in QUERIES}
    assert have == {f"q{i}" for i in range(1, 23)}, sorted(have)


def test_q1_plan_pushes_agg(sess):
    rows = sess.execute("explain " + QUERIES["q1"])[0].rows
    cop = [r for r in rows if r[2] == "cop[tpu]"]
    assert any("Aggregation" in r[0] for r in cop)
    assert any("Selection" in r[0] for r in cop)


def test_explain_analyze_names_engine(sess):
    """EXPLAIN ANALYZE attributes each scan to the engine that actually ran
    it; the flagship queries must report `mesh` (no silent fallback —
    VERDICT r2 weak #5)."""
    sess.execute("set tidb_use_tpu = 1")
    for name in ("q1", "q6"):
        rows = sess.execute("explain analyze " + QUERIES[name])[0].rows
        readers = [r for r in rows if "TableReader" in r[0]]
        assert readers, rows
        assert any("engine:mesh" in r[4] for r in readers), (name, readers)
    # the CPU engine honestly reports cpu
    sess.execute("set tidb_use_tpu = 0")
    rows = sess.execute("explain analyze " + QUERIES["q6"])[0].rows
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("engine:cpu" in r[4] for r in readers), readers
    sess.execute("set tidb_use_tpu = 1")


def test_mesh_reject_reason_surfaces(sess):
    """A query the mesh declines shows the reason in EXPLAIN ANALYZE
    instead of silently degrading."""
    sess.execute("set tidb_use_tpu = 1")
    # distinct agg is not device-pushable: mesh rejects at analysis
    # force a mesh-ineligible request: >4 disjoint ranges (the mesh
    # declines multi-range scans; the fan-out path serves them)
    import tidb_tpu.copr.jax_engine as je

    orig = je._Analyzed.__init__

    def reject(self, dag, table):
        from tidb_tpu.copr.jax_eval import JaxUnsupported

        raise JaxUnsupported("test-injected rejection")

    je._Analyzed.__init__ = reject
    try:
        rows = sess.execute(
            "explain analyze select count(*) from lineitem"
        )[0].rows
    finally:
        je._Analyzed.__init__ = orig
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("mesh rejected: test-injected rejection" in r[4]
               for r in readers), readers
