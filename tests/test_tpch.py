"""TPC-H golden suite: the explaintest analog (SURVEY.md §4 carry-over).

Every query runs on BOTH engines — device (jax) and host oracle (numpy) —
and the result sets must be identical (the north-star's result-identity
requirement).  Data is synthetic TPC-H-shaped at a tiny scale factor,
deterministic, loaded through the columnar bulk path with multi-region
splits so the DP fan-out is exercised.

Reference: cmd/explaintest/t/tpch.test (golden TPC-H plans).
"""

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.types.values import parse_date

N_LINE = 8000
N_ORDERS = 2000
N_CUST = 300
N_PART = 200
N_SUPP = 40
N_NATION = 25


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    rng = np.random.default_rng(1234)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base

    def load(name, ddl, arrays):
        s.execute(ddl)
        t = d.catalog.info_schema().table("test", name)
        store = d.storage.table(t.id)
        store.bulk_load_arrays(arrays, ts=d.storage.current_ts())
        d.storage.regions.split_even(t.id, 4, store.base_rows)
        return t

    load("nation", "create table nation (n_nationkey bigint, n_name "
         "varchar(25), n_regionkey bigint)", [
        np.arange(N_NATION, dtype=np.int64),
        np.array([f"NATION{i:02d}" for i in range(N_NATION)], dtype=object),
        rng.integers(0, 5, N_NATION, dtype=np.int64),
    ])
    load("region", "create table region (r_regionkey bigint, r_name "
         "varchar(25))", [
        np.arange(5, dtype=np.int64),
        np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"],
                 dtype=object),
    ])
    scomments = np.array(["quick brown fox", "Customer stuff Complaints",
                          "regular deposits", "silent Customer noise"],
                         dtype=object)
    load("supplier", "create table supplier (s_suppkey bigint, s_name "
         "varchar(25), s_nationkey bigint, s_acctbal decimal(12,2), "
         "s_comment varchar(40))", [
        np.arange(N_SUPP, dtype=np.int64),
        np.array([f"SUPP{i:04d}" for i in range(N_SUPP)], dtype=object),
        rng.integers(0, N_NATION, N_SUPP, dtype=np.int64),
        np.round(rng.uniform(-999, 9999, N_SUPP) * 100).astype(np.int64),
        scomments[rng.integers(0, 4, N_SUPP)],
    ])
    load("partsupp", "create table partsupp (ps_partkey bigint, ps_suppkey "
         "bigint, ps_availqty bigint, ps_supplycost decimal(12,2))", [
        np.repeat(np.arange(N_PART, dtype=np.int64), 4),
        rng.integers(0, N_SUPP, N_PART * 4, dtype=np.int64),
        rng.integers(1, 10000, N_PART * 4, dtype=np.int64),
        np.round(rng.uniform(1, 1000, N_PART * 4) * 100).astype(np.int64),
    ])
    phones = np.array([f"{cc}-555-{i:04d}" for i, cc in zip(
        range(N_CUST),
        np.array(["13", "31", "23", "29", "30", "18", "17", "44", "99"])[
            rng.integers(0, 9, N_CUST)])], dtype=object)
    load("customer", "create table customer (c_custkey bigint, c_name "
         "varchar(25), c_nationkey bigint, c_mktsegment varchar(10), "
         "c_acctbal decimal(12,2), c_phone varchar(15))", [
        np.arange(N_CUST, dtype=np.int64),
        np.array([f"CUST{i:05d}" for i in range(N_CUST)], dtype=object),
        rng.integers(0, N_NATION, N_CUST, dtype=np.int64),
        np.array(["BUILDING", "MACHINERY", "AUTOMOBILE", "HOUSEHOLD",
                  "FURNITURE"], dtype=object)[rng.integers(0, 5, N_CUST)],
        np.round(rng.uniform(-999, 9999, N_CUST) * 100).astype(np.int64),
        phones,
    ])
    load("part", "create table part (p_partkey bigint, p_name varchar(30), "
         "p_type varchar(25), p_size bigint, p_brand varchar(10))", [
        np.arange(N_PART, dtype=np.int64),
        np.array([f"PART{i:05d}" for i in range(N_PART)], dtype=object),
        np.array(["PROMO BRUSHED", "STANDARD POLISHED", "SMALL PLATED",
                  "MEDIUM BURNISHED"], dtype=object)[
            rng.integers(0, 4, N_PART)],
        rng.integers(1, 50, N_PART, dtype=np.int64),
        np.array([f"Brand#{i}" for i in range(1, 6)], dtype=object)[
            rng.integers(0, 5, N_PART)],
    ])
    odate = (base + rng.integers(0, span, N_ORDERS)).astype(np.int32)
    ocomments = np.array(["ordinary request", "special packed requests",
                          "pending special asks", "normal special requests",
                          "quiet commentary"], dtype=object)
    load("orders", "create table orders (o_orderkey bigint, o_custkey "
         "bigint, o_orderstatus varchar(1), o_totalprice decimal(15,2), "
         "o_orderdate date, o_orderpriority varchar(15), "
         "o_comment varchar(40))", [
        np.arange(N_ORDERS, dtype=np.int64),
        # leave the top 60 custkeys order-less so NOT IN subqueries hit
        rng.integers(0, N_CUST - 60, N_ORDERS, dtype=np.int64),
        np.array(["O", "F", "P"], dtype=object)[
            rng.integers(0, 3, N_ORDERS)],
        np.round(rng.uniform(1000, 400000, N_ORDERS) * 100).astype(np.int64),
        odate,
        np.array(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
                  "5-LOW"], dtype=object)[rng.integers(0, 5, N_ORDERS)],
        ocomments[rng.integers(0, 5, N_ORDERS)],
    ])
    okeys = rng.integers(0, N_ORDERS, N_LINE, dtype=np.int64)
    sdate = odate[okeys] + rng.integers(1, 120, N_LINE).astype(np.int32)
    cdate = sdate + rng.integers(-30, 30, N_LINE).astype(np.int32)
    rdate = sdate + rng.integers(1, 30, N_LINE).astype(np.int32)
    load("lineitem", "create table lineitem (l_orderkey bigint, l_partkey "
         "bigint, l_suppkey bigint, l_quantity decimal(15,2), "
         "l_extendedprice decimal(15,2), l_discount decimal(15,2), "
         "l_tax decimal(15,2), "
         "l_returnflag varchar(1), l_linestatus varchar(1), "
         "l_shipdate date, l_commitdate date, l_receiptdate date, "
         "l_shipmode varchar(10))", [
        okeys,
        rng.integers(0, N_PART, N_LINE, dtype=np.int64),
        rng.integers(0, N_SUPP, N_LINE, dtype=np.int64),
        rng.integers(100, 5100, N_LINE, dtype=np.int64),  # scaled .2
        np.round(rng.uniform(900, 105000, N_LINE) * 100).astype(np.int64),
        np.round(rng.uniform(0.0, 0.1, N_LINE) * 100).astype(np.int64),
        np.round(rng.uniform(0.0, 0.08, N_LINE) * 100).astype(np.int64),
        np.array(["A", "N", "R"], dtype=object)[rng.integers(0, 3, N_LINE)],
        np.array(["O", "F"], dtype=object)[rng.integers(0, 2, N_LINE)],
        sdate,
        cdate,
        rdate,
        np.array(["AIR", "MAIL", "SHIP", "TRUCK", "RAIL", "REG AIR",
                  "FOB"], dtype=object)[rng.integers(0, 7, N_LINE)],
    ])
    for t in ("lineitem", "orders", "customer"):
        s.execute(f"analyze table {t}")
    return s


QUERIES = {
    "q1": """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus""",
    "q3": """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate
order by revenue desc, o_orderkey
limit 10""",
    "q5": """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'
group by n_name order by revenue desc""",
    "q6": """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    "q10": """
select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01' and o_orderdate < date '1994-01-01'
  and l_returnflag = 'R'
group by c_custkey, c_name
order by revenue desc, c_custkey limit 20""",
    "q12": """
select l_shipmode,
       sum(case when o_orderpriority = '1-URGENT'
                  or o_orderpriority = '2-HIGH' then 1 else 0 end)
         as high_line_count,
       sum(case when o_orderpriority <> '1-URGENT'
                 and o_orderpriority <> '2-HIGH' then 1 else 0 end)
         as low_line_count
from orders join lineitem on o_orderkey = l_orderkey
where l_shipmode in ('MAIL', 'SHIP')
  and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
  and l_receiptdate >= date '1994-01-01'
  and l_receiptdate < date '1995-01-01'
group by l_shipmode order by l_shipmode""",
    "q13": """
select c_count, count(*) as custdist from (
  select c_custkey, count(o_orderkey) as c_count
  from customer left join orders on c_custkey = o_custkey
      and o_comment not like '%special%requests%'
  group by c_custkey
) c_orders
group by c_count
order by custdist desc, c_count desc limit 10""",
    "q14": """
select 100.00 * sum(case when p_type like 'PROMO%%'
                         then l_extendedprice * (1 - l_discount)
                         else 0 end) / sum(l_extendedprice * (1 - l_discount))
       as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'""",
    "q18": """
select c_custkey, o_orderkey, o_totalprice, sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem group by l_orderkey
    having sum(l_quantity) > 100
  )
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_custkey, o_orderkey, o_totalprice
order by o_totalprice desc, o_orderkey limit 10""",
    "q19": """
select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where p_partkey = l_partkey
  and ((p_size >= 1 and p_size <= 15 and l_quantity >= 1)
       or (p_size >= 16 and l_quantity >= 10))
  and l_shipdate >= date '1994-01-01'""",
    "q4": """
select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01' and o_orderdate < date '1993-10-01'
  and exists (select 1 from lineitem
              where l_orderkey = o_orderkey and l_shipdate > o_orderdate)
group by o_orderpriority order by o_orderpriority""",
    "q17": """
select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey and p_type = 'PROMO BRUSHED'
  and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                    where l_partkey = p_partkey)""",
    "q2": """
select s_acctbal, s_name, n_name, p_partkey, p_name
from part, supplier, partsupp, nation, region
where p_partkey = ps_partkey and s_suppkey = ps_suppkey
  and p_size < 25 and p_type like '%%POLISHED%%'
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'EUROPE'
  and ps_supplycost = (
    select min(ps_supplycost)
    from partsupp, supplier, nation, region
    where p_partkey = ps_partkey and s_suppkey = ps_suppkey
      and s_nationkey = n_nationkey and n_regionkey = r_regionkey
      and r_name = 'EUROPE')
order by s_acctbal desc, n_name, s_name, p_partkey limit 100""",
    "q7": """
select supp_nation, cust_nation, l_year, sum(volume) as revenue
from (
  select n1.n_name as supp_nation, n2.n_name as cust_nation,
         year(l_shipdate) as l_year,
         l_extendedprice * (1 - l_discount) as volume
  from supplier, lineitem, orders, customer, nation n1, nation n2
  where s_suppkey = l_suppkey and o_orderkey = l_orderkey
    and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
    and c_nationkey = n2.n_nationkey
    and ((n1.n_name = 'NATION01' and n2.n_name = 'NATION02')
         or (n1.n_name = 'NATION02' and n2.n_name = 'NATION01'))
    and l_shipdate between date '1995-01-01' and date '1996-12-31'
) shipping
group by supp_nation, cust_nation, l_year
order by supp_nation, cust_nation, l_year""",
    "q8": """
select o_year,
       sum(case when nation = 'NATION02' then volume else 0 end)
         / sum(volume) as mkt_share
from (
  select year(o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) as volume,
         n2.n_name as nation
  from part, supplier, lineitem, orders, customer, nation n1, nation n2,
       region
  where p_partkey = l_partkey and s_suppkey = l_suppkey
    and l_orderkey = o_orderkey and o_custkey = c_custkey
    and c_nationkey = n1.n_nationkey and n1.n_regionkey = r_regionkey
    and r_name = 'AMERICA' and s_nationkey = n2.n_nationkey
    and o_orderdate between date '1995-01-01' and date '1996-12-31'
    and p_type = 'STANDARD POLISHED'
) all_nations
group by o_year order by o_year""",
    "q9": """
select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation, year(o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount)
           - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey and p_partkey = l_partkey
    and o_orderkey = l_orderkey and s_nationkey = n_nationkey
    and p_name like '%%1%%'
) profit
group by nation, o_year
order by nation, o_year desc limit 30""",
    "q11": """
select ps_partkey, sum(ps_supplycost * ps_availqty) as value
from partsupp, supplier, nation
where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
  and n_name = 'NATION16'
group by ps_partkey
having sum(ps_supplycost * ps_availqty) > (
  select sum(ps_supplycost * ps_availqty) * 0.02
  from partsupp, supplier, nation
  where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
    and n_name = 'NATION16')
order by value desc""",
    "q15": """
select s_suppkey, s_name, total_revenue
from supplier, (
  select l_suppkey as supplier_no,
         sum(l_extendedprice * (1 - l_discount)) as total_revenue
  from lineitem
  where l_shipdate >= date '1996-01-01' and l_shipdate < date '1996-04-01'
  group by l_suppkey) revenue
where s_suppkey = supplier_no
  and total_revenue = (
    select max(total_revenue) from (
      select l_suppkey as supplier_no,
             sum(l_extendedprice * (1 - l_discount)) as total_revenue
      from lineitem
      where l_shipdate >= date '1996-01-01'
        and l_shipdate < date '1996-04-01'
      group by l_suppkey) r)
order by s_suppkey""",
    "q16": """
select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt
from partsupp, part
where p_partkey = ps_partkey and p_brand <> 'Brand#1'
  and p_type not like 'SMALL%%'
  and p_size in (1, 5, 10, 15, 20, 25, 30, 35)
  and ps_suppkey not in (
    select s_suppkey from supplier
    where s_comment like '%%Customer%%Complaints%%')
group by p_brand, p_type, p_size
order by supplier_cnt desc, p_brand, p_type, p_size limit 20""",
    "q20": """
select s_name, s_nationkey
from supplier, nation
where s_suppkey in (
    select ps_suppkey from partsupp
    where ps_partkey in (select p_partkey from part
                         where p_name like 'PART000%%')
      and ps_availqty > (
        select 0.5 * sum(l_quantity) from lineitem
        where l_partkey = ps_partkey and l_suppkey = ps_suppkey
          and l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1995-01-01'))
  and s_nationkey = n_nationkey and n_name = 'NATION03'
order by s_name""",
    "q21": """
select s_name, count(*) as numwait
from supplier, lineitem l1, orders, nation
where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
  and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
  and exists (select 1 from lineitem l2
              where l2.l_orderkey = l1.l_orderkey
                and l2.l_suppkey <> l1.l_suppkey)
  and not exists (select 1 from lineitem l3
                  where l3.l_orderkey = l1.l_orderkey
                    and l3.l_suppkey <> l1.l_suppkey
                    and l3.l_receiptdate > l3.l_commitdate)
  and s_nationkey = n_nationkey and n_name = 'NATION05'
group by s_name
order by numwait desc, s_name limit 100""",
    "q22": """
select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
from (
  select substring(c_phone, 1, 2) as cntrycode, c_acctbal
  from customer
  where substring(c_phone, 1, 2) in ('13', '31', '23', '29', '30', '18',
                                     '17')
    and c_acctbal > (
      select avg(c_acctbal) from customer
      where c_acctbal > 0.00
        and substring(c_phone, 1, 2) in ('13', '31', '23', '29', '30',
                                         '18', '17'))
    and not exists (select 1 from orders where o_custkey = c_custkey)
) custsale
group by cntrycode order by cntrycode""",
}


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(v, 6) if isinstance(v, float) else v for v in r
        ))
    return out


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_parity(sess, name):
    sql = QUERIES[name]
    sess.execute("set tidb_use_tpu = 1")
    tpu = _norm(sess.query(sql))
    sess.execute("set tidb_use_tpu = 0")
    cpu = _norm(sess.query(sql))
    assert tpu == cpu, f"{name}: engine mismatch"
    if name not in ("q18", "q20", "q21"):
        # q18/q20/q21 can legitimately be empty at this scale factor
        assert len(tpu) > 0, f"{name}: empty result"


def test_tpch_covers_all_22(sess):
    """Every TPC-H query shape q1-q22 is present (VERDICT r2 item 6)."""
    have = {n.split("_")[0] for n in QUERIES}
    assert have == {f"q{i}" for i in range(1, 23)}, sorted(have)


def test_q1_plan_pushes_agg(sess):
    rows = sess.execute("explain " + QUERIES["q1"])[0].rows
    cop = [r for r in rows if r[2] == "cop[tpu]"]
    assert any("Aggregation" in r[0] for r in cop)
    assert any("Selection" in r[0] for r in cop)


def test_explain_analyze_names_engine(sess):
    """EXPLAIN ANALYZE attributes each scan to the engine that actually ran
    it; the flagship queries must report `mesh` (no silent fallback —
    VERDICT r2 weak #5)."""
    sess.execute("set tidb_use_tpu = 1")
    for name in ("q1", "q6"):
        rows = sess.execute("explain analyze " + QUERIES[name])[0].rows
        readers = [r for r in rows if "TableReader" in r[0]]
        assert readers, rows
        assert any("engine:mesh" in r[4] for r in readers), (name, readers)
    # the CPU engine honestly reports cpu
    sess.execute("set tidb_use_tpu = 0")
    rows = sess.execute("explain analyze " + QUERIES["q6"])[0].rows
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("engine:cpu" in r[4] for r in readers), readers
    sess.execute("set tidb_use_tpu = 1")


def test_mesh_reject_reason_surfaces(sess):
    """A query the mesh declines shows the reason in EXPLAIN ANALYZE
    instead of silently degrading."""
    sess.execute("set tidb_use_tpu = 1")
    # distinct agg is not device-pushable: mesh rejects at analysis
    # force a mesh-ineligible request: >4 disjoint ranges (the mesh
    # declines multi-range scans; the fan-out path serves them)
    import tidb_tpu.copr.jax_engine as je

    orig = je._Analyzed.__init__

    def reject(self, dag, table):
        from tidb_tpu.copr.jax_eval import JaxUnsupported

        raise JaxUnsupported("test-injected rejection")

    je._Analyzed.__init__ = reject
    try:
        rows = sess.execute(
            "explain analyze select count(*) from lineitem"
        )[0].rows
    finally:
        je._Analyzed.__init__ = orig
    readers = [r for r in rows if "TableReader" in r[0]]
    assert any("mesh rejected: test-injected rejection" in r[4]
               for r in readers), readers
