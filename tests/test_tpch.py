"""TPC-H golden suite: the explaintest analog (SURVEY.md §4 carry-over).

Every query runs on BOTH engines — device (jax) and host oracle (numpy) —
and the result sets must be identical (the north-star's result-identity
requirement).  Data is synthetic TPC-H-shaped at a tiny scale factor,
deterministic, loaded through the columnar bulk path with multi-region
splits so the DP fan-out is exercised.

Reference: cmd/explaintest/t/tpch.test (golden TPC-H plans).
"""

import numpy as np
import pytest

from tidb_tpu.session import Domain
from tidb_tpu.types.values import parse_date

N_LINE = 8000
N_ORDERS = 2000
N_CUST = 300
N_PART = 200
N_SUPP = 40
N_NATION = 25


@pytest.fixture(scope="module")
def sess():
    d = Domain()
    s = d.new_session()
    rng = np.random.default_rng(1234)
    base = parse_date("1992-01-01")
    span = parse_date("1998-12-01") - base

    def load(name, ddl, arrays):
        s.execute(ddl)
        t = d.catalog.info_schema().table("test", name)
        store = d.storage.table(t.id)
        store.bulk_load_arrays(arrays, ts=d.storage.current_ts())
        d.storage.regions.split_even(t.id, 4, store.base_rows)
        return t

    load("nation", "create table nation (n_nationkey bigint, n_name "
         "varchar(25), n_regionkey bigint)", [
        np.arange(N_NATION, dtype=np.int64),
        np.array([f"NATION{i:02d}" for i in range(N_NATION)], dtype=object),
        rng.integers(0, 5, N_NATION, dtype=np.int64),
    ])
    load("supplier", "create table supplier (s_suppkey bigint, s_name "
         "varchar(25), s_nationkey bigint, s_acctbal double)", [
        np.arange(N_SUPP, dtype=np.int64),
        np.array([f"SUPP{i:04d}" for i in range(N_SUPP)], dtype=object),
        rng.integers(0, N_NATION, N_SUPP, dtype=np.int64),
        np.round(rng.uniform(-999, 9999, N_SUPP), 2),
    ])
    load("customer", "create table customer (c_custkey bigint, c_name "
         "varchar(25), c_nationkey bigint, c_mktsegment varchar(10), "
         "c_acctbal double)", [
        np.arange(N_CUST, dtype=np.int64),
        np.array([f"CUST{i:05d}" for i in range(N_CUST)], dtype=object),
        rng.integers(0, N_NATION, N_CUST, dtype=np.int64),
        np.array(["BUILDING", "MACHINERY", "AUTOMOBILE", "HOUSEHOLD",
                  "FURNITURE"], dtype=object)[rng.integers(0, 5, N_CUST)],
        np.round(rng.uniform(-999, 9999, N_CUST), 2),
    ])
    load("part", "create table part (p_partkey bigint, p_name varchar(30), "
         "p_type varchar(25), p_size bigint)", [
        np.arange(N_PART, dtype=np.int64),
        np.array([f"PART{i:05d}" for i in range(N_PART)], dtype=object),
        np.array(["PROMO BRUSHED", "STANDARD POLISHED", "SMALL PLATED",
                  "MEDIUM BURNISHED"], dtype=object)[
            rng.integers(0, 4, N_PART)],
        rng.integers(1, 50, N_PART, dtype=np.int64),
    ])
    odate = (base + rng.integers(0, span, N_ORDERS)).astype(np.int32)
    load("orders", "create table orders (o_orderkey bigint, o_custkey "
         "bigint, o_orderstatus varchar(1), o_totalprice double, "
         "o_orderdate date, o_orderpriority varchar(15))", [
        np.arange(N_ORDERS, dtype=np.int64),
        # leave the top 60 custkeys order-less so NOT IN subqueries hit
        rng.integers(0, N_CUST - 60, N_ORDERS, dtype=np.int64),
        np.array(["O", "F", "P"], dtype=object)[
            rng.integers(0, 3, N_ORDERS)],
        np.round(rng.uniform(1000, 400000, N_ORDERS), 2),
        odate,
        np.array(["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED",
                  "5-LOW"], dtype=object)[rng.integers(0, 5, N_ORDERS)],
    ])
    okeys = rng.integers(0, N_ORDERS, N_LINE, dtype=np.int64)
    sdate = odate[okeys] + rng.integers(1, 120, N_LINE).astype(np.int32)
    load("lineitem", "create table lineitem (l_orderkey bigint, l_partkey "
         "bigint, l_suppkey bigint, l_quantity decimal(15,2), "
         "l_extendedprice double, l_discount double, l_tax double, "
         "l_returnflag varchar(1), l_linestatus varchar(1), "
         "l_shipdate date)", [
        okeys,
        rng.integers(0, N_PART, N_LINE, dtype=np.int64),
        rng.integers(0, N_SUPP, N_LINE, dtype=np.int64),
        rng.integers(100, 5100, N_LINE, dtype=np.int64),  # scaled .2
        np.round(rng.uniform(900, 105000, N_LINE), 2),
        np.round(rng.uniform(0.0, 0.1, N_LINE), 2),
        np.round(rng.uniform(0.0, 0.08, N_LINE), 2),
        np.array(["A", "N", "R"], dtype=object)[rng.integers(0, 3, N_LINE)],
        np.array(["O", "F"], dtype=object)[rng.integers(0, 2, N_LINE)],
        sdate,
    ])
    for t in ("lineitem", "orders", "customer"):
        s.execute(f"analyze table {t}")
    return s


QUERIES = {
    "q1": """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus""",
    "q3": """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate
order by revenue desc, o_orderkey
limit 10""",
    "q5": """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'
group by n_name order by revenue desc""",
    "q6": """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    "q10": """
select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01' and o_orderdate < date '1994-01-01'
  and l_returnflag = 'R'
group by c_custkey, c_name
order by revenue desc, c_custkey limit 20""",
    "q12": """
select o_orderpriority, count(*) as order_count,
       sum(case when o_orderpriority = '1-URGENT' then 1 else 0 end) urgent
from orders join lineitem on o_orderkey = l_orderkey
where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01'
group by o_orderpriority order by o_orderpriority""",
    "q13": """
select c_count, count(*) as custdist from (
  select c_custkey, count(o_orderkey) as c_count
  from customer left join orders on c_custkey = o_custkey
  group by c_custkey
) c_orders
group by c_count
order by custdist desc, c_count desc limit 10""",
    "q14": """
select 100.00 * sum(case when p_type like 'PROMO%%'
                         then l_extendedprice * (1 - l_discount)
                         else 0 end) / sum(l_extendedprice * (1 - l_discount))
       as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'""",
    "q18": """
select c_custkey, o_orderkey, o_totalprice, sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem group by l_orderkey
    having sum(l_quantity) > 100
  )
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_custkey, o_orderkey, o_totalprice
order by o_totalprice desc, o_orderkey limit 10""",
    "q19": """
select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where p_partkey = l_partkey
  and ((p_size >= 1 and p_size <= 15 and l_quantity >= 1)
       or (p_size >= 16 and l_quantity >= 10))
  and l_shipdate >= date '1994-01-01'""",
    "q4": """
select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01' and o_orderdate < date '1993-10-01'
  and exists (select 1 from lineitem
              where l_orderkey = o_orderkey and l_shipdate > o_orderdate)
group by o_orderpriority order by o_orderpriority""",
    "q17": """
select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey and p_type = 'PROMO BRUSHED'
  and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                    where l_partkey = p_partkey)""",
    "q21_lite": """
select o_orderstatus, count(*) as waitcount
from orders
where exists (select 1 from lineitem
              where l_orderkey = o_orderkey and l_quantity > 30)
  and not exists (select 1 from lineitem
                  where l_orderkey = o_orderkey and l_quantity > 48)
group by o_orderstatus order by o_orderstatus""",
    "q22_lite": """
select c_mktsegment, count(*) as numcust, sum(c_acctbal) as totacctbal
from customer
where c_acctbal > (select avg(c_acctbal) from customer where c_acctbal > 0)
  and c_custkey not in (select o_custkey from orders)
group by c_mktsegment order by c_mktsegment""",
}


def _norm(rows):
    out = []
    for r in rows:
        out.append(tuple(
            round(v, 6) if isinstance(v, float) else v for v in r
        ))
    return out


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_parity(sess, name):
    sql = QUERIES[name]
    sess.execute("set tidb_use_tpu = 1")
    tpu = _norm(sess.query(sql))
    sess.execute("set tidb_use_tpu = 0")
    cpu = _norm(sess.query(sql))
    assert tpu == cpu, f"{name}: engine mismatch"
    if name not in ("q18",):
        assert len(tpu) > 0, f"{name}: empty result"


def test_q1_plan_pushes_agg(sess):
    rows = sess.execute("explain " + QUERIES["q1"])[0].rows
    cop = [r for r in rows if r[2] == "cop[tpu]"]
    assert any("Aggregation" in r[0] for r in cop)
    assert any("Selection" in r[0] for r in cop)
