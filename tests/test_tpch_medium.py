"""Medium-scale (1M-row) TPC-H parity with spill exercised.

VERDICT weak #7: the 8k-row parity suite proves engine-diff correctness but
never runs streaming/spill at sizes where they matter.  This module loads
1M lineitem rows once, asserts device/oracle parity on aggregation-heavy
shapes, and re-runs a grouping query under a memory quota small enough to
force hash-agg spill — results must match the unconstrained run."""

import numpy as np
import pytest

from tidb_tpu.tpch_data import build_lineitem

N = 1_000_000


@pytest.fixture(scope="module")
def sess():
    s = build_lineitem(N, regions=8)
    s.domain.maintenance.stop()
    return s


def _norm(rows):
    # 10 significant digits: float64 reduction order differs between the
    # mesh tree-sum and numpy's pairwise sum; last-ulp noise is expected
    out = []
    for r in rows:
        out.append(tuple(float(f"{v:.10g}") if isinstance(v, float) else v
                         for v in r))
    return out


def _parity(sess, sql):
    sess.execute("set tidb_use_tpu = 1")
    dev = _norm(sess.query(sql))
    sess.execute("set tidb_use_tpu = 0")
    cpu = _norm(sess.query(sql))
    sess.execute("set tidb_use_tpu = 1")
    assert dev == cpu, (sql, dev[:3], cpu[:3])
    return dev


def test_q1_parity_at_1m(sess):
    rows = _parity(sess, """
        select l_returnflag, l_linestatus,
               sum(l_quantity), sum(l_extendedprice),
               sum(l_extendedprice * (1 - l_discount)),
               avg(l_quantity), count(*)
        from lineitem
        where l_shipdate <= '1998-09-02'
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus""")
    assert len(rows) == 6  # 3 flags x 2 statuses
    assert sum(r[6] for r in rows) > 0.9 * N


def test_q6_parity_at_1m(sess):
    _parity(sess, """
        select sum(l_extendedprice * l_discount)
        from lineitem
        where l_shipdate >= '1994-01-01' and l_shipdate < '1995-01-01'
          and l_discount between 0.05 and 0.07 and l_quantity < 24""")


def test_high_ndv_group_parity_at_1m(sess):
    """~100k groups: exercises the streaming device->host merge path."""
    _parity(sess, """
        select l_orderkey % 100000 as k, count(*), sum(l_quantity)
        from lineitem group by k order by k limit 50""")


def test_spill_produces_identical_results(sess):
    """A grouping query under a tiny memory quota must spill (host
    partial/final pools) and still match the unconstrained answer."""
    sql = ("select l_orderkey % 50000 as k, count(*),"
           " sum(l_extendedprice) from lineitem group by k")
    sess.execute("set tidb_use_tpu = 0")  # host path owns the spill code
    sess.execute("set tidb_mem_quota_query = 0")
    sess.execute("set tidb_oom_action = 'spill'")
    free = sorted(_norm(sess.query(sql)))
    sess.execute("set tidb_mem_quota_query = 4000000")  # 4MB: forces spill
    spilled = sorted(_norm(sess.query(sql)))
    sess.execute("set tidb_mem_quota_query = 0")
    sess.execute("set tidb_use_tpu = 1")
    assert len(free) == 50_000
    assert spilled == free


def test_q3_shaped_join_parity_at_1m(sess):
    """Join + group + topN at 1M rows: the runtime-filter pushdown and
    keep-order merge paths under real volume."""
    sess.execute("set tidb_use_tpu = 1")
    sess.execute("create table if not exists ords"
                 " (o_orderkey bigint, o_date date)")
    t = sess.domain.catalog.info_schema().table("test", "ords")
    store = sess.domain.storage.table(t.id)
    if store.base_rows == 0:
        import numpy as np

        from tidb_tpu.types.values import parse_date

        rng = np.random.default_rng(4)
        n = 100_000
        base = parse_date("1992-01-01")
        sess.domain.storage.table(t.id).bulk_load_arrays(
            [rng.integers(0, 200_000, n),
             (base + rng.integers(0, 2000, n)).astype(np.int32)],
            ts=sess.domain.storage.current_ts())
    _parity(sess, """
        select o.o_orderkey, count(*), sum(l.l_quantity)
        from lineitem l join ords o on l.l_orderkey % 200000 = o.o_orderkey
        where o.o_date < '1995-01-01'
        group by o.o_orderkey
        order by sum(l.l_quantity) desc, o.o_orderkey limit 10""")
