"""Query tracing & slow-query subsystem (tidb_tpu/trace).

Tentpole coverage (ISSUE 4 acceptance):

- TRACE [FORMAT='row'|'json'] <stmt> returns a span tree over the
  session API with compile / transfer / device-execute / readback spans
  carrying nonzero durations and byte counts;
- the same query past tidb_slow_log_threshold appears in
  INFORMATION_SCHEMA.SLOW_QUERY with per-phase columns, on BOTH device
  paths (the one-program mesh engine and the per-tile fan-out engine);
- tracing disabled is strictly zero-cost: span() returns the no-op
  singleton and nothing is recorded;
- chaos: a slow-log writer killed mid-record neither corrupts
  SLOW_QUERY nor leaks a file handle, and recovery drops the torn tail
  (the delta-log torn-tail contract);
- satellites: XLA error text attributes device ordinals (PR-2 (b)).
"""

import json
import time

import numpy as np
import pytest

from tidb_tpu import trace as trace_mod
from tidb_tpu.metrics import REGISTRY
from tidb_tpu.session import Domain

N = 6000


def _mk_session(tmp_dir=None):
    d = Domain(data_dir=tmp_dir)
    d.maintenance.stop()
    s = d.new_session()
    s.execute("create table li (l_orderkey bigint, l_qty bigint,"
              " l_price double, l_flag varchar(1))")
    rng = np.random.default_rng(5)
    t = d.catalog.info_schema().table("test", "li")
    flags = np.array(list("ANR"), dtype=object)
    d.storage.table(t.id).bulk_load_arrays([
        rng.integers(0, 500, N),
        rng.integers(1, 50, N),
        rng.uniform(1.0, 999.0, N),
        flags[rng.integers(0, 3, N)],
    ], ts=d.storage.current_ts())
    s.execute("analyze table li")
    return d, s


@pytest.fixture(scope="module")
def env():
    return _mk_session()


Q1ISH = ("select l_flag, sum(l_qty), avg(l_price), count(*) from li"
         " where l_qty < 40 group by l_flag")


def _span_names(tr):
    names = []

    def walk(s):
        names.append(s.name)
        for c in s.children:
            walk(c)

    walk(tr.root)
    return names


def _spans_by_name(tr, name):
    out = []

    def walk(s):
        if s.name == name:
            out.append(s)
        for c in s.children:
            walk(c)

    walk(tr.root)
    return out


# ---------------------------------------------------------------------------
# TRACE statement surfaces
# ---------------------------------------------------------------------------


def test_trace_row_output_has_device_phases(env):
    d, s = env
    rs = s.execute("trace " + Q1ISH)[-1]
    assert rs.headers == ["operation", "startTS", "duration"]
    ops = [r[0].strip() for r in rs.rows]
    assert ops[0] == "session.execute"
    for needed in ("parse", "plan", "executor.next", "distsql.fanout"):
        assert any(o.startswith(needed) for o in ops), (needed, ops)
    # device phases with nonzero durations
    tr = s.last_trace
    for phase in ("copr.compile", "copr.transfer", "copr.device.execute",
                  "copr.readback"):
        assert _spans_by_name(tr, phase), (phase, _span_names(tr))
    xfer = _spans_by_name(tr, "copr.transfer")
    assert sum(sp.attrs.get("bytes", 0) for sp in xfer) > 0
    rb = _spans_by_name(tr, "copr.readback")
    assert sum(sp.attrs.get("bytes", 0) for sp in rb) > 0
    exe = _spans_by_name(tr, "copr.device.execute")
    assert any(sp.dur_ns > 0 for sp in exe)
    # indentation encodes the tree
    assert any(r[0].startswith("  ") for r in rs.rows)


def test_trace_json_output(env):
    d, s = env
    rs = s.execute("trace format='json' select count(*) from li")[-1]
    doc = json.loads(rs.rows[0][0])
    assert doc["root"]["name"] == "session.execute"
    names = json.dumps(doc)
    assert "distsql.fanout" in names and "plan" in names


def test_trace_bad_format_rejected(env):
    d, s = env
    from tidb_tpu.errors import TiDBTPUError

    with pytest.raises(TiDBTPUError):
        s.execute("trace format='yaml' select 1")


def test_compile_cache_hit_attributed(env):
    d, s = env
    sql = "select sum(l_price) from li where l_qty < 17"
    s.execute("trace " + sql)
    s.execute("trace " + sql)  # second run: program cache hit
    hits = [sp for sp in _spans_by_name(s.last_trace, "copr.compile")
            if sp.attrs and sp.attrs.get("cache") == "hit"]
    assert hits, "second run must record a compile cache hit span"


# ---------------------------------------------------------------------------
# SLOW_QUERY + statement summary on both device engines
# ---------------------------------------------------------------------------


def test_slow_query_populates_with_phase_columns(env):
    d, s = env
    s.execute("set tidb_slow_log_threshold = 0")
    try:
        s.query(Q1ISH)
    finally:
        s.execute("set tidb_slow_log_threshold = 300")
    rows = s.query(
        "select query, compile_ms, transfer_bytes, device_ms, readback_ms,"
        " engines, cop_tasks from information_schema.slow_query")
    mine = [r for r in rows if r[0] == Q1ISH]
    assert mine, rows
    q, compile_ms, xfer, device_ms, readback_ms, engines, tasks = mine[-1]
    assert compile_ms + device_ms + readback_ms > 0
    assert engines  # tpu / mesh attribution recorded
    # mesh path: transfer happened at least once (per-column sharded load)
    assert xfer >= 0


def test_slow_query_covers_tile_fanout_engine(env, monkeypatch):
    """Force the per-tile fan-out rung (mesh declined) and verify the
    same per-phase spans appear — 'both engines' acceptance."""
    d, s = env
    from tidb_tpu.copr import parallel

    monkeypatch.setattr(parallel, "try_run_mesh",
                        lambda *a, **k: None)
    sql = "select l_flag, min(l_price) from li group by l_flag"
    s.execute("trace " + sql)
    tr = s.last_trace
    fanout = _spans_by_name(tr, "distsql.fanout")
    assert fanout and fanout[0].attrs.get("scan_engine") == "tile-fanout"
    for phase in ("copr.transfer", "copr.readback"):
        assert _spans_by_name(tr, phase), (phase, _span_names(tr))
    assert (_spans_by_name(tr, "copr.compile")
            or _spans_by_name(tr, "copr.device.execute"))


def test_statement_summary_gains_phase_aggregates(env):
    d, s = env
    s.execute("set tidb_slow_log_threshold = 0")
    try:
        s.query("select count(l_qty) from li where l_qty < 33")
    finally:
        s.execute("set tidb_slow_log_threshold = 300")
    rows = s.query(
        "select digest_text, sum_device_ms, sum_compile_ms from"
        " information_schema.statements_summary"
        " where digest_text like '%count(l_qty)%'")
    assert rows and rows[0][1] + rows[0][2] >= 0


# ---------------------------------------------------------------------------
# zero-cost disabled mode
# ---------------------------------------------------------------------------


def test_disabled_mode_is_noop(env):
    d, s = env
    s.execute("set tidb_enable_slow_log = 0")
    try:
        before = len(trace_mod.TRACE_RING)
        s.query("select count(*) from li")
        assert len(trace_mod.TRACE_RING) == before  # nothing recorded
        # the hook itself degenerates to the no-op singleton
        assert trace_mod.span("anything") is trace_mod.NOOP
        assert not trace_mod.tracing_active()
    finally:
        s.execute("set tidb_enable_slow_log = 1")


def test_trace_statement_works_with_slow_log_disabled(env):
    d, s = env
    s.execute("set tidb_enable_slow_log = 0")
    try:
        rs = s.execute("trace select count(*) from li")[-1]
        ops = [r[0].strip() for r in rs.rows]
        assert any(o.startswith("distsql.fanout") for o in ops)
    finally:
        s.execute("set tidb_enable_slow_log = 1")


# ---------------------------------------------------------------------------
# chaos: slow-log writer killed mid-record (torn-tail recovery)
# ---------------------------------------------------------------------------


def _slowlog_fds() -> int:
    import os

    n = 0
    for fd in os.listdir("/proc/self/fd"):
        try:
            if os.readlink(f"/proc/self/fd/{fd}").endswith("slow_query.log"):
                n += 1
        except OSError:
            pass
    return n


def test_slow_log_torn_write_recovers(tmp_path):
    from tidb_tpu.store.fault import failpoint, once
    from tidb_tpu.trace.slowlog import SlowQueryLog

    d, s = _mk_session(str(tmp_path))
    s.execute("set tidb_slow_log_threshold = 0")
    s.query("select count(*) from li")  # one clean entry on disk
    n_ok = len(d.slow_log.entries())
    assert n_ok >= 1
    with failpoint("trace/slow_log_write", once(OSError("writer killed"))):
        # writer dies mid-record: the statement must still succeed and
        # the in-memory table stays consistent
        s.query("select sum(l_qty) from li")
    s.execute("set tidb_slow_log_threshold = 300")
    assert _slowlog_fds() == 0, "slow-log writer leaked a file handle"
    assert REGISTRY.snapshot().get("slow_log_write_errors_total", 0) >= 1
    # SLOW_QUERY (in-memory ring) not corrupted: still queryable
    rows = s.query("select query from information_schema.slow_query")
    assert len(rows) == len(d.slow_log.entries()) == n_ok + 1
    # a record written AFTER the torn one must not merge into it (the
    # failed append resyncs the stream with a terminating newline)
    s.execute("set tidb_slow_log_threshold = 0")
    s.query("select max(l_price) from li")
    s.execute("set tidb_slow_log_threshold = 300")
    # restart: recovery drops ONLY the torn record (resync'd mid-file,
    # so it counts under the corrupt-record metric), keeps clean entries
    # on both sides of it

    def _dropped():
        snap = REGISTRY.snapshot()
        return (snap.get("slow_log_torn_tail_total", 0)
                + snap.get("slow_log_corrupt_records_total", 0))

    d0 = _dropped()
    recovered = SlowQueryLog(str(tmp_path / "slow_query.log"))
    assert _dropped() == d0 + 1
    qs = [e["query"] for e in recovered.entries()]
    assert any("count(*)" in q for q in qs)       # pre-torn entry kept
    assert any("max(l_price)" in q for q in qs)   # post-torn entry kept
    assert not any("sum(l_qty)" in q for q in qs)  # torn record dropped
    assert all("query" in e for e in recovered.entries())


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


def test_xla_error_text_attributes_device_ids():
    """ROADMAP PR-2 (b): real XLA/jaxlib error shapes resolve to device
    ordinals so the RIGHT breaker trips instead of a blind retry."""
    from tidb_tpu.copr.device_health import attribute_devices

    cases = [
        ("XlaRuntimeError: INTERNAL: failed to enqueue program on "
         "TPU:3 (core halted)", (3,)),
        ("jaxlib.xla_extension.XlaRuntimeError: DATA_LOSS: device "
         "ordinal 2 lost", (2,)),
        ("RuntimeError: /device:TPU:1 unreachable", (1,)),
        ("INTERNAL: TpuDevice(id=7) returned DataLoss", (7,)),
        ("collective abort on chip 0 and chip 4", (0, 4)),
        ("RESOURCE_EXHAUSTED: out of memory on device 5", (5,)),
        ("some unattributable failure", ()),
    ]
    for msg, want in cases:
        assert attribute_devices(RuntimeError(msg)) == want, msg


def test_backoff_wait_lands_in_trace(env):
    d, s = env
    from tidb_tpu.store.fault import failpoint, once

    with failpoint("distsql/task_error", once(RuntimeError("transient"))):
        s.execute("trace select count(*) from li where l_qty < 7")
    tr = s.last_trace
    tasks = _spans_by_name(tr, "cop.task")
    # the mesh path may absorb the scan; only assert when fan-out ran
    if tasks:
        assert any((sp.attrs or {}).get("backoff_ms", 0) > 0
                   for sp in tasks)
    assert tr.phase_totals()["backoff_ms"] >= 0


def test_2pc_spans_recorded(env):
    d, s = env
    s.execute("create table if not exists w (a bigint primary key,"
              " b bigint)")
    s.execute("insert into w values (1, 10), (2, 20)")
    tr = s.last_trace  # BEFORE any further statement replaces it
    assert _spans_by_name(tr, "txn.prewrite")
    assert _spans_by_name(tr, "txn.commit")


def test_trace_ring_feeds_status_surface(env):
    d, s = env
    s.query("select count(*) from li")
    assert len(trace_mod.TRACE_RING) > 0
    tr = list(trace_mod.TRACE_RING)[-1]
    tot = tr.phase_totals()
    assert set(tot) >= {"compile_ms", "transfer_bytes", "device_ms",
                        "readback_ms", "backoff_ms", "engines"}


# ---------------------------------------------------------------------------
# continuous profiling + SLO plane (ISSUE 13)
# ---------------------------------------------------------------------------


def test_profiler_folds_finished_traces(env):
    d, s = env
    from tidb_tpu.trace import PROFILER

    f0 = REGISTRY.get("profile_traces_folded_total")
    s.query(Q1ISH)
    assert REGISTRY.get("profile_traces_folded_total") == f0 + 1
    folded = PROFILER.folded()
    assert folded.strip()
    stacks = dict(ln.rsplit(" ", 1) for ln in folded.strip().splitlines())
    assert any(st.startswith("session.execute") for st in stacks)
    # engine attribution rides the frames (compiled vs interpreted path)
    assert any(":" in st for st in stacks), stacks


def test_profiler_chains_export_hook(env):
    """The profiler hook CHAINS onto the recorder export chain — a
    directly-installed forwarder (the coord seam) and the profiler both
    see every finished trace, and unchaining is list-removal: either
    participant can leave without dropping the other."""
    from tidb_tpu.trace import Profiler, recorder

    d, s = env
    seen = []

    def forwarder(tr):
        seen.append(tr.sql)

    prev = recorder.TRACE_EXPORT_HOOK
    recorder.TRACE_EXPORT_HOOK = forwarder  # direct install (third party)
    p = Profiler(enabled=True)
    try:
        p.install()  # adopts the direct hook into the chain
        s.query("select count(*) from li")
        assert seen and "count(*)" in seen[-1]  # forwarder still ran
        assert p.folded().strip()               # and the profiler folded
        # list-removal semantics: the forwarder leaves mid-chain while
        # the profiler (chained AFTER it) keeps running
        recorder.unchain_export_hook(forwarder)
        n = len(seen)
        s.query("select count(*) from li")
        assert len(seen) == n  # forwarder gone, regardless of order
    finally:
        recorder.unchain_export_hook(forwarder)
        recorder.unchain_export_hook(p.fold)
        recorder.TRACE_EXPORT_HOOK = prev


def test_profiler_disabled_paths_are_noop(env):
    d, s = env
    from tidb_tpu.trace import PROFILER

    # tracing disabled: nothing reaches the export hook, and the span
    # seam degenerates to the no-op singleton (one contextvar read)
    s.execute("set tidb_enable_slow_log = 0")
    try:
        f0 = REGISTRY.get("profile_traces_folded_total")
        s.query("select count(*) from li")
        assert REGISTRY.get("profile_traces_folded_total") == f0
        assert trace_mod.span("anything") is trace_mod.NOOP
    finally:
        s.execute("set tidb_enable_slow_log = 1")
    # profiler disabled: traces still record, the fold is a no-op
    prev = PROFILER.enabled
    PROFILER.enabled = False
    try:
        PROFILER.reset()
        f0 = REGISTRY.get("profile_traces_folded_total")
        s.query("select count(*) from li")
        assert REGISTRY.get("profile_traces_folded_total") == f0
        assert PROFILER.folded() == ""
    finally:
        PROFILER.enabled = prev


def test_stmt_class_and_latency_histograms(env):
    from tidb_tpu.trace import stmt_class

    assert stmt_class("select * from t where a = 1") == "point"
    assert stmt_class("SELECT sum(a) FROM t") == "agg"
    assert stmt_class("select a from t group by a") == "agg"
    assert stmt_class("select * from a join b on a.x = b.x") == "join"
    assert stmt_class("insert into t values (1)") == "dml"
    assert stmt_class("update t set a = 1") == "dml"
    assert stmt_class("show tables") == "other"
    d, s = env
    h0 = (REGISTRY.hist_stats("stmt_latency_agg_ms") or
          {"count": 0})["count"]
    s.query("select count(*) from li")
    assert REGISTRY.hist_stats("stmt_latency_agg_ms")["count"] == h0 + 1


def test_explain_analyze_reports_hbm_peak(env):
    """Device-memory telemetry (ISSUE 13): EXPLAIN ANALYZE surfaces the
    statement's HBM high-water mark stamped on the execute spans."""
    d, s = env
    s.query(Q1ISH)  # warm the mesh cache so resident bytes are nonzero
    rs = s.execute("explain analyze " + Q1ISH)[-1]
    extra = rs.rows[0][4]
    assert "hbm_peak:" in extra, rs.rows
    peak = int(extra.split("hbm_peak:")[1].split()[0])
    assert peak > 0


# ---------------------------------------------------------------------------
# slow-log rotation (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


def test_slow_log_rotation_caps_size(tmp_path):
    import os

    from tidb_tpu.trace.slowlog import SlowQueryLog

    path = str(tmp_path / "slow_query.log")
    log = SlowQueryLog(path, max_bytes=500, keep=2)
    r0 = REGISTRY.get("slow_log_rotations_total")
    for i in range(40):
        log.record({"query": f"q{i}", "time": "t", "conn_id": i})
    assert REGISTRY.get("slow_log_rotations_total") > r0
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".3")  # keep=2 drops older files
    assert os.path.getsize(path) <= 500 + 128  # one record past the cap
    assert len(log.entries()) == 40  # the in-memory ring is unaffected
    # torn-tail recovery still honored on the ACTIVE file post-rotation
    with open(path, "ab") as f:
        f.write(b'{"query": "torn-tail')
    t0 = REGISTRY.get("slow_log_torn_tail_total")
    recovered = SlowQueryLog(path)
    assert REGISTRY.get("slow_log_torn_tail_total") == t0 + 1
    assert all("torn-tail" not in e.get("query", "")
               for e in recovered.entries())


def test_slow_log_rotation_rides_global_sysvar(tmp_path):
    d, s = _mk_session(str(tmp_path))
    s.execute("set global tidb_tpu_slow_log_max_bytes = 400")
    s.execute("set tidb_slow_log_threshold = 0")
    r0 = REGISTRY.get("slow_log_rotations_total")
    try:
        for _ in range(4):
            s.query("select count(*) from li")
    finally:
        s.execute("set tidb_slow_log_threshold = 300")
    assert REGISTRY.get("slow_log_rotations_total") > r0
    import os

    assert os.path.exists(str(tmp_path / "slow_query.log.1"))


def test_profiler_persists_windows_across_restart(env, tmp_path):
    """ISSUE 17 trace (b): windows persist atomically on rotation and a
    fresh Profiler (the restarted process) restores them at install —
    /flame survives a rolling restart instead of starting cold."""
    import os.path

    from tidb_tpu.trace import Profiler, recorder

    d, s = env
    pdir = str(tmp_path / "prof")
    p = Profiler(enabled=True, window_s=0.01, persist_dir=pdir)
    s.query(Q1ISH)
    p.fold(s.last_trace)
    time.sleep(0.02)
    s.query(Q1ISH)
    p.fold(s.last_trace)  # rotates -> persists the closed window
    assert os.path.exists(os.path.join(pdir, "profile_windows.json"))
    before = p.folded()
    assert before.strip()

    # "restart": a new profiler over the same dir restores the windows
    p2 = Profiler(enabled=True, window_s=0.01, persist_dir=pdir)
    try:
        p2.install()
        assert p2.folded().strip()
        assert set(p2.folded().splitlines()) & set(before.splitlines())
        sec = p2.status_section()
        assert sec["windows"], "restored windows missing from /status"
    finally:
        recorder.unchain_export_hook(p2.fold)

    # persist_now drains the live window unconditionally (graceful stop)
    p.persist_now()
    p3 = Profiler(enabled=True, window_s=0.01, persist_dir=pdir)
    try:
        p3.install()
        assert p3.folded().strip()
    finally:
        recorder.unchain_export_hook(p3.fold)


def test_profiler_torn_persist_file_starts_fresh(env, tmp_path):
    from tidb_tpu.trace import Profiler, recorder

    pdir = tmp_path / "prof"
    pdir.mkdir()
    (pdir / "profile_windows.json").write_text('{"windows": [{"bad"')
    p = Profiler(enabled=True, persist_dir=str(pdir))
    try:
        p.install()  # torn/foreign file: fresh start, no raise
        assert p.folded() == ""
    finally:
        recorder.unchain_export_hook(p.fold)
