import datetime

from tidb_tpu.types import (
    TypeKind,
    common_arith_type,
    common_compare_type,
    merge_types,
    parse_date,
    parse_datetime,
    ty_decimal,
    ty_float,
    ty_int,
    ty_string,
    ty_null,
    decimal_round_half_up,
)
from tidb_tpu.types.values import days_to_date, format_date, micros_to_datetime


def test_date_roundtrip():
    d = parse_date("1998-09-02")
    assert days_to_date(d) == datetime.date(1998, 9, 2)
    assert format_date(d) == "1998-09-02"
    assert parse_date("19980902") == d


def test_datetime_parse():
    us = parse_datetime("1998-09-02 12:30:15")
    assert micros_to_datetime(us) == datetime.datetime(1998, 9, 2, 12, 30, 15)
    assert parse_datetime("1998-09-02") == parse_date("1998-09-02") * 86_400_000_000


def test_arith_types():
    assert common_arith_type(ty_int(), ty_int()).kind == TypeKind.INT
    assert common_arith_type(ty_int(), ty_float()).kind == TypeKind.FLOAT
    t = common_arith_type(ty_decimal(10, 2), ty_int())
    assert t.kind == TypeKind.DECIMAL and t.scale == 2
    assert common_arith_type(ty_string(), ty_int()).kind == TypeKind.FLOAT


def test_compare_types():
    assert common_compare_type(ty_int(), ty_float()).kind == TypeKind.FLOAT
    assert common_compare_type(ty_string(), ty_string()).kind == TypeKind.STRING
    assert common_compare_type(ty_null(), ty_int()).kind == TypeKind.INT


def test_merge_types_nullability():
    t = merge_types(ty_int(nullable=False), ty_null())
    assert t.kind == TypeKind.INT and t.nullable


def test_decimal_round():
    assert decimal_round_half_up(12345, 2) == 123
    assert decimal_round_half_up(12350, 2) == 124  # half away from zero
    assert decimal_round_half_up(-12350, 2) == -124
