"""MySQL type-surface depth: wide DECIMAL (exact past 18 digits),
TIME/ENUM/SET/BIT storage + compare, JSON-lite functions.

Reference: types/mydecimal.go (65-digit exact decimal FromString/Add/Mul/
Div with half-away-from-zero rounding), types/time.go (Duration),
types/etc.go (ENUM/SET), types/json/binary.go (path extraction).

Design under test (field_type.py): precision <= 18 stays scaled int64 — the
device-shaped fast path; wider declarations store exact Python ints in
object arrays and evaluate host-side, with runtime escalation in builtins
(_mul_safe/_add_safe/_div_round) so narrow columns never silently wrap."""

import decimal
import random

import numpy as np
import pytest

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.chunk.codec import decode_chunk, encode_chunk
from tidb_tpu.session import Domain
from tidb_tpu.types import ty_decimal, ty_enum, ty_json, ty_time

decimal.getcontext().prec = 200
Q = decimal.Decimal


@pytest.fixture()
def s():
    return Domain().new_session()


def _rows(sess, sql):
    return sess.execute(sql)[-1].rows


# ---------------------------------------------------------------------------
# wide decimal
# ---------------------------------------------------------------------------


def test_wide_decimal_roundtrip(s):
    s.execute("create table w (v decimal(40,10))")
    lit = "99999999999999999999999999999.9999999999"
    s.execute(f"insert into w values ({lit}), (-{lit}), (0.0000000001)")
    got = [Q(str(r[0])) for r in _rows(s, "select v from w")]
    assert sorted(got) == sorted([Q(lit), -Q(lit), Q("1e-10")])


def test_wide_decimal_property_vs_python_decimal(s):
    """mydecimal.go parity: +,-,*,/ exact against Python Decimal."""
    s.execute("create table pw (a decimal(38,6), b decimal(38,6))")
    random.seed(11)
    rows = []
    for _ in range(250):
        a = Q(random.randint(-10**31, 10**31)).scaleb(-6)
        b = Q(random.randint(1, 10**30)).scaleb(-6)
        rows.append((a, b))
    s.execute("insert into pw values " +
              ", ".join(f"({a}, {b})" for a, b in rows))
    got = s.query("select a + b, a - b, a * b, a / b from pw")
    for (ga, gs, gm, gd), (a, b) in zip(got, rows):
        assert Q(str(ga)) == a + b
        assert Q(str(gs)) == a - b
        assert Q(str(gm)) == a * b  # scale 12 holds the exact product
        exp = (a / b).quantize(Q("1e-10"), rounding=decimal.ROUND_HALF_UP)
        assert Q(str(gd)) == exp


def test_narrow_decimal_no_silent_wrap(s):
    """The 18-digit int64 cap must escalate, not wrap (VERDICT weak #4)."""
    s.execute("create table nw (a decimal(18,0), b decimal(18,0))")
    big = 10**17 * 9  # near int64 ceiling
    s.execute(f"insert into nw values ({big}, {big})")
    (prod,), = s.query("select a * b from nw")
    assert Q(str(prod)) == Q(big) * Q(big)  # would be garbage if wrapped
    (tot,), = s.query("select a + b from nw")
    assert Q(str(tot)) == Q(big) * 2


def test_wide_decimal_sum_exact(s):
    s.execute("create table sw (v decimal(38,2))")
    vals = [10**30 + i for i in range(7)]
    s.execute("insert into sw values " +
              ", ".join(f"({v}.25)" for v in vals))
    (got,), = s.query("select sum(v) from sw")
    exp = sum(Q(f"{v}.25") for v in vals)
    assert Q(str(got)) == exp


def test_wide_decimal_compare_and_group(s):
    s.execute("create table cw (v decimal(30,0), k bigint)")
    s.execute("insert into cw values (100000000000000000000000, 1),"
              " (100000000000000000000001, 2),"
              " (100000000000000000000001, 3)")
    assert _rows(s, "select k from cw where v > 100000000000000000000000"
                 " order by k") == [(2,), (3,)]
    got = sorted(_rows(s, "select v, count(*) from cw group by v"))
    assert [g[1] for g in got] == [1, 2]


def test_decimal_literal_exactness(s):
    """INSERT literal -> readback with no float round-trip anywhere."""
    s.execute("create table lx (v decimal(35,5))")
    lit = "123456789012345678901234567890.12345"
    s.execute(f"insert into lx values ({lit})")
    (got,), = s.query("select v from lx")
    assert Q(str(got)) == Q(lit)


def test_division_rounds_half_away_from_zero(s):
    s.execute("create table dr (a decimal(10,0), b decimal(10,0))")
    s.execute("insert into dr values (5, 2), (-5, 2), (1, 3)")
    got = s.query("select a / b from dr")
    # scale = 0 + 4 -> 2.5000, -2.5000, 0.3333
    assert [float(x[0]) for x in got] == [2.5, -2.5, 0.3333]


# ---------------------------------------------------------------------------
# TIME / ENUM / SET / BIT
# ---------------------------------------------------------------------------


def test_time_storage_compare_format(s):
    s.execute("create table tt (t time)")
    s.execute("insert into tt values ('12:34:56'), ('-01:30:00'),"
              " ('838:59:59'), ('1 02:00:00')")
    got = [r[0] for r in _rows(s, "select t from tt order by t")]
    assert got == ["-01:30:00", "12:34:56", "26:00:00", "838:59:59"]
    assert _rows(s, "select count(*) from tt where t > '12:00:00'") == [(3,)]
    assert _rows(s, "select time_to_sec(t) from tt where t = '-01:30:00'") \
        == [(-5400,)]
    assert _rows(s, "select sec_to_time(3661) from tt limit 1") \
        == [("01:01:01",)]


def test_enum_semantics(s):
    s.execute("create table te (e enum('small','medium','large'))")
    s.execute("insert into te values ('medium'), ('small'), ('large')")
    # MySQL sorts ENUM by member index, not lexically
    assert [r[0] for r in _rows(s, "select e from te order by e")] == [
        "small", "medium", "large"]
    assert _rows(s, "select e from te where e = 'medium'") == [("medium",)]
    assert _rows(s, "select count(*) from te where e > 'small'") == [(2,)]
    # numeric context: index values
    assert _rows(s, "select cast(e as char) from te where e = 2") \
        == [("medium",)]


def test_set_semantics(s):
    s.execute("create table ts (v set('a','b','c','d'))")
    s.execute("insert into ts values ('a,c'), ('b'), ('a,b,c,d'), ('')")
    got = sorted(r[0] for r in _rows(s, "select v from ts"))
    assert got == ["", "a,b,c,d", "a,c", "b"]
    assert _rows(s, "select v from ts where v = 'a,c'") == [("a,c",)]
    assert _rows(s, "select find_in_set('c', v) from ts where v = 'a,c'") \
        == [(2,)]


def test_bit_column(s):
    s.execute("create table tb (b bit(8))")
    s.execute("insert into tb values (5), (255)")
    assert sorted(_rows(s, "select b from tb")) == [(5,), (255,)]
    assert _rows(s, "select b & 4 from tb where b = 5") == [(4,)]


def test_show_create_new_types(s):
    s.execute("create table sc (t time, e enum('x','y'), v set('p','q'),"
              " b bit(4), j json, w decimal(30,5))")
    out = _rows(s, "show create table sc")[0][1]
    for frag in ("TIME", "ENUM('x','y')", "SET('p','q')", "BIT(4)", "JSON",
                 "DECIMAL(30,5)"):
        assert frag.lower() in out.lower(), (frag, out)


# ---------------------------------------------------------------------------
# JSON
# ---------------------------------------------------------------------------


def test_json_extract_paths(s):
    s.execute("create table tj (j json)")
    s.execute("""insert into tj values ('{"a": {"b": [10, 20, {"c": "x"}]},
        "d e": true}')""")
    q = lambda p: _rows(s, f"select json_extract(j, '{p}') from tj")[0][0]
    assert q("$.a.b[1]") == "20"
    assert q("$.a.b[2].c") == '"x"'
    assert q('$."d e"') == "true"
    assert q("$.missing") is None
    assert _rows(s, "select json_unquote(json_extract(j, '$.a.b[2].c'))"
                 " from tj") == [("x",)]


def test_json_type_valid_length(s):
    s.execute("create table tv (j json)")
    s.execute("insert into tv values ('{\"k\": 1, \"l\": 2}'), ('[1,2,3]'),"
              " ('\"str\"'), ('3.5'), ('null')")
    got = _rows(s, "select json_type(j), json_valid(j), json_length(j)"
                " from tv")
    assert got == [("OBJECT", 1, 2), ("ARRAY", 1, 3), ("STRING", 1, 1),
                   ("DOUBLE", 1, 1), ("NULL", 1, 1)]


def test_json_object_array_builders(s):
    s.execute("create table jb (a bigint, b varchar(5))")
    s.execute("insert into jb values (1, 'x')")
    assert _rows(s, "select json_object('n', a, 's', b) from jb") == [
        ('{"n":1,"s":"x"}',)]
    assert _rows(s, "select json_array(a, b, 2.5) from jb")[0][0] in (
        '[1,"x",2.5]', '[1,"x","2.5"]')


def test_json_invalid_document_rejected_loosely(s):
    s.execute("create table ji (j json)")
    s.execute("insert into ji values ('not json')")
    # non-strict: stored quoted, valid afterwards (MySQL errors in strict
    # mode; the session layer is non-strict throughout)
    assert _rows(s, "select json_valid(j) from ji") == [(1,)]


def test_enum_merges_as_text_in_case_coalesce(s):
    s.execute("create table e1 (e enum('red','blue'))")
    s.execute("insert into e1 values ('red'), (null)")
    assert s.query("select coalesce(e, 'none') from e1") == [
        ("red",), ("none",)]
    assert s.query("select case when e = 'red' then e else 'other' end"
                   " from e1") == [("red",), ("other",)]


def test_update_null_key_frees_old_unique_slot(s):
    """Setting a unique key to NULL releases the old value for another row
    in the same statement (MySQL succeeds; the seen-map must pop first)."""
    s.execute("create table u1 (u bigint, unique key (u))")
    s.execute("insert into u1 values (10), (20)")
    s.execute("update u1 set u = if(u = 10, null, 10)")
    got = sorted(s.query("select u from u1"), key=lambda r: (r[0] is None, r))
    assert got == [(10,), (None,)]


def test_decimal_vs_string_compare_exact(s):
    s.execute("create table dc (v decimal(30,0))")
    s.execute("insert into dc values (99999999999999999999),"
              " (99999999999999999998)")
    assert s.query("select v from dc where v = '99999999999999999999'") == [
        ("99999999999999999999",)]
    assert s.query("select v from dc where v > '99999999999999999998.5'") \
        == [("99999999999999999999",)]


def test_cast_to_narrow_decimal_saturates(s):
    (got,), = s.query("select cast('99999999999999999999' as decimal(18,0))")
    assert got == "999999999999999999"  # MySQL non-strict out-of-range


# ---------------------------------------------------------------------------
# storage / codec round-trips
# ---------------------------------------------------------------------------


def test_wide_decimal_wire_codec_roundtrip():
    ft = ty_decimal(40, 10)
    vals = [10**38 + 7, -(10**37), None, 0]
    c = Column.from_values(ft, vals)
    out = decode_chunk(encode_chunk(Chunk([c])))
    assert out.col(0).to_pylist() == vals
    assert out.col(0).ftype.precision == 40


def test_enum_codec_keeps_members():
    ft = ty_enum(("a", "b"))
    c = Column.from_values(ft, [1, 2, None])
    out = decode_chunk(encode_chunk(Chunk([c])))
    assert out.col(0).ftype.elems == ("a", "b")


def test_new_types_persist_roundtrip(tmp_path):
    dd = str(tmp_path / "data")
    d1 = Domain(data_dir=dd)
    s1 = d1.new_session()
    s1.execute("create table p (w decimal(40,5), t time,"
               " e enum('a','b'), j json)")
    s1.execute("insert into p values"
               " (12345678901234567890123456789.12345, '10:00:00', 'b',"
               " '{\"z\": 1}')")
    s1.execute("commit")
    # force base snapshot via compaction path
    t = d1.catalog.info_schema().table("test", "p")
    d1.storage.table(t.id).compact(d1.storage.current_ts())
    d2 = Domain(data_dir=dd)
    s2 = d2.new_session()
    got = _rows(s2, "select * from p")
    assert got == [("12345678901234567890123456789.12345", "10:00:00",
                    "b", '{"z":1}')]
