"""Window function tests (executor/window.go parity surface)."""

import pytest

from tidb_tpu.session import Domain


@pytest.fixture()
def sess():
    s = Domain().new_session()
    s.execute("create table emp (dept varchar(5), name varchar(10), sal bigint)")
    s.execute(
        "insert into emp values ('a','x',100),('a','y',200),('a','z',200),"
        "('b','p',50),('b','q',150)"
    )
    return s


def q(s, sql):
    return s.query(sql)


def test_row_number_partition(sess):
    assert q(sess, "select dept, name, row_number() over "
                   "(partition by dept order by sal) from emp "
                   "order by dept, sal, name") == [
        ("a", "x", 1), ("a", "y", 2), ("a", "z", 3),
        ("b", "p", 1), ("b", "q", 2),
    ]


def test_rank_dense_rank(sess):
    assert q(sess, "select name, rank() over (order by sal), "
                   "dense_rank() over (order by sal) from emp "
                   "order by sal, name") == [
        ("p", 1, 1), ("x", 2, 2), ("q", 3, 3), ("y", 4, 4), ("z", 4, 4),
    ]


def test_running_sum_and_partition_total(sess):
    assert q(sess, "select dept, sal, sum(sal) over "
                   "(partition by dept order by sal) from emp "
                   "order by dept, sal") == [
        ("a", 100, 100), ("a", 200, 500), ("a", 200, 500),
        ("b", 50, 50), ("b", 150, 200),
    ]
    assert q(sess, "select dept, sal, sum(sal) over (partition by dept) "
                   "from emp order by dept, sal") == [
        ("a", 100, 500), ("a", 200, 500), ("a", 200, 500),
        ("b", 50, 200), ("b", 150, 200),
    ]


def test_lead_lag(sess):
    assert q(sess, "select name, lag(sal) over (order by sal, name), "
                   "lead(sal, 1, 0) over (order by sal, name) from emp "
                   "order by sal, name") == [
        ("p", None, 100), ("x", 50, 150), ("q", 100, 200),
        ("y", 150, 200), ("z", 200, 0),
    ]


def test_rows_frame(sess):
    rows = q(sess, "select name, min(sal) over (order by sal, name "
                   "rows between 1 preceding and 1 following) from emp "
                   "order by sal, name")
    assert rows == [("p", 50), ("x", 50), ("q", 100), ("y", 150), ("z", 200)]


def test_first_value_cume_dist(sess):
    rows = q(
        sess,
        "select name, first_value(name) over (partition by dept order by sal),"
        " cume_dist() over (order by sal) from emp order by sal, name")
    assert rows[0][1] == "p" and rows[-1][2] == 1.0


def test_window_over_aggregate(sess):
    assert q(sess, "select dept, max(sal), row_number() over "
                   "(order by max(sal) desc) from emp group by dept "
                   "order by dept") == [("a", 200, 1), ("b", 150, 2)]


def test_ntile(sess):
    rows = q(sess, "select name, ntile(2) over (order by sal, name) "
                   "from emp order by sal, name")
    assert [r[1] for r in rows] == [1, 1, 1, 2, 2]


def test_empty_frames_at_partition_edges(sess2=None):
    s = Domain().new_session()
    s.execute("create table t (a bigint)")
    s.execute("insert into t values (10),(20),(30),(40)")
    rows = q(s, "select a, sum(a) over (order by a rows between 2 preceding "
                "and 1 preceding), count(*) over (order by a rows between "
                "1 following and 2 following) from t order by a")
    assert rows == [(10, None, 2), (20, 10, 2), (30, 30, 1), (40, 50, 0)]


def test_same_named_partition_cols_do_not_collide():
    s = Domain().new_session()
    s.execute("create table t1 (a bigint, v bigint)")
    s.execute("create table t2 (a bigint, k bigint)")
    s.execute("insert into t1 values (1,1),(1,2),(2,3)")
    s.execute("insert into t2 values (7,1),(8,2),(7,3)")
    rows = q(s, "select t1.a, t2.a, count(*) over (partition by t1.a), "
                "count(*) over (partition by t2.a) from t1 join t2 "
                "on t1.v = t2.k order by t1.a, t2.a")
    assert rows == [(1, 7, 2, 2), (1, 8, 2, 1), (2, 7, 1, 2)]


def test_percent_rank(sess):
    rows = q(sess, "select name, percent_rank() over (order by sal) "
                   "from emp order by sal, name")
    assert rows[0][1] == 0.0 and rows[-1][1] == 0.75
