"""tidb_tpu — a TPU-native analytical SQL execution framework.

A ground-up re-design of the capabilities of TiDB's SQL layer (reference:
Aloxaf/tidb) for TPU hardware:

- columnar ``Chunk`` batches in Arrow layout (reference: util/chunk/column.go:59-67)
- a Volcano-with-chunks root executor (reference: executor/executor.go:187-193)
- a planner that splits physical plans into *root tasks* (host) and *cop tasks*
  (device) behind a narrow ``Client.Send(DAGRequest) -> chunk stream`` pushdown
  boundary (reference: kv/kv.go:197-203, planner/core/task.go:44-106)
- the coprocessor engine itself is a JAX/XLA program over fixed-shape column
  blocks — pjit/shard_map across a device mesh, Pallas kernels where fusion
  isn't enough — not a port of the reference's row-at-a-time Go interpreters.

Subpackage map (reference component in parens):

- ``tidb_tpu.types``    scalar type system, MySQL semantics        (types/)
- ``tidb_tpu.chunk``    columnar batches, codec                    (util/chunk)
- ``tidb_tpu.parser``   SQL lexer/parser -> AST                    (pingcap/parser)
- ``tidb_tpu.expr``     expression trees, vectorized eval, pushdown(expression/)
- ``tidb_tpu.plan``     logical/physical planner, task split       (planner/)
- ``tidb_tpu.copr``     DAG IR + device/host coprocessor engines   (mocktikv cop + TiKV copr)
- ``tidb_tpu.exec``     root executors                             (executor/)
- ``tidb_tpu.distsql``  request builder, fan-out, ordered merge    (distsql/, store/tikv/coprocessor.go)
- ``tidb_tpu.store``    KV + block store, regions, MVCC, faults    (kv/, store/)
- ``tidb_tpu.parallel`` mesh/sharding/collectives helpers          (client_batch.go &c., re-imagined)
- ``tidb_tpu.session``  session, catalog, sysvars                  (session/, infoschema/)
- ``tidb_tpu.ops``      jax/pallas kernels (segment reduce, compaction, hash)
- ``tidb_tpu.utils``    memory tracking, timing, misc
"""

__version__ = "0.1.0"
