"""tidb-tpu server entry point (tidb-server/main.go:152 analog).

    python -m tidb_tpu --host 127.0.0.1 --port 4000

Boots a Domain (storage + catalog + stats), then serves the MySQL wire
protocol.  Checkpoint/resume: --data-dir persists the catalog JSON on DDL
and reloads it at boot (storage blocks are rebuilt from LOAD DATA / inserts;
the durable-store tier is a later-round item).
"""

from __future__ import annotations

import argparse
import os


def main():
    ap = argparse.ArgumentParser("tidb-tpu")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=4000)
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--engine", default="tpu", choices=["tpu", "cpu"],
                    help="default coprocessor engine routing")
    args = ap.parse_args()

    from .session import Domain
    from .server import serve_forever

    domain = Domain()
    if args.engine == "cpu":
        domain.global_vars["tidb_use_tpu"] = "0"
    if args.data_dir:
        os.makedirs(args.data_dir, exist_ok=True)
        meta = os.path.join(args.data_dir, "catalog.json")
        if os.path.exists(meta):
            domain.catalog.load_json(open(meta).read())

        def persist(catalog):
            tmp = meta + ".tmp"
            with open(tmp, "w") as f:
                f.write(catalog.to_json())
            os.replace(tmp, meta)

        domain.catalog.on_ddl = persist
    serve_forever(args.host, args.port, domain)


if __name__ == "__main__":
    main()
