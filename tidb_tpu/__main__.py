"""tidb-tpu server entry point (tidb-server/main.go:152 analog).

    python -m tidb_tpu --host 127.0.0.1 --port 4000

Boots a Domain (storage + catalog + stats), then serves the MySQL wire
protocol.  Checkpoint/resume: --data-dir makes the store durable — catalog
JSON on DDL, base-block snapshots on load/compact, a committed-delta log on
every commit; boot reloads all of it (store/persist.py).
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser("tidb-tpu")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=4000)
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--status-port", type=int, default=10080,
                    help="HTTP /metrics + /status port (0 disables)")
    ap.add_argument("--engine", default="tpu", choices=["tpu", "cpu"],
                    help="default coprocessor engine routing")
    args = ap.parse_args()

    # multi-host bring-up MUST precede the first jax backend touch
    # (jax.distributed contract); no-op without TIDB_TPU_COORDINATOR
    from .copr.parallel import _maybe_init_multihost

    _maybe_init_multihost()
    from .session import Domain
    from .server import StatusServer, serve_forever

    domain = Domain(data_dir=args.data_dir or None)
    if args.engine == "cpu":
        domain.global_vars["tidb_use_tpu"] = "0"
    if args.status_port:
        StatusServer(domain, args.host, args.status_port).start()
    serve_forever(args.host, args.port, domain)


if __name__ == "__main__":
    main()
