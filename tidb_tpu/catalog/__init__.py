from .catalog import Catalog, DDLJob, InfoSchema
from .schema import (
    STATE_PUBLIC,
    ColumnInfo,
    DBInfo,
    IndexInfo,
    TableInfo,
)

__all__ = [
    "Catalog", "DDLJob", "InfoSchema", "ColumnInfo", "DBInfo", "IndexInfo",
    "TableInfo", "STATE_PUBLIC",
]
