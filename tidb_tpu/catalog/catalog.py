"""Catalog: the schema authority (meta + infoschema + DDL executor).

Reference mapping:
- `meta/` (schema metadata in KV, GenGlobalID, SchemaVersion) -> Catalog's
  id allocator + version counter + to_json/from_json persistence.
- `infoschema/` (immutable schema snapshot per version, builder applying
  diffs) -> InfoSchema frozen view handed to sessions; a new snapshot per
  DDL (schema lease convergence collapses to instant refresh in-process).
- `ddl/` (online schema change via job queue + owner worker,
  ddl_worker.go:362,500) -> synchronous job execution here, with the same
  F1 state ladder recorded per object and a DDL-job history list.  The
  multi-step ladder matters when other nodes cache old versions; in-process
  every session sees the new snapshot atomically, so jobs run all steps
  eagerly while still recording them (tested + surfaced in ADMIN SHOW DDL).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..errors import (
    KVError,
    TableExistsError,
    UnknownDatabaseError,
    UnknownTableError,
    PlanError,
)
from ..types import FieldType, TypeKind
from .schema import (
    STATE_DELETE_ONLY,
    STATE_NONE,
    STATE_PUBLIC,
    STATE_WRITE_ONLY,
    STATE_WRITE_REORG,
    ColumnInfo,
    DBInfo,
    IndexInfo,
    TableInfo,
)


@dataclass
class DDLJob:
    id: int
    typ: str  # create_table, add_index, ...
    db: str
    table: str
    state: str = "done"  # queued|running|done|cancelled|rollback
    schema_version: int = 0
    start_time: float = 0.0
    states_walked: List[str] = field(default_factory=list)
    error: str = ""


class InfoSchema:
    """Immutable schema snapshot at one schema version.

    Reference: infoschema.InfoSchema (infoschema/infoschema.go); sessions
    hold one for the duration of a statement/txn and the commit-time schema
    check compares versions (2pc.go:1151-1155).
    """

    def __init__(self, version: int, dbs: Dict[str, DBInfo]):
        self.version = version
        self._dbs = dbs
        self._by_id: Dict[int, TableInfo] = {}
        for db in dbs.values():
            for t in db.tables.values():
                self._by_id[t.id] = t

    def schema_names(self) -> List[str]:
        return sorted(db.name for db in self._dbs.values())

    def has_schema(self, name: str) -> bool:
        return name.lower() in self._dbs

    def schema(self, name: str) -> DBInfo:
        db = self._dbs.get(name.lower())
        if db is None:
            raise UnknownDatabaseError(name)
        return db

    def tables(self, db: str) -> List[TableInfo]:
        return sorted(self.schema(db).tables.values(), key=lambda t: t.name)

    def table(self, db: str, name: str) -> TableInfo:
        t = self.schema(db).tables.get(name.lower())
        if t is None:
            raise UnknownTableError(f"{db}.{name}")
        return t

    def has_table(self, db: str, name: str) -> bool:
        d = self._dbs.get(db.lower())
        return d is not None and name.lower() in d.tables

    def table_by_id(self, tid: int) -> Optional[TableInfo]:
        return self._by_id.get(tid)


class Catalog:
    def __init__(self, storage):
        self.storage = storage
        self._mu = threading.RLock()
        self._dbs: Dict[str, DBInfo] = {}
        self._next_id = 100
        self.schema_version = 0
        self.jobs: List[DDLJob] = []
        self._snapshot: Optional[InfoSchema] = None
        # optional hook: called with a table id whenever its storage is
        # dropped/replaced (Domain wires this to StatsHandle.drop)
        self.on_table_dropped = None
        # optional hook: called (with this catalog) after every committed
        # DDL — the supported seam for persistence (ddl callbacks analog,
        # domain/domain.go:584-589)
        self.on_ddl = None

    def _notify_drop(self, table_id: int):
        if self.on_table_dropped is not None:
            self.on_table_dropped(table_id)

    # ------------------------------------------------------------------
    # id / version bookkeeping (meta.GenGlobalID / SchemaVersion analog)
    # ------------------------------------------------------------------
    def gen_id(self) -> int:
        with self._mu:
            self._next_id += 1
            return self._next_id

    def _bump(self):
        self.schema_version += 1
        self._snapshot = None
        if self.on_ddl is not None:
            self.on_ddl(self)

    def info_schema(self) -> InfoSchema:
        with self._mu:
            if self._snapshot is None:
                # deep-ish copy not needed: TableInfos are replaced, not
                # mutated, by DDL ops below
                self._snapshot = InfoSchema(self.schema_version, dict(self._dbs))
            return self._snapshot

    def _record(self, job: DDLJob):
        job.schema_version = self.schema_version
        job.start_time = time.time()
        self.jobs.append(job)

    # ------------------------------------------------------------------
    # databases
    # ------------------------------------------------------------------
    def create_database(self, name: str, if_not_exists: bool = False):
        with self._mu:
            key = name.lower()
            if key in self._dbs:
                if if_not_exists:
                    return
                raise KVError(f"database {name!r} exists")
            self._dbs[key] = DBInfo(self.gen_id(), name)
            self._bump()
            self._record(DDLJob(self.gen_id(), "create_schema", name, ""))

    def drop_database(self, name: str, if_exists: bool = False):
        with self._mu:
            key = name.lower()
            db = self._dbs.get(key)
            if db is None:
                if if_exists:
                    return
                raise UnknownDatabaseError(name)
            for t in db.tables.values():
                if not t.is_view:
                    self.storage.drop_table(t.id)
                    self._notify_drop(t.id)
            del self._dbs[key]
            self._bump()
            self._record(DDLJob(self.gen_id(), "drop_schema", name, ""))

    # ------------------------------------------------------------------
    # tables
    # ------------------------------------------------------------------
    def create_table(self, db: str, info: TableInfo,
                     if_not_exists: bool = False) -> TableInfo:
        with self._mu:
            d = self._dbs.get(db.lower())
            if d is None:
                raise UnknownDatabaseError(db)
            if info.name.lower() in d.tables:
                if if_not_exists:
                    return d.tables[info.name.lower()]
                raise TableExistsError(f"{db}.{info.name}")
            if info.id == 0:
                info.id = self.gen_id()
            for i, c in enumerate(info.columns):
                c.offset = i
            d.tables[info.name.lower()] = info
            if not info.is_view:
                self.storage.create_table(info.id, info.storage_columns())
            self._bump()
            self._record(DDLJob(self.gen_id(), "create_table", db, info.name))
            return info

    def drop_table(self, db: str, name: str, if_exists: bool = False,
                   view_only: bool = False):
        with self._mu:
            d = self._dbs.get(db.lower())
            t = d.tables.get(name.lower()) if d else None
            if t is None or (view_only and not t.is_view):
                if if_exists:
                    return
                raise UnknownTableError(f"{db}.{name}")
            del d.tables[name.lower()]
            if not t.is_view:
                self.storage.drop_table(t.id)
                self._notify_drop(t.id)
            self._bump()
            self._record(DDLJob(self.gen_id(), "drop_table", db, name))

    def truncate_table(self, db: str, name: str):
        """Drop + recreate with a fresh table id (ddl_api.go TruncateTable)."""
        with self._mu:
            t = self.info_schema().table(db, name)
            d = self._dbs[db.lower()]
            self.storage.drop_table(t.id)
            self._notify_drop(t.id)
            new = TableInfo(
                self.gen_id(), t.name, t.columns, t.indexes, t.pk_is_handle, 1
            )
            d.tables[name.lower()] = new
            self.storage.create_table(new.id, new.storage_columns())
            self._bump()
            self._record(DDLJob(self.gen_id(), "truncate_table", db, name))

    def rename_table(self, db: str, old: str, new: str):
        with self._mu:
            d = self._dbs.get(db.lower())
            if d is None:
                raise UnknownDatabaseError(db)
            t = d.tables.get(old.lower())
            if t is None:
                raise UnknownTableError(f"{db}.{old}")
            if new.lower() in d.tables:
                raise TableExistsError(f"{db}.{new}")
            del d.tables[old.lower()]
            t2 = TableInfo(t.id, new, t.columns, t.indexes, t.pk_is_handle,
                           t.auto_inc_id)
            d.tables[new.lower()] = t2
            self._bump()
            self._record(DDLJob(self.gen_id(), "rename_table", db, new))

    # ------------------------------------------------------------------
    # columns (add/drop rebuild storage blocks; the reference reorganizes
    # lazily via row-format versioning — columnar blocks make the eager
    # rebuild the natural choice, and it doubles as delta-merge compaction)
    # ------------------------------------------------------------------
    def add_column(self, db: str, table: str, col: ColumnInfo):
        with self._mu:
            t = self.info_schema().table(db, table)
            if t.find_column(col.name) is not None:
                raise KVError(f"column {col.name!r} exists")
            job = DDLJob(self.gen_id(), "add_column", db, table)
            job.states_walked = [STATE_NONE, STATE_DELETE_ONLY,
                                 STATE_WRITE_ONLY, STATE_PUBLIC]
            col.offset = len(t.columns)
            col.state = STATE_PUBLIC
            new_cols = t.columns + [col]
            default = col.default if col.has_default else None
            self._rebuild_storage(t, new_cols, add_default=(col, default))
            self._replace_table(db, table, t, columns=new_cols)
            self._record(job)

    def drop_column(self, db: str, table: str, name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            col = t.find_column(name)
            if col is None:
                raise KVError(f"no column {name!r}")
            if len(t.public_columns()) == 1:
                raise KVError("cannot drop the only column")
            job = DDLJob(self.gen_id(), "drop_column", db, table)
            job.states_walked = [STATE_PUBLIC, STATE_WRITE_ONLY,
                                 STATE_DELETE_ONLY, STATE_NONE]
            new_cols = [c for c in t.columns if c is not col]
            for i, c in enumerate(new_cols):
                c.offset = i
            new_idx = [ix for ix in t.indexes
                       if col.name.lower() not in [c.lower() for c in ix.columns]]
            self._rebuild_storage(t, new_cols, drop=col.name)
            self._replace_table(db, table, t, columns=new_cols, indexes=new_idx)
            self._record(job)

    def modify_column(self, db: str, table: str, col: ColumnInfo):
        """Change column type (lossy conversions surface as errors)."""
        with self._mu:
            t = self.info_schema().table(db, table)
            old = t.find_column(col.name)
            if old is None:
                raise KVError(f"no column {col.name!r}")
            col.offset = old.offset
            new_cols = list(t.columns)
            new_cols[old.offset] = col
            self._rebuild_storage(t, new_cols, retype=(old.offset, col.ftype))
            self._replace_table(db, table, t, columns=new_cols)
            self._record(DDLJob(self.gen_id(), "modify_column", db, table))

    # ------------------------------------------------------------------
    # indexes.  write-reorg backfill (ddl/index.go) collapses to metadata:
    # our indexes are materialized lazily from blocks (store side), so
    # "backfill" = first build; the state ladder is still recorded.
    # ------------------------------------------------------------------
    def create_index(self, db: str, table: str, name: str,
                     columns: List[str], unique: bool = False,
                     primary: bool = False):
        with self._mu:
            t = self.info_schema().table(db, table)
            if t.find_index(name) is not None:
                raise KVError(f"index {name!r} exists")
            for c in columns:
                if t.find_column(c) is None:
                    raise KVError(f"no column {c!r} for index {name!r}")
            job = DDLJob(self.gen_id(), "add_index", db, table)
            job.states_walked = [STATE_NONE, STATE_DELETE_ONLY,
                                 STATE_WRITE_ONLY, STATE_WRITE_REORG,
                                 STATE_PUBLIC]
            ix = IndexInfo(self.gen_id(), name, columns, unique, primary)
            if unique:
                self._check_unique(t, columns, name)
            self._replace_table(db, table, t, indexes=t.indexes + [ix])
            self._record(job)

    def drop_index(self, db: str, table: str, name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            ix = t.find_index(name)
            if ix is None:
                raise KVError(f"no index {name!r}")
            self._replace_table(
                db, table, t, indexes=[i for i in t.indexes if i is not ix]
            )
            self._record(DDLJob(self.gen_id(), "drop_index", db, table))

    def _check_unique(self, t: TableInfo, columns: List[str], name: str):
        store = self.storage.table(t.id)
        offs = t.col_offsets(columns)
        ts = self.storage.current_ts()
        chunk = store.base_chunk(offs, 0, store.base_rows)
        deleted, inserted = store.delta_overlay(ts, 0, 1 << 62)
        seen = set()
        dele = set(deleted)
        for h in range(chunk.num_rows):
            if h in dele:
                continue
            key = chunk.row(h)
            if None in key:
                continue  # NULLs never collide (MySQL unique semantics)
            if key in seen:
                raise KVError(f"duplicate entry for unique index {name!r}")
            seen.add(key)
        for row in inserted.values():
            key = tuple(row[o] for o in offs)
            if None in key:
                continue
            if key in seen:
                raise KVError(f"duplicate entry for unique index {name!r}")
            seen.add(key)

    # ------------------------------------------------------------------
    def _replace_table(self, db: str, table: str, t: TableInfo, **overrides):
        d = self._dbs[db.lower()]
        new = TableInfo(
            t.id, t.name,
            overrides.get("columns", t.columns),
            overrides.get("indexes", t.indexes),
            t.pk_is_handle, t.auto_inc_id, t.comment, t.is_view, t.view_select,
        )
        d.tables[table.lower()] = new
        self._bump()

    def _rebuild_storage(self, t: TableInfo, new_cols: List[ColumnInfo],
                         add_default=None, drop: str = None, retype=None):
        """Rewrite the TableStore for a column-layout change.  Committed
        delta folds in (compact), so the new store is base-only."""
        store = self.storage.table(t.id)
        ts = self.storage.current_ts()
        store.compact(ts)
        old_names = [c.name for c in t.columns]
        chunk = store.base_chunk(range(store.n_cols), 0, store.base_rows)
        n = chunk.num_rows
        arrays, valids = [], []
        for c in new_cols:
            if add_default is not None and c is add_default[0]:
                default = add_default[1]
                ft = c.ftype
                if ft.kind == TypeKind.STRING:
                    arr = np.full(n, "" if default is None else str(default),
                                  dtype=object)
                else:
                    arr = np.full(n, 0 if default is None else default,
                                  dtype=ft.np_dtype)
                valid = np.full(n, default is not None, dtype=np.bool_)
            else:
                oi = old_names.index(c.name)
                col = chunk.col(oi)
                arr, valid = col.data, col.validity()
                if retype is not None and oi == retype[0]:
                    arr = _convert_array(arr, valid, t.columns[oi].ftype,
                                         retype[1])
            arrays.append(arr)
            valids.append(valid)
        # keep the persisted snapshot until the replacement is written:
        # the new store's save_base atomically replaces the same files, so
        # a crash mid-ALTER leaves the OLD consistent state (catalog.json
        # only advances after this method returns)
        self.storage.drop_table(t.id, keep_files=True)
        self._notify_drop(t.id)
        new_store = self.storage.create_table(
            t.id, [(c.name, c.ftype) for c in new_cols]
        )
        if n:
            new_store.bulk_load_arrays(arrays, valids, ts)
        elif new_store.persister is not None:
            # empty table: still replace the on-disk snapshot so the old
            # layout can't be reloaded against the new schema
            new_store.persister.save_base(new_store)

    # ------------------------------------------------------------------
    # persistence (checkpoint/resume story, SURVEY.md §5)
    # ------------------------------------------------------------------
    def to_json(self) -> str:
        with self._mu:
            return json.dumps({
                "version": self.schema_version,
                "next_id": self._next_id,
                "dbs": {k: d.to_dict() for k, d in self._dbs.items()},
            })

    def load_json(self, blob: str):
        with self._mu:
            d = json.loads(blob)
            self.schema_version = d["version"]
            self._next_id = d["next_id"]
            self._dbs = {k: DBInfo.from_dict(v) for k, v in d["dbs"].items()}
            self._snapshot = None
            for db in self._dbs.values():
                for t in db.tables.values():
                    if not t.is_view and not self.storage.has_table(t.id):
                        self.storage.create_table(t.id, t.storage_columns())


def _convert_array(arr, valid, old_ft: FieldType, new_ft: FieldType):
    from ..chunk import Column
    from ..expr.builtins import cast_vec
    from ..expr.vec import Vec

    v = Vec(old_ft, arr, np.asarray(valid))
    return cast_vec(v, new_ft).data
