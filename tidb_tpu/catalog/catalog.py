"""Catalog: the schema authority (meta + infoschema + DDL executor).

Reference mapping:
- `meta/` (schema metadata in KV, GenGlobalID, SchemaVersion) -> Catalog's
  id allocator + version counter + to_json/from_json persistence.
- `infoschema/` (immutable schema snapshot per version, builder applying
  diffs) -> InfoSchema frozen view handed to sessions; a new snapshot per
  DDL (schema lease convergence collapses to instant refresh in-process).
- `ddl/` (online schema change via job queue + owner worker,
  ddl_worker.go:362,500) -> synchronous job execution here, with the same
  F1 state ladder recorded per object and a DDL-job history list.  The
  multi-step ladder matters when other nodes cache old versions; in-process
  every session sees the new snapshot atomically, so jobs run all steps
  eagerly while still recording them (tested + surfaced in ADMIN SHOW DDL).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..errors import (
    KVError,
    LockedError,
    TableExistsError,
    UnknownDatabaseError,
    UnknownTableError,
    PlanError,
)
from ..store.fault import FAILPOINTS
from ..types import FieldType, TypeKind
from .schema import (
    STATE_DELETE_ONLY,
    STATE_NONE,
    STATE_PUBLIC,
    STATE_WRITE_ONLY,
    STATE_WRITE_REORG,
    ColumnInfo,
    DBInfo,
    IndexInfo,
    TableInfo,
)
from ..util_concurrency import make_rlock


@dataclass
class DDLJob:
    id: int
    typ: str  # create_table, add_index, ...
    db: str
    table: str
    state: str = "done"  # queued|running|done|cancelled|rollback
    schema_version: int = 0
    start_time: float = 0.0
    states_walked: List[str] = field(default_factory=list)
    error: str = ""
    # online-reorg checkpoint (ddl/reorg.go): next handle to backfill and
    # the job's payload (index definition) so a restarted domain can resume
    reorg_progress: int = 0
    meta: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "id": self.id, "typ": self.typ, "db": self.db,
            "table": self.table, "state": self.state,
            "schema_version": self.schema_version,
            "states_walked": list(self.states_walked), "error": self.error,
            "reorg_progress": self.reorg_progress, "meta": dict(self.meta),
        }

    @staticmethod
    def from_dict(d: dict) -> "DDLJob":
        j = DDLJob(d["id"], d["typ"], d["db"], d["table"], d["state"],
                   d.get("schema_version", 0), 0.0,
                   list(d.get("states_walked", [])), d.get("error", ""))
        j.reorg_progress = d.get("reorg_progress", 0)
        j.meta = dict(d.get("meta", {}))
        return j


class InfoSchema:
    """Immutable schema snapshot at one schema version.

    Reference: infoschema.InfoSchema (infoschema/infoschema.go); sessions
    hold one for the duration of a statement/txn and the commit-time schema
    check compares versions (2pc.go:1151-1155).
    """

    def __init__(self, version: int, dbs: Dict[str, DBInfo]):
        self.version = version
        self._dbs = dbs
        self._by_id: Dict[int, TableInfo] = {}
        for db in dbs.values():
            for t in db.tables.values():
                self._by_id[t.id] = t
                if t.partition_info is not None:
                    # partition physical id -> owning logical table
                    for pd in t.partition_info.defs:
                        self._by_id[pd.id] = t

    def schema_names(self) -> List[str]:
        return sorted(db.name for db in self._dbs.values())

    def has_schema(self, name: str) -> bool:
        return name.lower() in self._dbs

    def schema(self, name: str) -> DBInfo:
        db = self._dbs.get(name.lower())
        if db is None:
            raise UnknownDatabaseError(name)
        return db

    def tables(self, db: str) -> List[TableInfo]:
        return sorted(self.schema(db).tables.values(), key=lambda t: t.name)

    def table(self, db: str, name: str) -> TableInfo:
        t = self.schema(db).tables.get(name.lower())
        if t is None:
            raise UnknownTableError(f"{db}.{name}")
        return t

    def has_table(self, db: str, name: str) -> bool:
        d = self._dbs.get(db.lower())
        return d is not None and name.lower() in d.tables

    def table_by_id(self, tid: int) -> Optional[TableInfo]:
        return self._by_id.get(tid)


class Catalog:
    def __init__(self, storage):
        self.storage = storage
        self._mu = make_rlock("catalog.catalog:Catalog._mu")
        self._dbs: Dict[str, DBInfo] = {}
        self._next_id = 100
        self.schema_version = 0
        self.jobs: List[DDLJob] = []
        self._snapshot: Optional[InfoSchema] = None
        # (wall_ms, InfoSchema) ring for historical reads (tidb_snapshot):
        # GetSnapshotInfoSchema role — old TableInfos are shared, not
        # copied, so entries are cheap
        self._history: List[tuple] = []
        # table id -> schema_version of its last DDL: the commit-time
        # schema checker (domain/schema_validator.go) compares a txn's
        # write set against these so a txn straddling a DDL on a table it
        # wrote must retry under the new schema
        self.table_versions: Dict[int, int] = {}
        # optional hook: called with a table id whenever its storage is
        # dropped/replaced (Domain wires this to StatsHandle.drop)
        self.on_table_dropped = None
        # optional hook: called (with this catalog) after every committed
        # DDL — the supported seam for persistence (ddl callbacks analog,
        # domain/domain.go:584-589)
        self.on_ddl = None
        # dropped tables awaiting GC: RECOVER TABLE flashback source
        # (ddl_api.go:1457; purged by the maintenance GC past gc_life)
        self.recycle_bin: List[dict] = []

    def _notify_drop(self, table_id: int):
        if self.on_table_dropped is not None:
            self.on_table_dropped(table_id)

    # ------------------------------------------------------------------
    # id / version bookkeeping (meta.GenGlobalID / SchemaVersion analog)
    # ------------------------------------------------------------------
    def gen_id(self) -> int:
        with self._mu:
            self._next_id += 1
            return self._next_id

    def _bump_locked(self):
        # DDL paths mutate DBInfo.tables in place before bumping, so the
        # snapshot here reflects the POST-change schema as of now; per-DB
        # table dicts are copied because future DDLs keep mutating them
        # (TableInfo values themselves are replaced, never mutated)
        frozen = {k: DBInfo(d.id, d.name, dict(d.tables))
                  for k, d in self._dbs.items()}
        self.schema_version += 1
        self._history.append((int(time.time() * 1000),
                              InfoSchema(self.schema_version, frozen)))
        if len(self._history) > 64:
            self._history = self._history[-48:]
        self._snapshot = None
        if self.on_ddl is not None:
            self.on_ddl(self)

    def _touch_locked(self, tid: int):
        self.table_versions[tid] = self.schema_version

    def _touch_info_locked(self, t):
        """Touch the logical id AND every partition's physical id: txn
        write-sets key on physical ids, so the commit-time schema check
        (domain/schema_validator.go analog) must see partition bumps."""
        self._touch_locked(t.id)
        for pid in t.physical_ids():
            self._touch_locked(pid)

    def info_schema(self) -> InfoSchema:
        with self._mu:
            if self._snapshot is None:
                # deep-ish copy not needed: TableInfos are replaced, not
                # mutated, by DDL ops below
                self._snapshot = InfoSchema(self.schema_version, dict(self._dbs))
            return self._snapshot

    def info_schema_at(self, wall_ms: int) -> InfoSchema:
        """Schema as of a historical wall-clock ms (domain.go:286
        GetSnapshotInfoSchema).  Each history entry is the post-DDL schema
        stamped at DDL time, so the schema AT `wall_ms` is the newest entry
        not newer than it; older than all history = best effort (the
        oldest recorded), no DDL since = current."""
        with self._mu:
            best = None
            for t_ms, isc in self._history:
                if t_ms <= wall_ms:
                    best = isc
                else:
                    break
            if best is not None:
                return best
            if self._history and self._history[0][0] > wall_ms:
                return self._history[0][1]
            return self.info_schema()

    def _persist(self):
        if getattr(self, "on_ddl", None) is not None:
            self.on_ddl(self)

    def _record_locked(self, job: DDLJob):
        job.schema_version = self.schema_version
        job.start_time = time.time()
        self.jobs.append(job)

    # ------------------------------------------------------------------
    # databases
    # ------------------------------------------------------------------
    def create_database(self, name: str, if_not_exists: bool = False):
        with self._mu:
            key = name.lower()
            if key in self._dbs:
                if if_not_exists:
                    return
                raise KVError(f"database {name!r} exists")
            self._dbs[key] = DBInfo(self.gen_id(), name)
            self._bump_locked()
            self._record_locked(DDLJob(self.gen_id(), "create_schema", name, ""))

    def drop_database(self, name: str, if_exists: bool = False):
        with self._mu:
            key = name.lower()
            db = self._dbs.get(key)
            if db is None:
                if if_exists:
                    return
                raise UnknownDatabaseError(name)
            for t in db.tables.values():
                if not t.is_view:
                    for pid in t.physical_ids():
                        self.storage.drop_table(pid)
                        self._notify_drop(pid)
            del self._dbs[key]
            self._bump_locked()
            self._record_locked(DDLJob(self.gen_id(), "drop_schema", name, ""))

    # ------------------------------------------------------------------
    # tables
    # ------------------------------------------------------------------
    def create_table(self, db: str, info: TableInfo,
                     if_not_exists: bool = False) -> TableInfo:
        with self._mu:
            d = self._dbs.get(db.lower())
            if d is None:
                raise UnknownDatabaseError(db)
            if info.name.lower() in d.tables:
                if if_not_exists:
                    return d.tables[info.name.lower()]
                raise TableExistsError(f"{db}.{info.name}")
            if info.id == 0:
                info.id = self.gen_id()
            for i, c in enumerate(info.columns):
                c.offset = i
            d.tables[info.name.lower()] = info
            if not info.is_view:
                if info.partition_info is not None:
                    for pd in info.partition_info.defs:
                        if pd.id == 0:
                            pd.id = self.gen_id()
                        self.storage.create_table(pd.id,
                                                  info.storage_columns())
                        self._touch_locked(pd.id)
                else:
                    self.storage.create_table(info.id, info.storage_columns())
            self._bump_locked()
            self._touch_locked(info.id)
            self._record_locked(DDLJob(self.gen_id(), "create_table", db, info.name))
            return info

    def drop_table(self, db: str, name: str, if_exists: bool = False,
                   view_only: bool = False):
        with self._mu:
            d = self._dbs.get(db.lower())
            t = d.tables.get(name.lower()) if d else None
            if t is None or (view_only and not t.is_view):
                if if_exists:
                    return
                raise UnknownTableError(f"{db}.{name}")
            del d.tables[name.lower()]
            if not t.is_view:
                # detach into the recycle bin instead of destroying: data
                # survives until the GC horizon so RECOVER TABLE can
                # flashback (ddl_api.go:1457; TiKV keeps dropped ranges
                # until the delete-range GC task passes the drop TSO)
                stores = {}
                for pid in t.physical_ids():
                    st = self.storage.detach_table(pid)
                    if st is not None:
                        stores[pid] = st
                    self._notify_drop(pid)
                self.recycle_bin.append(
                    {"t": t, "db": db.lower(), "stores": stores,
                     "drop_wall": time.time()})
            self._bump_locked()
            self._touch_info_locked(t)
            self._record_locked(DDLJob(self.gen_id(), "drop_table", db, name))

    def recover_table(self, db: str, name: str) -> TableInfo:
        """RECOVER TABLE: restore the newest recycle-bin entry for
        `db.name` (flashback before the GC horizon purges it)."""
        with self._mu:
            d = self._dbs.get(db.lower())
            if d is None:
                raise UnknownDatabaseError(db)
            if name.lower() in d.tables:
                raise TableExistsError(
                    f"{db}.{name} exists; rename or drop it first")
            for i in range(len(self.recycle_bin) - 1, -1, -1):
                e = self.recycle_bin[i]
                if e["db"] == db.lower() and e["t"].name.lower() == \
                        name.lower():
                    del self.recycle_bin[i]
                    t = e["t"]
                    for pid, st in e["stores"].items():
                        self.storage.attach_table(pid, st)
                        self._touch_locked(pid)
                    d.tables[name.lower()] = t
                    self._bump_locked()
                    self._touch_info_locked(t)
                    self._persist()
                    self._record_locked(DDLJob(self.gen_id(), "recover_table",
                                        db, name))
                    return t
            raise KVError(
                f"no recoverable table {db}.{name} (GC may have purged it)")

    def purge_recycle_bin(self, older_than_s: float):
        """GC: destroy recycle-bin entries past the retention window
        (the delete-range task the reference's gc_worker drives)."""
        cutoff = time.time() - older_than_s
        with self._mu:
            keep = []
            for e in self.recycle_bin:
                if e["drop_wall"] <= cutoff:
                    for st in e["stores"].values():
                        if st.persister is not None:
                            st.persister.remove()
                else:
                    keep.append(e)
            purged = len(self.recycle_bin) - len(keep)
            self.recycle_bin = keep
            return purged

    def truncate_table(self, db: str, name: str):
        """Drop + recreate with a fresh table id (ddl_api.go TruncateTable)."""
        with self._mu:
            t = self.info_schema().table(db, name)
            d = self._dbs[db.lower()]
            for pid in t.physical_ids():
                self.storage.drop_table(pid)
                self._notify_drop(pid)
            new = TableInfo(
                self.gen_id(), t.name, t.columns, t.indexes, t.pk_is_handle,
                1, t.comment, foreign_keys=list(t.foreign_keys),
            )
            d.tables[name.lower()] = new
            if t.partition_info is not None:
                from .schema import PartitionDef, PartitionInfo

                new.partition_info = PartitionInfo(
                    t.partition_info.kind, t.partition_info.column,
                    [PartitionDef(self.gen_id(), p.name, p.less_than)
                     for p in t.partition_info.defs])
                for pd in new.partition_info.defs:
                    self.storage.create_table(pd.id, new.storage_columns())
                    self._touch_locked(pd.id)
            else:
                self.storage.create_table(new.id, new.storage_columns())
            self._bump_locked()
            self._touch_info_locked(t)
            self._touch_info_locked(new)
            self._record_locked(DDLJob(self.gen_id(), "truncate_table", db, name))

    def rename_table(self, db: str, old: str, new: str):
        with self._mu:
            d = self._dbs.get(db.lower())
            if d is None:
                raise UnknownDatabaseError(db)
            t = d.tables.get(old.lower())
            if t is None:
                raise UnknownTableError(f"{db}.{old}")
            if new.lower() in d.tables:
                raise TableExistsError(f"{db}.{new}")
            del d.tables[old.lower()]
            # dataclasses.replace copies EVERY field: a positional
            # constructor copy here silently reset foreign_keys (round-5
            # ADVICE) and would reset any field added to TableInfo later
            t2 = dataclasses.replace(t, name=new,
                                     foreign_keys=list(t.foreign_keys))
            d.tables[new.lower()] = t2
            self._rewrite_referencing_fks_locked(db, old, new_table=new)
            self._bump_locked()
            self._touch_info_locked(t)
            self._record_locked(DDLJob(self.gen_id(), "rename_table", db, new))

    # ------------------------------------------------------------------
    # columns (add/drop rebuild storage blocks; the reference reorganizes
    # lazily via row-format versioning — columnar blocks make the eager
    # rebuild the natural choice, and it doubles as delta-merge compaction)
    # ------------------------------------------------------------------
    def add_column(self, db: str, table: str, col: ColumnInfo):
        with self._mu:
            t = self.info_schema().table(db, table)
            if t.find_column(col.name) is not None:
                raise KVError(f"column {col.name!r} exists")
            job = DDLJob(self.gen_id(), "add_column", db, table)
            job.states_walked = [STATE_NONE, STATE_DELETE_ONLY,
                                 STATE_WRITE_ONLY, STATE_PUBLIC]
            col.offset = len(t.columns)
            col.state = STATE_PUBLIC
            new_cols = t.columns + [col]
            default = col.default if col.has_default else None
            self._rebuild_storage(t, new_cols, add_default=(col, default))
            self._replace_table_locked(db, table, t, columns=new_cols)
            self._record_locked(job)

    def drop_column(self, db: str, table: str, name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            col = t.find_column(name)
            if col is None:
                raise KVError(f"no column {name!r}")
            if len(t.public_columns()) == 1:
                raise KVError("cannot drop the only column")
            job = DDLJob(self.gen_id(), "drop_column", db, table)
            job.states_walked = [STATE_PUBLIC, STATE_WRITE_ONLY,
                                 STATE_DELETE_ONLY, STATE_NONE]
            new_cols = [c for c in t.columns if c is not col]
            for i, c in enumerate(new_cols):
                c.offset = i
            new_idx = [ix for ix in t.indexes
                       if col.name.lower() not in [c.lower() for c in ix.columns]]
            new_fks = [fk for fk in t.foreign_keys
                       if col.name.lower() not in
                       [c.lower() for c in fk["columns"]]]
            self._rebuild_storage(t, new_cols, drop=col.name)
            self._replace_table_locked(db, table, t, columns=new_cols,
                                indexes=new_idx, foreign_keys=new_fks)
            self._record_locked(job)

    def modify_column(self, db: str, table: str, col: ColumnInfo):
        """Change column type (lossy conversions surface as errors)."""
        with self._mu:
            t = self.info_schema().table(db, table)
            old = t.find_column(col.name)
            if old is None:
                raise KVError(f"no column {col.name!r}")
            col.offset = old.offset
            new_cols = list(t.columns)
            new_cols[old.offset] = col
            self._rebuild_storage(t, new_cols, retype=(old.offset, col.ftype))
            self._replace_table_locked(db, table, t, columns=new_cols)
            self._record_locked(DDLJob(self.gen_id(), "modify_column", db, table))

    def change_column(self, db: str, table: str, old_name: str,
                      col: ColumnInfo):
        """CHANGE COLUMN: rename + retype in one op (ddl_api.go:2785)."""
        with self._mu:
            t = self.info_schema().table(db, table)
            old = t.find_column(old_name)
            if old is None:
                raise KVError(f"no column {old_name!r}")
            if col.name.lower() != old_name.lower() and \
                    t.find_column(col.name) is not None:
                raise KVError(f"column {col.name!r} exists")
            col.offset = old.offset
            new_cols = list(t.columns)
            new_cols[old.offset] = col

            def ren(n):
                return col.name if n.lower() == old.name.lower() else n

            new_ixs = [IndexInfo(x.id, x.name, [ren(c) for c in x.columns],
                                 x.unique, x.primary, x.state)
                       for x in t.indexes]
            new_fks = [{**fk, "columns": [ren(c) for c in fk["columns"]]}
                       for fk in t.foreign_keys]
            self._rebuild_storage(t, new_cols,
                                  retype=(old.offset, col.ftype),
                                  rename=(old.name, col.name))
            self._replace_table_locked(db, table, t, columns=new_cols,
                                indexes=new_ixs, foreign_keys=new_fks)
            # other tables referencing THIS column track the new name
            self._rewrite_referencing_fks_locked(
                db, table, ref_col_rename=(old.name, col.name))
            self._record_locked(DDLJob(self.gen_id(), "change_column", db, table))

    def _rewrite_referencing_fks_locked(self, ref_db: str, ref_table: str,
                                 ref_col_rename=None, new_table=None):
        """Keep FK metadata in OTHER tables pointing at (ref_db,
        ref_table) consistent across renames (SHOW CREATE TABLE must emit
        replayable DDL)."""
        for dname, dinfo in self._dbs.items():
            for tname, ti in list(dinfo.tables.items()):
                changed = False
                fks = []
                for fk in ti.foreign_keys:
                    if fk["ref_db"] == ref_db.lower() and                             fk["ref_table"] == ref_table.lower():
                        fk = dict(fk)
                        if new_table is not None:
                            fk["ref_table"] = new_table.lower()
                            changed = True
                        if ref_col_rename is not None:
                            old_c, new_c = ref_col_rename
                            cols = [new_c if c.lower() == old_c.lower()
                                    else c for c in fk["ref_columns"]]
                            if cols != fk["ref_columns"]:
                                fk["ref_columns"] = cols
                                changed = True
                    fks.append(fk)
                if changed:
                    dinfo.tables[tname] = TableInfo(
                        ti.id, ti.name, ti.columns, ti.indexes,
                        ti.pk_is_handle, ti.auto_inc_id, ti.comment,
                        ti.is_view, ti.view_select, ti.partition_info, fks)

    # ------------------------------------------------------------------
    # indexes.  write-reorg backfill (ddl/index.go) collapses to metadata:
    # our indexes are materialized lazily from blocks (store side), so
    # "backfill" = first build; the state ladder is still recorded.
    # ------------------------------------------------------------------
    BACKFILL_BATCH = 4096  # handles per reorg step (ddl/reorg.go batches)

    def create_index(self, db: str, table: str, name: str,
                     columns: List[str], unique: bool = False,
                     primary: bool = False):
        """Online add-index: the F1 state ladder none -> delete-only ->
        write-only -> write-reorg -> public (ddl_worker.go:466-469), one
        schema-version bump per step; the write-reorg backfill runs in
        handle ranges with progress checkpointed in the persisted job, so
        a domain reopened mid-reorg resumes where the dead process stopped
        (ddl/reorg.go)."""
        with self._mu:
            t = self.info_schema().table(db, table)
            if t.find_index(name) is not None:
                raise KVError(f"index {name!r} exists")
            for c in columns:
                if t.find_column(c) is None:
                    raise KVError(f"no column {c!r} for index {name!r}")
            if t.is_partitioned:
                # partitioned path: every unique key must embed the
                # partition column (MySQL 1503), so uniqueness is local to
                # each partition; sorted indexes materialize lazily per
                # partition store, so no eager backfill ladder is needed.
                pi = t.partition_info
                if unique and pi.column.lower() not in [c.lower()
                                                        for c in columns]:
                    raise KVError(
                        f"a UNIQUE INDEX must include the partitioning "
                        f"column {pi.column!r}")
                if unique:
                    for pd in pi.defs:
                        self._check_unique(t, columns, name, store_id=pd.id)
                ix = IndexInfo(self.gen_id(), name, list(columns), unique,
                               primary, STATE_PUBLIC)
                self._replace_table_locked(db, table, t, indexes=t.indexes + [ix])
                self._record_locked(DDLJob(self.gen_id(), "add_index", db, table))
                return
            if unique:
                self._check_unique(t, columns, name)
            job = DDLJob(self.gen_id(), "add_index", db, table,
                         state="running")
            job.meta = {"index_id": self.gen_id(), "name": name,
                        "columns": list(columns), "unique": unique,
                        "primary": primary}
            self.jobs.append(job)
            self._persist()
        self.run_ddl_job(job)

    def run_ddl_job(self, job: DDLJob):
        """Walk (or resume) an online DDL job to completion."""
        if job.typ != "add_index" or job.state == "done":
            job.state = "done"
            return
        m = job.meta
        ix = IndexInfo(m["index_id"], m["name"], m["columns"],
                       m["unique"], m["primary"], STATE_NONE)
        ladder = [STATE_DELETE_ONLY, STATE_WRITE_ONLY, STATE_WRITE_REORG,
                  STATE_PUBLIC]
        done_states = set(job.states_walked)
        try:
            for st in ladder:
                if st in done_states:
                    continue
                if st == STATE_WRITE_REORG:
                    self._set_index_state(job, ix, st)
                    FAILPOINTS.hit("ddl/set_state", job=job.id, state=st)
                    self._backfill_index(job, ix)
                else:
                    self._set_index_state(job, ix, st)
                    FAILPOINTS.hit("ddl/set_state", job=job.id, state=st)
                job.states_walked.append(st)
                with self._mu:
                    self._persist()
        except Exception as e:
            # an ERROR rolls the job back (duplicate key, bad state...):
            # remove the half-added index so the name is reusable.  A real
            # crash never runs this handler — the persisted 'running' job
            # resumes on the next domain open (ddl_worker rollback vs
            # owner-resume split).
            with self._mu:
                t = self.info_schema().table(job.db, job.table)
                self._replace_table_locked(
                    job.db, job.table, t,
                    indexes=[i for i in t.indexes if i.name != ix.name])
                job.state = "rollback"
                job.error = str(e)
                self._persist()
            self._drop_reorg_parts(job)
            raise
        job.state = "done"
        job.states_walked = [STATE_NONE] + job.states_walked
        with self._mu:
            self._persist()

    def _set_index_state(self, job: DDLJob, ix: IndexInfo, st: str):
        from dataclasses import replace as dc_replace

        with self._mu:
            t = self.info_schema().table(job.db, job.table)
            others = [i for i in t.indexes if i.name != ix.name]
            self._replace_table_locked(job.db, job.table, t,
                                indexes=others + [dc_replace(ix, state=st)])
            job.schema_version = self.schema_version

    def _backfill_index(self, job: DDLJob, ix: IndexInfo):
        """Range-batched backfill of the sorted-index snapshot.  Each batch
        checkpoints as its own self-describing npz (covered range + the
        base_version it was scanned under), so (a) resume needs no second
        file to agree with, (b) I/O per batch is O(batch), and (c) a
        compaction mid-scan — which renumbers handles and dict codes —
        invalidates the checkpoints and restarts the scan."""
        import numpy as np

        from ..store.index import finalize_sorted_index

        with self._mu:
            t = self.info_schema().table(job.db, job.table)
        store = self.storage.table(t.id)
        offs = t.col_offsets(ix.columns)
        ncols = len(offs)
        from ..lifecycle import current_scope

        scope = current_scope()
        while True:
            parts, scan_version = self._load_reorg_parts(job, store)
            start = job.reorg_progress
            while start < store.base_rows:
                # cancellation seam per backfill batch: a KILLed (or
                # timed-out, or drained) online DDL unwinds here and the
                # job handler rolls the half-added index back
                FAILPOINTS.hit("exec/cancel", site="backfill", scope=scope)
                scope.check()
                if store.base_version != scan_version:
                    # compaction renumbered handles: restart the scan
                    parts, start = [], 0
                    scan_version = store.base_version
                    self._drop_reorg_parts(job)
                end = min(start + self.BACKFILL_BATCH, store.base_rows)
                # per-batch trace span: online index builds surface in
                # TRACE / SLOW_QUERY.backfill_ms / /status instead of
                # being an invisible stall inside the DDL statement
                from ..trace import span as _span

                with _span("ddl.backfill", job=job.id, index=ix.name,
                           start=start, end=end) as bsp:
                    chunk = store.base_chunk(list(offs), start, end,
                                             decode_strings=False)
                    valid = np.ones(end - start, dtype=np.bool_)
                    cols = []
                    for i in range(len(offs)):
                        c = chunk.col(i)
                        valid &= c.validity()
                        cols.append(c.data)
                    handles = np.arange(start, end, dtype=np.int64)[valid]
                    part = [c[valid] for c in cols] + [handles]
                    self._save_reorg_part(job, len(parts), part, end,
                                          scan_version)
                    bsp.set(rows=int(len(handles)))
                parts.append(part)
                job.reorg_progress = end
                FAILPOINTS.hit("ddl/backfill_batch", job=job.id, upto=end)
                start = end
            if parts:
                merged = [np.concatenate([p[i] for p in parts])
                          for i in range(ncols + 1)]
            else:
                merged = [np.zeros(0) for _ in range(ncols)] + [
                    np.zeros(0, dtype=np.int64)]
            idx = finalize_sorted_index(tuple(offs), merged[:ncols],
                                        merged[ncols], scan_version)
            if ix.unique and len(idx.handles) > 1:
                # recheck under the final sorted order: a duplicate written
                # through the delete-only window must fail the DDL
                # (the reference backfill's ErrKeyExists -> job rollback)
                dup = np.ones(len(idx.handles) - 1, dtype=bool)
                for k in idx.cols:
                    dup &= k[1:] == k[:-1]
                if dup.any():
                    raise KVError(
                        f"duplicate entry for unique index {ix.name!r}")
            if ix.unique:
                self._recheck_unique_overlay(store, ix, offs, idx)
            if store.base_version == scan_version:
                store.indexes.put(tuple(offs), idx)
                break
            if not ix.unique:
                # leave it to the lazy builder — the scan raced a compaction
                # and no constraint is at stake
                break
            # a compaction slipped in between the scan and the rechecks:
            # rows it folded into base may never have been seen by either
            # check — restart so the unique scan covers them
            job.reorg_progress = 0
            self._drop_reorg_parts(job)
        self._drop_reorg_parts(job)

    def _recheck_unique_overlay(self, store, ix: IndexInfo, offs, idx):
        """Rows committed during the delete-only window live only in the
        delta overlay (dml.py skips unique maintenance there), so the
        base-only backfill scan cannot see them.  Probe just the overlay
        rows against the freshly built index (value -> sorted-dict code; an
        absent code matches no base row).  An in-flight commit's lock must
        not kill the whole DDL — wait it out."""
        for _ in range(500):
            try:
                deleted, inserted = store.delta_overlay(
                    self.storage.current_ts(), 0, 1 << 62)
                break
            except LockedError:
                time.sleep(0.01)
        else:
            raise KVError(
                f"unique recheck for {ix.name!r} blocked on live locks")
        dele = set(deleted)
        seen = set()
        dict_cols = store.dict_encoded_cols()
        dup_err = KVError(f"duplicate entry for unique index {ix.name!r}")
        for row in inserted.values():
            key = tuple(row[o] for o in offs)
            if None in key:
                continue  # NULLs never collide
            if key in seen:
                raise dup_err
            seen.add(key)
            probe = []
            for ci, o in enumerate(offs):
                if o in dict_cols:
                    code = store.encode_dict_const(o, key[ci])
                    if code < 0:
                        probe = None  # value not in any base row
                        break
                    probe.append(code)
                else:
                    probe.append(key[ci])
            if probe is None:
                continue
            hs = idx.search_range(tuple(probe), tuple(probe))
            if any(int(h) not in dele for h in hs):
                raise dup_err

    def _reorg_dir(self):
        return self.storage.data_dir

    def _reorg_glob(self, job: DDLJob):
        import glob
        import os

        d = self._reorg_dir()
        if d is None:
            return []
        return sorted(glob.glob(os.path.join(d, f"ddl_reorg_{job.id}_*.npz")),
                      key=lambda p: int(p.rsplit("_", 1)[1][:-4]))

    def _load_reorg_parts(self, job: DDLJob, store):
        """(parts, scan_version) from per-batch checkpoints; progress is
        derived from the checkpoints themselves (single source of truth)."""
        import numpy as np

        files = self._reorg_glob(job)
        parts, upto, ver = [], 0, store.base_version
        for p in files:
            with np.load(p, allow_pickle=False) as z:
                v = int(z["base_version"])
                if v != store.base_version:
                    parts, upto = [], 0
                    break
                w = int(z["w"])
                parts.append([z[f"c{j}"] for j in range(w)])
                upto = max(upto, int(z["upto"]))
        job.reorg_progress = upto
        if upto == 0:
            parts = []
            self._drop_reorg_parts(job)
        return parts, ver

    def _save_reorg_part(self, job: DDLJob, i: int, part, upto: int,
                         base_version: int):
        d = self._reorg_dir()
        if d is None:
            return
        import os

        import numpy as np

        arrays = {"upto": np.int64(upto),
                  "base_version": np.int64(base_version),
                  "w": np.int64(len(part))}
        for j, arr in enumerate(part):
            arrays[f"c{j}"] = arr
        p = os.path.join(d, f"ddl_reorg_{job.id}_{i}.npz")
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def _drop_reorg_parts(self, job: DDLJob):
        import os

        for p in self._reorg_glob(job):
            try:
                os.unlink(p)
            except OSError:
                pass

    def resume_pending_jobs(self):
        """Called by a reopened domain: finish DDL jobs a dead process left
        mid-ladder (the re-elected owner resuming the job queue,
        ddl_worker.go:362).  A job that errors on resume (e.g. a duplicate
        key discovered by the backfill recheck) has already been rolled back
        and its error recorded by run_ddl_job — swallow it per job so one
        bad job neither blocks later jobs nor fails the domain open."""
        from ..metrics import REGISTRY

        with self._mu:
            pending = list(self.jobs)
        for job in pending:
            if job.state == "running":
                try:
                    self.run_ddl_job(job)
                except Exception as e:
                    REGISTRY.inc("ddl_resume_failures_total")
                    if job.state == "running":
                        # the failure escaped run_ddl_job's rollback handler
                        # (e.g. corrupted job meta): record it so the job
                        # isn't silently re-tried forever
                        job.state = "rollback"
                        job.error = str(e)
                        with self._mu:
                            self._persist()

    def drop_index(self, db: str, table: str, name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            ix = t.find_index(name)
            if ix is None:
                raise KVError(f"no index {name!r}")
            self._replace_table_locked(
                db, table, t, indexes=[i for i in t.indexes if i is not ix]
            )
            self._record_locked(DDLJob(self.gen_id(), "drop_index", db, table))

    def _check_unique(self, t: TableInfo, columns: List[str], name: str,
                      store_id: Optional[int] = None):
        store = self.storage.table(store_id if store_id is not None else t.id)
        offs = t.col_offsets(columns)
        chunk = store.base_chunk(offs, 0, store.base_rows)
        # same lock-wait as the backfill recheck: an in-flight commit must
        # stall the check, not abort the DDL
        for _ in range(500):
            try:
                deleted, inserted = store.delta_overlay(
                    self.storage.current_ts(), 0, 1 << 62)
                break
            except LockedError:
                time.sleep(0.01)
        else:
            raise KVError(
                f"unique check for {name!r} blocked on live locks")
        seen = set()
        dele = set(deleted)
        for h in range(chunk.num_rows):
            if h in dele:
                continue
            key = chunk.row(h)
            if None in key:
                continue  # NULLs never collide (MySQL unique semantics)
            if key in seen:
                raise KVError(f"duplicate entry for unique index {name!r}")
            seen.add(key)
        for row in inserted.values():
            key = tuple(row[o] for o in offs)
            if None in key:
                continue
            if key in seen:
                raise KVError(f"duplicate entry for unique index {name!r}")
            seen.add(key)

    # ------------------------------------------------------------------
    def _replace_table_locked(self, db: str, table: str, t: TableInfo, **overrides):
        d = self._dbs[db.lower()]
        new = TableInfo(
            t.id, t.name,
            overrides.get("columns", t.columns),
            overrides.get("indexes", t.indexes),
            t.pk_is_handle, t.auto_inc_id, t.comment, t.is_view, t.view_select,
            overrides.get("partition_info", t.partition_info),
            overrides.get("foreign_keys", list(t.foreign_keys)),
        )
        d.tables[table.lower()] = new
        self._bump_locked()
        self._touch_info_locked(new)

    # ------------------------------------------------------------------
    # light ALTERs: metadata-only changes (ddl_api.go RebaseAutoID :1999,
    # AlterTableComment :2902, RenameIndex :3105, FK :3509/:3541)
    # ------------------------------------------------------------------
    def rebase_auto_increment(self, db: str, table: str, n: int):
        with self._mu:
            t = self.info_schema().table(db, table)
            # MySQL: rebase never goes backwards
            new = TableInfo(t.id, t.name, t.columns, t.indexes,
                            t.pk_is_handle, max(int(n), t.auto_inc_id),
                            t.comment, t.is_view, t.view_select,
                            t.partition_info, list(t.foreign_keys))
            self._dbs[db.lower()].tables[table.lower()] = new
            self._bump_locked()
            self._touch_info_locked(new)
            self._record_locked(DDLJob(self.gen_id(), "rebase_auto_id", db, table))

    def set_table_comment(self, db: str, table: str, comment: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            new = TableInfo(t.id, t.name, t.columns, t.indexes,
                            t.pk_is_handle, t.auto_inc_id, comment,
                            t.is_view, t.view_select, t.partition_info,
                            list(t.foreign_keys))
            self._dbs[db.lower()].tables[table.lower()] = new
            self._bump_locked()
            self._touch_info_locked(new)
            self._record_locked(DDLJob(self.gen_id(), "modify_comment", db, table))

    def rename_index(self, db: str, table: str, old: str, new_name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            ix = next((x for x in t.indexes
                       if x.name.lower() == old.lower()), None)
            if ix is None:
                raise KVError(f"index {old!r} does not exist")
            if any(x.name.lower() == new_name.lower() for x in t.indexes):
                raise KVError(f"index {new_name!r} exists")
            new_ixs = [IndexInfo(x.id, new_name if x is ix else x.name,
                                 x.columns, x.unique, x.primary, x.state)
                       for x in t.indexes]
            self._replace_table_locked(db, table, t, indexes=new_ixs)
            self._record_locked(DDLJob(self.gen_id(), "rename_index", db, table))

    def add_foreign_key(self, db: str, table: str, name: str, columns,
                        ref_db: str, ref_table: str, ref_columns):
        with self._mu:
            isc = self.info_schema()
            t = isc.table(db, table)
            rt = isc.table(ref_db, ref_table)  # referenced table must exist
            for c in columns:
                if t.find_column(c) is None:
                    raise KVError(f"no column {c!r} in {table}")
            for c in ref_columns:
                if rt.find_column(c) is None:
                    raise KVError(f"no column {c!r} in {ref_table}")
            if len(columns) != len(ref_columns):
                raise KVError("FK column count mismatch")
            if any(fk["name"].lower() == name.lower()
                   for fk in t.foreign_keys):
                raise KVError(f"foreign key {name!r} exists")
            fks = list(t.foreign_keys) + [{
                "name": name, "columns": list(columns),
                "ref_db": ref_db.lower(), "ref_table": ref_table.lower(),
                "ref_columns": list(ref_columns),
            }]
            new = TableInfo(t.id, t.name, t.columns, t.indexes,
                            t.pk_is_handle, t.auto_inc_id, t.comment,
                            t.is_view, t.view_select, t.partition_info, fks)
            self._dbs[db.lower()].tables[table.lower()] = new
            self._bump_locked()
            self._touch_info_locked(new)
            self._record_locked(DDLJob(self.gen_id(), "add_foreign_key", db, table))

    def drop_foreign_key(self, db: str, table: str, name: str):
        with self._mu:
            t = self.info_schema().table(db, table)
            fks = [fk for fk in t.foreign_keys
                   if fk["name"].lower() != name.lower()]
            if len(fks) == len(t.foreign_keys):
                raise KVError(f"foreign key {name!r} does not exist")
            new = TableInfo(t.id, t.name, t.columns, t.indexes,
                            t.pk_is_handle, t.auto_inc_id, t.comment,
                            t.is_view, t.view_select, t.partition_info, fks)
            self._dbs[db.lower()].tables[table.lower()] = new
            self._bump_locked()
            self._touch_info_locked(new)
            self._record_locked(DDLJob(self.gen_id(), "drop_foreign_key", db,
                                table))

    # ------------------------------------------------------------------
    # partition management DDL (ddl_api.go:2187-2316 Add/Drop/Truncate/
    # CoalescePartition).  RANGE add/drop/truncate are metadata + store
    # create/drop (no data movement); HASH add/coalesce re-buckets every
    # row (MySQL rebuilds the same way).
    # ------------------------------------------------------------------
    def add_partition(self, db: str, table: str, defs=None,
                      add_buckets: int = 0):
        from .schema import PartitionDef, PartitionInfo

        with self._mu:
            t = self.info_schema().table(db, table)
            pi = t.partition_info
            if pi is None:
                raise KVError(f"table {table} is not partitioned")
            if pi.kind == "hash":
                if add_buckets <= 0:
                    raise KVError(
                        "ADD PARTITION on a HASH table takes PARTITIONS n")
                self._rehash_partitions(db, t, len(pi.defs) + add_buckets)
                return
            if not defs:
                raise KVError("ADD PARTITION requires partition definitions")
            cur = list(pi.defs)
            if cur and cur[-1].less_than is None:
                raise KVError(
                    "cannot ADD PARTITION after the MAXVALUE partition")
            # validate EVERY def before creating any store (no orphan
            # stores on a failed statement); MAXVALUE may only close the
            # list — a def after it would hide rows from ordered pruning
            names = {p.name.lower() for p in cur}
            last = cur[-1].less_than if cur else None
            maxvalue_seen = False
            for name, less_than in defs:
                if maxvalue_seen:
                    raise KVError(
                        "no partition may follow the MAXVALUE partition")
                if name.lower() in names:
                    raise KVError(f"duplicate partition name {name!r}")
                if less_than is None:
                    maxvalue_seen = True
                elif last is not None and less_than <= last:
                    raise KVError(
                        f"partition {name!r} bound {less_than} must exceed "
                        f"the previous bound {last}")
                names.add(name.lower())
                last = less_than if less_than is not None else last
            for name, less_than in defs:
                pd = PartitionDef(self.gen_id(), name, less_than)
                self.storage.create_table(pd.id, t.storage_columns())
                self._touch_locked(pd.id)
                cur.append(pd)
            new_pi = PartitionInfo(pi.kind, pi.column, cur)
            self._replace_table_locked(db, table, t, partition_info=new_pi)
            self._persist()
            self._record_locked(DDLJob(self.gen_id(), "add_partition", db, table))

    def drop_partition(self, db: str, table: str, names):
        from .schema import PartitionInfo

        with self._mu:
            t = self.info_schema().table(db, table)
            pi = t.partition_info
            if pi is None:
                raise KVError(f"table {table} is not partitioned")
            if pi.kind != "range":
                raise KVError("DROP PARTITION applies to RANGE tables"
                               " (use COALESCE PARTITION for HASH)")
            want = {n.lower() for n in names}
            have = {p.name.lower() for p in pi.defs}
            missing = want - have
            if missing:
                raise KVError(f"no partition named {sorted(missing)}")
            keep = [p for p in pi.defs if p.name.lower() not in want]
            if not keep:
                raise KVError("cannot drop every partition "
                               "(use DROP TABLE instead)")
            dropped = [p for p in pi.defs if p.name.lower() in want]
            for pd in dropped:
                self.storage.drop_table(pd.id)
                self._notify_drop(pd.id)
            new_pi = PartitionInfo(pi.kind, pi.column, keep)
            self._replace_table_locked(db, table, t, partition_info=new_pi)
            self._persist()
            self._record_locked(DDLJob(self.gen_id(), "drop_partition", db, table))

    def truncate_partition(self, db: str, table: str, names):
        from .schema import PartitionDef, PartitionInfo

        with self._mu:
            t = self.info_schema().table(db, table)
            pi = t.partition_info
            if pi is None:
                raise KVError(f"table {table} is not partitioned")
            want = {n.lower() for n in names}
            have = {p.name.lower() for p in pi.defs}
            missing = want - have
            if missing:
                raise KVError(f"no partition named {sorted(missing)}")
            out = []
            for pd in pi.defs:
                if pd.name.lower() in want:
                    # fresh physical id, fresh store (TruncateTable rule:
                    # readers holding the old snapshot keep the old id)
                    self.storage.drop_table(pd.id)
                    self._notify_drop(pd.id)
                    new_pd = PartitionDef(self.gen_id(), pd.name,
                                          pd.less_than)
                    self.storage.create_table(new_pd.id, t.storage_columns())
                    self._touch_locked(new_pd.id)
                    out.append(new_pd)
                else:
                    out.append(pd)
            new_pi = PartitionInfo(pi.kind, pi.column, out)
            self._replace_table_locked(db, table, t, partition_info=new_pi)
            self._persist()
            self._record_locked(DDLJob(self.gen_id(), "truncate_partition", db,
                                table))

    def coalesce_partition(self, db: str, table: str, n: int):
        with self._mu:
            t = self.info_schema().table(db, table)
            pi = t.partition_info
            if pi is None:
                raise KVError(f"table {table} is not partitioned")
            if pi.kind != "hash":
                raise KVError("COALESCE PARTITION applies to HASH tables")
            if n <= 0 or n >= len(pi.defs):
                raise KVError(
                    f"cannot coalesce {n} of {len(pi.defs)} partitions")
            self._rehash_partitions(db, t, len(pi.defs) - n)

    def _rehash_partitions(self, db: str, t: TableInfo, new_num: int):
        """Re-bucket a HASH table to `new_num` partitions: fold committed
        deltas, read every row, route by abs(key) %% new_num into fresh
        stores (MySQL's hash reorganization copies rows the same way).

        Concurrency: the old stores are DETACHED before any row is read,
        so a commit racing the rebuild fails with 'no storage for table'
        (the DDL-aborts-concurrent-writer rule) instead of silently
        landing in a store that is about to be destroyed.  A store with
        live prewrite locks aborts the DDL and everything reattaches."""
        from .schema import PartitionDef, PartitionInfo

        pi = t.partition_info
        off = t.find_column(pi.column).offset
        n_cols = len(t.storage_columns())
        old = {pd.id: self.storage.detach_table(pd.id) for pd in pi.defs}
        # The fold TSO is taken AFTER every store is detached: a commit
        # racing the rebuild either finished before its store detached
        # (commit_ts < ts — folded below) or hits a detached store and
        # aborts.  Taken earlier, a commit landing between the ts capture
        # and detach would get commit_ts > ts and compact(ts) would
        # silently discard it (round-5 ADVICE).
        ts = self.storage.current_ts()
        parts_data = []
        try:
            for pd in pi.defs:
                store = old[pd.id]
                store.compact(ts)  # raises on live locks: DDL loses
                parts_data.append(store.base_chunk(
                    range(n_cols), 0, store.base_rows,
                    decode_strings=True))
        except Exception:
            for pid, st in old.items():
                if st is not None:
                    self.storage.attach_table(pid, st)
            raise
        new_defs = [PartitionDef(self.gen_id(), f"p{i}", None)
                    for i in range(new_num)]
        for pd in pi.defs:
            st = old.get(pd.id)
            if st is not None and st.persister is not None:
                st.persister.remove()
            self._notify_drop(pd.id)
        stores = {}
        for pd in new_defs:
            stores[pd.id] = self.storage.create_table(
                pd.id, t.storage_columns())
            self._touch_locked(pd.id)
        for chunk in parts_data:
            n = chunk.num_rows
            if not n:
                continue
            key = chunk.col(off)
            ridx = np.abs(key.data.astype(np.int64)) % new_num
            ridx = np.where(key.validity(), ridx, 0)
            for b, pd in enumerate(new_defs):
                m = ridx == b
                if not m.any():
                    continue
                arrays, valids = [], []
                for ci in range(n_cols):
                    col = chunk.col(ci)
                    arrays.append(col.data[m])
                    valids.append(col.validity()[m])
                stores[pd.id].bulk_load_arrays(arrays, valids, ts)
        new_pi = PartitionInfo(pi.kind, pi.column, new_defs)
        self._replace_table_locked(db, t.name, t, partition_info=new_pi)
        self._persist()
        self._record_locked(DDLJob(self.gen_id(), "rehash_partition", db, t.name))

    def _rebuild_storage(self, t: TableInfo, new_cols: List[ColumnInfo],
                         add_default=None, drop: str = None, retype=None,
                         rename=None):
        """Rewrite the TableStore for a column-layout change.  Committed
        delta folds in (compact), so the new store is base-only.  For a
        partitioned table every partition store is rebuilt."""
        for pid in t.physical_ids():
            self._rebuild_one_store(pid, t, new_cols, add_default, drop,
                                    retype, rename)

    def _rebuild_one_store(self, store_id: int, t: TableInfo,
                           new_cols: List[ColumnInfo],
                           add_default=None, drop: str = None, retype=None,
                           rename=None):
        store = self.storage.table(store_id)
        ts = self.storage.current_ts()
        store.compact(ts)
        old_names = [c.name for c in t.columns]
        chunk = store.base_chunk(range(store.n_cols), 0, store.base_rows)
        n = chunk.num_rows
        arrays, valids = [], []
        for c in new_cols:
            if add_default is not None and c is add_default[0]:
                default = add_default[1]
                ft = c.ftype
                if ft.kind == TypeKind.STRING:
                    arr = np.full(n, "" if default is None else str(default),
                                  dtype=object)
                else:
                    arr = np.full(n, 0 if default is None else default,
                                  dtype=ft.np_dtype)
                valid = np.full(n, default is not None, dtype=np.bool_)
            else:
                src_name = c.name
                if rename is not None and c.name == rename[1]:
                    src_name = rename[0]  # CHANGE COLUMN: data moves over
                oi = old_names.index(src_name)
                col = chunk.col(oi)
                arr, valid = col.data, col.validity()
                if retype is not None and oi == retype[0]:
                    arr = _convert_array(arr, valid, t.columns[oi].ftype,
                                         retype[1])
            arrays.append(arr)
            valids.append(valid)
        # keep the persisted snapshot until the replacement is written:
        # the new store's save_base atomically replaces the same files, so
        # a crash mid-ALTER leaves the OLD consistent state (catalog.json
        # only advances after this method returns)
        self.storage.drop_table(store_id, keep_files=True)
        self._notify_drop(store_id)
        new_store = self.storage.create_table(
            store_id, [(c.name, c.ftype) for c in new_cols]
        )
        if n:
            new_store.bulk_load_arrays(arrays, valids, ts)
        elif new_store.persister is not None:
            # empty table: still replace the on-disk snapshot so the old
            # layout can't be reloaded against the new schema
            new_store.persister.save_base(new_store)

    # ------------------------------------------------------------------
    # persistence (checkpoint/resume story, SURVEY.md §5)
    # ------------------------------------------------------------------
    def to_json(self) -> str:
        with self._mu:
            return json.dumps({
                "version": self.schema_version,
                "next_id": self._next_id,
                "dbs": {k: d.to_dict() for k, d in self._dbs.items()},
                "jobs": [j.to_dict() for j in self.jobs[-64:]],
            })

    def load_json(self, blob: str):
        with self._mu:
            d = json.loads(blob)
            self.schema_version = d["version"]
            self._next_id = d["next_id"]
            self._dbs = {k: DBInfo.from_dict(v) for k, v in d["dbs"].items()}
            self.jobs = [DDLJob.from_dict(j) for j in d.get("jobs", [])]
            self._snapshot = None
            for db in self._dbs.values():
                for t in db.tables.values():
                    if t.is_view:
                        continue
                    for pid in t.physical_ids():
                        if not self.storage.has_table(pid):
                            self.storage.create_table(pid,
                                                      t.storage_columns())


def _convert_array(arr, valid, old_ft: FieldType, new_ft: FieldType):
    from ..chunk import Column
    from ..expr.builtins import cast_vec
    from ..expr.vec import Vec

    v = Vec(old_ft, arr, np.asarray(valid))
    return cast_vec(v, new_ft).data
