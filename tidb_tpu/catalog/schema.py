"""Schema objects: column / index / table / database metadata.

Reference: pingcap/parser's model package (model.TableInfo et al.) as consumed
by infoschema (infoschema/tables.go) and ddl (ddl/ddl_api.go).  Kept
JSON-serializable so the whole catalog can be checkpointed and reloaded
("all state reconstructible from the host store", SURVEY.md §5).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..types import FieldType, TypeKind


# F1 online-schema-change states (ddl_worker.go:466-469).  Columns/indexes
# move through the ladder one schema version at a time so concurrent readers
# at most one version behind stay correct.
STATE_NONE = "none"
STATE_DELETE_ONLY = "delete-only"
STATE_WRITE_ONLY = "write-only"
STATE_WRITE_REORG = "write-reorg"
STATE_PUBLIC = "public"


@dataclass
class ColumnInfo:
    name: str
    ftype: FieldType
    offset: int = 0
    default: object = None  # python literal; None + not has_default -> NULL
    has_default: bool = False
    auto_increment: bool = False
    primary_key: bool = False
    state: str = STATE_PUBLIC
    comment: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ftype": [int(self.ftype.kind), self.ftype.nullable,
                      self.ftype.precision, self.ftype.scale,
                      list(self.ftype.elems)],
            "offset": self.offset,
            "default": self.default,
            "has_default": self.has_default,
            "auto_increment": self.auto_increment,
            "primary_key": self.primary_key,
            "state": self.state,
        }

    @staticmethod
    def from_dict(d: dict) -> "ColumnInfo":
        ft = d["ftype"]
        k, nl, p, s = ft[:4]
        elems = tuple(ft[4]) if len(ft) > 4 else ()
        return ColumnInfo(
            d["name"], FieldType(TypeKind(k), nl, p, s, elems), d["offset"],
            d["default"], d["has_default"], d["auto_increment"],
            d["primary_key"], d.get("state", STATE_PUBLIC),
        )


@dataclass
class PartitionDef:
    """One partition of a partitioned table.  Each partition owns a full
    physical table id -> its own TableStore + region set, so a partition IS
    a shard group (SURVEY.md §2.6): per-partition scans fan out over mesh
    tiles exactly like independent tables.

    Reference: model.PartitionDefinition as used by table/tables/partition.go
    (each partition has its own physical table ID there too)."""

    id: int
    name: str
    # RANGE: exclusive upper bound; None = MAXVALUE.  Unused for HASH.
    less_than: Optional[int] = None

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name, "less_than": self.less_than}

    @staticmethod
    def from_dict(d: dict) -> "PartitionDef":
        return PartitionDef(d["id"], d["name"], d.get("less_than"))


@dataclass
class PartitionInfo:
    """RANGE / HASH partitioning over a single column.

    Reference: model.PartitionInfo + the pruning contract of
    planner/core/rule_partition_processor.go (single-column partition
    expressions are the prunable subset there as well)."""

    kind: str  # "range" | "hash"
    column: str
    defs: List[PartitionDef] = field(default_factory=list)

    def ids(self) -> List[int]:
        return [p.id for p in self.defs]

    def find(self, name: str) -> Optional[PartitionDef]:
        lname = name.lower()
        for p in self.defs:
            if p.name.lower() == lname:
                return p
        return None

    def partition_for_value(self, v) -> PartitionDef:
        """Route a partition-column value to its partition (write path).
        NULL sorts below every value: lowest RANGE partition / hash bucket 0
        (MySQL partitioning NULL handling)."""
        if self.kind == "hash":
            if v is None:
                return self.defs[0]
            # MySQL/TiDB locateHashPartition: abs of the TRUNCATED
            # remainder (Go %), equal to abs(v) % n — not Python's floored
            # modulo; negative keys must land in the reference's bucket
            return self.defs[abs(int(v)) % len(self.defs)]
        if v is None:
            return self.defs[0]
        v = int(v)
        for p in self.defs:
            if p.less_than is None or v < p.less_than:
                return p
        from ..errors import KVError

        raise KVError(
            f"Table has no partition for value {v}"
        )

    def to_dict(self) -> dict:
        return {"kind": self.kind, "column": self.column,
                "defs": [p.to_dict() for p in self.defs]}

    @staticmethod
    def from_dict(d: dict) -> "PartitionInfo":
        return PartitionInfo(d["kind"], d["column"],
                             [PartitionDef.from_dict(p) for p in d["defs"]])


@dataclass
class IndexInfo:
    id: int
    name: str
    columns: List[str]
    unique: bool = False
    primary: bool = False
    state: str = STATE_PUBLIC

    def to_dict(self) -> dict:
        return {
            "id": self.id, "name": self.name, "columns": list(self.columns),
            "unique": self.unique, "primary": self.primary, "state": self.state,
        }

    @staticmethod
    def from_dict(d: dict) -> "IndexInfo":
        return IndexInfo(d["id"], d["name"], list(d["columns"]),
                         d["unique"], d["primary"], d.get("state", STATE_PUBLIC))


@dataclass
class TableInfo:
    id: int
    name: str
    columns: List[ColumnInfo]
    indexes: List[IndexInfo] = field(default_factory=list)
    # offset of the integer PK column used as row handle, or -1.  Mirrors
    # TiDB's PKIsHandle (int primary key == row key).
    pk_is_handle: int = -1
    auto_inc_id: int = 1
    comment: str = ""
    is_view: bool = False
    view_select: str = ""  # original SELECT text for views
    partition_info: Optional[PartitionInfo] = None
    # FOREIGN KEY metadata (stored + displayed, unenforced — the
    # reference's support level, ddl_api.go:3509): list of dicts
    # {name, columns, ref_db, ref_table, ref_columns}
    foreign_keys: List[dict] = field(default_factory=list)

    @property
    def is_partitioned(self) -> bool:
        return self.partition_info is not None

    def partition_table(self, pd: PartitionDef) -> "TableInfo":
        """A view of one partition as its own physical table (executors and
        the txn layer address partitions by their physical id, like
        table/tables/partition.go's partition objects)."""
        return TableInfo(pd.id, self.name, self.columns, self.indexes,
                         self.pk_is_handle, self.auto_inc_id, self.comment)

    def physical_ids(self) -> List[int]:
        if self.partition_info is not None:
            return self.partition_info.ids()
        return [self.id]

    def public_columns(self) -> List[ColumnInfo]:
        return [c for c in self.columns if c.state == STATE_PUBLIC]

    def writable_columns(self) -> List[ColumnInfo]:
        return [
            c for c in self.columns
            if c.state in (STATE_PUBLIC, STATE_WRITE_ONLY, STATE_WRITE_REORG)
        ]

    def find_column(self, name: str) -> Optional[ColumnInfo]:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        return None

    def find_index(self, name: str) -> Optional[IndexInfo]:
        lname = name.lower()
        for ix in self.indexes:
            if ix.name.lower() == lname:
                return ix
        return None

    def col_offsets(self, names: List[str]) -> List[int]:
        return [self.find_column(n).offset for n in names]

    def storage_columns(self) -> List[Tuple[str, FieldType]]:
        """(name, ftype) pairs in storage layout order."""
        return [(c.name, c.ftype) for c in self.columns]

    def to_dict(self) -> dict:
        return {
            "id": self.id, "name": self.name,
            "columns": [c.to_dict() for c in self.columns],
            "indexes": [i.to_dict() for i in self.indexes],
            "pk_is_handle": self.pk_is_handle,
            "auto_inc_id": self.auto_inc_id,
            "is_view": self.is_view,
            "view_select": self.view_select,
            "partition_info": (self.partition_info.to_dict()
                               if self.partition_info else None),
            "foreign_keys": [dict(fk) for fk in self.foreign_keys],
            "comment": self.comment,
        }

    @staticmethod
    def from_dict(d: dict) -> "TableInfo":
        pi = d.get("partition_info")
        return TableInfo(
            d["id"], d["name"],
            [ColumnInfo.from_dict(c) for c in d["columns"]],
            [IndexInfo.from_dict(i) for i in d["indexes"]],
            d.get("pk_is_handle", -1), d.get("auto_inc_id", 1),
            comment=d.get("comment", ""),
            is_view=d.get("is_view", False),
            view_select=d.get("view_select", ""),
            partition_info=PartitionInfo.from_dict(pi) if pi else None,
            foreign_keys=[dict(fk) for fk in d.get("foreign_keys", [])],
        )


@dataclass
class DBInfo:
    id: int
    name: str
    tables: dict = field(default_factory=dict)  # lower name -> TableInfo

    def to_dict(self) -> dict:
        return {
            "id": self.id, "name": self.name,
            "tables": {k: t.to_dict() for k, t in self.tables.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "DBInfo":
        return DBInfo(
            d["id"], d["name"],
            {k: TableInfo.from_dict(t) for k, t in d["tables"].items()},
        )
