from .column import Column
from .chunk import Chunk, DEFAULT_CHUNK_SIZE, chunk_from_pylists, concat_chunks
from .codec import encode_chunk, decode_chunk

__all__ = [
    "Column",
    "Chunk",
    "DEFAULT_CHUNK_SIZE",
    "chunk_from_pylists",
    "concat_chunks",
    "encode_chunk",
    "decode_chunk",
]
