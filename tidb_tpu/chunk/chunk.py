"""Chunk: an ordered batch of equal-length Columns.

Reference: /root/reference/util/chunk/chunk.go:32 (Chunk), :152-166
(RequiredRows early stop), iterator.go (Iterator4Chunk).  Executors pull
chunks through ``Next(chunk)``; a chunk of 0 rows signals exhaustion.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..types import FieldType
from .column import Column

# Default max rows per chunk flowing between root executors (reference
# variable tidb_max_chunk_size, default 1024).
DEFAULT_CHUNK_SIZE = 1024


class Chunk:
    __slots__ = ("columns",)

    def __init__(self, columns: List[Column]):
        self.columns = columns
        if columns:
            n = len(columns[0])
            for c in columns[1:]:
                assert len(c) == n, "ragged chunk"

    # ---- constructors --------------------------------------------------
    @staticmethod
    def empty(ftypes: Sequence[FieldType]) -> "Chunk":
        return Chunk([Column.from_values(ft, []) for ft in ftypes])

    @staticmethod
    def from_columns(columns: List[Column]) -> "Chunk":
        return Chunk(columns)

    # ---- shape ---------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def ftypes(self) -> List[FieldType]:
        return [c.ftype for c in self.columns]

    def __len__(self) -> int:
        return self.num_rows

    # ---- access --------------------------------------------------------
    def col(self, i: int) -> Column:
        return self.columns[i]

    def row(self, i: int) -> tuple:
        return tuple(c.get(i) for c in self.columns)

    def iter_rows(self) -> Iterator[tuple]:
        for i in range(self.num_rows):
            yield self.row(i)

    def to_pylist(self) -> list:
        """List of row tuples (test/result-set friendly)."""
        return [self.row(i) for i in range(self.num_rows)]

    # ---- transforms ----------------------------------------------------
    def take(self, idx: np.ndarray) -> "Chunk":
        return Chunk([c.take(idx) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "Chunk":
        return Chunk([c.filter(mask) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Chunk":
        return Chunk([c.slice(start, stop) for c in self.columns])

    def select(self, col_idx: Sequence[int]) -> "Chunk":
        return Chunk([self.columns[i] for i in col_idx])

    def append(self, other: "Chunk") -> "Chunk":
        assert self.num_cols == other.num_cols
        return Chunk([a.concat(b) for a, b in zip(self.columns, other.columns)])

    def split(self, max_rows: int = DEFAULT_CHUNK_SIZE) -> Iterator["Chunk"]:
        n = self.num_rows
        if n == 0:
            return
        for s in range(0, n, max_rows):
            yield self.slice(s, min(s + max_rows, n))

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def __repr__(self):
        return f"Chunk(rows={self.num_rows}, cols={self.num_cols})"


def chunk_from_pylists(ftypes: Sequence[FieldType], cols: Sequence[Sequence]) -> Chunk:
    assert len(ftypes) == len(cols)
    return Chunk([Column.from_values(ft, vs) for ft, vs in zip(ftypes, cols)])


def concat_chunks(chunks: Sequence[Chunk]) -> Optional[Chunk]:
    chunks = [c for c in chunks if c is not None and c.num_rows >= 0]
    if not chunks:
        return None
    out = chunks[0]
    for c in chunks[1:]:
        out = out.append(c)
    return out
