"""Chunk wire codec.

Reference: /root/reference/util/chunk/codec.go (Arrow-chunk RPC encoding used
when ``canUseChunkRPC``, distsql/distsql.go:147-188).  Our wire format is a
simple length-prefixed layout: a JSON header (ftypes, row count, per-column
flags) + raw little-endian buffers.  It exists so the distsql layer has a real
serialization boundary (multi-host DCN transport serializes through this), and
so fault-injection tests can corrupt/travel bytes.
"""

from __future__ import annotations

import json
import struct
from typing import List

import numpy as np

from ..types import FieldType, TypeKind
from .chunk import Chunk
from .column import Column

_MAGIC = b"TPCH"  # tidb-tpu chunk
_VERSION = 1


def _col_header(c: Column) -> dict:
    return {
        "kind": int(c.ftype.kind),
        "nullable": c.ftype.nullable,
        "precision": c.ftype.precision,
        "scale": c.ftype.scale,
        "elems": list(c.ftype.elems),
        "has_valid": c.valid is not None,
    }


def encode_chunk(chunk: Chunk) -> bytes:
    parts: List[bytes] = []
    header = {
        "version": _VERSION,
        "rows": chunk.num_rows,
        "cols": [_col_header(c) for c in chunk.columns],
    }
    for c in chunk.columns:
        if c.data.dtype == object:
            # Arrow-style varlen layout: int64 offsets (n+1) + utf-8 data
            # buffer.  Covers STRING, JSON texts, and wide-decimal Python
            # ints (as decimal digit strings).
            encs = [str(x).encode("utf-8") for x in c.data]
            offsets = np.zeros(len(encs) + 1, dtype=np.int64)
            np.cumsum([len(e) for e in encs], out=offsets[1:])
            parts.append(offsets.tobytes() + b"".join(encs))
        else:
            parts.append(np.ascontiguousarray(c.data).tobytes())
        if c.valid is not None:
            parts.append(np.packbits(c.valid).tobytes())
        else:
            parts.append(b"")
    hdr = json.dumps(header).encode("utf-8")
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<I", len(hdr))
    out += hdr
    for p in parts:
        out += struct.pack("<Q", len(p))
        out += p
    return bytes(out)


def decode_chunk(buf: bytes) -> Chunk:
    assert buf[:4] == _MAGIC, "bad chunk magic"
    off = 4
    (hlen,) = struct.unpack_from("<I", buf, off)
    off += 4
    header = json.loads(buf[off : off + hlen].decode("utf-8"))
    off += hlen
    rows = header["rows"]
    cols: List[Column] = []

    def read_part():
        nonlocal off
        (n,) = struct.unpack_from("<Q", buf, off)
        off += 8
        p = buf[off : off + n]
        off += n
        return p

    for ch in header["cols"]:
        ft = FieldType(
            TypeKind(ch["kind"]), ch["nullable"], ch["precision"],
            ch["scale"], tuple(ch.get("elems", ())),
        )
        raw = read_part()
        if ft.np_dtype == object:
            data = np.empty(rows, dtype=object)
            wide_dec = ft.kind == TypeKind.DECIMAL
            if rows:
                off_end = (rows + 1) * 8
                offsets = np.frombuffer(raw[:off_end], dtype=np.int64)
                sbuf = raw[off_end:]
                assert offsets[-1] == len(sbuf), "string column buffer mismatch"
                for i in range(rows):
                    txt = sbuf[offsets[i] : offsets[i + 1]].decode("utf-8")
                    data[i] = int(txt) if wide_dec else txt
        else:
            data = np.frombuffer(raw, dtype=ft.np_dtype).copy()
        vraw = read_part()
        valid = None
        if ch["has_valid"]:
            valid = np.unpackbits(np.frombuffer(vraw, dtype=np.uint8))[:rows].astype(
                np.bool_
            )
        cols.append(Column(ft, data, valid))
    return Chunk(cols)
