"""Columnar vector with Arrow-style validity.

Reference: /root/reference/util/chunk/column.go:59-67 — nullBitmap / offsets /
data / elemBuf.  TPU-native departure: instead of byte-packed bitmaps and
variable-length byte buffers, a Column is

- ``data``: a dense numpy array of the type's physical dtype (object dtype for
  host-side strings), always length ``n``
- ``valid``: None (all rows valid) or a bool numpy array, True = non-NULL

Fixed-width everything means a column converts to a jax array with zero copies
or reshapes; strings are dictionary-encoded before they reach a device (see
store/blockstore.py).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from ..types import FieldType, TypeKind


class Column:
    __slots__ = ("ftype", "data", "valid")

    def __init__(self, ftype: FieldType, data: np.ndarray, valid: Optional[np.ndarray] = None):
        self.ftype = ftype
        self.data = data
        if valid is not None and valid.dtype != np.bool_:
            valid = valid.astype(np.bool_)
        if valid is not None and bool(valid.all()):
            valid = None  # normalize: all-valid -> None
        self.valid = valid

    # ---- constructors -------------------------------------------------
    @staticmethod
    def _object_fill(ftype: FieldType) -> object:
        """NULL placeholder inside object-dtype data arrays."""
        if ftype.kind == TypeKind.DECIMAL:
            return 0  # wide decimal: exact Python ints
        return ""  # STRING / JSON

    @staticmethod
    def from_values(ftype: FieldType, values: Sequence) -> "Column":
        """Build from a python sequence of PHYSICAL-repr values (scaled ints
        for decimals, member indexes for enums, ...); None entries -> NULL."""
        n = len(values)
        valid = np.fromiter((v is not None for v in values), dtype=np.bool_, count=n)
        all_valid = bool(valid.all())
        dt = ftype.np_dtype
        if dt == object:
            fill = Column._object_fill(ftype)
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                data[i] = v if v is not None else fill
        else:
            data = np.zeros(n, dtype=dt)
            if all_valid:
                data[:] = np.asarray(values, dtype=dt)
            else:
                for i, v in enumerate(values):
                    if v is not None:
                        data[i] = v
        return Column(ftype, data, None if all_valid else valid)

    @staticmethod
    def nulls(ftype: FieldType, n: int) -> "Column":
        if ftype.np_dtype == object:
            data = np.empty(n, dtype=object)
            data[:] = Column._object_fill(ftype)
        else:
            data = np.zeros(n, dtype=ftype.np_dtype)
        return Column(ftype, data, np.zeros(n, dtype=np.bool_))

    @staticmethod
    def constant(ftype: FieldType, value, n: int) -> "Column":
        if value is None:
            return Column.nulls(ftype, n)
        if ftype.np_dtype == object:
            data = np.empty(n, dtype=object)
            data[:] = value
        else:
            data = np.full(n, value, dtype=ftype.np_dtype)
        return Column(ftype, data)

    # ---- basic properties ---------------------------------------------
    def __len__(self) -> int:
        return len(self.data)

    @property
    def has_nulls(self) -> bool:
        return self.valid is not None

    def validity(self) -> np.ndarray:
        """Materialized bool validity array (True = non-NULL)."""
        if self.valid is None:
            return np.ones(len(self.data), dtype=np.bool_)
        return self.valid

    def null_count(self) -> int:
        return 0 if self.valid is None else int((~self.valid).sum())

    def is_null(self, i: int) -> bool:
        return self.valid is not None and not bool(self.valid[i])

    def get(self, i: int):
        """Python scalar at row i (None for NULL)."""
        if self.is_null(i):
            return None
        v = self.data[i]
        if isinstance(v, np.generic):
            return v.item()
        return v

    # ---- transforms ----------------------------------------------------
    def take(self, idx: np.ndarray) -> "Column":
        return Column(
            self.ftype,
            self.data[idx],
            None if self.valid is None else self.valid[idx],
        )

    def filter(self, mask: np.ndarray) -> "Column":
        return Column(
            self.ftype,
            self.data[mask],
            None if self.valid is None else self.valid[mask],
        )

    def slice(self, start: int, stop: int) -> "Column":
        return Column(
            self.ftype,
            self.data[start:stop],
            None if self.valid is None else self.valid[start:stop],
        )

    def concat(self, other: "Column") -> "Column":
        data = np.concatenate([self.data, other.data])
        if self.valid is None and other.valid is None:
            valid = None
        else:
            valid = np.concatenate([self.validity(), other.validity()])
        return Column(self.ftype, data, valid)

    def copy(self) -> "Column":
        return Column(
            self.ftype,
            self.data.copy(),
            None if self.valid is None else self.valid.copy(),
        )

    def to_pylist(self) -> list:
        return [self.get(i) for i in range(len(self))]

    def nbytes(self) -> int:
        b = self.data.nbytes if self.data.dtype != object else sum(
            len(str(x)) for x in self.data
        )
        if self.valid is not None:
            b += self.valid.nbytes
        return int(b)

    def __repr__(self):
        return f"Column({self.ftype!r}, n={len(self)}, nulls={self.null_count()})"
