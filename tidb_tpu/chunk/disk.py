"""Disk spill for chunk lists.

Reference: util/chunk/disk.go:60-147 (ListInDisk) — chunks serialize through
the wire codec into a temp file; readback streams them in insertion order.
"""

from __future__ import annotations

import os
import struct
import tempfile
from typing import Iterator, List, Optional

from .chunk import Chunk
from .codec import decode_chunk, encode_chunk


class ListInDisk:
    def __init__(self, label: str = "spill"):
        self._f = tempfile.TemporaryFile(prefix=f"tidbtpu-{label}-")
        self._offsets: List[int] = []
        self.n_chunks = 0
        self.n_rows = 0
        self.bytes_written = 0

    def add(self, chunk: Chunk):
        buf = encode_chunk(chunk)
        self._offsets.append(self._f.tell())
        self._f.write(struct.pack("<Q", len(buf)))
        self._f.write(buf)
        self.n_chunks += 1
        self.n_rows += chunk.num_rows
        self.bytes_written += len(buf)

    def __iter__(self) -> Iterator[Chunk]:
        for off in self._offsets:
            self._f.seek(off)
            (n,) = struct.unpack("<Q", self._f.read(8))
            yield decode_chunk(self._f.read(n))
        self._f.seek(0, os.SEEK_END)

    def chunk_at(self, i: int) -> Chunk:
        off = self._offsets[i]
        self._f.seek(off)
        (n,) = struct.unpack("<Q", self._f.read(8))
        c = decode_chunk(self._f.read(n))
        self._f.seek(0, os.SEEK_END)
        return c

    def close(self):
        try:
            self._f.close()
        except Exception:
            pass
