"""Multi-host coordination plane (ISSUE 9, ROADMAP "True multi-host
production mesh").

DrJAX (PAPERS.md) scales MapReduce-style primitives across JAX hosts by
letting the collective runtime carry the DATA plane while a thin
coordination layer owns membership; "Query Processing on Tensor
Computation Runtimes" is the same bet from the database side.  This
package is that thin layer for the TPU query engine — three
capabilities, all chaos-tested without real hardware:

1. **Epoch-numbered mesh membership** — the coordinator broadcasts the
   participating process ids and each process's healthy device set (fed
   by its DeviceHealthRegistry).  A breaker trip on ANY host bumps the
   epoch; every process rebuilds the same survivor mesh from the
   broadcast, and an epoch mismatch detected at dispatch time raises
   the typed retriable `CoordEpochMismatch` instead of desyncing an XLA
   collective (copr/parallel.py).
2. **Span forwarding** — workers ship each finished QueryTrace to the
   coordinator at query end (per-host byte cap + drop counter), so
   EXPLAIN ANALYZE / SLOW_QUERY / /status show ONE tree spanning hosts
   (trace/export.py).
3. **Session-state handoff** — `shutdown(drain_s)` parks prepared
   statements + session sysvars on the coordinator; the replacement
   process replays them when it rejoins at a new epoch, so a rolling
   restart loses no prepared sessions (lifecycle/handoff.py).

The plane is jax-free by contract (purity lint covers this package):
it moves plain ints and JSON, never device arrays, and the membership
epoch is host-side control state that must never capture into compiled
code (lint.kernelcheck traces the fused mesh corpus across epoch bumps
and requires identical jaxprs).
"""

from __future__ import annotations

import threading
from typing import Optional

from .membership import CoordEpochMismatch, MembershipView  # noqa: F401
from .plane import (  # noqa: F401
    Coordinator,
    CoordinatorPlane,
    LocalPlane,
    WorkerPlane,
)
from ..util_concurrency import make_lock

_PLANE = None
_PLANE_LOCK = make_lock("coord:_PLANE_LOCK")


def get_plane():
    """The process's active coordination plane — the degenerate
    LocalPlane until a multi-host activation swaps in a TCP plane.
    First use installs the DeviceHealthRegistry epoch hook, so breaker
    transitions renumber the membership epoch from then on."""
    global _PLANE
    if _PLANE is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                plane = LocalPlane()
                _install(plane)
                _PLANE = plane
    return _PLANE


def _install(plane):
    from ..copr.device_health import DEVICE_HEALTH

    DEVICE_HEALTH.set_epoch_hook(plane.on_health_change)


def _swap(plane):
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = plane
        _install(plane)
    return plane


def activate_coordinator(host: str = "127.0.0.1", port: int = 0,
                         pid: int = 0, devices=(), lease_s: float = 5.0,
                         expect: Optional[int] = None) -> CoordinatorPlane:
    """Bind the coordination endpoint in THIS process and join it as
    member `pid` (the coordinator runs queries too — SPMD)."""
    coord = Coordinator(host=host, port=port, lease_s=lease_s,
                        expect=expect, self_pid=pid)
    return _swap(CoordinatorPlane(coord, pid=pid).start(devices))


def activate_worker(addr, pid: int, devices=(),
                    lease_s: float = 5.0) -> WorkerPlane:
    """Join an existing coordinator as member `pid` (retries while the
    coordinator is still binding)."""
    return _swap(WorkerPlane(addr, pid, lease_s=lease_s).start(devices))


def activate_env_plane(addr: str, pid: int, devices,
                       expect: Optional[int] = None,
                       form_timeout_s: float = 45.0):
    """jax.distributed bring-up seam (copr/parallel._maybe_init_multihost
    when TIDB_TPU_COORD_ADDR is set): process 0 binds, everyone else
    joins, and ALL processes block until the cluster FORMS (every
    expected member registered) so the first mesh every process builds
    derives from the same broadcast.  A formation timeout degrades to
    the unfiltered full-device mesh on every process identically (the
    view stays un-formed everywhere until the last member registers)."""
    host, _, port = addr.rpartition(":")
    if pid == 0:
        plane = activate_coordinator(host=host, port=int(port), pid=0,
                                     devices=devices, expect=expect)
    else:
        plane = activate_worker((host, int(port)), pid=pid,
                                devices=devices)
    plane.wait_formed(form_timeout_s)
    return plane


def reset_plane():
    """Tear down the active plane and restore the lazy local default
    (tests; also clears the span-forwarding and epoch hooks)."""
    global _PLANE
    with _PLANE_LOCK:
        plane, _PLANE = _PLANE, None
    if plane is not None:
        try:
            plane.stop()
        except Exception:
            pass
    from ..trace import recorder

    recorder.clear_export_hooks()
    # wiping the chain drops the continuous profiler too — re-chain it
    # so profiling survives plane teardown
    from ..trace import install_profiler

    install_profiler()
    from ..copr.device_health import DEVICE_HEALTH

    DEVICE_HEALTH.set_epoch_hook(None)
