"""Epoch-numbered mesh membership: the broadcast value every process
builds its survivor mesh from.

Reference: the reference's PD keeps an epoch-versioned region/store
topology that every TiKV client caches and re-fetches on a stale-epoch
error (region_cache.go).  Here the "topology" is the set of live
processes and their healthy device sets; the epoch renumbers on EVERY
membership change (join, leave, lease expiry, per-device breaker trip),
so two processes can cheaply agree whether they derived their mesh from
the same broadcast — and a mismatch detected at dispatch time becomes a
typed retriable error instead of an XLA collective desync (DrJAX's
thin-control-plane bet, PAPERS.md).

This module is jax-free by contract: the control plane carries plain
ints (process ids, device ids) and never holds device-array provenance
(enforced by the purity lint over tidb_tpu/coord).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Tuple


@dataclass(frozen=True)
class MembershipView:
    """One broadcast: the epoch plus every live process's healthy device
    ids.  `formed` latches once the expected process count has joined —
    before formation the view is advisory (mesh builds keep the full
    device set, the pre-coordination behavior) and after it the view is
    authoritative (survivor meshes exclude lost members' devices)."""

    epoch: int
    members: Dict[int, Tuple[int, ...]] = field(default_factory=dict)
    formed: bool = True
    #: per-member data-plane RPC address ("host:port"), present only for
    #: members that registered one (the dataplane subsystem, ISSUE 18);
    #: membership-only deployments carry an empty dict
    addrs: Dict[int, str] = field(default_factory=dict)

    def device_ids(self) -> FrozenSet[int]:
        out = set()
        for ids in self.members.values():
            out.update(ids)
        return frozenset(out)


class CoordEpochMismatch(RuntimeError):
    """The membership epoch advanced between mesh build and dispatch (a
    member was lost, rejoined, or reported a device unhealthy on some
    host).  Typed and retriable BY DESIGN: the dispatcher rebuilds the
    mesh from the current broadcast and re-runs, instead of launching an
    XLA collective whose participant set no longer matches what the
    other hosts will launch — the desync that otherwise presents as a
    hang.  The message deliberately avoids device-failure vocabulary so
    device_health.classify_failure can never mistake it for a chip
    fault (no breaker trips, no cache evictions)."""

    def __init__(self, built_at, current):
        super().__init__(
            f"mesh membership epoch advanced {built_at} -> {current}; "
            "rebuilding over the current member set")
        self.built_at = built_at
        self.current = current
