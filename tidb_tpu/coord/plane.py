"""Coordination-plane transports: local loopback, TCP coordinator, TCP
worker.

The data plane (scans, exchanges, aggregation merges) rides XLA's
collective runtime; this module is the deliberately small control plane
that owns what collectives cannot: WHO is in the mesh (epoch-numbered
membership with lease-based liveness), getting worker span trees back
into the coordinator's trace ring, and parking drained sessions for a
rolling restart.  The wire format is one JSON line per request over a
short-lived localhost/DCN TCP connection — no new dependencies, and
deliberately not jax.distributed's KV store so the plane keeps working
(and keeps being testable) in environments where the gRPC coordination
service cannot form.

Three interchangeable planes share one duck-typed surface (view /
current_epoch / bump / publish_local / on_health_change / forward_trace
/ handoff_put / take_handoff / wait_formed / leave / stop):

- ``LocalPlane``: single-process degenerate loops — epoch bumps ride the
  DeviceHealthRegistry hook, membership is this process's healthy device
  set, handoff is an in-memory parking lot that survives server
  restarts within the process.  The tier-1 CPU suite exercises the
  whole plane through it without spawning workers.
- ``Coordinator`` + ``CoordinatorPlane``: process 0 binds the TCP
  endpoint and is also member 0 (multi-controller SPMD: the coordinator
  runs queries too).
- ``WorkerPlane``: every other process; registers, heartbeats a lease,
  reports breaker trips, forwards finished traces, parks handoff state.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..metrics import REGISTRY
from ..store.fault import FAILPOINTS
from .membership import MembershipView
from ..util_concurrency import make_lock, make_rlock, witness_wait_check


def _span_cap_bytes() -> int:
    """Per-host byte cap on one forwarded span payload (worker AND
    coordinator enforce it); oversize trees drop with a counter instead
    of bloating the control plane."""
    try:
        return int(os.environ.get("TIDB_TPU_COORD_SPAN_CAP",
                                  str(256 * 1024)))
    except ValueError:
        return 256 * 1024


def _hit_handoff(pid: int, n: int):
    # chaos site: a raised action simulates a handoff lost mid-drain
    # (coordinator unreachable, payload refused); callers must degrade
    # to "sessions lost, drain still completes"
    FAILPOINTS.hit("coord/handoff", pid=pid, sessions=n)


def _local_fleet_payload(refresh_memory: bool = True) -> dict:
    """This process's metric snapshot for fleet aggregation (counters +
    histograms + gauges), with the device-cache gauges refreshed first
    so HBM watermarks travel with it (skippable when the caller just
    refreshed them — e.g. the /status memory section)."""
    if refresh_memory:
        try:
            from ..copr.cache import memory_stats

            memory_stats()
        except Exception:
            pass
    return REGISTRY.export_fleet_payload()


def _view_from_resp(resp: dict) -> MembershipView:
    return MembershipView(
        epoch=int(resp.get("epoch", 0)),
        members={int(p): tuple(int(d) for d in ids)
                 for p, ids in (resp.get("members") or {}).items()},
        formed=bool(resp.get("formed", True)),
        addrs={int(p): str(a)
               for p, a in (resp.get("addrs") or {}).items()},
    )


class Coordinator:
    """Membership/handoff/span state + the TCP endpoint serving it.

    Liveness is lease-based and LAZILY swept: every state operation
    first expires members whose lease lapsed (any live worker's
    heartbeat therefore evicts a dead peer within ~one lease).  Every
    membership change bumps the epoch; `formed` latches once `expect`
    members have joined and stays latched, so survivor views remain
    authoritative after a loss."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 lease_s: float = 5.0, expect: Optional[int] = None,
                 self_pid: Optional[int] = None, clock=time.monotonic,
                 state_path: Optional[str] = None):
        self.host = host
        self.port = port
        self.lease_s = lease_s
        self.expect = expect
        self.self_pid = self_pid  # exempt from lease expiry (no heartbeat)
        self._clock = clock
        self._mu = make_rlock("coord.plane:Coordinator._mu")
        self._epoch = 0
        self._formed = expect is None
        self._members: Dict[int, dict] = {}
        self._handoff: Dict[int, List[dict]] = {}
        # versioned shared payloads (ISSUE 18): small JSON documents a
        # member publishes for the whole fleet (resource-group
        # definitions today) — piggybacked on EVERY response, so any
        # heartbeat delivers the latest version to every worker.
        # key -> {"v": monotonically increasing int, "doc": payload}
        self._shared: Dict[str, dict] = {}
        # fleet metric snapshots (ISSUE 13): workers piggyback their
        # registry exports on span batches; in-memory only (a restarted
        # coordinator re-learns them within one snapshot interval)
        self._fleet: Dict[int, dict] = {}
        self._save_dirty = False
        self._save_io_mu = make_lock("coord.plane:Coordinator._save_io_mu")
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        # persist-layer backing (ISSUE 12 / ROADMAP coord (b)): epoch,
        # membership and parked handoff survive a coordinator restart —
        # a restarted coordinator REPLAYS the epoch (strictly above any
        # epoch ever broadcast) instead of renumbering from 0, so
        # surviving workers' stamped meshes stay safely "behind" and
        # their parked sessions ride back after the kill
        if state_path is None:
            state_path = os.environ.get("TIDB_TPU_COORD_STATE") or None
        self._persist = None
        if state_path:
            from ..store.persist import JsonStatePersister

            self._persist = JsonStatePersister(state_path)
            self._load_state()

    # ---- persist backing -----------------------------------------------
    def _load_state(self):
        doc = self._persist.load()
        if not doc:
            return
        # under the membership mutex: the RPC listener may already be
        # serving registers while a reopened coordinator replays state,
        # and an unlocked replay can clobber a concurrent join
        with self._mu:
            self._epoch = int(doc.get("epoch", 0))
            now = self._clock()
            for pid_s, m in (doc.get("members") or {}).items():
                self._members[int(pid_s)] = {
                    "devices": tuple(int(d)
                                     for d in m.get("devices", ())),
                    # a fresh lease window: live members re-heartbeat
                    # within one lease, dead ones expire exactly like a
                    # lost member
                    "last_seen": now,
                    "lease_s": float(m.get("lease_s", self.lease_s)),
                    "addr": m.get("addr") or None,
                }
            self._handoff = {int(p): list(v) for p, v in
                             (doc.get("handoff") or {}).items()}
            self._shared = {str(k): {"v": int(s.get("v", 0)),
                                     "doc": s.get("doc")}
                            for k, s in (doc.get("shared") or {}).items()}
            # the restart itself is a membership event: renumber once so
            # every surviving worker rebuilds from the replayed broadcast
            self._epoch += 1
            if self.expect is not None \
                    and len(self._members) >= self.expect:
                self._formed = True
            epoch = self._epoch
            self._save_locked()
        REGISTRY.inc("coord_state_replayed_total")
        REGISTRY.set("coord_epoch", epoch)
        # persist the renumbered epoch IMMEDIATELY: a second restart
        # before any membership change must replay strictly above THIS
        # incarnation's broadcasts, not re-issue the same epoch
        self._flush_state()

    def _save_locked(self):
        """Mark the persisted document dirty; the actual double-fsync
        write happens in _flush_state() AFTER the mutex is released
        (public entry points call it before acking), so a bump storm
        never serializes every membership RPC behind disk I/O."""
        if self._persist is None:
            return
        self._save_dirty = True

    def _flush_state(self):
        """Write the current state document if dirty — called outside
        `self._mu` but BEFORE the mutating RPC acks, so durability
        ordering (e.g. a handoff pop persisted before its response) is
        preserved.  `_save_io_mu` serializes concurrent writers; each
        write snapshots fresh full state, so last-writer-wins is safe."""
        if self._persist is None:
            return
        # take the io lock BEFORE checking the dirty flag: if another
        # thread's in-flight write already snapshotted our change (and
        # cleared the flag), we must WAIT for that write's fsync before
        # acking — an early return on a pre-checked flag would ack a
        # pop whose covering write could still be torn by a crash
        with self._save_io_mu:
            with self._mu:
                if not self._save_dirty:
                    return
                self._save_dirty = False
                doc = {
                    "epoch": self._epoch,
                    "members": {str(p): {"devices": list(m["devices"]),
                                         "lease_s": m.get("lease_s",
                                                          self.lease_s),
                                         "addr": m.get("addr")}
                                for p, m in self._members.items()},
                    "handoff": {str(p): list(v)
                                for p, v in self._handoff.items()},
                    "shared": {k: {"v": s["v"], "doc": s["doc"]}
                               for k, s in self._shared.items()},
                }
            try:
                self._persist.save(doc)
            except OSError:
                REGISTRY.inc("coord_state_save_errors_total")

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(32)
        s.settimeout(0.2)
        self.port = s.getsockname()[1]
        self._sock = s
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="tidb-tpu-coord")
        self._thread.start()
        return self.host, self.port

    def stop(self):
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # ---- membership state ops ------------------------------------------
    def _bump_locked(self, reason: str):
        self._epoch += 1
        REGISTRY.inc("coord_epoch_bumps_total")
        REGISTRY.set("coord_epoch", self._epoch)
        REGISTRY.set("coord_member_count", len(self._members))
        self._save_locked()

    def bump(self, reason: str = ""):
        with self._mu:
            self._bump_locked(reason)
        self._flush_state()

    def _expire_locked(self):
        now = self._clock()
        dead = [pid for pid, m in self._members.items()
                if pid != self.self_pid
                and now - m["last_seen"] > m.get("lease_s", self.lease_s)]
        for pid in dead:
            del self._members[pid]
            self._fleet.pop(pid, None)
            REGISTRY.inc("coord_members_expired_total")
            self._bump_locked(f"member {pid} lease expired")

    def _touch_locked(self, pid: int):
        m = self._members.get(pid)
        if m is not None:
            m["last_seen"] = self._clock()

    def register(self, pid: int, devices,
                 lease_s: Optional[float] = None,
                 addr: Optional[str] = None) -> dict:
        """A process joins (or REJOINS after a restart) with its healthy
        local device ids; any parked handoff state for this pid rides
        back in the response, consumed exactly once.  `addr` is the
        member's data-plane RPC endpoint (ISSUE 18), broadcast with the
        membership so peers can exchange partition fragments."""
        devices = tuple(int(d) for d in devices)
        with self._mu:
            self._expire_locked()
            prev = self._members.get(pid)
            if addr is None and prev is not None:
                addr = prev.get("addr")  # re-register keeps the endpoint
            self._members[pid] = {
                "devices": devices,
                "last_seen": self._clock(),
                "lease_s": float(lease_s or self.lease_s),
                "addr": addr,
            }
            if prev is None or prev["devices"] != devices \
                    or prev.get("addr") != addr:
                self._bump_locked(f"member {pid} joined")
            if self.expect is not None \
                    and len(self._members) >= self.expect:
                self._formed = True
            handoff = self._handoff.pop(pid, [])
            if handoff:
                self._save_locked()  # consumed exactly once, durably
            out = {"view": self._view_locked(), "handoff": handoff}
        self._flush_state()
        return out

    def poll(self, pid: int) -> MembershipView:
        with self._mu:
            self._touch_locked(pid)
            self._expire_locked()
            view = self._view_locked()
        self._flush_state()
        return view

    def report(self, pid: int, healthy_devices) -> MembershipView:
        """A member publishes its CURRENT healthy device set (fed by its
        DeviceHealthRegistry): shrink on a breaker trip, regrow on a
        half-open recovery — either way the epoch renumbers."""
        devices = tuple(int(d) for d in healthy_devices)
        with self._mu:
            m = self._members.get(pid)
            if m is not None:
                m["last_seen"] = self._clock()
                if m["devices"] != devices:
                    m["devices"] = devices
                    self._bump_locked(f"member {pid} health changed")
            self._expire_locked()
            view = self._view_locked()
        self._flush_state()
        return view

    def leave(self, pid: int) -> MembershipView:
        with self._mu:
            if self._members.pop(pid, None) is not None:
                self._bump_locked(f"member {pid} left")
            # a departed member's metric snapshot leaves with it — only
            # lease expiry pruned _fleet otherwise, and an ex-member has
            # no lease to expire
            self._fleet.pop(pid, None)
            self._expire_locked()
            view = self._view_locked()
        self._flush_state()
        return view

    def put_handoff(self, pid: int, states: List[dict]):
        with self._mu:
            self._handoff[pid] = list(states)
            self._touch_locked(pid)
            self._save_locked()
        self._flush_state()
        REGISTRY.inc("coord_handoff_put_total", len(states))

    def pop_handoff(self, pid: int) -> List[dict]:
        with self._mu:
            out = self._handoff.pop(pid, [])
            if out:
                self._save_locked()
        self._flush_state()
        return out

    def ingest_spans(self, pid: int, payload: dict, nbytes: int) -> str:
        """Rebuild a worker's forwarded span tree into this process's
        trace ring — grafted under the matching local trace when the
        qid correlates (ONE tree spanning hosts), standalone otherwise."""
        if nbytes > _span_cap_bytes():
            REGISTRY.inc("coord_spans_dropped_total")
            return "dropped"
        from ..trace.export import graft_or_append

        outcome = graft_or_append(payload, host=pid)
        REGISTRY.inc("coord_spans_ingested_total")
        REGISTRY.inc("coord_span_bytes_total", nbytes)
        if outcome == "grafted":
            REGISTRY.inc("coord_spans_grafted_total")
        with self._mu:
            self._touch_locked(pid)
        return outcome

    def ingest_metrics(self, pid: int, payload: dict):
        """Store a worker's piggybacked metric snapshot (latest wins —
        snapshots are cumulative registry exports, not deltas).  Only
        CURRENT members store: a snapshot racing in after lease expiry /
        leave would otherwise resurrect a ghost host in the fleet view
        with nothing left to prune it."""
        with self._mu:
            if pid not in self._members:
                return
            self._fleet[pid] = dict(payload or {})
            self._touch_locked(pid)
        REGISTRY.inc("coord_metrics_snapshots_total")

    def fleet_snapshot(self, refresh: bool = True) -> Dict[int, dict]:
        """Per-host metric payloads: every worker's latest snapshot plus
        this process's live registry when it is a member itself."""
        with self._mu:
            self._expire_locked()
            snaps = dict(self._fleet)
        if self.self_pid is not None:
            snaps[self.self_pid] = _local_fleet_payload(refresh)
        return snaps

    def _view_locked(self) -> MembershipView:
        return MembershipView(
            epoch=self._epoch,
            members={p: m["devices"] for p, m in self._members.items()},
            formed=self._formed,
            addrs={p: m["addr"] for p, m in self._members.items()
                   if m.get("addr")},
        )

    def view(self) -> MembershipView:
        with self._mu:
            self._expire_locked()
            return self._view_locked()

    # ---- shared fleet payloads (ISSUE 18) -------------------------------
    def shared_put(self, key: str, doc) -> int:
        """Publish one fleet-wide document under `key`; returns the new
        version.  Versions are per-key monotonic; publication is NOT a
        membership change (no epoch bump) — workers pick the new version
        off any subsequent response."""
        with self._mu:
            cur = self._shared.get(key)
            ver = (cur["v"] if cur else 0) + 1
            self._shared[key] = {"v": ver, "doc": doc}
            self._save_locked()
        self._flush_state()
        REGISTRY.inc("coord_shared_puts_total")
        return ver

    def shared_get(self, key: str):
        """(doc, version) for `key`; (None, 0) when never published."""
        with self._mu:
            cur = self._shared.get(key)
            return (cur["doc"], cur["v"]) if cur else (None, 0)

    def shared_version(self, key: str) -> int:
        with self._mu:
            cur = self._shared.get(key)
            return cur["v"] if cur else 0

    def _shared_locked(self) -> dict:
        return {k: {"v": s["v"], "doc": s["doc"]}
                for k, s in self._shared.items()}

    # ---- wire -----------------------------------------------------------
    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True,
                             name="tidb-tpu-coord-conn").start()

    def _handle(self, conn: socket.socket):
        try:
            conn.settimeout(3.0)
            f = conn.makefile("rwb")
            line = f.readline()
            if not line:
                return
            try:
                req = json.loads(line)
                resp = self._dispatch(req, len(line))
            except Exception as e:  # noqa: BLE001 — protocol boundary
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            f.write(json.dumps(resp).encode() + b"\n")
            f.flush()
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, req: dict, nbytes: int) -> dict:
        cmd = req.get("cmd")
        pid = int(req.get("pid", -1))
        if cmd == "register":
            out = self.register(pid, req.get("devices") or (),
                                req.get("lease_s"), addr=req.get("addr"))
            return self._resp(out["view"], handoff=out["handoff"])
        if cmd == "shared_put":
            ver = self.shared_put(str(req.get("key")), req.get("doc"))
            with self._mu:
                self._touch_locked(pid)
            return self._resp(self.view(), version=ver)
        if cmd == "poll":
            # heartbeat polls piggyback metric snapshots too (ISSUE 16
            # satellite (d)): an idle worker with zero finished traces
            # never sends a span batch, but must still appear in the
            # coordinator's fleet view
            m = req.get("metrics")
            if m:
                self.ingest_metrics(pid, m)
            return self._resp(self.poll(pid))
        if cmd == "report":
            return self._resp(self.report(pid, req.get("devices") or ()))
        if cmd == "leave":
            return self._resp(self.leave(pid))
        if cmd == "handoff":
            self.put_handoff(pid, req.get("sessions") or [])
            return self._resp(self.view())
        if cmd == "spans":
            payloads = req.get("payloads")
            if payloads is None:
                payloads = [req.get("payload") or {}]
                sizes = [nbytes]
            else:
                # batched forwarding (ISSUE 11 coord follow-up (c)): the
                # per-host byte cap applies PER PAYLOAD, not to the
                # batch.  The worker measured each payload at enqueue
                # time and ships the sizes — re-serializing here would
                # cost O(span bytes) on the coordinator's request thread
                sizes = req.get("sizes") or [
                    len(json.dumps(p)) for p in payloads]
            outcome = None
            for p, sz in zip(payloads, sizes):
                outcome = self.ingest_spans(pid, p, sz)
            # fleet aggregation (ISSUE 13): workers piggyback periodic
            # metric snapshots on the span batches they already send
            m = req.get("metrics")
            if m:
                self.ingest_metrics(pid, m)
            return self._resp(self.view(), outcome=outcome)
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def _resp(self, view: MembershipView, **extra) -> dict:
        with self._mu:
            shared = self._shared_locked()
        d = {"ok": True, "epoch": view.epoch, "formed": view.formed,
             "members": {str(p): list(ids)
                         for p, ids in view.members.items()},
             "addrs": {str(p): a for p, a in view.addrs.items()},
             "shared": shared}
        d.update(extra)
        return d


class LocalPlane:
    """Single-process degenerate plane: every coordination primitive
    works as a local loop so the tier-1 suite exercises the plane
    without worker processes.  Epoch bumps arrive through the
    DeviceHealthRegistry hook; membership is the healthy device set the
    mesh builder last published; handoff parks in memory and survives
    server restarts within the process (the single-host rolling-restart
    story)."""

    kind = "local"
    pid = 0

    def __init__(self):
        self._mu = make_lock("coord.plane:LocalPlane._mu")
        self._epoch = 1
        self._devices: Tuple[int, ...] = ()
        self._handoff: List[dict] = []
        self._shared: Dict[str, dict] = {}
        self._dp_addr: Optional[str] = None

    def view(self) -> MembershipView:
        with self._mu:
            members = {0: self._devices} if self._devices else {}
            addrs = {0: self._dp_addr} if self._dp_addr else {}
            return MembershipView(self._epoch, members, formed=True,
                                  addrs=addrs)

    # ---- shared fleet payloads (degenerate single-member fleet) ---------
    def advertise_addr(self, addr: Optional[str]):
        with self._mu:
            self._dp_addr = addr

    def shared_put(self, key: str, doc) -> int:
        with self._mu:
            cur = self._shared.get(key)
            ver = (cur["v"] if cur else 0) + 1
            self._shared[key] = {"v": ver, "doc": doc}
            return ver

    def shared_get(self, key: str):
        with self._mu:
            cur = self._shared.get(key)
            return (cur["doc"], cur["v"]) if cur else (None, 0)

    def shared_version(self, key: str) -> int:
        with self._mu:
            cur = self._shared.get(key)
            return cur["v"] if cur else 0

    def current_epoch(self) -> int:
        with self._mu:
            return self._epoch

    def bump(self, reason: str = ""):
        with self._mu:
            self._epoch += 1
            REGISTRY.inc("coord_epoch_bumps_total")
            REGISTRY.set("coord_epoch", self._epoch)

    def publish_local(self, device_ids):
        # no bump: publishing the same healthy set is not a membership
        # change (trips/recoveries bump through on_health_change)
        with self._mu:
            self._devices = tuple(int(d) for d in device_ids)

    def on_health_change(self, tripped_ids, reason: str):
        self.bump(reason)

    def wait_formed(self, timeout_s: float = 0.0) -> bool:
        return True

    def forward_trace(self, tr):  # local traces are already in the ring
        pass

    def fleet_metrics(self, refresh: bool = True) -> Dict[int, dict]:
        """Single-host degenerate fleet: this process IS the fleet, so
        the merge path runs in tier-1 with one member."""
        return {self.pid: _local_fleet_payload(refresh)}

    def handoff_put(self, states):
        states = list(states or ())
        if not states:
            return
        _hit_handoff(self.pid, len(states))
        with self._mu:
            self._handoff = states
        REGISTRY.inc("coord_handoff_put_total", len(states))

    def take_handoff(self) -> List[dict]:
        with self._mu:
            out, self._handoff = self._handoff, []
            return out

    def leave(self):
        pass

    def stop(self, leave: bool = False):
        pass


class CoordinatorPlane:
    """Process 0's plane: owns the Coordinator state in-process (no TCP
    round trip to itself) and participates as member `pid`."""

    kind = "coordinator"

    def __init__(self, coordinator: Coordinator, pid: int = 0):
        self.coord = coordinator
        self.pid = pid
        self._devices: Tuple[int, ...] = ()
        self._handoff_in: List[dict] = []

    def start(self, devices=()):
        self._devices = tuple(int(d) for d in devices)
        if self.coord._thread is None:
            self.coord.start()
        out = self.coord.register(self.pid, self._devices)
        self._handoff_in = list(out["handoff"])
        return self

    # ---- shared fleet payloads ------------------------------------------
    def advertise_addr(self, addr: Optional[str]):
        self.coord.register(self.pid, self._devices, addr=addr)

    def shared_put(self, key: str, doc) -> int:
        return self.coord.shared_put(key, doc)

    def shared_get(self, key: str):
        return self.coord.shared_get(key)

    def shared_version(self, key: str) -> int:
        return self.coord.shared_version(key)

    def view(self) -> MembershipView:
        return self.coord.view()

    def current_epoch(self) -> int:
        return self.view().epoch

    def bump(self, reason: str = ""):
        self.coord.bump(reason)

    def publish_local(self, device_ids):
        pass  # membership truth flows through register/report

    def on_health_change(self, tripped_ids, reason: str):
        tripped = set(int(d) for d in tripped_ids)
        healthy = tuple(d for d in self._devices if d not in tripped)
        self.coord.report(self.pid, healthy)

    def wait_formed(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.coord.view().formed:
                return True
            time.sleep(0.1)
        return self.coord.view().formed

    def forward_trace(self, tr):  # the coordinator's traces are local
        pass

    def fleet_metrics(self, refresh: bool = True) -> Dict[int, dict]:
        """Workers' piggybacked snapshots + this host's live registry
        (fleet_snapshot already exports it when the coordinator knows
        its own pid — don't build the registry payload twice)."""
        snaps = self.coord.fleet_snapshot(refresh)
        if self.pid not in snaps:
            snaps[self.pid] = _local_fleet_payload(refresh)
        return snaps

    def handoff_put(self, states):
        states = list(states or ())
        if not states:
            return
        _hit_handoff(self.pid, len(states))
        self.coord.put_handoff(self.pid, states)

    def take_handoff(self) -> List[dict]:
        # registration snapshot PLUS anything parked since (an in-process
        # server drain on the coordinator host puts straight into the
        # live store — LocalPlane and WorkerPlane rejoin both read live
        # state, and this path must match)
        out, self._handoff_in = self._handoff_in, []
        return out + self.coord.pop_handoff(self.pid)

    def leave(self):
        pass  # the coordinator leaving takes the plane down with it

    def stop(self, leave: bool = False):
        self.coord.stop()


class WorkerPlane:
    """A non-coordinator process's plane: registers with the
    coordinator, heartbeats its lease (caching each membership
    broadcast), reports breaker trips, forwards finished traces, and
    parks/retrieves handoff state.  Every RPC is a short-lived
    connection with a small timeout; a dead coordinator degrades the
    worker to its last cached view (counted, never blocking a query)."""

    kind = "worker"

    def __init__(self, addr, pid: int, lease_s: float = 5.0,
                 heartbeat_s: Optional[float] = None,
                 rpc_timeout_s: float = 2.0):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host, int(port))
        self.addr = (addr[0], int(addr[1]))
        self.pid = int(pid)
        self.lease_s = float(lease_s)
        self.heartbeat_s = heartbeat_s or max(self.lease_s / 3.0, 0.05)
        self.rpc_timeout_s = rpc_timeout_s
        self._mu = make_lock("coord.plane:WorkerPlane._mu")
        self._view = MembershipView(0, {}, formed=False)
        self._devices: Tuple[int, ...] = ()
        self._handoff_in: List[dict] = []
        # shared fleet payloads cached off every response (ISSUE 18)
        self._shared: Dict[str, dict] = {}
        self._dp_addr: Optional[str] = None
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        # batched span forwarding (ISSUE 11 / coord follow-up (c)): a
        # bounded queue drained by a background flusher, so finish_trace
        # enqueues instead of paying a synchronous RPC on the high-QPS
        # path.  Flushes trigger by SIZE (batch threshold) or AGE
        # (flush interval); drain/stop flushes whatever remains.
        self._span_q: List[str] = []
        self._span_mu = make_lock("coord.plane:WorkerPlane._span_mu")
        self._span_wake = threading.Event()
        self._span_thread: Optional[threading.Thread] = None
        self._span_batch = max(int(os.environ.get(
            "TIDB_TPU_COORD_SPAN_BATCH", "16")), 1)
        self._span_queue_max = max(int(os.environ.get(
            "TIDB_TPU_COORD_SPAN_QUEUE", "256")), 1)
        self._span_flush_s = float(os.environ.get(
            "TIDB_TPU_COORD_SPAN_FLUSH_S", "0.2"))
        # fleet metric snapshots (ISSUE 13) piggyback on span batches at
        # most once per interval (0 = every batch)
        self._metrics_interval_s = float(os.environ.get(
            "TIDB_TPU_COORD_METRICS_S", "2.0"))
        self._metrics_sent = 0.0

    # ---- lifecycle ------------------------------------------------------
    def start(self, devices=()):
        self._devices = tuple(int(d) for d in devices)
        resp = self._rpc({"cmd": "register", "pid": self.pid,
                          "devices": list(self._devices),
                          "lease_s": self.lease_s,
                          "addr": self._dp_addr},
                         retries=40, retry_sleep=0.25)
        self._apply(resp)
        with self._mu:
            self._handoff_in = list(resp.get("handoff") or [])
        self._stop.clear()
        self._hb = threading.Thread(target=self._heartbeat, daemon=True,
                                    name="tidb-tpu-coord-hb")
        self._hb.start()
        self._span_thread = threading.Thread(
            target=self._span_flusher, daemon=True,
            name="tidb-tpu-coord-spans")
        self._span_thread.start()
        # worker span trees rejoin the coordinator's trace ring.  The
        # recorder-level chain keeps any already-installed participant
        # (the continuous profiler): both must see every finished trace.
        from ..trace import recorder

        recorder.chain_export_hook(self.forward_trace)
        return self

    def stop(self, leave: bool = False):
        if leave:
            self.leave()
        self._stop.set()
        self._span_wake.set()
        if self._hb is not None:
            self._hb.join(timeout=2.0)
            self._hb = None
        if self._span_thread is not None:
            self._span_thread.join(timeout=2.0)
            self._span_thread = None
        # drain: anything the flusher didn't get to goes out now
        self.flush_spans()
        from ..trace import recorder

        # list removal, not restore-if-top: the forwarder leaves the
        # chain even when the profiler (or a later plane) chained after
        # us; every other participant keeps running
        recorder.unchain_export_hook(self.forward_trace)

    def leave(self):
        try:
            self._apply(self._rpc({"cmd": "leave", "pid": self.pid}))
        except Exception:
            REGISTRY.inc("coord_rpc_errors_total")

    # ---- views ----------------------------------------------------------
    def view(self) -> MembershipView:
        with self._mu:
            return self._view

    def current_epoch(self) -> int:
        return self.view().epoch

    def bump(self, reason: str = ""):
        """Local-cache bump (tests/diagnostics): makes the next dispatch
        observe an epoch ahead of its mesh stamp."""
        with self._mu:
            self._view = MembershipView(self._view.epoch + 1,
                                        self._view.members,
                                        self._view.formed,
                                        self._view.addrs)

    # ---- shared fleet payloads ------------------------------------------
    def advertise_addr(self, addr: Optional[str]):
        """Publish this worker's data-plane endpoint: re-register with
        the addr (an addr change is a membership change — epoch bumps)."""
        self._dp_addr = addr
        try:
            resp = self._rpc({"cmd": "register", "pid": self.pid,
                              "devices": list(self._devices),
                              "lease_s": self.lease_s, "addr": addr})
            with self._mu:
                self._handoff_in += list(resp.get("handoff") or [])
            self._apply(resp)
        except Exception:
            REGISTRY.inc("coord_rpc_errors_total")

    def shared_put(self, key: str, doc) -> int:
        resp = self._rpc({"cmd": "shared_put", "pid": self.pid,
                          "key": key, "doc": doc})
        self._apply(resp)
        return int(resp.get("version", 0))

    def shared_get(self, key: str):
        with self._mu:
            cur = self._shared.get(key)
            return (cur["doc"], cur["v"]) if cur else (None, 0)

    def shared_version(self, key: str) -> int:
        with self._mu:
            cur = self._shared.get(key)
            return cur["v"] if cur else 0

    def publish_local(self, device_ids):
        pass  # membership truth flows through register/report

    def wait_formed(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.view().formed:
                return True
            try:
                self._apply(self._rpc({"cmd": "poll", "pid": self.pid}))
            except Exception:
                REGISTRY.inc("coord_rpc_errors_total")
            time.sleep(0.1)
        return self.view().formed

    # ---- plane surface --------------------------------------------------
    def on_health_change(self, tripped_ids, reason: str):
        tripped = set(int(d) for d in tripped_ids)
        healthy = tuple(d for d in self._devices if d not in tripped)
        try:
            self._apply(self._rpc({"cmd": "report", "pid": self.pid,
                                   "devices": list(healthy)}))
        except Exception:
            REGISTRY.inc("coord_rpc_errors_total")

    def forward_trace(self, tr):
        """finish_trace hook: ENQUEUE the finished span tree for the
        background flusher (batch + age triggered) — no synchronous RPC
        on the statement path (ISSUE 11 / coord follow-up (c)).
        Oversize payloads (per-host byte cap) and a full queue drop with
        counters; a dead coordinator costs the flusher a short timeout,
        never a query failure."""
        if self._stop.is_set():
            # a dispatch snapshot taken just before stop() unchained us
            # can still deliver here — a stopped plane must not keep
            # feeding a queue nobody drains
            return
        try:
            from ..trace.export import trace_payload

            data = json.dumps(trace_payload(tr))
            if len(data) > _span_cap_bytes():
                REGISTRY.inc("coord_spans_dropped_total")
                return
            with self._span_mu:
                if len(self._span_q) >= self._span_queue_max:
                    REGISTRY.inc("coord_spans_dropped_total")
                    return
                self._span_q.append(data)
                depth = len(self._span_q)
            if depth >= self._span_batch:
                self._span_wake.set()  # size-triggered flush
        except Exception:
            REGISTRY.inc("coord_rpc_errors_total")

    def _span_flusher(self):
        """Background worker: flush the span queue when the batch
        threshold fills (size) or the flush interval lapses (age)."""
        while not self._stop.is_set():
            self._flusher_wait()
            self._span_wake.clear()
            self.flush_spans()

    def _flusher_wait(self):
        """The flusher's age-trigger wait, witness-checked (concurrency
        (d)): blocking here while holding a ranked lock would stall the
        only thread that drains the span queue."""
        witness_wait_check("WorkerPlane._span_wake.wait")
        self._span_wake.wait(self._span_flush_s)

    def flush_spans(self):
        """Drain the span queue now (the flusher's body; also the
        drain/stop path so no finished trace is left behind)."""
        while True:
            with self._span_mu:
                batch, self._span_q = (
                    self._span_q[: self._span_batch],
                    self._span_q[self._span_batch:],
                )
            if not batch:
                return
            # piggyback a metric snapshot at most once per interval: the
            # batch is already crossing the wire, so fleet aggregation
            # costs one extra JSON field, not a new RPC
            extra = ""
            now = time.monotonic()
            if now - self._metrics_sent >= self._metrics_interval_s:
                try:
                    extra = (', "metrics": '
                             + json.dumps(_local_fleet_payload()))
                except Exception:
                    extra = ""
            try:
                sizes = json.dumps([len(b) for b in batch])
                data = ('{"cmd": "spans", "pid": %d, "sizes": %s%s,'
                        ' "payloads": [%s]}'
                        % (self.pid, sizes, extra, ", ".join(batch)))
                self._rpc_line(data)
                if extra:
                    self._metrics_sent = now
                REGISTRY.inc("coord_spans_forwarded_total", len(batch))
                REGISTRY.inc("coord_span_batches_total")
                REGISTRY.inc("coord_span_bytes_total",
                             sum(len(b) for b in batch))
            except Exception:
                REGISTRY.inc("coord_rpc_errors_total")
                # coordinator unreachable: requeue this batch at the
                # front (bounded — overflow drops with the counter) and
                # let a later flush retry
                with self._span_mu:
                    room = self._span_queue_max - len(self._span_q)
                    kept = batch[:max(room, 0)]
                    if len(kept) < len(batch):
                        REGISTRY.inc("coord_spans_dropped_total",
                                     len(batch) - len(kept))
                    self._span_q = kept + self._span_q
                return

    def fleet_metrics(self, refresh: bool = True) -> Dict[int, dict]:
        """A worker's /status shows its own host; the merged fleet view
        lives on the coordinator."""
        return {self.pid: _local_fleet_payload(refresh)}

    def handoff_put(self, states):
        states = list(states or ())
        if not states:
            return
        _hit_handoff(self.pid, len(states))
        self._rpc({"cmd": "handoff", "pid": self.pid, "sessions": states})

    def take_handoff(self) -> List[dict]:
        with self._mu:
            out, self._handoff_in = self._handoff_in, []
            return out

    # ---- internals ------------------------------------------------------
    def _apply(self, resp: dict):
        view = _view_from_resp(resp)
        with self._mu:
            if view.epoch >= self._view.epoch:
                self._view = view
            # shared payloads ride every response; per-key versions are
            # monotonic so a stale response can never roll one back
            for k, s in (resp.get("shared") or {}).items():
                try:
                    ver = int(s.get("v", 0))
                except (TypeError, AttributeError, ValueError):
                    continue
                cur = self._shared.get(k)
                if cur is None or ver > cur["v"]:
                    self._shared[k] = {"v": ver, "doc": s.get("doc")}
        REGISTRY.set("coord_epoch", view.epoch)

    def _hb_wait(self) -> bool:
        """One heartbeat-interval wait, witness-checked (concurrency
        (d)): the heartbeat thread must never sleep on the stop event
        while holding a ranked lock."""
        witness_wait_check("WorkerPlane._stop.wait")
        return self._stop.wait(self.heartbeat_s)

    def _heartbeat(self):
        while not self._hb_wait():
            try:
                req = {"cmd": "poll", "pid": self.pid}
                now = time.monotonic()
                if now - self._metrics_sent >= self._metrics_interval_s:
                    # piggyback a metric snapshot on the heartbeat: an
                    # idle worker (no finished traces, so no span
                    # batches) must still reach the fleet view
                    try:
                        req["metrics"] = _local_fleet_payload()
                    except Exception:
                        pass
                resp = self._rpc(req)
                if "metrics" in req:
                    self._metrics_sent = now
                view = _view_from_resp(resp)
                if self.pid not in view.members:
                    # expired while alive (paused/partitioned): rejoin at
                    # the new epoch; any parked handoff rides back
                    resp = self._rpc({"cmd": "register", "pid": self.pid,
                                      "devices": list(self._devices),
                                      "lease_s": self.lease_s,
                                      "addr": self._dp_addr})
                    with self._mu:
                        self._handoff_in += list(resp.get("handoff") or [])
                self._apply(resp)
            except Exception:
                REGISTRY.inc("coord_rpc_errors_total")

    def _rpc(self, obj: dict, retries: int = 1,
             retry_sleep: float = 0.2) -> dict:
        data = json.dumps(obj)
        last: Optional[Exception] = None
        for _i in range(max(retries, 1)):
            try:
                return self._rpc_line(data)
            except Exception as e:  # noqa: BLE001 — transport boundary
                last = e
                time.sleep(retry_sleep)
        raise last

    def _rpc_line(self, data: str) -> dict:
        with socket.create_connection(
                self.addr, timeout=self.rpc_timeout_s) as s:
            s.settimeout(self.rpc_timeout_s)
            f = s.makefile("rwb")
            f.write(data.encode() + b"\n")
            f.flush()
            line = f.readline()
        if not line:
            raise ConnectionError("coordinator closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise RuntimeError(str(resp.get("error")))
        return resp
