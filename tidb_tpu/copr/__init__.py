from .ir import (
    DAG,
    TableScanIR,
    SelectionIR,
    AggregationIR,
    TopNIR,
    LimitIR,
    ProjectionIR,
    serialize_expr,
    deserialize_expr,
    serialize_ftype,
    deserialize_ftype,
)

__all__ = [
    "DAG",
    "TableScanIR",
    "SelectionIR",
    "AggregationIR",
    "TopNIR",
    "LimitIR",
    "ProjectionIR",
    "serialize_expr",
    "deserialize_expr",
    "serialize_ftype",
    "deserialize_ftype",
]
