"""Aggregate partial-state kernels (host/numpy side).

Shared by: the CPU cop engine (producing partials), the root HashAgg
(merging partials / final agg), and tests as the oracle for the jax engine.
Reference pattern: executor/aggfuncs PartialResult + AggFuncToPBExpr
partial/final split.

All functions are vectorized over a group-index array ``gidx`` (values in
[0, G)); states are lists of numpy arrays of length G.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..chunk import Chunk, Column
from ..errors import ExecutorError
from ..expr.aggregation import AggDesc, avg_type, sum_type
from ..expr.vec import Vec
from ..types import FieldType, TypeKind
from ..types.values import decimal_round_half_up


def _sum_repr(v: Vec, st: FieldType) -> np.ndarray:
    """Arg values in the sum-state representation (scaled int64 / float64)."""
    from ..expr.builtins import cast_vec

    return cast_vec(v, st).data


def group_indices(cols: List[Column]) -> Tuple[np.ndarray, List[tuple], int]:
    """Map rows to dense group ids.  Returns (gidx, key_tuples, G).

    Single fixed-width columns factorize through the native open-addressing
    hash (tidb_tpu/native), assigning codes in first-appearance order — the
    C-speed replacement for the reference's row-at-a-time agg hash maps."""
    n = len(cols[0]) if cols else 0
    if not cols:
        return np.zeros(n, dtype=np.int64), [()], 1
    if len(cols) == 1 and cols[0].data.dtype != object and n:
        from ..native import KeyTable

        c = cols[0]
        data = c.data
        if data.dtype == np.float64:
            data = np.where(data == 0.0, 0.0, data).view(np.int64)
        else:
            data = data.astype(np.int64, copy=False)
        valid = c.valid  # None = all valid
        kt = KeyTable(min(n, 1 << 20))
        gidx = kt.upsert(data, valid)
        n_named = int(gidx.max()) + 1 if (gidx >= 0).any() else 0
        has_null = bool((gidx < 0).any())
        if has_null:
            gidx = np.where(gidx < 0, n_named, gidx)  # NULL = its own group
        G = n_named + (1 if has_null else 0)
        # first-occurrence row per group -> key tuples
        first = np.full(G, n, dtype=np.int64)
        np.minimum.at(first, gidx, np.arange(n, dtype=np.int64))
        keys = [(c.get(int(first[g])),) for g in range(G)]
        return gidx, keys, G
    # multi-column / object keys: per-column vectorized factorize +
    # mixed-radix combine (re-factorized per step so codes stay < n and
    # never overflow), then a first-appearance remap so group ids and
    # key ordering match the old row-at-a-time dict exactly.  NULL is
    # its own code per column (validity joins the key), and equal float
    # keys collapse like the single-column bit-domain path.
    if n == 0:
        return np.zeros(0, dtype=np.int64), [], 0
    combined = np.zeros(n, dtype=np.int64)
    for c in cols:
        inv, card = _factorize_column(c)
        combined = combined * card + inv
        combined = np.unique(combined, return_inverse=True)[1] \
            .astype(np.int64)
    G = int(combined.max()) + 1
    first = np.full(G, n, dtype=np.int64)
    np.minimum.at(first, combined, np.arange(n, dtype=np.int64))
    order = np.argsort(first, kind="stable")
    rank = np.empty(G, dtype=np.int64)
    rank[order] = np.arange(G, dtype=np.int64)
    gidx = rank[combined]
    # G key tuples gathered from each group's first row (G-scale, the
    # same per-group materialization the single-column path does)
    keys = [tuple(c.get(int(first[g])) for c in cols) for g in order]
    return gidx, keys, G


def _factorize_column(c: Column) -> Tuple[np.ndarray, int]:
    """(dense codes, cardinality) for one key column, NULL rows coded 0.
    np.unique vectorizes str/numeric payloads; exotic object payloads
    (mixed types that don't compare) fall back to a hash-map pass."""
    n = len(c)
    data, valid = c.data, c.valid
    try:
        if valid is None:
            _u, iv = np.unique(data, return_inverse=True)
            return iv.astype(np.int64, copy=False), max(len(_u), 1)
        inv = np.zeros(n, dtype=np.int64)
        _u, iv = np.unique(data[valid], return_inverse=True)
        inv[valid] = iv.astype(np.int64, copy=False) + 1
        return inv, len(_u) + 1
    except TypeError:
        codes: Dict[object, int] = {}
        inv = np.zeros(n, dtype=np.int64)
        vv = valid
        for i, x in enumerate(data.tolist()):
            if vv is not None and not vv[i]:
                continue  # NULL keeps code 0
            g = codes.get(x)
            if g is None:
                g = codes[x] = len(codes) + 1
            inv[i] = g
        return inv, len(codes) + 1


def partial_states(agg: AggDesc, arg_vecs: List[Vec], gidx: np.ndarray,
                   G: int) -> List[Column]:
    """Compute per-group partial state columns from raw rows."""
    name = agg.name
    pts = agg.partial_types()
    if name == "count":
        if not agg.args or isinstance(arg_vecs[0], type(None)):
            cnt = np.bincount(gidx, minlength=G).astype(np.int64)
        else:
            v = arg_vecs[0]
            cnt = np.bincount(gidx, weights=v.validity().astype(np.float64),
                              minlength=G).astype(np.int64)
        return [Column(pts[0], cnt)]
    v = arg_vecs[0]
    valid = v.validity()
    if name in ("sum", "avg"):
        st = pts[0]
        data = _sum_repr(v, st)
        acc = np.zeros(G, dtype=st.np_dtype)
        masked = np.where(valid, data, 0)
        np.add.at(acc, gidx, masked)
        cnt = np.bincount(gidx, weights=valid.astype(np.float64),
                          minlength=G).astype(np.int64)
        sum_col = Column(st, acc, (cnt > 0))
        if name == "sum":
            return [sum_col]
        return [sum_col, Column(pts[1], cnt)]
    if name in ("min", "max"):
        st = pts[0]
        if st.kind == TypeKind.STRING:
            out = np.empty(G, dtype=object)
            out[:] = None
            for i in range(len(gidx)):
                if not valid[i]:
                    continue
                g = gidx[i]
                x = v.data[i]
                if out[g] is None or (x < out[g] if name == "min" else x > out[g]):
                    out[g] = x
            ovalid = np.array([x is not None for x in out], dtype=np.bool_)
            data = np.empty(G, dtype=object)
            for i in range(G):
                data[i] = out[i] if out[i] is not None else ""
            return [Column(st, data, ovalid)]
        ident = (
            np.iinfo(np.int64).max if name == "min" else np.iinfo(np.int64).min
        ) if st.np_dtype != np.float64 else (np.inf if name == "min" else -np.inf)
        acc = np.full(G, ident, dtype=st.np_dtype)
        masked = np.where(valid, v.data, ident)
        if name == "min":
            np.minimum.at(acc, gidx, masked)
        else:
            np.maximum.at(acc, gidx, masked)
        cnt = np.bincount(gidx, weights=valid.astype(np.float64), minlength=G)
        ovalid = cnt > 0
        acc = np.where(ovalid, acc, 0)
        return [Column(st, acc.astype(st.np_dtype), ovalid)]
    if name == "first_row":
        st = pts[0]
        seen = np.zeros(G, dtype=np.bool_)
        if st.kind == TypeKind.STRING:
            data = np.empty(G, dtype=object)
            data[:] = ""
        else:
            data = np.zeros(G, dtype=st.np_dtype)
        ovalid = np.zeros(G, dtype=np.bool_)
        for i in range(len(gidx)):
            g = gidx[i]
            if not seen[g]:
                seen[g] = True
                data[g] = v.data[i]
                ovalid[g] = valid[i]
        return [Column(st, data, ovalid)]
    if name in ("bit_and", "bit_or", "bit_xor"):
        ident = -1 if name == "bit_and" else 0
        acc = np.full(G, ident, dtype=np.int64)
        masked = np.where(valid, v.data.astype(np.int64), ident)
        op = {"bit_and": np.bitwise_and, "bit_or": np.bitwise_or,
              "bit_xor": np.bitwise_xor}[name]
        op.at(acc, gidx, masked)
        return [Column(pts[0], acc)]
    if name in ("var_pop", "stddev_pop", "var_samp", "stddev_samp"):
        from ..expr.builtins import _to_float

        x = np.where(valid, _to_float(v), 0.0)
        s = np.zeros(G)
        np.add.at(s, gidx, x)
        s2 = np.zeros(G)
        np.add.at(s2, gidx, x * x)
        cnt = np.bincount(gidx, weights=valid.astype(np.float64),
                          minlength=G).astype(np.int64)
        return [Column(pts[0], s), Column(pts[1], s2), Column(pts[2], cnt)]
    if name == "group_concat":
        from ..expr.builtins import _str_data

        sep = agg.ftype and ","  # MySQL default separator
        strs = _str_data(v)
        parts: List[List[str]] = [[] for _ in range(G)]
        for i in range(len(gidx)):
            if valid[i]:
                parts[gidx[i]].append(str(strs[i]))
        out = np.empty(G, dtype=object)
        ovalid = np.zeros(G, dtype=np.bool_)
        for g in range(G):
            if parts[g]:
                out[g] = ",".join(parts[g])
                ovalid[g] = True
            else:
                out[g] = ""
        return [Column(pts[0], out, ovalid)]
    raise ExecutorError(f"partial_states: unsupported agg {name}")


def merge_states(agg: AggDesc, state_cols: List[Column], gidx: np.ndarray,
                 G: int) -> List[Column]:
    """Merge partial-state rows into G groups (final-merge accumulation)."""
    name = agg.name
    pts = agg.partial_types()
    if name == "count":
        acc = np.zeros(G, dtype=np.int64)
        np.add.at(acc, gidx, state_cols[0].data)
        return [Column(pts[0], acc)]
    if name in ("sum", "avg"):
        st = pts[0]
        acc = np.zeros(G, dtype=st.np_dtype)
        sv = state_cols[0]
        np.add.at(acc, gidx, np.where(sv.validity(), sv.data, 0))
        if name == "sum":
            cnt = np.zeros(G, dtype=np.int64)
            np.add.at(cnt, gidx, sv.validity().astype(np.int64))
            return [Column(st, acc, cnt > 0)]
        cnt = np.zeros(G, dtype=np.int64)
        np.add.at(cnt, gidx, state_cols[1].data)
        return [Column(st, acc, cnt > 0), Column(pts[1], cnt)]
    if name in ("min", "max", "first_row"):
        # reuse row-accumulation on the state column
        sub = AggDesc(name, agg.args, agg.distinct, agg.ftype)
        return partial_states(sub, [Vec.from_column(state_cols[0])], gidx, G)
    if name in ("bit_and", "bit_or", "bit_xor"):
        ident = -1 if name == "bit_and" else 0
        acc = np.full(G, ident, dtype=np.int64)
        op = {"bit_and": np.bitwise_and, "bit_or": np.bitwise_or,
              "bit_xor": np.bitwise_xor}[name]
        op.at(acc, gidx, state_cols[0].data)
        return [Column(pts[0], acc)]
    if name in ("var_pop", "stddev_pop", "var_samp", "stddev_samp"):
        s = np.zeros(G)
        np.add.at(s, gidx, state_cols[0].data)
        s2 = np.zeros(G)
        np.add.at(s2, gidx, state_cols[1].data)
        cnt = np.zeros(G, dtype=np.int64)
        np.add.at(cnt, gidx, state_cols[2].data)
        return [Column(pts[0], s), Column(pts[1], s2), Column(pts[2], cnt)]
    if name == "group_concat":
        parts: List[List[str]] = [[] for _ in range(G)]
        sv = state_cols[0]
        valid = sv.validity()
        for i in range(len(gidx)):
            if valid[i]:
                parts[gidx[i]].append(str(sv.data[i]))
        out = np.empty(G, dtype=object)
        ovalid = np.zeros(G, dtype=np.bool_)
        for g in range(G):
            if parts[g]:
                out[g] = ",".join(parts[g])
                ovalid[g] = True
            else:
                out[g] = ""
        return [Column(pts[0], out, ovalid)]
    raise ExecutorError(f"merge_states: unsupported agg {name}")


def merge_partials_to_final(n_keys: int, aggs: List[AggDesc],
                            chunks: List[Chunk]) -> Optional[Chunk]:
    """Merge partial-state chunks ([keys..., states...] layout) from many
    shards/engines into one final chunk [keys..., finals...].

    Returns None when there are no input rows AND n_keys > 0 (empty group-by
    result); for scalar agg (n_keys == 0) the caller handles the
    one-row-from-nothing case."""
    rows = [c for c in chunks if c is not None and c.num_rows > 0]
    if not rows:
        return None
    whole = rows[0]
    for c in rows[1:]:
        whole = whole.append(c)
    key_cols = [whole.col(i) for i in range(n_keys)]
    if key_cols:
        gidx, keys, G = group_indices(key_cols)
    else:
        gidx, keys, G = np.zeros(whole.num_rows, dtype=np.int64), [()], 1
    out_cols: List[Column] = []
    for ci in range(n_keys):
        vals = [k[ci] for k in keys]
        out_cols.append(Column.from_values(key_cols[ci].ftype, vals))
    off = n_keys
    for a in aggs:
        width = len(a.partial_types())
        states = [whole.col(off + j) for j in range(width)]
        off += width
        merged = merge_states(a, states, gidx, G)
        out_cols.append(finalize(a, merged))
    return Chunk(out_cols)


def empty_final_row(aggs: List[AggDesc]) -> Chunk:
    """The one row a scalar aggregation yields over zero input rows:
    COUNT -> 0, SUM/AVG/MIN/MAX -> NULL."""
    cols = []
    for a in aggs:
        if a.name == "count":
            cols.append(Column(a.ftype, np.zeros(1, dtype=np.int64)))
        elif a.name in ("bit_or", "bit_xor"):
            cols.append(Column(a.ftype, np.zeros(1, dtype=np.int64)))
        elif a.name == "bit_and":
            cols.append(Column(a.ftype, np.full(1, -1, dtype=np.int64)))
        else:
            cols.append(Column.nulls(a.ftype, 1))
    return Chunk(cols)


def finalize(agg: AggDesc, states: List[Column]) -> Column:
    """Final value from merged states."""
    name = agg.name
    ft = agg.ftype
    if name == "count":
        return Column(ft, states[0].data.astype(np.int64))
    if name == "sum":
        s = states[0]
        return Column(ft, s.data.astype(ft.np_dtype) if ft.np_dtype != s.data.dtype
                      else s.data, s.valid)
    if name == "avg":
        s, c = states
        cnt = c.data
        safe = np.where(cnt > 0, cnt, 1)
        if ft.kind == TypeKind.FLOAT:
            data = s.data.astype(np.float64) / safe
        else:
            # decimal: state scale -> result scale with round-half-up
            st = sum_type(agg.args[0].ftype)
            up = ft.scale - st.scale
            num = s.data.astype(np.int64) * (10 ** max(up, 0))
            sign = np.sign(num)
            data = sign * ((np.abs(num) + safe // 2) // safe)
        return Column(ft, data.astype(ft.np_dtype), (cnt > 0))
    if name in ("min", "max", "first_row"):
        s = states[0]
        return Column(ft, s.data, s.valid)
    if name in ("bit_and", "bit_or", "bit_xor"):
        return Column(ft, states[0].data)
    if name in ("var_pop", "stddev_pop", "var_samp", "stddev_samp"):
        s, s2, c = (x.data for x in states)
        cnt = np.where(c > 0, c, 1).astype(np.float64)
        mean = s / cnt
        var = s2 / cnt - mean * mean
        var = np.maximum(var, 0.0)
        if name in ("var_samp", "stddev_samp"):
            denom = np.where(c > 1, c - 1, 1).astype(np.float64)
            var = var * cnt / denom
            valid = c > 1
        else:
            valid = c > 0
        data = np.sqrt(var) if name.startswith("stddev") else var
        return Column(ft, data, valid)
    if name == "group_concat":
        s = states[0]
        return Column(ft, s.data, s.valid)
    raise ExecutorError(f"finalize: unsupported agg {name}")
