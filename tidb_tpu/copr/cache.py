"""Byte-capped FIFO cache for device-resident arrays.

Shared by the per-tile device cache (jax_engine._DeviceCache) and the
mesh-sharded column cache (parallel.MESH_CACHE) — one eviction policy, one
bookkeeping implementation.  The role of TiKV's block cache: immutable base
data keyed on (store_uid, base_version, ...), so a version bump naturally
invalidates without explicit eviction.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Tuple


class ByteCapCache:
    """key -> tuple of device arrays (anything with .nbytes)."""

    def __init__(self, capacity_bytes: int):
        self._cache: Dict[tuple, tuple] = {}
        self._order: List[tuple] = []
        self._bytes = 0
        self.capacity = capacity_bytes
        self._mu = threading.Lock()
        # per-key in-flight latches: a background prefetch and a query
        # racing on the same column must not BOTH push it over the link
        # (transfers are the expensive part; see _MeshCache)
        self._inflight: Dict[tuple, threading.Event] = {}

    def get_or_load(self, key: tuple, loader: Callable[[], Tuple]) -> tuple:
        while True:
            with self._mu:
                hit = self._cache.get(key)
                if hit is not None:
                    return hit
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    break  # we are the loader
            ev.wait()  # another thread is loading this key
        try:
            value = loader()  # outside the lock: loads transfer data
        except BaseException:
            with self._mu:
                self._inflight.pop(key, None)
            ev.set()
            raise
        nbytes = sum(v.nbytes for v in value if v is not None)
        with self._mu:
            while self._bytes + nbytes > self.capacity and self._order:
                old = self._order.pop(0)
                ov = self._cache.pop(old)
                self._bytes -= sum(v.nbytes for v in ov if v is not None)
            self._cache[key] = value
            self._order.append(key)
            self._bytes += nbytes
            self._inflight.pop(key, None)
        ev.set()
        return value

    def clear(self):
        with self._mu:
            self._cache.clear()
            self._order.clear()
            self._bytes = 0

    def __len__(self):
        return len(self._cache)

    @property
    def items_view(self):
        return self._cache
