"""Byte-capped FIFO cache for device-resident arrays.

Shared by the per-tile device cache (jax_engine._DeviceCache) and the
mesh-sharded column cache (parallel.MESH_CACHE) — one eviction policy, one
bookkeeping implementation.  The role of TiKV's block cache: immutable base
data keyed on (store_uid, base_version, ...), so a version bump naturally
invalidates without explicit eviction.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple
from ..util_concurrency import make_lock


class _InFlight:
    """One pending load: waiters block on `ev` and read the outcome off
    the record, so a doomed (evicted-mid-load) value still reaches every
    current waiter WITHOUT any of them restarting the load against a
    condemned device set."""

    __slots__ = ("ev", "value", "failed")

    def __init__(self):
        self.ev = threading.Event()
        self.value: Optional[tuple] = None
        self.failed = False


class ByteCapCache:
    """key -> tuple of device arrays (anything with .nbytes)."""

    def __init__(self, capacity_bytes: int, name: Optional[str] = None):
        self._cache: Dict[tuple, tuple] = {}
        self._order: List[tuple] = []
        self._bytes = 0
        self.capacity = capacity_bytes
        # device-memory telemetry (ISSUE 13): hwm_bytes is the
        # high-water mark since process start (or the last clear) — the
        # "how close did we get to the cap" gauge
        self.name = name
        self.hwm_bytes = 0
        self._mu = make_lock("copr.cache:ByteCapCache._mu")
        # value-weighted eviction policy (layout autotuner): priority_fn
        # ranks resident keys (lowest evicts first; None = FIFO) and
        # demote_fn gets each victim BEFORE it is dropped — the hook that
        # re-homes a column into the compressed cold tier instead of
        # losing it outright
        self._priority_fn: Optional[Callable[[tuple], float]] = None
        self._demote_fn: Optional[Callable[[tuple, tuple], None]] = None
        # per-key in-flight records: a background prefetch and a query
        # racing on the same column must not BOTH push it over the link
        # (transfers are the expensive part; see _MeshCache)
        self._inflight: Dict[tuple, _InFlight] = {}
        # keys evicted WHILE their load was in flight: the finished value
        # must not be cached (it may be placed on a dead device)
        self._doomed: set = set()
        # named caches register for the /status "memory" section and the
        # fleet metric snapshots — LAST, fully constructed: memory_stats
        # on another thread may iterate the registry immediately
        if name is not None:
            BYTE_CAP_CACHES[name] = self

    def set_policy(self, priority_fn=None, demote_fn=None):
        """Install the value-weighted eviction policy (both optional)."""
        with self._mu:
            self._priority_fn = priority_fn
            self._demote_fn = demote_fn

    def _eviction_order_locked(self) -> List[tuple]:
        """Victim order for one eviction pass: priorities are ranked
        ONCE (one priority_fn call per resident, not per victim) so a
        multi-victim eviction holds the mutex for O(N log N), never
        O(V*N) cross-lock lookups.  FIFO fallback when no policy (or a
        broken one — a bad policy must never wedge the cache)."""
        if self._priority_fn is not None:
            try:
                return sorted(self._order, key=self._priority_fn)
            except Exception:
                pass
        return list(self._order)

    def get_or_load(self, key: tuple, loader: Callable[[], Tuple]) -> tuple:
        while True:
            with self._mu:
                hit = self._cache.get(key)
                if hit is not None:
                    return hit
                rec = self._inflight.get(key)
                if rec is None:
                    rec = self._inflight[key] = _InFlight()
                    break  # we are the loader
            rec.ev.wait()  # another thread is loading this key
            if not rec.failed:
                return rec.value  # loaded (cached, or doomed-uncached)
            # the loader failed: loop and possibly become the new loader
        try:
            value = loader()  # outside the lock: loads transfer data
        except BaseException:
            with self._mu:
                rec.failed = True
                self._inflight.pop(key, None)
                self._doomed.discard(key)
            rec.ev.set()
            raise
        nbytes = sum(v.nbytes for v in value if v is not None)
        victims: List[Tuple[tuple, tuple]] = []
        with self._mu:
            rec.value = value
            doomed = key in self._doomed
            self._doomed.discard(key)
            self._inflight.pop(key, None)
            if not doomed:
                ranked: Optional[List[tuple]] = None
                while self._bytes + nbytes > self.capacity and self._order:
                    if ranked is None:
                        ranked = self._eviction_order_locked()
                    old = ranked.pop(0)
                    self._order.remove(old)
                    ov = self._cache.pop(old)
                    self._bytes -= sum(v.nbytes for v in ov if v is not None)
                    victims.append((old, ov))
                self._cache[key] = value
                self._order.append(key)
                self._bytes += nbytes
                if self._bytes > self.hwm_bytes:
                    self.hwm_bytes = self._bytes
            demote = self._demote_fn
            # doomed: hand the value to this caller and every waiter
            # (their mesh is already condemned and will retry) but never
            # cache it for a future, possibly-restored mesh
        rec.ev.set()
        if demote is not None:
            # outside the lock: demotion compresses + transfers, and a
            # demote hook that loads through ANOTHER cache must not hold
            # this one's lock
            for vk, vv in victims:
                try:
                    demote(vk, vv)
                except Exception:
                    pass  # demotion is best-effort; the drop already won
        return value

    def peek(self, key: tuple):
        """Resident value for key (no load, no ordering effect); None on
        miss.  Used for tier bookkeeping (cold-hit/promotion metrics)."""
        with self._mu:
            return self._cache.get(key)

    def evict_if(self, pred: Callable[[tuple], bool]) -> int:
        """Drop every entry whose key satisfies pred (device-failover
        eviction: keys carrying a dead device's id must never serve a
        rebuilt mesh).  In-flight loads matching pred are doomed: their
        results are handed to the loading caller but never cached.
        Returns the number of resident entries evicted."""
        with self._mu:
            victims = [k for k in self._cache if pred(k)]
            for k in victims:
                v = self._cache.pop(k)
                self._order.remove(k)
                self._bytes -= sum(x.nbytes for x in v if x is not None)
            for k in self._inflight:
                if pred(k):
                    self._doomed.add(k)
        return len(victims)

    def clear(self):
        with self._mu:
            self._cache.clear()
            self._order.clear()
            self._bytes = 0
            self._doomed.update(self._inflight)  # don't cache mid-flight loads

    def __len__(self):
        return len(self._cache)

    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._cache), "bytes": self._bytes,
                    "capacity_bytes": self.capacity,
                    "watermark_bytes": self.hwm_bytes}

    @property
    def items_view(self):
        return self._cache


#: named ByteCapCache instances (mesh column cache, cold tier, per-tile
#: device cache) — one registry so the /status "memory" section and the
#: fleet metric snapshots see every device-resident byte pool
BYTE_CAP_CACHES: Dict[str, "ByteCapCache"] = {}


def memory_stats() -> Dict[str, dict]:
    """Byte/capacity/watermark stats for every named device cache, also
    refreshed into REGISTRY gauges (`cache_<name>_bytes` etc.) so fleet
    snapshots and /metrics carry them without a pull from each cache."""
    from ..metrics import REGISTRY

    out = {}
    for name, cache in sorted(BYTE_CAP_CACHES.items()):
        st = cache.stats()
        out[name] = st
        REGISTRY.set(f"cache_{name}_bytes", float(st["bytes"]))
        REGISTRY.set(f"cache_{name}_capacity_bytes",
                     float(st["capacity_bytes"]))
        REGISTRY.set(f"cache_{name}_watermark_bytes",
                     float(st["watermark_bytes"]))
        REGISTRY.set(f"cache_{name}_entry_count", float(st["entries"]))
    return out


#: every ProgramCache registers here so /status can report one
#: compiled-cache section across the tile/mesh/MPP/micro-batch engines
PROGRAM_CACHES: List["ProgramCache"] = []


class ProgramCache:
    """LRU-bounded compiled-program cache (the `_COMPILED` dicts, bounded).

    Unbounded program caches were a slow leak: every new fingerprint —
    parameter-different before hoisting, shape-different before
    bucketing, every rebuilt mesh — pinned a compiled XLA executable
    forever.  With shape buckets the steady-state key population is
    small, so a modest LRU cap holds the working set while long-tail
    shapes age out.  Counters feed `compiled_programs_{hits,misses,
    evictions}_total` and the /status compiled-cache section.
    """

    def __init__(self, name: str, capacity: Optional[int] = None):
        self.name = name
        self.capacity = capacity if capacity is not None else int(
            os.environ.get("TIDB_TPU_PROGRAM_CACHE_SIZE", "256"))
        self._d: "OrderedDict" = OrderedDict()
        self._mu = make_lock("copr.cache:ProgramCache._mu")
        self.hits = self.misses = self.evictions = 0
        PROGRAM_CACHES.append(self)

    def get(self, key):
        from ..metrics import REGISTRY

        with self._mu:
            fn = self._d.get(key)
            if fn is not None:
                self._d.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        REGISTRY.inc("compiled_programs_hits_total" if fn is not None
                     else "compiled_programs_misses_total")
        return fn

    def put(self, key, fn):
        from ..metrics import REGISTRY

        evicted = 0
        with self._mu:
            self._d[key] = fn
            self._d.move_to_end(key)
            while len(self._d) > max(self.capacity, 1):
                self._d.popitem(last=False)
                self.evictions += 1
                evicted += 1
        if evicted:
            REGISTRY.inc("compiled_programs_evictions_total", evicted)

    def stats(self) -> dict:
        with self._mu:
            return {"size": len(self._d), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}

    def clear(self):
        with self._mu:
            self._d.clear()

    def __len__(self):
        with self._mu:
            return len(self._d)

    def __iter__(self):
        with self._mu:
            return iter(list(self._d))

    def __contains__(self, key):
        with self._mu:
            return key in self._d
