"""Chunk budgets for interruptible device dispatch (ISSUE 17).

An in-flight XLA dispatch cannot be interrupted — the host only regains
control between launches.  So the dispatcher splits any fragment whose
estimated device time exceeds ``tidb_tpu_dispatch_chunk_ms`` into a
sequence of range-slot sub-dispatches over the SAME compiled program:
range bounds already ride the program as runtime scalar operands
(`MESH_RANGE_SLOTS` in copr/parallel.py), so chunking changes only the
operand VALUES — never the jaxpr, never the fingerprint, never a
recompile.  Between chunks the dispatcher checks the statement's
QueryScope and re-acquires resource-group admission, which bounds
KILL/timeout/quota latency by one chunk budget and lets a depleted
group's monster scan yield the device at every boundary.

The rows-per-chunk budget is derived from the measured per-kind chunk
latency histograms (`dispatch_chunk_<kind>_ms` / `_rows`, fed back by
`observe_chunk` after every dispatch — the same log2 histograms the SLO
plane uses), falling back to a flat rows-per-ms heuristic until the
first observations land.

Knobs:

- ``tidb_tpu_dispatch_chunk_ms`` sysvar / ``TIDB_TPU_DISPATCH_CHUNK``
  env: target device ms per chunk; 0 disables chunking entirely (the
  bench comparator and the pre-ISSUE-17 behavior).
- ``TIDB_TPU_DISPATCH_CHUNK_ROWS``: direct rows-per-chunk override for
  deterministic tests (bypasses the latency estimate).
- ``TIDB_TPU_CHUNK_ROWS_PER_MS``: the cold-start throughput guess.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from ..metrics import REGISTRY

#: chunk kinds with their own latency/row histograms
CHUNK_KINDS = ("filter", "agg", "topn", "tile", "mpp", "batch")

#: never chunk below this many rows: a mis-estimated throughput must
#: degrade into a few extra launches, not thousands of tiny ones
MIN_CHUNK_ROWS = 1024

# process-wide override installed by `SET tidb_tpu_dispatch_chunk_ms`
# (None = fall through to the env / default)
_CHUNK_MS: Optional[float] = None
_DEFAULT_CHUNK_MS = 100.0


def dispatch_chunk_ms() -> float:
    """Target device milliseconds per chunk; <= 0 disables chunking."""
    if _CHUNK_MS is not None:
        return _CHUNK_MS
    try:
        return float(os.environ.get("TIDB_TPU_DISPATCH_CHUNK",
                                    str(_DEFAULT_CHUNK_MS)))
    except ValueError:
        return _DEFAULT_CHUNK_MS


def set_dispatch_chunk_ms(ms: Optional[float]):
    """Sysvar hook (session/_run_set): GLOBAL-scope SET retargets the
    process knob, mirroring the serving sysvars."""
    global _CHUNK_MS
    _CHUNK_MS = None if ms is None else float(ms)


def _rows_per_ms(kind: str) -> float:
    """Measured rows/ms for `kind` from the chunk histograms' medians,
    or the cold-start heuristic.  Median-of-log2-buckets is within one
    bucket of truth — plenty for a budget that only has to land the
    chunk near the ms target, not exactly on it."""
    med_ms = REGISTRY.quantile(f"dispatch_chunk_{kind}_ms", 0.5, 0.0)
    med_rows = REGISTRY.quantile(f"dispatch_chunk_{kind}_rows", 0.5, 0.0)
    if med_ms > 0.0 and med_rows > 0.0:
        return med_rows / med_ms
    try:
        return float(os.environ.get("TIDB_TPU_CHUNK_ROWS_PER_MS", "8192"))
    except ValueError:
        return 8192.0


def chunk_budget_rows(kind: str) -> int:
    """Rows per chunk for `kind`; 0 = chunking disabled."""
    rows_env = os.environ.get("TIDB_TPU_DISPATCH_CHUNK_ROWS")
    if rows_env:
        try:
            n = int(rows_env)
            return max(n, 0)
        except ValueError:
            pass
    ms = dispatch_chunk_ms()
    if ms <= 0:
        return 0
    return max(int(ms * _rows_per_ms(kind)), MIN_CHUNK_ROWS)


def chunk_bounds(bounds: Sequence[Tuple[int, int]], budget_rows: int,
                 max_slots: int = 4) -> List[List[Tuple[int, int]]]:
    """Split [(lo, hi), ...] into per-chunk bound lists: each chunk
    covers at most `budget_rows` rows across at most `max_slots` ranges
    (the program's range-slot count).  budget 0 → one chunk, verbatim —
    the disabled path MUST be byte-identical to the old single
    dispatch.  Ranges stay ascending and disjoint, so rows-path
    concatenation preserves order."""
    if not bounds:
        return []
    if budget_rows <= 0:
        return [list(bounds)]
    out: List[List[Tuple[int, int]]] = []
    cur: List[Tuple[int, int]] = []
    cur_rows = 0
    for lo, hi in bounds:
        pos = lo
        while pos < hi:
            if cur and (cur_rows >= budget_rows or len(cur) >= max_slots):
                out.append(cur)
                cur, cur_rows = [], 0
            take = min(hi - pos, budget_rows - cur_rows)
            cur.append((pos, pos + take))
            cur_rows += take
            pos += take
    if cur:
        out.append(cur)
    return out


def observe_chunk(kind: str, ms: float, rows: int):
    """Feed one completed chunk back into the budget estimate and the
    chunk telemetry (/metrics, EXPLAIN ANALYZE `chunks: N`)."""
    REGISTRY.inc("dispatch_chunks_total")
    REGISTRY.observe_hist(f"dispatch_chunk_{kind}_ms", ms)
    REGISTRY.observe_hist(f"dispatch_chunk_{kind}_rows", float(rows))
