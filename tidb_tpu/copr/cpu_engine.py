"""CPU coprocessor engine: the DAG interpreter over host chunks.

Two roles (SURVEY.md §7): the correctness oracle the jax engine is diffed
against, and the real execution path for delta rows / non-pushable regions —
the moral successor of mocktikv's row-based DAG interpreter
(mocktikv/cop_handler_dag.go:56-177), but columnar/vectorized.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from ..chunk import Chunk, Column
from ..errors import ExecutorError
from ..expr.expression import eval_bool_mask
from ..expr.vec import Vec
from . import aggstate
from .ir import (
    DAG,
    AggregationIR,
    JoinLookupIR,
    JoinProbeIR,
    LimitIR,
    ProjectionIR,
    SelectionIR,
    TableScanIR,
    TopNIR,
    key_bits_int64,
)


def run_dag_on_chunk(dag: DAG, chunk: Chunk, aux: Optional[dict] = None) -> Chunk:
    """Interpret the post-scan part of `dag` over one scan-output chunk."""
    for ex in dag.executors[1:]:
        if isinstance(ex, SelectionIR):
            mask = eval_bool_mask(ex.conditions, chunk)
            chunk = chunk.filter(mask)
        elif isinstance(ex, JoinProbeIR):
            keys = (aux or {}).get(f"probe_keys_{ex.filter_id}")
            if keys is None:
                raise ExecutorError(
                    f"missing runtime probe keys {ex.filter_id}"
                )
            v = ex.key.eval(chunk)
            bits = key_bits_int64(v.data)
            pos = np.searchsorted(keys, bits)
            pos_c = np.clip(pos, 0, max(len(keys) - 1, 0))
            member = (
                (keys[pos_c] == bits) & v.validity()
                if len(keys) else np.zeros(chunk.num_rows, dtype=np.bool_)
            )
            chunk = chunk.filter(member)
        elif isinstance(ex, JoinLookupIR):
            keys = (aux or {}).get(f"probe_keys_{ex.filter_id}")
            payload = (aux or {}).get(f"payload_{ex.filter_id}")
            pvalids = (aux or {}).get(f"payload_valid_{ex.filter_id}")
            if keys is None or payload is None:
                raise ExecutorError(
                    f"missing join lookup aux {ex.filter_id}")
            v = ex.key.eval(chunk)
            bits = key_bits_int64(v.data)
            if len(keys):
                pos = np.searchsorted(keys, bits)
                pos_c = np.clip(pos, 0, len(keys) - 1)
                member = (keys[pos_c] == bits) & v.validity()
            else:
                pos_c = np.zeros(chunk.num_rows, dtype=np.int64)
                member = np.zeros(chunk.num_rows, dtype=np.bool_)
            chunk = chunk.filter(member)
            hit_pos = pos_c[member]
            cols = list(chunk.columns)
            for j, ft in enumerate(ex.payload_ftypes):
                data = payload[j][hit_pos] if len(keys) else \
                    payload[j][:0]
                pv = None
                if pvalids is not None and pvalids[j] is not None:
                    pv = pvalids[j][hit_pos] if len(keys) else \
                        pvalids[j][:0]
                cols.append(Column(ft, data, pv))
            chunk = Chunk(cols)
        elif isinstance(ex, ProjectionIR):
            chunk = Chunk([e.eval(chunk).to_column() for e in ex.exprs])
        elif isinstance(ex, AggregationIR):
            chunk = _run_agg(ex, chunk)
        elif isinstance(ex, TopNIR):
            chunk = run_topn(ex.order_by, ex.limit, chunk)
        elif isinstance(ex, LimitIR):
            chunk = chunk.slice(0, min(ex.limit, chunk.num_rows))
        else:
            raise ExecutorError(f"cpu engine: unknown executor {ex!r}")
    return chunk


def grouped_partial_chunks(group_by, aggs, chunks) -> List[Chunk]:
    """Grouped PARTIAL aggregation over row chunks, one partial chunk
    ([keys..., states...] layout) per non-empty input chunk — the shared
    host-tail recipe of the MPP agg-peel rung and the MPP host fallback
    (a FINAL HashAgg upstream merges groups across chunks)."""
    agg_ir = AggregationIR(list(group_by), list(aggs), mode="partial")
    out: List[Chunk] = []
    for c in chunks:
        if not c.num_rows:
            continue
        r = _run_agg(agg_ir, c)
        if r.num_rows:
            out.append(r)
    return out


def _run_agg(agg_ir: AggregationIR, chunk: Chunk) -> Chunk:
    gcols = [g.eval(chunk).to_column() for g in agg_ir.group_by]
    if gcols:
        gidx, keys, G = aggstate.group_indices(gcols)
    else:
        # scalar aggregation: one group, one output row
        gidx, keys, G = np.zeros(chunk.num_rows, dtype=np.int64), [()], 1
    out_cols: List[Column] = []
    # group-key output columns (one row per group)
    for ci, g in enumerate(agg_ir.group_by):
        vals = [k[ci] for k in keys]
        out_cols.append(Column.from_values(g.ftype, vals))
    for a in agg_ir.aggs:
        if a.distinct:
            cols = _distinct_states(a, chunk, gidx, G)
        else:
            arg_vecs = [x.eval(chunk) for x in a.args]
            cols = aggstate.partial_states(a, arg_vecs, gidx, G)
        if agg_ir.mode == "complete":
            out_cols.append(aggstate.finalize(a, cols))
        else:
            out_cols.extend(cols)
    return Chunk(out_cols)


def _distinct_states(a, chunk: Chunk, gidx: np.ndarray, G: int):
    """COUNT/SUM/AVG(DISTINCT x): dedup (group, value) pairs first."""
    arg_vecs = [x.eval(chunk) for x in a.args]
    n = chunk.num_rows
    seen = set()
    keep = np.zeros(n, dtype=np.bool_)
    cols = [v.to_column() for v in arg_vecs]
    for i in range(n):
        key = (int(gidx[i]),) + tuple(c.get(i) for c in cols)
        if key not in seen:
            seen.add(key)
            keep[i] = True
    sub_vecs = [Vec.from_column(c.filter(keep)) for c in cols]
    return aggstate.partial_states(a, sub_vecs, gidx[keep], G)


def run_topn(order_by, limit: int, chunk: Chunk) -> Chunk:
    """Stable multi-key sort + head(limit).  NULLs sort first ascending
    (MySQL semantics), last descending."""
    if chunk.num_rows == 0 or limit == 0:
        return chunk.slice(0, 0)
    idx = sort_indices(order_by, chunk)
    return chunk.take(idx[: limit if limit >= 0 else len(idx)])


def sort_indices(order_by, chunk: Chunk) -> np.ndarray:
    n = chunk.num_rows
    keys = []  # np.lexsort takes last key as primary -> reverse order
    for e, desc in reversed(list(order_by)):
        v = e.eval(chunk)
        data = v.data
        if data.dtype == object:
            # strings: rank via sorted unique values
            uniq = sorted(set(str(x) for x in data))
            rank = {s: i for i, s in enumerate(uniq)}
            data = np.fromiter(
                (rank[str(x)] for x in data), dtype=np.int64, count=n
            )
        else:
            data = data.astype(np.float64) if data.dtype == np.float64 else data
        valid = v.validity()
        if desc:
            if data.dtype == np.float64:
                key = np.where(valid, -data, np.inf)
            else:
                key = np.where(valid, -data.astype(np.int64), np.iinfo(np.int64).max)
        else:
            if data.dtype == np.float64:
                key = np.where(valid, data, -np.inf)
            else:
                key = np.where(
                    valid, data.astype(np.int64), np.iinfo(np.int64).min
                )
        keys.append(key)
    if not keys:
        return np.arange(n)
    return np.lexsort(keys)
