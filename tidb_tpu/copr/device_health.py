"""Device health tracking + circuit breakers for the TPU mesh.

Reference: the store-failover machinery of the reference — region_cache.go
marks a sick store needCheck and routes around it, region_request.go's
onSendFail backs off and retries another peer, and the health worker's
liveness probes slowly re-admit a recovered store.  Our "store" is a
*device* (one chip in the mesh): a runtime failure attributed to device k
trips a per-device circuit breaker, the mesh rebuilds over the surviving
device set, and a later half-open probe re-admits the chip once its
cooldown passes — the same failover ladder a training stack needs when a
chip drops out of the ring.

The registry is process-global (devices are process-global); every
transition is surfaced through metrics.REGISTRY and the
information_schema.TIDB_TPU_DEVICE_HEALTH memtable.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..metrics import REGISTRY
from ..util_concurrency import make_lock

HEALTHY = "healthy"
TRIPPED = "tripped"
PROBING = "probing"  # half-open: one in-flight trial over the full mesh


class DeviceFailure(RuntimeError):
    """A runtime failure attributable to specific mesh devices.

    Raised by fault injection (the mesh/device_error failpoint) and usable
    by backends that can name the failing chip; `device_ids` drives the
    per-device breakers."""

    def __init__(self, msg: str, device_ids: Tuple[int, ...] = ()):
        super().__init__(msg)
        self.device_ids = tuple(device_ids)


class HbmOomError(DeviceFailure):
    """Device memory exhaustion: recoverable by evicting the tile caches
    (HBM is a cache over host blocks) and re-running the program."""


_OOM_RE = re.compile(
    r"resource[_ ]exhausted|out of memory|hbm.*(?:exceed|exhaust|alloc)"
    r"|allocation failure", re.I)
_DEVICE_RE = re.compile(
    r"xla\w*error|data[_ ]loss|unavailable|device.*(?:fail|lost|halt)"
    r"|internal error", re.I)
# device ordinals in real XLA / jaxlib error text.  Shapes seen in the
# wild (PJRT/StreamExecutor/libtpu): "device ordinal 3", "TPU:2",
# "/device:TPU:1", "TPU_0", "device 3", "chip 2", "on device #1",
# "core 5 of chip 0" (chip wins), "TpuDevice(id=3)".  Matched with
# findall so a multi-chip failure trips every implicated breaker.
_DEVICE_ID_RE = re.compile(
    r"(?:device[ _]ordinal|device|tpu|chip|tpudevice\(id=)[ :_#=]{0,2}(\d+)",
    re.I)


def classify_failure(exc: BaseException) -> Optional[str]:
    """"oom" | "device" | None for an exception raised while running a
    mesh program.  None means "not a device-health event" — semantic
    errors and unknown failures keep their existing fallback semantics."""
    if isinstance(exc, HbmOomError):
        return "oom"
    if isinstance(exc, DeviceFailure):
        return "device"
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return None
    from ..coord import CoordEpochMismatch

    if isinstance(exc, CoordEpochMismatch):
        return None  # membership move: retried upstream, never a chip fault
    from ..errors import TiDBTPUError

    if isinstance(exc, TiDBTPUError):
        return None  # semantic (lock/kill/quota): never a device event
    msg = f"{type(exc).__name__}: {exc}"
    if _OOM_RE.search(msg):
        return "oom"
    if _DEVICE_RE.search(msg):
        return "device"
    return None


def attribute_devices(exc: BaseException) -> Tuple[int, ...]:
    """Device ids implicated by the failure: an explicit DeviceFailure
    payload first, else a parse of the runtime's message for XLA/jaxlib
    ordinal shapes ("device ordinal 3", "TPU:2", "/device:TPU:1",
    "TpuDevice(id=3)", "chip 0") — ROADMAP PR-2 follow-up (b): real
    runtime errors now trip the RIGHT breaker instead of retrying
    blind.  Every distinct ordinal in the text is implicated (a
    collective abort names several).  Empty when unattributable — the
    caller then retries without tripping any breaker.  The implicated
    ids also tag the failing span in the active query trace."""
    ids = getattr(exc, "device_ids", ())
    if not ids:
        seen = []
        for m in _DEVICE_ID_RE.findall(str(exc)):
            did = int(m)
            if did not in seen:
                seen.append(did)
        ids = tuple(seen)
    if ids:
        from ..trace import annotate

        annotate(device_ids=list(ids), failed=True)
    return tuple(ids)


@dataclass
class DeviceState:
    device_id: int
    state: str = HEALTHY
    error_count: int = 0
    consecutive_errors: int = 0
    trip_count: int = 0
    last_error: str = ""
    retry_at: float = 0.0  # monotonic deadline for the half-open probe
    tripped_at: float = field(default=0.0)


class DeviceHealthRegistry:
    """Per-device error counters + circuit breakers with half-open probes.

    trip_threshold consecutive attributed errors open the breaker (default
    1: a hard device fault quarantines the chip immediately; the half-open
    probe re-admits transient victims quickly).  After probe_after_s the
    breaker goes half-open: the device rejoins the mesh for one trial run,
    and the run's outcome either closes the breaker or re-trips it with a
    doubled cooldown (capped)."""

    def __init__(self, trip_threshold: int = 1, probe_after_s: float = 30.0,
                 max_cooldown_s: float = 600.0, clock=time.monotonic):
        self.trip_threshold = trip_threshold
        self.probe_after_s = probe_after_s
        self.max_cooldown_s = max_cooldown_s
        self._clock = clock
        self._mu = make_lock("copr.device_health:DeviceHealthRegistry._mu")
        self._devices: Dict[int, DeviceState] = {}
        # coordination-plane epoch publication hook (tidb_tpu/coord):
        # invoked OUTSIDE the lock after any transition that changes the
        # mesh-eligible set, so a breaker trip on this host renumbers
        # the cluster's membership epoch
        self._epoch_hook = None

    def set_epoch_hook(self, cb):
        """cb(tripped_ids, reason) or None.  Called after trips, probe
        admissions, half-open recoveries and resets — every event that
        changes which devices the mesh may span."""
        self._epoch_hook = cb

    def _notify(self, reason: str):
        cb = self._epoch_hook
        if cb is None:
            return
        try:
            cb(self.tripped_ids(), reason)
        except Exception:
            pass  # the plane must never break health bookkeeping

    # ---- state transitions ---------------------------------------------
    def record_error(self, device_id: int, exc: BaseException):
        tripped = False
        with self._mu:
            st = self._devices.setdefault(device_id, DeviceState(device_id))
            st.error_count += 1
            st.consecutive_errors += 1
            st.last_error = f"{type(exc).__name__}: {exc}"[:200]
            REGISTRY.inc("device_errors_total")
            if (st.state == PROBING
                    or st.consecutive_errors >= self.trip_threshold):
                self._trip(st)
                tripped = True
            self._publish()
        if tripped:
            self._notify("trip")

    def _trip(self, st: DeviceState):
        st.state = TRIPPED
        st.trip_count += 1
        st.tripped_at = self._clock()
        cooldown = min(self.probe_after_s * (2 ** (st.trip_count - 1)),
                       self.max_cooldown_s)
        st.retry_at = st.tripped_at + cooldown
        REGISTRY.inc("device_breaker_trips_total")

    def record_success(self, device_ids):
        """A mesh program completed over these devices: close half-open
        breakers and reset consecutive-error counters."""
        recovered = False
        with self._mu:
            for did in device_ids:
                st = self._devices.get(did)
                if st is None:
                    continue
                st.consecutive_errors = 0
                if st.state == PROBING:
                    st.state = HEALTHY
                    REGISTRY.inc("device_breaker_recoveries_total")
                    recovered = True
            self._publish()
        if recovered:
            self._notify("recover")

    def select_devices(self, devices: List) -> List:
        """Filter a device list down to mesh-eligible devices: healthy ones
        plus tripped ones whose cooldown elapsed (admitted as half-open
        probes).  Order is preserved (shard placement stays deterministic)."""
        now = self._clock()
        out = []
        probed = False
        with self._mu:
            for d in devices:
                st = self._devices.get(d.id)
                if st is None or st.state == HEALTHY:
                    out.append(d)
                elif st.state == PROBING:
                    out.append(d)  # probe already in flight this round
                elif now >= st.retry_at:
                    st.state = PROBING
                    REGISTRY.inc("device_breaker_probes_total")
                    out.append(d)
                    probed = True
            self._publish()
        if probed:
            self._notify("probe")
        return out

    def expire_cooldowns(self):
        """Make every open breaker immediately probe-eligible (operator
        'retry now' action; tests drive the half-open transition without
        waiting out real cooldowns)."""
        with self._mu:
            now = self._clock()
            for st in self._devices.values():
                if st.state == TRIPPED:
                    st.retry_at = now

    # ---- introspection --------------------------------------------------
    def state_of(self, device_id: int) -> str:
        with self._mu:
            st = self._devices.get(device_id)
            return st.state if st is not None else HEALTHY

    def tripped_ids(self) -> Tuple[int, ...]:
        with self._mu:
            return tuple(sorted(d for d, st in self._devices.items()
                                if st.state == TRIPPED))

    def snapshot(self) -> List[DeviceState]:
        """Copy of every tracked device state (infoschema provider)."""
        import copy

        with self._mu:
            return [copy.copy(st)
                    for _, st in sorted(self._devices.items())]

    def reset(self):
        """Forget all history (tests; operator ADMIN-style reset)."""
        with self._mu:
            self._devices.clear()
            self._publish()
        self._notify("reset")

    def _publish(self):
        # gauge, not counter: reflects the CURRENT quarantine set
        REGISTRY.set("device_health_tripped_count",
                     sum(1 for st in self._devices.values()
                         if st.state == TRIPPED))


# process-global: devices are process-global resources
DEVICE_HEALTH = DeviceHealthRegistry()
