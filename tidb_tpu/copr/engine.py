"""Region-level coprocessor dispatch.

Reference: the storage-node side of the cop request (TiKV's coprocessor;
simulated in-process by mocktikv/cop_handler_dag.go:56-97).  Per region:

1. read the MVCC delta overlay at the snapshot ts (deleted base rows +
   committed inserted/updated rows) — the UnionScan merge, done store-side
2. run the DAG over base rows on the requested engine (tpu via jax, falling
   back to cpu on JaxUnsupported), with deleted rows masked out
3. run the DAG over delta rows on the cpu engine
4. merge the two result streams per DAG tail (agg partials: concat;
   topn: re-topn; limit: slice; plain rows: concat)
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..chunk import Chunk, Column
from ..store.kv import CopRequest, CopResponse
from ..types import TypeKind
from .cpu_engine import run_dag_on_chunk, run_topn
from .ir import DAG, AggregationIR, LimitIR, TopNIR
from .jax_eval import JaxUnsupported


def run_dag_on_region(storage, req: CopRequest, region, clipped) -> CopResponse:
    table = storage.table(region.table_id)
    dag = DAG.from_dict(req.dag)
    aux = req.aux
    ts = req.ts
    deleted, inserted = table.delta_overlay(ts, clipped.start, clipped.end)

    chunks: List[Chunk] = []
    base_end = min(clipped.end, table.base_rows)
    table.check_read_horizon(ts)
    if table.base_ts <= ts and clipped.start < base_end:
        if req.engine == "tpu":
            try:
                # fused-region execution with the per-phase fallback
                # ladder (copr/fusion.py): an unfusable suffix runs as a
                # host tail over the fused region's output; only a
                # fragment with no device-eligible region at all steps
                # down to the CPU interpreter
                from .fusion import run_fragment

                chunks.extend(
                    run_fragment(table, dag, clipped.start, base_end,
                                 deleted, aux=aux)
                )
            except JaxUnsupported:
                chunks.extend(
                    _run_base_cpu(table, dag, clipped.start, base_end,
                                  deleted, aux)
                )
        else:
            chunks.extend(
                _run_base_cpu(table, dag, clipped.start, base_end, deleted,
                              aux)
            )
    if inserted:
        handles = sorted(inserted)
        scan = dag.scan
        cols = []
        for out_i, store_ci in enumerate(scan.columns):
            ft = scan.ftypes[out_i]
            vals = [inserted[h][store_ci] for h in handles]
            cols.append(Column.from_values(ft, vals))
        delta_chunk = Chunk(cols)
        res = run_dag_on_chunk(dag, delta_chunk, aux)
        if res.num_rows:
            chunks.append(res)

    chunks = _merge_tail(dag, chunks)
    return CopResponse(chunks=[c for c in chunks if c.num_rows > 0])


def _run_base_cpu(table, dag: DAG, start: int, end: int,
                  deleted, aux=None) -> List[Chunk]:
    """CPU path over base rows, tile by tile (bounded memory)."""
    TILE = 1 << 18
    del_arr = np.asarray(sorted(deleted), dtype=np.int64)
    out: List[Chunk] = []
    scan = dag.scan
    for t0 in range(start, end, TILE):
        t1 = min(t0 + TILE, end)
        chunk = table.base_chunk(scan.columns, t0, t1)
        if len(del_arr):
            dd = del_arr[(del_arr >= t0) & (del_arr < t1)] - t0
            if len(dd):
                keep = np.ones(chunk.num_rows, dtype=np.bool_)
                keep[dd] = False
                chunk = chunk.filter(keep)
        res = run_dag_on_chunk(dag, chunk, aux)
        if res.num_rows:
            out.append(res)
    return out


def _merge_tail(dag: DAG, chunks: List[Chunk]) -> List[Chunk]:
    """Per-region merge of per-tile results according to the DAG tail."""
    if len(chunks) <= 1:
        return chunks
    tail = dag.executors[-1]
    if isinstance(tail, TopNIR):
        merged = chunks[0]
        for c in chunks[1:]:
            merged = merged.append(c)
        return [run_topn(tail.order_by, tail.limit, merged)]
    if isinstance(tail, LimitIR):
        out: List[Chunk] = []
        left = tail.limit
        for c in chunks:
            if left <= 0:
                break
            take = c.slice(0, min(left, c.num_rows))
            out.append(take)
            left -= take.num_rows
        return out
    # aggregation partials and plain row streams: pass through, the root
    # executor merges
    return chunks
